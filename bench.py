"""Benchmark: sec/iteration on a Higgs-like binary workload (driver contract).

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.

`python bench.py --diff A.json B.json` instead compares two saved bench
lines' per-phase `timer_top_ms` breakdowns (perf-PR review mode,
ROADMAP PR-2 follow-up): per-scope ms/calls for both runs, delta and
ratio, plus the headline sec/iter movement.

Baseline anchor (BASELINE.md): reference CPU LightGBM trains Higgs (10.5M rows,
28 features, num_leaves=255, 500 iters) in 130.094 s => 0.260 s/iter
(docs/Experiments.rst:110-123).  This bench runs the same config shape on a
synthetic Higgs-like dataset at BENCH_ROWS rows (default 1M; the real Higgs
file is not downloadable in this environment) and scales the baseline
linearly in rows for vs_baseline — the reference's histogram cost is linear in
num_data, so sec_per_iter_baseline ~ 0.260 * rows / 10.5e6.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

ROWS = int(os.environ.get("BENCH_ROWS", 1_000_000))
FEATURES = 28
NUM_LEAVES = int(os.environ.get("BENCH_LEAVES", 255))
ITERS = int(os.environ.get("BENCH_ITERS", 10))
WARMUP = 3
BASELINE_SEC_PER_ITER_10M = 130.094 / 500  # ref docs/Experiments.rst
HIGGS_ROWS = 10_500_000


def make_higgs_like(n, F, seed=0):
    rng = np.random.RandomState(seed)
    X = np.empty((n, F), dtype=np.float32)
    # mix of gaussian "low-level" and heavy-tailed "high-level" features
    for f in range(F):
        if f % 3 == 0:
            X[:, f] = rng.randn(n)
        elif f % 3 == 1:
            X[:, f] = np.abs(rng.randn(n)) ** 1.5
        else:
            X[:, f] = rng.rand(n)
    # the label function is FIXED across seeds so train/test share it
    w = np.random.RandomState(1234).randn(F) / np.sqrt(F)
    logit = X @ w + 0.5 * X[:, 0] * X[:, 1]
    y = (rng.rand(n) < 1 / (1 + np.exp(-logit))).astype(np.float32)
    return X, y


def _auc(y, s):
    """Tie-averaged rank-sum AUC (ties get 0.5 credit per pos/neg pair, as
    binary_metric.hpp's AUCMetric does via equal-score blocks)."""
    _, inv, counts = np.unique(s, return_inverse=True, return_counts=True)
    cum = np.cumsum(counts) - counts
    ranks = (cum + (counts + 1) / 2.0)[inv]
    pos = y > 0
    n_pos, n_neg = pos.sum(), (~pos).sum()
    return float((ranks[pos].sum() - n_pos * (n_pos + 1) / 2)
                 / max(n_pos * n_neg, 1))


def _ensure_jax_backend(probe_timeout: float = 180.0) -> bool:
    """Probe JAX backend init in a THROWAWAY subprocess (jax caches a
    failed backend init for the process lifetime, so probing in-process
    would poison this run).  If the configured backend can't come up —
    BENCH_r05.json showed `RuntimeError: Unable to initialize backend
    'axon'` killing the whole bench with rc=1 — fall back to CPU with a
    warning so the bench still emits its JSON line.  Returns True when
    the fallback was taken.

    The probe does REAL device work (device_put + compute + fetch), not
    just jax.devices(): r05's failure surfaced only at the first
    device_put, after a devices() enumeration would have succeeded."""
    if os.environ.get("_BENCH_CPU_REEXEC") == "1":
        return True  # second life after _backend_guard re-exec'd us
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax, jax.numpy as jnp; jax.devices(); "
             "print(float((jnp.ones((8,), jnp.float32) + 1).sum()))"],
            capture_output=True, text=True, timeout=probe_timeout,
            env=os.environ.copy())
        if probe.returncode == 0:
            return False
        reason = (probe.stderr or probe.stdout or "").strip().splitlines()
        reason = reason[-1] if reason else f"exit code {probe.returncode}"
    except subprocess.TimeoutExpired:
        reason = f"backend probe hung for {probe_timeout:.0f}s"
    print(f"[bench] WARNING: JAX backend unavailable ({reason}); "
          "falling back to JAX_PLATFORMS=cpu", file=sys.stderr, flush=True)
    os.environ["JAX_PLATFORMS"] = "cpu"
    return True


def _backend_guard() -> None:
    """Last line of defense: force backend init NOW, in-process, before
    any Dataset/Booster device work.  If it fails despite the subprocess
    probe passing (flaky TPU runtime), re-exec this script pinned to CPU
    — jax caches the failed init for the process lifetime, so switching
    platforms in-process would not recover.

    The guard runs a REAL device op, not just jax.devices(): BENCH_r05's
    `Unable to initialize backend 'axon'` surfaced only at the first
    jax.device_put, inside the previously-unguarded region, after a
    devices() enumeration had already succeeded."""
    import jax
    try:
        jax.devices()
        x = jax.device_put(np.ones(8, np.float32))
        float(jax.numpy.sum(x + 1.0))
    except RuntimeError as e:
        if os.environ.get("_BENCH_CPU_REEXEC") == "1":
            raise  # already on the CPU fallback; give up loudly
        print(f"[bench] WARNING: in-process backend init failed ({e}); "
              "re-executing with JAX_PLATFORMS=cpu",
              file=sys.stderr, flush=True)
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["_BENCH_CPU_REEXEC"] = "1"
        sys.stdout.flush()
        sys.stderr.flush()
        os.execv(sys.executable, [sys.executable] + sys.argv)


def diff_main(path_a, path_b):
    """Compare two bench JSON lines' timer_top_ms breakdowns per phase.

    The timer_top_ms field is [[scope, total_ms, calls], ...] over the 3
    instrumented post-loop iterations (docs/Observability.md).  Scopes
    present in only one run are listed with the other side blank — a
    new/removed phase is exactly what a perf-PR review needs to see."""
    runs = []
    for p in (path_a, path_b):
        with open(p) as f:
            runs.append(json.load(f))
    a, b = runs
    ta = {name: (ms, cnt) for name, ms, cnt in a.get("timer_top_ms", [])}
    tb = {name: (ms, cnt) for name, ms, cnt in b.get("timer_top_ms", [])}
    # keep A's ordering (slowest first), then B-only scopes
    names = [n for n, _, _ in a.get("timer_top_ms", [])]
    names += [n for n, _, _ in b.get("timer_top_ms", []) if n not in ta]
    wn = max([len(n) for n in names] + [5])
    print(f"{'phase':<{wn}} {'A ms':>10} {'B ms':>10} {'delta':>10} "
          f"{'ratio':>7}  calls A->B")
    for n in names:
        ma, ca = ta.get(n, (None, None))
        mb, cb = tb.get(n, (None, None))
        sa = f"{ma:.1f}" if ma is not None else "-"
        sb = f"{mb:.1f}" if mb is not None else "-"
        if ma is not None and mb is not None:
            delta = f"{mb - ma:+.1f}"
            ratio = f"{mb / ma:.2f}x" if ma > 0 else "-"
        else:
            delta, ratio = "-", "-"
        calls = f"{ca if ca is not None else '-'}" \
                f"->{cb if cb is not None else '-'}"
        print(f"{n:<{wn}} {sa:>10} {sb:>10} {delta:>10} {ratio:>7}  {calls}")
    va, vb = a.get("value"), b.get("value")
    if va and vb:
        print(f"headline: {va} -> {vb} {a.get('unit', 's/iter')} "
              f"({vb / va:.3f}x; {'faster' if vb < va else 'slower'} B)")
    for key in ("auc", "quality_mode_sec_per_iter", "quality_mode_auc",
                "peak_device_bytes", "backend", "host_block_ms_per_iter",
                "setup_construct_s", "setup_compile_s"):
        if a.get(key) is not None or b.get(key) is not None:
            print(f"{key}: {a.get(key)} -> {b.get(key)}")
    return 0


def _predict_throughput(booster, X):
    """Serving-side rows/s for the three predict paths (ISSUE 4): the
    jitted device traversal, the native (single-core C) batch predictor,
    and the pure-Python per-tree loop.  Device/python row counts shrink
    off-TPU so the phase stays inside the bench budget; the reported
    number is a RATE either way."""
    import jax
    g = booster._gbdt
    g._sync_model()
    on_tpu = jax.default_backend() == "tpu"
    out = {}

    def timed(fn, rows, warmup=True):
        if warmup:
            fn()
        t0 = time.time()
        fn()
        dt = time.time() - t0
        return round(rows / max(dt, 1e-9), 1)

    # device path: forced on (auto would skip off-TPU); float32 input
    n_dev = X.shape[0] if on_tpu else min(X.shape[0], 200_000)
    Xd = np.ascontiguousarray(X[:n_dev], np.float32)
    prev_mode = g.config.device_predict
    try:
        g.config.device_predict = "true"
        hit = g._device_predictor(Xd, 0, -1)
        if hit is not None:
            dp, Xd = hit
            out["device"] = timed(lambda: dp.predict_raw(Xd), n_dev)
            out["device_rows"] = n_dev
    except Exception as e:  # noqa: BLE001 - throughput must not kill bench
        print(f"[bench] device predict path failed: {e}", file=sys.stderr)
    finally:
        g.config.device_predict = prev_mode

    # native path (PackedPredictor, OpenMP where available)
    K = g.num_tree_per_iteration
    total_iters = len(g.models_) // max(K, 1)
    packed = g._packed_for(0, total_iters, K)
    X64 = np.ascontiguousarray(X, np.float64)
    if packed is not None:
        out["native"] = timed(
            lambda: packed.predict(X64, K, g.average_output_), X.shape[0])
        out["native_rows"] = X.shape[0]

    # pure-Python per-tree loop (the fallback path), subsampled: at 1M
    # rows x hundreds of leaves it would take minutes on this host
    n_py = min(X.shape[0], 50_000)
    Xp = X64[:n_py]

    def py_path():
        acc = np.zeros(n_py)
        for t in g.models_:
            acc += t.predict(Xp)
        return acc

    out["python"] = timed(py_path, n_py, warmup=False)
    out["python_rows"] = n_py
    return out


def serve_main(smoke: bool = False) -> int:
    """Closed-loop serving bench (ISSUE 10): `python bench.py --serve`.

    Drives the serving daemon with S concurrent closed-loop streams
    (one outstanding request per stream, resubmitted on completion),
    hot-swaps a second model mid-run, and prints ONE JSON line with
    `serve_p50_ms` / `serve_p99_ms` / `serve_rows_per_s` /
    `serve_recompiles`.  Every response is checked BYTE-IDENTICAL
    against `Booster.predict` of the model version that served it —
    a swap may answer with either version, never a mix, never a drop.

    Streams are multiplexed over a small thread pool (S streams / T
    threads, each thread submits its streams' requests then waits them
    all — one outstanding request per stream, closed-loop): the CPU
    container has a single core, so S OS threads would bench the GIL,
    not the daemon.  `--smoke` shrinks everything for the verify gate.
    """
    backend_fallback = _ensure_jax_backend()
    import jax
    if backend_fallback:
        jax.config.update("jax_platforms", "cpu")
    _backend_guard()

    import threading

    import lightgbm_tpu as lgb
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.serving import ServingDaemon

    streams = int(os.environ.get("BENCH_SERVE_STREAMS",
                                 64 if smoke else 1024))
    rounds = int(os.environ.get("BENCH_SERVE_ROUNDS", 3 if smoke else 10))
    req_rows = int(os.environ.get("BENCH_SERVE_REQ_ROWS", 4))
    n_threads = max(1, min(16, streams))
    per_thread = max(1, streams // n_threads)
    streams = n_threads * per_thread

    # model pair: v2 continues v1 so the swap changes every score
    Xtr, ytr = make_higgs_like(20_000, FEATURES, seed=7)
    params = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
              "min_data_in_leaf": 20, "device_predict": "true",
              "device_predict_min_bucket": 128}
    b1 = lgb.train(params, lgb.Dataset(Xtr, label=ytr), num_boost_round=20)
    b2 = lgb.train(params, lgb.Dataset(Xtr, label=ytr), num_boost_round=40)

    pool, _ = make_higgs_like(4096, FEATURES, seed=8)
    pool = np.ascontiguousarray(pool, np.float32)
    # expected scores per version VIA Booster.predict (the acceptance
    # oracle); responses must match the serving version byte-for-byte
    expected = {1: b1.predict(pool), 2: b2.predict(pool)}

    cfg = Config({**params,
                  "serve_max_batch_rows": 4096,
                  "serve_queue_depth": max(streams * 2, 64),
                  "metrics_port": 0,  # ephemeral /metrics; scraped below
                  "serve_max_coalesce_wait_ms": float(
                      os.environ.get("BENCH_SERVE_WAIT_MS", 2.0))})
    daemon = ServingDaemon(cfg).start()
    v1_handle = daemon.registry.register("higgs", booster=b1, block=True)
    warmup_recompiles = daemon.registry.serve_recompiles()

    latencies: list = []
    failures: list = []
    lat_lock = threading.Lock()
    rows_served = [0]
    versions_seen: set = set()
    swap_gate = threading.Event()
    start_gate = threading.Barrier(n_threads + 1)

    def slice_for(stream: int, rnd: int):
        start = ((stream * 2654435761 + rnd * 97) % (len(pool) - req_rows))
        return start, pool[start:start + req_rows]

    def client(tid: int) -> None:
        start_gate.wait()
        my_streams = range(tid * per_thread, (tid + 1) * per_thread)
        for rnd in range(rounds):
            futs = []
            for s in my_streams:
                start, rows = slice_for(s, rnd)
                try:
                    futs.append((start, daemon.submit("higgs", rows)))
                except Exception as e:  # noqa: BLE001
                    with lat_lock:
                        failures.append(f"submit:{e}")
            for start, fut in futs:
                try:
                    out = fut.result(timeout=120)
                except Exception as e:  # noqa: BLE001
                    with lat_lock:
                        failures.append(f"result:{e}")
                    continue
                exp = expected[fut.version][start:start + req_rows]
                ok = np.array_equal(out, exp)
                with lat_lock:
                    latencies.append(fut.latency_ms)
                    rows_served[0] += req_rows
                    versions_seen.add(fut.version)
                    if not ok:
                        failures.append(
                            f"mismatch v{fut.version}@{start}")
            if tid == 0 and rnd == max(rounds // 2 - 1, 0):
                swap_gate.set()  # main hot-swaps while rounds continue

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(n_threads)]
    for t in threads:
        t.start()
    t0 = time.time()
    start_gate.wait()
    swap_gate.wait(timeout=300)
    # hot swap MID-LOAD: the v2 load+warmup runs on a background thread
    # while v1 keeps serving; in-flight requests finish on v1
    swap_handle = daemon.registry.register("higgs", booster=b2, block=False)
    for t in threads:
        t.join(timeout=600)
    wall = time.time() - t0
    swap_handle.wait(timeout=120)

    # post-swap phase: the background v2 warmup typically outlasts the
    # closed-loop rounds, so prove the swap END state explicitly — v2
    # serves byte-identically and the retired v1 entry released its
    # device buffers once its last in-flight request finished
    for i in range(16):
        start, rows = slice_for(i, rounds)
        fut = daemon.submit("higgs", rows)
        out = fut.result(timeout=120)
        versions_seen.add(fut.version)
        if fut.version != 2 or not np.array_equal(
                out, expected[2][start:start + req_rows]):
            failures.append(f"post-swap mismatch v{fut.version}@{start}")
    if not v1_handle.entry.released:
        failures.append("retired v1 entry still holds device buffers")

    recompiles = daemon.registry.serve_recompiles() - warmup_recompiles
    stats = daemon.stats()

    # Prometheus scrape gate (docs/Observability.md): the fleet/router
    # layer consumes GET /metrics, so the bench asserts a parseable page
    # with the serve counters and tail-latency quantile gauges present
    metrics_scrape_ok = False
    scrape_error = None
    try:
        import urllib.request
        port = daemon.metrics_server.port
        page = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30).read().decode()
        required = ("lgbm_serve_requests", "lgbm_serve_rows",
                    'lgbm_serve_latency_ms{quantile="0.5"}',
                    'lgbm_serve_latency_ms{quantile="0.99"}',
                    "lgbm_serve_queue_pending",
                    'lgbm_serve_requests_by_model{model="higgs"}')
        missing = [r for r in required if r not in page]
        # every exposition line must be a comment or name[{labels}] value
        malformed = [ln for ln in page.splitlines()
                     if ln and not ln.startswith("#")
                     and len(ln.rsplit(" ", 1)) != 2]
        if missing:
            scrape_error = f"missing series: {missing}"
        elif malformed:
            scrape_error = f"malformed lines: {malformed[:3]}"
        else:
            metrics_scrape_ok = True
    except Exception as e:  # noqa: BLE001 - reported in the JSON line
        scrape_error = str(e)

    serve_roofline = stats.get("roofline")
    daemon.stop(drain=True, timeout=30)

    lat = np.asarray(latencies, np.float64)
    n_req = streams * rounds
    hot_swap_ok = (not failures and len(lat) == n_req
                   and swap_handle.entry is not None
                   and swap_handle.entry.version == 2
                   and versions_seen == {1, 2})
    out = {
        "metric": "serve_closed_loop",
        "value": round(float(np.percentile(lat, 99)), 3) if len(lat) else None,
        "unit": "p99_ms",
        "serve_p50_ms": round(float(np.percentile(lat, 50)), 3)
        if len(lat) else None,
        "serve_p99_ms": round(float(np.percentile(lat, 99)), 3)
        if len(lat) else None,
        "serve_rows_per_s": round(rows_served[0] / max(wall, 1e-9), 1),
        "serve_requests_per_s": round(len(lat) / max(wall, 1e-9), 1),
        "serve_recompiles": int(recompiles),
        "streams": streams,
        "rounds": rounds,
        "request_rows": req_rows,
        "requests": int(len(lat)),
        "rows": int(rows_served[0]),
        "hot_swap_ok": bool(hot_swap_ok),
        "versions_seen": sorted(versions_seen),
        "coalesced_batches": int(stats["serve_batches"]),
        "coalesce_wait_ms": cfg.serve_max_coalesce_wait_ms,
        "metrics_scrape_ok": bool(metrics_scrape_ok),
        "metrics_scrape_error": scrape_error,
        "serve_measured_mfu": (round(serve_roofline["measured_mfu"], 7)
                               if serve_roofline
                               and serve_roofline.get("measured_mfu")
                               is not None else None),
        "serve_roofline_bound": (serve_roofline or {}).get("bound"),
        "errors": failures[:5],
        "backend": jax.default_backend(),
        "smoke": bool(smoke),
    }
    print(json.dumps(out))
    ok = hot_swap_ok and recompiles == 0 and metrics_scrape_ok
    return 0 if ok else 1


def _parse_fleet_faults(smoke: bool) -> dict:
    """BENCH_FLEET_FAULT=replica_crash@N,serve_slow@N,serve_shed@N,
    canary_diverge@N — the fleet bench's chaos spec.  replica_crash /
    serve_slow / serve_shed become LGBM_TPU_FAULT specs injected into a
    replica's environment (@N = that replica's N-th accepted request);
    canary_diverge@N is a bench-level drill: once N client requests
    have succeeded, publish a deliberately-divergent model as a canary
    and demand the auto-rollback.  The default (smoke included) drills
    one crash, one shed, one slow dispatcher (the SLO-burn bait: the
    armed sleep stalls the dispatch loop, so every queued request
    behind it breaches the latency SLO at once), and one divergent
    canary."""
    raw = os.environ.get("BENCH_FLEET_FAULT")
    if raw is None:
        raw = ("replica_crash@25,serve_shed@10,serve_slow@60,"
               "canary_diverge@120")
    out = {"replica_crash": None, "serve_slow": None,
           "serve_shed": None, "canary_diverge": None}
    for tok in raw.split(","):
        tok = tok.strip()
        if not tok:
            continue
        kind, _, n = tok.partition("@")
        if kind in out and n.lstrip("-").isdigit():
            out[kind] = int(n)
        else:
            print(f"[bench] WARNING: ignoring malformed "
                  f"BENCH_FLEET_FAULT spec {tok!r}", file=sys.stderr)
    return out


def serve_fleet_main(smoke: bool = False) -> int:
    """Fleet serving bench (ISSUE 13): `python bench.py --serve-fleet`.

    Spawns K replica daemons + the retry/shed/canary router, drives
    closed-loop client threads THROUGH the router, and chaos-drills the
    fault domain mid-load (BENCH_FLEET_FAULT): one replica crashes and
    is relaunched, one replica sheds, a rolling publish swaps every
    replica to v2, and a deliberately-divergent canary must AUTO-ROLL
    BACK.  Gates (rc != 0 on violation): ZERO failed client requests
    through all of it, every response byte-identical to
    `Booster.predict` of the version that served it, the
    `serve_rollback`/`serve_shed` counters present on the router's
    /metrics page, and every replica draining to rc 143 on SIGTERM.
    ISSUE 14 adds the observability-plane gates: the router's merged
    `lgbm_fleet_*` scrape must equal the sum of the per-replica scrapes
    with BOTH replicas contributing, at least one sampled request must
    assemble into a full cross-process trace (router route/attempt +
    replica serve/queue/dispatch/respond spans, >= 2 pids, monotone
    stamps), and the serve_slow dispatcher stall must fire >= 1
    `slo_burn` (75 ms p99 SLO, shrunk burn windows)."""
    backend_fallback = _ensure_jax_backend()
    import jax
    if backend_fallback:
        jax.config.update("jax_platforms", "cpu")
    _backend_guard()

    import tempfile
    import threading
    import urllib.request

    import lightgbm_tpu as lgb
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.observability.registry import global_registry
    from lightgbm_tpu.serving import OverloadedError, ReplicaFleet, Router
    from lightgbm_tpu.serving.daemon import serve_counters_reset

    faults = _parse_fleet_faults(smoke)
    replicas = int(os.environ.get("BENCH_FLEET_REPLICAS",
                                  2 if smoke else 3))
    n_threads = int(os.environ.get("BENCH_FLEET_THREADS",
                                   6 if smoke else 12))
    req_rows = int(os.environ.get("BENCH_FLEET_REQ_ROWS", 4))
    target_requests = int(os.environ.get(
        "BENCH_FLEET_REQUESTS", 400 if smoke else 4000))

    # model trio: v2 continues the workload (the GOOD publish); the
    # canary candidate is trained with a pathological class weight so
    # its score distribution visibly diverges — the auto-rollback bait
    Xtr, ytr = make_higgs_like(20_000, FEATURES, seed=7)
    params = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
              "min_data_in_leaf": 20, "device_predict": "true",
              "device_predict_min_bucket": 64}
    b1 = lgb.train(params, lgb.Dataset(Xtr, label=ytr), num_boost_round=20)
    b2 = lgb.train(params, lgb.Dataset(Xtr, label=ytr), num_boost_round=40)
    b_bad = lgb.train({**params, "scale_pos_weight": 100.0},
                      lgb.Dataset(Xtr, label=ytr), num_boost_round=10)

    workdir = tempfile.mkdtemp(prefix="lgbm-fleet-bench-")
    paths = {}
    for tag, bst in (("v1", b1), ("v2", b2), ("bad", b_bad)):
        paths[tag] = os.path.join(workdir, f"model_{tag}.txt")
        bst.save_model(paths[tag])

    pool, _ = make_higgs_like(2048, FEATURES, seed=8)
    pool = np.ascontiguousarray(pool, np.float32)
    # byte-identity oracle: every routed response must equal ONE
    # version's Booster.predict rows exactly (versions are per-replica
    # registry counters, so the SCORES identify the model, and a row
    # mix of two versions inside one response can never match any)
    expected = {tag: b.predict(pool)
                for tag, b in (("v1", b1), ("v2", b2), ("bad", b_bad))}

    serve_counters_reset()
    for key in ("slo_burn_total", "router_requests", "router_rows"):
        global_registry.inc(key, -global_registry.counter(key))
    victim = 1 % replicas
    fault_envs = {}
    specs = []
    if faults["replica_crash"] is not None:
        specs.append((victim, f"serve_crash@{faults['replica_crash']}"))
    if faults["serve_shed"] is not None:
        specs.append((0, f"serve_shed@{faults['serve_shed']}"))
    if faults["serve_slow"] is not None:
        specs.append((0, f"serve_slow@{faults['serve_slow']}"))
    for idx, spec in specs:
        env = fault_envs.setdefault(idx, {})
        env["LGBM_TPU_FAULT"] = ",".join(
            filter(None, [env.get("LGBM_TPU_FAULT"), spec]))

    serve_params = {"device_predict": "true",
                    "device_predict_min_bucket": 64,
                    "serve_max_batch_rows": 256,
                    "serve_max_coalesce_wait_ms": 2.0,
                    "serve_queue_depth": 256,
                    "verbosity": -1}
    cfg = Config({**serve_params,
                  "serve_replicas": replicas,
                  "serve_retry_max": 4,
                  "serve_retry_backoff_ms": 25.0,
                  "serve_request_timeout_s": 60.0,
                  "serve_canary_pct": 50.0,
                  "serve_canary_min_samples": 24,
                  "serve_canary_max_divergence": 2.0,
                  "serve_canary_max_error_rate": 0.2,
                  # cross-process tracing (ISSUE 14): sample every 16th
                  # routed request so the smoke run assembles a few
                  # dozen full client->router->replica->device traces
                  "serve_trace_sample": 16,
                  # SLO burn gate: normal container latency (p99 tens
                  # of ms) stays inside budget; the serve_slow fault's
                  # armed 2 s dispatcher stall pushes every queued
                  # request over 75 ms at once and must burn BOTH
                  # windows (shrunk so a smoke run spans several)
                  "serve_slo_p99_ms": 75.0,
                  "serve_slo_error_pct": 1.0,
                  "serve_slo_fast_window_s": 2.0,
                  "serve_slo_slow_window_s": 20.0})
    fleet = ReplicaFleet(
        num_replicas=replicas, model_entries=[("higgs", paths["v1"])],
        workdir=workdir, params=serve_params,
        max_restarts=3, health_interval_s=0.25, force_cpu=True,
        fault_envs=fault_envs).start()
    router = Router(fleet, cfg)
    router.register_incumbent("higgs", paths["v1"])
    failures: list = []
    latencies: list = []
    lat_lock = threading.Lock()
    ok_count = [0]
    overload_rejections = [0]
    rows_served = [0]
    versions_matched: set = set()
    stop_flag = threading.Event()
    try:
        if not fleet.wait_ready(timeout=420.0):
            print(json.dumps({"metric": "serve_fleet", "value": None,
                              "error": "fleet never became ready",
                              "replicas": fleet.describe()}))
            return 1

        def match_version(out_rows, start):
            for tag, exp in expected.items():
                if np.array_equal(out_rows, exp[start:start + req_rows]):
                    return tag
            return None

        def client(tid: int) -> None:
            rnd = 0
            while not stop_flag.is_set():
                rnd += 1
                start = ((tid * 2654435761 + rnd * 97)
                         % (len(pool) - req_rows))
                try:
                    r = router.predict("higgs",
                                       pool[start:start + req_rows],
                                       deadline_ms=45_000.0)
                except OverloadedError:
                    # an explicit admission rejection is the correct
                    # answer from a saturated fleet, not a lost request
                    # — the client backs off; the gate bounds the RATE
                    with lat_lock:
                        overload_rejections[0] += 1
                    time.sleep(0.1)
                    continue
                except Exception as e:  # noqa: BLE001
                    with lat_lock:
                        failures.append(f"t{tid}r{rnd}: {e!r}")
                    time.sleep(0.05)  # no failure-storm spinning
                    continue
                tag = match_version(np.asarray(r.preds), start)
                with lat_lock:
                    latencies.append(r.latency_ms)
                    rows_served[0] += req_rows
                    ok_count[0] += 1
                    if tag is None:
                        failures.append(
                            f"t{tid}r{rnd}: response matches NO "
                            f"version byte-for-byte (v{r.version} "
                            f"replica {r.replica})")
                    else:
                        versions_matched.add(tag)

        threads = [threading.Thread(target=client, args=(i,), daemon=True)
                   for i in range(n_threads)]
        t0 = time.time()
        for t in threads:
            t.start()

        def done_fraction() -> float:
            with lat_lock:
                return ok_count[0] / max(target_requests, 1)

        def wait_until(frac: float, timeout: float = 420.0) -> None:
            deadline = time.time() + timeout
            while done_fraction() < frac and time.time() < deadline:
                time.sleep(0.05)

        # phase A: plain load; the crash + shed faults fire in here
        wait_until(0.35)
        # phase B: rolling publish v2 (no canary) under live load —
        # after the crashed replica rejoined, so the roll covers the
        # whole fleet (a replica skipped mid-restart would relaunch
        # onto the new version anyway via fleet.set_model_path)
        fleet.wait_ready(timeout=180.0)
        publish_info = router.publish("higgs", paths["v2"], canary_pct=0)
        # phase C: wait for the canary threshold, then drop the bait
        canary_at = faults["canary_diverge"]
        rollback_ok = None
        if canary_at is not None:
            while done_fraction() * target_requests < canary_at and \
                    time.time() - t0 < 420.0:
                time.sleep(0.05)
            fleet.wait_ready(timeout=120.0, min_replicas=2)
            router.publish("higgs", paths["bad"])  # serve_canary_pct=50
            verdict = router.canary_wait("higgs", timeout=240.0)
            rollback_ok = verdict == "rolled_back"
        wait_until(1.0)
        stop_flag.set()
        for t in threads:
            t.join(timeout=120.0)
        wall = time.time() - t0

        # --- fleet-aggregation gate (ISSUE 14): one forced synchronous
        # scrape of every replica, then the router's MERGED counter must
        # equal the sum of the per-replica scrapes exactly (traffic has
        # stopped, so the counters are static) and BOTH replicas must
        # have contributed a non-zero share
        fleet.wait_ready(timeout=60.0)
        fleet.scrape_all()
        agg_snapshot = fleet.aggregator.snapshot()
        per_replica_requests = {
            idx: s["counters"].get("lgbm_serve_requests", 0.0)
            for idx, s in sorted(agg_snapshot.items())}
        merged_requests = fleet.aggregator.merged_counters().get(
            "lgbm_serve_requests", 0.0)
        fleet_metrics_ok = (
            len(per_replica_requests) >= min(replicas, 2)
            and all(v > 0 for v in per_replica_requests.values())
            and abs(merged_requests
                    - sum(per_replica_requests.values())) < 1e-9)

        # --- assembled-trace gate (ISSUE 14): at least one sampled
        # request produced a full cross-process waterfall — router
        # routing (route/attempt), replica coalesce/dispatch
        # (serve/queue/dispatch) and device settle (dispatch span end +
        # respond span) — from >= 2 processes with monotone stamps
        trace_ok = False
        trace_seen = router.assembler.traces()
        for tr in trace_seen:
            if tr.get("outcome") != "ok":
                continue
            names = {s["name"] for s in tr["spans"]}
            if not {"route", "attempt", "serve", "queue", "dispatch",
                    "respond"} <= names:
                continue
            if len(tr.get("processes", ())) < 2:
                continue
            rels = [s["rel_ms"] for s in tr["spans"]]
            if any(b < a for a, b in zip(rels, rels[1:])) \
                    or any(r < 0 for r in rels):
                continue
            trace_ok = True
            break

        # --- SLO burn gate (ISSUE 14): the serve_slow fault's 2 s
        # dispatcher stall breached the 75 ms latency SLO for every
        # queued request at once; the router's multi-window burn-rate
        # tracker must have fired at least one slo_burn
        slo_burns = int(global_registry.counter("slo_burn_total"))
        slo_wanted = faults["serve_slow"] is not None
        slo_ok = (slo_burns >= 1) if slo_wanted else None

        # /metrics gate: the router's scrape page must carry the fleet
        # counters the acceptance names (serve_rollback, serve_shed)
        # plus the merged fleet families and per-replica gauges
        router.start_frontend(port=0, metrics_port=0)
        metrics_scrape_ok = False
        scrape_error = None
        try:
            page = urllib.request.urlopen(
                f"http://127.0.0.1:{router.metrics_server.port}/metrics",
                timeout=30).read().decode()
            required = ["lgbm_router_requests", "lgbm_router_rows",
                        "lgbm_serve_shed", "lgbm_router_p99_ms",
                        "lgbm_fleet_replicas_routable",
                        "lgbm_fleet_serve_requests",
                        'lgbm_fleet_replica_up{replica="0"}',
                        'lgbm_fleet_replica_up{replica="1"}',
                        "lgbm_fleet_latency_ms"]
            if rollback_ok is not None:
                required.append("lgbm_serve_rollback")
            if slo_wanted:
                required.append("lgbm_fleet_slo_burning")
            missing = [r for r in required if r not in page]
            malformed = [ln for ln in page.splitlines()
                         if ln and not ln.startswith("#")
                         and len(ln.rsplit(" ", 1)) != 2]
            page_fleet_requests = None
            for ln in page.splitlines():
                if ln.startswith("lgbm_fleet_serve_requests "):
                    page_fleet_requests = float(ln.rsplit(" ", 1)[1])
            if missing:
                scrape_error = f"missing series: {missing}"
            elif malformed:
                scrape_error = f"malformed lines: {malformed[:3]}"
            elif page_fleet_requests is not None and abs(
                    page_fleet_requests
                    - sum(per_replica_requests.values())) > 1e-9:
                scrape_error = (
                    f"merged lgbm_fleet_serve_requests "
                    f"{page_fleet_requests} != per-replica sum "
                    f"{sum(per_replica_requests.values())}")
            else:
                metrics_scrape_ok = True
        except Exception as e:  # noqa: BLE001 - reported in the JSON line
            scrape_error = str(e)

        # one TCP round trip through the router wire (clients above ran
        # in-process; the wire is what a real fleet client sees)
        wire_ok = False
        try:
            import socket
            port = router.frontend.server_address[1]
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=30) as s:
                f = s.makefile("rwb")
                f.write((json.dumps({"model": "higgs",
                                     "rows": pool[:req_rows].tolist()})
                         + "\n").encode())
                f.flush()
                resp = json.loads(f.readline())
            wire_ok = bool(resp.get("ok")) and match_version(
                np.asarray(resp["preds"]), 0) is not None
        except Exception as e:  # noqa: BLE001
            failures.append(f"wire: {e!r}")

        stats = router.stats()
        crashes = int(global_registry.counter("serve_replica_down"))
        restarts = int(global_registry.counter("serve_replica_restarts"))
    finally:
        stop_flag.set()
        rcs = fleet.stop(drain=True, timeout=60.0)
        router.stop()
    drain_ok = all(rc in (143, -15) for rc in rcs.values())

    lat = np.asarray(latencies, np.float64)
    crash_wanted = faults["replica_crash"] is not None
    out = {
        "metric": "serve_fleet",
        "value": round(float(np.percentile(lat, 99)), 3)
        if len(lat) else None,
        "unit": "p99_ms",
        "fleet_p50_ms": round(float(np.percentile(lat, 50)), 3)
        if len(lat) else None,
        "fleet_p99_ms": round(float(np.percentile(lat, 99)), 3)
        if len(lat) else None,
        "fleet_rows_per_s": round(rows_served[0] / max(wall, 1e-9), 1),
        "fleet_requests_per_s": round(len(lat) / max(wall, 1e-9), 1),
        "replicas": replicas,
        "requests_ok": int(ok_count[0]),
        "requests_failed": len(failures),
        "overload_rejections": int(overload_rejections[0]),
        "replica_crashes": crashes,
        "replica_restarts": restarts,
        "router_retries": int(stats["router_retries"]),
        "serve_shed": int(stats["serve_shed"]),
        "serve_overloaded": int(stats["serve_overloaded"]),
        "publishes": int(stats["serve_publish"]),
        "rollback_ok": rollback_ok,
        "serve_rollback": int(stats["serve_rollback"]),
        "versions_matched": sorted(versions_matched),
        "publish_rolled_replicas": sorted(
            publish_info.get("replicas", {})) if publish_info else None,
        "metrics_scrape_ok": bool(metrics_scrape_ok),
        "metrics_scrape_error": scrape_error,
        "fleet_metrics_ok": bool(fleet_metrics_ok),
        "fleet_requests_per_replica": {
            str(k): int(v) for k, v in per_replica_requests.items()},
        "fleet_requests_merged": int(merged_requests),
        "traces_assembled": len(trace_seen),
        "trace_ok": bool(trace_ok),
        "slo_burns": slo_burns,
        "slo_ok": slo_ok,
        "wire_ok": bool(wire_ok),
        "drain_returncodes": {str(k): v for k, v in sorted(rcs.items())},
        "drain_ok": bool(drain_ok),
        "errors": failures[:5],
        "fault_spec": {k: v for k, v in faults.items() if v is not None},
        "backend": jax.default_backend(),
        "smoke": bool(smoke),
    }
    print(json.dumps(out))
    ok = (not failures
          and ok_count[0] >= target_requests
          and overload_rejections[0] <= 0.05 * max(ok_count[0], 1)
          and (not crash_wanted or (crashes >= 1 and restarts >= 1))
          and int(stats["serve_publish"]) >= 1
          and {"v1", "v2"} <= versions_matched
          and (rollback_ok is None or rollback_ok)
          and fleet_metrics_ok and trace_ok
          and (slo_ok is None or slo_ok)
          and metrics_scrape_ok and wire_ok and drain_ok)
    return 0 if ok else 1


def online_main(smoke: bool = False) -> int:
    """Online continual-learning bench (docs/Online.md):
    `python bench.py --online [--smoke]`.

    Phase 1 (in-process, sustained load): an OnlineTrainer thread
    consumes MemoryChunkSource generations — boosting new trees per
    chunk, checkpointing each generation, hot-publishing into a local
    ServingDaemon — while closed-loop client threads keep querying.
    The chaos spec (`LGBM_TPU_FAULT=online_publish_fail@…,
    online_chunk_corrupt@…`) drills the failure semantics mid-run: a
    failed publish must retry and land (old generation serving
    throughout), a corrupt chunk must be SKIPPED with the previous
    generation serving.  Gates: ZERO lost client requests across all
    publishes, every response byte-identical to `Booster.predict` of
    the exact generation that served it, >= 3 generations published,
    reported freshness lag finite and under `online_max_lag_s`.

    Phase 2 (subprocess SIGTERM drill): a control `task=train-and-serve`
    run consumes 3 on-disk chunks to completion; a drill run is
    SIGTERM-killed mid-loop after generation 2, then relaunched — the
    relaunch must resume from the generation-2 checkpoint, serve it
    immediately (no served-version regression), re-train generation 3
    BYTE-IDENTICALLY to the control run, and exit cleanly."""
    backend_fallback = _ensure_jax_backend()
    import jax
    if backend_fallback:
        jax.config.update("jax_platforms", "cpu")
    _backend_guard()

    import shutil
    import signal
    import tempfile
    import threading
    import urllib.request

    import lightgbm_tpu as lgb
    from lightgbm_tpu.basic import Booster
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.observability.registry import global_registry
    from lightgbm_tpu.online import (LocalPublisher, MemoryChunkSource,
                                     OnlineTrainer, write_chunk)
    from lightgbm_tpu.reliability import faults
    from lightgbm_tpu.serving import ServingClient, ServingDaemon
    from lightgbm_tpu.serving.daemon import serve_counters_reset

    n_rows = int(os.environ.get("BENCH_ONLINE_CHUNK_ROWS",
                                1500 if smoke else 20000))
    n_chunks = int(os.environ.get("BENCH_ONLINE_CHUNKS",
                                  5 if smoke else 10))
    n_threads = int(os.environ.get("BENCH_ONLINE_THREADS",
                                   4 if smoke else 8))
    req_rows = 4
    max_lag_s = float(os.environ.get("BENCH_ONLINE_MAX_LAG_S", 60.0))
    trees_per_chunk = 3

    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 10, "device_predict": "true",
              "device_predict_min_bucket": 64,
              "serve_max_batch_rows": 256, "serve_queue_depth": 256,
              "serve_max_coalesce_wait_ms": 2.0,
              "metrics_port": 0,
              "online_trees_per_chunk": trees_per_chunk,
              "online_mode": "boost", "online_max_lag_s": max_lag_s,
              "online_publish_backoff_ms": 25.0}

    def mk_chunk(seed):
        X, y = make_higgs_like(n_rows, FEATURES, seed=seed)
        return X, y

    workdir = tempfile.mkdtemp(prefix="lgbm-online-bench-")
    failures: list = []
    samples: list = []       # (version, start, preds) under lat_lock
    lat_lock = threading.Lock()
    versions_models: dict = {}

    # chaos spec: publish of generation 2 fails once (must retry and
    # land); chunk generation 4 arrives corrupt (must be skipped with
    # generation 3 still serving)
    chaos = os.environ.get("BENCH_ONLINE_FAULT",
                           "online_publish_fail@2,online_chunk_corrupt@4")
    prev_fault = os.environ.get("LGBM_TPU_FAULT")
    corrupt_gens = {int(tok.split("@")[1]) for tok in chaos.split(",")
                    if tok.startswith("online_chunk_corrupt@")}
    try:
        serve_counters_reset()
        for key in ("online_generations_published",
                    "online_generations_skipped",
                    "online_publish_retries"):
            global_registry.inc(key, -global_registry.counter(key))
        if chaos:
            os.environ["LGBM_TPU_FAULT"] = chaos
        else:
            os.environ.pop("LGBM_TPU_FAULT", None)
        faults.reload()

        X0, y0 = mk_chunk(0)
        seed_booster = lgb.train(
            {k: v for k, v in params.items()
             if not k.startswith(("serve_", "online_", "metrics_"))},
            lgb.Dataset(X0, label=y0), num_boost_round=10)
        seed_path = os.path.join(workdir, "seed.txt")
        seed_booster.save_model(seed_path)

        daemon = ServingDaemon(Config(params)).start()
        source = MemoryChunkSource()
        ckpt_dir = os.path.join(workdir, "ckpt")

        def on_publish(gen, version, model_str):
            with lat_lock:
                versions_models[version] = model_str

        trainer = OnlineTrainer(source, LocalPublisher(daemon),
                                params=params, checkpoint_dir=ckpt_dir,
                                seed_model=seed_path,
                                on_publish=on_publish)
        trainer.start()

        pool, _ = make_higgs_like(2048, FEATURES, seed=99)
        pool = np.ascontiguousarray(pool, np.float32)
        stop_flag = threading.Event()

        def client(tid):
            rnd = 0
            while not stop_flag.is_set():
                rnd += 1
                start = ((tid * 2654435761 + rnd * 97)
                         % (len(pool) - req_rows))
                try:
                    fut = daemon.submit(trainer.model_name,
                                        pool[start:start + req_rows])
                    out = fut.result(timeout=120)
                except Exception as e:  # noqa: BLE001
                    with lat_lock:
                        failures.append(f"t{tid}r{rnd}: {e!r}")
                    time.sleep(0.05)
                    continue
                with lat_lock:
                    samples.append((fut.version, start,
                                    np.asarray(out)))

        threads = [threading.Thread(target=client, args=(i,), daemon=True)
                   for i in range(n_threads)]
        t0 = time.time()
        for t in threads:
            t.start()
        loop = threading.Thread(
            target=lambda: trainer.run(max_generations=n_chunks,
                                       idle_exit_s=60.0), daemon=True)
        loop.start()
        for g in range(1, n_chunks + 1):
            source.push(*mk_chunk(g))
            time.sleep(0.3 if smoke else 1.0)
        loop.join(timeout=600)
        stop_flag.set()
        for t in threads:
            t.join(timeout=60)
        wall = time.time() - t0
        stats = trainer.stats()
        if loop.is_alive():
            failures.append("trainer loop did not finish")

        # byte-identity: every sampled response must equal
        # Booster.predict of the exact version that served it (device
        # path forced: the daemon serves through the same float32
        # traversal, so the comparison is bit-for-bit)
        def _oracle(model_str):
            b = Booster(model_str=model_str)
            b._gbdt.config.device_predict = "true"
            return b

        with lat_lock:
            model_of = {v: _oracle(s)
                        for v, s in versions_models.items()}
        expected = {v: b.predict(pool) for v, b in model_of.items()}
        mismatches = 0
        for version, start, preds in samples:
            exp = expected.get(version)
            if exp is None or not np.array_equal(
                    preds, exp[start:start + req_rows]):
                mismatches += 1
        if mismatches:
            failures.append(f"{mismatches} responses not byte-identical "
                            "to their serving generation")

        published = int(global_registry.counter(
            "online_generations_published"))
        skipped = int(global_registry.counter(
            "online_generations_skipped"))
        retries = int(global_registry.counter("online_publish_retries"))
        lag = stats.get("freshness_lag_s")
        lag_ok = lag is not None and np.isfinite(lag) and lag <= max_lag_s

        # the freshness plane must be scrapable (docs/Online.md)
        metrics_scrape_ok = False
        scrape_error = None
        try:
            page = urllib.request.urlopen(
                f"http://127.0.0.1:{daemon.metrics_server.port}/metrics",
                timeout=30).read().decode()
            required = ["lgbm_model_freshness_lag_s",
                        "lgbm_online_generations_published",
                        "lgbm_online_generation"]
            if skipped:
                required.append("lgbm_online_generations_skipped")
            missing = [r for r in required if r not in page]
            if missing:
                scrape_error = f"missing series: {missing}"
            else:
                metrics_scrape_ok = True
        except Exception as e:  # noqa: BLE001 - reported in the JSON line
            scrape_error = str(e)
        daemon.stop(drain=True, timeout=30)
    finally:
        if prev_fault is None:
            os.environ.pop("LGBM_TPU_FAULT", None)
        else:
            os.environ["LGBM_TPU_FAULT"] = prev_fault
        faults.reload()

    # ---- phase 2: the SIGTERM kill/resume drill (subprocesses) ----
    drill = {"control_rc": None, "kill_rc": None, "resume_rc": None,
             "byte_exact": None, "served_no_regress": None,
             "error": None}
    try:
        chunks_a = os.path.join(workdir, "chunks-a")
        chunks_b = os.path.join(workdir, "chunks-b")
        os.makedirs(chunks_a)
        os.makedirs(chunks_b)
        drill_chunks = {}
        for g in (1, 2, 3):
            Xg, yg = mk_chunk(100 + g)
            drill_chunks[g] = write_chunk(chunks_a, g, Xg, yg)
        base_cmd = [sys.executable, "-m", "lightgbm_tpu",
                    "task=train-and-serve",
                    "objective=binary", "num_leaves=15", "verbosity=-1",
                    "min_data_in_leaf=10", "device_predict=true",
                    "device_predict_min_bucket=64", "serve_warmup=false",
                    "online_mode=boost", "online_trees_per_chunk=2",
                    "online_poll_interval_s=0.05",
                    f"input_model={seed_path}"]
        env = {k: v for k, v in os.environ.items()
               if k != "LGBM_TPU_FAULT"}
        env.setdefault("JAX_PLATFORMS", "cpu")

        ck_a = os.path.join(workdir, "ckpt-a")
        res = subprocess.run(
            base_cmd + [f"online_chunk_dir={chunks_a}",
                        f"checkpoint_dir={ck_a}", "serve_port=-1",
                        "online_idle_exit_s=1.5"],
            capture_output=True, text=True, timeout=600, env=env)
        drill["control_rc"] = res.returncode
        control_final = open(os.path.join(ck_a, "ckpt_0000003.txt"),
                             "rb").read()
        control_g2 = open(os.path.join(ck_a, "ckpt_0000002.txt"),
                          "rb").read()

        # drill run: only generations 1-2 available, killed mid-loop
        for g in (1, 2):
            shutil.copy(drill_chunks[g], chunks_b)
        ck_b = os.path.join(workdir, "ckpt-b")
        ready1 = os.path.join(workdir, "ready-b1.json")
        child = subprocess.Popen(
            base_cmd + [f"online_chunk_dir={chunks_b}",
                        f"checkpoint_dir={ck_b}", "serve_port=-1",
                        "online_idle_exit_s=0",
                        f"serve_ready_file={ready1}"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env)
        deadline = time.time() + 300
        while time.time() < deadline:
            if os.path.exists(os.path.join(ck_b, "ckpt_0000002.txt")):
                break
            if child.poll() is not None:
                break
            time.sleep(0.1)
        time.sleep(0.3)  # let the generation-2 publish settle
        child.send_signal(signal.SIGTERM)
        try:
            out_b1, _ = child.communicate(timeout=60)
        except subprocess.TimeoutExpired:
            child.kill()
            out_b1, _ = child.communicate()
        drill["kill_rc"] = child.returncode

        # relaunch with generation 3 landed: must resume from the
        # generation-2 checkpoint, serve it immediately, and re-train
        # generation 3 byte-identically to the control run
        shutil.copy(drill_chunks[3], chunks_b)
        ready2 = os.path.join(workdir, "ready-b2.json")
        child2 = subprocess.Popen(
            base_cmd + [f"online_chunk_dir={chunks_b}",
                        f"checkpoint_dir={ck_b}", "serve_port=0",
                        "online_idle_exit_s=1.5",
                        f"serve_ready_file={ready2}"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env)
        deadline = time.time() + 300
        port = None
        while time.time() < deadline and port is None:
            if os.path.exists(ready2):
                port = json.load(open(ready2)).get("port")
                break
            if child2.poll() is not None:
                break
            time.sleep(0.1)
        served_ok = None
        if port and port > 0:
            # the ready file lands right after the RESUME publish: the
            # served model must already be generation >= 2 — never the
            # seed (that would regress the fleet below its checkpoint)
            exp_g2 = _oracle(control_g2.decode()).predict(
                pool[:req_rows])
            exp_g3 = _oracle(control_final.decode()).predict(
                pool[:req_rows])
            try:
                cl = ServingClient.connect("127.0.0.1", int(port),
                                           request_timeout_s=60.0)
                got = np.asarray(cl.predict("online", pool[:req_rows]))
                cl.close()
                served_ok = (np.array_equal(got, exp_g2)
                             or np.array_equal(got, exp_g3))
            except Exception as e:  # noqa: BLE001
                served_ok = False
                drill["error"] = f"resume probe: {e!r}"
        drill["served_no_regress"] = served_ok
        try:
            out_b2, _ = child2.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            child2.kill()
            out_b2, _ = child2.communicate()
        drill["resume_rc"] = child2.returncode
        resumed_final_path = os.path.join(ck_b, "ckpt_0000003.txt")
        if os.path.exists(resumed_final_path):
            drill["byte_exact"] = (open(resumed_final_path, "rb").read()
                                   == control_final)
        else:
            drill["byte_exact"] = False
            drill["error"] = (drill["error"] or "") + \
                f" no resumed gen-3 checkpoint; b2 tail: {out_b2[-500:]}"
    except Exception as e:  # noqa: BLE001 - drill outcome rides the JSON line
        drill["error"] = f"{type(e).__name__}: {e}"
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    drill_ok = (drill["control_rc"] == 0
                and drill["kill_rc"] in (143, -15)
                and drill["resume_rc"] == 0
                and drill["byte_exact"] is True
                and drill["served_no_regress"] is True)
    chaos_ok = (not chaos) or (retries >= 1 and skipped >= 1
                               and skipped == len(corrupt_gens))
    out = {
        "metric": "online_continual",
        "value": (round(lag, 3) if lag is not None else None),
        "unit": "freshness_lag_s",
        "generations_published": published,
        "generations_skipped": skipped,
        "publish_retries": retries,
        "freshness_lag_s": (round(lag, 4) if lag is not None else None),
        "freshness_lag_ok": bool(lag_ok),
        "online_max_lag_s": max_lag_s,
        "requests_ok": len(samples),
        "requests_failed": len(failures),
        "requests_per_s": round(len(samples) / max(wall, 1e-9), 1),
        "chunk_rows": n_rows,
        "chunks": n_chunks,
        "versions_served": sorted({v for v, _, _ in samples}),
        "chaos_spec": chaos or None,
        "chaos_ok": bool(chaos_ok),
        "metrics_scrape_ok": bool(metrics_scrape_ok),
        "metrics_scrape_error": scrape_error,
        "sigterm_drill": drill,
        "sigterm_drill_ok": bool(drill_ok),
        "errors": failures[:5],
        "backend": jax.default_backend(),
        "smoke": bool(smoke),
    }
    print(json.dumps(out))
    ok = (not failures and published >= 3 and lag_ok and chaos_ok
          and metrics_scrape_ok and drill_ok
          and len(samples) > 0)
    return 0 if ok else 1


_MULTICHIP_CHILD = r"""
import os, sys
sys.path.insert(0, os.environ["BENCH_REPO"])
import jax
if os.environ.get("BENCH_MULTICHIP_FORCE_CPU") == "1":
    jax.config.update("jax_platforms", "cpu")
import numpy as np
import lightgbm_tpu as lgb

work = os.environ["BENCH_MULTICHIP_DIR"]
rng = np.random.RandomState(11)
X = rng.rand(1024, 5)
y = (3 * (X[:, 0] - 0.5) + X[:, 1] * X[:, 2]).astype(np.float64)
params = {
    "objective": "regression", "num_leaves": 7, "verbosity": -1,
    "min_data_in_leaf": 5, "learning_rate": 0.2,
    "tree_learner": "data", "tpu_growth_strategy": "wave",
    "metrics_dir": os.path.join(work, "metrics"),
    "checkpoint_dir": os.path.join(work, "ckpt"), "checkpoint_freq": 1,
    "auto_degrade": True,
    "stall_floor_s": float(os.environ.get("BENCH_STALL_FLOOR_S", "30")),
    "stall_factor": 10.0,
}
b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=6)
assert np.isfinite(b.predict(X[:64])).all()
print("MULTICHIP_TRAIN_OK", b.current_iteration(), flush=True)
"""


def multichip_main(n_devices: int) -> int:
    """Guarded multi-chip smoke runner (ISSUE 7): train a short
    sharded-wave run over an `n_devices` mesh UNDER the stall watchdog,
    walking the degradation ladder across relaunches when an attempt
    hangs.  Prints one MULTICHIP-style JSON line that is
    self-explaining on failure: `stall_diagnosis` carries the wedged
    attempt's stack + knob fingerprint and `degraded_knobs` the ladder
    steps a recovered run needed — the two fields MULTICHIP_r05 (rc=124,
    one stderr line) did not have.

    Fault injection for self-tests / driver drills:
    `BENCH_MULTICHIP_FAULT=hang@3` wedges attempt 0 at iteration 3.
    """
    import shutil
    import tempfile

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from lightgbm_tpu.reliability.guard import (DEGRADE_LADDER,
                                                degraded_knobs,
                                                stall_file_path)
    from lightgbm_tpu.reliability.supervisor import classify_returncode

    timeout = float(os.environ.get("BENCH_MULTICHIP_TIMEOUT", "600"))
    env = dict(os.environ)
    env["BENCH_REPO"] = os.path.dirname(os.path.abspath(__file__))
    # self-provision the mesh (as __graft_entry__.dryrun_multichip does):
    # when this host has fewer devices, the children run on a virtual
    # n-device CPU platform
    probe = subprocess.run(
        [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
        capture_output=True, text=True, timeout=300, env=env)
    have = int(probe.stdout.strip() or 0) if probe.returncode == 0 else 0
    if have < n_devices:
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{n_devices}").strip()
        env["BENCH_MULTICHIP_FORCE_CPU"] = "1"
    if os.environ.get("BENCH_MULTICHIP_FAULT"):
        env["LGBM_TPU_FAULT"] = os.environ["BENCH_MULTICHIP_FAULT"]

    work = tempfile.mkdtemp(prefix="lgbtpu_multichip")
    metrics = os.path.join(work, "metrics")
    out = {"metric": "multichip_guarded", "n_devices": int(n_devices),
           "rc": None, "ok": False, "classification": None,
           "attempts": 0, "stall_diagnosis": None, "degraded_knobs": [],
           # recovery telemetry (ISSUE 8): which recovery machinery
           # fired and how long the run was down — so an r06+ line
           # names the mechanism, not just the outcome
           "time_to_recover_s": None, "elastic_shrinks": 0,
           "ckpt_fallbacks": 0, "preempt_ckpt_saved": 0,
           "tail": ""}
    first_failure_t = None
    try:
        env["BENCH_MULTICHIP_DIR"] = work
        script = os.path.join(work, "child.py")
        with open(script, "w") as f:
            f.write(_MULTICHIP_CHILD)
        # one first try + one relaunch per ladder rung: a run that still
        # hangs with every risky knob off is a real bug, not a knob
        for attempt in range(1 + len(DEGRADE_LADDER)):
            out["attempts"] = attempt + 1
            env["LGBM_TPU_FAULT_ATTEMPT"] = str(attempt)
            try:
                res = subprocess.run(
                    [sys.executable, script], capture_output=True,
                    text=True, timeout=timeout, env=env)
                rc = res.returncode
                out["tail"] = ((res.stdout or "") + (res.stderr or ""))[-2000:]
            except subprocess.TimeoutExpired as e:
                rc = 124
                out["tail"] = (str(e.stdout or "") + str(e.stderr or ""))[-2000:]
            out["rc"] = rc
            out["classification"] = classify_returncode(rc)
            if out["stall_diagnosis"] is None:
                spath = stall_file_path(metrics, 0)
                if os.path.exists(spath):
                    try:
                        out["stall_diagnosis"] = json.load(open(spath))
                    except (OSError, ValueError):
                        pass
            if rc == 0:
                out["ok"] = True
                if first_failure_t is not None:
                    out["time_to_recover_s"] = round(
                        time.monotonic() - first_failure_t, 3)
                break
            if first_failure_t is None:
                first_failure_t = time.monotonic()
            # hangs walk the degradation ladder on relaunch; preempts
            # and crashes relaunch unchanged, resuming from checkpoint
            # (injected faults are attempt-gated so they do not re-fire)
            if out["classification"] not in ("hang", "preempt", "crash"):
                break
        out["degraded_knobs"] = degraded_knobs(metrics)
        out.update(_recovery_counts(metrics))
    finally:
        shutil.rmtree(work, ignore_errors=True)
    print(json.dumps(out))
    return 0 if out["ok"] else 1


def _recovery_counts(metrics_dir):
    """Count recovery events across every rank's event log: which of
    the ISSUE-8 mechanisms (generation fallback, elastic shrink,
    preemption checkpoint) actually fired during the guarded run."""
    import glob
    counts = {"ckpt_fallbacks": 0, "elastic_shrinks": 0,
              "preempt_ckpt_saved": 0}
    for path in glob.glob(os.path.join(metrics_dir, "events-rank*.jsonl*")):
        try:
            with open(path) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    ev = rec.get("event")
                    if ev == "ckpt_fallback":
                        counts["ckpt_fallbacks"] += 1
                    elif ev == "elastic_shrink":
                        counts["elastic_shrinks"] += 1
                    elif ev == "preempt" and rec.get("saved"):
                        counts["preempt_ckpt_saved"] += 1
        except OSError:
            continue
    return counts


def main():
    backend_fallback = _ensure_jax_backend()
    import jax
    if backend_fallback:
        # the axon TPU plugin ignores JAX_PLATFORMS; pin explicitly
        jax.config.update("jax_platforms", "cpu")
    _backend_guard()

    import lightgbm_tpu as lgb

    X, y = make_higgs_like(ROWS, FEATURES)
    Xte, yte = make_higgs_like(100_000, FEATURES, seed=1)
    params = {
        "objective": "binary",
        "num_leaves": NUM_LEAVES,
        "learning_rate": 0.1,
        "max_bin": int(os.environ.get("BENCH_BINS", 255)),
        "min_data_in_leaf": 20,
        "verbosity": -1,
        # the timed loop never evaluates (headline comparability); the
        # metric exists for the instrumented eval-tick phase below
        "metric": "binary_logloss",
    }
    # setup split (ISSUE 5): construct = binning + device placement +
    # booster init; compile = first update through its device sync (the
    # part a persistent compilation cache removes on repeat runs —
    # enable with compile_cache_dir=<dir>)
    t0 = time.time()
    train_set = lgb.Dataset(X, label=y)
    booster = lgb.Booster(params=params, train_set=train_set)
    setup_construct_s = time.time() - t0

    # warmup: the first iteration compiles the whole-tree program and the
    # first post-compile execution pays one-time device autotuning; sync
    # before timing so the measured loop is steady-state
    t0 = time.time()
    booster.update()
    _ = np.asarray(booster._gbdt.scores[0][:8])
    setup_compile_s = time.time() - t0
    for _ in range(WARMUP - 1):
        booster.update()
    _ = np.asarray(booster._gbdt.scores[0][:8])
    t0 = time.time()
    for _ in range(ITERS):
        booster.update()
    # force all device work to finish
    _ = np.asarray(booster._gbdt.scores[0][:8])
    elapsed = (time.time() - t0) / ITERS

    # quality gate: held-out AUC after the timed iterations (speed must not
    # be bought with broken trees).  Measured BEFORE the instrumented
    # extra iterations below so the tree count matches iters_trained (and
    # the same-host oracle's iters_lo anchor).
    auc = _auc(yte, booster._gbdt.predict_raw(Xte))

    # phase breakdown (docs/Observability.md): a few EXTRA instrumented
    # iterations AFTER the timed loop — the timers' phase-boundary syncs
    # would de-pipeline the dispatch, so the headline number stays
    # uninstrumented and comparable with every earlier BENCH_*.json.
    # The cost model rides the same window: compiled-HLO flop/byte
    # deltas against the ::device phase times give MEASURED per-phase
    # MFU and a roofline classification next to the analytic estimate
    from lightgbm_tpu.observability.costmodel import (backend_peaks,
                                                      global_cost_model)
    from lightgbm_tpu.utils.timer import global_timer
    timer_prev = global_timer.enabled
    cost_prev = global_cost_model.enabled
    global_timer.enabled = True
    global_cost_model.enabled = True
    global_timer.reset()
    cost_snap0 = global_cost_model.snapshot()
    timer_snap0 = global_timer.snapshot()
    for _ in range(3):
        booster.update()
        # eval tick, mirroring engine.train's scope: with device eval
        # this is ONE packed D2H (ops/metrics.py); its cost is the
        # host-block headline below
        with global_timer.scope("GBDT::eval"):
            booster.eval_train()
    _ = np.asarray(booster._gbdt.scores[0][:8])
    all_scopes = global_timer.items()
    timer_top = [[name, round(sec * 1000, 3), cnt]
                 for name, sec, cnt in all_scopes[:10]]
    phase_secs = {name: sec - timer_snap0.get(name, (0.0, 0))[0]
                  for name, (sec, _c) in global_timer.snapshot().items()}
    cost_snap1 = global_cost_model.snapshot()
    roofline_phases = global_cost_model.phase_roofline(
        cost_snap0, cost_snap1, phase_secs)
    # headline measured MFU: total compiled flops of the instrumented
    # window over its total attributed device seconds (the analytic
    # b10m_useful_mac_mfu's measured cross-check)
    _tot_flops = sum(v["flops"] for v in roofline_phases.values())
    _tot_dev_s = sum(v["device_s"] or 0.0
                     for v in roofline_phases.values())
    peak_flops, _peak_bw = backend_peaks()
    measured_mfu = (_tot_flops / _tot_dev_s / peak_flops
                    if _tot_dev_s > 0 else None)
    global_cost_model.enabled = cost_prev
    # host-block attribution (docs/Observability.md): the scopes that
    # synchronize the training thread on device results or host I/O —
    # the boundary the ISSUE-5 work shrinks (device eval metrics, async
    # checkpoint writer, pipelined tree materialization)
    _HOST_BLOCK_SCOPES = ("GBDT::eval", "GBDT::materialize_tree",
                          "Checkpoint::save")
    host_block_ms_per_iter = round(sum(
        sec * 1000 for name, sec, _cnt in all_scopes
        if name in _HOST_BLOCK_SCOPES) / 3.0, 3)
    global_timer.enabled = timer_prev
    global_timer.reset()

    # peak device memory over the run (empty off-TPU: the CPU backend
    # exposes no memory_stats)
    from lightgbm_tpu.observability import sample_device_memory
    mem = sample_device_memory()

    # predict throughput: serving rows/s for device / native / python
    # paths over the just-trained model (the trajectory tracks serving
    # perf alongside s/iter)
    predict_rows_per_s = _predict_throughput(booster, X)

    # jaxpr-level IR audit over the entries this run actually compiled
    # (tools/tpulint/ir, ISSUE 12): the BENCH line records that the hot
    # path it just measured is f64-free and callback-free — the
    # guard rail the quantized-gradient/Pallas work lands behind.
    # Groups come from the cost model's window (what dispatched) plus
    # the inference ladder when the device predict path ran.
    ir_audit_clean = None
    ir_audit = {}
    try:
        t0 = time.time()
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from tools.tpulint.ir import run_ir_audit
        _groups = sorted(set(cost_snap1)
                         | ({"device_predict"}
                            if "device" in predict_rows_per_s else set()))
        _findings, _num = run_ir_audit(
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "lightgbm_tpu"), groups=_groups)
        _active = [f for f in _findings if not f.suppressed]
        ir_audit_clean = not _active
        ir_audit = {"groups": _groups, "entries_traced": _num,
                    "findings": len(_active),
                    "s": round(time.time() - t0, 3)}
    except Exception as e:  # noqa: BLE001 - the audit must not kill bench
        ir_audit = {"error": f"{type(e).__name__}: {e}"}

    # kernel-correctness gate (tools/kernel_checks.py): the Pallas kernel
    # unit tests skip off-TPU, so the driver's chip run is the only CI
    # that executes them — carry a pass/fail field every round
    kernel_checks = "skipped"
    try:
        if jax.default_backend() == "tpu":
            sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
            from tools.kernel_checks import run_checks
            kernel_checks = run_checks()
    except Exception as e:  # noqa: BLE001 - the gate must not kill bench
        kernel_checks = f"error:{type(e).__name__}"

    # quality mode: the spike-wave config (wave_spike_reserve=16) trades
    # ~1.5x iteration cost for oracle-parity AUC (PERF_NOTES round-5
    # frontier); measured here so the driver line carries both points
    q_elapsed = q_auc = None
    if os.environ.get("BENCH_QUALITY_MODE", "1") != "0":
        qp = dict(params)
        qp["wave_spike_reserve"] = 16
        qb = lgb.Booster(params=qp, train_set=train_set)
        for _ in range(WARMUP):
            qb.update()
        _ = np.asarray(qb._gbdt.scores[0][:8])
        t0 = time.time()
        for _ in range(ITERS):
            qb.update()
        _ = np.asarray(qb._gbdt.scores[0][:8])
        q_elapsed = (time.time() - t0) / ITERS
        q_auc = _auc(yte, qb._gbdt.predict_raw(Xte))

    baseline = BASELINE_SEC_PER_ITER_10M * ROWS / HIGGS_ROWS
    out = {
        "metric": f"higgs_like_{ROWS//1000}k_binary_255leaves_sec_per_iter",
        "value": round(elapsed, 4),
        "unit": "s/iter",
        "vs_baseline": round(baseline / elapsed, 4),
        "auc": round(auc, 5),
        "iters_trained": WARMUP + ITERS,
        "kernel_checks": kernel_checks,
        "backend": jax.default_backend(),
        "backend_fallback": backend_fallback,
        # setup split: construct (binning + placement + init) vs the
        # first-update compile a persistent compile_cache_dir removes
        "setup_construct_s": round(setup_construct_s, 3),
        "setup_compile_s": round(setup_compile_s, 3),
        # host-blocking ms per instrumented iteration (eval tick +
        # pipelined tree materialization + checkpoint capture)
        "host_block_ms_per_iter": host_block_ms_per_iter,
        # where the time goes: [scope, total_ms, calls] over 3
        # instrumented post-loop iterations (top scopes first)
        "timer_top_ms": timer_top,
        # compiled-HLO roofline over the same window
        # (docs/Observability.md): per-phase measured MFU, arithmetic
        # intensity and compute- vs HBM-bound classification
        "measured_mfu": (round(measured_mfu, 7)
                         if measured_mfu is not None else None),
        "roofline": {g: {"mfu": (round(v["mfu"], 7)
                                 if v.get("mfu") is not None else None),
                         "ai": (round(v["arithmetic_intensity"], 4)
                                if v.get("arithmetic_intensity")
                                is not None else None),
                         "bound": v.get("bound"),
                         "flops": v.get("flops"),
                         "bytes": v.get("bytes")}
                     for g, v in roofline_phases.items()},
        # serving throughput per predict path (rows/s; *_rows = measured
        # batch — python is subsampled, device shrinks off-TPU)
        "predict_rows_per_s": predict_rows_per_s,
        # jaxpr-level audit verdict for the entries this run compiled
        # (docs/StaticAnalysis.md v4): true = hot path proven f64-free,
        # callback-free, churn-free at the IR level
        "ir_audit_clean": ir_audit_clean,
        "ir_audit": ir_audit,
    }
    if mem.get("device_peak_bytes_in_use") is not None:
        out["peak_device_bytes"] = mem["device_peak_bytes_in_use"]
    if q_elapsed is not None:
        out["quality_mode_sec_per_iter"] = round(q_elapsed, 4)
        out["quality_mode_auc"] = round(q_auc, 5)
    # measured-oracle anchor (tools/bench_oracle.py): the REAL reference
    # CLI trained on this same dataset on this host — pins the target AUC
    # and a same-host time next to the docs-scaled 2015 28-core anchor
    oracle = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "docs", "oracle_bench.json")
    config_is_default = (NUM_LEAVES == 255 and ITERS == 10
                        and params["max_bin"] == 255)
    if os.path.exists(oracle) and config_is_default:
        try:
            ref = json.load(open(oracle))
        except (OSError, ValueError):
            ref = {}
        # the anchor is comparable only when the oracle trained the same
        # number of trees as this run's AUC measurement
        if (ref.get("rows") == ROWS
                and ref.get("num_leaves") == NUM_LEAVES
                and ref.get("iters_lo") == WARMUP + ITERS):
            if ref.get("ref_auc_at_iters_lo") is not None:
                out["ref_auc"] = ref["ref_auc_at_iters_lo"]
            sec = ref.get("ref_sec_per_iter")
            if sec is not None and sec > 0:
                out["ref_sec_per_iter"] = sec
                out["ref_host_cpus"] = ref.get("host_cpus")
                out["vs_ref_measured"] = round(sec / elapsed, 4)
    # BASELINE 10M-row workload (tools/bench_10m.py, >=100 timed iters on
    # the chip) and its same-host oracle (tools/bench_oracle_10m.py):
    # folded into the single driver line when measured this round
    for fname, prefix, keys in (
            ("bench_10m.json", "b10m_",
             ("sec_per_iter", "auc", "iters", "vs_baseline_28core_2015",
              "setup_s", "e2e_500iter_s",
              "e2e_500iter_vs_baseline_28core_2015",
              "useful_mac_mfu", "measured_mfu", "roofline_bound",
              "measured_vs_useful_mac_ratio", "measured_at")),
            ("oracle_bench_10m.json", "b10m_ref_",
             ("ref_sec_per_iter", "ref_auc_at_iters", "host_cpus"))):
        p = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "docs", fname)
        if os.path.exists(p):
            try:
                d = json.load(open(p))
            except (OSError, ValueError):
                continue
            if d.get("rows") == 10_000_000:
                for k in keys:
                    if d.get(k) is not None:
                        out[prefix + k.replace("ref_", "")] = d[k]
    print(json.dumps(out))


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--diff":
        if len(sys.argv) != 4:
            print("usage: python bench.py --diff A.json B.json",
                  file=sys.stderr)
            sys.exit(2)
        sys.exit(diff_main(sys.argv[2], sys.argv[3]))
    if len(sys.argv) >= 2 and sys.argv[1] == "--multichip":
        n = int(sys.argv[2]) if len(sys.argv) > 2 else 8
        sys.exit(multichip_main(n))
    if len(sys.argv) >= 2 and sys.argv[1] == "--serve":
        sys.exit(serve_main(smoke="--smoke" in sys.argv[2:]))
    if len(sys.argv) >= 2 and sys.argv[1] == "--serve-fleet":
        sys.exit(serve_fleet_main(smoke="--smoke" in sys.argv[2:]))
    if len(sys.argv) >= 2 and sys.argv[1] == "--online":
        sys.exit(online_main(smoke="--smoke" in sys.argv[2:]))
    main()
