"""End-to-end walkthrough of the lightgbm_tpu API surface.

Mirrors the reference's examples/python-guide: train/valid flow with
early stopping, sklearn estimators, categorical features, SHAP,
model IO, continued training, and the CLI. Run:

    python examples/walkthrough.py
"""

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root (package not pip-installed)
import lightgbm_tpu as lgb


def main():
    rng = np.random.RandomState(0)
    n = 5000
    X = rng.randn(n, 6)
    X[:, 5] = rng.randint(0, 8, n)                 # a categorical column
    logit = X[:, 0] + X[:, 1] * X[:, 2] + (X[:, 5] > 4)
    y = (rng.rand(n) < 1 / (1 + np.exp(-logit))).astype(np.float64)
    X_tr, X_va, y_tr, y_va = X[:4000], X[4000:], y[:4000], y[4000:]

    # --- core train() API with a valid set + early stopping ------------
    train_set = lgb.Dataset(X_tr, label=y_tr, categorical_feature=[5])
    valid_set = train_set.create_valid(X_va, label=y_va)
    booster = lgb.train(
        {"objective": "binary", "num_leaves": 31, "learning_rate": 0.1,
         "metric": ["auc", "binary_logloss"], "early_stopping_round": 10,
         "verbosity": -1},
        train_set, num_boost_round=200,
        valid_sets=[valid_set], valid_names=["valid"])
    print("best_iteration:", booster.best_iteration)

    # --- prediction modes ---------------------------------------------
    proba = booster.predict(X_va)
    raw = booster.predict(X_va, raw_score=True)
    leaves = booster.predict(X_va, pred_leaf=True)
    shap = booster.predict(X_va, pred_contrib=True)   # native TreeSHAP
    assert np.allclose(shap.sum(1), raw, rtol=1e-5)
    print("AUC-ish acc:", float(np.mean((proba > 0.5) == y_va)))
    print("leaf matrix:", leaves.shape, "| SHAP:", shap.shape)

    # --- model IO + continued training --------------------------------
    with tempfile.NamedTemporaryFile(suffix=".txt") as f:
        booster.save_model(f.name)
        reloaded = lgb.Booster(model_file=f.name)
        assert np.allclose(reloaded.predict(X_va), proba, rtol=1e-6)
        more = lgb.train({"objective": "binary", "verbosity": -1},
                         lgb.Dataset(X_tr, label=y_tr,
                                     categorical_feature=[5]),
                         num_boost_round=5, init_model=f.name)
        print("continued to", more._gbdt.current_iteration(), "iters")

    # --- sklearn estimators -------------------------------------------
    clf = lgb.LGBMClassifier(n_estimators=30, num_leaves=15)
    clf.fit(X_tr, y_tr, eval_set=[(X_va, y_va)])
    print("sklearn acc:", float(np.mean(clf.predict(X_va) == y_va)))

    # --- distributed (virtual mesh; on a pod this is multi-chip) ------
    b_dp = lgb.train({"objective": "binary", "num_leaves": 15,
                      "tree_learner": "data", "verbosity": -1},
                     lgb.Dataset(X_tr, label=y_tr), num_boost_round=10)
    mesh = b_dp._gbdt.mesh
    print("data-parallel mesh:", None if mesh is None
          else tuple(mesh.shape.items()))


if __name__ == "__main__":
    main()
