"""lightgbm_tpu: a TPU-native gradient-boosting framework.

A from-scratch JAX/XLA re-design of the LightGBM surface (reference analyzed in
SURVEY.md): histogram-based leaf-wise GBDT/DART/RF, the full objective/metric suite,
LightGBM-compatible model text format and train()/predict() API — with binned features
resident in TPU HBM, whole-tree growth inside jitted XLA programs, and distributed
data-parallel training over `jax.sharding.Mesh` ICI/DCN collectives.
"""

__version__ = "0.1.0"

from .config import Config
from .io.dataset import Dataset as _RawDataset  # internal binned dataset
from .utils.log import LightGBMError, register_callback

__all__ = [
    "Config",
    "LightGBMError",
    "register_callback",
    "__version__",
]
