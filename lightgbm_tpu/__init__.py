"""lightgbm_tpu: a TPU-native gradient-boosting framework.

A from-scratch JAX/XLA re-design of the LightGBM surface (reference analyzed in
SURVEY.md): histogram-based leaf-wise GBDT/DART/RF, the full objective/metric suite,
LightGBM-compatible model text format and train()/predict() API — with binned features
resident in TPU HBM, whole-tree growth inside jitted XLA programs, and distributed
data-parallel training over `jax.sharding.Mesh` ICI/DCN collectives.

Public surface mirrors python-package/lightgbm/__init__.py.
"""

__version__ = "0.1.0"

from .basic import Booster, Dataset, Sequence
from .callback import (EarlyStopException, checkpoint, early_stopping,
                       log_evaluation, record_evaluation, record_metrics,
                       reset_parameter)
from .config import Config
from .engine import CVBooster, cv, train
from .reliability import CheckpointManager, NonFiniteError
from .plotting import (create_tree_digraph, plot_importance,
                       plot_metric, plot_split_value_histogram, plot_tree)
from .sklearn import (LGBMClassifier, LGBMModel, LGBMRanker,
                      LGBMRegressor)
from .utils.log import (LightGBMError, register_callback,
                        register_logger)

__all__ = [
    "plot_importance", "plot_metric", "plot_split_value_histogram",
    "plot_tree", "create_tree_digraph",
    "LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker",
    "Booster",
    "CVBooster",
    "CheckpointManager",
    "Config",
    "Dataset",
    "EarlyStopException",
    "NonFiniteError",
    "checkpoint",
    "LightGBMError",
    "Sequence",
    "cv",
    "early_stopping",
    "log_evaluation",
    "record_evaluation",
    "record_metrics",
    "register_callback",
    "register_logger",
    "train",
    "__version__",
]
