"""`python -m lightgbm_tpu config=train.conf` (ref: src/main.cpp:14)."""
import sys

from .cli import main

sys.exit(main())
