"""Entrypoint manifest for the tpulint IR audit (docs/StaticAnalysis.md v4).

Every hot jitted entry the RecompileDetector fingerprints at runtime —
the grow/grow-wave engines (donated or not), the gradient program,
DeviceEval's packed eval tick, and the inference bucket ladder that the
serving dispatch compiles — is declared here with exemplar
`jax.ShapeDtypeStruct` signatures, the SAME (shape, dtype, static)
scheme the recompile watchdog and the cost model key on
(observability/watchdog.py call_signature).  `python -m tools.tpulint
--ir` abstractly traces each entry to its ClosedJaxpr (no device, no
data, no compile) and runs the IR rule passes over it: a silent
f32→f64 weak-type promotion, a pure_callback smuggled into device
code, a convert_element_type round trip, or a giant literal baked into
the program is a 10–20× TPU regression invisible in source — this file
is where it becomes lint-visible.  The reference enforces the same
discipline (histogram entry width, device/host boundaries) in its C++
type system; our typed artifact is the jaxpr.

Protocol (consumed by tools/tpulint/ir/trace.py, duck-typed so the
package never imports tools/):

* the module exposes `ENTRIES`, an iterable of objects with attributes
  `name` (detector-style entry name), `group` (RecompileDetector
  accounting group, `costmodel.group_of` of the runtime name), `build`
  (zero-argument callable returning `fn` or `(fn, args)` or
  `(fn, args, kwargs)` ready for abstract tracing), `declares`
  (frozenset of IR-shape declarations the scatter-audit rule honours)
  and `line` (anchor for findings/suppressions);
* exemplar sizes are deliberately small — the IR rules check dtypes,
  primitives and constants, none of which depend on the exemplar's row
  count staying production-sized;
* entries are traced under `jax.experimental.enable_x64` so weak-type
  float64 promotions (an np.float64 constant leaking into f32 device
  code) become VISIBLE instead of being silently squashed by the
  default x64-off config.

Declarations (`declares`) are entry-level, pattern-scoped suppressions
with the justification carried by the manifest itself:

* ``onehot-dot`` — the entry intentionally builds histograms through
  XLA's one-hot × MXU dot trick (the shape the ROADMAP's Pallas
  histogram kernel replaces); undeclared one-hot dots are findings so
  the pattern cannot silently spread to new entries.
* ``narrow-acc`` — the entry intentionally accumulates into sub-32-bit
  histogram entries (the LightGBM-style quantized-gradient path);
  undeclared narrow accumulation is an overflow hazard and a finding.
"""

from __future__ import annotations

from typing import NamedTuple

# exemplar dimensions — small on purpose (see module docstring)
_F = 8          # features
_N = 4096       # rows
_B = 255        # max_bin
_T = 6          # trees in the packed-inference exemplar
_NI = 31        # internal nodes per tree
_NL = 32        # leaves per tree
_W = 8          # categorical bitset words


class LintEntry(NamedTuple):
    name: str
    group: str
    build: object       # () -> fn | (fn, args) | (fn, args, kwargs)
    declares: frozenset
    line: int


ENTRIES = []


def lint_entry(name: str, declares=()):
    """Register `build` as the manifest entry `name`; the accounting
    group is the detector-name prefix (costmodel.group_of)."""
    def deco(build):
        ENTRIES.append(LintEntry(
            name=name, group=name.split("[", 1)[0], build=build,
            declares=frozenset(declares),
            line=build.__code__.co_firstlineno))
        return build
    return deco


# ----------------------------------------------------------------- helpers
def _sds(shape, dtype):
    import jax
    import numpy as np
    return jax.ShapeDtypeStruct(shape, np.dtype(dtype))


def _feature_meta():
    from .learner.grow import FeatureMeta
    return FeatureMeta(num_bin=_sds((_F,), "int32"),
                       missing_type=_sds((_F,), "int32"),
                       default_bin=_sds((_F,), "int32"),
                       penalty=_sds((_F,), "float32"))


def _grow_args():
    """(binned, grad, hess, row_mask, col_mask, meta) — the positional
    prefix of every grow entry (boosting/gbdt.py train_one_iter)."""
    return (_sds((_F, _N), "uint8"), _sds((_N,), "float32"),
            _sds((_N,), "float32"), _sds((_N,), "float32"),
            _sds((_F,), "bool"), _feature_meta())


def _config(**params):
    from .config import Config
    return Config(dict(params, verbosity=-1))


def _binary_objective():
    import numpy as np
    from .objective import BinaryLogloss
    obj = BinaryLogloss(_config(objective="binary"))
    # init() only derives class-balance scalars; a two-row exemplar
    # label gives the same traced program as any real dataset
    class _MD:
        label = np.asarray([0.0, 1.0], np.float32)
        weight = None
    obj.init(_MD(), 2)
    return obj


def _multiclass_objective(K: int = 3):
    import numpy as np
    from .objective import MulticlassSoftmax
    obj = MulticlassSoftmax(_config(objective="multiclass", num_class=K))
    class _MD:  # noqa: E306
        label = np.arange(K, dtype=np.float32)
        weight = None
    obj.init(_MD(), K)
    return obj


# ------------------------------------------------------- grow (tree growth)
# Runtime detector name: "grow_tree" (boosting/gbdt.py wraps whichever
# engine the strategy selected).  One manifest entry per engine variant
# so the audit sees every program the single runtime name can stand for.

@lint_entry("grow_tree[leafwise]")
def _build_grow_leafwise():
    from .learner.grow import GrowParams, grow_tree
    params = GrowParams(num_leaves=15, max_bin=_B, compact_min=0)
    return grow_tree, (*_grow_args(), params)


@lint_entry("grow_tree[leafwise-donated]")
def _build_grow_leafwise_donated():
    from .learner.grow import GrowParams, grow_tree_donated
    params = GrowParams(num_leaves=15, max_bin=_B, compact_min=0)
    return grow_tree_donated, (*_grow_args(), params)


@lint_entry("grow_tree[leafwise-hist-stack]")
def _build_grow_leafwise_hist_stack():
    # the per-leaf histogram stack + partitioned-segment engine — the
    # default single-device leaf-wise configuration
    from .learner.grow import GrowParams, grow_tree
    params = GrowParams(num_leaves=15, max_bin=_B, use_hist_stack=True,
                        compact_min=1024)
    return grow_tree, (*_grow_args(), params)


@lint_entry("grow_tree[wave]", declares=("onehot-dot",))
def _build_grow_wave():
    # declares onehot-dot: the wave engine's histogram IS the XLA
    # one-hot × MXU dot (PERF_NOTES round 3) — the declared shape the
    # ROADMAP's Pallas histogram kernel replaces
    from .learner.grow import GrowParams
    from .learner.wave import grow_tree_wave
    params = GrowParams(num_leaves=16, max_bin=_B)
    return grow_tree_wave, (*_grow_args(), params)


@lint_entry("grow_tree[wave-donated]", declares=("onehot-dot",))
def _build_grow_wave_donated():
    from .learner.grow import GrowParams
    from .learner.wave import grow_tree_wave_donated
    params = GrowParams(num_leaves=16, max_bin=_B)
    return grow_tree_wave_donated, (*_grow_args(), params)


@lint_entry("grow_tree[wave-quant]", declares=("onehot-dot", "narrow-acc"))
def _build_grow_wave_quant():
    # quantized training: int8-packed grad/hess through the MXU int8
    # histogram path — narrow accumulation is the point (declared), and
    # the audit guards the convert discipline around it
    from .learner.grow import GrowParams
    from .learner.wave import grow_tree_wave
    params = GrowParams(num_leaves=16, max_bin=_B, quant_bins=16)
    return grow_tree_wave, (*_grow_args(), params), {
        "quant_scales": _sds((2,), "float32")}


@lint_entry("grow_tree[wave-sharded]", declares=("onehot-dot",))
def _build_grow_wave_sharded():
    # the data-parallel engine: shard_map over a row mesh + histogram
    # psum (parallel/data_parallel.py).  Traced on however many local
    # devices exist — the PROGRAM (and thus the IR discipline) is the
    # same at any axis size; only the axis extent changes.
    import jax
    import numpy as np
    from jax.sharding import Mesh
    from .learner.grow import GrowParams
    from .parallel.data_parallel import DATA_AXIS, make_sharded_wave_fn
    devs = np.array(jax.devices()[:1])
    mesh = Mesh(devs, (DATA_AXIS,))
    fn = make_sharded_wave_fn(mesh)
    params = GrowParams(num_leaves=16, max_bin=_B, compact_min=0)
    # .build is the EXACT production jit entry (shard_map + specs);
    # the plain wrapper resolves params/kwargs host-side per call
    return fn.build(params, ()), _grow_args()


# ------------------------------------------------------------- gradients
# Runtime detector name: "gradients" (boosting/gbdt.py _grad_fn_raw).

@lint_entry("gradients[regression]")
def _build_gradients_regression():
    import jax
    from .objective import RegressionL2
    obj = RegressionL2(_config(objective="regression"))

    # the K == 1 wrapper mirrors gbdt.py _grad1: slice + expand in-jit
    def _grad1(sc, lab, w):
        g, h = obj.get_gradients(sc[0], lab, w)
        return g[None, :], h[None, :]
    return jax.jit(_grad1), (_sds((1, _N), "float32"),
                             _sds((_N,), "float32"), None)


@lint_entry("gradients[binary]")
def _build_gradients_binary():
    import jax
    obj = _binary_objective()

    def _grad1(sc, lab, w):
        g, h = obj.get_gradients(sc[0], lab, w)
        return g[None, :], h[None, :]
    return jax.jit(_grad1), (_sds((1, _N), "float32"),
                             _sds((_N,), "float32"), None)


@lint_entry("gradients[multiclass]")
def _build_gradients_multiclass():
    import jax
    obj = _multiclass_objective()
    fn = jax.jit(lambda sc, lab, w: obj.get_gradients(sc, lab, w))
    return fn, (_sds((3, _N), "float32"), _sds((_N,), "float32"),
                _sds((_N,), "float32"))


# ------------------------------------------------------------ device_eval
# Runtime detector name: "device_eval" (ops/metrics.py DeviceEval).

def _tick_args(K: int):
    # (scores, label, weight, pad_mask, grad_ok) — DeviceEval.run
    return (_sds((K, _N), "float32"), _sds((_N,), "float32"), None,
            _sds((_N,), "float32"), _sds((), "bool"))


@lint_entry("device_eval[binary-auc]")
def _build_device_eval_binary():
    import jax
    from .metric import create_metrics
    from .ops.metrics import build_plans, make_tick_fn
    obj = _binary_objective()
    cfg = _config(objective="binary", metric="auc,binary_logloss")
    plans = build_plans(create_metrics(cfg), cfg, obj, 1)
    return jax.jit(make_tick_fn(plans, obj, 1, 1)), _tick_args(1)


@lint_entry("device_eval[regression-rmse]")
def _build_device_eval_regression():
    import jax
    from .metric import create_metrics
    from .ops.metrics import build_plans, make_tick_fn
    from .objective import RegressionL2
    obj = RegressionL2(_config(objective="regression"))
    cfg = _config(objective="regression", metric="rmse,l1")
    plans = build_plans(create_metrics(cfg), cfg, obj, 1)
    return jax.jit(make_tick_fn(plans, obj, 1, 1)), _tick_args(1)


@lint_entry("device_eval[multiclass]")
def _build_device_eval_multiclass():
    import jax
    from .metric import create_metrics
    from .ops.metrics import build_plans, make_tick_fn
    obj = _multiclass_objective()
    cfg = _config(objective="multiclass", num_class=3,
                  metric="multi_logloss,multi_error")
    plans = build_plans(create_metrics(cfg), cfg, obj, 3)
    return jax.jit(make_tick_fn(plans, obj, 3, 1)), _tick_args(3)


# ---------------------------------------------- device_predict (inference)
# Runtime detector names: "device_predict[<mode>@<bucket>]" — one per
# (mode, bucket) rung of the ladder DevicePredictor._fn_for compiles and
# the serving registry warms.  The program is bucket-size-generic, so
# one exemplar bucket per MODE covers the whole ladder.

def _pack_args():
    """The 11 packed-ensemble arrays (inference/pack.py layout)."""
    return (_sds((_T, _NI), "int32"),    # split_feature
            _sds((_T, _NI), "float32"),  # threshold (f32-floored)
            _sds((_T, _NI), "int32"),    # missing_type
            _sds((_T, _NI), "bool"),     # default_left
            _sds((_T, _NI), "bool"),     # is_cat
            _sds((_T, _NI), "int32"),    # left
            _sds((_T, _NI), "int32"),    # right
            _sds((_T, _NL), "float32"),  # leaf_value
            _sds((_T, _NI), "int32"),    # cat_start
            _sds((_T, _NI), "int32"),    # cat_nwords
            _sds((_W,), "uint32"))       # cat_words


def _predict_entry(mode: str, num_class: int = 1, convert=None,
                   es_freq: int = 0, average: bool = False):
    import jax
    from .inference.predictor import build_program
    fn = jax.jit(build_program(6, num_class, average, convert, mode,
                               es_freq), donate_argnums=(0,))
    x = _sds((_N, _F), "float32")
    if es_freq > 0:
        return fn, (x, _sds((), "float32"), *_pack_args())
    return fn, (x, *_pack_args())


@lint_entry("device_predict[raw]")
def _build_predict_raw():
    return _predict_entry("raw")


@lint_entry("device_predict[leaf]")
def _build_predict_leaf():
    return _predict_entry("leaf")


@lint_entry("device_predict[convert]")
def _build_predict_convert():
    # the serving dispatch's default mode: objective conversion fused
    obj = _binary_objective()
    return _predict_entry("convert", convert=obj.convert_output)


@lint_entry("device_predict[convert-multiclass]")
def _build_predict_convert_multiclass():
    obj = _multiclass_objective()
    return _predict_entry("convert", num_class=3,
                          convert=obj.convert_output)


@lint_entry("device_predict[raw-es]")
def _build_predict_raw_es():
    # prediction early stopping: the masked lax.scan accumulation
    return _predict_entry("raw", es_freq=10)


@lint_entry("device_predict[raw-average]")
def _build_predict_raw_average():
    # RF output averaging (average_output models)
    return _predict_entry("raw", average=True)
