"""User-facing Dataset and Booster (ref: python-package/lightgbm/basic.py).

The reference's basic.py talks to the C++ core over ctypes (LGBM_* C API); here
the "core" is the in-process TPU engine, so these classes wrap
io.dataset.Dataset and boosting.GBDT directly with the same surface.
"""

from __future__ import annotations

import abc
from typing import Any, Dict, List, Optional, Sequence as _TSeq, Union

import numpy as np

from .config import Config
from .io.dataset import Dataset as _CoreDataset, load_dataset_from_file
from .metric import create_metrics
from .objective import create_objective
from .boosting import create_boosting
from .boosting.model_io import (load_model_from_file, load_model_from_string,
                                save_model_to_file, save_model_to_string)
from .utils import log


def _coerce_matrix(data) -> np.ndarray:
    """pandas / pyarrow / scipy-sparse / array-like -> float ndarray.
    float32 passes through unconverted: binning treats it per column, and
    large float32 matrices take the exact device bucketize path
    (io/device_bin.py) instead of a host float64 pass."""
    if (type(data).__module__ or "").startswith("pyarrow"):
        return np.column_stack([
            np.asarray(data.column(i).to_numpy(zero_copy_only=False),
                       dtype=np.float64)
            for i in range(data.num_columns)])
    if hasattr(data, "values"):          # pandas
        data = data.values
    if hasattr(data, "toarray"):         # scipy CSR/CSC/COO
        data = data.toarray()
    data = np.asarray(data)
    if data.dtype == np.float32:
        return data
    return np.asarray(data, dtype=np.float64)


class Sequence(abc.ABC):
    """Generic batched/random data access interface for Dataset
    construction (ref: python-package basic.py Sequence): supports
    `len(seq)`, integer/slice indexing, and an optional `batch_size`.
    Dataset accepts a Sequence (or list of Sequences, concatenated
    row-wise) and reads it in batches, so the full data never needs to
    exist as one in-memory array on the caller's side."""

    batch_size = 4096

    @abc.abstractmethod
    def __getitem__(self, idx):
        raise NotImplementedError

    @abc.abstractmethod
    def __len__(self) -> int:
        raise NotImplementedError


def _materialize_sequences(seqs) -> np.ndarray:
    """Batched reads -> one float64 matrix (the TPU Dataset bins from a
    dense matrix; batching bounds the caller's per-read memory)."""
    parts = []
    for seq in seqs:
        n = len(seq)
        bs = max(1, int(getattr(seq, "batch_size", 4096) or 4096))
        for lo in range(0, n, bs):
            chunk = np.asarray(seq[lo:min(lo + bs, n)], dtype=np.float64)
            parts.append(chunk.reshape(chunk.shape[0], -1))
    if not parts:
        log.fatal("Cannot construct a Dataset from empty Sequence input")
    return np.concatenate(parts, axis=0)


class Dataset:
    """Lazily-constructed training dataset (ref: basic.py:1555 Dataset)."""

    def __init__(self, data, label=None, reference: Optional["Dataset"] = None,
                 weight=None, group=None, init_score=None,
                 feature_name="auto", categorical_feature="auto",
                 params: Optional[Dict[str, Any]] = None,
                 free_raw_data: bool = True, position=None):
        self.data = data
        self.label = label
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = dict(params or {})
        self.free_raw_data = free_raw_data
        self.position = position
        self._core: Optional[_CoreDataset] = None
        self.used_indices: Optional[np.ndarray] = None

    # ------------------------------------------------------------------
    def _construct_from_sequences(self, seqs, cfg) -> "Dataset":
        """Streamed (two-round) Sequence ingestion: batches are read
        twice, bin codes are packed directly, and the concatenated float
        matrix never exists (ref: the streaming push ingestion of
        c_api.h:177-323 LGBM_DatasetPushRows)."""
        from .io.dataset import Dataset as _CD
        if cfg.linear_tree:
            # same rejection as the file path (io/dataset.py): linear
            # leaves need the raw values two_round exists to not hold
            log.fatal("Cannot use two_round loading with linear tree")

        def stream():
            for seq in seqs:
                n = len(seq)
                bs = max(1, int(getattr(seq, "batch_size", 4096) or 4096))
                for lo in range(0, n, bs):
                    chunk = np.asarray(seq[lo:min(lo + bs, n)],
                                       dtype=np.float64)
                    yield chunk.reshape(chunk.shape[0], -1), None

        names = (None if self.feature_name == "auto"
                 else list(self.feature_name))
        cat = []
        if self.categorical_feature not in ("auto", None):
            for c in self.categorical_feature:
                if isinstance(c, str) and names is not None:
                    cat.append(names.index(c))
                elif not isinstance(c, str):
                    cat.append(int(c))
                else:
                    log.warning(f"categorical_feature {c!r} needs "
                                "feature_name to resolve; ignored")
        ref_core = (self.reference._core_or_construct()
                    if self.reference else None)
        self._core = _CD.construct_from_stream(
            stream, weight=self.weight, group=self.group,
            max_bin=cfg.max_bin, min_data_in_bin=cfg.min_data_in_bin,
            min_data_in_leaf=cfg.min_data_in_leaf,
            bin_construct_sample_cnt=cfg.bin_construct_sample_cnt,
            categorical_feature=cat, feature_names=names,
            use_missing=cfg.use_missing,
            zero_as_missing=cfg.zero_as_missing,
            feature_pre_filter=cfg.feature_pre_filter,
            seed=cfg.data_random_seed,
            max_bin_by_feature=cfg.max_bin_by_feature or None,
            forcedbins_filename=cfg.forcedbins_filename,
            reference=ref_core)
        if self.label is not None:
            self._core.metadata.set_label(self.label)
        if self.init_score is not None:
            self._core.metadata.set_init_score(self.init_score)
        if self.position is not None:
            self._core.metadata.set_position(self.position)
        return self

    # ------------------------------------------------------------------
    def construct(self) -> "Dataset":
        if self._core is not None:
            return self
        cfg = Config(self.params)
        ref_core = self.reference._core_or_construct() if self.reference else None
        if isinstance(self.data, (str, bytes)):
            self._core = load_dataset_from_file(str(self.data), cfg,
                                                reference=ref_core)
            if self.label is not None:
                self._core.metadata.set_label(self.label)
        else:
            data = self.data
            seqs = None
            if isinstance(data, Sequence):
                seqs = [data]
            elif (isinstance(data, list) and data
                    and all(isinstance(s, Sequence) for s in data)):
                seqs = data
            if seqs is not None and cfg.two_round:
                # STREAMED Sequence ingestion (the incremental-push
                # ingestion role of LGBM_DatasetPushRows,
                # c_api.h:177-323): Sequences are random-access, so the
                # two-round streaming constructor reads them twice in
                # batches and the full float matrix never materializes
                return self._construct_from_sequences(seqs, cfg)
            if seqs is not None:
                data = _materialize_sequences(seqs)
            # column names from pandas / arrow before coercion
            if self.feature_name == "auto":
                if (type(data).__module__ or "").startswith("pyarrow") \
                        and hasattr(data, "column_names"):
                    self.feature_name = list(data.column_names)
                elif hasattr(data, "columns"):
                    self.feature_name = list(map(str, data.columns))
            cat = []
            if self.categorical_feature not in ("auto", None):
                for c in self.categorical_feature:
                    if isinstance(c, str) and self.feature_name != "auto":
                        cat.append(list(self.feature_name).index(c))
                    else:
                        cat.append(int(c))
            names = (None if self.feature_name == "auto"
                     else list(self.feature_name))
            from .io.sparse import construct_from_sparse, is_scipy_sparse
            if is_scipy_sparse(data) and not cfg.linear_tree:
                # scipy CSR/CSC/COO (LGBM_DatasetCreateFromCSR/CSC) go
                # CSC-direct-to-EFB-bundles: the dense [n, F] matrix is
                # never materialized (ref: sparse_bin.hpp /
                # multi_val_sparse_bin.hpp, redesigned as bundle codes —
                # io/sparse.py).  linear_tree needs raw feature values,
                # so it falls through to the dense path.
                self._core = construct_from_sparse(
                    data, label=self.label, weight=self.weight,
                    group=self.group, init_score=self.init_score,
                    max_bin=cfg.max_bin,
                    min_data_in_bin=cfg.min_data_in_bin,
                    min_data_in_leaf=cfg.min_data_in_leaf,
                    bin_construct_sample_cnt=cfg.bin_construct_sample_cnt,
                    categorical_feature=cat, feature_names=names,
                    use_missing=cfg.use_missing,
                    zero_as_missing=cfg.zero_as_missing,
                    feature_pre_filter=cfg.feature_pre_filter,
                    seed=cfg.data_random_seed,
                    max_conflict_rate=cfg.max_conflict_rate,
                    enable_bundle=cfg.enable_bundle,
                    max_bin_by_feature=cfg.max_bin_by_feature or None,
                    forcedbins_filename=cfg.forcedbins_filename,
                    reference=ref_core)
                if self.position is not None:
                    self._core.metadata.set_position(self.position)
                if self.free_raw_data:
                    self.data = None
                return self
            # Arrow (arrow.h; LGBM_DatasetCreateFromArrow), pandas, and
            # remaining inputs are densified — device storage is dense
            # binned tensors and EFB re-compresses exclusive sparse columns
            data = _coerce_matrix(data)
            if ref_core is not None:
                self._core = ref_core.create_valid(
                    data, label=self.label, weight=self.weight,
                    group=self.group, init_score=self.init_score)
            else:
                self._core = _CoreDataset.construct_from_arrays(
                    data, label=self.label, weight=self.weight,
                    group=self.group, init_score=self.init_score,
                    max_bin=cfg.max_bin, min_data_in_bin=cfg.min_data_in_bin,
                    min_data_in_leaf=cfg.min_data_in_leaf,
                    bin_construct_sample_cnt=cfg.bin_construct_sample_cnt,
                    categorical_feature=cat, feature_names=names,
                    use_missing=cfg.use_missing,
                    zero_as_missing=cfg.zero_as_missing,
                    feature_pre_filter=cfg.feature_pre_filter,
                    seed=cfg.data_random_seed,
                    keep_raw_data=cfg.linear_tree or not self.free_raw_data,
                    max_bin_by_feature=cfg.max_bin_by_feature or None,
                    forcedbins_filename=cfg.forcedbins_filename)
        if self.position is not None:
            self._core.metadata.set_position(self.position)
        if self.free_raw_data and not isinstance(self.data, (str, bytes)):
            # the core keeps its own raw copy only when needed
            # (linear trees / free_raw_data=False), matching the reference
            self.data = None
        return self

    def _core_or_construct(self) -> _CoreDataset:
        self.construct()
        return self._core

    # ------------------------------------------------------------------
    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, params=None) -> "Dataset":
        return Dataset(data, label=label, reference=self, weight=weight,
                       group=group, init_score=init_score,
                       params=params or self.params)

    def subset(self, used_indices: _TSeq[int], params=None) -> "Dataset":
        core = self._core_or_construct().copy_subrow(
            np.asarray(used_indices, dtype=np.int64))
        out = Dataset.__new__(Dataset)
        out.__dict__.update(self.__dict__)
        out._core = core
        out.used_indices = np.asarray(used_indices)
        return out

    def save_binary(self, filename: str) -> "Dataset":
        self._core_or_construct().save_binary(filename)
        return self

    # ------------------------------------------------------------------
    def set_label(self, label) -> "Dataset":
        self.label = label
        if self._core is not None:
            self._core.metadata.set_label(label)
        return self

    def set_weight(self, weight) -> "Dataset":
        self.weight = weight
        if self._core is not None:
            self._core.metadata.set_weight(weight)
        return self

    def set_group(self, group) -> "Dataset":
        self.group = group
        if self._core is not None:
            self._core.metadata.set_group(group)
        return self

    def set_init_score(self, init_score) -> "Dataset":
        self.init_score = init_score
        if self._core is not None:
            self._core.metadata.set_init_score(init_score)
        return self

    def get_label(self):
        return (self._core.metadata.label if self._core is not None
                else self.label)

    def get_weight(self):
        return (self._core.metadata.weight if self._core is not None
                else self.weight)

    def get_group(self):
        if self._core is not None and self._core.metadata.query_boundaries is not None:
            return np.diff(self._core.metadata.query_boundaries)
        return self.group

    def get_init_score(self):
        return (self._core.metadata.init_score
                if self._core is not None else self.init_score)

    def get_position(self):
        return (self._core.metadata.position
                if self._core is not None else self.position)

    def set_position(self, position) -> "Dataset":
        self.position = position
        if self._core is not None and position is not None:
            self._core.metadata.set_position(position)
        return self

    def get_data(self):
        """Raw data (ref: basic.py get_data; raises after the raw data was
        freed, matching the reference's error).  Subsets return their own
        rows."""
        if self._core is not None and self.data is None:
            log.fatal("Cannot call `get_data` after freed raw data, set "
                      "free_raw_data=False when construct Dataset to avoid "
                      "this.")
        if self.used_indices is not None and self.data is not None \
                and not isinstance(self.data, (str, bytes)):
            return _coerce_matrix(self.data)[np.asarray(self.used_indices)]
        return self.data

    def get_field(self, field_name: str):
        """ref: basic.py get_field / LGBM_DatasetGetField."""
        getter = {"label": self.get_label, "weight": self.get_weight,
                  "group": self.get_group, "init_score": self.get_init_score,
                  "position": self.get_position}.get(field_name)
        if getter is None:
            log.fatal(f"Unknown field name: {field_name}")
        return getter()

    def set_field(self, field_name: str, data) -> "Dataset":
        """ref: basic.py set_field / LGBM_DatasetSetField."""
        setter = {"label": self.set_label, "weight": self.set_weight,
                  "group": self.set_group,
                  "init_score": self.set_init_score,
                  "position": self.set_position}.get(field_name)
        if setter is None:
            log.fatal(f"Unknown field name: {field_name}")
        return setter(data)

    def get_feature_name(self) -> List[str]:
        return self.feature_names()

    def set_feature_name(self, feature_name) -> "Dataset":
        if feature_name != "auto":
            names = list(feature_name)
            if (self._core is not None
                    and len(names) != self._core.num_total_features):
                log.fatal("Length of feature_name error")
            self.feature_name = names
            if self._core is not None:
                self._core.feature_names = list(map(str, names))
        return self

    def set_categorical_feature(self, categorical_feature) -> "Dataset":
        """ref: basic.py set_categorical_feature: free while the raw data
        is retained (triggers a re-bin); fatal once it was freed."""
        if self.categorical_feature == categorical_feature:
            return self
        if self.used_indices is not None:
            log.fatal("Cannot modify a Dataset returned by subset(); "
                      "apply the change to the parent Dataset instead")
        if self._core is not None:
            if self.data is None:
                log.fatal("Cannot set categorical feature after freed raw "
                          "data, set free_raw_data=False when construct "
                          "Dataset to avoid this.")
            log.warning("categorical_feature in Dataset is overridden.\n"
                        f"New categorical_feature is {categorical_feature}")
            self._core = None
        self.categorical_feature = categorical_feature
        return self

    def set_reference(self, reference: "Dataset") -> "Dataset":
        """ref: basic.py set_reference: free while the raw data is
        retained (triggers re-binning against the new reference)."""
        if reference is self.reference:
            return self
        if self.used_indices is not None:
            log.fatal("Cannot modify a Dataset returned by subset(); "
                      "apply the change to the parent Dataset instead")
        if self._core is not None:
            if self.data is None:
                log.fatal("Cannot set reference after freed raw data, set "
                          "free_raw_data=False when construct Dataset to "
                          "avoid this.")
            self._core = None
        self.reference = reference
        return self

    def get_ref_chain(self, ref_limit: int = 100):
        """Set of Datasets reachable through reference links
        (ref: basic.py get_ref_chain)."""
        head = self
        ref_chain = set()
        while len(ref_chain) < ref_limit:
            if isinstance(head, Dataset):
                ref_chain.add(head)
                if (head.reference is not None
                        and head.reference not in ref_chain):
                    head = head.reference
                else:
                    break
            else:
                break
        return ref_chain

    def feature_num_bin(self, feature: Union[int, str]) -> int:
        """Number of bins for a feature (ref: basic.py feature_num_bin /
        LGBM_DatasetGetFeatureNumBin)."""
        core = self._core_or_construct()
        if isinstance(feature, str):
            feature = core.feature_names.index(feature)
        return int(core.bin_mappers[feature].num_bin)

    def add_features_from(self, other: "Dataset") -> "Dataset":
        """Column-concatenate another Dataset's features into this one
        (ref: basic.py add_features_from / LGBM_DatasetAddFeaturesFrom).
        Both must still hold raw data; the merged Dataset re-bins."""
        if self.used_indices is not None or other.used_indices is not None:
            log.fatal("Cannot add features to/from a Dataset returned by "
                      "subset()")
        for ds, tag in ((self, "self"), (other, "other")):
            if ds.data is None:
                log.fatal(f"Cannot add features from {tag} with freed raw "
                          "data (set free_raw_data=False)")
        a = _coerce_matrix(self.data)
        b = _coerce_matrix(other.data)
        if a.shape[0] != b.shape[0]:
            log.fatal("Cannot add features from a Dataset with a different "
                      "row count")
        self.data = np.hstack([a, b])
        if self.feature_name != "auto" and other.feature_name != "auto":
            self.feature_name = (list(self.feature_name)
                                 + list(other.feature_name))
        else:
            self.feature_name = "auto"

        def _cats(ds, offset):
            cf = ds.categorical_feature
            if cf in ("auto", None):
                return []
            out = []
            for c in cf:
                if isinstance(c, str):
                    if ds.feature_name == "auto":
                        log.fatal("Cannot merge a name-based "
                                  "categorical_feature without feature "
                                  "names")
                    c = list(ds.feature_name).index(c)
                out.append(int(c) + offset)
            return out
        if not (self.categorical_feature in ("auto", None)
                and other.categorical_feature in ("auto", None)):
            self.categorical_feature = (_cats(self, 0)
                                        + _cats(other, a.shape[1]))
        self.reference = None  # widened columns cannot share old mappers
        self._core = None      # re-bin on next construct
        return self

    def num_data(self) -> int:
        return self._core_or_construct().num_data

    def num_feature(self) -> int:
        return self._core_or_construct().num_total_features

    def feature_names(self) -> List[str]:
        return self._core_or_construct().feature_names


class Booster:
    """ref: basic.py:2800 Booster (ctypes wrapper there; direct engine here)."""

    def __init__(self, params: Optional[Dict[str, Any]] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None):
        self.params = dict(params or {})
        self.best_iteration = -1
        self.best_score: Dict[str, Dict[str, float]] = {}
        self._train_set = train_set
        self.name_valid_sets: List[str] = []
        self._valid_wrappers: List[Dataset] = []
        if train_set is not None:
            cfg = Config(self.params)
            train_set.params = {**self.params, **train_set.params}
            core = train_set._core_or_construct()
            objective = create_objective(cfg)
            metrics = create_metrics(cfg)
            self._gbdt = create_boosting(cfg.boosting, cfg)
            self._gbdt.init(cfg, core, objective, metrics)
            self._num_valid = 0
        elif model_file is not None:
            self._gbdt = load_model_from_file(model_file)
        elif model_str is not None:
            self._gbdt = load_model_from_string(model_str)
        else:
            log.fatal("Booster needs train_set, model_file or model_str")

    # ------------------------------------------------------------------
    def add_valid(self, data: Dataset, name: str) -> "Booster":
        data.reference = data.reference or self._train_set
        core = data._core_or_construct()
        cfg = self._gbdt.config
        self._gbdt.add_valid_data(core, name, create_metrics(cfg))
        self.name_valid_sets.append(name)
        self._valid_wrappers.append(data)
        return self

    def update(self, train_set: Optional[Dataset] = None, fobj=None) -> bool:
        """One boosting iteration; returns True if stopped
        (ref: basic.py Booster.update -> LGBM_BoosterUpdateOneIter)."""
        if train_set is not None:
            log.fatal("Resetting training data is not yet supported")
        if fobj is not None:
            K = self._gbdt.num_tree_per_iteration
            n = self._gbdt.num_data
            self._gbdt.pre_gradient_hook()
            score = self.__inner_predict_train()
            grad, hess = fobj(score if K == 1 else score.T, self._train_set)
            grad = np.asarray(grad, np.float32)
            hess = np.asarray(hess, np.float32)
            if not (np.all(np.isfinite(grad)) and np.all(np.isfinite(hess))):
                from .reliability import NonFiniteError
                raise NonFiniteError(
                    "Custom objective returned NaN/Inf gradients at "
                    f"iteration {self._gbdt.current_iteration()}: boosting "
                    "on non-finite values produces garbage trees. Check the "
                    "objective for division by zero / log of non-positive "
                    "values.")
            if K > 1:
                grad = grad.T.reshape(K, n) if grad.ndim == 2 else grad.reshape(K, n)
                hess = hess.T.reshape(K, n) if hess.ndim == 2 else hess.reshape(K, n)
            return self._gbdt.train_one_iter(grad, hess)
        return self._gbdt.train_one_iter()

    def __inner_predict_train(self) -> np.ndarray:
        sc = np.asarray(self._gbdt.scores)[:, :self._gbdt.num_data]
        return sc[0] if sc.shape[0] == 1 else sc

    def rollback_one_iter(self) -> "Booster":
        self._gbdt.rollback_one_iter()
        return self

    def get_leaf_output(self, tree_id: int, leaf_id: int) -> float:
        """ref: basic.py get_leaf_output / LGBM_BoosterGetLeafValue."""
        self._gbdt._sync_model()
        return float(self._gbdt.models_[tree_id].leaf_value[leaf_id])

    def set_leaf_output(self, tree_id: int, leaf_id: int,
                        value: float) -> "Booster":
        """ref: basic.py set_leaf_output / LGBM_BoosterSetLeafValue."""
        self._gbdt._sync_model()
        self._gbdt.models_[tree_id].set_leaf_output(leaf_id, float(value))
        self._gbdt._model_mutations = getattr(
            self._gbdt, "_model_mutations", 0) + 1  # invalidate pred cache
        return self

    def get_split_value_histogram(self, feature, bins=None,
                                  xgboost_style: bool = False):
        """Histogram of a feature's split threshold values across the model
        (ref: basic.py get_split_value_histogram)."""
        self._gbdt._sync_model()
        if isinstance(feature, str):
            feature = self.feature_name().index(feature)
        values = []
        for tree in self._gbdt.models_:
            nl = tree.num_leaves
            for i in range(max(nl - 1, 0)):
                if (tree.split_feature[i] == feature
                        and tree.decision_type[i] & 1 == 0):  # numerical
                    values.append(float(tree.threshold[i]))
        values = np.asarray(values, np.float64)
        n_unique = len(np.unique(values))
        if bins is None or (isinstance(bins, int)
                            and bins > max(n_unique, 1)):
            bins = max(n_unique, 1)
        hist, bin_edges = np.histogram(values, bins=bins)
        if xgboost_style:
            ret = np.column_stack((bin_edges[1:], hist))
            return ret[ret[:, 1] > 0]
        return hist, bin_edges

    def free_network(self) -> "Booster":
        """No-op on TPU: collectives ride the XLA mesh runtime, there is
        no socket network to tear down (ref: basic.py free_network;
        SURVEY §2.2 N15)."""
        self.network = False
        return self

    def set_network(self, machines, local_listen_port: int = 12400,
                    listen_time_out: int = 120,
                    num_machines: int = 1) -> "Booster":
        """Accepted for API compatibility: multi-host runs configure the
        mesh through jax.distributed instead (ref: basic.py set_network)."""
        log.warning("set_network is a no-op on TPU: configure multi-host "
                    "training via jax.distributed + tree_learner=data")
        self.network = True
        return self

    def current_iteration(self) -> int:
        return self._gbdt.current_iteration()

    def num_trees(self) -> int:
        return self._gbdt.num_trees

    def num_model_per_iteration(self) -> int:
        return self._gbdt.num_tree_per_iteration

    def __getstate__(self):
        """Pickle via the model text (ref: basic.py Booster.__getstate__):
        the live GBDT holds device arrays and jitted closures."""
        state = self.__dict__.copy()
        state.pop("_train_set", None)
        state.pop("_valid_wrappers", None)  # hold raw data arrays
        gbdt = state.pop("_gbdt", None)
        state["_model_str"] = (save_model_to_string(gbdt)
                               if gbdt is not None else None)
        return state

    def __setstate__(self, state):
        model_str = state.pop("_model_str", None)
        self.__dict__.update(state)
        self._train_set = None
        # the restored GBDT is predictor-mode: no valid-set machinery
        self.name_valid_sets = []
        self._valid_wrappers = []
        self._gbdt = (load_model_from_string(model_str)
                      if model_str is not None else None)

    def model_from_string(self, model_str: str) -> "Booster":
        """Replace this booster's model (ref: basic.py model_from_string)."""
        self._gbdt = load_model_from_string(model_str)
        self._train_set = None
        self.name_valid_sets = []
        self.best_iteration = -1
        self.best_score = {}
        return self

    def dump_model(self, num_iteration: int = None,
                   start_iteration: int = 0) -> dict:
        """JSON model dump (ref: basic.py dump_model -> DumpModel;
        gbdt_model_text.cpp DumpModel)."""
        g = self._gbdt
        g._sync_model()
        K = g.num_tree_per_iteration
        total_iters = len(g.models_) // max(K, 1)
        if num_iteration is None:
            num_iteration = (self.best_iteration
                             if self.best_iteration > 0 else -1)
        if num_iteration < 0:
            num_iteration = total_iters - start_iteration
        end = min(start_iteration + num_iteration, total_iters)
        cfg = g.config
        ds = g.train_data
        trees = [g.models_[it * K + k].to_json(it * K + k)
                 for it in range(start_iteration, end) for k in range(K)]
        return {
            "name": "tree",
            "version": "v4",
            "num_class": cfg.num_class,
            "num_tree_per_iteration": K,
            "label_index": 0,
            "max_feature_idx": (ds.num_total_features - 1
                                if ds is not None else 0),
            "objective": cfg.objective,
            "feature_names": (ds.feature_names if ds is not None else []),
            "tree_info": trees,
        }

    def trees_to_dataframe(self):
        """Tree structure as a pandas DataFrame (ref: basic.py
        trees_to_dataframe)."""
        import pandas as pd
        g = self._gbdt
        g._sync_model()
        rows = []
        names = (g.train_data.feature_names if g.train_data is not None
                 else None)
        for ti, tree in enumerate(g.models_):
            nl = tree.num_leaves
            for i in range(max(nl - 1, 0)):
                f = int(tree.split_feature[i])
                is_cat = bool(tree.decision_type[i] & 1)
                rows.append(dict(
                    tree_index=ti, node_depth=None,
                    node_index=f"{ti}-S{i}",
                    split_feature=(names[f] if names and f < len(names)
                                   else f"Column_{f}"),
                    split_gain=float(tree.split_gain[i]),
                    threshold=("||".join(str(c)
                                         for c in tree._cats_of_node(i))
                               if is_cat else float(tree.threshold[i])),
                    decision_type="==" if is_cat else "<=",
                    left_child=int(tree.left_child[i]),
                    right_child=int(tree.right_child[i]),
                    value=float(tree.internal_value[i]),
                    weight=float(tree.internal_weight[i]),
                    count=int(tree.internal_count[i])))
            for l in range(nl):
                rows.append(dict(
                    tree_index=ti, node_depth=int(tree.leaf_depth[l]),
                    node_index=f"{ti}-L{l}", split_feature=None,
                    split_gain=None, threshold=None, decision_type=None,
                    left_child=None, right_child=None,
                    value=float(tree.leaf_value[l]),
                    weight=float(tree.leaf_weight[l]),
                    count=int(tree.leaf_count[l])))
        return pd.DataFrame(rows)

    def lower_bound(self) -> float:
        """Min possible raw prediction (ref: gbdt.h GetLowerBoundValue)."""
        self._gbdt._sync_model()
        return float(sum(t.leaf_value[:t.num_leaves].min()
                         for t in self._gbdt.models_))

    def upper_bound(self) -> float:
        """Max possible raw prediction (ref: gbdt.h GetUpperBoundValue)."""
        self._gbdt._sync_model()
        return float(sum(t.leaf_value[:t.num_leaves].max()
                         for t in self._gbdt.models_))

    def shuffle_models(self, start_iteration: int = 0,
                       end_iteration: int = -1) -> "Booster":
        """Random shuffle of tree order (ref: gbdt.h:114 ShuffleModels)."""
        g = self._gbdt
        g._sync_model()
        K = g.num_tree_per_iteration
        total = len(g.models_) // max(K, 1)
        end = total if end_iteration < 0 else min(end_iteration, total)
        idx = np.arange(start_iteration, end)
        np.random.RandomState(g.config.seed).shuffle(idx)
        g._model_mutations = getattr(g, "_model_mutations", 0) + 1
        blocks = [g.models_[i * K:(i + 1) * K] for i in range(total)]
        reordered = blocks[:start_iteration] + [blocks[i] for i in idx] \
            + blocks[end:]
        g.models_ = [t for b in reordered for t in b]
        return self

    def free_dataset(self) -> "Booster":
        """Drop the training dataset reference (ref: basic.py
        free_dataset)."""
        self._train_set = None
        return self

    def set_train_data_name(self, name: str) -> "Booster":
        self._train_data_name = name
        return self

    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        """Update mutable training parameters (ref: basic.py
        reset_parameter -> LGBM_BoosterResetParameter); used by the
        reset_parameter callback (e.g. learning-rate schedules).
        Parameters baked into the jitted grow program are rebuilt
        (changing them triggers one recompile)."""
        g = self._gbdt
        for k, v in params.items():
            if hasattr(g.config, k):
                setattr(g.config, k, v)
        if "learning_rate" in params:
            g.shrinkage_rate = float(params["learning_rate"])
        # rebuild the static split params the grow program was traced
        # with; unknown/structural keys (num_leaves, max_bin, ...) are
        # not resettable mid-training
        _SPLIT_KEYS = {"lambda_l1", "lambda_l2", "min_data_in_leaf",
                       "min_sum_hessian_in_leaf", "min_gain_to_split",
                       "max_delta_step", "path_smooth", "cat_l2",
                       "cat_smooth", "min_data_per_group",
                       "max_cat_to_onehot", "max_cat_threshold"}
        hit = _SPLIT_KEYS & set(params)
        if hit and getattr(g, "grow_params", None) is not None:
            sp = g.grow_params.split._replace(
                **{k: params[k] for k in hit})
            g.grow_params = g.grow_params._replace(split=sp)
        if "max_depth" in params and getattr(g, "grow_params", None) is not None:
            g.grow_params = g.grow_params._replace(
                max_depth=int(params["max_depth"]))
        self.params.update(params)
        return self

    def eval(self, data: "Dataset", name: str, feval=None):
        """Evaluate on an arbitrary dataset (ref: basic.py Booster.eval).
        Works on trained AND loaded (predictor-mode) boosters."""
        g = self._gbdt
        if getattr(g, "valid_sets", None) is None:
            # predictor-mode GBDT (loaded from file/string): evaluate
            # directly without the training-time valid machinery
            core = data._core_or_construct()
            X = g._raw_or_reconstruct(core)
            # no float64 cast: float32 data takes the device traversal
            raw = g.predict_raw(X)
            score = raw.T if raw.ndim == 2 else raw[None, :]
            metrics = create_metrics(g.config)
            for m in metrics:
                m.init(core.metadata, core.num_data)
            results = g._eval(score, metrics, core)
            return self._format_eval(name, results, feval, None)
        if name not in self.name_valid_sets:
            self.add_valid(data, name)
            # newly added sets start at init score only: replay the
            # current model's raw predictions into the score buffer
            core = data._core_or_construct()
            X = g._raw_or_reconstruct(core)
            # fresh-data eval seeding: float32 raw data rides the device
            # traversal; the float64 score buffer keeps host precision
            raw = g.predict_raw(X)
            g.valid_scores[-1] += (raw.T if raw.ndim == 2
                                   else raw[None, :])
        return [e for e in self.eval_valid(feval) if e[0] == name]

    # ------------------------------------------------------------------
    def eval_train(self, feval=None):
        return self._format_eval("training", self._gbdt.eval_train(),
                                 feval, None)

    def eval_valid(self, feval=None):
        out = []
        for i, name in enumerate(self.name_valid_sets):
            out.extend(self._format_eval(name, self._gbdt.eval_valid(i),
                                         feval, i))
        return out

    def _format_eval(self, name, results, feval, valid_idx):
        from .metric import _METRIC_CLASSES
        out = []
        for metric_name, val in results:
            base = metric_name.split("@")[0]
            cls = _METRIC_CLASSES.get(base)
            hib = bool(cls and cls.is_higher_better)
            out.append((name, metric_name, val, hib))
        if feval is not None:
            if valid_idx is None:
                score = self.__inner_predict_train()
                dset = self._train_set
            else:
                sc = self._gbdt.valid_scores[valid_idx]
                score = sc[0] if sc.shape[0] == 1 else sc
                # the Dataset wrapper so feval can read labels/weights
                # (ref: basic.py __inner_eval passes the valid Dataset)
                dset = (self._valid_wrappers[valid_idx]
                        if valid_idx < len(self._valid_wrappers) else None)
            res = feval(score, dset)
            if res:
                if not isinstance(res[0], (list, tuple)):
                    res = [res]
                for metric_name, val, hib in res:
                    out.append((name, metric_name, val, hib))
        return out

    # ------------------------------------------------------------------
    def predict(self, data, start_iteration: int = 0, num_iteration: int = -1,
                raw_score: bool = False, pred_leaf: bool = False,
                pred_contrib: bool = False, single_row_fast: bool = False,
                **kwargs) -> np.ndarray:
        from .io.sparse import is_scipy_sparse
        if (single_row_fast and not pred_leaf and not pred_contrib
                and not is_scipy_sparse(data)):
            row = np.asarray(data, np.float64)
            if row.ndim == 1:
                row = row[None, :]
            # the model may reference any original feature index: a
            # narrower row would read past the C buffer — fall through
            # to the batch path, which validates/raises
            if row.shape[0] == 1 and row.shape[1] >= self.num_feature():
                sp = self._single_row_fast_for(
                    row.shape[1], start_iteration,
                    -1 if num_iteration is None else num_iteration,
                    raw_score)
                if sp is not None:
                    out = sp.predict(row[0])
                    # match the batch path's shapes: [1] binary/reg,
                    # [1, K] multiclass
                    return out[None, :] if len(out) > 1 else out[:1]
        if is_scipy_sparse(data) and data.shape[0] == 0 and pred_contrib:
            # keep the sparse-in -> sparse-out contract on the empty edge
            from scipy import sparse as sps
            nc = getattr(self._gbdt, "num_tree_per_iteration", 1)
            return sps.csr_matrix((0, (data.shape[1] + 1) * nc))
        if is_scipy_sparse(data) and data.shape[0] > 0:
            # bounded-memory sparse prediction: densify row CHUNKS only
            # (~64 MB each), never the whole matrix (ref: the CSR
            # predictor paths of c_api.cpp predict row-wise too).  With
            # pred_contrib the result stays sparse (the reference Python
            # package returns scipy CSR for sparse input): each chunk's
            # dense [chunk, (F+1)*num_class] block is converted to CSR
            # immediately so peak memory is one chunk's block.
            from scipy import sparse as sps
            csr = data.tocsr()
            chunk = max(1, (64 << 20) // max(8 * data.shape[1], 1))
            parts = []
            for i in range(0, data.shape[0], chunk):
                p = self.predict(csr[i:i + chunk].toarray(),
                                 start_iteration=start_iteration,
                                 num_iteration=num_iteration,
                                 raw_score=raw_score, pred_leaf=pred_leaf,
                                 pred_contrib=pred_contrib, **kwargs)
                parts.append(sps.csr_matrix(p) if pred_contrib else p)
            if pred_contrib:
                return sps.vstack(parts, format="csr")
            return np.concatenate(parts, axis=0)
        data = _coerce_matrix(data)
        if num_iteration is None:
            num_iteration = -1
        if self.best_iteration > 0 and num_iteration == -1:
            num_iteration = self.best_iteration
        if pred_contrib:
            return self._gbdt.predict_contrib(
                np.asarray(data, np.float64),
                start_iteration=start_iteration,
                num_iteration=num_iteration)
        pred_kwargs = {k: v for k, v in kwargs.items()
                       if k in ("pred_early_stop", "pred_early_stop_freq",
                                "pred_early_stop_margin")}
        # _coerce_matrix preserved float32: the device inference path
        # (docs/Inference.md) only engages on float32 inputs, where its
        # routing is bit-identical; GBDT casts to float64 for host paths
        return self._gbdt.predict(data, raw_score=raw_score,
                                  start_iteration=start_iteration,
                                  num_iteration=num_iteration,
                                  pred_leaf=pred_leaf, **pred_kwargs)

    def _single_row_fast_for(self, num_features, start_iteration,
                             num_iteration, raw_score):
        """Cached per-(slice, raw) fast predictors; invalidated by model
        growth (ref: the FastConfig handle of c_api.h:1350).  A dict so
        serving loops alternating raw/converted or slices keep every
        variant warm."""
        key = (num_features, start_iteration, num_iteration, raw_score,
               len(self._gbdt.models_),
               getattr(self._gbdt, "_model_mutations", 0))
        cache = getattr(self, "_srf_cache", None)
        if cache is None or cache.get("model_key") != key[4:]:
            cache = {"model_key": key[4:]}     # model changed: drop all
            self._srf_cache = cache
        if key not in cache:
            if num_iteration == -1 and self.best_iteration > 0:
                num_iteration = self.best_iteration
            cache[key] = self._gbdt.make_single_row_fast(
                num_features, start_iteration=start_iteration,
                num_iteration=num_iteration, raw_score=raw_score)
        return cache[key]

    # ------------------------------------------------------------------
    def refit(self, data, label, weight=None, **kwargs) -> "Booster":
        """Refit existing tree structures to new data (ref: basic.py
        Booster.refit -> LGBM_BoosterRefit; gbdt.cpp:252 RefitTree)."""
        self._gbdt.refit(_coerce_matrix(data),
                         np.asarray(label, np.float64), weight=weight)
        return self

    def model_to_if_else(self) -> str:
        """Standalone C++ if-else predictor source
        (ref: gbdt_model_text.cpp SaveModelToIfElse)."""
        self._gbdt._sync_model()
        trees = self._gbdt.models_
        out = ["#include <cmath>", "", "namespace lightgbm_tpu {", ""]
        for i, tree in enumerate(trees):
            out.append(f"double PredictTree{i}(const double* row) {{")
            ni = tree.num_leaves - 1

            def emit(node, indent):
                pad = "  " * indent
                if node < 0:
                    out.append(f"{pad}return {tree.leaf_value[~node]!r};")
                    return
                f = int(tree.split_feature[node])
                thr = float(tree.threshold[node])
                dt = int(tree.decision_type[node])
                default_left = bool(dt & 2)
                miss = "std::isnan(row[%d])" % f
                if dt & 1:  # categorical membership
                    cat = int(tree.threshold[node])
                    s, e = (tree.cat_boundaries[cat],
                            tree.cat_boundaries[cat + 1])
                    words = ",".join(str(int(w))
                                     for w in tree.cat_threshold[s:e])
                    cond = (f"[&]{{ if ({miss} || row[{f}] < 0) return false;"
                            f" unsigned v = (unsigned)row[{f}];"
                            f" unsigned bits[] = {{{words}}};"
                            f" return v/32 < {e - s}u &&"
                            f" ((bits[v/32] >> (v%32)) & 1u); }}()")
                else:
                    base = f"row[{f}] <= {thr!r}"
                    mt = (dt >> 2) & 3
                    if mt == 2:  # nan
                        cond = (f"({miss} ? {str(default_left).lower()}"
                                f" : ({base}))")
                    elif mt == 1:  # zero
                        cond = (f"((std::fabs(row[{f}]) <= 1e-35)"
                                f" ? {str(default_left).lower()} : ({base}))")
                    else:
                        cond = base
                out.append(f"{pad}if ({cond}) {{")
                emit(int(tree.left_child[node]), indent + 1)
                out.append(f"{pad}}} else {{")
                emit(int(tree.right_child[node]), indent + 1)
                out.append(f"{pad}}}")

            if tree.num_leaves <= 1:
                out.append(f"  return {tree.leaf_value[0]!r};")
            else:
                emit(0, 1)
            out.append("}")
            out.append("")
        out.append("double Predict(const double* row) {")
        out.append("  double sum = 0.0;")
        for i in range(len(trees)):
            out.append(f"  sum += PredictTree{i}(row);")
        if getattr(self._gbdt, "average_output_", False) and trees:
            out.append(f"  sum /= {len(trees)}.0;")
        out.append("  return sum;")
        out.append("}")
        out.append("")
        out.append("}  // namespace lightgbm_tpu")
        return "\n".join(out)

    def model_to_string(self, num_iteration: int = None,
                        start_iteration: int = 0,
                        importance_type: str = "split") -> str:
        if num_iteration is None:
            num_iteration = (self.best_iteration
                             if self.best_iteration > 0 else -1)
        return save_model_to_string(self._gbdt, num_iteration, start_iteration,
                                    importance_type)

    def save_model(self, filename: str, num_iteration: int = None,
                   start_iteration: int = 0,
                   importance_type: str = "split") -> "Booster":
        """ref: basic.py Booster.save_model — num_iteration defaults to
        best_iteration when early stopping fired."""
        if num_iteration is None:
            num_iteration = (self.best_iteration
                             if self.best_iteration > 0 else -1)
        save_model_to_file(self._gbdt, filename, num_iteration, start_iteration,
                           importance_type)
        return self

    def feature_importance(self, importance_type: str = "split",
                           iteration=None) -> np.ndarray:
        return self._gbdt.feature_importance(importance_type)

    def feature_name(self) -> List[str]:
        if self._gbdt.train_data is not None:
            return self._gbdt.train_data.feature_names
        return self._gbdt._loaded_feature_names

    def num_feature(self) -> int:
        if self._gbdt.train_data is not None:
            return self._gbdt.train_data.num_total_features
        return self._gbdt._loaded_max_feature_idx + 1
