"""Boosting drivers (ref: src/boosting/: GBDT, DART, RF; factory boosting.cpp:34)."""

from .gbdt import GBDT


def create_boosting(boosting_type: str, config=None):
    """ref: src/boosting/boosting.cpp:34 Boosting::CreateBoosting."""
    from ..utils import log
    if boosting_type == "gbdt":
        return GBDT()
    if boosting_type == "dart":
        from .dart import DART
        return DART()
    if boosting_type == "rf":
        from .rf import RF
        return RF()
    log.fatal(f"Unknown boosting type: {boosting_type}")


__all__ = ["GBDT", "create_boosting"]
