"""DART boosting: per-iteration tree dropout + normalization
(ref: src/boosting/dart.hpp:23 DART).

Mechanics per iteration (ref: dart.hpp Normalize note):
  1. pick dropped trees, subtract their contribution from the training score
     (gradients are then computed on the "dropped" ensemble);
  2. train the new tree with shrinkage lr/(1+k);
  3. re-add the dropped trees scaled to k/(k+1) of their old weight and fix
     up train/valid scores accordingly.
"""

from __future__ import annotations

from typing import List

import numpy as np

from .gbdt import GBDT


class DART(GBDT):
    """ref: dart.hpp:23."""

    def init(self, config, train_data, objective, metrics) -> None:
        super().init(config, train_data, objective, metrics)
        self._rng_drop = np.random.RandomState(config.drop_seed)
        self.tree_weight_: List[float] = []
        self.sum_weight_ = 0.0
        self.drop_index_: List[int] = []
        self._dropped_cur_iter = False

    def pre_gradient_hook(self) -> None:
        """Drop before the caller reads training scores, once per iteration
        (ref: dart.hpp:77 GetTrainingScore / is_update_score_cur_iter_)."""
        if not self._dropped_cur_iter:
            self._sync_model()  # dropping reads host trees
            self._dropping_trees()
            self._dropped_cur_iter = True

    def train_one_iter(self, gradients=None, hessians=None) -> bool:
        cfg = self.config
        self.pre_gradient_hook()
        self._dropped_cur_iter = False
        ret = super().train_one_iter(gradients, hessians)
        if ret:
            return ret
        self._normalize()
        if not cfg.uniform_drop:
            self.tree_weight_.append(self.shrinkage_rate)
            self.sum_weight_ += self.shrinkage_rate
        return False

    # ------------------------------------------------------------------
    def _dropping_trees(self) -> None:
        """ref: dart.hpp:97 DroppingTrees."""
        cfg = self.config
        self.drop_index_ = []
        if self._rng_drop.rand() >= cfg.skip_drop:
            drop_rate = cfg.drop_rate
            if not cfg.uniform_drop:
                if self.sum_weight_ > 0:
                    inv_avg = len(self.tree_weight_) / self.sum_weight_
                    if cfg.max_drop > 0:
                        drop_rate = min(drop_rate,
                                        cfg.max_drop * inv_avg / self.sum_weight_)
                    for i in range(self.iter_):
                        if self._rng_drop.rand() < (drop_rate
                                                    * self.tree_weight_[i] * inv_avg):
                            self.drop_index_.append(self.num_init_iteration_ + i)
                            if (cfg.max_drop > 0
                                    and len(self.drop_index_) >= cfg.max_drop):
                                break
            else:
                if cfg.max_drop > 0 and self.iter_ > 0:
                    drop_rate = min(drop_rate, cfg.max_drop / self.iter_)
                for i in range(self.iter_):
                    if self._rng_drop.rand() < drop_rate:
                        self.drop_index_.append(self.num_init_iteration_ + i)
                        if (cfg.max_drop > 0
                                and len(self.drop_index_) >= cfg.max_drop):
                            break
        # drop: flip each selected tree to -weight and add to train score
        K = self.num_tree_per_iteration
        for i in self.drop_index_:
            for k in range(K):
                tree = self.models_[i * K + k]
                tree.apply_shrinkage(-1.0)
                self._add_tree_score(tree, k, valid=False)
        k_cnt = float(len(self.drop_index_))
        if not cfg.xgboost_dart_mode:
            self.shrinkage_rate = cfg.learning_rate / (1.0 + k_cnt)
        else:
            self.shrinkage_rate = (cfg.learning_rate if not self.drop_index_
                                   else cfg.learning_rate
                                   / (cfg.learning_rate + k_cnt))

    def _normalize(self) -> None:
        """ref: dart.hpp:160 Normalize."""
        cfg = self.config
        K = self.num_tree_per_iteration
        k_cnt = float(len(self.drop_index_))
        for i in self.drop_index_:
            for k in range(K):
                tree = self.models_[i * K + k]
                if not cfg.xgboost_dart_mode:
                    # tree currently at -w; scale to -w/(k+1), fix valid, then
                    # to +w*k/(k+1), fix train
                    tree.apply_shrinkage(1.0 / (k_cnt + 1.0))
                    self._add_tree_score(tree, k, train=False)
                    tree.apply_shrinkage(-k_cnt)
                    self._add_tree_score(tree, k, valid=False)
                else:
                    tree.apply_shrinkage(self.shrinkage_rate)
                    self._add_tree_score(tree, k, train=False)
                    tree.apply_shrinkage(-k_cnt / cfg.learning_rate)
                    self._add_tree_score(tree, k, valid=False)
            j = i - self.num_init_iteration_
            if not cfg.uniform_drop:
                if not cfg.xgboost_dart_mode:
                    self.sum_weight_ -= self.tree_weight_[j] / (k_cnt + 1.0)
                    self.tree_weight_[j] *= k_cnt / (k_cnt + 1.0)
                else:
                    self.sum_weight_ -= (self.tree_weight_[j]
                                         / (k_cnt + cfg.learning_rate))
                    self.tree_weight_[j] *= (k_cnt
                                             / (k_cnt + cfg.learning_rate))
