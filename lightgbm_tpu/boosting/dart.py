"""DART boosting: per-iteration tree dropout + normalization
(ref: src/boosting/dart.hpp:23 DART).

Mechanics per iteration (ref: dart.hpp Normalize note):
  1. pick dropped trees, subtract their contribution from the training score
     (gradients are then computed on the "dropped" ensemble);
  2. train the new tree with shrinkage lr/(1+k);
  3. re-add the dropped trees scaled to k/(k+1) of their old weight and fix
     up train/valid scores accordingly.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..utils import log
from .gbdt import GBDT


class DART(GBDT):
    """ref: dart.hpp:23."""

    def init(self, config, train_data, objective, metrics) -> None:
        super().init(config, train_data, objective, metrics)
        self._rng_drop = np.random.RandomState(config.drop_seed)
        self.tree_weight_: List[float] = []
        self.sum_weight_ = 0.0
        self.drop_index_: List[int] = []
        self._dropped_cur_iter = False

    def pre_gradient_hook(self) -> None:
        """Drop before the caller reads training scores, once per iteration
        (ref: dart.hpp:77 GetTrainingScore / is_update_score_cur_iter_)."""
        if not self._dropped_cur_iter:
            self._sync_model()  # dropping reads host trees
            self._dropping_trees()
            self._dropped_cur_iter = True

    # ------------------------------------------------- checkpoint state
    def capture_train_state(self, async_copy: bool = False):
        """DART drop-state rides the checkpoint (byte-exact resume,
        docs/Reliability.md): the dropped-tree selection RNG stream, the
        normalization counters (per-tree weights and their sum), and the
        full-precision per-tree shrinkage/internal_value — the model
        text prints those two at reference-compatible %g precision, and
        dropping keeps MULTIPLYING them, so a resume seeded from text
        alone drifts from the uninterrupted run at the first re-drop of
        an adopted tree."""
        state = super().capture_train_state(async_copy)
        if state is None:
            return None
        state["dart_rng_drop"] = np.array(
            self._rng_drop.get_state(legacy=False), dtype=object)
        state["dart_tree_weight"] = np.asarray(self.tree_weight_, np.float64)
        state["dart_sum_weight"] = np.float64(self.sum_weight_)
        trees = self.models_
        state["dart_shrinkage"] = np.asarray(
            [t.shrinkage for t in trees], np.float64)
        sizes = [max(t.num_leaves - 1, 0) for t in trees]
        state["dart_internal_sizes"] = np.asarray(sizes, np.int64)
        state["dart_internal_value"] = (
            np.concatenate([np.asarray(t.internal_value[:n], np.float64)
                            for t, n in zip(trees, sizes)])
            if trees else np.zeros(0, np.float64))
        return state

    def restore_train_state(self, state) -> bool:
        ok = super().restore_train_state(state)
        if state is None or "dart_rng_drop" not in state:
            # plain init_model continuation: reference semantics (the
            # adopted trees are never dropped, fresh drop RNG)
            return ok
        st = state["dart_rng_drop"]
        try:
            self._rng_drop.set_state(st.item() if hasattr(st, "item")
                                     else st)
        except (ValueError, TypeError) as e:
            log.warning(f"Could not restore DART drop RNG state: {e}")
        tw = state.get("dart_tree_weight")
        if tw is not None:
            self.tree_weight_ = [float(x) for x in np.asarray(tw)]
        self.sum_weight_ = float(state.get("dart_sum_weight",
                                           sum(self.tree_weight_)))
        sh = state.get("dart_shrinkage")
        if sh is not None and len(sh) == len(self.models_):
            for t, s in zip(self.models_, np.asarray(sh, np.float64)):
                t.shrinkage = float(s)
        sizes = state.get("dart_internal_sizes")
        ivals = state.get("dart_internal_value")
        if sizes is not None and ivals is not None \
                and len(sizes) == len(self.models_):
            off = 0
            for t, n in zip(self.models_, np.asarray(sizes, np.int64)):
                t.internal_value[:n] = np.asarray(ivals[off:off + n],
                                                  np.float64)
                off += int(n)
        # the restored full-precision shrinkage/internal values mutated
        # the trees in place; repack before any serve
        self._bump_model_mutations()
        # a checkpoint resume CONTINUES the same DART run: the adopted
        # trees must stay droppable, so fold them back into `iter_`
        # (continue_from counted them as frozen init trees).  Every
        # absolute-iteration consumer reads the sum num_init_iteration_
        # + iter_, which is unchanged.
        self.iter_ = self.num_init_iteration_
        self.num_init_iteration_ = 0
        return ok

    def train_one_iter(self, gradients=None, hessians=None) -> bool:
        cfg = self.config
        self.pre_gradient_hook()
        self._dropped_cur_iter = False
        ret = super().train_one_iter(gradients, hessians)
        if ret:
            return ret
        self._normalize()
        if not cfg.uniform_drop:
            self.tree_weight_.append(self.shrinkage_rate)
            self.sum_weight_ += self.shrinkage_rate
        return False

    # ------------------------------------------------------------------
    def _dropping_trees(self) -> None:
        """ref: dart.hpp:97 DroppingTrees."""
        cfg = self.config
        self.drop_index_ = []
        if self._rng_drop.rand() >= cfg.skip_drop:
            drop_rate = cfg.drop_rate
            if not cfg.uniform_drop:
                if self.sum_weight_ > 0:
                    inv_avg = len(self.tree_weight_) / self.sum_weight_
                    if cfg.max_drop > 0:
                        drop_rate = min(drop_rate,
                                        cfg.max_drop * inv_avg / self.sum_weight_)
                    for i in range(self.iter_):
                        if self._rng_drop.rand() < (drop_rate
                                                    * self.tree_weight_[i] * inv_avg):
                            self.drop_index_.append(self.num_init_iteration_ + i)
                            if (cfg.max_drop > 0
                                    and len(self.drop_index_) >= cfg.max_drop):
                                break
            else:
                if cfg.max_drop > 0 and self.iter_ > 0:
                    drop_rate = min(drop_rate, cfg.max_drop / self.iter_)
                for i in range(self.iter_):
                    if self._rng_drop.rand() < drop_rate:
                        self.drop_index_.append(self.num_init_iteration_ + i)
                        if (cfg.max_drop > 0
                                and len(self.drop_index_) >= cfg.max_drop):
                            break
        # drop: flip each selected tree to -weight and add to train score
        K = self.num_tree_per_iteration
        for i in self.drop_index_:
            for k in range(K):
                tree = self.models_[i * K + k]
                tree.apply_shrinkage(-1.0)
                self._add_tree_score(tree, k, valid=False)
        if self.drop_index_:
            # the in-place leaf re-weighting invalidates the packed and
            # device predictor caches: a predict between drop and
            # normalize (serving a live DART booster) must repack so it
            # scores the CURRENT drop state, matching Booster.predict
            self._bump_model_mutations()
        k_cnt = float(len(self.drop_index_))
        if not cfg.xgboost_dart_mode:
            self.shrinkage_rate = cfg.learning_rate / (1.0 + k_cnt)
        else:
            self.shrinkage_rate = (cfg.learning_rate if not self.drop_index_
                                   else cfg.learning_rate
                                   / (cfg.learning_rate + k_cnt))

    def _normalize(self) -> None:
        """ref: dart.hpp:160 Normalize."""
        cfg = self.config
        K = self.num_tree_per_iteration
        k_cnt = float(len(self.drop_index_))
        for i in self.drop_index_:
            for k in range(K):
                tree = self.models_[i * K + k]
                if not cfg.xgboost_dart_mode:
                    # tree currently at -w; scale to -w/(k+1), fix valid, then
                    # to +w*k/(k+1), fix train
                    tree.apply_shrinkage(1.0 / (k_cnt + 1.0))
                    self._add_tree_score(tree, k, train=False)
                    tree.apply_shrinkage(-k_cnt)
                    self._add_tree_score(tree, k, valid=False)
                else:
                    tree.apply_shrinkage(self.shrinkage_rate)
                    self._add_tree_score(tree, k, train=False)
                    tree.apply_shrinkage(-k_cnt / cfg.learning_rate)
                    self._add_tree_score(tree, k, valid=False)
            j = i - self.num_init_iteration_
            if not cfg.uniform_drop:
                if not cfg.xgboost_dart_mode:
                    self.sum_weight_ -= self.tree_weight_[j] / (k_cnt + 1.0)
                    self.tree_weight_[j] *= k_cnt / (k_cnt + 1.0)
                else:
                    self.sum_weight_ -= (self.tree_weight_[j]
                                         / (k_cnt + cfg.learning_rate))
                    self.tree_weight_[j] *= (k_cnt
                                             / (k_cnt + cfg.learning_rate))
        if self.drop_index_:
            # normalization re-weighted the dropped trees in place — a
            # mid-training DART model must serve its current weights
            self._bump_model_mutations()
