"""GBDT boosting driver (ref: src/boosting/gbdt.cpp, gbdt.h:37).

Orchestrates the TPU training loop: binned data and scores live on device; per
iteration the objective's gradient map, bagging mask, the jitted whole-tree
grower and the score update all run as XLA computations.  Trees are pulled to
host as `Tree` objects (one small D2H per tree, like the CUDA learner's
CUDATree::ToHost, ref: src/io/cuda/cuda_tree.cpp) for model serialization and
raw-feature prediction.
"""

from __future__ import annotations

import copy as _copy
import functools
from typing import List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..config import Config
from ..io.binning import BIN_CATEGORICAL
from ..io.dataset import Dataset
from ..learner import (FeatureMeta, GrowParams, grow_tree,
                       grow_tree_donated, grow_tree_wave,
                       grow_tree_wave_donated)
from ..models.tree import Tree
from ..objective import ObjectiveFunction
from ..ops.split import SplitParams
from ..metric import Metric
from ..observability import global_registry as _metrics
from ..reliability import faults
from ..utils import log
from ..utils.timer import global_timer

K_EPSILON = 1e-15
_PAD = 1024  # row padding multiple (histogram chunking requirement)

# score/gradient buffers are donated through the jitted update entries
# (docs/Performance.md); CPU XLA cannot alias every donated buffer and
# warns per executable — same silencing as inference/predictor.py
import warnings as _warnings  # noqa: E402

_warnings.filterwarnings("ignore",
                         message="Some donated buffers were not usable")

# sentinel stored in models_ for device trees not yet pulled to host
_PENDING_TREE = object()


@functools.partial(jax.jit, static_argnames=("top_k", "other_k"),
                   donate_argnums=(0, 1))
def _goss_sample(grad, hess, pad_mask, key, top_k, other_k):
    """Gradient one-side sampling on device (ref: goss.hpp:118-165):
    keep the top_k rows by sum_k |g*h|, Bernoulli-sample ~other_k of the rest
    and amplify them by (n_kept_pool)/other_k.  The incoming grad/hess
    are replaced by the rescaled outputs, so their buffers are donated."""
    imp = jnp.sum(jnp.abs(grad * hess), axis=0) * pad_mask
    thr = jax.lax.top_k(imp, top_k)[0][-1]
    is_top = (imp >= thr) & (pad_mask > 0)
    n_real = jnp.sum(pad_mask)
    rest = n_real - jnp.sum(is_top.astype(jnp.float32))
    prob = other_k / jnp.maximum(rest, 1.0)
    sampled = ((jax.random.uniform(key, imp.shape) < prob)
               & ~is_top & (pad_mask > 0))
    multiply = rest / other_k
    scale = jnp.where(sampled, multiply, 1.0)
    keep = (is_top | sampled).astype(grad.dtype)
    return keep, grad * scale[None, :], hess * scale[None, :]


def _fetch_host(a) -> np.ndarray:
    """Device -> host fetch that also works for multi-process arrays:
    np.asarray refuses ANY array spanning non-addressable devices, but the
    packed tree buffer is pinned fully-replicated under multi-process
    SPMD (see _pack_tree_fn), so the local shard IS the whole value."""
    if isinstance(a, jax.Array) and not a.is_fully_addressable:
        return np.asarray(a.addressable_shards[0].data)
    return np.asarray(a)


def _mesh_size(config, ndev: int) -> int:
    """Device-mesh size policy shared by the EFB gate and
    _make_training_mesh (ref: config.h num_machines; application.cpp:100
    machine setup).  Under multi-process SPMD the machine list already
    defines the cluster, so the mesh spans every global device; in a
    single process num_machines caps the local device count (mesh
    emulation of an N-machine run)."""
    if jax.process_count() > 1:
        return ndev
    want = config.num_machines if config.num_machines > 1 else ndev
    return min(want, ndev)


def _pad_rows(arr: np.ndarray, n_pad: int, axis: int = -1, fill=0):
    n = arr.shape[axis]
    if n == n_pad:
        return arr
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, n_pad - n)
    return np.pad(arr, widths, constant_values=fill)


def leaf_index_bin_space(split_feature_inner, threshold_bin, default_left,
                         left_child, right_child, num_leaves,
                         missing_type, num_bin, default_bin,
                         binned: np.ndarray, is_cat_node=None,
                         cat_boundaries_inner=None,
                         cat_threshold_inner=None,
                         bundle_group=None, bundle_offset=None,
                         bundle_zero_bin=None) -> np.ndarray:
    """Vectorized bin-space tree traversal on host (mirror of the device
    partition rule; ref: dense_bin.hpp:346-366 SplitInner + tree.h:372
    CategoricalDecision over bin bitsets).  When bundle_* arrays are
    given, `binned` holds EFB bundle codes (sparse-ingested datasets)
    and each node's feature bin is decoded from its bundle column."""
    from ..io.binning import MISSING_NAN, MISSING_ZERO
    n = binned.shape[1]
    if num_leaves <= 1:
        return np.zeros(n, dtype=np.int32)
    has_cat = is_cat_node is not None and np.any(is_cat_node)
    if has_cat:
        cb = np.asarray(cat_boundaries_inner, np.int64)
        ct = np.asarray(cat_threshold_inner, np.uint32)
    node = np.zeros(n, dtype=np.int32)
    for _ in range(num_leaves):
        active = node >= 0
        if not active.any():
            break
        nd = node[active]
        f = split_feature_inner[nd]
        if bundle_group is not None:
            code = binned[bundle_group[f], np.nonzero(active)[0]]
            code = code.astype(np.int64)
            off = bundle_offset[f]
            local = code - off
            valid = (local >= 0) & (local < num_bin[f])
            b = np.where(off == 0, code,
                         np.where(valid, local, bundle_zero_bin[f]))
        else:
            b = binned[f, np.nonzero(active)[0]]
        mt = missing_type[f]
        is_missing = (((mt == MISSING_NAN) & (b == num_bin[f] - 1))
                      | ((mt == MISSING_ZERO) & (b == default_bin[f])))
        go_left = np.where(is_missing, default_left[nd], b <= threshold_bin[nd])
        if has_cat:
            cat_nd = is_cat_node[nd]
            cat_idx = np.where(cat_nd, threshold_bin[nd], 0)
            start = cb[cat_idx]
            nwords = cb[cat_idx + 1] - start
            word = b.astype(np.int64) // 32
            ok = word < nwords
            wv = ct[np.clip(start + word, 0, len(ct) - 1)] if len(ct) else 0
            cat_left = ok & (((wv >> (b % 32).astype(np.uint32)) & 1) > 0)
            go_left = np.where(cat_nd, cat_left, go_left)
        node[active] = np.where(go_left, left_child[nd], right_child[nd])
    return (~node).astype(np.int32)


class GBDT:
    """ref: src/boosting/gbdt.cpp GBDT."""

    average_output_ = False  # RF overrides (ref: gbdt.h average_output_)

    def __init__(self):
        self.models_: List[Tree] = []
        self.iter_ = 0
        self.num_init_iteration_ = 0
        self.config: Optional[Config] = None
        self.train_data: Optional[Dataset] = None
        self.objective: Optional[ObjectiveFunction] = None
        self.best_iteration = -1
        self._pending = []       # device trees awaiting host materialization
        self._stump_idxs = set()  # model indices of no-split trees
        self._device_eval = None  # lazy ops.metrics.DeviceEval
        self._finite_cache = None  # (grads_finite, scores_finite) this iter

    # ------------------------------------------------------------ distributed
    def _make_training_mesh(self, config: Config):
        """Distributed learner selection (ref: tree_learner.cpp:15
        CreateTreeLearner; SURVEY §2.3).  tree_learner=data shards the row
        axis over a 1-D device mesh: the histogram reduction becomes a GSPMD
        psum, replacing Network::ReduceScatter
        (data_parallel_tree_learner.cpp:284), and the best-split argmax runs
        on the replicated histogram, replacing SyncUpGlobalBestSplit.
        tree_learner=feature shards the FEATURE axis of the binned matrix
        (feature_parallel_tree_learner.cpp:23): each device scans its feature
        block and the argmax all-gathers the winner.  voting is data-parallel
        with the PV-Tree top-k vote: per-leaf scans elect ~top_k features
        and reduce only those histograms over the mesh
        (voting_parallel_tree_learner.cpp:151 GlobalVoting)."""
        tl = config.tree_learner
        if tl not in ("serial", "data", "feature", "voting"):
            log.fatal(f"Unknown tree_learner {tl!r}")
        self._voting = tl == "voting"
        if tl == "serial":
            return None
        n_mesh = _mesh_size(config, len(jax.devices()))
        if tl == "feature":
            # GSPMD needs the sharded axis size divisible by the mesh: use
            # the largest divisor of the device column count (the reference
            # instead hand-balances unequal feature subsets,
            # feature_parallel_tree_learner.cpp:30)
            F = self._n_device_cols
            requested = n_mesh
            while n_mesh > 1 and F % n_mesh != 0:
                n_mesh -= 1
            if n_mesh != requested:
                log.warning(
                    f"tree_learner=feature: {F} feature columns have no "
                    f"equal split over {requested} devices; using "
                    f"{n_mesh} device(s) instead"
                    + (" (feature parallelism DISABLED — consider "
                       "tree_learner=data)" if n_mesh <= 1 else ""))
        if n_mesh <= 1:
            self._voting = False
            return None
        from ..parallel import make_mesh
        self._mesh_axis = 1 if tl in ("data", "voting") else 0
        return make_mesh(n_mesh)

    def _put_by_row(self, arr, axis=None, is_binned=False):
        """Place a host array on the mesh, sharded along its row axis (the
        LAST axis unless given); no-op single-device put without a mesh.
        Under feature-parallel only the binned [F, n] matrix is sharded
        (axis 0); all row tensors stay replicated."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        if self.mesh is None:
            return jnp.asarray(arr)
        a = np.asarray(arr)
        if self._mesh_axis == 0:
            if not is_binned:
                return jnp.asarray(a)
            spec = P("data", None)
        else:
            ax = a.ndim - 1 if axis is None else axis
            spec = P(*(["data" if i == ax else None
                        for i in range(a.ndim)]))
        return jax.device_put(a, NamedSharding(self.mesh, spec))

    # ------------------------------------------------------------------ init
    def init(self, config: Config, train_data: Dataset,
             objective: Optional[ObjectiveFunction],
             metrics: Sequence[Metric]) -> None:
        if config.compile_cache_dir:
            # persistent XLA compilation cache: repeat runs of the same
            # config skip the multi-minute ladder compile; must be wired
            # before the first jit below traces (docs/Performance.md)
            from ..observability import configure_compile_cache
            configure_compile_cache(config.compile_cache_dir)
        self.config = config
        self.train_data = train_data
        self.objective = objective
        self.train_metrics = list(metrics)
        self.shrinkage_rate = config.learning_rate
        self.num_class = config.num_class
        self.num_tree_per_iteration = (objective.num_model_per_iteration()
                                       if objective is not None else config.num_class)
        self.num_data = train_data.num_data
        self.valid_sets: List[Dataset] = []
        self.valid_metrics: List[List[Metric]] = []
        self.valid_names: List[str] = []
        self.valid_scores: List[np.ndarray] = []
        self.class_need_train = [True] * self.num_tree_per_iteration

        n = train_data.num_data
        self.n_pad = (n + _PAD - 1) // _PAD * _PAD
        binned = train_data.binned
        # EFB: bundle exclusive sparse features into shared device columns
        # (ref: feature_group.h; io/bundle.py).  The bundle plan is purely
        # a device-layout optimization — host paths (prediction, leaf ids,
        # model IO) keep per-feature bins.
        self.bundle_plan = None
        # the PV-Tree vote is per-feature, so EFB is skipped only when
        # voting will actually engage: a >1-device mesh exists AND the
        # num_machines cap doesn't reduce the mesh to a single device
        # (otherwise _make_training_mesh returns None and serial training
        # would silently lose bundling)
        voting_engages = (config.tree_learner == "voting"
                          and _mesh_size(config, len(jax.devices())) > 1)
        if train_data.pre_bundled_plan is not None:
            # sparse CSC-direct ingestion already produced bundle codes
            # (io/sparse.py); never re-plan or densify
            self.bundle_plan = train_data.pre_bundled_plan
        elif (config.enable_bundle and train_data.num_features > 1
                and not voting_engages):
            from ..io.bundle import build_bundled, plan_bundles
            plan_src = binned
            if isinstance(binned, jax.Array):
                # device-binned: plan from host bins of the construction
                # sample (gathering sample columns through the remote
                # tunnel costs ~1000x more)
                plan_src = train_data.efb_sample_bins()
                if plan_src is None:
                    plan_src = train_data.binned_host()
            plan = plan_bundles(plan_src, train_data.bin_mappers,
                                train_data.used_features,
                                max_conflict_rate=config.max_conflict_rate)
            if plan.effective:
                self.bundle_plan = plan
                if isinstance(binned, jax.Array):
                    binned = train_data.binned_host()
                binned = build_bundled(binned, plan)
                log.info(f"EFB bundled {len(plan.group_idx)} features into "
                         f"{plan.num_groups} columns")
        dtype = np.uint8 if (binned.max() if self.bundle_plan else
                             train_data.max_num_bin - 1) <= 255 else np.int32
        self._n_device_cols = binned.shape[0]
        self.mesh = self._make_training_mesh(config)
        if self.mesh is not None and self._mesh_axis == 1:
            # sharded rows: each device's local shard must itself be a
            # _PAD multiple (the sharded-wave Pallas kernel tiles local
            # rows; shard_map sees only the shard) — pad the global row
            # count to _PAD * mesh_size
            m = _PAD * int(self.mesh.devices.size)
            self.n_pad = (n + m - 1) // m * m
        if self._voting and train_data.pre_bundled_plan is not None:
            # the PV-Tree vote is per-feature; bundle codes from sparse
            # ingestion cannot vote — run the plain data-parallel
            # histogram reduction over the same mesh instead
            log.warning("tree_learner=voting needs per-feature bins; "
                        "sparse pre-bundled datasets fall back to "
                        "data-parallel histogram reduction")
            self._voting = False
        if isinstance(binned, jax.Array) and self.mesh is None:
            # device-binned dataset (io/device_bin.py): pad on device —
            # the 280MB-class bin matrix never makes a host round-trip.
            # The unpadded buffer is DONATED so only one device copy
            # stays resident; the dataset keeps a view descriptor for
            # lazy host recovery (binned_host)
            pad = self.n_pad - binned.shape[1]
            n_true = binned.shape[1]
            if pad == 0:
                bd = binned
            else:
                bd = jnp.pad(binned, ((0, 0), (0, pad)))
                # drop the unpadded device copy — the dataset recovers a
                # host view lazily through _binned_view when needed
                train_data.binned = None
            self.binned_dev = (bd if bd.dtype == dtype
                               else bd.astype(dtype))
            train_data._binned_view = (self.binned_dev, n_true)
        else:
            if isinstance(binned, jax.Array):
                binned = train_data.binned_host()   # mesh placement is
                # host-driven (_put_by_row shards the host copy)
            self.binned_dev = self._put_by_row(
                _pad_rows(binned.astype(dtype), self.n_pad), axis=1,
                is_binned=True)
        self.pad_mask = self._put_by_row(
            _pad_rows(np.ones(n, np.float32), self.n_pad))

        # per-feature metadata, device side
        mt, nb, db, cat = [], [], [], []
        for f in train_data.used_features:
            m = train_data.bin_mappers[f]
            mt.append(m.missing_type)
            nb.append(m.num_bin)
            db.append(m.default_bin)
            cat.append(m.bin_type == BIN_CATEGORICAL)
        self.f_missing_type = np.array(mt, np.int32)
        self.f_num_bin = np.array(nb, np.int32)
        self.f_default_bin = np.array(db, np.int32)
        self.f_is_cat = np.array(cat, bool)
        penalty = np.ones(len(nb), np.float32)
        if config.feature_contri:
            for i, f in enumerate(train_data.used_features):
                if f < len(config.feature_contri):
                    penalty[i] = config.feature_contri[f]
        # monotone constraints indexed by real feature -> used features
        # (ref: config.h monotone_constraints; monotone_constraints.hpp)
        mono = np.zeros(len(nb), np.int32)
        if config.monotone_constraints:
            mc_list = list(config.monotone_constraints)
            for i, f in enumerate(train_data.used_features):
                if f < len(mc_list):
                    mono[i] = int(mc_list[f])
        self.f_monotone = mono
        has_mono = bool(np.any(mono != 0))
        if has_mono and config.monotone_constraints_method not in (
                "basic", "intermediate", "advanced"):
            log.fatal("Unknown monotone_constraints_method "
                      f"{config.monotone_constraints_method!r}")
        self._mono_intermediate = False
        self._mono_advanced = False
        if has_mono and config.monotone_constraints_method != "basic":
            if config.extra_trees or config.feature_fraction_bynode < 1.0:
                log.warning("monotone_constraints_method="
                            f"{config.monotone_constraints_method} "
                            "falls back to basic with extra_trees / "
                            "feature_fraction_bynode (the full-tree "
                            "pending rescan has no per-leaf random state)")
            else:
                self._mono_intermediate = True
                self._mono_advanced = (
                    config.monotone_constraints_method == "advanced")
        # CEGB (ref: cost_effective_gradient_boosting.hpp IsEnable)
        has_lazy = bool(config.cegb_penalty_feature_lazy)
        has_cegb = (config.cegb_tradeoff < 1.0
                    or config.cegb_penalty_split > 0.0
                    or bool(config.cegb_penalty_feature_coupled)
                    or has_lazy)
        lazy = np.zeros(len(nb), np.float32)
        if has_lazy:
            lz = list(config.cegb_penalty_feature_lazy)
            if len(lz) != train_data.num_total_features:
                log.fatal("cegb_penalty_feature_lazy should be the same "
                          "size as feature number.")
            for i, f in enumerate(train_data.used_features):
                lazy[i] = lz[f]
        coupled = np.zeros(len(nb), np.float32)
        if config.cegb_penalty_feature_coupled:
            cp = list(config.cegb_penalty_feature_coupled)
            if len(cp) != train_data.num_total_features:
                log.fatal("cegb_penalty_feature_coupled should be the same "
                          "size as feature number.")
            for i, f in enumerate(train_data.used_features):
                coupled[i] = cp[f]
        self._cegb_used = (jnp.zeros(len(nb), bool) if has_cegb else None)
        if has_lazy and self._mono_intermediate:
            log.warning("monotone intermediate mode falls back to basic "
                        "with cegb_penalty_feature_lazy")
            self._mono_intermediate = False
        if has_lazy and self._voting:
            log.fatal("cegb_penalty_feature_lazy is not supported with "
                      "tree_learner=voting")
        # per-(feature, row) fetched bitset, persistent across trees
        # (ref: cost_effective_gradient_boosting.hpp:63 feature_used_in_data_)
        self._lazy_used = (self._put_by_row(
            np.zeros((len(nb), self.n_pad), bool), axis=1)
            if has_lazy else None)
        bp = self.bundle_plan
        self.meta = FeatureMeta(
            num_bin=jnp.asarray(self.f_num_bin),
            missing_type=jnp.asarray(self.f_missing_type),
            default_bin=jnp.asarray(self.f_default_bin),
            penalty=jnp.asarray(penalty),
            is_cat=jnp.asarray(self.f_is_cat),
            monotone=jnp.asarray(mono),
            cegb_coupled=jnp.asarray(coupled),
            cegb_lazy=jnp.asarray(lazy),
            group=None if bp is None else jnp.asarray(bp.group_idx),
            offset=None if bp is None else jnp.asarray(bp.offsets),
            zero_bin=None if bp is None else jnp.asarray(bp.zero_bin),
            in_bundle=None if bp is None else jnp.asarray(bp.in_bundle))

        max_b = int(self.f_num_bin.max()) if len(nb) else 1
        # histogram stack memory guard (HistogramPool analogue)
        stack_bytes = config.num_leaves * len(nb) * max_b * 2 * 4
        budget = (config.histogram_pool_size * 1024 * 1024
                  if config.histogram_pool_size > 0 else 512 * 1024 * 1024)
        self.grow_params = GrowParams(
            num_leaves=config.num_leaves,
            max_depth=config.max_depth,
            max_bin=max_b,
            split=SplitParams(
                lambda_l1=config.lambda_l1, lambda_l2=config.lambda_l2,
                min_data_in_leaf=config.min_data_in_leaf,
                min_sum_hessian_in_leaf=config.min_sum_hessian_in_leaf,
                min_gain_to_split=config.min_gain_to_split,
                max_delta_step=config.max_delta_step,
                path_smooth=config.path_smooth,
                has_categorical=bool(self.f_is_cat.any()),
                cat_features=tuple(np.nonzero(self.f_is_cat)[0].tolist()),
                max_cat_to_onehot=config.max_cat_to_onehot,
                max_cat_threshold=config.max_cat_threshold,
                cat_l2=config.cat_l2, cat_smooth=config.cat_smooth,
                min_data_per_group=config.min_data_per_group,
                has_monotone=has_mono,
                monotone_penalty=config.monotone_penalty,
                extra_trees=config.extra_trees,
                extra_seed=config.extra_seed,
                has_cegb=has_cegb,
                cegb_tradeoff=config.cegb_tradeoff,
                cegb_penalty_split=config.cegb_penalty_split,
                has_cegb_lazy=has_lazy),
            has_bundles=bp is not None,
            group_max_bin=(0 if bp is None
                           else int(bp.group_num_bin.max())),
            feature_fraction_bynode=config.feature_fraction_bynode,
            bynode_seed=config.feature_fraction_seed + 1,
            monotone_intermediate=self._mono_intermediate,
            monotone_advanced=self._mono_advanced,
            wave_tail_halving=config.wave_tail_halving,
            wave_prune=config.wave_prune,
            wave_prune_overshoot=config.wave_prune_overshoot,
            wave_spike_reserve=config.wave_spike_reserve,
            wave_spike_k=config.wave_spike_k,
            # int8 MXU histogram path for quantized training (grid must
            # fit int8; hessian ints reach num_grad_quant_bins).  The
            # int32 accumulator must hold n * max_int for a root-level
            # cell (the reference bounds this with per-leaf 8/16/32-bit
            # histogram widths, SetNumBitsInHistogramBin); larger inputs
            # fall back to the fp32 kernel
            quant_bins=(config.num_grad_quant_bins
                        if (config.use_quantized_grad
                            and config.num_grad_quant_bins <= 126
                            and self.n_pad * config.num_grad_quant_bins
                            < 2**31) else 0),
            use_hist_stack=stack_bytes <= budget,
            # Fused Pallas one-hot kernel on TPU (one-hot tiles live only in
            # VMEM, like the CUDA shared-memory histogram kernels); XLA's
            # scatter path wins on CPU.  Both accumulate fp32; gpu_use_dp
            # selects the 3-pass high-precision matmul fallback instead
            # (ref: gpu_tree_learner.h:79 single-precision default).
            hist_method=(("onehot_hp" if config.gpu_use_dp else "pallas")
                         if jax.default_backend() == "tpu" else "segment"))
        if (self.grow_params.monotone_intermediate
                and not self.grow_params.use_hist_stack):
            log.warning("monotone intermediate mode needs the per-leaf "
                        "histogram stack (histogram_pool_size); falling "
                        "back to basic")
            self.grow_params = self.grow_params._replace(
                monotone_intermediate=False)
        if self.mesh is not None and self._mesh_axis == 1:
            # row sharding: masked engine (global-index row gathers would
            # all-gather the binned matrix).  The wave engine keeps its
            # Pallas histogram and runs under explicit shard_map (the
            # sharded-wave selection below); only the leaf-wise engine,
            # which rides GSPMD annotations, downgrades to the XLA
            # segment histogram (GSPMD cannot partition a pallas_call).
            from ..parallel import grow_params_for_mesh
            self.grow_params = grow_params_for_mesh(self.grow_params)
            if self._voting:
                # PV-Tree vote (ref: voting_parallel_tree_learner.cpp):
                # children rebuilt per scan (elected feature sets differ
                # between parent and children, so subtraction is invalid)
                from ..parallel.voting import VotingSpec
                if config.forcedsplits_filename:
                    log.fatal("tree_learner=voting does not support "
                              "forced splits")
                if config.top_k <= 0:
                    log.fatal("top_k should be greater than 0 "
                              "(ref: config.cpp CHECK_GT(top_k, 0))")
                if self.grow_params.monotone_intermediate:
                    log.warning("monotone intermediate mode falls back to "
                                "basic under tree_learner=voting (no "
                                "histogram stack to rescan)")
                self.grow_params = self.grow_params._replace(
                    use_hist_stack=False, monotone_intermediate=False,
                    voting=VotingSpec(self.mesh, min(config.top_k, len(nb)),
                                      int(self.mesh.devices.size)))
        # forced splits (ref: serial_tree_learner.cpp:614 ForceSplits):
        # parse the BFS JSON into static (leaf, inner_feature, bin) tuples
        # using our split numbering (left child keeps the leaf index,
        # right child becomes leaf step+1)
        if config.forcedsplits_filename:
            import json as _json
            from collections import deque
            with open(config.forcedsplits_filename) as f:
                forced_json = _json.load(f)
            inner_of = {f: i for i, f in enumerate(train_data.used_features)}
            forced = []
            queue = deque([(forced_json, 0)])
            while queue and len(forced) < config.num_leaves - 1:
                node, leaf = queue.popleft()
                if not node or "feature" not in node:
                    continue
                real_f = int(node["feature"])
                if real_f not in inner_of:
                    log.warning(f"forced split feature {real_f} unused; "
                                "skipping subtree")
                    continue
                fi = inner_of[real_f]
                mapper = train_data.bin_mappers[real_f]
                thr_bin = mapper.value_to_bin(float(node["threshold"]))
                new_leaf = len(forced) + 1
                forced.append((leaf, fi, int(thr_bin)))
                if "left" in node and node["left"]:
                    queue.append((node["left"], leaf))
                if "right" in node and node["right"]:
                    queue.append((node["right"], new_leaf))
            self.grow_params = self.grow_params._replace(
                forced_splits=tuple(forced))
            if not self.grow_params.use_hist_stack:
                log.fatal("forced splits need the per-leaf histogram stack; "
                          "raise histogram_pool_size")
        # growth engine: wave (level-batched; one MXU histogram sweep per
        # round with leaf slots as the matmul's output columns) vs strict
        # leaf-wise (partitioned segments; the reference-parity order)
        from ..ops.histogram import wave_pallas_vmem_ok
        strategy = config.tpu_growth_strategy
        if strategy not in ("auto", "wave", "leafwise"):
            log.fatal(f"Unknown tpu_growth_strategy {strategy!r}; "
                      "expected auto, wave, or leafwise")
        # interaction constraints (ref: config.h:585; col_sampler.hpp:91):
        # "[0,1,2],[2,3]" -> static inner-index sets
        if config.interaction_constraints:
            import re as _re
            inner_of = {f: i for i, f in enumerate(train_data.used_features)}
            sets = []
            # accept both the string form "[0,1],[2,3]" and the python
            # list-of-lists form (str() of which nests brackets)
            for grp in _re.findall(r"\[([^\[\]]*)\]",
                                   str(config.interaction_constraints)):
                idxs = tuple(sorted(inner_of[int(tok)]
                                    for tok in grp.split(",")
                                    if tok.strip() != ""
                                    and int(tok) in inner_of))
                if idxs:
                    sets.append(idxs)
            self.grow_params = self.grow_params._replace(
                interaction_sets=tuple(sets))
        if (self.grow_params.voting is not None
                or self.grow_params.monotone_intermediate
                or self.grow_params.split.has_cegb_lazy):
            # interaction constraints and forced splits run on the wave
            # engine (branch masks compose with waves; forced splits
            # apply as a one-split-per-wave prologue, wave.py).  Voting
            # elects per-leaf feature sets (children not derivable by
            # subtraction), and intermediate monotone / lazy CEGB
            # recompute global state after EVERY split — inherently
            # sequential, so they keep the leaf-wise engine (measured
            # 0.958 s/iter at bench scale vs the same-host oracle's
            # 9.8 — see PERF_NOTES).
            if strategy == "wave":
                log.warning("voting / intermediate monotone / lazy CEGB "
                            "use the leaf-wise engine")
            strategy = "leafwise"
        if strategy == "auto":
            strategy = ("wave" if jax.default_backend() == "tpu"
                        and config.num_leaves >= 8
                        and self.grow_params.hist_method == "pallas"
                        and wave_pallas_vmem_ok(len(nb), max_b,
                                                config.num_leaves)
                        else "leafwise")
        elif (strategy == "wave" and jax.default_backend() == "tpu"
              and not (self.grow_params.hist_method == "pallas"
                       and wave_pallas_vmem_ok(len(nb), max_b,
                                               config.num_leaves))):
            log.warning("tpu_growth_strategy=wave without the fused Pallas "
                        "histogram falls back to the XLA one-hot wave "
                        "histogram, which materializes [F, n, B] — only "
                        "viable for small datasets")
        # grad/hess buffer donation into the grow program
        # (docs/Performance.md): the per-class slices die at the grow
        # call in every configuration except linear trees, whose leaf
        # fitting re-reads them afterwards
        donate_grow = (config.tpu_donate_buffers and not config.linear_tree)
        if donate_grow and self.mesh is not None:
            # Donating the sharded grad/hess slices under the mesh is the
            # donation x SPMD interaction implicated in the MULTICHIP_r05
            # timeout: XLA cannot alias the row-sharded f32 inputs into
            # any output of the grow program (different dtype/sharding),
            # so donation buys nothing and destabilizes the multi-device
            # compile.  tests/test_multichip_smoke.py guards this matrix.
            log.warning("tpu_donate_buffers: grow-buffer donation is "
                        "disabled under a device mesh (sharded inputs "
                        "cannot alias the grow outputs)")
            donate_grow = False
        if strategy == "wave" and (self.mesh is not None
                                   and self._mesh_axis == 1
                                   and self.grow_params.voting is None):
            # data-parallel wave: the DEFAULT engine sharded over the row
            # mesh via shard_map + histogram psum (the reference's
            # ReduceScatter path, data_parallel_tree_learner.cpp:282)
            from ..parallel import make_sharded_wave_fn
            self._grow_fn = make_sharded_wave_fn(self.mesh,
                                                 donate=donate_grow)
        elif strategy == "wave":
            self._grow_fn = (grow_tree_wave_donated if donate_grow
                             else grow_tree_wave)
        else:
            if self.mesh is not None and self._mesh_axis == 1:
                # leaf-wise under a row mesh rides GSPMD annotations,
                # which cannot partition a pallas_call
                self.grow_params = self.grow_params._replace(
                    hist_method="segment")
            self._grow_fn = (grow_tree_donated if donate_grow
                             else grow_tree)
        self.growth_strategy = strategy
        # recompile watchdog (docs/Observability.md): a mid-training
        # shape change on a jitted hot-path entry re-traces the whole
        # program — a multi-second stall with no other symptom.  The
        # wrapper warns once per new signature and counts `recompiles`
        # into the metrics registry.
        from ..observability import RecompileDetector
        self._grow_fn = RecompileDetector(self._grow_fn, "grow_tree")

        # scores [K, n_pad] on device
        K = self.num_tree_per_iteration
        self.scores = self._put_by_row(
            np.zeros((K, self.n_pad), np.float32), axis=1)
        md = train_data.metadata
        self.has_init_score = md.init_score is not None
        if self.has_init_score:
            init = np.asarray(md.init_score, np.float64)
            if len(init) == n:
                init = np.tile(init, (K, 1)) if K > 1 else init[None, :]
            else:
                init = init.reshape(K, n)
            self.scores = self._put_by_row(
                _pad_rows(init.astype(np.float32), self.n_pad), axis=1)

        if objective is not None:
            objective.init(md, n)
            # objective.label may be transformed (e.g. reg_sqrt) — use it
            self.label_dev = self._put_by_row(
                _pad_rows(np.asarray(objective.label, np.float32), self.n_pad))
            self.weight_dev = (None if md.weight is None
                               else self._put_by_row(_pad_rows(
                                   np.asarray(md.weight, np.float32),
                                   self.n_pad)))
            if getattr(objective, "need_train", True) is False:
                self.class_need_train = [False] * K
            if not getattr(objective, "run_on_host", False):
                # one jitted gradient program per training run, taking the
                # FULL [K, n] scores and returning [K, n] grads.  All large
                # arrays are EXPLICIT arguments: a jit that closes over a
                # big device array embeds it as a constant, which on the
                # remote-TPU runtime permanently degrades every subsequent
                # dispatch in the process (~110ms floor); slicing/expansion
                # also stay inside jit (eager device ops cost ~100ms each).
                if self.num_tree_per_iteration > 1:
                    self._grad_fn_raw = jax.jit(
                        lambda sc, lab, w: objective.get_gradients(
                            sc, lab, w))
                else:  # single-model path: slice + expand inside jit
                    def _grad1(sc, lab, w):
                        g, h = objective.get_gradients(sc[0], lab, w)
                        return g[None, :], h[None, :]
                    self._grad_fn_raw = jax.jit(_grad1)
                from ..observability import RecompileDetector
                self._grad_fn_raw = RecompileDetector(self._grad_fn_raw,
                                                      "gradients")
                self._grad_fn = lambda sc: self._grad_fn_raw(
                    sc, self.label_dev, self.weight_dev)
        for m in self.train_metrics:
            m.init(md, n)
        self.init_scores_applied = [0.0] * K

        # ---- host-boundary machinery (docs/Performance.md) ----
        # device eval metrics: built lazily on the first eval tick
        # (ops/metrics.py); _finite_cache carries the sentinel flags
        # fetched with (or instead of) that tick's packed vector
        self._device_eval = None
        self._finite_cache = None
        self._true_flag = jnp.asarray(True)

        # tpulint: disable-next=donate-argnums -- read-only sentinel reduction; the boosting loop keeps updating the score buffer
        @jax.jit
        def _finite_flags(scores, grad_ok):
            return jnp.stack([grad_ok.astype(jnp.float32),
                              jnp.all(jnp.isfinite(scores))
                              .astype(jnp.float32)])
        self._finite_flags_fn = _finite_flags
        # private device-side copy for async checkpointing: the live
        # buffer may be DONATED to the next update while the writer
        # thread is still fetching, so snapshots fetch their own copy
        # tpulint: disable-next=donate-argnums -- the point is a second live copy; donating would delete the source buffer
        self._snapshot_scores_fn = jax.jit(lambda scores: scores + 0.0)
        # Donation: the per-iteration score updates consume the old
        # buffer and produce its replacement — donating lets XLA reuse
        # the HBM allocation instead of copying [K, n_pad] every tree
        # (enforced package-wide by the tpulint donate-argnums rule).
        _donate0 = (0,) if config.tpu_donate_buffers else ()

        def _score_update(scores, class_id, leaf_vals, leaf_id, pad_mask):
            delta = jnp.take(leaf_vals,
                             jnp.clip(leaf_id, 0, leaf_vals.shape[0] - 1))
            return scores.at[class_id].add(delta * pad_mask)
        self._score_update_fn = jax.jit(_score_update,
                                        donate_argnums=_donate0)

        @jax.jit
        def _pack_tree(t):
            # single flat f32 buffer so the host pulls the whole tree in ONE
            # D2H transfer (each transfer pays a ~11ms round trip on the
            # remote-TPU runtime); int arrays ride along bit-exactly via
            # bitcast (mirrors CUDATree::ToHost's batched copy,
            # ref: src/io/cuda/cuda_tree.cpp)
            as_f32 = lambda a: jax.lax.bitcast_convert_type(
                a.astype(jnp.int32), jnp.float32)
            return jnp.concatenate([
                as_f32(t.num_leaves[None]),
                as_f32(t.split_feature), as_f32(t.threshold_bin),
                as_f32(t.default_left), t.split_gain,
                as_f32(t.left_child), as_f32(t.right_child),
                t.internal_value, t.internal_weight,
                as_f32(t.internal_count),
                t.leaf_value, t.leaf_weight, as_f32(t.leaf_count),
                as_f32(t.leaf_parent), as_f32(t.leaf_depth),
                as_f32(t.split_is_cat),
                as_f32(t.cat_bitset.reshape(-1))])
        if jax.process_count() > 1 and self.mesh is not None:
            # multi-process SPMD: GSPMD may assign the packed buffer a
            # sharding spanning other processes' devices, which the host
            # cannot fetch; pin it fully-replicated so every rank reads
            # its local copy (the reference's workers likewise each hold
            # the whole model after SyncUpGlobalBestSplit)
            from jax.sharding import NamedSharding, PartitionSpec
            self._pack_tree_fn = jax.jit(
                _pack_tree,
                out_shardings=NamedSharding(self.mesh, PartitionSpec()))
        else:
            self._pack_tree_fn = _pack_tree
        from ..ops.split import cat_bitset_words
        self._cat_words = cat_bitset_words(max_b)
        # hot-path helpers kept inside jit (eager device ops are ~100ms
        # each through the remote-TPU tunnel)
        self._slice_row_fn = jax.jit(
            lambda a, k: jax.lax.dynamic_index_in_dim(a, k, 0,
                                                      keepdims=False))
        self._score_add_fn = jax.jit(lambda sc, k, v: sc.at[k].add(v),
                                     donate_argnums=_donate0)

        def _score_update_shrink(scores, class_id, leaf_vals, rate,
                                 leaf_id, pad_mask):
            delta = jnp.take(leaf_vals * rate,
                             jnp.clip(leaf_id, 0, leaf_vals.shape[0] - 1))
            return scores.at[class_id].add(delta * pad_mask)
        self._score_update_shrink_fn = jax.jit(_score_update_shrink,
                                               donate_argnums=_donate0)
        # ---- quantized training (ref: gradient_discretizer.{hpp,cpp};
        # config use_quantized_grad/num_grad_quant_bins/stochastic_rounding).
        # Gradients/hessians are snapped to the reference's integer grid on
        # device and DEQUANTIZED in place: the information content matches
        # the reference's int8 path exactly (k * scale for k in
        # [-qbins/2, qbins/2]), while accumulation stays in the fp32
        # histogram kernels (small integers times one scale are exact in
        # bf16 multiply / fp32 add).  The reference's 8/16/32-bit histogram
        # bin-width selection (SetNumBitsInHistogramBin) is a CPU memory
        # optimization with no TPU analogue.
        if config.linear_tree and objective is not None and getattr(
                objective, "need_renew_tree_output", False):
            # ref: config.cpp "Cannot use regression_l1 objective for
            # linear tree" (renewal overwrites the fitted leaf models)
            log.fatal(f"Cannot use objective {config.objective!r} "
                      "with linear_tree")
        self.use_quant = config.use_quantized_grad
        if self.use_quant:
            qhalf = max(config.num_grad_quant_bins // 2, 1)
            qbins = config.num_grad_quant_bins
            stoch = config.stochastic_rounding
            const_hess = bool(objective is not None
                              and getattr(objective, "is_constant_hessian",
                                          False)
                              and train_data.metadata.weight is None)
            base_key = jax.random.PRNGKey(config.seed + 5)

            def _disc(grad, hess, it):
                # ref: gradient_discretizer.cpp:120-160 DiscretizeGradients
                gscale = jnp.maximum(jnp.max(jnp.abs(grad)), 1e-35) / qhalf
                if const_hess:
                    hscale = jnp.maximum(jnp.max(jnp.abs(hess)), 1e-35)
                else:
                    hscale = (jnp.maximum(jnp.max(jnp.abs(hess)), 1e-35)
                              / qbins)
                if stoch:
                    kg, kh = jax.random.split(
                        jax.random.fold_in(base_key, it))
                    rg = jax.random.uniform(kg, grad.shape)
                    rh = jax.random.uniform(kh, hess.shape)
                else:
                    rg = rh = 0.5
                # static_cast<int8_t> truncates toward zero; the +/- noise
                # by gradient sign makes it stochastic round away from zero
                gi = jnp.trunc(grad / gscale + jnp.sign(grad) * rg)
                hi = (jnp.ones_like(hess) if const_hess
                      else jnp.trunc(hess / hscale + rh))
                return (gi * gscale, hi * hscale,
                        jnp.stack([gscale, hscale]))
            # tpulint: disable-next=donate-argnums -- the float grad/hess slices are reused for leaf renewal (float_grads) after discretization
            self._discretize_fn = jax.jit(_disc)
            if config.quant_train_renew_leaf:
                renew_p = SplitParams(
                    lambda_l1=config.lambda_l1, lambda_l2=config.lambda_l2,
                    max_delta_step=config.max_delta_step)

                def _renew(leaf_value, leaf_id, grad, hess, mask):
                    # ref: gradient_discretizer.cpp RenewIntGradTreeOutput —
                    # leaf outputs recomputed from the ORIGINAL float grads
                    from ..ops.split import leaf_output
                    L = leaf_value.shape[0]
                    ids = jnp.clip(leaf_id, 0, L - 1)
                    sg = jnp.zeros(L, jnp.float32).at[ids].add(grad * mask)
                    sh = jnp.zeros(L, jnp.float32).at[ids].add(hess * mask)
                    out = leaf_output(sg, sh, jnp.zeros(L, jnp.float32),
                                      0.0, renew_p)
                    return jnp.where(sh > 0, out, leaf_value)
                # the float grad/hess slices die here: renewal is their
                # last consumer, so their buffers are donated
                self._renew_quant_fn = jax.jit(
                    _renew, donate_argnums=((2, 3)
                                            if config.tpu_donate_buffers
                                            else ()))

        if has_cegb:
            F_used = len(nb)

            @jax.jit
            def _cegb_mark(used, split_feature, num_leaves):
                m = (jnp.arange(split_feature.shape[0], dtype=jnp.int32)
                     < num_leaves - 1)
                return used.at[jnp.where(m, split_feature, F_used)].set(
                    True, mode="drop")
            self._cegb_mark_fn = _cegb_mark
        self._rng_bag = np.random.RandomState(config.bagging_seed)
        self._rng_feat = np.random.RandomState(config.feature_fraction_seed)
        self._ones_col_mask = jnp.ones(len(nb), bool)
        self._bag_mask_host = np.ones(self.n_pad, np.float32)
        self._bag_mask_host[n:] = 0.0
        self.bag_mask = self._put_by_row(self._bag_mask_host)

    def _raw_or_reconstruct(self, ds: Dataset) -> np.ndarray:
        """Raw feature matrix for prediction: the kept raw data when present,
        else representative bin values (exact for trees trained with the same
        bin mappers, since numerical thresholds are bin upper bounds)."""
        if ds.raw_data is not None:
            return ds.raw_data
        from ..io.binning import MISSING_NAN, MISSING_ZERO
        X = np.zeros((ds.num_data, ds.num_total_features))
        for i, f in enumerate(ds.used_features):
            m = ds.bin_mappers[f]
            lut = np.array([m.bin_to_value(b) for b in range(m.num_bin)])
            # missing-value bins must reconstruct to the value the predictor's
            # default_left routing expects, not the bin's upper bound
            if m.missing_type == MISSING_NAN:
                lut[m.num_bin - 1] = np.nan
            elif m.missing_type == MISSING_ZERO:
                lut[m.default_bin] = 0.0
            X[:, f] = lut[np.clip(ds.feature_bins(i), 0, m.num_bin - 1)]
        return X

    def continue_from(self, prev: "GBDT", train_raw=None,
                      valid_raws=None) -> None:
        """Continued training: adopt prev's trees and seed train/valid scores
        with its predictions (ref: application.cpp:94-97 init score from
        input_model; gbdt.h:70 MergeFrom)."""
        if hasattr(prev, "_sync_model"):
            prev._sync_model()
        K = self.num_tree_per_iteration
        if prev.num_tree_per_iteration != K:
            log.fatal("Cannot continue training: the initial model has "
                      f"{prev.num_tree_per_iteration} trees per iteration, "
                      f"this one needs {K}")
        if getattr(prev, "average_output_", False) != self.average_output_:
            log.fatal("Cannot continue training across averaging modes "
                      "(rf vs gbdt/dart): tree outputs would be combined "
                      "with the wrong weights")
        self.models_ = [_copy.deepcopy(t) for t in prev.models_]
        for t in self.models_:
            self._reconstruct_bin_space(t)
        self.num_init_iteration_ = len(self.models_) // max(K, 1)
        self.iter_ = 0
        X = (train_raw if train_raw is not None
             else self._raw_or_reconstruct(self.train_data))
        raw = prev.predict_raw(np.asarray(X, np.float64))
        raw = raw[:, None] if raw.ndim == 1 else raw  # [n, K]
        self.scores = self.scores + jnp.asarray(
            _pad_rows(raw.T.astype(np.float32), self.n_pad))
        for vi, vds in enumerate(self.valid_sets):
            vX = (valid_raws[vi] if valid_raws is not None
                  and valid_raws[vi] is not None
                  else self._raw_or_reconstruct(vds))
            vraw = prev.predict_raw(np.asarray(vX, np.float64))
            vraw = vraw[:, None] if vraw.ndim == 1 else vraw
            self.valid_scores[vi] += vraw.T

    def _ensure_finite_flags(self):
        """(gradients_finite, scores_finite) for the current iteration.
        The device eval tick folds both flags into its packed fetch
        (ops/metrics.py); when no device eval ran this iteration, one
        dedicated tiny [2]-vector fetch computes them — either way the
        sentinel never pulls score samples to host (it used to fetch
        scores[:, :256])."""
        if self._finite_cache is None:
            flag = getattr(self, "_grad_ok", None)
            if flag is None:
                flag = self._true_flag
            flags = _fetch_host(self._finite_flags_fn(self.scores, flag))
            self._finite_cache = (bool(flags[0] > 0), bool(flags[1] > 0))
        return self._finite_cache

    def gradients_finite(self) -> bool:
        """Accumulated device-side gradient-finiteness flag (engine
        sentinel; one shared host fetch per check tick)."""
        return self._ensure_finite_flags()[0]

    def scores_finite(self) -> bool:
        """Device-side all-finite reduction over the full score buffer
        (engine sentinel; rides the same fetch as gradients_finite)."""
        return self._ensure_finite_flags()[1]

    # ------------------------------------------------------- checkpoint state
    def capture_train_state(self, async_copy: bool = False):
        """Exact trainer state for CheckpointManager: the float32 score
        buffer plus the stateful sampling RNGs.  Model text alone is not
        enough for byte-identical resume — re-seeding scores from
        predictions differs from the accumulated buffer in ulps, which
        changes later trees.  Returns None when the scores span
        non-addressable devices (multi-process SPMD): resume then falls
        back to predict-based seeding, which is rank-deterministic.

        With `async_copy` (the async checkpoint writer,
        docs/Performance.md) the scores stay a DEVICE array in the
        returned dict: a private snapshot copy whose D2H transfer is
        started here and completed by whoever serializes the state —
        the training thread never blocks on the fetch, and the live
        buffer is free to be donated to the next update meanwhile."""
        sc = self.scores
        if isinstance(sc, jax.Array) and not sc.is_fully_addressable:
            return None
        if async_copy and isinstance(sc, jax.Array):
            sc = self._snapshot_scores_fn(sc)
            copy_async = getattr(sc, "copy_to_host_async", None)
            if copy_async is not None:
                copy_async()
        else:
            sc = np.asarray(sc)
        state = {"scores": sc,
                 "num_data": np.int64(self.num_data),
                 "rng_bag": np.array(self._rng_bag.get_state(legacy=False),
                                     dtype=object),
                 "rng_feat": np.array(self._rng_feat.get_state(legacy=False),
                                      dtype=object),
                 "bag_mask": np.asarray(self._bag_mask_host)}
        return state

    def restore_train_state(self, state) -> bool:
        """Restore a capture_train_state() payload (after continue_from
        adopted the checkpoint's trees).  Returns True when the exact
        score buffer was restored."""
        if state is None:
            return False
        ok = False
        sc = state.get("scores")
        if sc is not None:
            sc = np.asarray(sc, np.float32)
            n = int(state.get("num_data", sc.shape[-1]))
            if n != self.num_data:
                log.warning(f"Checkpoint state has {n} rows but the train "
                            f"set has {self.num_data}; keeping "
                            "predict-seeded scores")
            else:
                # re-pad for this run's mesh (n_pad can differ)
                self.scores = self._put_by_row(
                    _pad_rows(sc[:, :n], self.n_pad), axis=1)
                ok = True
        for key, rng in (("rng_bag", self._rng_bag),
                         ("rng_feat", self._rng_feat)):
            st = state.get(key)
            if st is not None:
                try:
                    rng.set_state(st.item() if hasattr(st, "item") else st)
                except (ValueError, TypeError) as e:
                    log.warning(f"Could not restore {key} RNG state: {e}")
        bm = state.get("bag_mask")
        if bm is not None and len(bm) >= self.num_data:
            mask = np.zeros(self.n_pad, np.float32)
            mask[:self.num_data] = np.asarray(bm, np.float32)[:self.num_data]
            self._bag_mask_host = mask
            self.bag_mask = self._put_by_row(mask)
        return ok

    def add_valid_data(self, valid_data: Dataset, name: str,
                       metrics: Sequence[Metric]) -> None:
        self.valid_sets.append(valid_data)
        self.valid_names.append(name)
        ms = list(metrics)
        for m in ms:
            m.init(valid_data.metadata, valid_data.num_data)
        self.valid_metrics.append(ms)
        K = self.num_tree_per_iteration
        sc = np.zeros((K, valid_data.num_data), np.float64)
        md = valid_data.metadata
        if md.init_score is not None:
            init = np.asarray(md.init_score, np.float64)
            sc += (np.tile(init, (K, 1)) if init.ndim == 1 and K > 1
                   else init.reshape(K, -1))
        self.valid_scores.append(sc)

    # ------------------------------------------------------------------ train
    def _boost_from_average(self, class_id: int) -> float:
        """ref: gbdt.cpp:313 BoostFromAverage."""
        cfg, obj = self.config, self.objective
        if self.models_ or self.has_init_score or obj is None:
            return 0.0
        if cfg.boost_from_average or self.train_data.num_features == 0:
            init = obj.boost_from_score(class_id)
            if abs(init) > K_EPSILON:
                self.scores = self._score_add_fn(self.scores, class_id, init)
                for sc in self.valid_scores:
                    sc[class_id] += init
                log.info(f"Start training from score {init:.6f}")
                return init
        elif obj.name in ("regression_l1", "quantile", "mape"):
            log.warning(f"Disabling boost_from_average in {obj.name} "
                        "may cause the slow convergence")
        return 0.0

    def _compute_gradients(self):
        """Per-class gradients [K, n_pad] (ref: gbdt.cpp:220 Boosting)."""
        obj = self.objective
        if getattr(obj, "run_on_host", False):
            # ranking objectives with a device program (bucketed pairwise
            # lambdas / masked-softmax passes + on-device position-bias
            # Newton state, ranking.py make_device_grad_fn) skip the host
            # round-trip entirely; the per-query host loop remains only
            # for position-bias rank_xendcg and custom objectives
            dev_fn = getattr(self, "_ranking_dev_fn", None)
            if dev_fn is None and hasattr(obj, "make_device_grad_fn"):
                dev_fn = obj.make_device_grad_fn(self.n_pad)
                self._ranking_dev_fn = dev_fn if dev_fn else False
            if dev_fn:
                return dev_fn(self.scores, self.weight_dev)
            score_h = np.asarray(self._slice_row_fn(
                self.scores, 0))[:self.num_data].astype(np.float64)
            g, h = obj.get_gradients_host(score_h)
            grad = jnp.asarray(_pad_rows(g, self.n_pad))[None, :]
            hess = jnp.asarray(_pad_rows(h, self.n_pad))[None, :]
            return grad, hess
        return self._grad_fn(self.scores)

    def _update_bagging(self, grad=None, hess=None):
        """Row sampling per iteration.  Bagging is a row mask (ref:
        src/boosting/bagging.hpp); GOSS also rescales small-gradient rows
        (ref: src/boosting/goss.hpp:118-165 Helper).  Returns
        (bag_mask, grad, hess)."""
        cfg = self.config
        n = self.num_data
        # sampling streams are keyed by the ABSOLUTE iteration so a
        # checkpoint resume (or init_model continuation) advances the
        # stream instead of replaying the first run's draws
        abs_iter = self.num_init_iteration_ + self.iter_
        if cfg.data_sample_strategy == "goss" and grad is not None:
            # not subsampled for the first 1/learning_rate iterations
            if abs_iter < int(1.0 / max(cfg.learning_rate, 1e-10)):
                return self.bag_mask, grad, hess
            top_k = max(1, int(n * cfg.top_rate))
            other_k = max(1, int(n * cfg.other_rate))
            key = jax.random.PRNGKey(cfg.bagging_seed + abs_iter)
            mask, grad, hess = _goss_sample(
                grad, hess, self.pad_mask, key, top_k, other_k)
            return mask, grad, hess
        if cfg.bagging_freq > 0 and cfg.bagging_fraction < 1.0:
            if abs_iter % cfg.bagging_freq == 0:
                pos_frac, neg_frac = cfg.pos_bagging_fraction, cfg.neg_bagging_fraction
                if (pos_frac < 1.0 or neg_frac < 1.0) and self.objective is not None \
                        and self.objective.name == "binary":
                    # balanced bagging (ref: bagging.hpp balanced_bagging_)
                    lab = np.asarray(self.train_data.metadata.label) > 0
                    mask = np.zeros(self.n_pad, np.float32)
                    for cls_mask, frac in ((lab, pos_frac), (~lab, neg_frac)):
                        cls_idx = np.nonzero(cls_mask)[0]
                        take = int(len(cls_idx) * frac)
                        mask[self._rng_bag.choice(cls_idx, take, replace=False)] = 1.0
                else:
                    cnt = int(n * cfg.bagging_fraction)
                    mask = np.zeros(self.n_pad, np.float32)
                    idx = self._rng_bag.choice(n, cnt, replace=False)
                    mask[idx] = 1.0
                self._bag_mask_host = mask
                self.bag_mask = jnp.asarray(mask)
        return self.bag_mask, grad, hess

    def _col_mask(self):
        cfg = self.config
        F = self.train_data.num_features
        if cfg.feature_fraction >= 1.0:
            return self._ones_col_mask
        cnt = max(1, int(round(F * cfg.feature_fraction)))
        mask = np.zeros(F, bool)
        mask[self._rng_feat.choice(F, cnt, replace=False)] = True
        return jnp.asarray(mask)

    def pre_gradient_hook(self) -> None:
        """Called before training scores are read for gradient computation
        (custom fobj path).  DART drops trees here so the user's objective
        sees the dropped ensemble (ref: dart.hpp:77 GetTrainingScore)."""

    def train_one_iter(self, gradients=None, hessians=None) -> bool:
        """One boosting iteration; returns True when training should stop
        (ref: gbdt.cpp:338 TrainOneIter)."""
        K = self.num_tree_per_iteration
        if faults.active():
            faults.maybe_crash(self.num_init_iteration_ + self.iter_)
            faults.maybe_worker_lost(self.num_init_iteration_ + self.iter_)
            faults.maybe_hang(self.num_init_iteration_ + self.iter_)
        # sentinel flags fetched for the previous iteration are stale now
        self._finite_cache = None
        init_scores = [0.0] * K
        if gradients is None:
            for k in range(K):
                init_scores[k] = self._boost_from_average(k)
            with global_timer.scope("GBDT::gradients"):
                grad, hess = self._compute_gradients()
                grad, hess = global_timer.block((grad, hess))
            if faults.active():
                grad, hess = faults.maybe_nan_grad(
                    grad, hess, self.num_init_iteration_ + self.iter_)
            if self.config.nonfinite_check_freq > 0:
                # device-side finiteness flag, accumulated lazily (no host
                # sync here); the split program masks NaN gains/values to
                # zero, so corrupt gradients otherwise degrade the model
                # SILENTLY.  engine.train fetches the flag every
                # nonfinite_check_freq iterations (gradients_finite()).
                ok = (jnp.all(jnp.isfinite(grad))
                      & jnp.all(jnp.isfinite(hess)))
                prev = getattr(self, "_grad_ok", None)
                self._grad_ok = ok if prev is None else (prev & ok)
        else:
            grad = jnp.asarray(_pad_rows(np.asarray(gradients, np.float32)
                                         .reshape(K, -1), self.n_pad))
            hess = jnp.asarray(_pad_rows(np.asarray(hessians, np.float32)
                                         .reshape(K, -1), self.n_pad))

        with global_timer.scope("GBDT::bagging"):
            bag_mask, grad, hess = self._update_bagging(grad, hess)
        should_continue = False
        for k in range(K):
            tree = None
            if self.class_need_train[k] and self.train_data.num_features > 0:
                g_k = self._slice_row_fn(grad, k)
                h_k = self._slice_row_fn(hess, k)
                if self.use_quant:
                    # per-tree discretization (ref: serial_tree_learner
                    # BeforeTrain -> DiscretizeGradients on the class slice);
                    # keyed by absolute iteration so resume/continuation
                    # advances the rounding stream
                    gq, hq, qscales = self._discretize_fn(
                        g_k, h_k,
                        np.int32((self.num_init_iteration_ + self.iter_)
                                 * K + k))
                else:
                    gq, hq, qscales = g_k, h_k, None
                # the float g_k/h_k slices are consumed after growth only
                # by linear-leaf fitting (donation off) and quantized leaf
                # renewal (gq/hq are then distinct buffers); snapshot the
                # tuple BEFORE the grow call — when quantization is off,
                # gq/hq ALIAS g_k/h_k and the donated grow entries delete
                # their argument buffers (tpulint donated-buffer-reuse)
                float_grads = ((g_k, h_k)
                               if (self.config.linear_tree
                                   or (self.use_quant
                                       and self.config
                                       .quant_train_renew_leaf))
                               else None)
                with global_timer.scope("GBDT::grow_tree"):
                    grow_kw = ({"cegb_used": self._cegb_used}
                               if self._cegb_used is not None else {})
                    if (self.config.extra_trees
                            or self.config.feature_fraction_bynode < 1.0):
                        # continued training advances the stream instead
                        # of replaying the first run's draws
                        grow_kw["extra_tag"] = np.int32(
                            (self.num_init_iteration_ + self.iter_) * K
                            + k)
                    if self._lazy_used is not None:
                        grow_kw["lazy_used"] = self._lazy_used
                    if (qscales is not None
                            and self.growth_strategy == "wave"
                            and self.grow_params.quant_bins > 0):
                        grow_kw["quant_scales"] = qscales
                    if faults.active():
                        # one rank wedging HERE leaves its peers blocked
                        # inside the histogram psum — the live-but-hung
                        # shape the stall watchdog exists for
                        faults.maybe_collective_stall(
                            self.num_init_iteration_ + self.iter_)
                    out = self._grow_fn(
                        self.binned_dev, gq, hq, bag_mask,
                        self._col_mask(), self.meta, self.grow_params,
                        **grow_kw)
                    out = global_timer.block(out)
                    if self._lazy_used is not None:
                        arrays, leaf_id, self._lazy_used = out
                    else:
                        arrays, leaf_id = out
                if self._cegb_used is not None:
                    self._cegb_used = self._cegb_mark_fn(
                        self._cegb_used, arrays.split_feature,
                        arrays.num_leaves)
                with global_timer.scope("GBDT::finalize_tree"):
                    tree = self._finalize_tree(arrays, leaf_id, k,
                                               init_scores[k],
                                               float_grads=float_grads)
                _metrics.inc("trees_grown")
            if tree is None:
                if len(self.models_) < K:
                    tree = self._make_const_stump(k)
                else:
                    tree = Tree(2)
                    tree.num_leaves = 1
            else:
                should_continue = True
            self.models_.append(tree)

        if not should_continue:
            return self._stop_training(len(self.models_) // K - 1)
        # keep a short materialization pipeline: drain down to 2 in-flight
        # trees each iteration.  The oldest buffers have settled by then, so
        # the pull is a cheap transfer; probing readiness instead
        # (is_ready) costs a tunnel RPC per probe and deep queues degrade
        # the remote runtime, so neither polling nor unbounded async works.
        self._drain_pending(keep_depth=2)
        stop_iter = self._all_stump_iteration()
        if stop_iter is not None:
            return self._stop_training(stop_iter)
        self.iter_ += 1
        return False

    def _all_stump_iteration(self) -> Optional[int]:
        """First iteration whose K drained trees ALL grew no split (the
        reference's stop condition; a single class stalling only yields a
        stump for that class, ref: gbdt.cpp:395-418)."""
        K = self.num_tree_per_iteration
        for it in sorted({idx // K for idx in self._stump_idxs}):
            if all(it * K + k in self._stump_idxs for k in range(K)):
                return it
        return None

    def _make_const_stump(self, k: int) -> Tree:
        """Constant one-leaf tree for a class with no first-iteration split
        (boost_from_score when averages were not applied; ref:
        gbdt.cpp:372-391)."""
        tree = Tree(2)
        tree.num_leaves = 1
        init = 0.0
        if (self.objective is not None
                and not self.config.boost_from_average
                and not self.has_init_score):
            init = self.objective.boost_from_score(k)
            self.scores = self._score_add_fn(self.scores, k, init)
            for sc in self.valid_scores:
                sc[k] += init
        tree.leaf_value[0] = init
        tree.shrinkage = 1.0
        return tree

    def _stop_training(self, stop_iter: int) -> bool:
        """Reference stop semantics: drop the iteration that failed to split
        and everything after it (ref: gbdt.cpp:338-418 TrainOneIter's
        no-split handling), then report stop."""
        K = self.num_tree_per_iteration
        self._drain_pending(keep_depth=0)
        self._stump_idxs.clear()
        log.warning("Stopped training because there are no more leaves "
                    "that meet the split requirements")
        # trees past the stop point already contributed to the device
        # scores (the pipelined update runs a couple of iterations ahead);
        # revert them so scores stay consistent with the kept model
        for idx in range(stop_iter * K, len(self.models_)):
            tree = self.models_[idx]
            if isinstance(tree, Tree) and tree.num_leaves > 1:
                neg = _copy.deepcopy(tree)
                neg.leaf_value[:neg.num_leaves] *= -1.0
                self._add_tree_score(neg, idx % K, train=True, valid=False)
        if stop_iter > 0:
            del self.models_[stop_iter * K:]
            self.iter_ = stop_iter
        else:
            # first iteration: keep constant stumps (boost_from_score)
            del self.models_[K:]
            self.iter_ = 0
            for k in range(K):
                tree = self.models_[k]
                if not isinstance(tree, Tree) or tree.num_leaves > 1:
                    self.models_[k] = self._make_const_stump(k)
        return True

    def _arrays_to_tree(self, arrays) -> Optional[Tree]:
        """Device TreeArrays -> host Tree (pure conversion; one batched D2H
        transfer of the whole tree as a flat buffer, like CUDATree::ToHost,
        ref: src/io/cuda/cuda_tree.cpp)."""
        return self._packed_to_tree(_fetch_host(self._pack_tree_fn(arrays)))

    def _packed_to_tree(self, flat: np.ndarray) -> Optional[Tree]:
        """Decode the packed flat tree buffer into a host Tree."""
        ints = flat.view(np.int32)
        L = self.config.num_leaves
        ni = max(L - 1, 1)
        W = self._cat_words
        parts = []
        off = 1
        for size, arr_ints in ((ni, True), (ni, True), (ni, True),
                               (ni, False), (ni, True), (ni, True),
                               (ni, False), (ni, False), (ni, True),
                               (L, False), (L, False), (L, True),
                               (L, True), (L, True),
                               (ni, True), (ni * W, True)):
            parts.append(ints[off:off + size] if arr_ints
                         else flat[off:off + size])
            off += size
        (split_feature, threshold_bin, default_left, split_gain,
         left_child, right_child, internal_value, internal_weight,
         internal_count, leaf_value, leaf_weight, leaf_count,
         leaf_parent, leaf_depth, split_is_cat, cat_bits_flat) = parts
        cat_bits = cat_bits_flat.reshape(ni, W)

        class _Host:  # attribute-compatible host view of TreeArrays
            pass
        arrays = _Host()
        arrays.num_leaves = ints[0]
        arrays.split_feature = split_feature
        arrays.threshold_bin = threshold_bin
        arrays.default_left = default_left != 0
        arrays.split_gain = split_gain
        arrays.left_child = left_child
        arrays.right_child = right_child
        arrays.internal_value = internal_value
        arrays.internal_weight = internal_weight
        arrays.internal_count = internal_count
        arrays.leaf_value = leaf_value
        arrays.leaf_weight = leaf_weight
        arrays.leaf_count = leaf_count
        arrays.leaf_parent = leaf_parent
        arrays.leaf_depth = leaf_depth
        num_leaves = int(arrays.num_leaves)
        if num_leaves <= 1:
            return None
        ds = self.train_data
        L = self.config.num_leaves
        tree = Tree(max(L, 2))
        tree.num_leaves = num_leaves
        ni = num_leaves - 1
        sf_inner = np.asarray(arrays.split_feature)[:ni]
        thr_bin = np.asarray(arrays.threshold_bin)[:ni]
        dleft = np.asarray(arrays.default_left)[:ni]
        tree.split_feature_inner[:ni] = sf_inner
        tree.split_feature[:ni] = np.array(
            [ds.used_features[f] for f in sf_inner], np.int32)
        tree.threshold_in_bin[:ni] = thr_bin
        is_cat_node = split_is_cat[:ni] != 0
        for i in range(ni):
            mapper = ds.bin_mappers[tree.split_feature[i]]
            if is_cat_node[i]:
                # decode the device bins-left bitset, then register via the
                # shared Tree bookkeeping (tree.py register_cat_split)
                words = cat_bits[i]
                bins_left = [b for b in range(mapper.num_bin)
                             if (words[b // 32] >> (b % 32)) & 1]
                cats_left = [mapper.bin_2_categorical[b] for b in bins_left
                             if mapper.bin_2_categorical[b] >= 0]
                tree.register_cat_split(i, bins_left, cats_left,
                                        mapper.missing_type)
                continue
            tree.threshold[i] = mapper.bin_to_value(int(thr_bin[i]))
            dt = 0
            if dleft[i]:
                dt |= 2
            dt |= (mapper.missing_type & 3) << 2
            tree.decision_type[i] = dt
        tree.split_gain[:ni] = np.asarray(arrays.split_gain)[:ni]
        tree.left_child[:ni] = np.asarray(arrays.left_child)[:ni]
        tree.right_child[:ni] = np.asarray(arrays.right_child)[:ni]
        tree.internal_value[:ni] = np.asarray(arrays.internal_value)[:ni]
        tree.internal_weight[:ni] = np.asarray(arrays.internal_weight)[:ni]
        tree.internal_count[:ni] = np.asarray(arrays.internal_count)[:ni]
        nl = num_leaves
        tree.leaf_value[:nl] = np.asarray(arrays.leaf_value)[:nl]
        tree.leaf_weight[:nl] = np.asarray(arrays.leaf_weight)[:nl]
        tree.leaf_count[:nl] = np.asarray(arrays.leaf_count)[:nl]
        tree.leaf_parent[:nl] = np.asarray(arrays.leaf_parent)[:nl]
        tree.leaf_depth[:nl] = np.asarray(arrays.leaf_depth)[:nl]
        return tree

    def _finalize_tree(self, arrays, leaf_id, class_id: int,
                       init_score: float, float_grads=None):
        """Renew/shrink/score-update after growing (ref: gbdt.cpp:395-407).

        Fast path: every host sync on a fresh device result costs ~100ms on
        the remote-TPU runtime, so when no host-side tree work is needed
        this iteration (no renewal objective, no valid sets), the score
        update runs device-side with shrinkage fused and the host Tree is
        materialized LATER from a pending queue (_drain_pending) once its
        packed buffer has settled — the boosting loop never blocks on D2H.
        """
        obj = self.objective
        if self.config.linear_tree:
            # linear leaves (ref: linear_tree_learner.cpp:184
            # CalculateLinear runs after the structure is grown, before
            # shrinkage; scores then need the full linear prediction)
            tree = self._arrays_to_tree(arrays)
            if tree is None:
                return None
            g, h = float_grads
            bag = self._bag_mask_host[:self.num_data]
            self._calculate_linear(
                tree, np.asarray(leaf_id)[:self.num_data],
                np.asarray(g)[:self.num_data] * bag,
                np.asarray(h)[:self.num_data] * bag)
            tree.apply_shrinkage(self.shrinkage_rate)
            X = self._raw_or_reconstruct(self.train_data)
            delta = tree.predict(np.asarray(X, np.float64))
            self.scores = self._score_add_fn(
                self.scores, class_id,
                jnp.asarray(_pad_rows(delta.astype(np.float32),
                                      self.n_pad)))
            self._add_tree_score(tree, class_id, train=False)
            if abs(init_score) > K_EPSILON:
                tree.add_bias(init_score)
            return tree
        if (self.use_quant and self.config.quant_train_renew_leaf
                and float_grads is not None):
            # quantized leaf renewal runs first, then any objective renewal
            # (ref: serial tree learner renews int-grad outputs inside
            # Train; GBDT::TrainOneIter renews for the objective after)
            arrays = arrays._replace(leaf_value=self._renew_quant_fn(
                arrays.leaf_value, leaf_id, float_grads[0], float_grads[1],
                self.bag_mask))
        need_sync = ((obj is not None and obj.need_renew_tree_output)
                     or bool(self.valid_sets))
        if not need_sync:
            packed = self._pack_tree_fn(arrays)
            copy_async = getattr(packed, "copy_to_host_async", None)
            if copy_async is not None:
                copy_async()
            self._pending.append(dict(
                packed=packed, idx=len(self.models_),
                init=init_score, rate=self.shrinkage_rate))
            self.scores = self._score_update_shrink_fn(
                self.scores, class_id, arrays.leaf_value,
                self.shrinkage_rate, leaf_id, self.pad_mask)
            return _PENDING_TREE

        tree = self._arrays_to_tree(arrays)
        if tree is None:
            return None
        num_leaves = tree.num_leaves
        nl = num_leaves
        L = self.config.num_leaves

        # per-leaf output renewal (ref: RenewTreeOutput; L1/quantile/MAPE)
        obj = self.objective
        leaf_id_host = None
        if obj is not None and obj.need_renew_tree_output:
            leaf_id_host = np.asarray(leaf_id)[:self.num_data]
            score_host = np.asarray(self.scores[class_id])[:self.num_data]
            bag = self._bag_mask_host[:self.num_data] > 0
            renewed = obj.renew_tree_output(
                np.where(bag, leaf_id_host, -1), score_host, num_leaves)
            if renewed is not None:
                tree.leaf_value[:nl] = renewed

        tree.apply_shrinkage(self.shrinkage_rate)

        # score update on device (ref: ScoreUpdater::AddScore(tree_learner))
        leaf_vals = jnp.asarray(tree.leaf_value[:max(L, 2)].astype(np.float32))
        self.scores = self._score_update_fn(self.scores, class_id, leaf_vals,
                                            leaf_id, self.pad_mask)
        # valid scores on host
        self._add_tree_score(tree, class_id, train=False)

        if abs(init_score) > K_EPSILON:
            tree.add_bias(init_score)
        return tree

    def _drain_pending(self, keep_depth: int = 0) -> None:
        """Materialize pending device trees oldest-first until at most
        keep_depth remain in flight."""
        if len(self._pending) > keep_depth:
            with global_timer.scope("GBDT::materialize_tree"):
                self._drain_pending_now(keep_depth)

    def _drain_pending_now(self, keep_depth: int) -> None:
        while len(self._pending) > keep_depth:
            p = self._pending.pop(0)
            tree = self._packed_to_tree(_fetch_host(p["packed"]))
            if tree is None:
                # grew no split: keep a 0-value stump for this class (ref:
                # gbdt.cpp:372-391) and record it for the stop condition
                self._stump_idxs.add(p["idx"])
                tree = Tree(2)
                tree.num_leaves = 1
                tree.shrinkage = 1.0
                self.models_[p["idx"]] = tree
            else:
                tree.apply_shrinkage(p["rate"])
                if abs(p["init"]) > K_EPSILON:
                    tree.add_bias(p["init"])
                self.models_[p["idx"]] = tree

    def _sync_model(self) -> None:
        """Block until models_ holds real host trees (public consumers —
        predict/save/eval/rollback — call this first)."""
        self._drain_pending(keep_depth=0)
        stop_iter = self._all_stump_iteration()
        if stop_iter is not None:
            self._stop_training(stop_iter)

    # -------------------------------------------------------- score plumbing
    def _reconstruct_bin_space(self, tree: Tree) -> None:
        """Rebuild a text-adopted tree's BIN-space routing fields against
        this run's bin mappers (threshold_in_bin, split_feature_inner,
        inner categorical bitsets).  Model text stores real-valued
        thresholds only; training-time score adds (_add_tree_score —
        DART drops/normalize, RF averaging) route rows in bin space, so
        without this a resumed DART run subtracts GARBAGE contributions
        for every adopted tree it drops.  Exact inverse of
        _arrays_to_tree's bin->value mapping: the real threshold IS
        bin_upper_bound[bin], so searchsorted recovers the bin."""
        ni = tree.num_leaves - 1
        if getattr(tree, "_bin_space_valid", True):
            return
        tree._bin_space_valid = True
        if ni <= 0:
            return
        ds = self.train_data
        if ds is None or not getattr(ds, "bin_mappers", None):
            return
        from ..models.tree import K_CATEGORICAL_MASK, _to_bitset
        inner_of = {f: i for i, f in enumerate(ds.used_features)}
        cat_mask = (tree.decision_type[:ni] & K_CATEGORICAL_MASK) > 0
        per_ci_bins: Dict[int, List[int]] = {}
        for nd in range(ni):
            f = int(tree.split_feature[nd])
            if f in inner_of:
                tree.split_feature_inner[nd] = inner_of[f]
            mapper = ds.bin_mappers[f]
            if cat_mask[nd]:
                # outer bitset holds category VALUES; the inner one
                # holds this dataset's bin indices for those values
                ci = int(tree.threshold[nd])
                tree.threshold_in_bin[nd] = ci
                lo = tree.cat_boundaries[ci]
                hi = tree.cat_boundaries[ci + 1]
                cats = [32 * w + j
                        for w, word in enumerate(tree.cat_threshold[lo:hi])
                        for j in range(32) if (word >> j) & 1]
                c2b = getattr(mapper, "categorical_2_bin", {})
                per_ci_bins[ci] = _to_bitset(
                    [c2b[c] for c in cats if c in c2b])
                continue
            ub = np.asarray(mapper.bin_upper_bound, np.float64)
            b = int(np.searchsorted(ub, float(tree.threshold[nd]),
                                    side="left"))
            tree.threshold_in_bin[nd] = min(b, max(mapper.num_bin - 1, 0))
        if tree.num_cat > 0:
            ct_inner: List[int] = []
            cb_inner = [0]
            for ci in range(tree.num_cat):
                ct_inner.extend(per_ci_bins.get(ci, []))
                cb_inner.append(len(ct_inner))
            tree.cat_threshold_inner = ct_inner
            tree.cat_boundaries_inner = cb_inner

    def _tree_leaf_ids(self, tree: Tree, ds) -> np.ndarray:
        """Bin-space leaf index of every row for a tree trained on this
        dataset's bin mappers.  `ds` may store per-feature bins or (for
        sparse-ingested data) bundle codes with its own plan."""
        from ..models.tree import K_CATEGORICAL_MASK
        ni = tree.num_leaves - 1
        binned = ds.binned_host()
        plan = ds.pre_bundled_plan
        bundle_kw = {}
        if plan is not None:
            bundle_kw = dict(bundle_group=plan.group_idx,
                             bundle_offset=plan.offsets,
                             bundle_zero_bin=plan.zero_bin)
        return leaf_index_bin_space(
            tree.split_feature_inner[:ni], tree.threshold_in_bin[:ni],
            (tree.decision_type[:ni] & 2) > 0,
            tree.left_child[:ni], tree.right_child[:ni], tree.num_leaves,
            self.f_missing_type, self.f_num_bin, self.f_default_bin, binned,
            is_cat_node=(tree.decision_type[:ni] & K_CATEGORICAL_MASK) > 0,
            cat_boundaries_inner=tree.cat_boundaries_inner,
            cat_threshold_inner=tree.cat_threshold_inner, **bundle_kw)

    def _add_tree_score(self, tree: Tree, class_id: int,
                        train: bool = True, valid: bool = True) -> None:
        """score += tree's *current* leaf outputs (ref: score_updater.hpp:21
        AddScore; used by DART drop/normalize and RF averaging)."""
        if train:
            ids = self._tree_leaf_ids(tree, self.train_data)
            # fixed-size leaf_vals so _score_update_fn compiles once
            L = max(self.config.num_leaves, 2)
            vals = np.zeros(L, np.float32)
            vals[:tree.num_leaves] = tree.leaf_value[:tree.num_leaves]
            self.scores = self._score_update_fn(
                self.scores, class_id, jnp.asarray(vals),
                jnp.asarray(_pad_rows(ids, self.n_pad)), self.pad_mask)
        if valid:
            for vi, vds in enumerate(self.valid_sets):
                if tree.is_linear:
                    vX = self._raw_or_reconstruct(vds)
                    self.valid_scores[vi][class_id] += tree.predict(
                        np.asarray(vX, np.float64))
                else:
                    vids = self._tree_leaf_ids(tree, vds)
                    self.valid_scores[vi][class_id] += tree.leaf_value[vids]

    # ------------------------------------------------------------------- eval
    def eval_train(self):
        if (isinstance(self.scores, jax.Array)
                and not self.scores.is_fully_addressable):
            return self._eval_train_sharded()
        de = self._device_eval
        if de is None:
            from ..ops.metrics import DeviceEval
            de = self._device_eval = DeviceEval(self)
        if de.ok:
            if not de._plans:
                return []
            out, grads_ok, scores_ok = de.run(self.scores,
                                              getattr(self, "_grad_ok",
                                                      None))
            # the sentinel flags rode the packed fetch: cache them so
            # this tick's _check_finite costs no second sync
            self._finite_cache = (grads_ok, scores_ok)
            return out
        score = np.asarray(self.scores)[:, :self.num_data].astype(np.float64)
        return self._eval(score, self.train_metrics, self.train_data)

    def _eval_train_sharded(self):
        """Train-set metrics under multi-process SPMD: the scores span
        non-addressable devices, so each metric is computed as
        shard-local partial sums that GSPMD all-reduces over the mesh —
        every rank reads identical replicated scalars (the TPU analogue
        of the reference workers' synchronized Eval in gbdt.cpp
        EvalAndCheckEarlyStopping).  AUC uses a global score-bin
        histogram (metric.py device_binned_auc)."""
        from ..metric import device_binned_auc, device_pointwise_loss
        if getattr(self, "_sharded_eval_fn", None) is None:
            obj = self.objective
            plans = []      # (metric_name, kind, loss_fn)
            for m in self.train_metrics:
                base = m.name
                if self.num_tree_per_iteration > 1:
                    # multiclass: per-row class probabilities from the
                    # [K, n] scores, reduced the same sharded way
                    if base in ("multi_logloss", "multi_error"):
                        plans.append((base, base, None))
                    elif base == "auc_mu":
                        # pairwise-projection binned AUCs (metric.py
                        # device_auc_mu); the weight matrix is static
                        plans.append((base, "auc_mu",
                                      np.asarray(m.class_weights)))
                    else:
                        log.warning(f"train metric {base} has no sharded "
                                    "device form; skipped under "
                                    "multi-process SPMD")
                    continue
                if base == "auc":
                    plans.append((base, "auc", None))
                    continue
                if base == "average_precision":
                    plans.append((base, "average_precision", None))
                    continue
                if base == "ndcg":
                    from ..metric import ndcg_device_plan
                    bks, efn = ndcg_device_plan(
                        m, self.n_pad,
                        shared_buckets=getattr(obj, "_dev_buckets", None))
                    self._ndcg_buckets = bks
                    plans.append((base, "ndcg", (efn, list(m.eval_at))))
                    continue
                if base == "map":
                    from ..metric import map_device_plan
                    bks, efn = map_device_plan(
                        m, self.n_pad,
                        shared_buckets=getattr(obj, "_dev_buckets", None))
                    self._map_buckets = bks
                    plans.append((base, "map", (efn, list(m.eval_at))))
                    continue
                fn = device_pointwise_loss(base, self.config)
                if fn is None:
                    log.warning(f"train metric {base} has no sharded "
                                "device form; skipped under "
                                "multi-process SPMD")
                    continue
                sqrt_after = base == "rmse"
                plans.append((base, "sqrt" if sqrt_after else "avg", fn))
            self._sharded_eval_plans = plans
            # metrics compare in ORIGINAL label space (the host path uses
            # metadata.label): label_dev may be objective-transformed
            # (reg_sqrt) or absent entirely (custom fobj), so build a
            # dedicated sharded copy from the metadata
            md = self.train_data.metadata
            self._eval_label_dev = self._put_by_row(
                _pad_rows(np.asarray(md.label, np.float32), self.n_pad))
            self._eval_weight_dev = (
                None if md.weight is None else self._put_by_row(
                    _pad_rows(np.asarray(md.weight, np.float32),
                              self.n_pad)))

            def _fn(scores, label, weight, pad_mask, ndcg_buckets,
                    map_buckets):
                from ..metric import (device_auc_mu,
                                      device_binned_average_precision)
                w = pad_mask if weight is None else weight * pad_mask
                den = jnp.sum(w)
                outs = []
                if self.num_tree_per_iteration > 1:
                    # [K, n] -> per-class probabilities (softmax for
                    # multiclass; ova objectives convert per class)
                    prob = (obj.convert_output(scores) if obj is not None
                            and not getattr(obj, "run_on_host", False)
                            else scores)
                    K = prob.shape[0]
                    lab_oh = (label[None, :]
                              == jnp.arange(K, dtype=prob.dtype)[:, None])
                    p_lab = jnp.sum(jnp.where(lab_oh, prob, 0.0), axis=0)
                    for _, kind, extra in plans:
                        if kind == "multi_logloss":
                            pt = -jnp.log(jnp.clip(p_lab, 1e-15, 1.0))
                        elif kind == "auc_mu":
                            # pairwise projections are of RAW scores
                            # (multiclass_metric.hpp:255 uses score)
                            outs.append(device_auc_mu(
                                scores, label, w, extra))
                            continue
                        else:   # multi_error: true-class prob not in
                            # top_k; ties count AGAINST the row (ref:
                            # multiclass_metric.hpp:142 LossOnPoint
                            # counts >= incl. self, error when > top_k)
                            num_ge = jnp.sum(prob >= p_lab[None, :],
                                             axis=0)
                            pt = (num_ge > self.config.multi_error_top_k
                                  ).astype(jnp.float32)
                        outs.append(jnp.sum(pt * w) / den)
                    return tuple(outs)
                sc = scores[0]
                conv = (obj.convert_output(sc) if obj is not None
                        and not getattr(obj, "run_on_host", False) else sc)
                for _, kind, fn in plans:
                    if kind == "auc":
                        outs.append(device_binned_auc(conv, label, w))
                    elif kind == "average_precision":
                        outs.append(device_binned_average_precision(
                            conv, label, w))
                    elif kind == "ndcg":
                        # per-query partials from the raw scores (ndcg is
                        # rank-based; conversion is monotone) — one value
                        # per eval_at k
                        outs.append(fn[0](sc, ndcg_buckets))
                    elif kind == "map":
                        outs.append(fn[0](sc, map_buckets))
                    else:
                        v = jnp.sum(fn(conv, label) * w) / den
                        outs.append(jnp.sqrt(v) if kind == "sqrt" else v)
                return tuple(outs)

            # tpulint: disable-next=donate-argnums -- eval reads the live sharded score buffer; training keeps updating it
            self._sharded_eval_fn = jax.jit(_fn)
        vals = self._sharded_eval_fn(self.scores, self._eval_label_dev,
                                     self._eval_weight_dev, self.pad_mask,
                                     getattr(self, "_ndcg_buckets", []),
                                     getattr(self, "_map_buckets", []))
        out = []
        for (name, kind, extra), v in zip(self._sharded_eval_plans, vals):
            if kind in ("ndcg", "map"):
                out.extend((f"{name}@{k}", float(v[ki]))
                           for ki, k in enumerate(extra[1]))
            else:
                out.append((name, float(v)))
        return out

    def eval_valid(self, idx: int):
        return self._eval(self.valid_scores[idx], self.valid_metrics[idx],
                          self.valid_sets[idx])

    def _eval(self, score, metrics, dataset):
        out = []
        sc = score[0] if score.shape[0] == 1 else score
        for m in metrics:
            out.extend(m.eval(sc, self.objective))
        return out

    # ---------------------------------------------------------------- predict
    def _bump_model_mutations(self) -> None:
        """Invalidate the packed/device predictor caches after an IN-PLACE
        tree mutation that `len(models_)` cannot see — DART drop/
        normalize re-weighting, refit, set_leaf_output.  Serving a model
        mid-mutation must repack, never reuse stale leaf values."""
        self._model_mutations = getattr(self, "_model_mutations", 0) + 1

    def _packed_for(self, start_iteration: int, end: int, K: int):
        """Cached native PackedPredictor for a model slice, invalidated by
        growth (len) and in-place mutation (_model_mutations)."""
        from ..native import PackedPredictor, predictor_lib
        if predictor_lib() is None:
            return None
        key = (start_iteration, end, len(self.models_),
               getattr(self, "_model_mutations", 0))
        cached = getattr(self, "_packed_pred", None)
        if cached is None or cached[0] != key:
            cached = (key, PackedPredictor(
                self.models_[start_iteration * K:end * K]))
            self._packed_pred = cached
        packed = cached[1]
        return packed if packed.ok else None

    def make_single_row_fast(self, num_features: int,
                             start_iteration: int = 0,
                             num_iteration: int = -1,
                             raw_score: bool = False):
        """Cached single-row fast predictor (ref: c_api.h:1350
        LGBM_BoosterPredictForMatSingleRowFastInit): parse/pack once,
        reuse buffers per call.  None when the native predictor is
        unavailable (linear trees / no compiler)."""
        from ..native import SingleRowFastPredictor
        self._sync_model()
        K = self.num_tree_per_iteration
        total_iters = len(self.models_) // K
        if num_iteration is None or num_iteration < 0:
            num_iteration = total_iters - start_iteration
        end = min(start_iteration + num_iteration, total_iters)
        packed = self._packed_for(start_iteration, end, K)
        if packed is None:
            return None
        conv = None
        if not raw_score and self.objective is not None:
            conv = getattr(self.objective, "convert_output_host", None)
        sp = SingleRowFastPredictor(packed, num_features, K,
                                    self.average_output_, convert=conv)
        return sp if sp.ok else None

    def _host_fallback(self, reason: str):
        """One host-fallback decision of the device-predict router,
        named by its docs/Inference.md fallback-matrix KEY —
        tools/check_fallback_docs.py syncs the matrix against these
        call sites in both directions, so a new quiet host fallback
        cannot ship undocumented.  Returns None for the caller."""
        log.debug(f"device_predict: host fallback ({reason})")
        return None

    def _device_predictor(self, X, start_iteration: int, num_iteration: int,
                          pred_early_stop: bool = False):
        """Route decision for the TPU-resident inference path
        (docs/Inference.md fallback matrix).  Returns (DevicePredictor,
        float32 matrix) ready to serve, or None when the host paths
        must: float64 data that is NOT losslessly f32-representable
        (the bit-exact routing argument needs float32 inputs; lossless
        float64 — integral features, f32-round-tripped pipelines — is
        downcast and served, the ROADMAP'd Serving follow-up),
        linear-tree models, empty slices, or device_predict=false /
        auto without a TPU backend.  Prediction early stopping serves on
        device too (traverse.py class_scores_early_stop masked scan);
        the `pred_early_stop` argument is kept for callers that gate es
        activation themselves."""
        cfg = self.config
        mode = getattr(cfg, "device_predict", "false") if cfg else "false"
        if mode == "false":
            return None
        arr = X if isinstance(X, np.ndarray) else np.asarray(X)
        if arr.dtype == np.float32:
            X32 = arr
        elif arr.dtype == np.float64:
            # cheap host check: one downcast + one compare pass.  Equal
            # after the round trip (NaN kept as missing) means the f32
            # traversal routes bit-identically to the float64 host path.
            X32 = arr.astype(np.float32)
            if not bool(np.all((X32 == arr) | np.isnan(arr))):
                return self._host_fallback("float64-lossy")
        else:
            return self._host_fallback("non-float-input")
        if mode == "auto" and jax.default_backend() != "tpu":
            return None
        if jax.process_count() > 1:
            # predict is a host API; a packed model placed on this
            # process's devices cannot address remote shards, and the
            # peers are not running the same dispatch
            return self._host_fallback("multi-process")
        self._sync_model()
        K = self.num_tree_per_iteration
        total_iters = len(self.models_) // max(K, 1)
        if num_iteration is None or num_iteration < 0:
            num_iteration = total_iters - start_iteration
        end = min(start_iteration + num_iteration, total_iters)
        if end <= start_iteration:
            return self._host_fallback("empty-slice")
        dp = self._device_pred_for(start_iteration, end, K)
        # dp.ok is False exactly when the slice cannot pack — linear
        # trees (inference/pack.py) being the one reachable case here
        return (dp, X32) if dp.ok else self._host_fallback("linear-tree")

    def _device_pred_for(self, start_iteration: int, end: int, K: int):
        """Cached DevicePredictor per model slice, invalidated by growth
        (len) and in-place mutation, mirroring _packed_for."""
        from ..inference import DevicePredictor
        key = (start_iteration, end, len(self.models_),
               getattr(self, "_model_mutations", 0))
        cached = getattr(self, "_device_pred", None)
        if cached is None or cached[0] != key:
            obj = self.objective
            conv = obj.convert_output if obj is not None else None
            mesh = None
            if (getattr(self, "mesh", None) is not None
                    and getattr(self, "_mesh_axis", 1) == 1
                    and jax.process_count() == 1):
                # offline scoring shards rows over the training mesh; the
                # model replicates (each chip holds the whole ensemble)
                mesh = self.mesh
            cached = (key, DevicePredictor(
                self.models_[start_iteration * K:end * K], num_class=K,
                average=self.average_output_, convert=conv,
                min_bucket=getattr(self.config, "device_predict_min_bucket",
                                   4096),
                mesh=mesh))
            self._device_pred = cached
        return cached[1]

    def _device_predict_run(self, dp, X, mode: str,
                            early_stop=None) -> np.ndarray:
        """One device predict dispatch + telemetry (timer scope and a
        structured `predict` event when an EventLogger is active).
        `early_stop=(freq, margin)` routes through the device masked
        accumulation scan (parity with the host early-stop path)."""
        from ..observability import emit_event
        with global_timer.scope("GBDT::predict_device"):
            if mode == "leaf":
                out = dp.predict_leaf(X)
            elif mode == "raw":
                out = dp.predict_raw(X, early_stop=early_stop)
            else:
                out = dp.predict(X, early_stop=early_stop)
        n = out.shape[0]
        emit_event("predict", path="device", mode=mode, rows=int(n),
                   trees=dp.pack.num_trees, bucket=dp.bucket_rows(n),
                   early_stop=early_stop is not None)
        return out

    def _es_tuple(self, pred_early_stop, freq, margin):
        """(freq, margin) when prediction early stopping engages — same
        gate as the host path's use_es (off under output averaging,
        ref: gbdt_prediction.cpp)."""
        if pred_early_stop and not self.average_output_ and freq > 0:
            return (int(freq), float(margin))
        return None

    def predict_raw(self, X: np.ndarray, start_iteration: int = 0,
                    num_iteration: int = -1, pred_early_stop: bool = False,
                    pred_early_stop_freq: int = 10,
                    pred_early_stop_margin: float = 10.0) -> np.ndarray:
        """Raw scores [n] or [n, K] (ref: gbdt_prediction.cpp PredictRaw;
        early stopping per prediction_early_stop.cpp: rows whose margin
        exceeds the threshold every round_period iterations keep their
        partial sum — binary margin = 2|score|, multiclass = top1-top2)."""
        hit = self._device_predictor(X, start_iteration, num_iteration,
                                     pred_early_stop)
        if hit is not None:
            es = self._es_tuple(pred_early_stop, pred_early_stop_freq,
                                pred_early_stop_margin)
            return self._device_predict_run(hit[0], hit[1], "raw", es)
        with global_timer.scope("GBDT::predict"):
            return self._predict_raw_impl(
                X, start_iteration, num_iteration, pred_early_stop,
                pred_early_stop_freq, pred_early_stop_margin)

    def _predict_raw_impl(self, X, start_iteration, num_iteration,
                          pred_early_stop, pred_early_stop_freq,
                          pred_early_stop_margin) -> np.ndarray:
        self._sync_model()
        X = np.asarray(X, dtype=np.float64)
        n = X.shape[0]
        K = self.num_tree_per_iteration
        total_iters = len(self.models_) // K
        if num_iteration < 0:
            num_iteration = total_iters - start_iteration
        end = min(start_iteration + num_iteration, total_iters)
        use_es = pred_early_stop and not self.average_output_
        if not use_es and end > start_iteration:
            # native batch predictor (OpenMP over rows; ref:
            # src/application/predictor.hpp) — Python path on fallback.
            # The flattened pack is cached per model slice and invalidated
            # by growth/mutation (set_leaf_output etc. bump the counter).
            packed = self._packed_for(start_iteration, end, K)
            if packed is not None:
                res = packed.predict(X, K, self.average_output_)
                if res is not None:
                    return res[:, 0] if K == 1 else res
        out = np.zeros((K, n))
        active_idx = np.arange(n) if use_es else None
        Xa = X
        for i, it in enumerate(range(start_iteration, end)):
            if use_es and i > 0 and i % pred_early_stop_freq == 0:
                sub = out[:, active_idx]
                if K == 1:
                    margin = 2.0 * np.abs(sub[0])
                else:
                    top2 = np.partition(sub, K - 2, axis=0)[K - 2:]
                    margin = top2[1] - top2[0]
                keep = margin <= pred_early_stop_margin
                active_idx = active_idx[keep]
                if len(active_idx) == 0:
                    break
                # the point of early stopping is SKIPPING work: later
                # trees only traverse the still-active rows
                Xa = X[active_idx]
            for k in range(K):
                pred = self.models_[it * K + k].predict(Xa)
                if use_es:
                    out[k][active_idx] += pred
                else:
                    out[k] += pred
        if self.average_output_ and end > start_iteration:
            out /= end - start_iteration  # ref: gbdt_prediction.cpp:57
        return out[0] if K == 1 else out.T

    def predict(self, X: np.ndarray, raw_score: bool = False,
                start_iteration: int = 0, num_iteration: int = -1,
                pred_leaf: bool = False, **pred_kwargs) -> np.ndarray:
        if pred_leaf:
            return self.predict_leaf_index(X, start_iteration, num_iteration)
        if not raw_score and self.objective is not None:
            hit = self._device_predictor(
                X, start_iteration, num_iteration,
                pred_kwargs.get("pred_early_stop", False))
            if hit is not None:
                es = self._es_tuple(
                    pred_kwargs.get("pred_early_stop", False),
                    pred_kwargs.get("pred_early_stop_freq", 10),
                    pred_kwargs.get("pred_early_stop_margin", 10.0))
                # convert_output fused into the device program
                return self._device_predict_run(hit[0], hit[1], "convert",
                                                es)
        raw = self.predict_raw(X, start_iteration, num_iteration,
                               **pred_kwargs)
        if raw_score or self.objective is None:
            return raw
        # host path: the scores are already NumPy — use the objective's
        # host converter instead of a host->device->host round trip
        conv = self.objective.convert_output_host
        if raw.ndim == 2:
            return np.asarray(conv(raw.T)).T
        return np.asarray(conv(raw))

    def _calculate_linear(self, tree: Tree, leaf_id: np.ndarray,
                          grad: np.ndarray, hess: np.ndarray) -> None:
        """Fit linear leaf models by weighted ridge normal equations
        (ref: linear_tree_learner.cpp:184 CalculateLinear, Eq 3 of
        arXiv:1802.05640: coeffs = -(X'HX + lambda)^-1 X'g over the leaf's
        numerical branch features plus a constant column; rows with NaN in
        any branch feature are excluded; degenerate leaves keep
        leaf_value as the constant)."""
        from ..io.binning import BIN_NUMERICAL
        cfg = self.config
        ds = self.train_data
        raw = self._raw_or_reconstruct(ds)
        tree.is_linear = True
        nl = tree.num_leaves
        # branch features per leaf: climb the parent chain
        for leaf in range(nl):
            feats = []
            node = tree.leaf_parent[leaf]
            while node >= 0:
                feats.append(int(tree.split_feature[node]))
                # find this node's parent: scan child pointers
                parents = np.nonzero(
                    (tree.left_child[:nl - 1] == node)
                    | (tree.right_child[:nl - 1] == node))[0]
                node = int(parents[0]) if len(parents) else -1
            feats = sorted(set(
                f for f in feats
                if ds.bin_mappers[f].bin_type == BIN_NUMERICAL))
            rows = np.nonzero((leaf_id == leaf) & (hess > 0))[0]
            k = len(feats)
            if len(rows) == 0:
                tree.leaf_const[leaf] = tree.leaf_value[leaf]
                tree.leaf_features[leaf] = []
                tree.leaf_features_inner[leaf] = []
                tree.leaf_coeff[leaf] = []
                continue
            Xl = raw[np.ix_(rows, feats)] if k else np.zeros((len(rows), 0))
            ok = ~np.isnan(Xl).any(axis=1)
            if ok.sum() < k + 1:
                tree.leaf_const[leaf] = tree.leaf_value[leaf]
                tree.leaf_features[leaf] = []
                tree.leaf_features_inner[leaf] = []
                tree.leaf_coeff[leaf] = []
                continue
            Xd = np.column_stack([Xl[ok], np.ones(int(ok.sum()))])
            g = grad[rows][ok]
            h = hess[rows][ok]
            XTHX = Xd.T @ (Xd * h[:, None])
            XTHX[np.arange(k), np.arange(k)] += cfg.linear_lambda
            XTg = Xd.T @ g
            try:
                coeffs = -np.linalg.solve(XTHX, XTg)
            except np.linalg.LinAlgError:
                coeffs = -np.linalg.pinv(XTHX) @ XTg
            keep = [i for i in range(k)
                    if abs(coeffs[i]) > 1e-35]     # kZeroThreshold filter
            tree.leaf_features[leaf] = [feats[i] for i in keep]
            tree.leaf_features_inner[leaf] = [
                ds.inner_feature_index(feats[i]) for i in keep]
            tree.leaf_coeff[leaf] = [float(coeffs[i]) for i in keep]
            tree.leaf_const[leaf] = float(coeffs[k])

    def refit(self, X: np.ndarray, label: np.ndarray,
              weight: Optional[np.ndarray] = None) -> None:
        """Refit the existing tree structures' leaf values to new data
        (ref: gbdt.cpp:252 RefitTree; serial_tree_learner.cpp:241
        FitByExistingTree: new_leaf = decay*old + (1-decay)*output*shrink)."""
        self._sync_model()
        import jax.numpy as jnp_
        from ..io.dataset import Metadata
        from ..objective import create_objective
        X = np.asarray(X, np.float64)
        n = X.shape[0]
        K = self.num_tree_per_iteration
        leaf_preds = self.predict_leaf_index(X)        # [n, num_trees]
        md = Metadata(n)
        md.set_label(np.asarray(label, np.float64))
        if weight is not None:
            md.set_weight(weight)
        obj = self.objective or create_objective(self.config)
        obj.init(md, n)
        lab = jnp_.asarray(np.asarray(obj.label, np.float32))
        w = (None if md.weight is None
             else jnp_.asarray(np.asarray(md.weight, np.float32)))
        score = np.zeros((K, n), np.float64)
        try:
            self._refit_trees(obj, lab, w, score, leaf_preds)
        finally:
            # the in-place leaf mutations invalidate the packed-predictor
            # cache; bump AFTER them (not before predict_leaf_index above,
            # which would repopulate the cache under the new key) and even
            # when a later iteration raises mid-mutation
            self._model_mutations = getattr(self, "_model_mutations", 0) + 1

    def _refit_trees(self, obj, lab, w, score, leaf_preds):
        import jax.numpy as jnp_
        cfg = self.config
        K = self.num_tree_per_iteration
        num_iters = len(self.models_) // K
        decay = cfg.refit_decay_rate
        l1, l2 = cfg.lambda_l1, cfg.lambda_l2
        n = score.shape[1]
        for it in range(num_iters):
            sc = jnp_.asarray(score.astype(np.float32))
            g, h = obj.get_gradients(sc if K > 1 else sc[0], lab, w)
            g = np.asarray(g).reshape(K, n)
            h = np.asarray(h).reshape(K, n)
            for k in range(K):
                m = it * K + k
                tree = self.models_[m]
                nl = tree.num_leaves
                lp = np.clip(leaf_preds[:, m], 0, nl - 1)
                sg = np.bincount(lp, weights=g[k], minlength=nl)[:nl]
                sh = np.bincount(lp, weights=h[k], minlength=nl)[:nl] + K_EPSILON
                sg_l1 = np.sign(sg) * np.maximum(np.abs(sg) - l1, 0.0)
                out = -sg_l1 / (sh + l2)
                if cfg.max_delta_step > 0:
                    out = np.clip(out, -cfg.max_delta_step,
                                  cfg.max_delta_step)
                new = (decay * tree.leaf_value[:nl]
                       + (1.0 - decay) * out * tree.shrinkage)
                tree.leaf_value[:nl] = new
                tree.leaf_count[:nl] = np.bincount(lp, minlength=nl)[:nl]
                score[k] += new[lp]

    def predict_contrib(self, X: np.ndarray, start_iteration: int = 0,
                        num_iteration: int = -1) -> np.ndarray:
        """SHAP feature contributions [n, K*(F+1)]: per class, F per-feature
        columns plus the expected value, summing to the raw score
        (ref: gbdt.h:314 PredictContrib; tree.h:139; TreeSHAP in
        src/io/tree.cpp)."""
        from ..native import tree_shap
        # the recursive path-weight algorithm has no device form yet
        # (ROADMAP "kill the host-fallback matrix")
        self._host_fallback("pred-contrib")
        self._sync_model()
        X = np.asarray(X, dtype=np.float64)
        if X.ndim == 1:
            X = X[None, :]
        n = X.shape[0]
        K = self.num_tree_per_iteration
        F = self.train_data.num_total_features
        total_iters = len(self.models_) // K
        if num_iteration < 0:
            num_iteration = total_iters - start_iteration
        end = min(start_iteration + num_iteration, total_iters)
        phi = np.zeros((K, n, F + 1))
        for it in range(start_iteration, end):
            for k in range(K):
                tree_shap(self.models_[it * K + k], X, phi[k])
        if self.average_output_ and end > start_iteration:
            phi /= end - start_iteration
        if K == 1:
            return phi[0]
        return phi.transpose(1, 0, 2).reshape(n, K * (F + 1))

    def predict_leaf_index(self, X: np.ndarray, start_iteration: int = 0,
                           num_iteration: int = -1) -> np.ndarray:
        hit = self._device_predictor(X, start_iteration, num_iteration)
        if hit is not None:
            return self._device_predict_run(hit[0], hit[1], "leaf")
        self._sync_model()
        X = np.asarray(X, dtype=np.float64)
        K = self.num_tree_per_iteration
        total_iters = len(self.models_) // K
        if num_iteration < 0:
            num_iteration = total_iters - start_iteration
        end = min(start_iteration + num_iteration, total_iters)
        if end > start_iteration:
            # same native traversal as predict, returning leaf ids;
            # shares predict_raw's packed-model cache
            packed = self._packed_for(start_iteration, end, K)
            if packed is not None:
                res = packed.predict_leaf(X)
                if res is not None:
                    return res
        cols = []
        for it in range(start_iteration, end):
            for k in range(K):
                cols.append(self.models_[it * K + k].get_leaf_index(X))
        return np.stack(cols, axis=1) if cols else np.zeros((X.shape[0], 0), np.int32)

    @property
    def num_trees(self) -> int:
        return len(self.models_)

    def current_iteration(self) -> int:
        return len(self.models_) // max(self.num_tree_per_iteration, 1)

    def rollback_one_iter(self) -> None:
        """ref: gbdt.cpp:443 RollbackOneIter (model-side only; scores are
        rebuilt lazily on next use)."""
        self._sync_model()
        K = self.num_tree_per_iteration
        if len(self.models_) >= K:
            del self.models_[-K:]
            self.iter_ -= 1

    # --------------------------------------------------------------- model IO
    def feature_importance(self, importance_type: str = "split") -> np.ndarray:
        self._sync_model()
        F = self.train_data.num_total_features if self.train_data else (
            max(int(t.split_feature[:t.num_leaves - 1].max(initial=0))
                for t in self.models_) + 1 if self.models_ else 0)
        out = np.zeros(F)
        for t in self.models_:
            if importance_type == "split":
                out += t.feature_importance_split(F)
            else:
                out += t.feature_importance_gain(F)
        return out
