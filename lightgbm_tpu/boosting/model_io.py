"""Model text (de)serialization, line-compatible with the reference's v4 format
(ref: src/boosting/gbdt_model_text.cpp SaveModelToString/LoadModelFromString).

The text model is also the checkpoint format (ref: SURVEY.md §5 checkpoint/resume:
snapshot_freq writes model.snapshot_iter_N; resume = load + continue training).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..models.tree import Tree
from ..utils import log


def objective_to_string(objective, config) -> str:
    """ref: each objective's ToString()."""
    if objective is None:
        if config is not None and config.objective not in ("custom", ""):
            return config.objective
        return "custom"
    name = objective.name
    if name == "binary":
        return f"binary sigmoid:{objective.sigmoid:g}"
    if name in ("multiclass", "multiclassova"):
        s = f"{name} num_class:{objective.num_class}"
        if name == "multiclassova":
            s += f" sigmoid:{objective.binary[0].sigmoid:g}"
        return s
    if name == "quantile":
        return f"quantile alpha:{objective.alpha:g}"
    if name == "huber":
        return f"huber alpha:{objective.alpha:g}"
    if name == "fair":
        return f"fair c:{objective.c:g}"
    if name == "tweedie":
        return f"tweedie tweedie_variance_power:{objective.rho:g}"
    if name == "lambdarank":
        return "lambdarank"
    if name == "rank_xendcg":
        return "rank_xendcg"
    return name


def save_model_to_string(booster, num_iteration: int = -1,
                         start_iteration: int = 0,
                         importance_type: str = "split") -> str:
    """ref: gbdt_model_text.cpp GBDT::SaveModelToString."""
    if hasattr(booster, "_sync_model"):
        booster._sync_model()
    ds = booster.train_data
    K = booster.num_tree_per_iteration
    cfg = booster.config
    total_iters = len(booster.models_) // max(K, 1)
    if num_iteration < 0:
        num_iteration = total_iters - start_iteration
    end = min(start_iteration + num_iteration, total_iters)

    if ds is not None:
        max_feature_idx = ds.num_total_features - 1
        feature_names = ds.feature_names
        feature_infos = ds.feature_infos()
    else:
        max_feature_idx = booster._loaded_max_feature_idx
        feature_names = booster._loaded_feature_names
        feature_infos = booster._loaded_feature_infos

    lines = [
        "tree",
        "version=v4",
        f"num_class={cfg.num_class if cfg else K}",
        f"num_tree_per_iteration={K}",
        "label_index=0",
        f"max_feature_idx={max_feature_idx}",
        f"objective={objective_to_string(booster.objective, cfg)}",
        "feature_names=" + " ".join(feature_names),
        "feature_infos=" + " ".join(feature_infos),
    ]
    if getattr(booster, "average_output_", False):
        lines.append("average_output")  # ref: gbdt_model_text.cpp:330-331
    tree_blocks = []
    for it in range(start_iteration, end):
        for k in range(K):
            idx = it * K + k
            tree_blocks.append(booster.models_[idx].to_string(len(tree_blocks)))
    # each block is "Tree=N\n...\n\n"; tree_sizes are the exact byte lengths of
    # the blocks as written, concatenated with no separator, so the reference
    # loader can seek by cumulative offsets (ref: gbdt_model_text.cpp:355-372)
    lines.append("tree_sizes=" + " ".join(str(len(b)) for b in tree_blocks))
    lines.append("")
    out = "\n".join(lines) + "\n"
    out += "".join(tree_blocks)
    out += "end of trees\n"

    imp = booster.feature_importance(importance_type)
    order = np.argsort(-imp, kind="stable")
    out += "\nfeature_importances:\n"
    for f in order:
        if imp[f] > 0 and f < len(feature_names):
            out += f"{feature_names[f]}={imp[f]:g}\n"
    out += "\nparameters:\n"
    if cfg is not None:
        for key, val in sorted(cfg.changed_params().items()):
            if isinstance(val, list):
                val = ",".join(str(v) for v in val)
            out += f"[{key}: {val}]\n"
    out += "end of parameters\n"
    out += "\npandas_categorical:null\n"
    return out


def load_model_from_string(text: str):
    """ref: gbdt_model_text.cpp GBDT::LoadModelFromString.  Returns a GBDT in
    predictor mode (no train data)."""
    from ..config import Config
    from ..objective import create_objective
    from .gbdt import GBDT

    booster = GBDT()
    head, _, rest = text.partition("\nTree=")
    kv: Dict[str, str] = {}
    for line in head.splitlines():
        if "=" in line:
            k, v = line.split("=", 1)
            kv[k.strip()] = v.strip()
        elif line.strip() == "average_output":
            booster.average_output_ = True  # ref: gbdt_model_text.cpp:487
    # the reference Log::Fatal's on unrecognized text ("Model format
    # error"); a submodel header ("tree") must open the file
    if not text.lstrip().startswith("tree"):
        log.fatal("Unknown model format or submodel type in model file")
    if "version" not in kv:
        log.warning("Unknown model format version")
    if not rest.strip() and "end of trees" not in text:
        # zero-tree saves are valid (they carry the end-of-trees marker);
        # header-only junk is not (ref: gbdt_model_text.cpp Log::Fatal)
        log.fatal("Model file doesn't contain any trees "
                  "(ref: gbdt_model_text.cpp 'Model format error')")
    num_class = int(kv.get("num_class", "1"))
    K = int(kv.get("num_tree_per_iteration", str(num_class)))
    booster.num_class = num_class
    booster.num_tree_per_iteration = K
    booster._loaded_max_feature_idx = int(kv.get("max_feature_idx", "0"))
    booster._loaded_feature_names = kv.get("feature_names", "").split()
    booster._loaded_feature_infos = kv.get("feature_infos", "").split()

    obj_str = kv.get("objective", "custom")
    obj_tokens = obj_str.split()
    params = {"objective": obj_tokens[0], "num_class": num_class, "verbosity": -1}
    for tok in obj_tokens[1:]:
        if ":" in tok:
            k, v = tok.split(":", 1)
            params[{"num_class": "num_class", "sigmoid": "sigmoid",
                    "alpha": "alpha", "c": "fair_c",
                    "tweedie_variance_power": "tweedie_variance_power"}
                   .get(k, k)] = v
    prev_verbosity = log.get_verbosity()
    cfg = Config(params)
    # the predictor-mode Config is built quiet (verbosity -1 above), but
    # Config._post_process sets the PROCESS-WIDE log level as a side
    # effect — restore it, or loading any model silences the host (the
    # serving daemon loads models mid-flight and must keep its logs)
    log.set_verbosity(prev_verbosity)
    booster.config = cfg
    try:
        obj = create_objective(cfg)
        if obj is not None and obj_tokens[0] not in ("lambdarank", "rank_xendcg"):
            # predictor-mode init with a dummy label so convert_output works
            class _MD:
                label = np.zeros(1, np.float32)
                weight = None
                init_score = None
                query_boundaries = None
            if obj_tokens[0] not in ("multiclass", "multiclassova"):
                obj.init(_MD(), 1)
        booster.objective = obj
    except Exception:  # custom/unknown objective: raw-score predictor
        booster.objective = None

    # tree blocks
    if rest:
        body = "Tree=" + rest
        end_pos = body.find("end of trees")
        body = body[:end_pos] if end_pos >= 0 else body
        blocks = body.split("\nTree=")
        for i, blk in enumerate(blocks):
            blk = blk.strip()
            if not blk:
                continue
            if not blk.startswith("Tree="):
                blk = "Tree=" + blk
            booster.models_.append(Tree.from_string(blk))
    booster.iter_ = len(booster.models_) // max(K, 1)
    return booster


def save_model_to_file(booster, filename: str, num_iteration: int = -1,
                       start_iteration: int = 0,
                       importance_type: str = "split") -> None:
    # atomic: temp sibling + os.replace, so a crash mid-save never leaves
    # a truncated model on disk (the reference writes model files whole)
    from ..utils import atomic_write_text
    atomic_write_text(filename,
                      save_model_to_string(booster, num_iteration,
                                           start_iteration, importance_type))


def load_model_from_file(filename: str):
    with open(filename) as f:
        return load_model_from_string(f.read())
