"""Random-forest mode: bagged trees, no shrinkage, averaged outputs
(ref: src/boosting/rf.hpp:25 RF).

Gradients are computed ONCE from the constant init score (no boosting);
the training score is maintained as the running average of tree predictions
via the multiply/add/multiply pattern of rf.hpp TrainOneIter.
"""

from __future__ import annotations

from typing import List

import numpy as np
import jax.numpy as jnp

from ..models.tree import Tree
from ..utils import log
from .gbdt import GBDT, K_EPSILON


class RF(GBDT):
    """ref: rf.hpp:25."""

    average_output_ = True

    def init(self, config, train_data, objective, metrics) -> None:
        if config.data_sample_strategy == "goss":
            # GOSS reweights gradients per iteration; RF reuses ONE
            # gradient map for every tree (rf.hpp:95 Boosting computes
            # once) — the combination is meaningless, and the goss
            # sampler donates its inputs, which would consume the
            # persistent RF gradient buffers
            log.fatal("RF mode does not support data_sample_strategy=goss")
        if config.data_sample_strategy == "bagging":
            ok = ((config.bagging_freq > 0
                   and 0.0 < config.bagging_fraction < 1.0)
                  or 0.0 < config.feature_fraction < 1.0)
            if not ok:
                log.fatal("RF mode requires bagging "
                          "(bagging_freq > 0 and bagging_fraction in (0, 1)) "
                          "or feature_fraction in (0, 1)")
        if objective is None:
            log.fatal("RF mode does not support custom objective functions")
        super().init(config, train_data, objective, metrics)
        if self.has_init_score:
            log.fatal("RF mode does not support init_score")
        self.shrinkage_rate = 1.0
        self._rf_boosting()

    def _rf_boosting(self) -> None:
        """Gradients from the constant init score, computed once
        (ref: rf.hpp:95 Boosting)."""
        cfg, obj = self.config, self.objective
        K = self.num_tree_per_iteration
        self._rf_init_scores: List[float] = [0.0] * K
        if cfg.boost_from_average and self.train_data.num_features > 0:
            for k in range(K):
                self._rf_init_scores[k] = obj.boost_from_score(k)
        saved = self.scores
        self.scores = jnp.broadcast_to(
            jnp.asarray(self._rf_init_scores, jnp.float32)[:, None],
            (K, self.n_pad)).astype(jnp.float32) * 1.0
        self._rf_grad, self._rf_hess = self._compute_gradients()
        self.scores = saved

    # NOTE on rf.hpp:44-47's MultiplyScore(1/num_init): our continue_from
    # seeds with prev.predict_raw(), which already averages when the init
    # model is an RF (average_output_), so the seeded scores are correct
    # as-is and no extra division happens here.

    def _rf_multiply_score(self, class_id: int, val: float) -> None:
        """ref: rf.hpp:210 MultiplyScore (train + valid updaters)."""
        self.scores = self.scores.at[class_id].multiply(val)
        for sc in self.valid_scores:
            sc[class_id] *= val

    def train_one_iter(self, gradients=None, hessians=None) -> bool:
        """ref: rf.hpp:117 TrainOneIter — never stops, never shrinks."""
        if gradients is not None or hessians is not None:
            log.fatal("RF mode does not support custom objective functions")
        # sentinel flags fetched for the previous iteration are stale now
        self._finite_cache = None
        K = self.num_tree_per_iteration
        bag_mask, grad, hess = self._update_bagging(self._rf_grad,
                                                    self._rf_hess)
        cur = float(self.iter_ + self.num_init_iteration_)
        for k in range(K):
            tree = None
            leaf_id = None
            if self.class_need_train[k] and self.train_data.num_features > 0:
                grow_kw = {}
                if self._cegb_used is not None:
                    grow_kw["cegb_used"] = self._cegb_used
                if self._lazy_used is not None:
                    grow_kw["lazy_used"] = self._lazy_used
                out = self._grow_fn(
                    self.binned_dev, self._slice_row_fn(grad, k),
                    self._slice_row_fn(hess, k), bag_mask,
                    self._col_mask(), self.meta, self.grow_params,
                    **grow_kw)
                if self._lazy_used is not None:
                    arrays, leaf_id, self._lazy_used = out
                else:
                    arrays, leaf_id = out
                if self._cegb_used is not None:
                    self._cegb_used = self._cegb_mark_fn(
                        self._cegb_used, arrays.split_feature,
                        arrays.num_leaves)
                tree = self._arrays_to_tree(arrays)
            if tree is not None:
                nl = tree.num_leaves
                init = self._rf_init_scores[k]
                obj = self.objective
                if obj is not None and obj.need_renew_tree_output:
                    # residual against the constant init score, matching
                    # rf.hpp's residual_getter = label - init
                    leaf_id_host = np.asarray(leaf_id)[:self.num_data]
                    bag = self._bag_mask_host[:self.num_data] > 0
                    renewed = obj.renew_tree_output(
                        np.where(bag, leaf_id_host, -1),
                        np.full(self.num_data, init, np.float64), nl)
                    if renewed is not None:
                        tree.leaf_value[:nl] = renewed
                if abs(init) > K_EPSILON:
                    tree.add_bias(init)
                # running average: score = (score*cur + tree_pred)/(cur+1)
                self._rf_multiply_score(k, cur)
                L = self.config.num_leaves
                leaf_vals = jnp.asarray(
                    tree.leaf_value[:max(L, 2)].astype(np.float32))
                self.scores = self._score_update_fn(
                    self.scores, k, leaf_vals, leaf_id, self.pad_mask)
                self._add_tree_score(tree, k, train=False)
                self._rf_multiply_score(k, 1.0 / (cur + 1.0))
            else:
                tree = Tree(2)
                tree.num_leaves = 1
                if len(self.models_) < K:
                    output = 0.0
                    if not self.class_need_train[k]:
                        output = self.objective.boost_from_score(k)
                    tree.leaf_value[0] = output
                    tree.shrinkage = 1.0
                    self._rf_multiply_score(k, cur)
                    self.scores = self.scores.at[k].add(
                        float(output) * self.pad_mask)
                    for sc in self.valid_scores:
                        sc[k] += output
                    self._rf_multiply_score(k, 1.0 / (cur + 1.0))
            self.models_.append(tree)
        self.iter_ += 1
        return False
