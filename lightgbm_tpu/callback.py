"""Training callbacks (ref: python-package/lightgbm/callback.py)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List

import numpy as np

from .utils import log


class EarlyStopException(Exception):
    """ref: callback.py EarlyStopException."""

    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


@dataclass
class CallbackEnv:
    """ref: callback.py CallbackEnv namedtuple."""
    model: Any
    params: Dict[str, Any]
    iteration: int
    begin_iteration: int
    end_iteration: int
    evaluation_result_list: List = field(default_factory=list)


def log_evaluation(period: int = 1, show_stdv: bool = True):
    """ref: callback.py log_evaluation."""
    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list \
                and (env.iteration + 1) % period == 0:
            result = "\t".join(
                f"{name}'s {metric}: {value:g}"
                for name, metric, value, _ in env.evaluation_result_list)
            log.info(f"[{env.iteration + 1}]\t{result}")
    _callback.order = 10
    return _callback


def record_evaluation(eval_result: Dict[str, Dict[str, List[float]]]):
    """ref: callback.py record_evaluation."""
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dictionary")

    def _callback(env: CallbackEnv) -> None:
        if env.iteration == env.begin_iteration:
            eval_result.clear()
        for name, metric, value, _ in env.evaluation_result_list:
            eval_result.setdefault(name, {}).setdefault(metric, []).append(value)
    _callback.order = 20
    return _callback


def reset_parameter(**kwargs):
    """Per-iteration parameter schedules (ref: callback.py reset_parameter).
    Currently supports learning_rate (list or callable)."""
    def _callback(env: CallbackEnv) -> None:
        for key, value in kwargs.items():
            if callable(value):
                new_val = value(env.iteration - env.begin_iteration)
            else:
                new_val = value[env.iteration - env.begin_iteration]
            if key in ("learning_rate", "shrinkage_rate", "eta"):
                env.model._gbdt.shrinkage_rate = float(new_val)
            else:
                log.warning(f"reset_parameter: unsupported parameter {key}")
    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def checkpoint(checkpoint_dir: str, frequency: int = 1, keep_last: int = 3,
               manager=None):
    """Periodic checkpoint callback: every `frequency` iterations (and at
    the final iteration) write an atomic, rotated checkpoint of the model
    plus exact trainer state, resumable via `train(checkpoint_dir=...)`.
    A failed write warns and training continues — losing one checkpoint
    must not kill a long run.  Under multi-process SPMD only rank 0
    writes (all ranks hold identical models by construction)."""
    from .reliability.checkpoint import CheckpointManager
    mgr = manager if manager is not None else CheckpointManager(
        checkpoint_dir, keep_last=keep_last)

    def _is_writer_rank() -> bool:
        try:
            import jax
            return jax.process_index() == 0
        except Exception:
            return True

    def _callback(env: CallbackEnv) -> None:
        if frequency <= 0:
            return
        it = env.iteration + 1
        if it % frequency != 0 and it != env.end_iteration:
            return
        if not _is_writer_rank():
            return
        if mgr.params_hash is None:
            from .reliability.checkpoint import hash_params
            mgr.params_hash = hash_params(env.params)
        from .observability import emit_event, global_registry

        def _on_done(ok, err, ck):
            # shared accounting for both write modes: in async mode this
            # fires from the writer thread once the files land (or fail)
            if ok:
                global_registry.inc("checkpoint_writes")
                emit_event("checkpoint", iteration=it, path=ck.model_path)
            else:
                global_registry.inc("checkpoint_failures")
                emit_event("checkpoint_write_failed", iteration=it,
                           error=str(err))
                log.warning(f"Checkpoint write failed at iteration {it}: "
                            f"{err}; training continues (the previous "
                            "checkpoint is intact)")
        mgr.save(env.model, it, on_done=_on_done)
    _callback.order = 40
    return _callback


def record_metrics(metrics_dir: str = None, logger=None):
    """Structured telemetry callback (docs/Observability.md): appends ONE
    JSONL event per boosting iteration to
    `<metrics_dir>/events-rank<r>.jsonl` — iteration wall-clock, the
    per-phase timer breakdown (delta of `global_timer` since the previous
    iteration), train/valid eval results, the grown trees' leaf/depth
    stats, and the cumulative counter/gauge snapshot (checkpoint writes,
    injected faults, retries, recompiles, device memory).

    `train(metrics_dir=...)` installs this automatically; pass it
    explicitly (with a shared EventLogger) to co-locate events from
    custom callbacks.  Phase deltas need the global timer: the engine
    enables it for metrics runs, or set LIGHTGBM_TPU_TIMETAG=1.

    With the cost model enabled (param `roofline`, on during metrics
    runs) the event additionally carries per-phase measured MFU,
    arithmetic intensity and a compute- vs HBM-bound classification —
    compiled-HLO flop/byte deltas over the same window as the phase
    timings (observability/costmodel.py).  Every iteration record is
    also appended to the always-on flight recorder, so a later stall or
    crash can dump the recent history it was part of."""
    import time as _time

    from .observability import EventLogger, global_registry
    from .observability.costmodel import global_cost_model
    from .observability.flightrec import flight_recorder
    from .utils.timer import global_timer

    if metrics_dir is None and logger is None:
        raise ValueError("record_metrics needs metrics_dir or a logger")
    state: Dict[str, Any] = {"t": _time.perf_counter(),
                             "snap": global_timer.snapshot(),
                             "cost": global_cost_model.snapshot()}

    def _callback(env: CallbackEnv) -> None:
        lg = state.get("logger")
        if lg is None:
            lg = logger if logger is not None else EventLogger(metrics_dir)
            state["logger"] = lg
        gbdt = env.model._gbdt
        # materialize this iteration's trees so the event carries their
        # real shape (and the residual device work is charged to a named
        # phase instead of leaking into the next iteration's timings)
        gbdt._drain_pending(keep_depth=0)
        now = _time.perf_counter()
        snap = global_timer.snapshot()
        prev = state["snap"]
        phases = {}
        phase_secs = {}
        for name, (sec, _cnt) in snap.items():
            d = sec - prev.get(name, (0.0, 0))[0]
            if d > 0:
                phase_secs[name] = d
                phases[name] = round(d, 6)
        state["snap"] = snap
        time_s = now - state["t"]
        state["t"] = now

        # per-phase roofline (docs/Observability.md): compiled flop/byte
        # deltas over this iteration's window, against the phase's
        # ::device time — measured MFU, not the bench's analytic guess
        roofline = None
        if global_cost_model.enabled:
            cost = global_cost_model.snapshot()
            roofline = global_cost_model.phase_roofline(
                state["cost"], cost, phase_secs) or None
            state["cost"] = cost

        train_evals, valid_evals = {}, {}
        for name, metric, value, _hb in env.evaluation_result_list:
            if name == "training":
                train_evals[metric] = value
            else:
                valid_evals[f"{name} {metric}"] = value
        K = gbdt.num_tree_per_iteration
        trees = []
        for t in gbdt.models_[-K:] if len(gbdt.models_) >= K else []:
            nl = int(getattr(t, "num_leaves", 1))
            depth = (int(np.max(t.leaf_depth[:nl]))
                     if nl > 1 and hasattr(t, "leaf_depth") else 0)
            trees.append({"leaves": nl, "depth": depth})
        reg = global_registry.snapshot()
        fields = dict(iteration=env.iteration + 1,
                      time_s=round(time_s, 6), phases=phases,
                      train=train_evals, valid=valid_evals, trees=trees,
                      counters=reg["counters"], gauges=reg["gauges"])
        if roofline:
            fields["roofline"] = roofline
        lg.emit("iteration", **fields)
        # flight recorder: the bounded in-process tail a stall/crash/
        # SIGUSR2 dump reads back (observability/flightrec.py)
        device_ms = sum(v for k, v in phase_secs.items()
                        if k.endswith("::device")) * 1000.0
        flight_recorder.record_iteration(
            iteration=env.iteration + 1, time_s=round(time_s, 6),
            phase_ms={k: round(v * 1000.0, 3)
                      for k, v in phase_secs.items()},
            device_ms=round(device_ms, 3),
            recompiles=reg["counters"].get("recompiles", 0),
            hbm_bytes=reg["gauges"].get("device_bytes_in_use"),
            rows_per_s=(round(gbdt.num_data / time_s, 1)
                        if time_s > 0 else None))
    _callback.order = 50
    return _callback


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True, min_delta: float = 0.0):
    """ref: callback.py early_stopping / _EarlyStoppingCallback."""
    state: Dict[str, Any] = {}

    def _is_improved(score, best, higher_better):
        if higher_better:
            return score > best + min_delta
        return score < best - min_delta

    def _callback(env: CallbackEnv) -> None:
        if state.get("disabled"):
            return
        if not env.evaluation_result_list:
            # warn ONCE and disable: repeating this every iteration was
            # pure log spam, and no validation set can appear mid-run
            log.warning("Early stopping requires at least one validation "
                        "set; disabling early stopping")
            state["disabled"] = True
            return
        if not state:
            state["best_score"] = {}
            state["best_iter"] = {}
            state["best_list"] = {}
        first_metric = env.evaluation_result_list[0][1].split(" ")[-1]
        for name, metric, value, higher_better in env.evaluation_result_list:
            if name == "training":
                continue
            if first_metric_only and metric.split(" ")[-1] != first_metric:
                continue
            key = f"{name} {metric}"
            if key not in state["best_score"] or _is_improved(
                    value, state["best_score"][key], higher_better):
                state["best_score"][key] = value
                state["best_iter"][key] = env.iteration
                state["best_list"][key] = list(env.evaluation_result_list)
            elif env.iteration - state["best_iter"][key] >= stopping_rounds:
                if verbose:
                    log.info(f"Early stopping, best iteration is:\n"
                             f"[{state['best_iter'][key] + 1}]")
                raise EarlyStopException(state["best_iter"][key],
                                         state["best_list"][key])
    _callback.order = 30
    return _callback
