"""CLI application: `python -m lightgbm_tpu config=train.conf [k=v ...]`.

TPU-native analogue of the reference CLI (ref: src/main.cpp:14;
src/application/application.cpp:31 Application / application.h:78 Run).
Parameter precedence matches LoadParameters: command-line `key=value`
pairs win over config-file entries (first occurrence wins,
ref: application.cpp:79 KeepFirstValues).  Tasks: train, predict,
refit, save_binary, convert_model (ref: config.h TaskType).
"""

from __future__ import annotations

import os
import sys
from typing import Dict, List, Optional

import numpy as np

from .basic import Booster, Dataset
from .config import Config, read_config_file
from .engine import train as train_api
from .utils import log


def parse_args(argv: List[str]) -> Dict[str, str]:
    """argv `key=value` tokens + optional config file, CLI first
    (ref: application.cpp:50-86 LoadParameters)."""
    params: Dict[str, str] = {}
    for tok in argv:
        if "=" not in tok:
            log.fatal(f"Unknown argument {tok!r}; expected key=value")
        k, v = tok.split("=", 1)
        params.setdefault(k.strip(), v.strip())
    conf = params.get("config", params.get("config_file", ""))
    if conf:
        for k, v in read_config_file(conf).items():
            params.setdefault(k, v)  # first (CLI) value wins
    params.pop("config", None)
    params.pop("config_file", None)
    return params


def _load_train_data(cfg: Config, params: Dict[str, str]) -> Dataset:
    if not cfg.data:
        log.fatal("No training data: set data=<file>")
    return Dataset(cfg.data, params=dict(params))


def _task_train(cfg: Config, params: Dict[str, str]) -> None:
    train_set = _load_train_data(cfg, params)
    valid_sets, valid_names = [], []
    for i, vf in enumerate(cfg.valid):
        valid_sets.append(Dataset(vf, params=dict(params),
                                  reference=train_set))
        valid_names.append(f"valid_{i}" if len(cfg.valid) > 1 else "valid")
    init_model = cfg.input_model or None
    callbacks = None
    if cfg.snapshot_freq > 0:
        # periodic checkpoints (ref: gbdt.cpp:244-248 snapshot_freq
        # writes model.snapshot_iter_N; resume via input_model)
        def _snapshot(env):
            it = env.iteration + 1
            if it % cfg.snapshot_freq == 0:
                env.model.save_model(
                    f"{cfg.output_model}.snapshot_iter_{it}")
        _snapshot.order = 100
        callbacks = [_snapshot]
    # task=train resume flags (docs/Reliability.md): checkpoint_dir=DIR
    # enables rotated atomic checkpoints every checkpoint_freq rounds;
    # re-running the same command continues from the newest one unless
    # resume=false.  (Distinct from snapshot_freq, which only writes
    # model files and never resumes by itself.)
    if cfg.checkpoint_dir:
        log.info(f"Checkpointing to {cfg.checkpoint_dir} every "
                 f"{cfg.checkpoint_freq} iteration(s) "
                 f"(resume={'on' if cfg.resume else 'off'})")
    # observability knobs (docs/Observability.md): metrics_dir= enables
    # the per-iteration JSONL event log, profile_dir= a jax profiler
    # trace; both flow to train() through the params dict
    if cfg.metrics_dir:
        log.info(f"Writing per-iteration telemetry events to "
                 f"{cfg.metrics_dir}")
    if cfg.profile_dir:
        log.info(f"Profiling run; TensorBoard trace will be written to "
                 f"{cfg.profile_dir}")
    booster = train_api(dict(params), train_set,
                        num_boost_round=cfg.num_iterations,
                        valid_sets=valid_sets or None,
                        valid_names=valid_names or None,
                        init_model=init_model, callbacks=callbacks,
                        checkpoint_dir=cfg.checkpoint_dir or None,
                        checkpoint_freq=cfg.checkpoint_freq,
                        resume=cfg.resume)
    booster.save_model(cfg.output_model)
    log.info(f"Finished training; model saved to {cfg.output_model}")


# per-chunk memory budget for streamed file prediction (bytes of float64
# features); tests shrink it to force multi-chunk runs
_PREDICT_CHUNK_BUDGET = 32 << 20


def _task_predict(cfg: Config, params: Dict[str, str]) -> None:
    """Bounded-memory file prediction: the input streams through
    parse_file_stream in row chunks (ref: predictor.hpp:30
    PipelineReader — the reference double-buffers file chunks the same
    way), so peak RSS is one chunk + the model, independent of file
    size."""
    if not cfg.input_model:
        log.fatal("task=predict needs input_model=<file>")
    booster = Booster(model_file=cfg.input_model)
    from .io.parser import parse_file_stream
    nf = booster.num_feature()
    chunk_rows = max(128, _PREDICT_CHUNK_BUDGET // max(8 * nf, 1))
    n_done = 0
    with open(cfg.output_result, "w") as f:
        for feats, _ in parse_file_stream(
                cfg.data, has_header=cfg.header,
                label_column=cfg.label_column, chunk_rows=chunk_rows,
                num_features=nf):
            pred = booster.predict(
                feats, raw_score=cfg.predict_raw_score,
                pred_leaf=cfg.predict_leaf_index,
                pred_contrib=cfg.predict_contrib,
                num_iteration=cfg.num_iteration_predict)
            for row in np.atleast_1d(pred):
                if np.ndim(row) == 0:
                    f.write(f"{row:.18g}\n")
                else:
                    f.write("\t".join(f"{v:.18g}" for v in row) + "\n")
            n_done += len(feats)
    log.info(f"Finished prediction of {n_done} rows; results saved to "
             f"{cfg.output_result}")


def _task_refit(cfg: Config, params: Dict[str, str]) -> None:
    """Refit existing tree structures to new data
    (ref: application.cpp ConvertModel... task=refit -> GBDT::RefitTree)."""
    if not cfg.input_model:
        log.fatal("task=refit needs input_model=<file>")
    booster = Booster(model_file=cfg.input_model)
    from .io.parser import parse_file
    feats, labels, _ = parse_file(cfg.data, has_header=cfg.header,
                                  label_column=cfg.label_column)
    booster.refit(feats, labels)
    booster.save_model(cfg.output_model)
    log.info(f"Finished refit; model saved to {cfg.output_model}")


def _task_save_binary(cfg: Config, params: Dict[str, str]) -> None:
    ds = _load_train_data(cfg, params)
    core = ds._core_or_construct()
    out = (cfg.data or "train") + ".bin"
    core.save_binary(out)
    log.info(f"Saved binary dataset to {out}")


def _task_serve(cfg: Config, params: Dict[str, str]) -> None:
    """Long-lived multi-model serving daemon (docs/Serving.md):
    `python -m lightgbm_tpu serve serve_models=name=model.txt [...]`.
    Loads + warms every model (bucket-ladder compiles) BEFORE serving,
    optionally exposes the line-JSON TCP front end (serve_port=0 for an
    ephemeral port), and treats SIGTERM as a drain notice — queued
    requests complete, a final `serve_drain` event lands, exit stays
    143 (the supervisor's *preempt* classification)."""
    import time as _time

    from .serving import ServingDaemon, start_frontend

    if cfg.metrics_dir:
        # serve_* events (swap/evict/drain) land in the standard JSONL
        # event log, same as training telemetry
        from .observability import set_event_logger
        from .observability.events import EventLogger
        set_event_logger(EventLogger(cfg.metrics_dir,
                                     rotate_mb=cfg.metrics_rotate_mb))
        # SIGUSR2 = dump the flight recorder + registry snapshot from
        # the LIVE daemon without killing it (reliability/faults.py)
        from .reliability.faults import register_flight_dump_signal
        register_flight_dump_signal(cfg.metrics_dir)
    entries = []
    for tok in cfg.serve_models:
        name, sep, path = tok.partition("=")
        if not sep:
            name, path = os.path.splitext(os.path.basename(tok))[0], tok
        entries.append((name.strip(), path.strip()))
    if not entries and cfg.input_model:
        entries.append(("default", cfg.input_model))
    if not entries:
        log.fatal("task=serve needs serve_models=name=model.txt[,...] "
                  "or input_model=<file>")
    daemon = ServingDaemon(cfg)
    for name, path in entries:
        daemon.registry.register(name, model_file=path, block=True)
        log.info(f"Serving model {name!r} from {path} (warmed)")
    daemon.start()
    daemon.install_signal_handlers()
    srv = None
    uds_srv = None
    if cfg.serve_port >= 0:
        srv = start_frontend(daemon, port=cfg.serve_port,
                             request_timeout_s=cfg.serve_request_timeout_s)
    if cfg.serve_uds_path:
        from .serving import start_uds_frontend
        uds_srv = start_uds_frontend(
            daemon, cfg.serve_uds_path,
            request_timeout_s=cfg.serve_request_timeout_s)
    if cfg.serve_ready_file:
        # readiness marker for the fleet supervisor: port + pid land
        # atomically only AFTER every model is loaded, warmed, and the
        # front end is listening — a torn or early file would route
        # traffic into cold compiles
        import json as _json

        from .utils import atomic_write_text
        atomic_write_text(cfg.serve_ready_file, _json.dumps({
            "pid": os.getpid(),
            "port": srv.server_address[1] if srv is not None else -1,
            "metrics_port": (daemon.metrics_server.port
                             if daemon.metrics_server else -1),
            "models": daemon.registry.versions()}))
        log.info(f"Ready file written to {cfg.serve_ready_file}")
    log.info(f"Serving {len(entries)} model(s); SIGTERM drains and exits")
    try:
        while not daemon.stopped:
            _time.sleep(0.2)
    except KeyboardInterrupt:
        log.info("Interrupted; draining the request queue")
        daemon.stop(drain=True, timeout=cfg.serve_drain_timeout_s)
    finally:
        if srv is not None:
            srv.shutdown()
        if uds_srv is not None:
            uds_srv.shutdown()


def _task_serve_fleet(cfg: Config, params: Dict[str, str]) -> None:
    """Serving fault domain (docs/Serving.md fleet section):
    `python -m lightgbm_tpu serve-fleet serve_models=m=model.txt
    serve_replicas=3 serve_port=0`.  Spawns `serve_replicas` replica
    daemons (each a supervised task=serve child with its own device
    context and ready file), health-checks them, and fronts them with
    the retry/shed/canary router on `serve_port`.  SIGTERM drains the
    WHOLE fleet: the router stops accepting, every replica gets its own
    SIGTERM drain (each exits 143), and the runner re-delivers — exit
    stays 143."""
    import tempfile
    import time as _time

    from .serving import ReplicaFleet, Router

    if cfg.metrics_dir:
        from .observability import set_event_logger
        from .observability.events import EventLogger
        set_event_logger(EventLogger(cfg.metrics_dir,
                                     rotate_mb=cfg.metrics_rotate_mb))
    entries = []
    for tok in cfg.serve_models:
        name, sep, path = tok.partition("=")
        if not sep:
            name, path = os.path.splitext(os.path.basename(tok))[0], tok
        entries.append((name.strip(), path.strip()))
    if not entries and cfg.input_model:
        entries.append(("default", cfg.input_model))
    if not entries:
        log.fatal("task=serve-fleet needs serve_models=name=model.txt"
                  "[,...] or input_model=<file>")
    workdir = cfg.metrics_dir or tempfile.mkdtemp(prefix="lgbm-fleet-")
    # replica daemons inherit the serving knobs; their OWN ports are
    # ephemeral (the ready file reports them) and the router owns the
    # client-facing serve_port
    replica_params = {k: v for k, v in params.items()
                      if k not in ("task", "serve_port", "serve_replicas",
                                   "serve_ready_file", "metrics_dir",
                                   "metrics_port")}
    fleet = ReplicaFleet(
        num_replicas=cfg.serve_replicas, model_entries=entries,
        workdir=workdir, params=replica_params,
        max_restarts=cfg.serve_max_replica_restarts,
        health_interval_s=cfg.serve_health_interval_s,
        force_cpu=os.environ.get("LGBM_TPU_SERVE_FORCE_CPU") == "1",
    ).start()
    router = Router(fleet, cfg)
    for name, path in entries:
        router.register_incumbent(name, path)
    if not fleet.wait_ready(timeout=300.0, min_replicas=1):
        fleet.stop(drain=False)
        log.fatal("serve-fleet: no replica became ready within 300 s "
                  f"(see {workdir}/replica-*.log)")
    srv = router.start_frontend(port=max(cfg.serve_port, 0),
                                metrics_port=cfg.metrics_port)
    log.info(f"Fleet router listening on "
             f"{srv.server_address[0]}:{srv.server_address[1]} "
             f"({cfg.serve_replicas} replicas); SIGTERM drains the fleet")
    if cfg.serve_slo_p99_ms > 0:
        # router-observed SLO burn tracking (docs/Observability.md
        # "Fleet metrics & SLO"): slo_burn events land in the event log
        # when metrics_dir= is set, fleet_slo_burning rides /metrics
        log.info(f"SLO tracking on: p99 <= {cfg.serve_slo_p99_ms:g} ms, "
                 f"error budget {cfg.serve_slo_error_pct:g}% "
                 f"(burn windows {cfg.serve_slo_fast_window_s:g}s / "
                 f"{cfg.serve_slo_slow_window_s:g}s)")
    if router.metrics_server is not None:
        log.info(f"Fleet observability on port "
                 f"{router.metrics_server.port}: GET /metrics (merged "
                 f"fleet view) and GET /trace/<id> (sampled "
                 f"cross-process waterfalls; op=trace on the wire)")
    if cfg.serve_ready_file:
        import json as _json

        from .utils import atomic_write_text
        atomic_write_text(cfg.serve_ready_file, _json.dumps({
            "pid": os.getpid(), "port": srv.server_address[1],
            "metrics_port": (router.metrics_server.port
                             if router.metrics_server else -1),
            "replicas": fleet.describe()}))
    stopping = {"flag": False}

    def _drain():
        stopping["flag"] = True
        router.stop()
        fleet.stop(drain=True, timeout=cfg.serve_drain_timeout_s + 30.0)
        return None  # finish_preemption re-delivers; rc stays 143

    from .observability import install_sigterm_flush, set_preemption_hook
    if install_sigterm_flush():
        set_preemption_hook(_drain)
    try:
        while not stopping["flag"] and fleet.alive():
            _time.sleep(0.2)
        if not stopping["flag"]:
            log.warning("serve-fleet: every replica exhausted its "
                        "restart budget; shutting down")
    except KeyboardInterrupt:
        log.info("Interrupted; draining the fleet")
        _drain()
    finally:
        router.stop()


def _task_train_and_serve(cfg: Config, params: Dict[str, str]) -> None:
    """Online continual learning (docs/Online.md):
    `python -m lightgbm_tpu task=train-and-serve online_chunk_dir=DIR
    checkpoint_dir=CKPT [input_model=seed.txt] [serve_port=0]`.

    One process closing the train->serve loop: a DirectoryChunkSource
    watches `online_chunk_dir`, the OnlineTrainer boosts/refits per
    chunk generation, checkpoints each generation (byte-exact
    SIGTERM/crash resume), and publishes atomically — into this
    process's own serving daemon (default; serve_port/serve_uds_path
    expose it), or over the wire to a remote router/replica when
    `online_publish_addr=host:port` is set.  SIGTERM stops the loop at
    the next boundary (mid-generation: the relaunch resumes from the
    last completed generation's checkpoint) and drains the local
    daemon; exit stays 143."""
    import json as _json
    import time as _time

    from .online import (DirectoryChunkSource, LocalPublisher,
                         OnlineTrainer, WirePublisher)

    if cfg.metrics_dir:
        from .observability import set_event_logger
        from .observability.events import EventLogger
        set_event_logger(EventLogger(cfg.metrics_dir,
                                     rotate_mb=cfg.metrics_rotate_mb))
        from .reliability.faults import register_flight_dump_signal
        register_flight_dump_signal(cfg.metrics_dir)
    if not cfg.online_chunk_dir:
        log.fatal("task=train-and-serve needs online_chunk_dir=<dir>")
    if not cfg.checkpoint_dir:
        log.warning("train-and-serve without checkpoint_dir=: a restart "
                    "re-trains from scratch (no byte-exact resume)")

    daemon = None
    srv = None
    uds_srv = None
    if cfg.online_publish_addr:
        host, _, port = cfg.online_publish_addr.rpartition(":")
        if not port.isdigit():
            log.fatal(f"online_publish_addr must be host:port "
                      f"(got {cfg.online_publish_addr!r})")
        publisher = WirePublisher(host or "127.0.0.1", int(port))
        log.info(f"Publishing generations to {cfg.online_publish_addr} "
                 "(op=publish over the wire)")
    else:
        from .serving import ServingDaemon, start_frontend, \
            start_uds_frontend
        daemon = ServingDaemon(cfg).start()
        publisher = LocalPublisher(daemon)
        if cfg.serve_port >= 0:
            srv = start_frontend(
                daemon, port=cfg.serve_port,
                request_timeout_s=cfg.serve_request_timeout_s)
        if cfg.serve_uds_path:
            uds_srv = start_uds_frontend(
                daemon, cfg.serve_uds_path,
                request_timeout_s=cfg.serve_request_timeout_s)

    source = DirectoryChunkSource(cfg.online_chunk_dir)
    trainer = OnlineTrainer(source, publisher, config=cfg,
                            params=dict(params),
                            checkpoint_dir=cfg.checkpoint_dir or None,
                            seed_model=cfg.input_model or None)
    trainer.install_signal_handlers()
    if daemon is not None:
        # one preemption-hook slot: the trainer owns it; chain the
        # daemon's drain behind the loop-stop so a SIGTERM between
        # generations completes queued requests before the exit
        from .observability import set_preemption_hook

        def _stop_all():
            trainer.request_stop()
            daemon.stop(drain=True, timeout=cfg.serve_drain_timeout_s)
            return None  # finish_preemption re-delivers; rc stays 143

        set_preemption_hook(_stop_all)
    trainer.start()  # resume (or seed) + initial publish
    if cfg.serve_ready_file:
        from .utils import atomic_write_text
        atomic_write_text(cfg.serve_ready_file, _json.dumps({
            "pid": os.getpid(),
            "port": (srv.server_address[1] if srv is not None else -1),
            "uds_path": cfg.serve_uds_path or None,
            "metrics_port": (daemon.metrics_server.port
                             if daemon is not None
                             and daemon.metrics_server else -1),
            "generation": trainer.generation,
            "model": trainer.model_name}))
        log.info(f"Ready file written to {cfg.serve_ready_file}")
    log.info(f"Online loop watching {cfg.online_chunk_dir} "
             f"(mode={cfg.online_mode}, "
             f"{cfg.online_trees_per_chunk} trees/chunk"
             + (f", freshness SLO {cfg.online_max_lag_s:g}s"
                if cfg.online_max_lag_s > 0 else "") + ")")
    try:
        stats = trainer.run()
        log.info(f"Online loop finished: {stats}")
    except KeyboardInterrupt:
        log.info("Interrupted; stopping the online loop")
        trainer.request_stop()
    finally:
        if daemon is not None and not daemon.stopped:
            daemon.stop(drain=True, timeout=cfg.serve_drain_timeout_s)
        if srv is not None:
            srv.shutdown()
        if uds_srv is not None:
            uds_srv.shutdown()
        # give the last published generation a beat to settle in logs
        _time.sleep(0.0)


def _task_convert_model(cfg: Config, params: Dict[str, str]) -> None:
    """Model -> standalone C-like if-else source
    (ref: gbdt_model_text.cpp SaveModelToIfElse)."""
    if not cfg.input_model:
        log.fatal("task=convert_model needs input_model=<file>")
    booster = Booster(model_file=cfg.input_model)
    out = cfg.convert_model or "gbdt_prediction.cpp"
    with open(out, "w") as f:
        f.write(booster.model_to_if_else())
    log.info(f"Converted model saved to {out}")


def _machine_entries(cfg: Config):
    """machines="ip1:port1,ip2:port2" or machine_list_filename (one
    "ip port" per line) -> ordered list of "host:port" strings
    (ref: config.h machines/machine_list_filename; network.cpp
    Network::Init parses both the same way)."""
    if cfg.machines:
        return [e.strip() for e in str(cfg.machines).split(",")
                if e.strip()]
    if cfg.machine_list_filename:
        entries = []
        with open(cfg.machine_list_filename) as f:
            for ln in f:
                toks = ln.split()
                if len(toks) >= 2:
                    entries.append(f"{toks[0]}:{toks[1]}")
        return entries
    return []


def _maybe_init_distributed(cfg: Config) -> None:
    """Multi-machine SPMD launch (ref: application.cpp:100-115 machine
    setup; the Dask launcher plays this role in the reference's Python
    stack).  Each worker runs this same CLI with the shared `machines`
    list and its OWN local_listen_port; the rank is the machine-list
    entry matching this host and port (the reference's rank resolution),
    entry 0 doubles as the jax.distributed coordinator.  After
    initialize(), jax.devices() spans every worker and tree_learner=
    data/feature/voting shards over the global mesh — the collectives
    replace the reference's socket linkers."""
    if cfg.num_machines <= 1:
        return
    entries = _machine_entries(cfg)
    if not entries:
        log.warning("num_machines > 1 without machines / "
                    "machine_list_filename: training runs single-process "
                    "over the local devices only")
        return
    if len(entries) != cfg.num_machines:
        log.fatal(f"num_machines={cfg.num_machines} but machine list has "
                  f"{len(entries)} entries")
    rank_env = os.environ.get("LIGHTGBM_TPU_MACHINE_RANK")
    if rank_env is not None:
        rank = int(rank_env)
    else:
        import socket
        local_names = {"localhost", "127.0.0.1", socket.gethostname()}
        try:
            local_names.update(
                socket.gethostbyname_ex(socket.gethostname())[2])
        except OSError:
            pass
        rank = -1
        for i, e in enumerate(entries):
            host, sep, port = e.rpartition(":")
            if not sep or not port.isdigit():
                log.fatal(f"Malformed machines entry {e!r}; expected "
                          "host:port")
            if host in local_names and int(port) == cfg.local_listen_port:
                rank = i
                break
        if rank < 0:
            log.fatal("This machine (with local_listen_port="
                      f"{cfg.local_listen_port}) is not in the machine "
                      "list; set machines to include host:port for every "
                      "worker")
    import jax
    jax.distributed.initialize(coordinator_address=entries[0],
                               num_processes=len(entries), process_id=rank)
    log.info(f"Joined distributed cluster as rank {rank}/{len(entries)} "
             f"(coordinator {entries[0]}); global devices: "
             f"{jax.device_count()}")


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] in ("serve", "serve-fleet", "train-and-serve"):
        # `python -m lightgbm_tpu serve[-fleet] k=v ...` sugar
        argv = [f"task={argv[0]}"] + list(argv[1:])
    params = parse_args(argv)
    cfg = Config(dict(params))
    _maybe_init_distributed(cfg)
    task = cfg.task
    handlers = {"train": _task_train, "predict": _task_predict,
                "prediction": _task_predict, "refit": _task_refit,
                "refit_tree": _task_refit,
                "save_binary": _task_save_binary,
                "serve": _task_serve,
                "serve-fleet": _task_serve_fleet,
                "serve_fleet": _task_serve_fleet,
                "train-and-serve": _task_train_and_serve,
                "train_and_serve": _task_train_and_serve,
                "convert_model": _task_convert_model}
    if task not in handlers:
        log.fatal(f"Unknown task {task!r}")
    handlers[task](cfg, params)
    return 0


if __name__ == "__main__":
    sys.exit(main())
