"""CLI application: `python -m lightgbm_tpu config=train.conf [k=v ...]`.

TPU-native analogue of the reference CLI (ref: src/main.cpp:14;
src/application/application.cpp:31 Application / application.h:78 Run).
Parameter precedence matches LoadParameters: command-line `key=value`
pairs win over config-file entries (first occurrence wins,
ref: application.cpp:79 KeepFirstValues).  Tasks: train, predict,
refit, save_binary, convert_model (ref: config.h TaskType).
"""

from __future__ import annotations

import sys
from typing import Dict, List, Optional

import numpy as np

from .basic import Booster, Dataset
from .config import Config, read_config_file
from .engine import train as train_api
from .utils import log


def parse_args(argv: List[str]) -> Dict[str, str]:
    """argv `key=value` tokens + optional config file, CLI first
    (ref: application.cpp:50-86 LoadParameters)."""
    params: Dict[str, str] = {}
    for tok in argv:
        if "=" not in tok:
            log.fatal(f"Unknown argument {tok!r}; expected key=value")
        k, v = tok.split("=", 1)
        params.setdefault(k.strip(), v.strip())
    conf = params.get("config", params.get("config_file", ""))
    if conf:
        for k, v in read_config_file(conf).items():
            params.setdefault(k, v)  # first (CLI) value wins
    params.pop("config", None)
    params.pop("config_file", None)
    return params


def _load_train_data(cfg: Config, params: Dict[str, str]) -> Dataset:
    if not cfg.data:
        log.fatal("No training data: set data=<file>")
    return Dataset(cfg.data, params=dict(params))


def _task_train(cfg: Config, params: Dict[str, str]) -> None:
    train_set = _load_train_data(cfg, params)
    valid_sets, valid_names = [], []
    for i, vf in enumerate(cfg.valid):
        valid_sets.append(Dataset(vf, params=dict(params),
                                  reference=train_set))
        valid_names.append(f"valid_{i}" if len(cfg.valid) > 1 else "valid")
    init_model = cfg.input_model or None
    callbacks = None
    if cfg.snapshot_freq > 0:
        # periodic checkpoints (ref: gbdt.cpp:244-248 snapshot_freq
        # writes model.snapshot_iter_N; resume via input_model)
        def _snapshot(env):
            it = env.iteration + 1
            if it % cfg.snapshot_freq == 0:
                env.model.save_model(
                    f"{cfg.output_model}.snapshot_iter_{it}")
        _snapshot.order = 100
        callbacks = [_snapshot]
    booster = train_api(dict(params), train_set,
                        num_boost_round=cfg.num_iterations,
                        valid_sets=valid_sets or None,
                        valid_names=valid_names or None,
                        init_model=init_model, callbacks=callbacks)
    booster.save_model(cfg.output_model)
    log.info(f"Finished training; model saved to {cfg.output_model}")


def _load_predict_matrix(cfg: Config) -> np.ndarray:
    from .io.parser import parse_file
    feats, _, _ = parse_file(cfg.data, has_header=cfg.header,
                             label_column=cfg.label_column)
    return feats


def _task_predict(cfg: Config, params: Dict[str, str]) -> None:
    if not cfg.input_model:
        log.fatal("task=predict needs input_model=<file>")
    booster = Booster(model_file=cfg.input_model)
    X = _load_predict_matrix(cfg)
    pred = booster.predict(
        X, raw_score=cfg.predict_raw_score,
        pred_leaf=cfg.predict_leaf_index,
        pred_contrib=cfg.predict_contrib,
        num_iteration=cfg.num_iteration_predict)
    with open(cfg.output_result, "w") as f:
        for row in np.atleast_1d(pred):
            if np.ndim(row) == 0:
                f.write(f"{row:.18g}\n")
            else:
                f.write("\t".join(f"{v:.18g}" for v in row) + "\n")
    log.info(f"Finished prediction; results saved to {cfg.output_result}")


def _task_refit(cfg: Config, params: Dict[str, str]) -> None:
    """Refit existing tree structures to new data
    (ref: application.cpp ConvertModel... task=refit -> GBDT::RefitTree)."""
    if not cfg.input_model:
        log.fatal("task=refit needs input_model=<file>")
    booster = Booster(model_file=cfg.input_model)
    from .io.parser import parse_file
    feats, labels, _ = parse_file(cfg.data, has_header=cfg.header,
                                  label_column=cfg.label_column)
    booster.refit(feats, labels)
    booster.save_model(cfg.output_model)
    log.info(f"Finished refit; model saved to {cfg.output_model}")


def _task_save_binary(cfg: Config, params: Dict[str, str]) -> None:
    ds = _load_train_data(cfg, params)
    core = ds._core_or_construct()
    out = (cfg.data or "train") + ".bin"
    core.save_binary(out)
    log.info(f"Saved binary dataset to {out}")


def _task_convert_model(cfg: Config, params: Dict[str, str]) -> None:
    """Model -> standalone C-like if-else source
    (ref: gbdt_model_text.cpp SaveModelToIfElse)."""
    if not cfg.input_model:
        log.fatal("task=convert_model needs input_model=<file>")
    booster = Booster(model_file=cfg.input_model)
    out = cfg.convert_model or "gbdt_prediction.cpp"
    with open(out, "w") as f:
        f.write(booster.model_to_if_else())
    log.info(f"Converted model saved to {out}")


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    params = parse_args(argv)
    cfg = Config(dict(params))
    task = cfg.task
    handlers = {"train": _task_train, "predict": _task_predict,
                "prediction": _task_predict, "refit": _task_refit,
                "refit_tree": _task_refit,
                "save_binary": _task_save_binary,
                "convert_model": _task_convert_model}
    if task not in handlers:
        log.fatal(f"Unknown task {task!r}")
    handlers[task](cfg, params)
    return 0


if __name__ == "__main__":
    sys.exit(main())
