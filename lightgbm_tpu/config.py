"""Single-definition parameter/config system.

Mirrors the reference's flat `struct Config` + generated alias table
(ref: include/LightGBM/config.h:39, src/io/config_auto.cpp:10, src/io/config.cpp
`Config::Set`/`KV2Map`/`KeepFirstValues`).  One declarative PARAMS table is the single
source of truth: typed fields, defaults, and aliases.  First occurrence of a
key (or any alias) wins; aliases normalize to the canonical name; unknown keys warn.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple, Union

from .utils import log


def _to_bool(v: Any) -> bool:
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)):
        return bool(v)
    s = str(v).strip().lower()
    if s in ("true", "1", "yes", "+"):
        return True
    if s in ("false", "0", "no", "-"):
        return False
    log.fatal(f"Cannot parse bool value: {v}")


def _to_int(v: Any) -> int:
    if isinstance(v, bool):
        return int(v)
    return int(float(v)) if not isinstance(v, int) else v


def _to_float(v: Any) -> float:
    return float(v)


def _to_str(v: Any) -> str:
    return str(v)


def _to_int_list(v: Any) -> List[int]:
    if isinstance(v, (list, tuple)):
        return [int(x) for x in v]
    s = str(v).strip()
    if not s:
        return []
    return [int(float(x)) for x in s.split(",")]


def _to_float_list(v: Any) -> List[float]:
    if isinstance(v, (list, tuple)):
        return [float(x) for x in v]
    s = str(v).strip()
    if not s:
        return []
    return [float(x) for x in s.split(",")]


def _to_str_list(v: Any) -> List[str]:
    if isinstance(v, (list, tuple)):
        return [str(x) for x in v]
    s = str(v).strip()
    if not s:
        return []
    return [x for x in s.split(",") if x]


_CONVERTERS = {
    "bool": _to_bool,
    "int": _to_int,
    "float": _to_float,
    "str": _to_str,
    "int_list": _to_int_list,
    "float_list": _to_float_list,
    "str_list": _to_str_list,
}

# (name, type, default, aliases) — alias lists follow the reference's generated table
# (ref: src/io/config_auto.cpp:10-210 GetAliasTable / config.h doc-comments).
PARAMS: List[Tuple[str, str, Any, Tuple[str, ...]]] = [
    # --- core ---
    ("task", "str", "train", ("task_type",)),
    ("objective", "str", "regression",
     ("objective_type", "app", "application", "loss")),
    ("boosting", "str", "gbdt", ("boosting_type", "boost")),
    ("data_sample_strategy", "str", "bagging", ()),
    ("data", "str", "", ("train", "train_data", "train_data_file", "data_filename")),
    ("valid", "str_list", [], ("test", "valid_data", "valid_data_file", "test_data",
                               "test_data_file", "valid_filenames")),
    ("num_iterations", "int", 100,
     ("num_iteration", "n_iter", "num_tree", "num_trees", "num_round", "num_rounds",
      "nrounds", "num_boost_round", "n_estimators", "max_iter")),
    ("learning_rate", "float", 0.1, ("shrinkage_rate", "eta")),
    ("num_leaves", "int", 31, ("num_leaf", "max_leaves", "max_leaf", "max_leaf_nodes")),
    ("tree_learner", "str", "serial", ("tree", "tree_type", "tree_learner_type")),
    # TPU-specific: tree growth engine.  "wave" splits every positive-gain
    # leaf per round (vectorized, TPU-fast); "leafwise" is the strict
    # one-split-at-a-time reference-parity engine; "auto" picks wave on TPU.
    ("tpu_growth_strategy", "str", "auto", ("growth_strategy",)),
    # wave engine tail shaping: once the leaf budget binds, spend at most
    # half of it per wave (best-gain-first), allocating tail leaves closer
    # to the leaf-wise order for a few extra cheap waves (PERF_NOTES.md)
    ("wave_tail_halving", "bool", False, ()),
    # wave engine quality mode (default): overgrow past num_leaves with
    # the cheap level-batched ladder, then prune back to num_leaves in
    # the reference's strict leaf-wise best-gain order simulated over the
    # overgrown tree's exact gains — recovers the leaf-wise tree exactly
    # whenever its splits lie within the overgrown region
    ("wave_prune", "bool", True, ()),
    ("wave_prune_overshoot", "float", 1.5, ()),
    ("wave_spike_reserve", "int", 0, ()),
    ("wave_spike_k", "int", 8, ()),
    ("num_threads", "int", 0, ("num_thread", "nthread", "nthreads", "n_jobs")),
    ("device_type", "str", "tpu", ("device",)),
    ("seed", "int", 0, ("random_seed", "random_state")),
    ("deterministic", "bool", False, ()),
    # --- learning control ---
    ("force_col_wise", "bool", False, ()),
    ("force_row_wise", "bool", False, ()),
    ("histogram_pool_size", "float", -1.0, ("hist_pool_size",)),
    ("max_depth", "int", -1, ()),
    ("min_data_in_leaf", "int", 20,
     ("min_data_per_leaf", "min_data", "min_child_samples", "min_samples_leaf")),
    ("min_sum_hessian_in_leaf", "float", 1e-3,
     ("min_sum_hessian_per_leaf", "min_sum_hessian", "min_hessian", "min_child_weight")),
    ("bagging_fraction", "float", 1.0, ("sub_row", "subsample", "bagging")),
    ("pos_bagging_fraction", "float", 1.0,
     ("pos_sub_row", "pos_subsample", "pos_bagging")),
    ("neg_bagging_fraction", "float", 1.0,
     ("neg_sub_row", "neg_subsample", "neg_bagging")),
    ("bagging_freq", "int", 0, ("subsample_freq",)),
    ("bagging_seed", "int", 3, ("bagging_fraction_seed",)),
    ("bagging_by_query", "bool", False, ()),
    ("feature_fraction", "float", 1.0, ("sub_feature", "colsample_bytree")),
    ("feature_fraction_bynode", "float", 1.0,
     ("sub_feature_bynode", "colsample_bynode")),
    ("feature_fraction_seed", "int", 2, ()),
    ("extra_trees", "bool", False, ("extra_tree",)),
    ("extra_seed", "int", 6, ()),
    ("early_stopping_round", "int", 0,
     ("early_stopping_rounds", "early_stopping", "n_iter_no_change")),
    ("early_stopping_min_delta", "float", 0.0, ()),
    ("first_metric_only", "bool", False, ()),
    ("max_delta_step", "float", 0.0, ("max_tree_output", "max_leaf_output")),
    ("lambda_l1", "float", 0.0, ("reg_alpha", "l1_regularization")),
    ("lambda_l2", "float", 0.0, ("reg_lambda", "lambda", "l2_regularization")),
    ("linear_lambda", "float", 0.0, ()),
    ("min_gain_to_split", "float", 0.0, ("min_split_gain",)),
    ("drop_rate", "float", 0.1, ("rate_drop",)),
    ("max_drop", "int", 50, ()),
    ("skip_drop", "float", 0.5, ()),
    ("xgboost_dart_mode", "bool", False, ()),
    ("uniform_drop", "bool", False, ()),
    ("drop_seed", "int", 4, ()),
    ("top_rate", "float", 0.2, ()),
    ("other_rate", "float", 0.1, ()),
    ("min_data_per_group", "int", 100, ()),
    ("max_cat_threshold", "int", 32, ()),
    ("cat_l2", "float", 10.0, ()),
    ("cat_smooth", "float", 10.0, ()),
    ("max_cat_to_onehot", "int", 4, ()),
    ("top_k", "int", 20, ("topk",)),
    ("monotone_constraints", "int_list", [],
     ("mc", "monotone_constraint", "monotonic_cst")),
    ("monotone_constraints_method", "str", "basic", ("monotone_constraining_method", "mc_method")),
    ("monotone_penalty", "float", 0.0, ("monotone_splits_penalty", "ms_penalty", "mc_penalty")),
    ("feature_contri", "float_list", [], ("feature_contrib", "fc", "fp", "feature_penalty")),
    ("forcedsplits_filename", "str", "", ("fs", "forced_splits_filename", "forced_splits_file", "forced_splits")),
    ("refit_decay_rate", "float", 0.9, ()),
    ("cegb_tradeoff", "float", 1.0, ()),
    ("cegb_penalty_split", "float", 0.0, ()),
    ("cegb_penalty_feature_lazy", "float_list", [], ()),
    ("cegb_penalty_feature_coupled", "float_list", [], ()),
    ("path_smooth", "float", 0.0, ()),
    ("interaction_constraints", "str", "", ()),
    ("verbosity", "int", 1, ("verbose",)),
    ("input_model", "str", "", ("model_input", "model_in")),
    ("output_model", "str", "LightGBM_model.txt", ("model_output", "model_out")),
    ("saved_feature_importance_type", "int", 0, ()),
    ("snapshot_freq", "int", -1, ("save_period",)),
    # --- reliability (docs/Reliability.md) ---
    ("checkpoint_dir", "str", "", ("ckpt_dir",)),
    ("checkpoint_freq", "int", 10, ("checkpoint_frequency", "ckpt_freq")),
    ("checkpoint_keep", "int", 3, ("checkpoint_keep_last",)),
    ("resume", "bool", True, ("resume_from_checkpoint",)),
    ("max_retries", "int", 0, ("num_retries",)),
    ("retry_backoff", "float", 1.0, ("retry_backoff_base",)),
    # non-finite sentinel: check train scores every N iterations (0 = off)
    ("nonfinite_check_freq", "int", 10, ("non_finite_check_freq",)),
    # stall watchdog (reliability/guard.py): trip when no boosting
    # iteration completes within max(stall_floor_s, stall_factor *
    # rolling-median iteration time); 0 disables the watchdog.  Active
    # only when metrics_dir (or a supervisor heartbeat file) gives the
    # diagnosis somewhere to land.
    ("stall_floor_s", "float", 120.0, ("stall_timeout_floor",)),
    ("stall_factor", "float", 20.0, ("stall_timeout_factor",)),
    # graceful degradation: after a hang-classified failure, relaunch
    # from the last checkpoint with the next risky knob disabled
    # (donation -> compile cache -> async_host_io -> device_eval)
    ("auto_degrade", "bool", False, ("auto_degradation",)),
    # preemption notice (SIGTERM) handling: grace budget for the
    # on-demand checkpoint captured before the signal is re-delivered;
    # 0 disables the checkpoint-on-demand (the handler only flushes)
    ("preempt_ckpt_grace_s", "float", 10.0, ("preemption_grace_s",)),
    # elastic recovery (distributed supervisor): a rank whose failures
    # persist across this many seconds of consecutive relaunch attempts
    # is classified permanently lost and the cluster shrinks around it
    ("elastic_rank_grace_s", "float", 60.0, ("rank_loss_grace_s",)),
    # smallest world size the elastic supervisor may shrink to; set it
    # to num_machines to disable shrink-to-fit entirely
    ("elastic_min_machines", "int", 1, ("min_machines",)),
    # --- observability (docs/Observability.md) ---
    # structured JSONL event log: one rank-tagged event per iteration
    ("metrics_dir", "str", "", ("telemetry_dir", "events_dir")),
    # size-based event-log rotation for multi-day runs: when the live
    # events-rank<r>.jsonl would exceed this many MiB it rolls to .1,
    # .2, ... (0 disables rotation)
    ("metrics_rotate_mb", "float", 0.0, ("metrics_rotate_megabytes",)),
    # bracket training with jax.profiler.start_trace/stop_trace for
    # TensorBoard device timelines
    ("profile_dir", "str", "", ("trace_dir",)),
    # compiled-HLO cost accounting (observability/costmodel.py): harvest
    # flops/bytes from every hot jitted entry and report measured
    # per-phase MFU + roofline classification in iteration events and
    # serving stats (active during metrics runs and daemon lifetimes)
    ("roofline", "bool", True, ("cost_analysis", "measured_mfu")),
    # bound of the always-on flight recorder's per-iteration ring
    # (observability/flightrec.py); the serve-trace ring is fixed
    ("flight_recorder_size", "int", 256, ("flight_recorder_capacity",)),
    # Prometheus GET /metrics listener (observability/prom.py):
    # -1 = off, 0 = ephemeral (logged), >0 = fixed port.  Served by
    # both the serving daemon and metrics-dir training runs
    ("metrics_port", "int", -1, ("prometheus_port",)),
    # --- host-boundary performance (docs/Performance.md) ---
    # persistent XLA compilation cache: repeat runs of the same config
    # skip the multi-minute ladder compile (cache-hit/miss counters land
    # in the metrics registry as compile_cache_hits / _misses)
    ("compile_cache_dir", "str", "", ("compilation_cache_dir",)),
    # drain JSONL event appends and checkpoint serialization through a
    # bounded single-worker writer thread so the training loop never
    # blocks on host I/O; false = synchronous writes (byte-identical
    # output either way)
    ("async_host_io", "bool", True, ("async_host_services",)),
    # in-jit eval metrics over the device score buffers (one packed D2H
    # per eval tick): "auto"/"true" = device forms when every configured
    # metric has one, "false" = host NumPy metric path
    ("device_eval", "str", "auto", ("device_eval_metrics",)),
    ("use_quantized_grad", "bool", False, ()),
    ("num_grad_quant_bins", "int", 4, ()),
    ("quant_train_renew_leaf", "bool", False, ()),
    ("stochastic_rounding", "bool", True, ()),
    # --- dataset ---
    ("linear_tree", "bool", False, ("linear_trees",)),
    ("max_bin", "int", 255, ("max_bins",)),
    ("max_bin_by_feature", "int_list", [], ()),
    ("min_data_in_bin", "int", 3, ()),
    ("bin_construct_sample_cnt", "int", 200000, ("subsample_for_bin",)),
    ("data_random_seed", "int", 1, ("data_seed",)),
    ("is_enable_sparse", "bool", True, ("is_sparse", "enable_sparse", "sparse")),
    ("enable_bundle", "bool", True, ("is_enable_bundle", "bundle")),
    ("max_conflict_rate", "float", 0.0, ()),
    ("use_missing", "bool", True, ()),
    ("zero_as_missing", "bool", False, ()),
    ("feature_pre_filter", "bool", True, ()),
    ("pre_partition", "bool", False, ("is_pre_partition",)),
    ("two_round", "bool", False, ("two_round_loading", "use_two_round_loading")),
    ("header", "bool", False, ("has_header",)),
    ("label_column", "str", "", ("label",)),
    ("weight_column", "str", "", ("weight",)),
    ("group_column", "str", "",
     ("group", "group_id", "query_column", "query", "query_id")),
    ("ignore_column", "str", "", ("ignore_feature", "blacklist")),
    ("categorical_feature", "str", "",
     ("cat_feature", "categorical_column", "cat_column", "categorical_features")),
    ("forcedbins_filename", "str", "", ()),
    ("save_binary", "bool", False, ("is_save_binary", "is_save_binary_file")),
    ("precise_float_parser", "bool", False, ()),
    ("parser_config_file", "str", "", ()),
    # --- predict ---
    # TPU-resident batch inference (docs/Inference.md): "auto" serves
    # float32 batches through the jitted device traversal when a TPU
    # backend is up, "true" forces it (any backend; float64 data still
    # falls back — the exactness argument needs float32 inputs), "false"
    # keeps every predict on the native/Python host paths
    ("device_predict", "str", "auto", ()),
    # smallest padded batch of the device predictor's bucket ladder;
    # buckets double from here so varying request sizes never recompile
    ("device_predict_min_bucket", "int", 4096, ("predict_min_bucket",)),
    # --- serving (docs/Serving.md) ---
    # models the serve task loads at startup: "name=path" entries (a
    # bare path serves under its file stem); task=serve also serves
    # input_model= as "default" when this list is empty
    ("serve_models", "str_list", [], ("serve_model",)),
    # request coalescing: after popping the first queued request the
    # dispatcher waits up to this long for more to merge into one padded
    # bucket dispatch — the explicit batching-efficiency vs p99 trade
    # (0 = dispatch whatever is already queued, lowest latency)
    ("serve_max_coalesce_wait_ms", "float", 2.0, ("coalesce_wait_ms",)),
    # bounded request queue: a saturated device backpressures submitters
    # instead of buffering unboundedly
    ("serve_queue_depth", "int", 1024, ()),
    # row cap per coalesced dispatch; also the top of the warmup bucket
    # ladder (every bucket up to this size compiles before a model entry
    # goes live, so steady-state serving never traces)
    ("serve_max_batch_rows", "int", 65536, ()),
    # compile the bucket ladder on the background load thread before the
    # hot swap; false = first requests pay the compiles (debug only)
    ("serve_warmup", "bool", True, ()),
    # TCP front end port for task=serve: -1 = in-process only,
    # 0 = ephemeral (logged), >0 = fixed port
    ("serve_port", "int", -1, ()),
    # bound on the SIGTERM drain: queued requests older than this are
    # failed so a preemption notice cannot stall the exit indefinitely
    ("serve_drain_timeout_s", "float", 10.0, ()),
    # flight-recorder request tracing: every Nth served request records
    # its enqueue->coalesce->dispatch->device-settle->respond stage
    # timestamps into the bounded trace ring (0 = off)
    ("serve_trace_sample", "int", 64, ("trace_sample",)),
    # per-request wait bound on the TCP front end when the caller sends
    # no deadline_ms of its own (was a hard-coded 60.0)
    ("serve_request_timeout_s", "float", 60.0, ("request_timeout_s",)),
    # --- serving fleet (docs/Serving.md fleet section) ---
    # replica daemons the serve-fleet task spawns behind the router
    ("serve_replicas", "int", 2, ("num_replicas",)),
    # relaunch budget PER replica: a crashed replica restarts with
    # exponential backoff until the budget runs out, then stays down
    ("serve_max_replica_restarts", "int", 3, ()),
    # fleet health-probe cadence (op=health: readiness + shed state)
    ("serve_health_interval_s", "float", 0.5, ()),
    # router retry budget per request: connection errors, timeouts and
    # sheds retry on a DIFFERENT replica up to this many times
    ("serve_retry_max", "int", 3, ()),
    # base of the router's exponential retry backoff (doubles per
    # retry, always bounded by the request's remaining deadline)
    ("serve_retry_backoff_ms", "float", 25.0, ()),
    # canary rollout: share of a model's traffic routed to the
    # candidate replica during publish (0 = plain rolling publish)
    ("serve_canary_pct", "float", 0.0, ("canary_pct",)),
    # observations per arm before the canary verdict is allowed
    ("serve_canary_min_samples", "int", 64, ()),
    # auto-rollback when the canary's mean score drifts more than this
    # many incumbent sigmas from the incumbent's mean
    ("serve_canary_max_divergence", "float", 4.0, ()),
    # auto-rollback when the canary arm's error rate exceeds this
    ("serve_canary_max_error_rate", "float", 0.1, ()),
    # task=serve writes {"port", "pid", "metrics_port", "models"} here
    # once every model is warmed and the front end is listening — the
    # fleet supervisor discovers replica ports through it
    ("serve_ready_file", "str", "", ()),
    # adaptive request coalescing (docs/Serving.md): "auto" derives the
    # per-batch wait window from an EWMA of request inter-arrival gaps —
    # it never exceeds the static serve_max_coalesce_wait_ms, and it
    # shrinks to 0 when arrivals are sparse (nobody else is coming
    # inside the window, so waiting only buys p50); "off" keeps the
    # static window unconditionally
    ("serve_adaptive_coalesce", "str", "off", ()),
    # Unix-domain-socket front end (docs/Serving.md): the same line-JSON
    # wire as serve_port, served on a filesystem socket — no TCP stack,
    # no port allocation, natural for same-host sidecars ("" = off)
    ("serve_uds_path", "str", "", ()),
    # --- online continual learning (docs/Online.md) ---
    # directory the train-and-serve task watches for chunk files
    # (chunk-<generation>.npz/.npy/.csv, atomically renamed into place)
    ("online_chunk_dir", "str", "", ("chunk_dir",)),
    # per-chunk update: "boost" = continue training
    # online_trees_per_chunk new trees via init_model, "refit" =
    # re-estimate the existing leaves on the fresh chunk, "auto" = refit
    # when the chunk has fewer rows than the ensemble has trees
    ("online_mode", "str", "auto", ()),
    ("online_trees_per_chunk", "int", 5, ()),
    # chunk-source poll cadence of the online loop
    ("online_poll_interval_s", "float", 0.25, ()),
    # name each generation publishes under in the serving registry/fleet
    ("online_model_name", "str", "online", ()),
    # model-freshness SLO (chunk arrival -> first request served by a
    # model that saw it): generations whose lag exceeds this feed the
    # burn-rate tracker (0 = freshness SLO off, lag still measured)
    ("online_max_lag_s", "float", 0.0, ()),
    # publish retry budget per generation: a failed publish keeps the
    # previous generation serving and retries with backoff
    ("online_publish_retry_max", "int", 3, ()),
    ("online_publish_backoff_ms", "float", 50.0, ()),
    # publish over the wire (op=publish) to a remote router/replica at
    # host:port instead of the task's own local serving daemon
    ("online_publish_addr", "str", "", ()),
    # stop after this many chunk generations (0 = run until SIGTERM)
    ("online_max_generations", "int", 0, ()),
    # exit cleanly when no new chunk arrives for this long (0 = never;
    # the drill/bench knob that makes a bounded run deterministic)
    ("online_idle_exit_s", "float", 0.0, ()),
    # --- fleet SLO tracking (docs/Observability.md "Fleet metrics &
    # SLO"): router-observed request outcomes feed a multi-window
    # burn-rate computation; both windows over threshold emits one
    # structured `slo_burn` event and raises the `fleet_slo_burning`
    # gauge until the burn clears ---
    # latency SLO: a routed request counts AGAINST the error budget
    # when it fails or takes longer than this (0 = SLO tracking off)
    ("serve_slo_p99_ms", "float", 0.0, ()),
    # error budget as a percentage of requests (1.0 = 99% of requests
    # must succeed within the latency SLO)
    ("serve_slo_error_pct", "float", 1.0, ()),
    # burn-rate windows: the fast window catches an acute breach, the
    # slow one filters out blips (both must burn to alert)
    ("serve_slo_fast_window_s", "float", 60.0, ()),
    ("serve_slo_slow_window_s", "float", 1800.0, ()),
    # burning when window_bad_fraction / error_budget exceeds this in
    # BOTH windows (1.0 = budget exhausted at the current rate)
    ("serve_slo_burn_threshold", "float", 1.0, ()),
    ("start_iteration_predict", "int", 0, ()),
    ("num_iteration_predict", "int", -1, ()),
    ("predict_raw_score", "bool", False, ("is_predict_raw_score", "predict_rawscore", "raw_score")),
    ("predict_leaf_index", "bool", False, ("is_predict_leaf_index", "leaf_index")),
    ("predict_contrib", "bool", False, ("is_predict_contrib", "contrib")),
    ("predict_disable_shape_check", "bool", False, ()),
    ("pred_early_stop", "bool", False, ()),
    ("pred_early_stop_freq", "int", 10, ()),
    ("pred_early_stop_margin", "float", 10.0, ()),
    ("output_result", "str", "LightGBM_predict_result.txt",
     ("predict_result", "prediction_result", "predict_name", "pred_name", "name_pred")),
    # --- convert ---
    ("convert_model_language", "str", "", ()),
    ("convert_model", "str", "gbdt_prediction.cpp", ("convert_model_file",)),
    # --- objective ---
    ("objective_seed", "int", 5, ()),
    ("num_class", "int", 1, ("num_classes",)),
    ("is_unbalance", "bool", False, ("unbalance", "unbalanced_sets")),
    ("scale_pos_weight", "float", 1.0, ()),
    ("sigmoid", "float", 1.0, ()),
    ("boost_from_average", "bool", True, ()),
    ("reg_sqrt", "bool", False, ()),
    ("alpha", "float", 0.9, ()),
    ("fair_c", "float", 1.0, ()),
    ("poisson_max_delta_step", "float", 0.7, ()),
    ("tweedie_variance_power", "float", 1.5, ()),
    ("lambdarank_truncation_level", "int", 30, ()),
    ("lambdarank_norm", "bool", True, ()),
    ("label_gain", "float_list", [], ()),
    ("lambdarank_position_bias_regularization", "float", 0.0, ()),
    # --- metric ---
    ("metric", "str_list", [], ("metrics", "metric_types")),
    ("metric_freq", "int", 1, ("output_freq",)),
    ("is_provide_training_metric", "bool", False,
     ("training_metric", "is_training_metric", "train_metric")),
    ("eval_at", "int_list", [1, 2, 3, 4, 5],
     ("ndcg_eval_at", "ndcg_at", "map_eval_at", "map_at")),
    ("multi_error_top_k", "int", 1, ()),
    ("auc_mu_weights", "float_list", [], ()),
    # --- network ---
    ("num_machines", "int", 1, ("num_machine",)),
    ("local_listen_port", "int", 12400, ("local_port", "port")),
    ("time_out", "int", 120, ()),
    ("machine_list_filename", "str", "",
     ("machine_list_file", "machine_list", "mlist")),
    ("machines", "str", "", ("workers", "nodes")),
    # --- device/tpu ---
    ("gpu_platform_id", "int", -1, ()),
    ("gpu_device_id", "int", -1, ()),
    ("gpu_use_dp", "bool", False, ()),
    ("num_gpu", "int", 1, ()),
    ("tpu_mesh_shape", "int_list", [], ()),  # TPU-native: data-parallel mesh shape
    ("tpu_donate_buffers", "bool", True, ()),  # TPU-native: donate score buffers in jit
]

_CANONICAL: Dict[str, Tuple[str, str]] = {}
for _name, _typ, _default, _aliases in PARAMS:
    _CANONICAL[_name] = (_name, _typ)
    for _a in _aliases:
        _CANONICAL[_a] = (_name, _typ)


def alias_table() -> Dict[str, str]:
    """alias -> canonical name map (ref: config_auto.cpp GetAliasTable)."""
    return {k: v[0] for k, v in _CANONICAL.items()}


def parameter_types() -> Dict[str, str]:
    return {name: typ for name, typ, _, _ in PARAMS}


def kv2map(args: List[str]) -> Dict[str, str]:
    """Parse 'key=value' strings; first occurrence wins
    (ref: config.cpp KV2Map + KeepFirstValues)."""
    out: Dict[str, str] = {}
    for arg in args:
        arg = arg.strip()
        if not arg or arg.startswith("#"):
            continue
        if "=" not in arg:
            log.warning(f"Unknown option: {arg}")
            continue
        k, v = arg.split("=", 1)
        k = k.strip()
        v = v.split("#", 1)[0].strip()
        if k in out:
            log.warning(f"{k} is set multiple times, keeping the first value")
            continue
        out[k] = v
    return out


_OBJECTIVE_ALIASES = {
    "regression": "regression", "regression_l2": "regression", "l2": "regression",
    "mean_squared_error": "regression", "mse": "regression", "l2_root": "regression",
    "root_mean_squared_error": "regression", "rmse": "regression",
    "regression_l1": "regression_l1", "l1": "regression_l1",
    "mean_absolute_error": "regression_l1", "mae": "regression_l1",
    "huber": "huber", "fair": "fair", "poisson": "poisson", "quantile": "quantile",
    "mape": "mape", "mean_absolute_percentage_error": "mape",
    "gamma": "gamma", "tweedie": "tweedie",
    "binary": "binary",
    "multiclass": "multiclass", "softmax": "multiclass",
    "multiclassova": "multiclassova", "multiclass_ova": "multiclassova",
    "ova": "multiclassova", "ovr": "multiclassova",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda", "xentlambda": "cross_entropy_lambda",
    "lambdarank": "lambdarank", "rank_xendcg": "rank_xendcg",
    "xendcg": "rank_xendcg", "xe_ndcg": "rank_xendcg", "xe_ndcg_mart": "rank_xendcg",
    "xendcg_mart": "rank_xendcg",
    "custom": "custom", "none": "custom", "null": "custom", "na": "custom",
}


def normalize_objective(name: str) -> str:
    name = name.strip().lower()
    if name in _OBJECTIVE_ALIASES:
        return _OBJECTIVE_ALIASES[name]
    log.fatal(f"Unknown objective: {name}")


class Config:
    """Flat typed config (ref: config.h:39 `struct Config`)."""

    def __init__(self, params: Optional[Union[Dict[str, Any], List[str], str]] = None,
                 **kwargs):
        for name, typ, default, _aliases in PARAMS:
            setattr(self, name, default() if callable(default)
                    else (list(default) if isinstance(default, list) else default))
        self.raw_params: Dict[str, Any] = {}
        merged: Dict[str, Any] = {}
        if isinstance(params, str):
            params = [p for p in params.replace("\n", " ").split(" ") if p]
        if isinstance(params, list):
            merged.update(kv2map(params))
        elif isinstance(params, dict):
            merged.update(params)
        merged.update(kwargs)
        self.update(merged)

    def update(self, params: Dict[str, Any]) -> None:
        seen_canonical: Dict[str, str] = {}
        for key, value in params.items():
            key_norm = key.strip().lower() if isinstance(key, str) else key
            if key_norm not in _CANONICAL:
                log.warning(f"Unknown parameter: {key}")
                self.raw_params[key] = value
                continue
            canonical, typ = _CANONICAL[key_norm]
            if value is None:
                continue
            if canonical in seen_canonical:
                log.warning(
                    f"{canonical} is set with {seen_canonical[canonical]} and {key}, "
                    f"current value ({getattr(self, canonical)}) is kept")
                continue
            seen_canonical[canonical] = key
            setattr(self, canonical, _CONVERTERS[typ](value))
            self.raw_params[canonical] = value
        self._post_process()

    def _post_process(self) -> None:
        log.set_verbosity(self.verbosity)
        obj = normalize_objective(self.objective) if self.objective else "custom"
        # objective-implied settings (ref: config.cpp Config::Set heuristics)
        if obj in ("multiclass", "multiclassova") and self.num_class < 2:
            log.fatal("num_class should be >=2 for multiclass objectives")
        if obj == "binary":
            self.num_class = 1
        self.objective = obj
        self.boosting = {"gbdt": "gbdt", "gbrt": "gbdt", "dart": "dart",
                         "rf": "rf", "random_forest": "rf", "goss": "goss",
                         }.get(self.boosting.strip().lower(), self.boosting)
        if self.boosting == "goss":
            # legacy alias: boosting=goss means gbdt + goss sampling (ref: boosting.cpp:26)
            self.boosting = "gbdt"
            self.data_sample_strategy = "goss"
        dp = str(self.device_predict).strip().lower()
        dp = {"1": "true", "yes": "true", "0": "false", "no": "false"}.get(dp, dp)
        if dp not in ("auto", "true", "false"):
            log.fatal(f"device_predict must be auto, true or false "
                      f"(got {self.device_predict!r})")
        self.device_predict = dp
        de = str(self.device_eval).strip().lower()
        de = {"1": "true", "yes": "true", "0": "false", "no": "false"}.get(de, de)
        if de not in ("auto", "true", "false"):
            log.fatal(f"device_eval must be auto, true or false "
                      f"(got {self.device_eval!r})")
        self.device_eval = de
        om = str(self.online_mode).strip().lower()
        if om not in ("auto", "boost", "refit"):
            log.fatal(f"online_mode must be auto, boost or refit "
                      f"(got {self.online_mode!r})")
        self.online_mode = om
        ac = str(self.serve_adaptive_coalesce).strip().lower()
        ac = {"1": "auto", "true": "auto", "yes": "auto",
              "0": "off", "false": "off", "no": "off"}.get(ac, ac)
        if ac not in ("auto", "off"):
            log.fatal(f"serve_adaptive_coalesce must be auto or off "
                      f"(got {self.serve_adaptive_coalesce!r})")
        self.serve_adaptive_coalesce = ac

    def to_dict(self) -> Dict[str, Any]:
        return {name: getattr(self, name) for name, _, _, _ in PARAMS}

    def changed_params(self) -> Dict[str, Any]:
        out = {}
        for name, typ, default, _ in PARAMS:
            cur = getattr(self, name)
            if cur != default:
                out[name] = cur
        return out


def read_config_file(path: str) -> Dict[str, str]:
    """Parse a CLI config file of `key = value` lines
    (ref: application.cpp:50-86 LoadParameters)."""
    lines = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            lines.append(line.replace(" = ", "=").replace("= ", "=").replace(" =", "="))
    return kv2map(lines)
