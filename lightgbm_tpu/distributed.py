"""Programmatic multi-machine training — the role the reference's Dask
integration plays in its Python stack (ref: python-package/lightgbm/
dask.py:414 _train: resolve workers -> build machine list -> run train on
every worker -> return the model), redesigned for the JAX runtime: the
"network" is jax.distributed + GSPMD collectives over the global device
mesh, not socket linkers.

Two entry points:

* `join_cluster(...)` — for users who already run one process per host
  (SLURM, k8s, GKE): resolves this worker's rank from a reference-style
  machine list (or explicit rank) and initializes jax.distributed; after
  it returns, plain `lgb.train(params with tree_learner=data)` shards
  over the global mesh.  This is the library form of the CLI's
  `machines=` launch (cli.py _maybe_init_distributed).

* `train_distributed(...)` — single-host convenience that SPAWNS
  num_machines local worker processes (the LocalCluster analogue),
  trains tree_learner=data across them, and returns the rank-0 model as
  a Booster.  Every worker loads the full host-side arrays (GSPMD owns
  the row sharding; workers' models are identical by construction —
  tests/test_multiprocess.py pins this).
"""

from __future__ import annotations

import json
import os
import pickle
import socket
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional

import numpy as np

from .utils import log


def resolve_rank(machines: List[str], local_listen_port: int) -> int:
    """Reference-style rank resolution: this host's (name/ip, port) found
    in the ordered machine list (ref: network.cpp Network::Init)."""
    local_names = {"localhost", "127.0.0.1", socket.gethostname()}
    try:
        local_names.update(socket.gethostbyname_ex(socket.gethostname())[2])
    except OSError:
        pass
    for i, e in enumerate(machines):
        host, sep, port = e.rpartition(":")
        if not sep or not port.isdigit():
            log.fatal(f"Malformed machines entry {e!r}; expected host:port")
        if host in local_names and int(port) == local_listen_port:
            return i
    log.fatal("This machine is not in the machine list; include host:port "
              "for every worker")


def join_cluster(machines, rank: Optional[int] = None,
                 local_listen_port: int = 12400) -> int:
    """Initialize jax.distributed from a reference-style machine list.
    Returns this process's rank.  Entry 0 is the coordinator."""
    if isinstance(machines, str):
        machines = [e.strip() for e in machines.split(",") if e.strip()]
    if rank is None:
        rank = resolve_rank(machines, local_listen_port)
    import jax
    jax.distributed.initialize(coordinator_address=machines[0],
                               num_processes=len(machines),
                               process_id=rank)
    log.info(f"Joined cluster as rank {rank}/{len(machines)} "
             f"(coordinator {machines[0]})")
    return rank


_WORKER_MAIN = r"""
import json, os, pickle, sys
spec = json.load(open(sys.argv[1]))
rank = int(sys.argv[2])
for k, v in spec.get("env", {}).items():
    os.environ[k] = v
import jax
if spec.get("force_cpu"):
    jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address=spec["coordinator"],
                           num_processes=spec["num_machines"],
                           process_id=rank)
sys.path.insert(0, spec["repo"])
import numpy as np
import lightgbm_tpu as lgb

with open(spec["data"], "rb") as f:
    payload = pickle.load(f)
params = dict(spec["params"])
params.setdefault("tree_learner", "data")
if isinstance(payload, str):
    ds = lgb.Dataset(payload, params=params)
else:
    ds = lgb.Dataset(payload["X"], label=payload.get("y"),
                     weight=payload.get("weight"),
                     group=payload.get("group"), params=params)
booster = lgb.train(params, ds,
                    num_boost_round=spec["num_boost_round"])
if rank == 0:
    booster.save_model(spec["model_out"])
print(f"worker {rank} done", flush=True)
"""


def train_distributed(params: Dict[str, Any], data, label=None, *,
                      weight=None, group=None, num_boost_round: int = 100,
                      num_machines: int = 2,
                      worker_env: Optional[Dict[str, str]] = None,
                      force_cpu: bool = False, timeout: int = 900):
    """Spawn `num_machines` local SPMD workers, train tree_learner=data
    across their combined devices, and return the trained Booster (all
    workers produce identical models; rank 0's is returned).

    `data` may be a file path (each worker loads it — pair with
    two_round for large files) or an array; arrays are shipped to
    workers through a temp file.  `worker_env` sets per-worker env vars
    (e.g. XLA_FLAGS for virtual-device tests); `force_cpu` pins the CPU
    backend inside the workers.
    """
    import shutil

    from .basic import Booster
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    work = tempfile.mkdtemp(prefix="lgbtpu_dist")
    try:
        return _train_distributed_in(
            work, port, params, data, label, weight, group,
            num_boost_round, num_machines, worker_env, force_cpu, timeout,
            Booster)
    finally:
        shutil.rmtree(work, ignore_errors=True)


def _train_distributed_in(work, port, params, data, label, weight, group,
                          num_boost_round, num_machines, worker_env,
                          force_cpu, timeout, Booster):
    data_path = os.path.join(work, "data.pkl")
    with open(data_path, "wb") as f:
        if isinstance(data, (str, os.PathLike)):
            pickle.dump(str(data), f)
        else:
            pickle.dump({"X": np.asarray(data),
                         "y": None if label is None else np.asarray(label),
                         "weight": (None if weight is None
                                    else np.asarray(weight)),
                         "group": (None if group is None
                                   else np.asarray(group))}, f)
    model_out = os.path.join(work, "model.txt")
    spec = {"coordinator": f"localhost:{port}",
            "num_machines": int(num_machines),
            "params": dict(params), "num_boost_round": int(num_boost_round),
            "data": data_path, "model_out": model_out,
            "repo": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "env": dict(worker_env or {}), "force_cpu": bool(force_cpu)}
    spec_path = os.path.join(work, "spec.json")
    with open(spec_path, "w") as f:
        json.dump(spec, f)
    script = os.path.join(work, "worker.py")
    with open(script, "w") as f:
        f.write(_WORKER_MAIN)
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    # worker output goes to files, not PIPEs: a chatty later-rank worker
    # filling the ~64KB pipe buffer while an earlier rank still trains
    # would block inside a collective and stall every rank until timeout
    log_paths = [os.path.join(work, f"worker_{r}.log")
                 for r in range(num_machines)]
    log_files = [open(p, "w") for p in log_paths]
    procs = [subprocess.Popen([sys.executable, script, spec_path, str(r)],
                              stdout=log_files[r],
                              stderr=subprocess.STDOUT, text=True, env=env)
             for r in range(num_machines)]
    logs = []
    ok = True
    deadline = time.monotonic() + timeout
    for r, p in enumerate(procs):
        try:
            p.wait(timeout=max(0.0, deadline - time.monotonic()))
            prefix = ""
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
            prefix = "(timeout)\n"
        log_files[r].close()
        with open(log_paths[r]) as f:
            logs.append(prefix + f.read())
        ok = ok and p.returncode == 0
    if not ok or not os.path.exists(model_out):
        log.fatal("distributed training failed:\n" + "\n".join(logs))
    return Booster(model_file=model_out)
