"""Programmatic multi-machine training — the role the reference's Dask
integration plays in its Python stack (ref: python-package/lightgbm/
dask.py:414 _train: resolve workers -> build machine list -> run train on
every worker -> return the model), redesigned for the JAX runtime: the
"network" is jax.distributed + GSPMD collectives over the global device
mesh, not socket linkers.

Two entry points:

* `join_cluster(...)` — for users who already run one process per host
  (SLURM, k8s, GKE): resolves this worker's rank from a reference-style
  machine list (or explicit rank) and initializes jax.distributed; after
  it returns, plain `lgb.train(params with tree_learner=data)` shards
  over the global mesh.  This is the library form of the CLI's
  `machines=` launch (cli.py _maybe_init_distributed).

* `train_distributed(...)` — single-host convenience that SPAWNS
  num_machines local worker processes (the LocalCluster analogue),
  trains tree_learner=data across them, and returns the rank-0 model as
  a Booster.  Every worker loads the full host-side arrays (GSPMD owns
  the row sharding; workers' models are identical by construction —
  tests/test_multiprocess.py pins this).
"""

from __future__ import annotations

import json
import os
import pickle
import socket
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional

import numpy as np

from .utils import log


def resolve_rank(machines: List[str], local_listen_port: int) -> int:
    """Reference-style rank resolution: this host's (name/ip, port) found
    in the ordered machine list (ref: network.cpp Network::Init)."""
    local_names = {"localhost", "127.0.0.1", socket.gethostname()}
    try:
        local_names.update(socket.gethostbyname_ex(socket.gethostname())[2])
    except OSError:
        pass
    for i, e in enumerate(machines):
        host, sep, port = e.rpartition(":")
        if not sep or not port.isdigit():
            log.fatal(f"Malformed machines entry {e!r}; expected host:port")
        if host in local_names and int(port) == local_listen_port:
            return i
    log.fatal("This machine is not in the machine list; include host:port "
              "for every worker")


def _wait_for_coordinator(address: str, timeout: float) -> None:
    """Pre-flight TCP probe of the coordinator before handing control to
    jax.distributed.initialize: this jaxlib's coordination client
    LOG(FATAL)s (hard process abort, no Python exception) when the
    coordinator never answers, so the only place to produce a clear
    diagnostic is BEFORE calling it.  Retries until `timeout` — workers
    may legitimately start before the coordinator is up."""
    host, sep, port = address.rpartition(":")
    if not sep or not port.isdigit():
        log.fatal(f"Malformed coordinator address {address!r}; expected "
                  "host:port (the first machine-list entry)")
    deadline = time.monotonic() + timeout
    last_err: Optional[Exception] = None
    while time.monotonic() < deadline:
        try:
            with socket.create_connection((host, int(port)), timeout=2):
                return
        except OSError as e:
            last_err = e
            time.sleep(0.5)
    log.fatal(
        f"Coordinator {address} is unreachable after {timeout:.0f}s "
        f"({last_err}). Check that the rank-0 process is running, that "
        "every worker uses the SAME machine list (entry 0 is the "
        "coordinator), and that the port is not blocked by a firewall.")


def join_cluster(machines, rank: Optional[int] = None,
                 local_listen_port: int = 12400,
                 initialize_timeout: Optional[float] = None) -> int:
    """Initialize jax.distributed from a reference-style machine list.
    Returns this process's rank.  Entry 0 is the coordinator.

    `initialize_timeout` bounds how long a worker waits for the
    coordinator (seconds; jax's default is 300).  On failure the error
    names the coordinator address and the usual causes instead of a bare
    gRPC traceback (or a hard process abort from the coordination
    client)."""
    if isinstance(machines, str):
        machines = [e.strip() for e in machines.split(",") if e.strip()]
    if rank is None:
        rank = resolve_rank(machines, local_listen_port)
    if rank != 0:
        _wait_for_coordinator(machines[0],
                              timeout=(initialize_timeout
                                       if initialize_timeout is not None
                                       else 60.0))
    import jax
    kwargs = {}
    if initialize_timeout is not None:
        kwargs["initialization_timeout"] = int(initialize_timeout)
    try:
        jax.distributed.initialize(coordinator_address=machines[0],
                                   num_processes=len(machines),
                                   process_id=rank, **kwargs)
    except TypeError:
        # older jax without initialization_timeout: join with the default
        jax.distributed.initialize(coordinator_address=machines[0],
                                   num_processes=len(machines),
                                   process_id=rank)
    except Exception as e:
        log.fatal(
            f"Could not join the training cluster as rank "
            f"{rank}/{len(machines)}: coordinator {machines[0]} is "
            f"unreachable ({type(e).__name__}: {e}). Check that the rank-0 "
            "process is running, that every worker uses the SAME machine "
            "list (entry 0 is the coordinator), and that the port is not "
            "blocked by a firewall.")
    log.info(f"Joined cluster as rank {rank}/{len(machines)} "
             f"(coordinator {machines[0]})")
    return rank


_WORKER_MAIN = r"""
import json, os, pickle, sys
spec = json.load(open(sys.argv[1]))
rank = int(sys.argv[2])
for k, v in spec.get("env", {}).items():
    os.environ[k] = v
# fault-injection context: which worker this is and which launch attempt
# (retried clusters bump the attempt so one-shot faults don't re-fire)
os.environ["LGBM_TPU_FAULT_SELF_RANK"] = str(rank)
os.environ["LGBM_TPU_FAULT_ATTEMPT"] = str(spec.get("attempt", 0))
os.environ["LGBM_TPU_WORLD_SIZE"] = str(spec["num_machines"])
# permanent-loss model (reliability/faults.py): a tombstoned (rank,
# world) refuses every same-world relaunch BEFORE joining the cluster,
# so the refusal is a fast clean exit the supervisor sees immediately —
# only an elastic shrink (different world size) gets past it
if spec.get("tombstone_dir"):
    os.environ["LGBM_TPU_TOMBSTONE_DIR"] = spec["tombstone_dir"]
    sys.path.insert(0, spec["repo"])
    from lightgbm_tpu.reliability import faults as _faults
    _faults.check_tombstone()
# stall detection (reliability/guard.py): the engine's RunGuard touches
# this file once per boosting iteration; the supervising parent polls
# its mtime to catch live-but-hung ranks, and the guard's stall
# diagnosis lands next to it when the run has no metrics_dir
if spec.get("heartbeat_dir"):
    os.makedirs(spec["heartbeat_dir"], exist_ok=True)
    os.environ["LGBM_TPU_HEARTBEAT_FILE"] = os.path.join(
        spec["heartbeat_dir"], f"heartbeat-rank{rank}")
    os.environ["LGBM_TPU_STALL_DIR"] = spec["heartbeat_dir"]
import jax
if spec.get("force_cpu"):
    jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address=spec["coordinator"],
                           num_processes=spec["num_machines"],
                           process_id=rank)
sys.path.insert(0, spec["repo"])
import numpy as np
import lightgbm_tpu as lgb
from lightgbm_tpu.observability import install_sigterm_flush
from lightgbm_tpu.reliability import faults
# kill -USR1 <pid>: on-demand all-thread stack dump from a live worker
faults.register_stack_dump_signal()
# a supervisor SIGTERM flushes queued events/checkpoints before exit
install_sigterm_flush()

with open(spec["data"], "rb") as f:
    payload = pickle.load(f)
params = dict(spec["params"])
params.setdefault("tree_learner", "data")
if spec.get("reshard"):
    # elastic relaunch: every rank derives the identical deterministic
    # row plan from the same three integers (parallel/elastic.py) — no
    # coordination, no rank-0 broadcast; printed so the worker log
    # records which rows this shard now owns
    from lightgbm_tpu.parallel import reshard_plan, rows_of
    rs = spec["reshard"]
    if rs.get("num_rows"):
        plan = reshard_plan(rs["old_n"], rs["new_n"], rs["num_rows"])
        assert plan.new_n == spec["num_machines"]
        print(f"worker {rank} reshard {plan.summary()} rows="
              f"{rows_of(rs['num_rows'], rs['new_n'], rank)}", flush=True)
if isinstance(payload, str):
    ds = lgb.Dataset(payload, params=params)
else:
    ds = lgb.Dataset(payload["X"], label=payload.get("y"),
                     weight=payload.get("weight"),
                     group=payload.get("group"), params=params)
ckpt_dir = spec.get("checkpoint_dir") or None
booster = lgb.train(params, ds,
                    num_boost_round=spec["num_boost_round"],
                    checkpoint_dir=ckpt_dir,
                    checkpoint_freq=spec.get("checkpoint_freq", 0),
                    resume=bool(ckpt_dir))
if rank == 0:
    booster.save_model(spec["model_out"])
print(f"worker {rank} done", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _ckpt_num_rows(checkpoint_dir: Optional[str]) -> Optional[int]:
    """Training-row count recorded in the checkpoint manifest — the one
    number the elastic reshard plan derives from, so the parent and
    every relaunched rank agree on it without communicating."""
    if not checkpoint_dir:
        return None
    try:
        from .reliability.checkpoint import MANIFEST
        with open(os.path.join(checkpoint_dir, MANIFEST)) as f:
            n = json.load(f).get("num_rows")
        return int(n) if n else None
    except (OSError, ValueError, TypeError):
        return None


def train_distributed(params: Dict[str, Any], data, label=None, *,
                      weight=None, group=None, num_boost_round: int = 100,
                      num_machines: int = 2,
                      worker_env: Optional[Dict[str, str]] = None,
                      force_cpu: bool = False, timeout: int = 900,
                      max_retries: int = 0, checkpoint_dir: Optional[str] = None,
                      checkpoint_freq: int = 0, retry_backoff: float = 1.0,
                      poll_interval: float = 0.25,
                      stall_timeout: Optional[float] = None):
    """Spawn `num_machines` local SPMD workers, train tree_learner=data
    across their combined devices, and return the trained Booster (all
    workers produce identical models; rank 0's is returned).

    `data` may be a file path (each worker loads it — pair with
    two_round for large files) or an array; arrays are shipped to
    workers through a temp file.  `worker_env` sets per-worker env vars
    (e.g. XLA_FLAGS for virtual-device tests); `force_cpu` pins the CPU
    backend inside the workers.

    Fault tolerance (docs/Reliability.md): workers are SUPERVISED — the
    first non-zero exit kills the remaining cluster immediately instead
    of letting the survivors stall in collectives until `timeout`.  With
    `max_retries > 0` the whole cluster is relaunched with exponential
    backoff (`retry_backoff * 2**attempt` seconds), resuming from the
    newest checkpoint; when retries are requested without an explicit
    `checkpoint_dir`, a per-run directory with checkpoint_freq=1 is used
    so a retry repeats at most one boosting iteration.
    """
    import shutil

    from .basic import Booster
    work = tempfile.mkdtemp(prefix="lgbtpu_dist")
    try:
        return _train_distributed_in(
            work, params, data, label, weight, group,
            num_boost_round, num_machines, worker_env, force_cpu, timeout,
            Booster, max_retries=max_retries, checkpoint_dir=checkpoint_dir,
            checkpoint_freq=checkpoint_freq, retry_backoff=retry_backoff,
            poll_interval=poll_interval, stall_timeout=stall_timeout)
    finally:
        shutil.rmtree(work, ignore_errors=True)


def _train_distributed_in(work, params, data, label, weight, group,
                          num_boost_round, num_machines, worker_env,
                          force_cpu, timeout, Booster, *, max_retries=0,
                          checkpoint_dir=None, checkpoint_freq=0,
                          retry_backoff=1.0, poll_interval=0.25,
                          stall_timeout=None):
    from .config import Config
    from .reliability.elastic import GIVE_UP, SHRINK, ElasticPolicy
    from .reliability.guard import (disabled_value, next_degradation,
                                    _LADDER_KNOBS)
    from .reliability.supervisor import supervise

    run_cfg = Config(dict(params))
    auto_degrade = bool(run_cfg.auto_degrade)
    if stall_timeout is None:
        # mtime-staleness backstop: must outlast the worker guard's
        # first-compile deadline, or the parent would kill a cluster
        # that is legitimately still compiling its device program
        stall_timeout = (max(10.0 * run_cfg.stall_floor_s, 600.0)
                         if run_cfg.stall_floor_s > 0 else 0.0)
    degraded_knobs: List[str] = []

    data_path = os.path.join(work, "data.pkl")
    with open(data_path, "wb") as f:
        if isinstance(data, (str, os.PathLike)):
            pickle.dump(str(data), f)
        else:
            pickle.dump({"X": np.asarray(data),
                         "y": None if label is None else np.asarray(label),
                         "weight": (None if weight is None
                                    else np.asarray(weight)),
                         "group": (None if group is None
                                   else np.asarray(group))}, f)
    model_out = os.path.join(work, "model.txt")
    if max_retries > 0 and not checkpoint_dir:
        # retries without checkpoints would replay the whole run; give the
        # workers a per-run checkpoint dir so a retry loses <= 1 iteration
        checkpoint_dir = os.path.join(work, "ckpt")
        if checkpoint_freq <= 0:
            checkpoint_freq = 1
    script = os.path.join(work, "worker.py")
    with open(script, "w") as f:
        f.write(_WORKER_MAIN)
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}

    # supervisor-side telemetry: with metrics_dir set, the workers write
    # their rank-tagged event logs and the parent adds a "supervisor"
    # stream recording cluster relaunches (docs/Observability.md)
    evt = None
    if params.get("metrics_dir"):
        from .observability import EventLogger
        try:
            evt = EventLogger(params["metrics_dir"], rank="supervisor")
        except OSError as e:
            log.warning(f"Could not open the supervisor event log in "
                        f"{params['metrics_dir']}: {e}")

    last_failure = "no workers launched"
    # the parent owns the degradation ladder in distributed mode: the
    # workers must not ALSO consume stall files and double-degrade
    worker_params = dict(params)
    worker_params["auto_degrade"] = False
    # elastic shrink-to-fit (docs/Reliability.md §Elastic recovery): a
    # permanently lost rank shrinks the next attempt's world size
    # instead of relaunching into the same dead host forever
    policy = ElasticPolicy(num_machines,
                           min_machines=run_cfg.elastic_min_machines,
                           rank_grace_s=run_cfg.elastic_rank_grace_s)
    reshard: Optional[Dict[str, Any]] = None
    for attempt in range(max_retries + 1):
        num_machines = policy.num_machines
        # fresh coordinator port per attempt: the previous coordinator
        # process is gone and its port may linger in TIME_WAIT
        port = _free_port()
        # per-attempt heartbeat dir: rank heartbeats + (when the run has
        # no metrics_dir) the stall diagnoses land here
        hb_dir = os.path.join(work, f"hb_a{attempt}")
        os.makedirs(hb_dir, exist_ok=True)
        spec = {"coordinator": f"localhost:{port}",
                "num_machines": int(num_machines),
                "params": dict(worker_params),
                "num_boost_round": int(num_boost_round),
                "data": data_path, "model_out": model_out,
                "repo": os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__))),
                "env": dict(worker_env or {}), "force_cpu": bool(force_cpu),
                "attempt": attempt, "checkpoint_dir": checkpoint_dir,
                "checkpoint_freq": int(checkpoint_freq),
                "heartbeat_dir": hb_dir,
                # tombstones OUTLIVE attempts (unlike heartbeats): a
                # permanently lost rank must refuse every same-world
                # relaunch, so they key on the stable work dir
                "tombstone_dir": work, "reshard": reshard}
        spec_path = os.path.join(work, f"spec_{attempt}.json")
        with open(spec_path, "w") as f:
            json.dump(spec, f)
        # worker output goes to files, not PIPEs: a chatty later-rank worker
        # filling the ~64KB pipe buffer while an earlier rank still trains
        # would block inside a collective and stall every rank until timeout
        log_paths = [os.path.join(work, f"worker_{r}_a{attempt}.log")
                     for r in range(num_machines)]
        log_files = [open(p, "w") for p in log_paths]
        try:
            procs = [subprocess.Popen(
                [sys.executable, script, spec_path, str(r)],
                stdout=log_files[r], stderr=subprocess.STDOUT, text=True,
                env=env) for r in range(num_machines)]
            result = supervise(
                procs, log_paths, timeout, poll_interval=poll_interval,
                heartbeats=[os.path.join(hb_dir, f"heartbeat-rank{r}")
                            for r in range(num_machines)],
                stall_timeout=stall_timeout,
                stall_dir=str(params.get("metrics_dir") or "") or hb_dir)
        finally:
            for lf in log_files:
                lf.close()
        if result.ok and os.path.exists(model_out):
            if attempt > 0:
                log.info(f"Distributed training succeeded on retry "
                         f"{attempt} (resumed from {checkpoint_dir})"
                         + (f" with degraded knobs {degraded_knobs}"
                            if degraded_knobs else "")
                         + (f" on a shrunken {num_machines}-rank cluster"
                            if policy.shrinks else ""))
                if evt is not None:
                    evt.emit("cluster_retry_succeeded", attempt=attempt,
                             degraded_knobs=degraded_knobs,
                             num_machines=num_machines,
                             elastic_shrinks=policy.shrinks)
            booster = Booster(model_file=model_out)
            booster.degraded_knobs = list(degraded_knobs)
            booster.elastic_shrinks = policy.shrinks
            booster.final_num_machines = num_machines
            return booster
        last_failure = result.describe() if not result.ok else \
            "all workers exited 0 but no model file was written"
        genuine = bool(result.failures) or result.timed_out
        classification = result.classification if genuine else "crash"
        if evt is not None:
            evt.emit("cluster_attempt_failed", attempt=attempt,
                     classification=classification,
                     failure=last_failure.splitlines()[0]
                     if last_failure else "")
        if attempt < max_retries:
            decision = policy.observe(result) if genuine else None
            if decision is not None and decision.action == GIVE_UP:
                log.fatal(
                    f"distributed training cannot continue: "
                    f"{decision.reason}\n{last_failure}")
            if decision is not None and decision.action == SHRINK:
                # shrink FIRST, then walk knobs (the ladder's hang
                # evidence was gathered on a topology that no longer
                # exists); the relaunch resumes from the checkpoint on
                # the surviving world size with a deterministic row plan
                # every rank recomputes identically
                from .reliability.elastic import plan_for_shrink
                old_n, new_n = num_machines, decision.num_machines
                plan = plan_for_shrink(old_n, new_n,
                                       _ckpt_num_rows(checkpoint_dir))
                reshard = {"old_n": old_n, "new_n": new_n,
                           "num_rows": plan.num_rows if plan else None}
                log.warning(
                    f"elastic_shrink: {decision.reason}; relaunching on "
                    f"{new_n} rank(s)"
                    + (f", reshard {plan.summary()}" if plan else "")
                    + (f", resuming from {checkpoint_dir}"
                       if checkpoint_dir else ""))
                if evt is not None:
                    evt.emit("elastic_shrink", old_num_machines=old_n,
                             new_num_machines=new_n,
                             lost_ranks=decision.lost_ranks,
                             attempt=attempt + 1,
                             reshard=plan.summary() if plan else None)
            elif result.hang and auto_degrade:
                # graceful degradation (reliability/guard.py): the
                # attempt HUNG, so the relaunch disables the next risky
                # knob instead of replaying the same configuration into
                # the same stall
                effective = {k: getattr(Config(dict(worker_params)), k)
                             for k in _LADDER_KNOBS}
                knob = next_degradation(effective, degraded_knobs)
                if knob is not None:
                    worker_params[knob] = disabled_value(knob)
                    degraded_knobs.append(knob)
                    log.warning(
                        f"auto_degrade: attempt {attempt} hung; "
                        f"relaunching with {knob} disabled "
                        f"(degraded so far: {degraded_knobs})")
                    if evt is not None:
                        evt.emit("degrade", knob=knob, attempt=attempt + 1,
                                 active=list(degraded_knobs))
                else:
                    log.warning("auto_degrade: ladder exhausted; "
                                "relaunching unchanged")
            elif classification == "preempt":
                log.warning(
                    f"attempt {attempt} was preempted (SIGTERM); the "
                    "workers saved on-demand checkpoints inside the grace "
                    "window — relaunching at the same world size"
                    + (f", resuming from {checkpoint_dir}"
                       if checkpoint_dir else ""))
            delay = retry_backoff * (2 ** attempt)
            if evt is not None:
                evt.emit("cluster_retry", next_attempt=attempt + 1,
                         delay_s=delay)
            log.warning(
                f"Distributed training attempt {attempt + 1}/"
                f"{max_retries + 1} failed:\n{last_failure}\n"
                f"Relaunching the cluster in {delay:.1f}s"
                + (f", resuming from checkpoints in {checkpoint_dir}"
                   if checkpoint_dir else ""))
            time.sleep(delay)
    log.fatal(f"distributed training failed after {max_retries + 1} "
              f"attempt(s):\n{last_failure}")
