"""Training entry points: train() and cv() (ref: python-package/lightgbm/engine.py)."""

from __future__ import annotations

import copy
import json
import os
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from .basic import Booster, Dataset
from .callback import (CallbackEnv, EarlyStopException, checkpoint,
                       early_stopping, log_evaluation, record_metrics)
from .config import Config
from .reliability import CheckpointManager, NonFiniteError
from .utils import atomic_write_text, log
from .utils.timer import global_timer


def _check_finite(booster: Booster, evals, iteration: int,
                  check_scores: bool) -> None:
    """Non-finite sentinel (reliability pillar 3): NaN gradients or eval
    scores mean every subsequent tree is garbage — fail fast instead of
    silently training on.  Both device-side flags (gradients and the
    FULL score buffer, not the old 256-row host sample) ride the eval
    tick's packed fetch when device metrics are on — the sentinel costs
    no extra host sync (docs/Performance.md)."""
    for name, metric, value, _ in evals:
        if value != value:  # NaN
            raise NonFiniteError(
                f"Evaluation metric {name} {metric} is NaN at iteration "
                f"{iteration + 1}. The model scores are corrupt — check the "
                "objective/labels for invalid values (or resume from a "
                "checkpoint). Set nonfinite_check_freq=0 to disable this "
                "sentinel.")
    if check_scores:
        if not booster._gbdt.gradients_finite():
            raise NonFiniteError(
                f"Non-finite gradients detected at (or before) iteration "
                f"{iteration + 1}: the split program masks NaN gains to "
                "zero, so every tree since the corruption is garbage. "
                "Check the objective/labels for invalid values (or resume "
                "from a checkpoint). Set nonfinite_check_freq=0 to disable "
                "this sentinel.")
        if not booster._gbdt.scores_finite():
            raise NonFiniteError(
                f"Non-finite training scores detected at iteration "
                f"{iteration + 1}: the gradients or tree outputs contain "
                "NaN/Inf. Check the objective, labels and learning_rate "
                "(or resume from a checkpoint). Set nonfinite_check_freq=0 "
                "to disable this sentinel.")


def train(params: Dict[str, Any], train_set: Dataset,
          num_boost_round: int = 100,
          valid_sets: Optional[List[Dataset]] = None,
          valid_names: Optional[List[str]] = None,
          feval=None, init_model: Optional[Union[str, Booster]] = None,
          keep_training_booster: bool = False,
          callbacks: Optional[List[Callable]] = None,
          fobj=None,
          checkpoint_dir: Optional[str] = None,
          checkpoint_freq: Optional[int] = None,
          resume: Optional[bool] = None,
          metrics_dir: Optional[str] = None) -> Booster:
    """ref: engine.py:66 train.

    Reliability extensions (docs/Reliability.md): `checkpoint_dir`
    enables periodic atomic checkpoints every `checkpoint_freq`
    iterations; with `resume` (default True) a run restarted with the
    same directory continues from the newest checkpoint instead of from
    zero, reproducing the uninterrupted run byte-for-byte.  All three
    can also be given as params (`checkpoint_dir=...` etc.).

    Observability extensions (docs/Observability.md): `metrics_dir`
    (also a param) appends a structured JSONL event per iteration —
    phase timings, eval results, tree stats, checkpoint/fault/retry
    events — to `<metrics_dir>/events-rank<r>.jsonl`; the `profile_dir`
    param brackets the run with jax.profiler.start_trace/stop_trace for
    TensorBoard device timelines."""
    params = dict(params or {})
    cfg = Config(params)
    # an explicitly-passed num_iterations (or alias) wins over the function
    # default, matching the reference alias resolution (ref: engine.py:145-152)
    if "num_iterations" in cfg.raw_params:
        num_boost_round = cfg.num_iterations

    if checkpoint_dir is None:
        checkpoint_dir = cfg.checkpoint_dir or None
    if checkpoint_freq is None:
        checkpoint_freq = cfg.checkpoint_freq
    if resume is None:
        resume = cfg.resume
    if metrics_dir is None:
        metrics_dir = cfg.metrics_dir or None

    # ---- degradation ladder (docs/Reliability.md) ----
    # a previous attempt that HUNG left a stall-rank<r>.json in
    # metrics_dir; with auto_degrade this restart consumes it, disables
    # the next risky knob (donation -> compile cache -> async_host_io ->
    # device_eval) and resumes from the checkpoint instead of re-hanging
    degrade_info = {"applied": [], "new": [], "stall": None}
    if cfg.auto_degrade:
        from .observability import process_rank
        from .reliability.guard import apply_auto_degrade
        degrade_info = apply_auto_degrade(cfg, params, metrics_dir,
                                          rank=process_rank())
    # async host services (docs/Performance.md): one bounded writer
    # thread drains event-log appends and checkpoint serialization so
    # the training loop never blocks on host I/O; `async_host_io=false`
    # restores synchronous writes (byte-identical output either way)
    writer = None
    if cfg.async_host_io and (checkpoint_dir or metrics_dir):
        from .observability import AsyncWriter
        writer = AsyncWriter()
    ckpt_mgr = (CheckpointManager(checkpoint_dir,
                                  keep_last=cfg.checkpoint_keep,
                                  params=params, writer=writer)
                if checkpoint_dir else None)
    if writer is not None or metrics_dir or ckpt_mgr is not None:
        # a supervisor SIGTERM must flush the queued events/checkpoints
        # before the process dies — the log tail is the diagnosis; with
        # a checkpoint dir the handler additionally saves an on-demand
        # checkpoint (preemption notice, docs/Reliability.md)
        from .observability import install_sigterm_flush
        install_sigterm_flush()
    # ---- preemption checkpoint-on-demand (docs/Reliability.md) ----
    # `_progress` is the handler's view of the run: the live booster,
    # the last COMPLETED iteration, and whether the main thread is
    # inside booster.update() right now — mid-update, model text /
    # scores / iteration are not a consistent triple, so the save is
    # deferred to the iteration boundary (`preempt_pending`)
    _progress: Dict[str, Any] = {"booster": None, "iteration": 0,
                                 "in_update": False,
                                 "preempt_pending": False}
    if ckpt_mgr is not None and cfg.preempt_ckpt_grace_s > 0:
        import time as _time

        from .observability import set_preemption_hook

        def _preempt_save():
            if _progress["in_update"]:
                # signal landed mid-update: queue it; the loop saves at
                # the iteration boundary and finishes the termination
                _progress["preempt_pending"] = True
                return False
            booster = _progress["booster"]
            it = int(_progress["iteration"])
            if booster is None or it <= 0:
                return True
            from .observability import emit_event, global_registry
            t0 = _time.monotonic()
            saved = False
            try:
                saved = ckpt_mgr.save_now(
                    booster, it, grace_s=cfg.preempt_ckpt_grace_s) is not None
            except OSError as e:
                log.warning(f"Preemption checkpoint at iteration {it} "
                            f"failed: {e}")
            if saved:
                global_registry.inc("preempt_ckpt_saved")
            emit_event("preempt", iteration=it, saved=saved,
                       elapsed_s=round(_time.monotonic() - t0, 3),
                       grace_s=cfg.preempt_ckpt_grace_s)
            return True

        set_preemption_hook(_preempt_save)

    # ---- observability setup (docs/Observability.md) ----
    profile_dir = cfg.profile_dir or None
    event_logger = None
    timer_was_enabled = global_timer.enabled
    cost_was_enabled = None
    metrics_srv = None
    if metrics_dir:
        from .observability import EventLogger, set_event_logger
        event_logger = EventLogger(metrics_dir,
                                   rotate_mb=cfg.metrics_rotate_mb,
                                   writer=writer)
        set_event_logger(event_logger)
        # the per-iteration phase breakdown diffs global_timer snapshots;
        # a metrics run therefore always times (restored afterwards)
        global_timer.enabled = True
        if cfg.roofline:
            # compiled-cost accounting: per-phase measured MFU +
            # roofline classification in the iteration events
            # (observability/costmodel.py; restored afterwards)
            from .observability import enable_cost_model
            cost_was_enabled = enable_cost_model(True)
        # flight recorder bound + SIGUSR2 on-demand dump: `kill -USR2`
        # writes <metrics_dir>/flight-rank<r>.json from the live run
        from .observability import process_rank as _prank
        from .observability.flightrec import flight_recorder
        from .reliability.faults import register_flight_dump_signal
        flight_recorder.resize(cfg.flight_recorder_size)
        register_flight_dump_signal(metrics_dir, rank=_prank())
        event_logger.emit("train_start", num_boost_round=num_boost_round,
                          params=cfg.changed_params())
        if degrade_info["new"]:
            # one `degrade` event per ladder step, right at the top of
            # the restarted run's log
            event_logger.emit("degrade", knobs=degrade_info["new"],
                              active=degrade_info["applied"],
                              stall_iteration=(degrade_info["stall"] or {})
                              .get("last_iteration"))
    if cfg.metrics_port >= 0:
        # the trainer exports the same registry snapshot the serving
        # daemon scrapes: counters, gauges, cost totals — GET /metrics
        # (observability/prom.py), shut down with the run
        from .observability import start_metrics_http
        metrics_srv = start_metrics_http(cfg.metrics_port)
    profiling = False
    if profile_dir:
        try:
            import jax
            jax.profiler.start_trace(profile_dir)
            profiling = True
            log.info(f"jax profiler trace started; timeline will be "
                     f"written to {profile_dir}")
        except Exception as e:  # profiling must never block training
            log.warning(f"Could not start the jax profiler trace in "
                        f"{profile_dir}: {e}")

    start_iteration = 0
    resume_ckpt = None
    if ckpt_mgr is not None and resume:
        ck = ckpt_mgr.resumable(params)
        if ck is not None:
            if init_model is not None:
                log.warning("Both init_model and a resumable checkpoint "
                            "were given; the checkpoint wins")
            init_model = ck.model_path
            start_iteration = min(ck.iteration, num_boost_round)
            resume_ckpt = ck
            log.info(f"Resuming from checkpoint at iteration {ck.iteration} "
                     f"({ck.model_path})")

    user_callbacks = list(callbacks or [])

    def _build_booster() -> Booster:
        booster = Booster(params=params, train_set=train_set)
        booster._train_in_valid = False
        valid_wrappers: List[Dataset] = []
        if valid_sets:
            for i, vs in enumerate(valid_sets):
                if vs is train_set:
                    booster._train_in_valid = True
                    continue
                name = (valid_names[i] if valid_names and i < len(valid_names)
                        else f"valid_{i}")
                booster.add_valid(vs, name)
                valid_wrappers.append(vs)

        if init_model is not None:
            # continued training (ref: engine.py init_model ->
            # _InnerPredictor; the previous model's trees are adopted and
            # its predictions seed the scores, so the returned booster
            # contains old + new trees)
            import os
            if isinstance(init_model, Booster):
                prev = init_model
            elif isinstance(init_model, (str, bytes, os.PathLike)):
                prev = Booster(model_file=os.fspath(init_model))
            else:
                log.fatal(f"Unknown init_model type: {type(init_model)}")

            def _raw_of(ds):
                d = getattr(ds, "data", None)
                if d is None or isinstance(d, (str, bytes)):
                    return None
                return d.values if hasattr(d, "values") else np.asarray(d)

            booster._gbdt.continue_from(
                prev._gbdt, train_raw=_raw_of(train_set),
                valid_raws=[_raw_of(vs) for vs in valid_wrappers])
            if resume_ckpt is not None:
                # checkpoint resume goes beyond init_model: restore the
                # EXACT score buffer and RNG streams so training continues
                # as if never interrupted (byte-identical final model)
                booster._gbdt.restore_train_state(resume_ckpt.load_state())
        return booster

    # ---- stall watchdog (reliability/guard.py) ----
    # active when there is somewhere for the diagnosis to land: the run's
    # metrics_dir, or the directory the distributed supervisor provided
    # (LGBM_TPU_STALL_DIR / the heartbeat file's directory)
    run_guard = None
    hb_path = os.environ.get("LGBM_TPU_HEARTBEAT_FILE") or None
    guard_dir = (metrics_dir or os.environ.get("LGBM_TPU_STALL_DIR")
                 or (os.path.dirname(hb_path) if hb_path else None))
    if cfg.stall_floor_s > 0 and guard_dir:
        from .observability import process_rank
        from .reliability.guard import RunGuard
        run_guard = RunGuard(
            guard_dir, rank=process_rank(),
            stall_floor_s=cfg.stall_floor_s,
            stall_factor=cfg.stall_factor,
            knobs={"tpu_donate_buffers": cfg.tpu_donate_buffers,
                   "async_host_io": cfg.async_host_io,
                   "compile_cache_dir": cfg.compile_cache_dir,
                   "device_eval": cfg.device_eval,
                   "sharded_wave": False,
                   "auto_degrade": cfg.auto_degrade,
                   "degraded_knobs": list(degrade_info["applied"])},
            heartbeat_path=hb_path, writer=writer)
        run_guard.start()

    rollbacks = 0
    try:
        while True:
            booster = _build_booster()
            _progress["booster"] = booster
            _progress["iteration"] = start_iteration
            if run_guard is not None:
                # the mesh (sharded wave) engages only once the booster
                # exists — refresh the risky-knob fingerprint
                gbdt = getattr(booster, "_gbdt", None)
                run_guard.update_knobs(
                    sharded_wave=bool(getattr(gbdt, "mesh", None)
                                      is not None),
                    growth_strategy=getattr(gbdt, "growth_strategy", None))
            callbacks = list(user_callbacks)
            if cfg.early_stopping_round > 0 and valid_sets:
                callbacks.append(early_stopping(
                    cfg.early_stopping_round, cfg.first_metric_only,
                    verbose=cfg.verbosity >= 1,
                    min_delta=cfg.early_stopping_min_delta))
            if cfg.verbosity >= 1 and cfg.metric_freq > 0:
                callbacks.append(log_evaluation(cfg.metric_freq))
            if ckpt_mgr is not None and checkpoint_freq \
                    and checkpoint_freq > 0:
                callbacks.append(checkpoint(checkpoint_dir,
                                            frequency=checkpoint_freq,
                                            manager=ckpt_mgr))
            if event_logger is not None:
                callbacks.append(record_metrics(logger=event_logger))
            callbacks_before = [cb for cb in callbacks
                                if getattr(cb, "before_iteration", False)]
            callbacks_after = [cb for cb in callbacks
                               if not getattr(cb, "before_iteration", False)]
            callbacks_before.sort(key=lambda cb: getattr(cb, "order", 0))
            callbacks_after.sort(key=lambda cb: getattr(cb, "order", 0))

            booster.best_iteration = -1
            train_has_metric = (bool(cfg.is_provide_training_metric)
                                or booster._train_in_valid)
            sentinel_freq = max(int(cfg.nonfinite_check_freq), 0)
            try:
                for i in range(start_iteration, num_boost_round):
                    env = CallbackEnv(model=booster, params=params,
                                      iteration=i,
                                      begin_iteration=start_iteration,
                                      end_iteration=num_boost_round,
                                      evaluation_result_list=[])
                    for cb in callbacks_before:
                        cb(env)
                    _progress["in_update"] = True
                    stopped = booster.update(fobj=fobj)
                    # the model/scores now describe iteration i+1 —
                    # publish that BEFORE clearing in_update so a
                    # preemption landing here saves a consistent triple
                    _progress["iteration"] = i + 1
                    _progress["in_update"] = False
                    if _progress["preempt_pending"]:
                        # a SIGTERM arrived mid-update; save at this
                        # boundary, then finish the termination the
                        # handler suppressed
                        _progress["preempt_pending"] = False
                        _preempt_save()
                        from .observability.hostio import finish_preemption
                        finish_preemption()
                    if stopped:
                        break
                    evals = []
                    with global_timer.scope("GBDT::eval"):
                        if train_has_metric:
                            evals.extend(booster.eval_train(feval))
                        evals.extend(booster.eval_valid(feval))
                    if sentinel_freq > 0:
                        if (i + 1) % sentinel_freq == 0:
                            # device-memory watchdog rides the sentinel
                            # tick: the HBM gauges land in the registry
                            # and thus in the next iteration event
                            from .observability import update_memory_gauges
                            update_memory_gauges()
                        # always check right before a checkpoint write, so
                        # a checkpoint never captures a silently-corrupt
                        # model (rollback would otherwise resume into the
                        # garbage)
                        will_ckpt = (ckpt_mgr is not None and checkpoint_freq
                                     and checkpoint_freq > 0
                                     and ((i + 1) % checkpoint_freq == 0
                                          or i + 1 == num_boost_round))
                        _check_finite(
                            booster, evals, i,
                            check_scores=((i + 1) % sentinel_freq == 0
                                          or will_ckpt))
                    env.evaluation_result_list = evals
                    for cb in callbacks_after:
                        cb(env)
                    if run_guard is not None:
                        run_guard.tick(i + 1)
                        if event_logger is None:
                            # guarded-but-unmetered runs (supervisor
                            # heartbeat dir, no metrics_dir) still leave
                            # a minimal trail for the stall diagnosis's
                            # flight tail; metrics runs get the rich
                            # record from record_metrics instead
                            from .observability.flightrec import \
                                flight_recorder
                            flight_recorder.record_iteration(
                                iteration=i + 1)
            except EarlyStopException as e:
                booster.best_iteration = e.best_iteration + 1
                for name, metric, value, _ in e.best_score:
                    booster.best_score.setdefault(name, {})[metric] = value
            except NonFiniteError as e:
                if writer is not None:
                    # an async checkpoint may still be in flight: land it
                    # before deciding where to roll back to
                    writer.flush()
                ck = (ckpt_mgr.resumable(params) if ckpt_mgr is not None
                      else None)
                if ck is None or rollbacks >= 1:
                    raise
                # roll back: rebuild from the last good checkpoint and
                # re-run the lost iterations (transient faults don't
                # recur; a persistent one raises on the second strike)
                rollbacks += 1
                from .observability import emit_event, global_registry
                global_registry.inc("rollback_retries")
                emit_event("rollback_retry", from_iteration=ck.iteration,
                           error=str(e))
                log.warning(f"{e}\nRolling back to the checkpoint at "
                            f"iteration {ck.iteration} and retrying once")
                init_model = ck.model_path
                start_iteration = min(ck.iteration, num_boost_round)
                resume_ckpt = ck
                continue
            break

        if booster.best_iteration < 0:
            evals = booster.eval_valid(feval)
            for name, metric, value, _ in evals:
                booster.best_score.setdefault(name, {})[metric] = value
        if event_logger is not None:
            if writer is not None:
                # land any in-flight checkpoint (and its event) first so
                # train_end stays the log's terminal record
                writer.flush()
            from .observability import global_registry
            event_logger.emit(
                "train_end", total_iterations=booster.current_iteration(),
                best_iteration=booster.best_iteration,
                # post-flush counter snapshot: per-iteration counters can
                # lag async checkpoint writes; this one is settled
                counters=global_registry.snapshot()["counters"])
        return booster
    finally:
        import sys as _sys
        if _sys.exc_info()[0] is not None and (metrics_dir or guard_dir):
            # crashing: dump the flight recorder synchronously next to
            # the logs so the supervisor's crash classification can
            # surface what the rank was doing (flight-rank<r>.json)
            from .observability import process_rank as _prank
            from .observability.flightrec import dump_flight_record
            dump_flight_record(metrics_dir or guard_dir, rank=_prank(),
                               reason="crash")
        if ckpt_mgr is not None and cfg.preempt_ckpt_grace_s > 0:
            from .observability import clear_preemption_hook
            clear_preemption_hook()
        if run_guard is not None:
            run_guard.stop()
        global_timer.enabled = timer_was_enabled
        if cost_was_enabled is not None:
            from .observability import enable_cost_model
            enable_cost_model(cost_was_enabled)
        if metrics_srv is not None:
            metrics_srv.shutdown()
        if profiling:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception as e:
                log.warning(f"jax profiler stop_trace failed: {e}")
        if writer is not None:
            # drain queued events/checkpoints on train end AND on error
            # (a crashed run's log stays complete up to the failure)
            writer.close()
        if event_logger is not None:
            from .observability import set_event_logger
            set_event_logger(None)
            event_logger.close()


class CVBooster:
    """Holds the per-fold boosters of cv() and redirects method calls to
    each, returning per-fold result lists (ref: python-package engine.py
    CVBooster).  Serializes as JSON of model texts + best_iteration."""

    def __init__(self, model_file=None):
        self.boosters: List[Booster] = []
        self.best_iteration = -1
        if model_file is not None:
            with open(model_file) as f:
                self._load(json.loads(f.read()))

    def _load(self, payload: Dict[str, Any]) -> None:
        self.best_iteration = payload["best_iteration"]
        self.boosters = [Booster(model_str=s) for s in payload["boosters"]]

    def model_from_string(self, model_str: str) -> "CVBooster":
        self._load(json.loads(model_str))
        return self

    def model_to_string(self, num_iteration=None, start_iteration=0,
                        importance_type="split") -> str:
        return json.dumps({
            "boosters": [b.model_to_string(num_iteration=num_iteration,
                                           start_iteration=start_iteration,
                                           importance_type=importance_type)
                         for b in self.boosters],
            "best_iteration": self.best_iteration})

    def save_model(self, filename, num_iteration=None, start_iteration=0,
                   importance_type="split") -> "CVBooster":
        atomic_write_text(filename,
                          self.model_to_string(num_iteration, start_iteration,
                                               importance_type))
        return self

    def __getattr__(self, name: str):
        if name.startswith("_") or name in ("boosters", "best_iteration"):
            raise AttributeError(name)

        def per_fold(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs)
                    for b in self.boosters]
        return per_fold


def cv(params: Dict[str, Any], train_set: Dataset, num_boost_round: int = 100,
       folds=None, nfold: int = 5, stratified: bool = True, shuffle: bool = True,
       metrics=None, feval=None, init_model=None,
       callbacks: Optional[List[Callable]] = None, seed: int = 0,
       eval_train_metric: bool = False,
       return_cvbooster: bool = False) -> Dict[str, List[float]]:
    """K-fold cross-validation (ref: engine.py:580 cv)."""
    params = dict(params or {})
    if metrics is not None:
        params["metric"] = metrics
    cfg = Config(params)
    if "num_iterations" in cfg.raw_params:
        num_boost_round = cfg.num_iterations
    core = train_set._core_or_construct()
    n = core.num_data
    label = np.asarray(core.metadata.label)
    rng = np.random.RandomState(seed)

    qb = core.metadata.query_boundaries
    if folds is None and qb is not None:
        # query-aware folds for ranking: whole queries go to one fold
        # (ref: python-package engine.py _make_n_folds group branch —
        # splitting inside a query would leak rank context across folds)
        qb = np.asarray(qb)
        nq = len(qb) - 1
        if nq < nfold:
            log.fatal(f"cv with ranking data needs >= nfold queries "
                      f"(got {nq} queries, nfold={nfold})")
        q_perm = np.arange(nq)
        if shuffle:
            rng.shuffle(q_perm)
        fold_of_q = np.empty(nq, np.int64)
        fold_of_q[q_perm] = np.arange(nq) % nfold
        row_fold = np.repeat(fold_of_q, np.diff(qb))
        folds = [(np.nonzero(row_fold != k)[0],
                  np.nonzero(row_fold == k)[0]) for k in range(nfold)]
    elif folds is None:
        idx = np.arange(n)
        if shuffle:
            rng.shuffle(idx)
        if stratified and cfg.objective in ("binary", "multiclass", "multiclassova"):
            order = np.argsort(label[idx], kind="stable")
            idx = idx[order]
            fold_of = np.arange(n) % nfold
            folds = [(idx[fold_of != k], idx[fold_of == k]) for k in range(nfold)]
        else:
            folds = [(np.concatenate([idx[:a], idx[b:]]), idx[a:b])
                     for a, b in ((k * n // nfold, (k + 1) * n // nfold)
                                  for k in range(nfold))]

    boosters = []
    histories: List[Dict[str, List[float]]] = []
    for train_idx, test_idx in folds:
        tr = train_set.subset(np.sort(train_idx))
        va = train_set.subset(np.sort(test_idx))
        from .callback import record_evaluation
        hist: Dict[str, Dict[str, List[float]]] = {}
        cbs = list(callbacks or []) + [record_evaluation(hist)]
        bst = train(params, tr, num_boost_round, valid_sets=[va],
                    valid_names=["valid"], feval=feval, callbacks=cbs)
        boosters.append(bst)
        histories.append(hist.get("valid", {}))

    out: Dict[str, List[float]] = {}
    for metric in (histories[0].keys() if histories else []):
        rounds = min(len(h.get(metric, [])) for h in histories)
        # one [nfold, rounds] materialization + vectorized reduction:
        # per-round np.mean/np.std over Python lists converted each fold
        # value individually — with device-scalar entries that was one
        # host/device ping-pong per (metric, round, fold) (the first
        # real finding of the ISSUE 3 no-host-sync sweep outside jit)
        vals = np.asarray([h.get(metric, [])[:rounds] for h in histories],
                          dtype=np.float64)
        out[f"valid {metric}-mean"] = vals.mean(axis=0).tolist()
        out[f"valid {metric}-stdv"] = vals.std(axis=0).tolist()
    if return_cvbooster:
        cvb = CVBooster()
        cvb.boosters = boosters
        cvb.best_iteration = max((b.best_iteration for b in boosters),
                                 default=-1)
        out["cvbooster"] = cvb
    return out
