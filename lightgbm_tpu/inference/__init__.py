"""TPU-resident batch inference (docs/Inference.md).

The first serving-side subsystem: a trained ensemble compiles to a jitted
tensor traversal (Hummingbird / RAPIDS-FIL style flat-node layout over XLA
gathers), with request batches padded to a bucket ladder so varying sizes
never recompile, and rows sharded over the `parallel/` mesh for offline
scoring.  `GBDT.predict` routes here behind the `device_predict` config
param; host semantics (missing values, categorical bitsets, multiclass,
average_output) are reproduced bit-identically in ROUTING for float32
inputs — see docs/Inference.md for the exactness argument and the
fallback matrix.
"""

from .pack import PackedEnsemble, pack_ensemble
from .predictor import DevicePredictor

__all__ = ["DevicePredictor", "PackedEnsemble", "pack_ensemble"]
