"""Flatten a trained tree ensemble into dense device arrays.

The serving-side twin of the training pack (boosting/gbdt.py _pack_tree):
Hummingbird (Nakandala et al., OSDI 2020) and RAPIDS FIL both showed that
tree-ensemble inference maps onto dense tensor ops once every tree is laid
out as flat node arrays — the traversal becomes a per-(row, tree) gather
chain instead of pointer chasing (ref: src/application/predictor.hpp keeps
the same flat layout for the host OpenMP predictor, native/predict.c here).

Layout: T trees are padded to a shared internal-node stride NI and leaf
stride NL, so node `i` of tree `t` lives at flat index `t * NI + i` in
every per-node array.  Child pointers keep the reference's `~leaf`
encoding (negative = bitwise-complemented leaf index, ref: tree.h:25).
Categorical splits index a single shared uint32 bitset table through
per-node (start, nwords) spans.

Exactness (docs/Inference.md): thresholds are float64 in the model but the
device compares in float32.  `bounds_to_f32_floor` (io/device_bin.py)
rounds each threshold DOWN to the nearest float32, which preserves
`v <= threshold` EXACTLY for every float32 `v` — so float32 inputs take
bit-identical routing to the float64 host predictor.  The same floor is
applied to the 1e-35 zero threshold of the missing-value rule.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional

import numpy as np

from ..io.binning import K_ZERO_THRESHOLD
from ..io.device_bin import bounds_to_f32_floor
from ..models.tree import K_CATEGORICAL_MASK, K_DEFAULT_LEFT_MASK

# float32 floor of the host's float64 zero threshold (meta.h:56): for a
# float32 |v|, `|v| <= 1e-35` in float64 iff `|v| <= ZERO_F32` in float32
ZERO_THRESHOLD_F32 = float(bounds_to_f32_floor(
    np.asarray([K_ZERO_THRESHOLD]))[0])

# categorical values at or past 2^31 cannot index an int32 bitset word;
# the host predictor routes them right too (the bitset is always shorter)
CAT_MAX_F32 = 2147483648.0


class PackedEnsemble(NamedTuple):
    """Host-side flat arrays; DevicePredictor puts them on device once."""
    split_feature: np.ndarray   # [T, NI] int32, ORIGINAL feature index
    threshold: np.ndarray       # [T, NI] float32 (floored from float64)
    missing_type: np.ndarray    # [T, NI] int32 (MISSING_NONE/ZERO/NAN)
    default_left: np.ndarray    # [T, NI] bool
    is_cat: np.ndarray          # [T, NI] bool
    left: np.ndarray            # [T, NI] int32 (~leaf encoding)
    right: np.ndarray           # [T, NI] int32
    leaf_value: np.ndarray      # [T, NL] float32 (shrinkage applied)
    cat_start: np.ndarray       # [T, NI] int32 into cat_words
    cat_nwords: np.ndarray      # [T, NI] int32
    cat_words: np.ndarray       # [W] uint32 shared bitset table
    num_trees: int
    node_stride: int            # NI
    leaf_stride: int            # NL
    max_depth: int              # traversal iterations to settle every row
    max_feature: int            # highest original feature index referenced


def _tree_depth(tree) -> int:
    """Longest root->leaf path length (decisions taken).  Walked from the
    child arrays instead of leaf_depth because text-loaded models
    (Tree.from_string) do not carry leaf_depth."""
    nl = tree.num_leaves
    if nl <= 1:
        return 1
    depth = 1
    stack = [(0, 1)]
    while stack:
        node, d = stack.pop()
        depth = max(depth, d)
        for child in (int(tree.left_child[node]), int(tree.right_child[node])):
            if child >= 0:
                stack.append((child, d + 1))
    return depth


def _host_fallback(reason: str):
    """One host-fallback decision of the inference layer, named by its
    docs/Inference.md fallback-matrix KEY (tools/check_fallback_docs.py
    syncs matrix and call sites both ways).  Returns None."""
    return None


def pack_ensemble(trees: List) -> Optional[PackedEnsemble]:
    """Pack a model slice; None when the slice cannot be served on device
    (linear-tree leaf models need per-leaf feature ridge evaluations)."""
    if any(getattr(t, "is_linear", False) for t in trees):
        return _host_fallback("linear-tree")
    T = len(trees)
    ni = max([max(t.num_leaves - 1, 1) for t in trees] or [1])
    nl = max([max(t.num_leaves, 1) for t in trees] or [1])
    sf = np.zeros((T, ni), np.int32)
    th = np.zeros((T, ni), np.float32)
    mt = np.zeros((T, ni), np.int32)
    dl = np.zeros((T, ni), bool)
    ic = np.zeros((T, ni), bool)
    lc = np.full((T, ni), -1, np.int32)   # ~0: route everything to leaf 0
    rc = np.full((T, ni), -1, np.int32)
    lv = np.zeros((T, nl), np.float32)
    cs = np.zeros((T, ni), np.int32)
    cn = np.zeros((T, ni), np.int32)
    words: List[np.ndarray] = []
    n_words = 0
    depth = 1
    for t, tree in enumerate(trees):
        n = max(tree.num_leaves - 1, 0)
        lv[t, :tree.num_leaves] = tree.leaf_value[:tree.num_leaves]
        if n == 0:
            continue  # stump: the prefilled ~0 children route to leaf 0
        dt = np.asarray(tree.decision_type[:n])
        sf[t, :n] = tree.split_feature[:n]
        th[t, :n] = bounds_to_f32_floor(tree.threshold[:n])
        mt[t, :n] = (dt.astype(np.int32) >> 2) & 3
        dl[t, :n] = (dt & K_DEFAULT_LEFT_MASK) != 0
        cat = (dt & K_CATEGORICAL_MASK) != 0
        ic[t, :n] = cat
        lc[t, :n] = tree.left_child[:n]
        rc[t, :n] = tree.right_child[:n]
        if cat.any():
            bounds = np.asarray(tree.cat_boundaries, np.int64)
            tw = np.asarray(tree.cat_threshold, np.uint32)
            for i in np.nonzero(cat)[0]:
                cat_idx = int(tree.threshold[i])  # threshold = cat set index
                start, end = int(bounds[cat_idx]), int(bounds[cat_idx + 1])
                cs[t, i] = n_words + start
                cn[t, i] = end - start
            words.append(tw)
            n_words += len(tw)
        depth = max(depth, _tree_depth(tree))
    cat_words = (np.concatenate(words).astype(np.uint32) if words
                 else np.zeros(1, np.uint32))
    return PackedEnsemble(
        split_feature=sf, threshold=th, missing_type=mt, default_left=dl,
        is_cat=ic, left=lc, right=rc, leaf_value=lv, cat_start=cs,
        cat_nwords=cn, cat_words=cat_words, num_trees=T, node_stride=ni,
        leaf_stride=nl, max_depth=depth,
        max_feature=int(sf.max()) if T else 0)
