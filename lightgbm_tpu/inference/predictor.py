"""DevicePredictor: TPU-resident batch inference over a packed ensemble.

Serving-side counterpart of the training engines: the trained model slice
is packed once (pack.py), placed on device once, and every predict call is
one jitted dispatch of the tensor traversal (traverse.py).

Shape discipline (the part that makes this servable): a jitted program is
specialized to its input SHAPES, so feeding raw request sizes would
recompile per distinct batch size — a multi-second stall the PR-2
RecompileDetector exists to catch.  Batches are instead padded up to a
small geometric ladder of bucket sizes (min_bucket * 2^k), one compiled
program per bucket; varying request sizes inside a bucket re-enter the
SAME trace.  Each bucket's entry is wrapped in its own RecompileDetector,
so the watchdog stays quiet in steady state and still fires if anything
else (dtype, feature count) destabilizes the signature.  The padded input
buffer is DONATED to the program, letting XLA reuse its pages for the
output instead of holding both live.

For offline scoring the row axis shards across chips through the existing
`parallel/` 1-D mesh: the traversal is row-wise embarrassingly parallel,
so GSPMD partitions it with zero collectives (the model arrays replicate,
exactly like the reference workers each holding the whole model).
"""

from __future__ import annotations

import warnings
from typing import List, Optional, Tuple

import numpy as np

from ..utils import log
from ..utils.timer import global_timer
from .pack import PackedEnsemble, pack_ensemble
from .traverse import (class_scores, class_scores_early_stop,
                       ensemble_leaf_ids)


def build_program(depth: int, num_class: int, average: bool, convert,
                  mode: str, es_freq: int = 0):
    """The bucket-entry program DevicePredictor jits: (x [B, F] f32,
    [margin f32 if es_freq > 0,] *pack arrays) -> scores/leaf ids.
    Module-level so the tpulint IR audit can abstractly trace the SAME
    program the serving dispatch compiles (lightgbm_tpu/_lint_entries.py)
    from exemplar shapes alone; DevicePredictor._program is the only
    runtime caller."""
    K = num_class

    if es_freq > 0:
        def run_es(x, margin, sf, th, mt, dl, ic, lc, rc, lv, cs, cn,
                   cw):
            leaf = ensemble_leaf_ids(x, sf, th, mt, dl, ic, lc, rc,
                                     cs, cn, cw, depth)
            scores = class_scores_early_stop(leaf, lv, K, es_freq,
                                             margin)
            if mode == "convert" and convert is not None:
                scores = convert(scores.T).T
            return scores
        return run_es

    def run(x, sf, th, mt, dl, ic, lc, rc, lv, cs, cn, cw):
        leaf = ensemble_leaf_ids(x, sf, th, mt, dl, ic, lc, rc,
                                 cs, cn, cw, depth)
        if mode == "leaf":
            return leaf
        scores = class_scores(leaf, lv, K, average)
        if mode == "convert" and convert is not None:
            # objectives convert in [K, n] layout (softmax over axis 0)
            scores = convert(scores.T).T
        return scores

    return run


class DevicePredictor:
    """Jitted ensemble predictor for one model slice.

    Parameters
    ----------
    trees : the model slice (host Tree objects, shrinkage applied)
    num_class : K — tree t scores class t % K
    average : RF output averaging (divide class sums by trees-per-class)
    convert : optional jittable score -> prediction map ([K, n] layout),
        fused into the device program (objective.convert_output)
    min_bucket : smallest padded batch; buckets double from here
    mesh : optional jax.sharding.Mesh — shard rows for offline scoring
    """

    def __init__(self, trees: List, num_class: int = 1,
                 average: bool = False, convert=None,
                 min_bucket: int = 4096, mesh=None):
        self.pack: Optional[PackedEnsemble] = pack_ensemble(trees)
        self.ok = self.pack is not None and self.pack.num_trees > 0
        self.num_class = max(int(num_class), 1)
        self.average = bool(average)
        self._convert = convert
        self._mesh = mesh
        self._min_bucket = max(int(min_bucket), 8)
        if mesh is not None:
            ndev = int(np.prod(mesh.devices.shape))
            # buckets must tile the mesh; doubling preserves divisibility
            self._min_bucket = max(
                self._min_bucket,
                ((self._min_bucket + ndev - 1) // ndev) * ndev)
        self._dev = None      # device copies of the pack arrays
        self._fns = {}        # (mode, bucket, F) -> RecompileDetector(jit)
        self._x_sharding = None
        # most recent accounted dispatch's compiled-cost delta (flops /
        # bytes / wall seconds / bucket) — the serving coalescer stamps
        # it onto the request's dispatch SPAN so a trace says where the
        # chip time went (docs/Observability.md "Distributed tracing");
        # lock-guarded: the serving dispatcher writes, any thread reads
        import threading
        self._dispatch_lock = threading.Lock()
        self._last_dispatch = None

    # ------------------------------------------------------------- device
    def _device_arrays(self):
        """Put the pack on device once (replicated over the mesh when
        sharding rows): 11 small transfers at first use, zero after."""
        if self._dev is None:
            import jax
            import jax.numpy as jnp
            p = self.pack
            arrs = (p.split_feature, p.threshold, p.missing_type,
                    p.default_left, p.is_cat, p.left, p.right,
                    p.leaf_value, p.cat_start, p.cat_nwords, p.cat_words)
            if self._mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P
                repl = NamedSharding(self._mesh, P())
                self._x_sharding = NamedSharding(
                    self._mesh, P(self._mesh.axis_names[0], None))
                self._dev = tuple(jax.device_put(a, repl) for a in arrs)
            else:
                self._dev = tuple(jnp.asarray(a) for a in arrs)
        return self._dev

    def bucket_rows(self, n: int) -> int:
        """Smallest ladder size >= n (docs/Inference.md Bucketing)."""
        b = self._min_bucket
        while b < n:
            b *= 2
        return b

    def num_traces(self, mode: str = "raw") -> int:
        """Distinct traced signatures across this predictor's compiled
        bucket entries (the recompile-watchdog parity tests assert this
        stays at one per touched bucket)."""
        return sum(fn.signatures_seen for (m, _, _), fn in self._fns.items()
                   if m == mode)

    def total_traces(self) -> int:
        """Distinct traced signatures across EVERY compiled entry (all
        modes, buckets, feature counts) — the serving registry's
        `serve_recompiles` accounting reads this before and after the
        warmup ladder."""
        return sum(fn.signatures_seen for fn in self._fns.values())

    def release_device(self) -> None:
        """Drop the device copies of the pack and every compiled entry so
        an evicted serving model frees its buffers; the predictor can be
        re-armed by the next predict (a fresh put + compile)."""
        self._dev = None
        self._fns = {}

    # ------------------------------------------------------------ program
    def _program(self, mode: str, es_freq: int = 0):
        return build_program(self.pack.max_depth, self.num_class,
                             self.average, self._convert, mode, es_freq)

    def _fn_for(self, mode: str, bucket: int, F: int, es_freq: int = 0):
        mode_key = f"{mode}+es{es_freq}" if es_freq > 0 else mode
        key = (mode_key, bucket, F)
        fn = self._fns.get(key)
        if fn is None:
            import jax
            from ..observability import RecompileDetector
            jitted = jax.jit(self._program(mode, es_freq),
                             donate_argnums=(0,))
            fn = RecompileDetector(
                jitted, f"device_predict[{mode_key}@{bucket}]")
            self._fns[key] = fn
        return fn

    # ------------------------------------------------------------ predict
    def _run(self, X: np.ndarray, mode: str,
             early_stop: Optional[Tuple[int, float]] = None,
             account: bool = True):
        """One padded-bucket dispatch.  With the cost model enabled and
        `account` (false for warmup compiles), the dispatch's compiled
        flops/bytes and wall seconds accumulate into the registry
        (`device_predict_flops` / `_bytes` / `_s`) — flop and second
        measured at the SAME site, so the serving roofline never mixes
        warmup work into serving time."""
        import time as _time

        import jax
        X = np.ascontiguousarray(X, np.float32)
        if X.ndim == 1:
            X = X[None, :]
        n, F = X.shape
        if self.pack.max_feature >= F:
            log.fatal(f"The model references feature index "
                      f"{self.pack.max_feature} but the data has only "
                      f"{F} columns")
        es_freq = 0
        extra = ()
        if early_stop is not None and mode != "leaf" and not self.average:
            # early stopping with output averaging is a no-op host-side
            # too (gbdt.py use_es); the margin rides as a traced scalar
            # so threshold changes never re-trace
            es_freq = max(int(early_stop[0]), 0)
            if es_freq > 0:
                extra = (np.float32(early_stop[1]),)
        bucket = self.bucket_rows(n)
        if bucket != n:
            xp = np.zeros((bucket, F), np.float32)
            xp[:n] = X
        else:
            xp = X
        xd = jax.device_put(xp, self._x_sharding)
        from ..observability.costmodel import global_cost_model
        t0 = (_time.perf_counter()
              if account and global_cost_model.enabled else None)
        with warnings.catch_warnings():
            # CPU XLA cannot alias the donated [bucket, F] input into the
            # differently-shaped output and warns at compile; on TPU the
            # donation frees the input pages for scratch, which is the point
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            with global_timer.scope("DevicePredictor::dispatch"):
                fn = self._fn_for(mode, bucket, F, es_freq)
                out = fn(xd, *extra, *self._device_arrays())
                # when timing, settle here so dispatch vs device time
                # split into ::dispatch / ::dispatch::device scopes
                out = global_timer.block(out)
        host = np.asarray(out)
        if t0 is not None:
            # host materialization above settled the device, so the
            # elapsed wall covers pad + H2D + program + D2H of exactly
            # this dispatch; the per-call cost is the harvested compiled
            # analysis of the bucket entry just invoked
            dt = _time.perf_counter() - t0
            cost = global_cost_model.per_call(fn._name)
            from ..observability.registry import global_registry
            if cost is not None:
                global_registry.inc("device_predict_flops", cost[0])
                global_registry.inc("device_predict_bytes", cost[1])
            global_registry.inc("device_predict_s", dt)
            global_registry.inc("device_predict_dispatches")
            with self._dispatch_lock:
                self._last_dispatch = {
                    "flops": cost[0] if cost is not None else None,
                    "bytes": cost[1] if cost is not None else None,
                    "dispatch_s": round(dt, 6),
                    "bucket": int(bucket),
                }
        return host[:n], bucket

    def last_dispatch_info(self):
        """The most recent accounted dispatch's cost-model delta
        (`{flops, bytes, dispatch_s, bucket}`), or None before any
        accounted dispatch / with the cost model off — the serving
        trace layer's dispatch-span attributes."""
        with self._dispatch_lock:
            info = self._last_dispatch
            return dict(info) if info is not None else None

    def warmup(self, num_features: int, max_rows: int,
               modes=("convert", "raw"),
               early_stop: Optional[Tuple[int, float]] = None) -> int:
        """Compile the whole bucket ladder for `num_features`-wide inputs
        up through the bucket covering `max_rows` — the serving
        registry runs this on a background thread BEFORE a model entry
        goes live, so the first real request never pays a compile.
        Returns the number of traced signatures."""
        if not self.ok:
            return 0
        b = self._min_bucket
        while True:
            x = np.zeros((b, num_features), np.float32)
            for mode in modes:
                # account=False: warmup compiles must not pollute the
                # serving roofline's flop/second ledger
                self._run(x, mode, early_stop=early_stop, account=False)
            if b >= max_rows:
                break
            b *= 2
        return self.total_traces()

    def predict_leaf(self, X: np.ndarray) -> np.ndarray:
        """[n, T] int32 leaf indices — bit-identical to the native
        predictor's routing for float32 inputs."""
        return self._run(X, "leaf")[0]

    def predict_raw(self, X: np.ndarray,
                    early_stop: Optional[Tuple[int, float]] = None
                    ) -> np.ndarray:
        """Raw scores [n] (K == 1) or [n, K]; float32 accumulation of the
        float64 leaf values (routing exact; see docs/Inference.md).
        `early_stop=(freq, margin)` runs the masked accumulation scan
        (prediction early stopping, traverse.py)."""
        out, _ = self._run(X, "raw", early_stop=early_stop)
        return out[:, 0] if self.num_class == 1 else out

    def predict(self, X: np.ndarray,
                early_stop: Optional[Tuple[int, float]] = None
                ) -> np.ndarray:
        """Converted predictions with the objective's convert_output fused
        on device (raw scores when no converter was given)."""
        mode = "convert" if self._convert is not None else "raw"
        out, _ = self._run(X, mode, early_stop=early_stop)
        return out[:, 0] if self.num_class == 1 else out
