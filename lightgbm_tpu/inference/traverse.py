"""Jitted tensor traversal of a packed ensemble: all [rows x trees] at once.

One compiled program evaluates every tree for every row in lock-step,
`max_depth` iterations of

    node = where(x[:, feat[node]] <= thr[node], left[node], right[node])

with the reference's missing-value and categorical-bitset semantics folded
into the `where` (ref: tree.h:335 NumericalDecision, :372
CategoricalDecision; native/predict.c get_leaf_node is the host mirror of
exactly this decision).  Rows that reach a leaf early park on the negative
`~leaf` child pointer and stop moving; after max_depth steps every lane
holds a leaf.  Leaf values are gathered, summed per class and (optionally)
the objective's convert_output is applied — all in one XLA program, so a
predict call is a single device dispatch.

All arrays are EXPLICIT arguments (never closed-over constants): a jit
that embeds the model as a constant degrades every later dispatch on the
remote-TPU runtime (see boosting/gbdt.py init's gradient-program note).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..io.binning import MISSING_NAN, MISSING_ZERO
from .pack import CAT_MAX_F32, ZERO_THRESHOLD_F32


def ensemble_leaf_ids(x, split_feature, threshold, missing_type,
                      default_left, is_cat, left, right, cat_start,
                      cat_nwords, cat_words, depth: int):
    """x [B, F] float32, per-node arrays [T, NI] -> leaf ids [B, T] int32.

    Bit-identical to the host routing for float32 inputs: thresholds are
    pre-floored to float32 (pack.py), so every comparison agrees with the
    float64 host comparison on float32 values.
    """
    T, NI = split_feature.shape
    base = (jnp.arange(T, dtype=jnp.int32) * jnp.int32(NI))[None, :]
    sf = split_feature.reshape(-1)
    th = threshold.reshape(-1)
    mt = missing_type.reshape(-1)
    dl = default_left.reshape(-1)
    ic = is_cat.reshape(-1)
    lc = left.reshape(-1)
    rc = right.reshape(-1)
    cs = cat_start.reshape(-1)
    cn = cat_nwords.reshape(-1)
    nwords_total = cat_words.shape[0]

    def step(_, node):
        g = jnp.maximum(node, 0) + base          # [B, T] flat node index
        f = jnp.take(sf, g, mode="clip")
        v = jnp.take_along_axis(x, f, axis=1, mode="clip")
        nan = jnp.isnan(v)
        m = jnp.take(mt, g, mode="clip")
        # numerical decision (tree.h:335): NaN under non-NaN missing
        # handling is treated as 0.0 before the zero test
        fz = jnp.where(nan & (m != MISSING_NAN), jnp.float32(0), v)
        is_zero = jnp.abs(fz) <= jnp.float32(ZERO_THRESHOLD_F32)
        take_default = (((m == MISSING_ZERO) & is_zero)
                        | ((m == MISSING_NAN) & nan))
        num_left = jnp.where(take_default, jnp.take(dl, g, mode="clip"),
                             fz <= jnp.take(th, g, mode="clip"))
        # categorical decision (tree.h:372): NaN / negative / huge go
        # right; v truncates toward zero ((-1, 0) -> category 0)
        ok = (~nan) & (v > jnp.float32(-1.0)) & (v < jnp.float32(CAT_MAX_F32))
        vi = jnp.where(ok, v, jnp.float32(0)).astype(jnp.int32)
        word = vi >> jnp.int32(5)
        inset = ok & (word < jnp.take(cn, g, mode="clip"))
        widx = jnp.clip(jnp.take(cs, g, mode="clip") + word, 0,
                        nwords_total - 1)
        bit = (jnp.take(cat_words, widx, mode="clip")
               >> (vi & jnp.int32(31)).astype(jnp.uint32)) & jnp.uint32(1)
        cat_left = inset & (bit > 0)
        go_left = jnp.where(jnp.take(ic, g, mode="clip"), cat_left, num_left)
        nxt = jnp.where(go_left, jnp.take(lc, g, mode="clip"),
                        jnp.take(rc, g, mode="clip"))
        # parked lanes (already on a leaf) keep their ~leaf pointer
        return jnp.where(node >= 0, nxt, node)

    node = jnp.zeros(x.shape[:1] + (T,), jnp.int32)
    node = jax.lax.fori_loop(0, depth, step, node, unroll=False)
    return jnp.invert(node)


def _leaf_values(leaf, leaf_value):
    """Leaf ids [B, T] + values [T, NL] -> per-tree contributions [B, T]."""
    T, NL = leaf_value.shape
    flat = leaf_value.reshape(-1)
    g = leaf + (jnp.arange(T, dtype=jnp.int32) * jnp.int32(NL))[None, :]
    return jnp.take(flat, g, mode="clip")


def class_scores(leaf, leaf_value, num_class: int, average: bool):
    """Leaf ids [B, T] + values [T, NL] -> raw scores [B, K] (tree t
    belongs to class t % K; ref: predict.c lgbt_predict_batch)."""
    vals = _leaf_values(leaf, leaf_value)            # [B, T]
    B = vals.shape[0]
    T = leaf_value.shape[0]
    iters = T // num_class if num_class else 0
    scores = vals.reshape(B, iters, num_class).sum(axis=1)
    if average and iters > 0:
        scores = scores / jnp.float32(iters)         # gbdt_prediction.cpp:57
    return scores


def class_scores_early_stop(leaf, leaf_value, num_class: int, freq: int,
                            margin):
    """Raw scores with prediction early stopping as a masked accumulation
    scan (ref: prediction_early_stop.cpp; gbdt.py _predict_raw_impl is
    the host mirror).

    The traversal already settled every (row, tree) leaf in one pass —
    on a vector machine there is nothing to skip — but early stopping
    CHANGES THE ANSWER: a row whose margin clears the threshold at a
    round check keeps its partial sum and ignores all later trees.  So
    the accumulation replays the host's sequential semantics as a
    lax.scan over iterations: before adding iteration i (i > 0, i %
    freq == 0) the margin of the running sum is tested — binary margin
    = 2|score| (ref: CreateBinaryPredictionEarlyStopInstance),
    multiclass = top1 - top2 (CreateMulticlassPredictionEarlyStopInstance)
    — and rows past it stop accumulating via a per-row done mask.

    `freq` is static (it shapes the check pattern); `margin` is a traced
    f32 scalar so sweeping thresholds never re-traces the program.
    """
    vals = _leaf_values(leaf, leaf_value)            # [B, T]
    B = vals.shape[0]
    T = leaf_value.shape[0]
    K = max(num_class, 1)
    iters = T // K
    vals = jnp.moveaxis(vals.reshape(B, iters, K), 1, 0)  # [iters, B, K]

    def body(carry, xs):
        acc, done = carry
        v_i, i = xs
        if K == 1:
            m = jnp.float32(2.0) * jnp.abs(acc[:, 0])
        else:
            top2 = jax.lax.top_k(acc, 2)[0]
            m = top2[:, 0] - top2[:, 1]
        check = (i > jnp.int32(0)) & (i % jnp.int32(freq) == jnp.int32(0))
        done = done | (check & (m > margin))
        acc = acc + jnp.where(done[:, None], jnp.float32(0), v_i)
        return (acc, done), None

    acc0 = jnp.zeros((B, K), jnp.float32)
    done0 = jnp.zeros((B,), jnp.bool_)
    (acc, _), _ = jax.lax.scan(
        body, (acc0, done0),
        (vals, jnp.arange(iters, dtype=jnp.int32)))
    return acc
