from .binning import BinMapper
from .dataset import Dataset, Metadata, load_dataset_from_file
from .parser import parse_file

__all__ = ["BinMapper", "Dataset", "Metadata", "load_dataset_from_file", "parse_file"]
