"""Feature binning: value -> bin mapping built from sampled values.

Behavioral parity with the reference's BinMapper (ref: src/io/bin.cpp:78-506,
include/LightGBM/bin.h:84-258,611-647): GreedyFindBin, FindBinWithZeroAsOneBin,
missing handling (None/Zero/NaN), categorical count-sorted bins, trivial-feature
pre-filtering.  Host-side NumPy — binning runs once at dataset construction; the
resulting integer codes are what live on TPU.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..utils import log

K_ZERO_THRESHOLD = 1e-35  # ref: include/LightGBM/meta.h:56
K_SPARSE_THRESHOLD = 0.8  # ref: include/LightGBM/bin.h kSparseThreshold

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2

BIN_NUMERICAL = 0
BIN_CATEGORICAL = 1

_MISSING_TYPE_STR = {MISSING_NONE: "none", MISSING_ZERO: "zero", MISSING_NAN: "nan"}
_MISSING_TYPE_FROM_STR = {v: k for k, v in _MISSING_TYPE_STR.items()}


def _next_after_up(a: float) -> float:
    return math.nextafter(a, math.inf)


def _double_equal_ordered(a: float, b: float) -> bool:
    # ref: utils/common.h:845 CheckDoubleEqualOrdered
    return b <= math.nextafter(a, math.inf)


def greedy_find_bin(distinct_values: Sequence[float], counts: Sequence[int],
                    max_bin: int, total_cnt: int, min_data_in_bin: int) -> List[float]:
    """Greedy equal-ish-frequency bin boundaries (ref: src/io/bin.cpp:78-155)."""
    num_distinct = len(distinct_values)
    bin_upper_bound: List[float] = []
    assert max_bin > 0
    if num_distinct == 0:
        return [math.inf]
    if num_distinct <= max_bin:
        cur_cnt_inbin = 0
        for i in range(num_distinct - 1):
            cur_cnt_inbin += counts[i]
            if cur_cnt_inbin >= min_data_in_bin:
                val = _next_after_up((distinct_values[i] + distinct_values[i + 1]) / 2.0)
                if not bin_upper_bound or not _double_equal_ordered(bin_upper_bound[-1], val):
                    bin_upper_bound.append(val)
                    cur_cnt_inbin = 0
        bin_upper_bound.append(math.inf)
        return bin_upper_bound

    if min_data_in_bin > 0:
        max_bin = max(1, min(max_bin, total_cnt // min_data_in_bin))
    mean_bin_size = total_cnt / max_bin
    rest_bin_cnt = max_bin
    rest_sample_cnt = total_cnt
    is_big = [c >= mean_bin_size for c in counts]
    for i in range(num_distinct):
        if is_big[i]:
            rest_bin_cnt -= 1
            rest_sample_cnt -= counts[i]
    mean_bin_size = rest_sample_cnt / rest_bin_cnt if rest_bin_cnt else math.inf

    upper_bounds = [math.inf] * max_bin
    lower_bounds = [math.inf] * max_bin
    bin_cnt = 0
    lower_bounds[0] = distinct_values[0]
    cur_cnt_inbin = 0
    for i in range(num_distinct - 1):
        if not is_big[i]:
            rest_sample_cnt -= counts[i]
        cur_cnt_inbin += counts[i]
        if (is_big[i] or cur_cnt_inbin >= mean_bin_size or
                (is_big[i + 1] and cur_cnt_inbin >= max(1.0, mean_bin_size * 0.5))):
            upper_bounds[bin_cnt] = distinct_values[i]
            bin_cnt += 1
            lower_bounds[bin_cnt] = distinct_values[i + 1]
            if bin_cnt >= max_bin - 1:
                break
            cur_cnt_inbin = 0
            if not is_big[i]:
                rest_bin_cnt -= 1
                mean_bin_size = rest_sample_cnt / rest_bin_cnt
    bin_cnt += 1
    for i in range(bin_cnt - 1):
        val = _next_after_up((upper_bounds[i] + lower_bounds[i + 1]) / 2.0)
        if not bin_upper_bound or not _double_equal_ordered(bin_upper_bound[-1], val):
            bin_upper_bound.append(val)
    bin_upper_bound.append(math.inf)
    return bin_upper_bound


def find_bin_with_zero_as_one_bin(distinct_values: Sequence[float], counts: Sequence[int],
                                  max_bin: int, total_sample_cnt: int,
                                  min_data_in_bin: int) -> List[float]:
    """Split negative/zero/positive ranges so zero gets its own bin
    (ref: src/io/bin.cpp:242-298)."""
    num_distinct = len(distinct_values)
    left_cnt_data = cnt_zero = right_cnt_data = 0
    for v, c in zip(distinct_values, counts):
        if v <= -K_ZERO_THRESHOLD:
            left_cnt_data += c
        elif v > K_ZERO_THRESHOLD:
            right_cnt_data += c
        else:
            cnt_zero += c

    left_cnt = next((i for i, v in enumerate(distinct_values) if v > -K_ZERO_THRESHOLD),
                    num_distinct)

    bin_upper_bound: List[float] = []
    if left_cnt > 0 and max_bin > 1:
        denom = total_sample_cnt - cnt_zero
        left_max_bin = int(left_cnt_data / denom * (max_bin - 1)) if denom else 1
        left_max_bin = max(1, left_max_bin)
        bin_upper_bound = greedy_find_bin(distinct_values[:left_cnt], counts[:left_cnt],
                                          left_max_bin, left_cnt_data, min_data_in_bin)
        if bin_upper_bound:
            bin_upper_bound[-1] = -K_ZERO_THRESHOLD

    right_start = next((i for i in range(left_cnt, num_distinct)
                        if distinct_values[i] > K_ZERO_THRESHOLD), -1)

    right_max_bin = max_bin - 1 - len(bin_upper_bound)
    if right_start >= 0 and right_max_bin > 0:
        right_bounds = greedy_find_bin(distinct_values[right_start:], counts[right_start:],
                                       right_max_bin, right_cnt_data, min_data_in_bin)
        bin_upper_bound.append(K_ZERO_THRESHOLD)
        bin_upper_bound.extend(right_bounds)
    else:
        bin_upper_bound.append(math.inf)
    assert len(bin_upper_bound) <= max_bin
    return bin_upper_bound


def find_bin_with_predefined_bin(distinct_values: Sequence[float],
                                 counts: Sequence[int], max_bin: int,
                                 total_sample_cnt: int, min_data_in_bin: int,
                                 forced_upper_bounds: Sequence[float]
                                 ) -> List[float]:
    """Forced bin upper bounds (forcedbins_filename), remaining bins
    allocated greedily per forced interval in proportion to its sample
    count (ref: src/io/bin.cpp:157-240 FindBinWithPredefinedBin)."""
    num_distinct = len(distinct_values)
    left_cnt = next((i for i, v in enumerate(distinct_values)
                     if v > -K_ZERO_THRESHOLD), num_distinct)
    right_start = next((i for i in range(left_cnt, num_distinct)
                        if distinct_values[i] > K_ZERO_THRESHOLD), -1)

    # zero bounds and the infinity bound come first (zero keeps its own
    # bin exactly like FindBinWithZeroAsOneBin)
    bin_upper_bound: List[float] = []
    if max_bin == 2:
        bin_upper_bound.append(K_ZERO_THRESHOLD if left_cnt == 0
                               else -K_ZERO_THRESHOLD)
    elif max_bin >= 3:
        if left_cnt > 0:
            bin_upper_bound.append(-K_ZERO_THRESHOLD)
        if right_start >= 0:
            bin_upper_bound.append(K_ZERO_THRESHOLD)
    bin_upper_bound.append(math.inf)

    # forced bounds, excluding zeros (already bounded above)
    max_to_insert = max_bin - len(bin_upper_bound)
    num_inserted = 0
    for v in forced_upper_bounds:
        if num_inserted >= max_to_insert:
            break
        if abs(v) > K_ZERO_THRESHOLD:
            bin_upper_bound.append(float(v))
            num_inserted += 1
    bin_upper_bound.sort()

    # remaining bins: greedy inside each forced interval, proportional to
    # its sample count; the last interval takes every remaining bin
    free_bins = max_bin - len(bin_upper_bound)
    bounds_to_add: List[float] = []
    value_ind = 0
    for i in range(len(bin_upper_bound)):
        cnt_in_bin = 0
        distinct_cnt_in_bin = 0
        bin_start = value_ind
        while (value_ind < num_distinct
               and distinct_values[value_ind] < bin_upper_bound[i]):
            cnt_in_bin += counts[value_ind]
            distinct_cnt_in_bin += 1
            value_ind += 1
        bins_remaining = (max_bin - len(bin_upper_bound)
                          - len(bounds_to_add))
        # std::lround (half away from zero; operand is non-negative)
        num_sub_bins = int(math.floor(cnt_in_bin * free_bins
                                      / total_sample_cnt + 0.5))
        num_sub_bins = min(num_sub_bins, bins_remaining) + 1
        if i == len(bin_upper_bound) - 1:
            num_sub_bins = bins_remaining + 1
        new_bounds = greedy_find_bin(
            distinct_values[bin_start:bin_start + distinct_cnt_in_bin],
            counts[bin_start:bin_start + distinct_cnt_in_bin],
            num_sub_bins, cnt_in_bin, min_data_in_bin)
        bounds_to_add.extend(new_bounds[:-1])   # last bound is infinity
    bin_upper_bound.extend(bounds_to_add)
    bin_upper_bound.sort()
    assert len(bin_upper_bound) <= max_bin
    return bin_upper_bound


def _need_filter(cnt_in_bin: List[int], total_cnt: int, filter_cnt: int,
                 bin_type: int) -> bool:
    """Pre-filter features that can never produce a valid split
    (ref: src/io/bin.cpp:33-76 NeedFilter)."""
    if bin_type == BIN_NUMERICAL:
        sum_left = 0
        for i in range(len(cnt_in_bin) - 1):
            sum_left += cnt_in_bin[i]
            if sum_left >= filter_cnt and total_cnt - sum_left >= filter_cnt:
                return False
        return True
    else:
        if len(cnt_in_bin) <= 2:
            for i in range(len(cnt_in_bin) - 1):
                sum_left = cnt_in_bin[i]
                if sum_left >= filter_cnt and total_cnt - sum_left >= filter_cnt:
                    return False
            return True
        return False


def prep_find_bin_values(col: np.ndarray) -> np.ndarray:
    """Sample column -> the `values` array find_bin expects: non-zero
    finite values followed by the NaNs; zeros are implied by
    total_sample_cnt - len(values) (find_bin's contract — keep every
    caller on this one helper so the zero/NaN sampling convention cannot
    diverge between the single-host and distributed binning paths)."""
    col = np.asarray(col, np.float64)
    nonzero = col[~((col == 0) | np.isnan(col))]
    nan_vals = col[np.isnan(col)]
    return np.concatenate([nonzero, nan_vals])


class BinMapper:
    """Per-feature value->bin mapping (ref: include/LightGBM/bin.h:84)."""

    def __init__(self):
        self.num_bin: int = 1
        self.missing_type: int = MISSING_NONE
        self.is_trivial: bool = True
        self.sparse_rate: float = 1.0
        self.bin_type: int = BIN_NUMERICAL
        self.min_val: float = 0.0
        self.max_val: float = 0.0
        self.default_bin: int = 0
        self.most_freq_bin: int = 0
        self.bin_upper_bound: np.ndarray = np.array([np.inf])
        self.bin_2_categorical: List[int] = []
        self.categorical_2_bin: Dict[int, int] = {}

    # -- construction ------------------------------------------------------
    def find_bin(self, values: np.ndarray, total_sample_cnt: int, max_bin: int,
                 min_data_in_bin: int = 3, min_split_data: int = 20,
                 pre_filter: bool = False, bin_type: int = BIN_NUMERICAL,
                 use_missing: bool = True, zero_as_missing: bool = False,
                 forced_upper_bounds: Optional[Sequence[float]] = None) -> None:
        """Build the mapping from sampled values (ref: src/io/bin.cpp:311-506).

        `values` are the sampled non-zero values; zeros are implied by
        total_sample_cnt - len(values).
        """
        values = np.asarray(values, dtype=np.float64)
        num_sample_values = len(values)
        non_na = values[~np.isnan(values)]
        na_cnt = 0
        if not use_missing:
            self.missing_type = MISSING_NONE
        elif zero_as_missing:
            self.missing_type = MISSING_ZERO
        else:
            if len(non_na) == num_sample_values:
                self.missing_type = MISSING_NONE
            else:
                self.missing_type = MISSING_NAN
                na_cnt = num_sample_values - len(non_na)

        self.bin_type = bin_type
        self.default_bin = 0
        zero_cnt = int(total_sample_cnt - len(non_na) - na_cnt)

        # distinct values with zero spliced into its sorted position,
        # carrying the implied zero count (ref: bin.cpp:343-375)
        svals = np.sort(non_na, kind="stable")
        distinct_values: List[float] = []
        counts: List[int] = []
        if len(svals) == 0 or (svals[0] > 0.0 and zero_cnt > 0):
            distinct_values.append(0.0)
            counts.append(zero_cnt)
        if len(svals) > 0:
            distinct_values.append(float(svals[0]))
            counts.append(1)
        for i in range(1, len(svals)):
            prev, cur = float(svals[i - 1]), float(svals[i])
            if not _double_equal_ordered(prev, cur):
                if prev < 0.0 and cur > 0.0:
                    distinct_values.append(0.0)
                    counts.append(zero_cnt)
                distinct_values.append(cur)
                counts.append(1)
            else:
                distinct_values[-1] = cur  # use the larger value
                counts[-1] += 1
        if len(svals) > 0 and svals[-1] < 0.0 and zero_cnt > 0:
            distinct_values.append(0.0)
            counts.append(zero_cnt)

        if not distinct_values:
            distinct_values = [0.0]
            counts = [zero_cnt]
        self.min_val = distinct_values[0]
        self.max_val = distinct_values[-1]
        num_distinct = len(distinct_values)
        cnt_in_bin: List[int] = []

        if bin_type == BIN_NUMERICAL:
            forced = list(forced_upper_bounds) if forced_upper_bounds else []

            def _find(mb, tc):
                # ref: bin.cpp:302-309 FindBin dispatch — forced bounds
                # select FindBinWithPredefinedBin
                if forced:
                    return find_bin_with_predefined_bin(
                        distinct_values, counts, mb, tc, min_data_in_bin,
                        forced)
                return find_bin_with_zero_as_one_bin(
                    distinct_values, counts, mb, tc, min_data_in_bin)

            if self.missing_type == MISSING_ZERO:
                bounds = _find(max_bin, total_sample_cnt)
                if len(bounds) == 2:
                    self.missing_type = MISSING_NONE
            elif self.missing_type == MISSING_NONE:
                bounds = _find(max_bin, total_sample_cnt)
            else:  # NaN: last bin reserved for missing (ref: bin.cpp:391-394)
                bounds = _find(max_bin - 1, total_sample_cnt - na_cnt)
                bounds = bounds + [math.nan]
            self.bin_upper_bound = np.array(bounds, dtype=np.float64)
            self.num_bin = len(bounds)
            cnt_in_bin = [0] * self.num_bin
            i_bin = 0
            for v, c in zip(distinct_values, counts):
                while i_bin < self.num_bin - 1 and v > self.bin_upper_bound[i_bin]:
                    i_bin += 1
                cnt_in_bin[i_bin] += c
            if self.missing_type == MISSING_NAN:
                cnt_in_bin[self.num_bin - 1] = na_cnt
            assert self.num_bin <= max_bin
        else:
            # categorical: count-sorted category->bin, bin 0 = NaN/other
            # (ref: bin.cpp:410-477)
            dv_int: List[int] = []
            cnt_int: List[int] = []
            for v, c in zip(distinct_values, counts):
                iv = int(v)
                if iv < 0:
                    na_cnt += c
                    log.warning("Met negative value in categorical features, "
                                "will convert it to NaN")
                elif dv_int and iv == dv_int[-1]:
                    cnt_int[-1] += c
                else:
                    dv_int.append(iv)
                    cnt_int.append(c)
            rest_cnt = total_sample_cnt - na_cnt
            if rest_cnt > 0 and dv_int:
                order = sorted(range(len(dv_int)), key=lambda i: (-cnt_int[i], i))
                dv_int = [dv_int[i] for i in order]
                cnt_int = [cnt_int[i] for i in order]
                cut_cnt = int(round((total_sample_cnt - na_cnt) * 0.99))
                distinct_cnt = len(dv_int) + (1 if na_cnt > 0 else 0)
                eff_max_bin = min(distinct_cnt, max_bin)
                self.bin_2_categorical = [-1]
                self.categorical_2_bin = {-1: 0}
                cnt_in_bin = [0]
                self.num_bin = 1
                used_cnt = 0
                cur = 0
                while cur < len(dv_int) and (used_cnt < cut_cnt or self.num_bin < eff_max_bin):
                    if cnt_int[cur] < min_data_in_bin and cur > 1:
                        break
                    self.bin_2_categorical.append(dv_int[cur])
                    self.categorical_2_bin[dv_int[cur]] = self.num_bin
                    used_cnt += cnt_int[cur]
                    cnt_in_bin.append(cnt_int[cur])
                    self.num_bin += 1
                    cur += 1
                if cur == len(dv_int) and na_cnt == 0:
                    self.missing_type = MISSING_NONE
                else:
                    self.missing_type = MISSING_NAN
                cnt_in_bin[0] = total_sample_cnt - used_cnt

        self.is_trivial = self.num_bin <= 1
        if not self.is_trivial and pre_filter and _need_filter(
                cnt_in_bin, total_sample_cnt, min_split_data, bin_type):
            self.is_trivial = True

        if not self.is_trivial:
            self.default_bin = int(self.value_to_bin(0.0))
            self.most_freq_bin = int(np.argmax(cnt_in_bin))
            max_sparse_rate = cnt_in_bin[self.most_freq_bin] / total_sample_cnt
            if self.most_freq_bin != self.default_bin and max_sparse_rate < K_SPARSE_THRESHOLD:
                self.most_freq_bin = self.default_bin
            self.sparse_rate = cnt_in_bin[self.most_freq_bin] / total_sample_cnt
        else:
            self.sparse_rate = 1.0

    # -- mapping -----------------------------------------------------------
    def value_to_bin(self, value: float) -> int:
        """Scalar value->bin (ref: bin.h:611-647)."""
        return int(self.values_to_bins(np.array([value]))[0])

    def values_to_bins(self, values: np.ndarray) -> np.ndarray:
        """Vectorized value->bin for a full column."""
        values = np.asarray(values, dtype=np.float64)
        out = np.zeros(len(values), dtype=np.int32)
        nan_mask = np.isnan(values)
        if self.bin_type == BIN_CATEGORICAL:
            iv = np.where(nan_mask, -1, values).astype(np.int64)
            cats = np.array(sorted(self.categorical_2_bin), dtype=np.int64)
            bins = np.array([self.categorical_2_bin[c] for c in cats], dtype=np.int32)
            pos = np.searchsorted(cats, iv)
            pos = np.clip(pos, 0, len(cats) - 1)
            hit = (cats[pos] == iv) & (iv >= 0)
            return np.where(hit, bins[pos], 0).astype(np.int32)
        vals = values.copy()
        if self.missing_type != MISSING_NAN:
            vals = np.where(nan_mask, 0.0, vals)
        n_search = self.num_bin - (1 if self.missing_type == MISSING_NAN else 0)
        # bin = first index with value <= upper_bound  (upper bounds ascending)
        bounds = self.bin_upper_bound[:n_search - 1] if n_search > 0 else np.array([])
        out = np.searchsorted(bounds, vals, side="left").astype(np.int32)
        # searchsorted 'left' gives first idx with bounds[idx] >= v; reference uses
        # v <= bound, identical for total order except exact equality, which matches.
        if self.missing_type == MISSING_NAN:
            out = np.where(nan_mask, self.num_bin - 1, out).astype(np.int32)
        return out

    def bin_to_value(self, bin_idx: int) -> float:
        """Representative threshold value for a bin (used in model text output;
        ref: tree.cpp RealThreshold via bin_upper_bound)."""
        if self.bin_type == BIN_CATEGORICAL:
            return float(self.bin_2_categorical[bin_idx])
        return float(self.bin_upper_bound[bin_idx])

    @property
    def missing_type_str(self) -> str:
        return _MISSING_TYPE_STR[self.missing_type]

    # -- serialization (model text "feature_infos" + binary) ---------------
    def feature_info_str(self) -> str:
        """Model-text feature info (ref: gbdt_model_text.cpp DumpModel feature_infos)."""
        if self.is_trivial:
            return "none"
        if self.bin_type == BIN_CATEGORICAL:
            cats = sorted(c for c in self.bin_2_categorical if c >= 0)
            return "[" + ":".join(str(c) for c in cats) + "]"
        return f"[{self.min_val:g}:{self.max_val:g}]"

    def to_dict(self) -> dict:
        return {
            "num_bin": self.num_bin,
            "missing_type": self.missing_type,
            "is_trivial": self.is_trivial,
            "sparse_rate": self.sparse_rate,
            "bin_type": self.bin_type,
            "min_val": self.min_val,
            "max_val": self.max_val,
            "default_bin": self.default_bin,
            "most_freq_bin": self.most_freq_bin,
            "bin_upper_bound": [float(x) for x in self.bin_upper_bound],
            "bin_2_categorical": list(self.bin_2_categorical),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "BinMapper":
        m = cls()
        m.num_bin = int(d["num_bin"])
        m.missing_type = int(d["missing_type"])
        m.is_trivial = bool(d["is_trivial"])
        m.sparse_rate = float(d["sparse_rate"])
        m.bin_type = int(d["bin_type"])
        m.min_val = float(d["min_val"])
        m.max_val = float(d["max_val"])
        m.default_bin = int(d["default_bin"])
        m.most_freq_bin = int(d["most_freq_bin"])
        m.bin_upper_bound = np.array(d["bin_upper_bound"], dtype=np.float64)
        m.bin_2_categorical = [int(x) for x in d.get("bin_2_categorical", [])]
        m.categorical_2_bin = {c: i for i, c in enumerate(m.bin_2_categorical)}
        return m
