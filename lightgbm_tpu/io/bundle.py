"""Exclusive Feature Bundling (EFB).

TPU-native analogue of the reference's FeatureGroup construction
(ref: include/LightGBM/feature_group.h:25; greedy bundling in
src/io/dataset.cpp FastFeatureBundling/FindGroups): sparse features that
are (almost) never simultaneously non-default share one device column,
shrinking the histogram pass's F axis — the "long axis" scaler for
wide-sparse data (SURVEY §5).

Encoding: bundle code 0 = every member at its default (zero) bin;
member i's bin b is encoded as offset_i + b, with disjoint
[offset_i, offset_i + num_bin_i) ranges (offset_0 = 1).  Conflicting
rows (two members non-default, allowed up to max_conflict_rate) keep the
LAST member's code, like the reference's ordered PushData.  The
histogram built over bundle columns is converted back to per-feature
histograms by slicing each member's range and recovering the default
bin by subtraction from the leaf totals — the reference's
Dataset::FixHistogram (dataset.h:759).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from .binning import BIN_NUMERICAL

MAX_BUNDLE_BINS = 256       # uint8 device codes; also the EFB win window:
                            # bundling pays when member bins sum small
                            # (one-hot histogram volume = total bins x n)
_SAMPLE = 50_000            # rows sampled for conflict counting


class BundlePlan:
    """Static bundling description (host side)."""

    def __init__(self, groups: List[List[int]], group_idx: np.ndarray,
                 offsets: np.ndarray, zero_bin: np.ndarray,
                 in_bundle: np.ndarray, group_num_bin: np.ndarray):
        self.groups = groups              # bundle -> inner feature list
        self.group_idx = group_idx        # [F] feature -> bundle column
        self.offsets = offsets            # [F] code offset (0 = singleton)
        self.zero_bin = zero_bin          # [F] the default (zero) bin
        self.in_bundle = in_bundle        # [F] bool: part of a >1 bundle
        self.group_num_bin = group_num_bin  # [F'] bins per bundle column

    @property
    def num_groups(self) -> int:
        return len(self.groups)

    @property
    def effective(self) -> bool:
        return bool(self.in_bundle.any())


def _default_bins(mappers, used_features) -> np.ndarray:
    """The 'default' bin per feature: the bin holding value 0.0
    (ref: most_freq_bin semantics for sparse data)."""
    zb = np.zeros(len(used_features), np.int32)
    for i, f in enumerate(used_features):
        m = mappers[f]
        if m.bin_type == BIN_NUMERICAL:
            zb[i] = m.value_to_bin(0.0)
        else:
            zb[i] = 0  # categorical: the NaN/other bin
    return zb


def plan_bundles_from_masks(nz, nbins: np.ndarray, zb: np.ndarray,
                            sample_size: int,
                            max_conflict_rate: float) -> BundlePlan:
    """Greedy conflict-bounded bundling core (ref: dataset.cpp
    FindGroups): features ordered by non-default count descending; each
    joins the first bundle whose accumulated conflicts stay under the
    cap.  `nz` is the [F, S] non-default mask over the row sample (any
    indexable of bool vectors); shared by the dense and the
    CSC-direct-sparse planners so their plans cannot diverge."""
    F = len(nbins)
    nz_cnt = np.array([int(nz[f].sum()) for f in range(F)], np.int64)
    cap = max_conflict_rate * sample_size

    order = np.argsort(-nz_cnt)
    groups: List[List[int]] = []
    group_nz: List[np.ndarray] = []
    group_conflicts: List[int] = []
    group_bins: List[int] = []
    for f in order:
        f = int(f)
        placed = False
        for gi in range(len(groups)):
            if group_bins[gi] + nbins[f] > MAX_BUNDLE_BINS:
                continue
            conflicts = int((group_nz[gi] & nz[f]).sum())
            if group_conflicts[gi] + conflicts <= cap:
                groups[gi].append(f)
                group_nz[gi] |= nz[f]
                group_conflicts[gi] += conflicts
                group_bins[gi] += int(nbins[f])
                placed = True
                break
        if not placed:
            groups.append([f])
            group_nz.append(np.array(nz[f], copy=True))
            group_conflicts.append(0)
            group_bins.append(1 + int(nbins[f]))

    group_idx = np.zeros(F, np.int32)
    offsets = np.zeros(F, np.int32)
    in_bundle = np.zeros(F, bool)
    group_num_bin = np.zeros(len(groups), np.int32)
    for gi, members in enumerate(groups):
        if len(members) == 1:
            f = members[0]
            group_idx[f] = gi
            offsets[f] = 0
            group_num_bin[gi] = nbins[f]
            continue
        off = 1
        for f in members:
            group_idx[f] = gi
            offsets[f] = off
            in_bundle[f] = True
            off += int(nbins[f])
        group_num_bin[gi] = off
    return BundlePlan(groups, group_idx, offsets, zb, in_bundle,
                      group_num_bin)


def plan_bundles(binned: np.ndarray, mappers, used_features,
                 max_conflict_rate: float = 0.0,
                 rng: Optional[np.random.RandomState] = None) -> BundlePlan:
    """Dense-binned front end of the planner."""
    F, n = binned.shape
    zb = _default_bins(mappers, used_features)
    sample = (np.arange(n) if n <= _SAMPLE else
              (rng or np.random.RandomState(3)).choice(n, _SAMPLE, False))
    # device-binned datasets (io/device_bin.py): gather the row sample on
    # device, pull only the [F, S] slice
    sub = np.asarray(binned[:, sample])
    nz = sub != zb[:, None]                       # [F, S] non-default mask
    nbins = np.array([mappers[f].num_bin for f in used_features], np.int32)
    return plan_bundles_from_masks(nz, nbins, zb, len(sample),
                                   max_conflict_rate)


def build_bundled(binned: np.ndarray, plan: BundlePlan) -> np.ndarray:
    """[F, n] feature bins -> [F', n] bundle codes."""
    F, n = binned.shape
    dtype = np.uint8 if plan.group_num_bin.max() <= 256 else np.int32
    out = np.zeros((plan.num_groups, n), dtype)
    for gi, members in enumerate(plan.groups):
        if len(members) == 1:
            out[gi] = binned[members[0]].astype(dtype)
            continue
        col = np.zeros(n, np.int32)
        for f in members:                # later members win conflicts
            nzm = binned[f] != plan.zero_bin[f]
            col[nzm] = plan.offsets[f] + binned[f][nzm]
        out[gi] = col.astype(dtype)
    return out
