"""Dataset: binned feature tensors + Metadata, ready for TPU residence.

TPU-first redesign of the reference's Dataset/FeatureGroup/DatasetLoader stack
(ref: include/LightGBM/dataset.h:486, src/io/dataset_loader.cpp): instead of
per-feature Bin objects with sparse/dense variants, all used features are binned into
one dense feature-major int32 matrix `binned [F_used, n]` (uint8-sized bins in
practice; int32 keeps XLA gathers simple — the histogram kernels re-cast).  Trivial
features are dropped at construction and restored at prediction/model-output time via
`used_feature_map`, mirroring the reference's inner-feature mapping
(ref: dataset.h:556-647 used_feature_map_/feature2group_).

Sampling-based bin construction follows DatasetLoader::ConstructFromSampleData
(ref: dataset_loader.cpp:593).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..utils import log
from ..utils.timer import global_timer
from .binning import BIN_CATEGORICAL, BIN_NUMERICAL, BinMapper


class Metadata:
    """Labels / weights / init scores / query boundaries / positions
    (ref: include/LightGBM/dataset.h:47-399, src/io/metadata.cpp)."""

    def __init__(self, num_data: int):
        self.num_data = num_data
        self.label: np.ndarray = np.zeros(num_data, dtype=np.float32)
        self.weight: Optional[np.ndarray] = None
        self.init_score: Optional[np.ndarray] = None
        self.query_boundaries: Optional[np.ndarray] = None  # int32 [num_queries+1]
        self.position: Optional[np.ndarray] = None

    def set_label(self, label: Sequence[float]) -> None:
        label = np.asarray(label, dtype=np.float32).reshape(-1)
        if len(label) != self.num_data:
            log.fatal(f"Length of label ({len(label)}) != num_data ({self.num_data})")
        self.label = label

    def set_weight(self, weight: Optional[Sequence[float]]) -> None:
        if weight is None:
            self.weight = None
            return
        weight = np.asarray(weight, dtype=np.float32).reshape(-1)
        if len(weight) != self.num_data:
            log.fatal(f"Length of weight ({len(weight)}) != num_data ({self.num_data})")
        self.weight = weight

    def set_init_score(self, init_score: Optional[Sequence[float]]) -> None:
        if init_score is None:
            self.init_score = None
            return
        init_score = np.asarray(init_score, dtype=np.float64).reshape(-1)
        self.init_score = init_score

    def set_group(self, group: Optional[Sequence[int]]) -> None:
        """`group` is sizes per query (LightGBM convention); converts to boundaries."""
        if group is None:
            self.query_boundaries = None
            return
        group = np.asarray(group, dtype=np.int64).reshape(-1)
        if group.sum() != self.num_data:
            log.fatal(f"Sum of query counts ({group.sum()}) != num_data ({self.num_data})")
        self.query_boundaries = np.concatenate(
            [[0], np.cumsum(group)]).astype(np.int32)

    def set_position(self, position: Optional[Sequence[int]]) -> None:
        self.position = None if position is None else np.asarray(position, dtype=np.int32)

    @property
    def num_queries(self) -> int:
        return 0 if self.query_boundaries is None else len(self.query_boundaries) - 1


def get_forced_bins(path: str, num_total_features: int,
                    categorical_features=()) -> List[List[float]]:
    """forcedbins_filename JSON -> per-feature forced bin upper bounds
    (ref: dataset_loader.cpp:1493 GetForcedBins): a list of
    {"feature": i, "bin_upper_bound": [...]} records; missing file warns
    and is ignored, categorical features warn and are skipped,
    consecutive duplicates are removed."""
    forced: List[List[float]] = [[] for _ in range(num_total_features)]
    if not path:
        return forced
    try:
        with open(path) as f:
            arr = json.load(f)
    except OSError:
        log.warning(f"Could not open {path}. Will ignore.")
        return forced
    cat = set(categorical_features or ())
    for rec in arr:
        fnum = int(rec["feature"])
        if fnum >= num_total_features or fnum < 0:
            log.fatal(f"forced bins feature index {fnum} out of range")
        if fnum in cat:
            log.warning(f"Feature {fnum} is categorical. Will ignore "
                        "forced bins for this feature.")
            continue
        forced[fnum].extend(float(v) for v in rec["bin_upper_bound"])
    for i in range(num_total_features):
        deduped: List[float] = []
        for v in forced[i]:
            if not deduped or deduped[-1] != v:
                deduped.append(v)
        forced[i] = deduped
    return forced


class Dataset:
    """Binned training data (ref: include/LightGBM/dataset.h:486 `class Dataset`)."""

    def __init__(self):
        self.num_data: int = 0
        self.num_total_features: int = 0
        self.feature_names: List[str] = []
        self.bin_mappers: List[BinMapper] = []          # per original feature
        self.used_feature_map: List[int] = []            # original -> inner (-1 trivial)
        self.used_features: List[int] = []               # inner -> original
        # [F_used, n] bin codes: host int32/uint8, or a DEVICE jax.Array
        # (uint8) when the device second pass ran (io/device_bin.py) — the
        # training path consumes it on device without a host round-trip;
        # host-only paths call binned_host()
        self.binned = None
        self.metadata: Optional[Metadata] = None
        self.max_bin: int = 255
        self.raw_data: Optional[np.ndarray] = None       # kept for linear trees
        # sparse CSC-direct ingestion (io/sparse.py): when set, `binned`
        # holds [num_bundles, n] EFB bundle codes instead of per-feature
        # bins, and this BundlePlan decodes them
        self.pre_bundled_plan = None
        # raw (float32) bin-construction sample rows, kept when `binned`
        # lives on device: EFB planning bins them lazily host-side
        # (efb_sample_bins) instead of gathering sample columns through
        # the device tunnel
        self._efb_sample_raw: Optional[np.ndarray] = None
        self._efb_sample_bins: Optional[np.ndarray] = None
        # (binned_dev_padded, n): set by the booster when it takes over
        # the device bin matrix (padded, donated) so binned_host() can
        # still recover the [F, n] host view without a duplicate copy
        self._binned_view = None

    # ------------------------------------------------------------------
    @property
    def num_features(self) -> int:
        return len(self.used_features)

    def num_bin(self, inner_feature: int) -> int:
        return self.bin_mappers[self.used_features[inner_feature]].num_bin

    @property
    def max_num_bin(self) -> int:
        if not self.used_features:
            return 1
        return max(self.bin_mappers[f].num_bin for f in self.used_features)

    def inner_feature_index(self, original: int) -> int:
        return self.used_feature_map[original]

    def binned_host(self) -> np.ndarray:
        """Host view of the bin matrix; pulls (once) when the device
        second pass left it on device (or the booster holds the padded
        device matrix after taking it over)."""
        if self.binned is None and self._binned_view is not None:
            from .device_bin import pull_host
            arr, n = self._binned_view
            self.binned = pull_host(arr)[:, :n]
        if self.binned is not None and not isinstance(self.binned,
                                                      np.ndarray):
            from .device_bin import pull_host
            self.binned = pull_host(self.binned)
        return self.binned

    def efb_sample_bins(self) -> Optional[np.ndarray]:
        """Host [F_used, S] bin codes of the bin-construction sample
        (EFB planning input for device-binned datasets); binned lazily
        and cached."""
        if self._efb_sample_bins is None and self._efb_sample_raw is not None:
            self._efb_sample_bins = np.stack([
                self.bin_mappers[f].values_to_bins(
                    np.asarray(self._efb_sample_raw[:, i], np.float64))
                for i, f in enumerate(self.used_features)])
        return self._efb_sample_bins

    def feature_bins(self, inner: int) -> np.ndarray:
        """Per-feature bin codes [n]; decodes bundle-space storage on
        demand for sparse-ingested datasets (the bundle member's code
        range is sliced out, everything else is the default bin — the
        host-side mirror of Dataset::FixHistogram's member recovery)."""
        plan = self.pre_bundled_plan
        if plan is None:
            return self.binned_host()[inner]
        g = int(plan.group_idx[inner])
        off = int(plan.offsets[inner])
        col = self.binned_host()[g].astype(np.int32)
        if off == 0:                     # singleton bundle: codes ARE bins
            return col
        local = col - off
        nb = self.bin_mappers[self.used_features[inner]].num_bin
        return np.where((local >= 0) & (local < nb), local,
                        int(plan.zero_bin[inner]))

    # ------------------------------------------------------------------
    @classmethod
    def construct_from_arrays(
            cls,
            data: np.ndarray,
            label: Optional[Sequence[float]] = None,
            weight: Optional[Sequence[float]] = None,
            group: Optional[Sequence[int]] = None,
            init_score: Optional[Sequence[float]] = None,
            max_bin: int = 255,
            min_data_in_bin: int = 3,
            min_data_in_leaf: int = 20,
            bin_construct_sample_cnt: int = 200000,
            categorical_feature: Optional[Sequence[int]] = None,
            feature_names: Optional[Sequence[str]] = None,
            use_missing: bool = True,
            zero_as_missing: bool = False,
            feature_pre_filter: bool = True,
            seed: int = 1,
            keep_raw_data: bool = False,
            reference: Optional["Dataset"] = None,
            max_bin_by_feature: Optional[Sequence[int]] = None,
            forcedbins_filename: str = "") -> "Dataset":
        """Build a Dataset from a dense float matrix
        (ref: dataset_loader.cpp:593 ConstructFromSampleData + :1263 ExtractFeatures).

        When `reference` is given, reuse its bin mappers (validation-set path,
        ref: basic.py create_valid / LoadFromFileAlignWithOtherDataset).
        """
        # keep the caller's dtype: values_to_bins converts per column, and
        # float32 inputs take the exact device bucketize path (the host is
        # single-core; ref does this pass in parallel C++,
        # dataset_loader.cpp:246 ExtractFeaturesFromMemory)
        data = np.asarray(data)
        if data.dtype not in (np.float32, np.float64):
            data = data.astype(np.float64)
        if data.ndim != 2:
            log.fatal("Training data must be 2-dimensional")
        n, num_features = data.shape
        ds = cls()
        ds.num_data = n
        ds.num_total_features = num_features
        ds.max_bin = max_bin
        if feature_names is not None:
            ds.feature_names = [str(s) for s in feature_names]
        else:
            ds.feature_names = [f"Column_{i}" for i in range(num_features)]

        if reference is not None:
            if reference.num_total_features != num_features:
                log.fatal("Validation data feature count mismatch with reference Dataset")
            ds.bin_mappers = reference.bin_mappers
            ds.used_feature_map = reference.used_feature_map
            ds.used_features = reference.used_features
            ds.feature_names = reference.feature_names
            ds.max_bin = reference.max_bin
        else:
            # sample rows for bin finding (ref: config `bin_construct_sample_cnt`)
            if n > bin_construct_sample_cnt:
                rng = np.random.RandomState(seed)
                sample_idx = np.sort(rng.choice(n, bin_construct_sample_cnt, replace=False))
                sample = data[sample_idx]
            else:
                sample = data
            ds._build_mappers(
                sample, len(sample), max_bin=max_bin,
                min_data_in_bin=min_data_in_bin,
                min_data_in_leaf=min_data_in_leaf,
                categorical_feature=categorical_feature,
                use_missing=use_missing, zero_as_missing=zero_as_missing,
                feature_pre_filter=feature_pre_filter,
                max_bin_by_feature=max_bin_by_feature,
                forcedbins_filename=forcedbins_filename)

        # bin every used feature (ref: ExtractFeaturesFromMemory PushOneRow).
        # float32 large-n numeric data bucketizes on device in one compiled
        # pass (io/device_bin.py, exact); otherwise the host searchsorted
        # loop runs per feature.
        from .device_bin import bin_matrix_device, device_binnable
        with global_timer.scope("Dataset::binning"):
            if device_binnable(ds.bin_mappers, ds.used_features,
                               data.dtype, n):
                ds.binned = global_timer.block(bin_matrix_device(
                    data, ds.bin_mappers, ds.used_features))
                if reference is None:
                    # keep the (already-sampled) bin-finding rows: EFB
                    # planning bins them lazily on first request
                    # (efb_sample_bins) — gathering sample columns out of
                    # the device matrix costs ~1000x more (tunnel gather),
                    # and eager binning would waste ~2s when bundling is
                    # off
                    ds._efb_sample_raw = np.ascontiguousarray(
                        sample[:, ds.used_features]
                        if sample.shape[1] != len(ds.used_features)
                        else sample)
            else:
                binned = np.empty((len(ds.used_features), n),
                                  dtype=np.int32)
                for inner, f in enumerate(ds.used_features):
                    binned[inner] = ds.bin_mappers[f].values_to_bins(
                        data[:, f])
                ds.binned = binned

        md = Metadata(n)
        if label is not None:
            md.set_label(label)
        md.set_weight(weight)
        md.set_group(group)
        md.set_init_score(init_score)
        ds.metadata = md
        if keep_raw_data:
            # linear-tree solves expect float64 raw values regardless of
            # the input dtype
            ds.raw_data = np.asarray(data, np.float64)
        return ds

    # ------------------------------------------------------------------
    def _build_mappers(self, sample, total_sample_cnt, *, max_bin,
                       min_data_in_bin, min_data_in_leaf,
                       categorical_feature, use_missing, zero_as_missing,
                       feature_pre_filter, max_bin_by_feature,
                       forcedbins_filename):
        """BinMappers + used-feature map from a sample matrix (ref:
        dataset_loader.cpp:593 ConstructFromSampleData)."""
        from .binning import prep_find_bin_values
        num_features = self.num_total_features
        cat_set = set(categorical_feature or [])
        forced_bins = get_forced_bins(forcedbins_filename, num_features,
                                      cat_set)
        self.bin_mappers = []
        with global_timer.scope("Dataset::find_bin"):
            for f in range(num_features):
                # reference samples *non-zero* values; zeros are implied
                # counts
                vals = prep_find_bin_values(sample[:, f])
                mapper = BinMapper()
                fmax_bin = (int(max_bin_by_feature[f])
                            if max_bin_by_feature else max_bin)
                mapper.find_bin(
                    vals, total_sample_cnt, fmax_bin,
                    min_data_in_bin=min_data_in_bin,
                    min_split_data=min_data_in_leaf,
                    pre_filter=feature_pre_filter,
                    bin_type=(BIN_CATEGORICAL if f in cat_set
                              else BIN_NUMERICAL),
                    use_missing=use_missing,
                    zero_as_missing=zero_as_missing,
                    forced_upper_bounds=forced_bins[f])
                self.bin_mappers.append(mapper)
        self.used_feature_map = []
        self.used_features = []
        for f, m in enumerate(self.bin_mappers):
            if m.is_trivial:
                self.used_feature_map.append(-1)
            else:
                self.used_feature_map.append(len(self.used_features))
                self.used_features.append(f)

    # ------------------------------------------------------------------
    @classmethod
    def construct_from_stream(
            cls, stream_factory, num_features: Optional[int] = None,
            weight=None, group=None,
            max_bin: int = 255, min_data_in_bin: int = 3,
            min_data_in_leaf: int = 20,
            bin_construct_sample_cnt: int = 200000,
            categorical_feature=None, feature_names=None,
            use_missing: bool = True, zero_as_missing: bool = False,
            feature_pre_filter: bool = True, seed: int = 1,
            max_bin_by_feature=None,
            forcedbins_filename: str = "",
            reference: Optional["Dataset"] = None) -> "Dataset":
        """Out-of-core (two-round) construction: bounded-memory streaming
        ingestion of data larger than RAM (ref: config.h `two_round`;
        dataset_loader.cpp:960 LoadTextDataToMemory is the ONE-round path
        this avoids, :1022 SampleTextDataFromFile + :1100
        ExtractFeaturesFromFile are the two file passes mirrored here).

        `stream_factory()` must return a fresh iterator of
        (feats [c, F] float, labels [c]) chunks each time it is called;
        chunk widths may grow over the stream (sparse LibSVM reveals its
        max feature index late) — narrower chunks are zero-padded.
        Pass 1 reservoir-samples rows for bin finding and counts rows;
        pass 2 streams again and bins each chunk straight into the packed
        [F_used, n] code matrix.  Peak memory is one chunk + the sample
        + the binned codes — the raw float matrix never materializes.
        """
        rng = np.random.RandomState(seed)
        # with a reference dataset the mappers are reused, so pass 1 only
        # counts rows and collects labels — keep the reservoir tiny
        cap = (1 if reference is not None
               else max(1, int(bin_construct_sample_cnt)))
        sample_buf = None
        filled = 0
        n = 0
        labels_parts = []
        # pass 1: count + reservoir sample (Vitter R, vectorized per
        # chunk: draws are batched; only accepted rows touch the buffer)
        for feats, labels in stream_factory():
            feats = np.asarray(feats, np.float64)
            c = feats.shape[0]
            if labels is not None:
                labels_parts.append(np.asarray(labels, np.float32))
            if sample_buf is None:
                sample_buf = np.zeros((cap, feats.shape[1]), np.float64)
            elif feats.shape[1] > sample_buf.shape[1]:
                # LibSVM width growth: widen with implicit zeros
                sample_buf = np.pad(
                    sample_buf,
                    ((0, 0), (0, feats.shape[1] - sample_buf.shape[1])))
            elif feats.shape[1] < sample_buf.shape[1]:
                feats = np.pad(
                    feats,
                    ((0, 0), (0, sample_buf.shape[1] - feats.shape[1])))
            take = min(cap - filled, c)
            if take > 0:
                sample_buf[filled:filled + take] = feats[:take]
                filled += take
            if take < c:
                seen = n + take + np.arange(1, c - take + 1)
                js = (rng.random_sample(c - take) * seen).astype(np.int64)
                hits = np.nonzero(js < cap)[0]
                for i in hits:            # expected O(cap * ln) accepts
                    sample_buf[js[i]] = feats[take + i]
            n += c
        if n == 0:
            log.fatal("Empty data stream")
        sample = sample_buf[:filled]
        if reference is not None:
            # wider than the training data is a real mismatch; NARROWER
            # is legal for sparse LibSVM (trailing features all-zero in
            # the validation file) and zero-pads below
            if sample.shape[1] > reference.num_total_features:
                log.fatal("Validation data feature count mismatch with "
                          "reference Dataset")
            num_features = reference.num_total_features
        elif num_features is None:
            num_features = sample.shape[1]
        elif sample.shape[1] != num_features:
            log.fatal(f"Stream width {sample.shape[1]} != declared "
                      f"num_features {num_features}")

        ds = cls()
        ds.num_data = n
        ds.num_total_features = num_features
        ds.max_bin = max_bin
        ds.feature_names = ([str(s) for s in feature_names]
                            if feature_names is not None else
                            [f"Column_{i}" for i in range(num_features)])
        if reference is not None:
            # validation-set alignment: reuse the training mappers
            # (ref: LoadFromFileAlignWithOtherDataset) — the sample pass
            # only counted rows and collected labels
            if reference.num_total_features != num_features:
                log.fatal("Validation data feature count mismatch with "
                          "reference Dataset")
            ds.bin_mappers = reference.bin_mappers
            ds.used_feature_map = reference.used_feature_map
            ds.used_features = reference.used_features
            ds.feature_names = reference.feature_names
            ds.max_bin = reference.max_bin
        else:
            ds._build_mappers(
                sample, len(sample), max_bin=max_bin,
                min_data_in_bin=min_data_in_bin,
                min_data_in_leaf=min_data_in_leaf,
                categorical_feature=categorical_feature,
                use_missing=use_missing, zero_as_missing=zero_as_missing,
                feature_pre_filter=feature_pre_filter,
                max_bin_by_feature=max_bin_by_feature,
                forcedbins_filename=forcedbins_filename)
        del sample

        # pass 2: stream again, bin chunks directly into the code matrix
        # (uint8 when every feature fits — 4x less resident memory and
        # device transfer than int32; ref Experiments.rst:160 two_round
        # peak-RAM table is the behavior being matched)
        narrow = all(m.num_bin <= 256 for m in ds.bin_mappers)
        code_t = np.uint8 if narrow else np.int32
        binned = np.empty((len(ds.used_features), n), dtype=code_t)
        off = 0
        with global_timer.scope("Dataset::binning"):
            for feats, _ in stream_factory():
                feats = np.asarray(feats, np.float64)
                c = feats.shape[0]
                if off + c > n:
                    log.fatal(
                        "Stream yielded more rows on pass 2 than pass 1")
                if feats.shape[1] < num_features:   # LibSVM implicit zeros
                    feats = np.pad(
                        feats, ((0, 0), (0, num_features - feats.shape[1])))
                for inner, f in enumerate(ds.used_features):
                    binned[inner, off:off + c] = \
                        ds.bin_mappers[f].values_to_bins(feats[:, f])
                off += c
        if off != n:
            log.fatal(f"Stream yielded {off} rows on pass 2, {n} on pass 1")
        ds.binned = binned

        md = Metadata(n)
        if labels_parts:
            md.set_label(np.concatenate(labels_parts))
        md.set_weight(weight)
        md.set_group(group)
        ds.metadata = md
        return ds

    # ------------------------------------------------------------------
    def create_valid(self, data: np.ndarray, label=None, weight=None, group=None,
                     init_score=None) -> "Dataset":
        return Dataset.construct_from_arrays(
            data, label=label, weight=weight, group=group, init_score=init_score,
            reference=self)

    # ------------------------------------------------------------------
    def copy_subrow(self, used_indices: np.ndarray) -> "Dataset":
        """Row-subset copy for bagging (ref: dataset.h:660 CopySubrow)."""
        used_indices = np.asarray(used_indices, dtype=np.int64)
        sub = Dataset()
        sub.num_data = len(used_indices)
        sub.num_total_features = self.num_total_features
        sub.feature_names = self.feature_names
        sub.bin_mappers = self.bin_mappers
        sub.used_feature_map = self.used_feature_map
        sub.used_features = self.used_features
        sub.max_bin = self.max_bin
        sub.binned = self.binned_host()[:, used_indices]
        sub.pre_bundled_plan = self.pre_bundled_plan
        md = Metadata(sub.num_data)
        src = self.metadata
        md.set_label(src.label[used_indices])
        if src.weight is not None:
            md.set_weight(src.weight[used_indices])
        if src.init_score is not None:
            if len(src.init_score) == self.num_data:
                md.set_init_score(src.init_score[used_indices])
            else:  # num_data * num_class layout (ref: metadata.cpp init_score)
                num_class = len(src.init_score) // self.num_data
                stacked = src.init_score.reshape(num_class, self.num_data)
                md.set_init_score(stacked[:, used_indices].reshape(-1))
        if src.query_boundaries is not None:
            # rebuild query boundaries from per-row query ids of the selected rows
            # (ref: metadata.cpp Metadata::Init(metadata, used_indices))
            qid = np.searchsorted(src.query_boundaries, used_indices, side="right") - 1
            counts = np.bincount(qid, minlength=src.num_queries)
            counts = counts[counts > 0]
            md.query_boundaries = np.concatenate(
                [[0], np.cumsum(counts)]).astype(np.int32)
        if src.position is not None:
            md.set_position(src.position[used_indices])
        sub.metadata = md
        if self.raw_data is not None:
            sub.raw_data = self.raw_data[used_indices]
        return sub

    # ------------------------------------------------------------------
    def feature_infos(self) -> List[str]:
        return [m.feature_info_str() for m in self.bin_mappers]

    def save_binary(self, path: str) -> None:
        """Binary dataset checkpoint (ref: dataset.h:691 SaveBinaryFile)."""
        md = self.metadata
        np.savez_compressed(
            path,
            binned=self.binned_host(),
            label=md.label,
            weight=md.weight if md.weight is not None else np.array([]),
            init_score=md.init_score if md.init_score is not None else np.array([]),
            query_boundaries=(md.query_boundaries if md.query_boundaries is not None
                              else np.array([], dtype=np.int32)),
            meta_json=np.frombuffer(json.dumps({
                "num_data": self.num_data,
                "num_total_features": self.num_total_features,
                "feature_names": self.feature_names,
                "used_features": self.used_features,
                "used_feature_map": self.used_feature_map,
                "max_bin": self.max_bin,
                "bin_mappers": [m.to_dict() for m in self.bin_mappers],
                "bundle_plan": (None if self.pre_bundled_plan is None else {
                    "groups": [list(map(int, g))
                               for g in self.pre_bundled_plan.groups],
                    "group_idx": self.pre_bundled_plan.group_idx.tolist(),
                    "offsets": self.pre_bundled_plan.offsets.tolist(),
                    "zero_bin": self.pre_bundled_plan.zero_bin.tolist(),
                    "in_bundle":
                        self.pre_bundled_plan.in_bundle.astype(int).tolist(),
                    "group_num_bin":
                        self.pre_bundled_plan.group_num_bin.tolist(),
                }),
            }).encode(), dtype=np.uint8))

    @classmethod
    def load_binary(cls, path: str) -> "Dataset":
        """(ref: dataset_loader.cpp:417 LoadFromBinFile)."""
        if not path.endswith(".npz"):
            path = path + ".npz"
        z = np.load(path, allow_pickle=False)
        meta = json.loads(bytes(z["meta_json"]).decode())
        ds = cls()
        ds.num_data = meta["num_data"]
        ds.num_total_features = meta["num_total_features"]
        ds.feature_names = meta["feature_names"]
        ds.used_features = meta["used_features"]
        ds.used_feature_map = meta["used_feature_map"]
        ds.max_bin = meta["max_bin"]
        ds.bin_mappers = [BinMapper.from_dict(d) for d in meta["bin_mappers"]]
        ds.binned = z["binned"]
        bp = meta.get("bundle_plan")
        if bp is not None:
            from .bundle import BundlePlan
            ds.pre_bundled_plan = BundlePlan(
                [list(g) for g in bp["groups"]],
                np.asarray(bp["group_idx"], np.int32),
                np.asarray(bp["offsets"], np.int32),
                np.asarray(bp["zero_bin"], np.int32),
                np.asarray(bp["in_bundle"], bool),
                np.asarray(bp["group_num_bin"], np.int32))
        md = Metadata(ds.num_data)
        md.set_label(z["label"])
        if len(z["weight"]):
            md.set_weight(z["weight"])
        if len(z["init_score"]):
            md.set_init_score(z["init_score"])
        if len(z["query_boundaries"]):
            md.query_boundaries = z["query_boundaries"].astype(np.int32)
        ds.metadata = md
        return ds


def _read_side_files(path: str):
    """.weight / .query sidecar files (ref: metadata.cpp LoadWeights /
    LoadQueryBoundaries)."""
    weight = group = None
    try:
        with open(path + ".weight") as f:
            weight = np.array([float(x) for x in f.read().split()],
                              dtype=np.float32)
    except FileNotFoundError:
        pass
    try:
        with open(path + ".query") as f:
            group = np.array([int(x) for x in f.read().split()],
                             dtype=np.int64)
    except FileNotFoundError:
        pass
    return weight, group


def _parse_categorical(cfg, names) -> List[int]:
    """categorical_feature tokens -> column indices; `name:` tokens
    resolve against header names (ref: dataset_loader.cpp:35 SetHeader)."""
    cat_features: List[int] = []
    if cfg.categorical_feature:
        for tok in str(cfg.categorical_feature).split(","):
            tok = tok.strip()
            if tok.startswith("name:"):
                if names and tok[5:] in names:
                    cat_features.append(names.index(tok[5:]))
                else:
                    log.warning(f"categorical_feature {tok!r} not found "
                                "in header names; ignored")
            elif tok:
                cat_features.append(int(tok))
    return cat_features


def _load_two_round(path: str, cfg, reference: Optional[Dataset] = None
                    ) -> Dataset:
    """two_round=true file loading (ref: config.h two_round;
    dataset_loader.cpp:1022 SampleTextDataFromFile + :1100
    ExtractFeaturesFromFile): the file is streamed twice and the raw
    float matrix never materializes — peak RAM is one parse chunk + the
    bin-finding sample + the packed bin codes, matching the reference's
    Higgs two_round peak-RAM behavior (docs/Experiments.rst:160)."""
    from .parser import (_header_names_of, _label_index,
                         parse_file_stream)

    if cfg.linear_tree:
        # the reference rejects the combination (config.cpp: "Cannot use
        # two_round loading with linear tree"): linear leaves need the
        # raw values that two_round exists to not hold
        log.fatal("Cannot use two_round loading with linear tree")

    names = None
    if cfg.header:
        with open(path) as f:
            header_names = _header_names_of(f.readline().rstrip("\n\r"))
        li = _label_index(cfg.label_column, header_names)
        names = [h for i, h in enumerate(header_names) if i != li]

    def stream():
        # smaller chunks than the predict path: the parse transients
        # (joined text + float matrix + label split) are the two_round
        # loader's peak-memory driver
        return parse_file_stream(path, has_header=cfg.header,
                                 label_column=cfg.label_column,
                                 chunk_rows=16384)

    weight, group = _read_side_files(path)
    return Dataset.construct_from_stream(
        stream, weight=weight, group=group,
        max_bin=cfg.max_bin, min_data_in_bin=cfg.min_data_in_bin,
        min_data_in_leaf=cfg.min_data_in_leaf,
        bin_construct_sample_cnt=cfg.bin_construct_sample_cnt,
        categorical_feature=_parse_categorical(cfg, names),
        feature_names=names, use_missing=cfg.use_missing,
        zero_as_missing=cfg.zero_as_missing,
        feature_pre_filter=cfg.feature_pre_filter,
        seed=cfg.data_random_seed,
        max_bin_by_feature=cfg.max_bin_by_feature or None,
        forcedbins_filename=cfg.forcedbins_filename,
        reference=reference)


def load_dataset_from_file(path: str, config_params: Optional[Dict[str, Any]] = None,
                           reference: Optional[Dataset] = None) -> Dataset:
    """File -> Dataset pipeline (ref: dataset_loader.cpp LoadFromFile)."""
    from ..config import Config
    from .parser import parse_file
    cfg = config_params if isinstance(config_params, Config) else Config(config_params or {})
    if path.endswith(".bin.npz") or path.endswith(".bin"):
        try:
            return Dataset.load_binary(path)
        except (FileNotFoundError, OSError, KeyError, ValueError):
            pass
    if cfg.two_round:
        return _load_two_round(path, cfg, reference=reference)
    feats, labels, names = parse_file(path, has_header=cfg.header,
                                      label_column=cfg.label_column)
    weight, group = _read_side_files(path)
    cat_features = _parse_categorical(cfg, names)
    if reference is not None:
        ds = reference.create_valid(feats, label=labels, weight=weight, group=group)
    else:
        ds = Dataset.construct_from_arrays(
            feats, label=labels, weight=weight, group=group,
            max_bin=cfg.max_bin, min_data_in_bin=cfg.min_data_in_bin,
            min_data_in_leaf=cfg.min_data_in_leaf,
            forcedbins_filename=cfg.forcedbins_filename,
            bin_construct_sample_cnt=cfg.bin_construct_sample_cnt,
            categorical_feature=cat_features,
            feature_names=names, use_missing=cfg.use_missing,
            zero_as_missing=cfg.zero_as_missing,
            feature_pre_filter=cfg.feature_pre_filter,
            seed=cfg.data_random_seed,
            keep_raw_data=cfg.linear_tree)
    return ds
