"""Device-side second-pass binning: value -> bin for the whole matrix on TPU.

The reference extracts features into bins with a parallel C++ pass over all
rows (ref: src/io/dataset_loader.cpp:246,327 ExtractFeaturesFromMemory under
OpenMP).  This host is single-core, so the NumPy per-feature `searchsorted`
pass costs ~68 s at 10M x 28 — the TPU replacement streams the raw float32
matrix to the device once and bucketizes every feature in one compiled
program (compare-and-count against the per-feature bound rows), writing the
uint8 bin matrix device-side.

Exactness: for float32 inputs the comparison `bound < v` in float64 is
EXACTLY equivalent to `floor32(bound) < v` in float32, where floor32 rounds
the float64 bound DOWN to the nearest float32 (any float32 v <= bound is
also <= floor32(bound), and bound < v implies floor32(bound) <= bound < v).
So the device path reproduces the host `np.searchsorted(bounds, v, 'left')`
bin codes bit-for-bit; it is only offered for float32 data (float64 inputs
keep the host pass, whose comparisons need the full mantissa).
"""

from __future__ import annotations

import functools

import numpy as np

from .binning import BIN_CATEGORICAL, MISSING_NAN


def bounds_to_f32_floor(bounds64: np.ndarray) -> np.ndarray:
    """Round float64 bin bounds DOWN to float32 (see module docstring)."""
    b64 = np.asarray(bounds64, np.float64)
    b32 = b64.astype(np.float32)
    over = b32.astype(np.float64) > b64
    if over.any():
        b32[over] = np.nextafter(b32[over], np.float32(-np.inf))
    return b32


def device_binnable(mappers, used_features, data_dtype, num_data: int,
                    min_rows: int = 1 << 20) -> bool:
    """Gate for the device second pass: float32 data, large-n, numeric
    features only, uint8-range bins, and a TPU backend present."""
    if data_dtype != np.float32 or num_data < min_rows:
        return False
    for f in used_features:
        m = mappers[f]
        if m.bin_type == BIN_CATEGORICAL or m.num_bin > 256:
            return False
    import jax
    return jax.default_backend() == "tpu"


@functools.lru_cache(maxsize=1)
def _bucketize_program():
    import jax
    import jax.numpy as jnp

    def prog(x, bounds, nan_zero, nan_bin, chunk: int):
        """x [n_pad, F] f32 (n_pad % chunk == 0), bounds [F, Bm] f32
        (floored, +inf padded), nan_zero [F] bool, nan_bin [F] int32
        -> [F, n_pad] uint8."""
        n, F = x.shape
        xr = x.reshape(n // chunk, chunk, F)

        def step(_, xc):
            nan = jnp.isnan(xc)
            xz = jnp.where(nan & nan_zero[None, :], jnp.float32(0), xc)
            cnt = jnp.sum((bounds[None, :, :] < xz[:, :, None]),
                          axis=-1, dtype=jnp.int32)      # [chunk, F]
            out = jnp.where(nan & ~nan_zero[None, :], nan_bin[None, :], cnt)
            return _, out.astype(jnp.uint8).T            # [F, chunk]

        _, outs = jax.lax.scan(step, None, xr)           # [C, F, chunk]
        return jnp.transpose(outs, (1, 0, 2)).reshape(F, n)

    return jax.jit(prog, static_argnames=("chunk",), donate_argnums=(0,))


def bin_matrix_device(data: np.ndarray, mappers, used_features,
                      chunk: int = 1 << 16):
    """Bin `data[:, used_features]` on device; returns a DEVICE
    jax.Array [F_used, n] uint8 — the whole point is that the bin
    matrix never visits the host (callers needing host bins go through
    Dataset.binned_host()).  Caller must have passed the
    `device_binnable` gate (float32 numeric data) — except
    `num_data`/backend, which only guard profitability, not correctness
    (tests run this on CPU)."""
    import jax
    import jax.numpy as jnp

    n = data.shape[0]
    Fu = len(used_features)
    n_bounds = []
    for f in used_features:
        m = mappers[f]
        n_search = m.num_bin - (1 if m.missing_type == MISSING_NAN else 0)
        n_bounds.append(m.bin_upper_bound[:n_search - 1]
                        if n_search > 0 else np.empty(0))
    Bm = max(1, max(len(b) for b in n_bounds))
    bounds = np.full((Fu, Bm), np.inf, np.float32)
    nan_zero = np.empty(Fu, bool)
    nan_bin = np.empty(Fu, np.int32)
    for i, f in enumerate(used_features):
        m = mappers[f]
        bounds[i, :len(n_bounds[i])] = bounds_to_f32_floor(n_bounds[i])
        nan_zero[i] = m.missing_type != MISSING_NAN
        nan_bin[i] = m.num_bin - 1
    n_pad = (n + chunk - 1) // chunk * chunk
    x = data if data.shape[1] == Fu else data[:, used_features]
    x = np.ascontiguousarray(x, np.float32)
    if n_pad != n:
        x = np.concatenate([x, np.zeros((n_pad - n, Fu), np.float32)])
    out = _bucketize_program()(jax.device_put(x), jnp.asarray(bounds),
                               jnp.asarray(nan_zero), jnp.asarray(nan_bin),
                               chunk)
    return out[:, :n] if n != out.shape[1] else out


def pull_host(binned) -> np.ndarray:
    """Device [F, n] -> host np.ndarray.  The remote-TPU tunnel pulls 2-D
    u8 arrays ~3x slower than flat buffers (minor-dim chunking), so the
    array is flattened device-side first."""
    import jax
    if not isinstance(binned, jax.Array):
        return np.asarray(binned)
    F, n = binned.shape
    flat = jax.jit(lambda a: a.reshape(-1))(binned)
    return np.asarray(flat).reshape(F, n)
