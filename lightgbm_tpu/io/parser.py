"""Text parsers: CSV / TSV / LibSVM with format auto-detection.

Mirrors the reference parser behavior (ref: src/io/parser.cpp:1-395): detect the
delimiter and sparse (LibSVM `idx:value`) format from the first lines, resolve the
label column, return dense float64 rows (NaN for missing).  NumPy-vectorized.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..utils import log


def _detect_format(sample_lines: List[str]) -> Tuple[str, str]:
    """Return (kind, delimiter) where kind in {'libsvm','dense'}
    (ref: parser.cpp GetDelimiter/DecideParser)."""
    # libsvm if any token beyond the first contains ':'
    for line in sample_lines:
        toks = line.replace("\t", " ").replace(",", " ").split()
        if any(":" in t for t in toks[1:]):
            return "libsvm", " "
    first = sample_lines[0]
    for delim in ("\t", ",", " "):
        if delim in first:
            return "dense", delim
    return "dense", "\t"


def _header_names_of(header_line: str):
    """Split a header line on the first matching delimiter."""
    for d in ("\t", ",", " "):
        if d in header_line:
            return header_line.split(d)
    return [header_line]


def _label_index(label_column: str, header_names) -> int:
    """'' (first column), 'N' (index), or 'name:COL' (header name)
    (ref: dataset_loader.cpp:35-130 SetHeader label resolution)."""
    if not label_column:
        return 0
    if label_column.startswith("name:"):
        name = label_column[5:]
        if header_names is None or name not in header_names:
            log.fatal(f"Label column '{name}' not found in header")
        return header_names.index(name)
    return int(label_column)


def parse_file(path: str, has_header: bool = False,
               label_column: str = "") -> Tuple[np.ndarray, np.ndarray, Optional[List[str]]]:
    """Parse a data file -> (features [n, F] float64 with NaN missing, labels [n],
    feature_names or None).

    label_column: '' (first column), 'N' (index), or 'name:COL' (header name)
    (ref: dataset_loader.cpp:35-130 SetHeader label resolution).
    """
    with open(path) as f:
        lines = [ln.rstrip("\n\r") for ln in f if ln.strip()]
    if not lines:
        log.fatal(f"Empty data file: {path}")
    header_names: Optional[List[str]] = None
    if has_header:
        header_line = lines[0]
        lines = lines[1:]
        if not lines:
            log.fatal(f"Data file has a header but no data rows: {path}")
    kind, delim = _detect_format(lines[:32])
    if has_header:
        header_names = _header_names_of(header_line)
    label_idx = _label_index(label_column, header_names)

    if kind == "libsvm":
        feats, labels = _parse_libsvm_lines(lines)
        return feats, labels, None  # libsvm ignores header feature names

    feats, labels = _parse_dense_lines(lines, delim, label_idx)
    if header_names is not None:
        feat_names = [h for i, h in enumerate(header_names) if i != label_idx]
    else:
        feat_names = None
    return feats, labels, feat_names


def _parse_libsvm_lines(lines, width_hint: int = 0, line_offset: int = 0):
    """LibSVM lines -> (feats [n, max(width_hint, max_idx+1)], labels).
    Native hot loop (ref: parser.cpp LibSVMParser) with Python fallback."""
    from ..native import parser_lib
    have_native = parser_lib() is not None
    if have_native:
        from ..native import parse_libsvm_native
        parsed = parse_libsvm_native("\n".join(lines).encode(),
                                     line_offset=line_offset)
        if parsed is not None:
            feats, labels = parsed
            if width_hint and feats.shape[1] < width_hint:
                feats = np.pad(feats,
                               ((0, 0), (0, width_hint - feats.shape[1])))
            return feats, labels
    labels = np.empty(len(lines), dtype=np.float64)
    rows: List[List[Tuple[int, float]]] = []
    max_idx = width_hint - 1
    for i, line in enumerate(lines):
        toks = line.split()
        labels[i] = float(toks[0])
        row = []
        for t in toks[1:]:
            k, v = t.split(":", 1)
            ki = int(k)
            if ki < 0:
                # match the native parser's rejection — same exception
                # type and message shape as parse_libsvm_native
                # (native/parser.c lgbt_parse_libsvm): a negative index
                # must not train silently via negative indexing
                raise ValueError("malformed libsvm pair on data line "
                                 f"{line_offset + i + 1}")
            row.append((ki, float(v)))
            max_idx = max(max_idx, ki)
        rows.append(row)
    feats = np.zeros((len(lines), max_idx + 1), dtype=np.float64)
    for i, row in enumerate(rows):
        for k, v in row:
            feats[i, k] = v
    return feats, labels


def _parse_dense_lines(lines, delim: str, label_idx: int):
    """Dense delimited lines -> (feats, labels).  Native tokenizer when
    available (ref: parser.cpp CSVParser), else the vectorized Python
    path (handles '' -> NaN identically)."""
    from ..native import parser_lib
    n_cols = len(lines[0].split(delim))
    mat = None
    if parser_lib() is not None:
        from ..native import parse_dense_native
        mat = parse_dense_native("\n".join(lines).encode(), delim,
                                 len(lines), n_cols)
    if mat is None:
        mat = np.array(
            [[(np.nan if tok == "" or tok.lower() in ("na", "nan", "null")
               else float(tok))
              for tok in line.split(delim)] for line in lines],
            dtype=np.float64)
    labels = mat[:, label_idx].copy()
    feats = np.delete(mat, label_idx, axis=1)
    return feats, labels


def parse_file_stream(path: str, has_header: bool = False,
                      label_column: str = "", chunk_rows: int = 65536,
                      num_features: int = 0):
    """Stream a data file in bounded row chunks, yielding (feats, labels)
    per chunk — the TPU-native analogue of the reference's double-buffered
    PipelineReader predict path (ref: predictor.hpp:30, application's
    predict loop): peak memory is one chunk, not the file.

    num_features: width hint for LibSVM chunks (a chunk may not contain
    the globally-largest feature index; predictions need the model's
    feature count)."""
    header_names: Optional[List[str]] = None
    kind = delim = None
    label_idx = 0
    buf: List[str] = []
    offset = 0

    def parse_chunk(chunk, off):
        if kind == "libsvm":
            return _parse_libsvm_lines(chunk, width_hint=num_features,
                                       line_offset=off)
        return _parse_dense_lines(chunk, delim, label_idx)

    with open(path) as f:
        if has_header:
            header_line = f.readline().rstrip("\n\r")
            if not header_line:
                log.fatal(f"Empty data file: {path}")
            header_names = _header_names_of(header_line)
        label_idx = _label_index(label_column, header_names)
        for ln in f:
            ln = ln.rstrip("\n\r")
            if not ln.strip():
                continue
            buf.append(ln)
            if kind is None and len(buf) >= 32:
                kind, delim = _detect_format(buf[:32])
            if len(buf) >= chunk_rows:
                if kind is None:
                    kind, delim = _detect_format(buf)
                yield parse_chunk(buf, offset)
                offset += len(buf)
                buf = []
    if buf:
        if kind is None:
            kind, delim = _detect_format(buf)
        yield parse_chunk(buf, offset)
    elif offset == 0:
        log.fatal(f"Empty data file: {path}")
