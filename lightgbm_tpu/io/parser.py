"""Text parsers: CSV / TSV / LibSVM with format auto-detection.

Mirrors the reference parser behavior (ref: src/io/parser.cpp:1-395): detect the
delimiter and sparse (LibSVM `idx:value`) format from the first lines, resolve the
label column, return dense float64 rows (NaN for missing).  NumPy-vectorized.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..utils import log


def _detect_format(sample_lines: List[str]) -> Tuple[str, str]:
    """Return (kind, delimiter) where kind in {'libsvm','dense'}
    (ref: parser.cpp GetDelimiter/DecideParser)."""
    # libsvm if any token beyond the first contains ':'
    for line in sample_lines:
        toks = line.replace("\t", " ").replace(",", " ").split()
        if any(":" in t for t in toks[1:]):
            return "libsvm", " "
    first = sample_lines[0]
    for delim in ("\t", ",", " "):
        if delim in first:
            return "dense", delim
    return "dense", "\t"


def parse_file(path: str, has_header: bool = False,
               label_column: str = "") -> Tuple[np.ndarray, np.ndarray, Optional[List[str]]]:
    """Parse a data file -> (features [n, F] float64 with NaN missing, labels [n],
    feature_names or None).

    label_column: '' (first column), 'N' (index), or 'name:COL' (header name)
    (ref: dataset_loader.cpp:35-130 SetHeader label resolution).
    """
    with open(path) as f:
        lines = [ln.rstrip("\n\r") for ln in f if ln.strip()]
    if not lines:
        log.fatal(f"Empty data file: {path}")
    header_names: Optional[List[str]] = None
    if has_header:
        header_line = lines[0]
        lines = lines[1:]
        if not lines:
            log.fatal(f"Data file has a header but no data rows: {path}")
    kind, delim = _detect_format(lines[:32])
    if has_header:
        for d in ("\t", ",", " "):
            if d in header_line:
                header_names = header_line.split(d)
                break
        else:
            header_names = [header_line]

    label_idx = 0
    if label_column:
        if label_column.startswith("name:"):
            name = label_column[5:]
            if header_names is None or name not in header_names:
                log.fatal(f"Label column '{name}' not found in header")
            label_idx = header_names.index(name)
        else:
            label_idx = int(label_column)

    from ..native import parser_lib
    have_native = parser_lib() is not None
    # the joined byte copy is only built when the native path will use it
    body = "\n".join(lines).encode() if have_native else b""

    if kind == "libsvm":
        # native hot loop (ref: parser.cpp LibSVMParser); Python fallback
        if have_native:
            from ..native import parse_libsvm_native
            parsed = parse_libsvm_native(body)
            if parsed is not None:
                return parsed[0], parsed[1], None
        labels = np.empty(len(lines), dtype=np.float64)
        rows: List[List[Tuple[int, float]]] = []
        max_idx = -1
        for i, line in enumerate(lines):
            toks = line.split()
            labels[i] = float(toks[0])
            row = []
            for t in toks[1:]:
                k, v = t.split(":", 1)
                ki = int(k)
                if ki < 0:
                    # match the native parser's rejection — same exception
                    # type and message shape as parse_libsvm_native
                    # (native/parser.c lgbt_parse_libsvm): a negative index
                    # must not train silently via negative indexing
                    raise ValueError(
                        f"malformed libsvm pair on data line {i + 1}")
                row.append((ki, float(v)))
                max_idx = max(max_idx, ki)
            rows.append(row)
        feats = np.zeros((len(lines), max_idx + 1), dtype=np.float64)
        for i, row in enumerate(rows):
            for k, v in row:
                feats[i, k] = v
        if header_names is not None:
            header_names = None  # libsvm ignores header names for features
        return feats, labels, None

    # dense: native tokenizer when available (ref: parser.cpp CSVParser),
    # else the vectorized Python path (handles '' -> NaN identically)
    n_cols = len(lines[0].split(delim))
    mat = None
    if have_native:
        from ..native import parse_dense_native
        mat = parse_dense_native(body, delim, len(lines), n_cols)
    if mat is None:
        mat = np.array(
            [[(np.nan if tok == "" or tok.lower() in ("na", "nan", "null")
               else float(tok))
              for tok in line.split(delim)] for line in lines],
            dtype=np.float64)
    labels = mat[:, label_idx].copy()
    feats = np.delete(mat, label_idx, axis=1)
    if header_names is not None:
        feat_names = [h for i, h in enumerate(header_names) if i != label_idx]
    else:
        feat_names = None
    return feats, labels, feat_names
