"""Sparse (scipy CSR/CSC/COO) ingestion WITHOUT densification.

TPU-native replacement for the reference's sparse bin storage
(ref: src/io/sparse_bin.hpp:1, multi_val_sparse_bin.hpp:1, and the
density heuristics in Dataset::GetShareStates, src/io/dataset.cpp).
The reference keeps per-feature delta-encoded sparse bins and a
multi-val row-wise bin for histogramming; on TPU the histogram pass
wants dense equal-shape columns, so the sparse path goes straight from
CSC columns to EFB bundle codes (io/bundle.py):

  CSC nonzeros -> per-feature bin mappers (zeros implied by count)
              -> nonzero bin codes (O(nnz))
              -> conflict-bounded greedy bundle plan (sampled rows)
              -> [num_bundles, n] dense uint8 bundle-code matrix

Host memory stays O(nnz + n * num_bundles + sample): the [n, F] dense
matrix is never materialized.  A 1M x 5000 matrix at 0.5% density lands
in a few dozen bundle columns (~tens of MB on device) instead of a 40 GB
dense float64 intermediate.

The resulting Dataset carries `pre_bundled_plan`; the GBDT driver uses
it directly instead of re-planning EFB from dense binned columns.

Validation sets against a sparse-trained reference reuse the reference's
plan, so valid rows where two bundle members conflict keep the LAST
member's code — the same by-design approximation EFB applies to training
rows (bounded there by max_conflict_rate; ref: FeatureGroup PushData).
A densified valid set keeps exact per-feature bins instead, so its
metric traces can differ in the 3rd decimal on conflicted rows.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..utils import log
from .binning import BIN_CATEGORICAL, BIN_NUMERICAL, BinMapper, \
    prep_find_bin_values
from .bundle import _SAMPLE, plan_bundles_from_masks
from .dataset import Dataset, Metadata, get_forced_bins


def is_scipy_sparse(data) -> bool:
    return hasattr(data, "tocsc") and hasattr(data, "nnz")


def construct_from_sparse(
        data,
        label=None, weight=None, group=None, init_score=None,
        max_bin: int = 255,
        min_data_in_bin: int = 3,
        min_data_in_leaf: int = 20,
        bin_construct_sample_cnt: int = 200000,
        categorical_feature: Optional[Sequence[int]] = None,
        feature_names: Optional[Sequence[str]] = None,
        use_missing: bool = True,
        zero_as_missing: bool = False,
        feature_pre_filter: bool = True,
        seed: int = 1,
        max_conflict_rate: float = 0.0,
        enable_bundle: bool = True,
        max_bin_by_feature: Optional[Sequence[int]] = None,
        forcedbins_filename: str = "",
        reference: Optional[Dataset] = None) -> Dataset:
    """Build a Dataset from a scipy sparse matrix, CSC-direct-to-bundles.

    Bin boundaries are IDENTICAL to the dense path's for the same data:
    the same row sample is drawn (same seed), and find_bin receives the
    same values (stored entries minus zeros, NaNs appended — exactly
    what prep_find_bin_values extracts from a dense column).
    """
    csc = data.tocsc()
    n, num_features = csc.shape
    ds = Dataset()
    ds.num_data = n
    ds.num_total_features = num_features
    ds.max_bin = max_bin
    if feature_names is not None:
        ds.feature_names = [str(s) for s in feature_names]
    else:
        ds.feature_names = [f"Column_{i}" for i in range(num_features)]

    ref_plan = None
    if reference is not None:
        if reference.num_total_features != num_features:
            log.fatal("Validation data feature count mismatch with "
                      "reference Dataset")
        ds.bin_mappers = reference.bin_mappers
        ds.used_feature_map = reference.used_feature_map
        ds.used_features = reference.used_features
        ds.feature_names = reference.feature_names
        ds.max_bin = reference.max_bin
        ref_plan = reference.pre_bundled_plan
    else:
        # row sample for bin finding (ref: bin_construct_sample_cnt);
        # CSR row slicing is O(nnz of the rows), then one CSC conversion
        # of the (small) sample
        if n > bin_construct_sample_cnt:
            rng = np.random.RandomState(seed)
            sample_idx = np.sort(rng.choice(n, bin_construct_sample_cnt,
                                            replace=False))
            sample_csc = data.tocsr()[sample_idx].tocsc()
        else:
            sample_csc = csc
        total_sample_cnt = sample_csc.shape[0]
        cat_set = set(categorical_feature or [])
        forced_bins = get_forced_bins(forcedbins_filename, num_features,
                                      cat_set)
        ds.bin_mappers = []
        for f in range(num_features):
            col_vals = sample_csc.data[
                sample_csc.indptr[f]:sample_csc.indptr[f + 1]]
            vals = prep_find_bin_values(col_vals)
            mapper = BinMapper()
            fmax_bin = (int(max_bin_by_feature[f])
                        if max_bin_by_feature else max_bin)
            mapper.find_bin(
                vals, total_sample_cnt, fmax_bin,
                min_data_in_bin=min_data_in_bin,
                min_split_data=min_data_in_leaf,
                pre_filter=feature_pre_filter,
                bin_type=(BIN_CATEGORICAL if f in cat_set
                          else BIN_NUMERICAL),
                use_missing=use_missing, zero_as_missing=zero_as_missing,
                forced_upper_bounds=forced_bins[f])
            ds.bin_mappers.append(mapper)
        ds.used_feature_map = []
        ds.used_features = []
        for f, m in enumerate(ds.bin_mappers):
            if m.is_trivial:
                ds.used_feature_map.append(-1)
            else:
                ds.used_feature_map.append(len(ds.used_features))
                ds.used_features.append(f)

    # --- nonzero bin codes per used feature (O(nnz), no dense bins).
    # TWO distinct "default" notions: the FILL bin (what an absent/zero
    # entry bins to, values_to_bins(0.0) for both types) and the bundle
    # PLAN default (bundle.py _default_bins: fill bin for numerical, the
    # NaN/other bin 0 for categorical).  When they differ (a categorical
    # whose category 0 is a real bin), the column is NOT sparse in bundle
    # terms — its implied rows are non-default — and is materialized
    # per-column so the plan and codes match the dense path exactly. ---
    nz_rows: List[np.ndarray] = []
    nz_bins: List[np.ndarray] = []
    zero_bin = np.zeros(len(ds.used_features), np.int32)
    nbins = np.zeros(len(ds.used_features), np.int32)
    for inner, f in enumerate(ds.used_features):
        m = ds.bin_mappers[f]
        s, e = csc.indptr[f], csc.indptr[f + 1]
        rows = np.asarray(csc.indices[s:e])
        bins = m.values_to_bins(np.asarray(csc.data[s:e], np.float64))
        fill = int(m.values_to_bins(np.zeros(1))[0])
        pzb = fill if m.bin_type == BIN_NUMERICAL else 0
        zero_bin[inner] = pzb
        nbins[inner] = m.num_bin
        if fill == pzb:
            keep = bins != pzb   # entries binning to the default act absent
            nz_rows.append(rows[keep])
            nz_bins.append(bins[keep].astype(np.int32))
        else:
            col = np.full(n, fill, np.int32)
            col[rows] = bins
            nzr = np.nonzero(col != pzb)[0]
            nz_rows.append(nzr)
            nz_bins.append(col[nzr])

    # --- conflict-bounded greedy bundling over a row sample (mirrors
    # io/bundle.py plan_bundles; ref: dataset.cpp FindGroups).  A
    # validation set against a sparse-trained reference reuses the
    # reference's plan so both sides decode identically; against a
    # dense-trained reference it emits plain per-feature bins. ---
    F = len(ds.used_features)
    if (reference is not None and ref_plan is None) or not enable_bundle:
        dtype = np.uint8 if max(
            (ds.bin_mappers[f].num_bin for f in ds.used_features),
            default=1) <= 256 else np.int32
        out = np.empty((F, n), dtype)
        for inner in range(F):
            col = np.full(n, zero_bin[inner], np.int32)
            col[nz_rows[inner]] = nz_bins[inner]
            out[inner] = col.astype(dtype)
        ds.binned = out
        md = Metadata(n)
        if label is not None:
            md.set_label(label)
        md.set_weight(weight)
        md.set_group(group)
        md.set_init_score(init_score)
        ds.metadata = md
        return ds
    if n <= _SAMPLE:
        in_sample = None
        sample_size = n
    else:
        srng = np.random.RandomState(3)
        srows = srng.choice(n, _SAMPLE, False)
        in_sample = np.full(n, -1, np.int64)
        in_sample[srows] = np.arange(_SAMPLE)
        sample_size = _SAMPLE

    _mask_cache = {}

    def sample_mask(inner):
        got = _mask_cache.get(inner)
        if got is not None:
            return got
        mask = np.zeros(sample_size, bool)
        r = nz_rows[inner]
        if in_sample is None:
            mask[r] = True
        else:
            pos = in_sample[r]
            mask[pos[pos >= 0]] = True
        _mask_cache[inner] = mask
        return mask

    if ref_plan is not None:
        # validation set against a sparse-trained reference: decode with
        # the SAME plan so train and valid bundle columns align
        plan = ref_plan
    else:
        # the shared greedy planner core over the SAME row sample the
        # dense path uses, so the plan is identical to plan_bundles on
        # the densified matrix

        class _LazyMasks:
            def __getitem__(self, f):
                return sample_mask(f)

        plan = plan_bundles_from_masks(_LazyMasks(), nbins, zero_bin,
                                       sample_size, max_conflict_rate)

    # --- bundle-code matrix [num_bundles, n]: the ONLY dense object ---
    dtype = np.uint8 if int(plan.group_num_bin.max(initial=1)) <= 256 \
        else np.int32
    out = np.zeros((plan.num_groups, n), dtype)
    for gi, members in enumerate(plan.groups):
        if len(members) == 1:
            f0 = members[0]
            col = np.full(n, plan.zero_bin[f0], np.int32)
            col[nz_rows[f0]] = nz_bins[f0]
            out[gi] = col.astype(dtype)
            continue
        col = np.zeros(n, np.int32)       # 0 = all members at default
        for f0 in members:                # later members win conflicts
            col[nz_rows[f0]] = plan.offsets[f0] + nz_bins[f0]
        out[gi] = col.astype(dtype)

    ds.binned = out
    ds.pre_bundled_plan = plan
    log.info(f"Sparse ingestion: {num_features} features "
             f"({csc.nnz} nonzeros) -> {plan.num_groups} bundle columns "
             f"without densification")

    md = Metadata(n)
    if label is not None:
        md.set_label(label)
    md.set_weight(weight)
    md.set_group(group)
    md.set_init_score(init_score)
    ds.metadata = md
    return ds
