"""Tree learners: jitted whole-tree growth on TPU.

Replaces the reference's src/treelearner/ (SerialTreeLearner + CUDA single-GPU
learner): the per-leaf loop runs inside one XLA program (lax.fori_loop) instead
of a host-driven kernel-launch loop, per SURVEY.md §3.3's TPU lesson.
"""

from .grow import (FeatureMeta, GrowParams, TreeArrays, grow_tree,
                   grow_tree_donated, make_grow_tree)
from .wave import grow_tree_wave, grow_tree_wave_donated

__all__ = ["FeatureMeta", "GrowParams", "TreeArrays", "grow_tree",
           "grow_tree_donated", "grow_tree_wave", "grow_tree_wave_donated",
           "make_grow_tree"]
