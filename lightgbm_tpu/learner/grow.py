"""Leaf-wise (best-first) tree growth as ONE jitted XLA program.

TPU-native re-design of SerialTreeLearner::Train
(ref: src/treelearner/serial_tree_learner.cpp:179-240) and the CUDA learner's
host-driven per-leaf loop (ref: src/treelearner/cuda/cuda_single_gpu_tree_learner.cpp:155-245).
Design differences from the reference, chosen for the TPU compilation model:

* The whole num_leaves-1 split loop is a `lax.fori_loop` inside one jit — no
  per-split host round trip (the CUDA learner pays a D2H sync per split;
  SURVEY.md §3.3 flags this as the thing to avoid on TPU).
* Row partition is a leaf-id recoloring array `leaf_id[n]` with fixed shape,
  not per-leaf index lists (ref: data_partition.hpp keeps ragged index lists —
  ragged shapes don't jit).
* Histogram bookkeeping keeps the reference's smaller-child trick: the smaller
  child's histogram is built fresh, the larger's is parent − smaller
  (ref: serial_tree_learner.cpp:334 BeforeFindBestSplit, feature_histogram.hpp
  Subtract).  A per-leaf histogram stack [L, F, B, 2] plays the role of the
  HistogramPool (ref: feature_histogram.hpp:1367); when it would not fit in
  HBM, `use_hist_stack=False` rebuilds both children instead.
* Bagging is a row mask multiplied into grad/hess (no subset copy);
  feature_fraction is a column mask into the gain scan.

All reductions over the row axis (histograms, sums, counts) are the only ops
touching sharded data, so the same program runs data-parallel under pjit with
rows sharded over a mesh — XLA inserts the psum that replaces
Network::ReduceScatter (ref: data_parallel_tree_learner.cpp:284).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops.histogram import build_histogram
from ..ops.split import (K_MIN_SCORE, SplitParams, SplitResult, find_best_split,
                         MISSING_NAN, MISSING_ZERO)


class FeatureMeta(NamedTuple):
    """Per-feature bin metadata, device-resident (ref: FeatureMetainfo,
    feature_histogram.hpp:40)."""
    num_bin: jnp.ndarray        # [F] int32
    missing_type: jnp.ndarray   # [F] int32
    default_bin: jnp.ndarray    # [F] int32
    penalty: jnp.ndarray        # [F] float32 (feature_contri)


class GrowParams(NamedTuple):
    """Static growth hyperparameters."""
    num_leaves: int = 31
    max_depth: int = -1
    max_bin: int = 255
    split: SplitParams = SplitParams()
    use_hist_stack: bool = True
    hist_method: str = "segment"


class TreeArrays(NamedTuple):
    """Device-side grown tree (mirrors Tree's parallel arrays, ref: tree.h:25)."""
    num_leaves: jnp.ndarray       # scalar int32
    split_feature: jnp.ndarray    # [L-1] int32 (inner feature index)
    threshold_bin: jnp.ndarray    # [L-1] int32
    default_left: jnp.ndarray     # [L-1] bool
    split_gain: jnp.ndarray       # [L-1] float32
    left_child: jnp.ndarray       # [L-1] int32 (~leaf encoding)
    right_child: jnp.ndarray      # [L-1] int32
    internal_value: jnp.ndarray   # [L-1] float32
    internal_weight: jnp.ndarray  # [L-1] float32
    internal_count: jnp.ndarray   # [L-1] int32
    leaf_value: jnp.ndarray       # [L] float32
    leaf_weight: jnp.ndarray      # [L] float32
    leaf_count: jnp.ndarray       # [L] int32
    leaf_parent: jnp.ndarray      # [L] int32
    leaf_depth: jnp.ndarray       # [L] int32


class _PendingSplits(NamedTuple):
    """Best pending split per leaf (ref: best_split_per_leaf_,
    serial_tree_learner.h:172)."""
    gain: jnp.ndarray           # [L]
    feature: jnp.ndarray        # [L] int32
    threshold: jnp.ndarray      # [L] int32
    default_left: jnp.ndarray   # [L] bool
    left_sum_gradient: jnp.ndarray
    left_sum_hessian: jnp.ndarray
    left_count: jnp.ndarray
    left_output: jnp.ndarray
    right_sum_gradient: jnp.ndarray
    right_sum_hessian: jnp.ndarray
    right_count: jnp.ndarray
    right_output: jnp.ndarray


class _State(NamedTuple):
    tree: TreeArrays
    pending: _PendingSplits
    leaf_id: jnp.ndarray
    hist_stack: jnp.ndarray     # [L, F, B, 2] (or [1,1,1,2] dummy)
    leaf_sum_g: jnp.ndarray     # [L]
    leaf_sum_h: jnp.ndarray     # [L]
    done: jnp.ndarray           # scalar bool


def _pending_set(p: _PendingSplits, idx, res: SplitResult) -> _PendingSplits:
    return _PendingSplits(
        gain=p.gain.at[idx].set(res.gain),
        feature=p.feature.at[idx].set(res.feature),
        threshold=p.threshold.at[idx].set(res.threshold),
        default_left=p.default_left.at[idx].set(res.default_left),
        left_sum_gradient=p.left_sum_gradient.at[idx].set(res.left_sum_gradient),
        left_sum_hessian=p.left_sum_hessian.at[idx].set(res.left_sum_hessian),
        left_count=p.left_count.at[idx].set(res.left_count),
        left_output=p.left_output.at[idx].set(res.left_output),
        right_sum_gradient=p.right_sum_gradient.at[idx].set(res.right_sum_gradient),
        right_sum_hessian=p.right_sum_hessian.at[idx].set(res.right_sum_hessian),
        right_count=p.right_count.at[idx].set(res.right_count),
        right_output=p.right_output.at[idx].set(res.right_output))


@functools.partial(jax.jit, static_argnames=("params",))
def grow_tree(binned: jnp.ndarray, grad: jnp.ndarray, hess: jnp.ndarray,
              row_mask: jnp.ndarray, col_mask: jnp.ndarray, meta: FeatureMeta,
              params: GrowParams):
    """Grow one leaf-wise tree.

    Args:
      binned: [F, n] int bin codes (n may include padded rows with row_mask=0).
      grad/hess: [n] float32 gradients/hessians.
      row_mask: [n] float32 0/1 (bagging x padding mask).
      col_mask: [F] bool (feature_fraction sampling).
      meta: per-feature bin metadata.
      params: static GrowParams.

    Returns: (TreeArrays, leaf_id [n] int32)
    """
    num_features, n = binned.shape
    L = params.num_leaves
    B = params.max_bin
    sp = params.split
    f32 = jnp.float32

    grad = grad.astype(f32) * row_mask.astype(f32)
    hess = hess.astype(f32) * row_mask.astype(f32)
    gh = jnp.stack([grad, hess], axis=1)
    ones_mask = jnp.ones((n,), dtype=f32)  # grad/hess already carry row_mask

    def hist_of(member_mask):
        return build_histogram(binned, gh, member_mask, max_bin=B,
                               method=params.hist_method)

    def best_of(hist, sum_g, sum_h, cnt, parent_out):
        return find_best_split(hist, meta.num_bin, meta.missing_type,
                               meta.default_bin, meta.penalty, col_mask,
                               sum_g, sum_h, cnt, parent_out, sp)

    # ---- root (ref: serial_tree_learner BeforeTrain + root leaf splits) ----
    sum_g0 = jnp.sum(grad)
    sum_h0 = jnp.sum(hess)
    cnt0 = jnp.sum(row_mask.astype(jnp.int32))
    root_hist = hist_of(ones_mask)
    root_best = best_of(root_hist, sum_g0, sum_h0, cnt0, jnp.asarray(0.0, f32))

    ni = max(L - 1, 1)
    tree = TreeArrays(
        num_leaves=jnp.asarray(1, jnp.int32),
        split_feature=jnp.zeros(ni, jnp.int32),
        threshold_bin=jnp.zeros(ni, jnp.int32),
        default_left=jnp.zeros(ni, bool),
        split_gain=jnp.zeros(ni, f32),
        left_child=jnp.zeros(ni, jnp.int32),
        right_child=jnp.zeros(ni, jnp.int32),
        internal_value=jnp.zeros(ni, f32),
        internal_weight=jnp.zeros(ni, f32),
        internal_count=jnp.zeros(ni, jnp.int32),
        leaf_value=jnp.zeros(L, f32),
        leaf_weight=jnp.zeros(L, f32).at[0].set(sum_h0),
        leaf_count=jnp.zeros(L, jnp.int32).at[0].set(cnt0),
        leaf_parent=jnp.full(L, -1, jnp.int32),
        leaf_depth=jnp.zeros(L, jnp.int32))
    pending = _PendingSplits(
        gain=jnp.full(L, K_MIN_SCORE, f32),
        feature=jnp.zeros(L, jnp.int32), threshold=jnp.zeros(L, jnp.int32),
        default_left=jnp.zeros(L, bool),
        left_sum_gradient=jnp.zeros(L, f32), left_sum_hessian=jnp.zeros(L, f32),
        left_count=jnp.zeros(L, jnp.int32), left_output=jnp.zeros(L, f32),
        right_sum_gradient=jnp.zeros(L, f32), right_sum_hessian=jnp.zeros(L, f32),
        right_count=jnp.zeros(L, jnp.int32), right_output=jnp.zeros(L, f32))
    pending = _pending_set(pending, 0, root_best)

    if params.use_hist_stack:
        hist_stack = jnp.zeros((L, num_features, B, 2), f32).at[0].set(root_hist)
    else:
        hist_stack = jnp.zeros((1, 1, 1, 2), f32)

    state = _State(tree=tree, pending=pending,
                   leaf_id=jnp.zeros(n, jnp.int32), hist_stack=hist_stack,
                   leaf_sum_g=jnp.zeros(L, f32).at[0].set(sum_g0),
                   leaf_sum_h=jnp.zeros(L, f32).at[0].set(sum_h0),
                   done=jnp.asarray(False))

    def body(i, st: _State):
        # leaf selection (ref: serial_tree_learner.cpp:219 ArgMax over leaves);
        # max_depth gates children depth (ref: serial_tree_learner BeforeFindBestSplit)
        sel_gain = st.pending.gain
        if params.max_depth > 0:
            sel_gain = jnp.where(st.tree.leaf_depth < params.max_depth,
                                 sel_gain, K_MIN_SCORE)
        best_leaf = jnp.argmax(sel_gain).astype(jnp.int32)
        proceed = jnp.logical_and(~st.done, sel_gain[best_leaf] > 0.0)

        def do_split(st: _State) -> _State:
            node = i                      # node index == step (num_leaves-1)
            new_leaf = i + 1              # new right-child leaf index
            pd = st.pending
            feat = pd.feature[best_leaf]
            thr = pd.threshold[best_leaf]
            dleft = pd.default_left[best_leaf]

            # --- partition by recoloring (ref: dense_bin.hpp:346-366 SplitInner) ---
            fbins = jnp.take(binned, feat, axis=0).astype(jnp.int32)
            mt_f = meta.missing_type[feat]
            is_missing = (((mt_f == MISSING_NAN) & (fbins == meta.num_bin[feat] - 1))
                          | ((mt_f == MISSING_ZERO) & (fbins == meta.default_bin[feat])))
            go_left = jnp.where(is_missing, dleft, fbins <= thr)
            in_leaf = st.leaf_id == best_leaf
            leaf_id = jnp.where(in_leaf & ~go_left, new_leaf, st.leaf_id)

            # actual per-child counts (ref: DataPartition gives actual counts)
            lmaskf = (in_leaf & go_left).astype(f32) * row_mask.astype(f32)
            rmaskf = (in_leaf & ~go_left).astype(f32) * row_mask.astype(f32)
            cnt_l = jnp.sum(lmaskf).astype(jnp.int32)
            cnt_r = jnp.sum(rmaskf).astype(jnp.int32)

            # --- tree arrays (ref: tree.cpp Tree::Split) ---
            t = st.tree
            parent = t.leaf_parent[best_leaf]
            # fix the parent's child pointer that referenced ~best_leaf
            lc = jnp.where((parent >= 0) & (t.left_child[parent] == ~best_leaf),
                           node, t.left_child[parent])
            rc = jnp.where((parent >= 0) & (t.left_child[parent] != ~best_leaf),
                           node, t.right_child[parent])
            left_child = t.left_child.at[parent].set(
                jnp.where(parent >= 0, lc, t.left_child[parent]))
            right_child = t.right_child.at[parent].set(
                jnp.where(parent >= 0, rc, t.right_child[parent]))
            depth = t.leaf_depth[best_leaf] + 1
            tree = TreeArrays(
                num_leaves=t.num_leaves + 1,
                split_feature=t.split_feature.at[node].set(feat),
                threshold_bin=t.threshold_bin.at[node].set(thr),
                default_left=t.default_left.at[node].set(dleft),
                split_gain=t.split_gain.at[node].set(pd.gain[best_leaf]),
                left_child=left_child.at[node].set(~best_leaf),
                right_child=right_child.at[node].set(~new_leaf),
                internal_value=t.internal_value.at[node].set(t.leaf_value[best_leaf]),
                internal_weight=t.internal_weight.at[node].set(
                    pd.left_sum_hessian[best_leaf] + pd.right_sum_hessian[best_leaf]),
                internal_count=t.internal_count.at[node].set(cnt_l + cnt_r),
                leaf_value=t.leaf_value.at[best_leaf].set(pd.left_output[best_leaf])
                                       .at[new_leaf].set(pd.right_output[best_leaf]),
                leaf_weight=t.leaf_weight.at[best_leaf].set(pd.left_sum_hessian[best_leaf])
                                         .at[new_leaf].set(pd.right_sum_hessian[best_leaf]),
                leaf_count=t.leaf_count.at[best_leaf].set(cnt_l)
                                       .at[new_leaf].set(cnt_r),
                leaf_parent=t.leaf_parent.at[best_leaf].set(node)
                                         .at[new_leaf].set(node),
                leaf_depth=t.leaf_depth.at[best_leaf].set(depth)
                                       .at[new_leaf].set(depth))

            # --- child histograms: smaller fresh, larger by subtraction
            # (ref: serial_tree_learner.cpp histogram subtraction) ---
            lsum_g, lsum_h = pd.left_sum_gradient[best_leaf], pd.left_sum_hessian[best_leaf]
            rsum_g, rsum_h = pd.right_sum_gradient[best_leaf], pd.right_sum_hessian[best_leaf]
            smaller_is_left = cnt_l <= cnt_r
            if params.use_hist_stack:
                small_mask = jnp.where(smaller_is_left, lmaskf, rmaskf)
                small_hist = hist_of(small_mask)
                parent_hist = st.hist_stack[best_leaf]
                large_hist = parent_hist - small_hist
                hist_l = jnp.where(smaller_is_left, small_hist, large_hist)
                hist_r = jnp.where(smaller_is_left, large_hist, small_hist)
                hist_stack = (st.hist_stack.at[best_leaf].set(hist_l)
                              .at[new_leaf].set(hist_r))
            else:
                hist_l = hist_of(lmaskf)
                hist_r = hist_of(rmaskf)
                hist_stack = st.hist_stack

            best_l = best_of(hist_l, lsum_g, lsum_h, cnt_l,
                             pd.left_output[best_leaf])
            best_r = best_of(hist_r, rsum_g, rsum_h, cnt_r,
                             pd.right_output[best_leaf])
            pending = _pending_set(_pending_set(pd, best_leaf, best_l),
                                   new_leaf, best_r)
            return _State(tree=tree, pending=pending, leaf_id=leaf_id,
                          hist_stack=hist_stack,
                          leaf_sum_g=st.leaf_sum_g.at[best_leaf].set(lsum_g)
                                                  .at[new_leaf].set(rsum_g),
                          leaf_sum_h=st.leaf_sum_h.at[best_leaf].set(lsum_h)
                                                  .at[new_leaf].set(rsum_h),
                          done=st.done)

        return jax.lax.cond(proceed, do_split,
                            lambda s: s._replace(done=jnp.asarray(True)), st)

    if L > 1:
        state = jax.lax.fori_loop(0, L - 1, body, state)
    return state.tree, state.leaf_id


def make_grow_tree(params: GrowParams):
    """Partial application helper so callers hold one traced function."""
    def fn(binned, grad, hess, row_mask, col_mask, meta):
        return grow_tree(binned, grad, hess, row_mask, col_mask, meta, params)
    return fn
