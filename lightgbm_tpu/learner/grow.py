"""Leaf-wise (best-first) tree growth as ONE jitted XLA program.

TPU-native re-design of SerialTreeLearner::Train
(ref: src/treelearner/serial_tree_learner.cpp:179-240) and the CUDA learner's
host-driven per-leaf loop (ref: src/treelearner/cuda/cuda_single_gpu_tree_learner.cpp:155-245).
Design differences from the reference, chosen for the TPU compilation model:

* The whole num_leaves-1 split loop is a `lax.fori_loop` inside one jit — no
  per-split host round trip (the CUDA learner pays a D2H sync per split;
  SURVEY.md §3.3 flags this as the thing to avoid on TPU).
* Row partition is a row-permutation `order` with contiguous per-leaf
  segments — the TPU analogue of DataPartition's per-leaf index lists
  (ref: data_partition.hpp:21).  Each split reads only the split leaf's
  segment through a pow2-bucketed `lax.switch` (static shapes), partitions
  it in place, and builds the smaller child's histogram from just those
  rows, so a tree costs ~n*log2(L) row visits like the reference's
  partitioned scan (ref: dense_bin.hpp:99-176), not n*(L-1).
* Histogram bookkeeping keeps the reference's smaller-child trick: the smaller
  child's histogram is built fresh, the larger's is parent − smaller
  (ref: serial_tree_learner.cpp:334 BeforeFindBestSplit, feature_histogram.hpp
  Subtract).  A per-leaf histogram stack [L, F, B, 2] plays the role of the
  HistogramPool (ref: feature_histogram.hpp:1367); when it would not fit in
  HBM, `use_hist_stack=False` rebuilds both children instead.
* Bagging is a row mask multiplied into grad/hess (no subset copy);
  feature_fraction is a column mask into the gain scan.

All reductions over the row axis (histograms, sums, counts) are the only ops
touching sharded data, so the same program runs data-parallel under pjit with
rows sharded over a mesh — XLA inserts the psum that replaces
Network::ReduceScatter (ref: data_parallel_tree_learner.cpp:284).  (The
partitioned engine gathers rows by global index, so the data-parallel path
uses the masked engine: set compact_min=0 under sharding.)
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops.histogram import build_histogram, build_histogram_rows_pallas
from ..ops.split import (K_MIN_SCORE, SplitParams, SplitResult,
                         cat_bitset_words, find_best_split,
                         MISSING_NAN, MISSING_ZERO)
from ..utils.timer import global_timer


class FeatureMeta(NamedTuple):
    """Per-feature bin metadata, device-resident (ref: FeatureMetainfo,
    feature_histogram.hpp:40)."""
    num_bin: jnp.ndarray        # [F] int32
    missing_type: jnp.ndarray   # [F] int32
    default_bin: jnp.ndarray    # [F] int32
    penalty: jnp.ndarray        # [F] float32 (feature_contri)
    is_cat: jnp.ndarray = None  # [F] bool (None when no categorical)
    monotone: jnp.ndarray = None  # [F] int32 -1/0/+1 (None when unused)
    cegb_coupled: jnp.ndarray = None  # [F] float32 coupled penalties
    cegb_lazy: jnp.ndarray = None     # [F] float32 lazy per-row penalties
    # EFB (ref: feature_group.h): feature -> bundle column, code offset,
    # default (zero) bin, membership flag (None/unused when not bundling)
    group: jnp.ndarray = None       # [F] int32 bundle column index
    offset: jnp.ndarray = None      # [F] int32 code offset (0 singleton)
    zero_bin: jnp.ndarray = None    # [F] int32 default bin
    in_bundle: jnp.ndarray = None   # [F] bool


class GrowParams(NamedTuple):
    """Static growth hyperparameters."""
    num_leaves: int = 31
    max_depth: int = -1
    max_bin: int = 255
    split: SplitParams = SplitParams()
    use_hist_stack: bool = True
    hist_method: str = "segment"
    # Partitioned-segment engine: the split leaf's rows are kept contiguous
    # in a row permutation and each split touches only that segment through
    # a pow2 bucket ladder starting at this size.  0 selects the masked
    # full-scan engine (every split rescans all n rows; needed under row
    # sharding, where rows may not be gathered by global index).
    compact_min: int = 4096
    # EFB: binned is [F_groups, n] bundle codes; histograms are built in
    # group space (group_max_bin bins) and converted back to per-feature
    # space for the scan (gather + FixHistogram by subtraction)
    has_bundles: bool = False
    group_max_bin: int = 0
    # forced splits (ref: serial_tree_learner.cpp:614 ForceSplits):
    # static BFS-ordered (leaf, inner_feature, threshold_bin) tuples
    # applied before best-gain growth; needs use_hist_stack
    forced_splits: tuple = ()
    # interaction constraints (ref: col_sampler.hpp:91 GetByNode): static
    # tuple of tuples of inner feature indices; a leaf may split only on
    # its branch features plus sets containing the whole branch
    interaction_sets: tuple = ()
    # per-node column sampling (ref: col_sampler.hpp fraction_bynode_):
    # each leaf scan draws a fresh feature subset of this fraction
    feature_fraction_bynode: float = 1.0
    bynode_seed: int = 2
    # voting-parallel (PV-Tree, ref: voting_parallel_tree_learner.cpp):
    # a parallel.voting.VotingSpec; per-leaf scans vote on top-k features
    # and reduce only the elected histograms across the mesh.  Requires
    # the masked engine (compact_min=0), no hist stack, no bundles.
    voting: object = None
    # quantized training: num_grad_quant_bins when use_quantized_grad —
    # the wave engine's Pallas kernel then accumulates exact int32
    # histograms through the MXU int8 path (needs quant_scales at call)
    quant_bins: int = 0
    # monotone_constraints_method=intermediate (ref:
    # monotone_constraints.hpp:516 IntermediateLeafConstraints): leaf
    # hyper-rectangles in bin space + a pairwise constraint recompute and
    # full pending rescan after every split replace the reference's
    # recursive GoUp/GoDownToFindLeavesToUpdate crawl.  Requires the
    # hist stack; incompatible with extra_trees / bynode sampling.
    monotone_intermediate: bool = False
    # wave engine: once the leaf budget binds, spend at most half of it
    # per wave (closer to the leaf-wise global-gain leaf allocation; a
    # few extra cheap waves).  See PERF_NOTES.md for the measured
    # wave-vs-leafwise AUC gap this addresses.
    wave_tail_halving: bool = False
    # wave engine: overgrow the tree past num_leaves with the normal
    # (cheap, level-batched) ladder, then PRUNE back to num_leaves by
    # simulating the reference's strict leaf-wise best-gain pop order
    # over the overgrown tree's exact split gains (ref:
    # serial_tree_learner.cpp:219 ArgMax leaf order).  Recovers the
    # leaf-wise tree exactly whenever its splits lie within the
    # overgrown depth; incompatible with monotone/CEGB (their
    # gains/constraints depend on realized split order).
    wave_prune: bool = False
    wave_prune_overshoot: float = 1.5
    # prune mode: leaves of the overgrow budget reserved for narrow
    # best-gain-only "spike" waves after the broad ladder (8 per wave;
    # deep probes into the top-gain frontier, see wave.py).  0 disables.
    wave_spike_reserve: int = 0
    wave_spike_k: int = 8        # splits per spike wave
    # monotone_constraints_method=advanced (ref:
    # monotone_constraints.hpp:858 AdvancedLeafConstraints): per-(leaf,
    # feature, threshold) constraint surfaces derived from the leaf
    # rects instead of the intermediate mode's whole-leaf scalar.
    # Requires monotone_intermediate.
    monotone_advanced: bool = False
    # data-parallel mesh axis name when the engine runs INSIDE
    # jax.shard_map over sharded rows (parallel/data_parallel.py
    # make_sharded_wave_fn): every row-axis reduction (histograms, root
    # sums, exact counts) is followed by a psum over this axis — the XLA
    # collective replacing the reference's Network::ReduceScatter of
    # histograms (ref: data_parallel_tree_learner.cpp:282-295).  None in
    # single-device / GSPMD-annotated runs.
    data_axis: object = None


def gather_forced_split(hist, ffeat, fthr, sum_g, sum_h_raw, nleaf,
                        meta: "FeatureMeta", B: int, sp) -> "SplitResult":
    """Scalar SplitResult for a FORCED (feature, threshold) split of one
    leaf, gathered from its feature-space histogram [F, B, 2] (ref:
    feature_histogram GatherInfoForThreshold; serial_tree_learner.cpp:614
    ForceSplits).  Missing values join the right side (default_left=False
    matches the partition rule both engines apply).  Shared by the
    leaf-wise prologue (forced_pending) and the wave engine's forced
    waves so the gather semantics cannot diverge."""
    from ..ops.split import leaf_gain, leaf_output
    f32 = jnp.float32
    sum_h = sum_h_raw + 2e-15
    cnt_factor = nleaf / sum_h
    bins = jnp.arange(B, dtype=jnp.int32)
    nb = meta.num_bin[ffeat]
    is_na = ((meta.missing_type[ffeat] == MISSING_NAN) & (bins == nb - 1))
    # MISSING_ZERO rows (the default bin) route right, matching
    # go_left_of's default_left=False partition of this split
    is_zero = ((meta.missing_type[ffeat] == MISSING_ZERO)
               & (bins == meta.default_bin[ffeat]))
    take = (bins <= fthr) & (bins < nb) & ~is_na & ~is_zero
    hf = hist[ffeat]
    lg = jnp.sum(jnp.where(take, hf[:, 0], 0.0))
    lh_raw = jnp.sum(jnp.where(take, hf[:, 1], 0.0))
    lh = lh_raw + 1e-15
    lc = jnp.round(lh_raw * cnt_factor).astype(jnp.int32)
    rg = sum_g - lg
    rh = sum_h - lh
    rc = jnp.round(nleaf).astype(jnp.int32) - lc
    po = jnp.asarray(0.0, f32)
    gain = (leaf_gain(lg, lh, lc.astype(f32), po, sp)
            + leaf_gain(rg, rh, rc.astype(f32), po, sp))
    valid = (lc > 0) & (rc > 0)
    from ..ops.split import SplitResult
    return SplitResult(
        gain=jnp.where(valid, gain, K_MIN_SCORE),
        feature=jnp.asarray(ffeat, jnp.int32),
        threshold=jnp.asarray(fthr, jnp.int32),
        default_left=jnp.asarray(False),
        left_sum_gradient=lg, left_sum_hessian=lh - 1e-15,
        left_count=lc,
        left_output=leaf_output(lg, lh, lc.astype(f32), po, sp),
        right_sum_gradient=rg, right_sum_hessian=rh - 1e-15,
        right_count=rc,
        right_output=leaf_output(rg, rh, rc.astype(f32), po, sp),
        is_cat=jnp.asarray(False),
        cat_bitset=jnp.zeros(cat_bitset_words(B), jnp.int32))



def bundle_hist_to_features(hist_g, sum_g, sum_h, meta: "FeatureMeta",
                            B: int, hist_B: int, has_bundles: bool):
    """[F_groups, B', 2] group hist -> [F, B, 2] per-feature hist under
    EFB: each member's code range is sliced out and its default bin is
    recovered by subtraction from the leaf totals
    (ref: dataset.h:759 FixHistogram).  No-op without bundles."""
    if not has_bundles:
        return hist_g
    cols = meta.offset[:, None] + jnp.arange(B, dtype=jnp.int32)[None, :]
    valid = ((jnp.arange(B, dtype=jnp.int32)[None, :]
              < meta.num_bin[:, None])
             & (cols < hist_B))
    hist_f = hist_g[meta.group[:, None],
                    jnp.clip(cols, 0, hist_B - 1)]          # [F, B, 2]
    hist_f = hist_f * valid[:, :, None]
    zb = meta.zero_bin
    nonzb = (jnp.arange(B, dtype=jnp.int32)[None, :] != zb[:, None])
    rest = jnp.sum(hist_f * nonzb[:, :, None], axis=1)      # [F, 2]
    fix = jnp.stack([sum_g, sum_h], -1)[None, :] - rest     # [F, 2]
    fixed = jnp.take_along_axis(
        hist_f, zb[:, None, None].repeat(2, 2), 1)
    new_zb = jnp.where(meta.in_bundle[:, None], fix, fixed[:, 0, :])
    hist_f = jnp.where(
        (jnp.arange(B, dtype=jnp.int32)[None, :, None]
         == zb[:, None, None]),
        new_zb[:, None, :], hist_f)
    return hist_f


class TreeArrays(NamedTuple):
    """Device-side grown tree (mirrors Tree's parallel arrays, ref: tree.h:25)."""
    num_leaves: jnp.ndarray       # scalar int32
    split_feature: jnp.ndarray    # [L-1] int32 (inner feature index)
    threshold_bin: jnp.ndarray    # [L-1] int32
    default_left: jnp.ndarray     # [L-1] bool
    split_gain: jnp.ndarray       # [L-1] float32
    left_child: jnp.ndarray       # [L-1] int32 (~leaf encoding)
    right_child: jnp.ndarray      # [L-1] int32
    internal_value: jnp.ndarray   # [L-1] float32
    internal_weight: jnp.ndarray  # [L-1] float32
    internal_count: jnp.ndarray   # [L-1] int32
    leaf_value: jnp.ndarray       # [L] float32
    leaf_weight: jnp.ndarray      # [L] float32
    leaf_count: jnp.ndarray       # [L] int32
    leaf_parent: jnp.ndarray      # [L] int32
    leaf_depth: jnp.ndarray       # [L] int32
    split_is_cat: jnp.ndarray = None  # [L-1] bool (categorical split)
    cat_bitset: jnp.ndarray = None    # [L-1, W] int32 bins-left bitsets


class _PendingSplits(NamedTuple):
    """Best pending split per leaf (ref: best_split_per_leaf_,
    serial_tree_learner.h:172)."""
    gain: jnp.ndarray           # [L]
    feature: jnp.ndarray        # [L] int32
    threshold: jnp.ndarray      # [L] int32
    default_left: jnp.ndarray   # [L] bool
    left_sum_gradient: jnp.ndarray
    left_sum_hessian: jnp.ndarray
    left_count: jnp.ndarray
    left_output: jnp.ndarray
    right_sum_gradient: jnp.ndarray
    right_sum_hessian: jnp.ndarray
    right_count: jnp.ndarray
    right_output: jnp.ndarray
    is_cat: jnp.ndarray          # [L] bool
    cat_bitset: jnp.ndarray      # [L, W] int32


class _State(NamedTuple):
    tree: TreeArrays
    pending: _PendingSplits
    leaf_id: jnp.ndarray
    hist_stack: jnp.ndarray     # [L, F, B, 2] (or [1,1,1,2] dummy)
    leaf_sum_g: jnp.ndarray     # [L]
    leaf_sum_h: jnp.ndarray     # [L]
    order: jnp.ndarray          # [n + S_max] row permutation (or [1] dummy)
    leaf_start: jnp.ndarray     # [L] segment starts (partitioned engine)
    leaf_seg_cnt: jnp.ndarray   # [L] segment lengths incl. bagged-out rows
    leaf_cmin: jnp.ndarray      # [L] monotone min constraint (or [1] dummy)
    leaf_cmax: jnp.ndarray      # [L] monotone max constraint
    cegb_used: jnp.ndarray      # [F] bool coupled-penalty paid (or [1])
    leaf_branch: jnp.ndarray    # [L, F] branch features (or [1, 1])
    done: jnp.ndarray           # scalar bool
    leaf_lo: jnp.ndarray = None  # [L, F] bin-space rect lower bounds
    leaf_hi: jnp.ndarray = None  # [L, F] rect upper bounds (exclusive)
    lazy_used: jnp.ndarray = None  # [F, n] bool rows already charged


def _pending_set(p: _PendingSplits, idx, res: SplitResult) -> _PendingSplits:
    return _PendingSplits(
        gain=p.gain.at[idx].set(res.gain),
        feature=p.feature.at[idx].set(res.feature),
        threshold=p.threshold.at[idx].set(res.threshold),
        default_left=p.default_left.at[idx].set(res.default_left),
        left_sum_gradient=p.left_sum_gradient.at[idx].set(res.left_sum_gradient),
        left_sum_hessian=p.left_sum_hessian.at[idx].set(res.left_sum_hessian),
        left_count=p.left_count.at[idx].set(res.left_count),
        left_output=p.left_output.at[idx].set(res.left_output),
        right_sum_gradient=p.right_sum_gradient.at[idx].set(res.right_sum_gradient),
        right_sum_hessian=p.right_sum_hessian.at[idx].set(res.right_sum_hessian),
        right_count=p.right_count.at[idx].set(res.right_count),
        right_output=p.right_output.at[idx].set(res.right_output),
        is_cat=p.is_cat.at[idx].set(res.is_cat),
        cat_bitset=p.cat_bitset.at[idx].set(res.cat_bitset))


def grow_tree_impl(binned: jnp.ndarray, grad: jnp.ndarray,
                   hess: jnp.ndarray, row_mask: jnp.ndarray,
                   col_mask: jnp.ndarray, meta: FeatureMeta,
                   params: GrowParams, cegb_used: jnp.ndarray = None,
                   extra_tag: jnp.ndarray = None,
                   lazy_used: jnp.ndarray = None):
    """Grow one leaf-wise tree.

    Args:
      binned: [F, n] int bin codes (n may include padded rows with row_mask=0).
      grad/hess: [n] float32 gradients/hessians.
      row_mask: [n] float32 0/1 (bagging x padding mask).
      col_mask: [F] bool (feature_fraction sampling).
      meta: per-feature bin metadata.
      params: static GrowParams.

    Returns: (TreeArrays, leaf_id [n] int32)
    """
    if params.has_bundles:
        num_features = meta.num_bin.shape[0]
    else:
        num_features = binned.shape[0]
    n = binned.shape[1]
    L = params.num_leaves
    B = params.max_bin
    hist_B = params.group_max_bin if params.has_bundles else B
    sp = params.split
    f32 = jnp.float32

    row_mask = row_mask.astype(f32)
    grad = grad.astype(f32) * row_mask
    hess = hess.astype(f32) * row_mask
    gh = jnp.stack([grad, hess], axis=1)
    ones_mask = jnp.ones((n,), dtype=f32)  # grad/hess already carry row_mask

    use_pallas = params.hist_method == "pallas"

    def to_feature_hist(hist_g, sum_g, sum_h):
        return bundle_hist_to_features(hist_g, sum_g, sum_h, meta, B,
                                       hist_B, params.has_bundles)

    def hist_of(member_mask):
        """Group-space histogram [F_groups, B', 2]; converted to feature
        space only at the scan (best_of), where the leaf sums needed by
        FixHistogram are in hand.  The per-leaf stack and the smaller-
        child subtraction stay in group space (subtraction is linear, so
        group-space subtraction == feature-space subtraction)."""
        with global_timer.device_scope("Tree::histogram"):
            if use_pallas:
                return build_histogram_rows_pallas(binned.T, gh,
                                                   member_mask,
                                                   max_bin=hist_B)
            return build_histogram(binned, gh, member_mask, max_bin=hist_B,
                                   method=params.hist_method)

    def hist_of_rows(rows, gh_sub, member_mask):
        """Histogram over row-major gathered rows [S, F_groups]."""
        with global_timer.device_scope("Tree::histogram"):
            if use_pallas:
                return build_histogram_rows_pallas(rows, gh_sub,
                                                   member_mask,
                                                   max_bin=hist_B)
            return build_histogram(rows.T, gh_sub, member_mask,
                                   max_bin=hist_B,
                                   method=params.hist_method)

    def mono_penalty_of(depth):
        """ref: monotone_constraints.hpp:357 ComputeMonotoneSplitGainPenalty."""
        pen = sp.monotone_penalty
        d = depth.astype(f32)
        eps = 1e-15
        return jnp.where(pen >= d + 1.0, eps,
                         jnp.where(pen <= 1.0,
                                   1.0 - pen / jnp.exp2(d) + eps,
                                   1.0 - jnp.exp2(pen - 1.0 - d) + eps))

    if params.interaction_sets:
        _iset_masks = [
            jnp.zeros(num_features, bool).at[jnp.asarray(S, jnp.int32)]
            .set(True) for S in params.interaction_sets]

        def allowed_of(branch):
            """[F] branch mask -> [F] allowed mask
            (ref: col_sampler.hpp:91 GetByNode)."""
            allow = branch
            for Sm in _iset_masks:
                ok = ~jnp.any(branch & ~Sm)
                allow = allow | (Sm & ok)
            return allow

    if sp.extra_trees:
        _extra_key = jax.random.PRNGKey(sp.extra_seed)
        if extra_tag is not None:
            # vary draws across trees/iterations (the reference's rand_
            # is stateful over the whole run)
            _extra_key = jax.random.fold_in(_extra_key, extra_tag)

    use_bynode = params.feature_fraction_bynode < 1.0
    if use_bynode:
        _bynode_key = jax.random.PRNGKey(params.bynode_seed)
        if extra_tag is not None:
            _bynode_key = jax.random.fold_in(_bynode_key, extra_tag)
        _bynode_k = max(1, int(round(
            params.feature_fraction_bynode * num_features)))

        def _bynode_mask(tag):
            """Exactly-k column subset per leaf scan
            (ref: col_sampler.hpp GetByNode sampling k indices)."""
            u = jax.random.uniform(jax.random.fold_in(_bynode_key, tag),
                                   (num_features,))
            kth = jax.lax.top_k(u, _bynode_k)[0][-1]
            return u >= kth

    def _rand_bins(tag):
        """One random threshold per feature for this leaf scan
        (ref: feature_histogram.hpp:204 rand.NextInt(0, num_bin - 2);
        2-bin features evaluate threshold 0)."""
        u = jax.random.uniform(jax.random.fold_in(_extra_key, tag),
                               (num_features,))
        span = jnp.maximum(meta.num_bin - 2, 1).astype(f32)
        return jnp.clip((u * span).astype(jnp.int32), 0,
                        jnp.maximum(meta.num_bin - 3, 0)).astype(jnp.int32)

    def _rand_cat_us(tag):
        """[F, 2] uniforms for the categorical USE_RAND draws (one-hot
        candidate bin + sorted-subset prefix; feature_histogram.cpp:187,268),
        from a stream distinct from the numerical draws."""
        return jax.random.uniform(
            jax.random.fold_in(jax.random.fold_in(_extra_key, 0x5EED), tag),
            (num_features, 2))

    use_voting = params.voting is not None
    if use_voting:
        assert params.compact_min == 0 and not params.use_hist_stack \
            and not params.has_bundles and not params.forced_splits, \
            "voting-parallel needs the masked engine without hist stack/EFB"
        from ..parallel.voting import voting_hist_elect

    use_intermediate = params.monotone_intermediate and sp.has_monotone
    if use_intermediate:
        assert params.use_hist_stack and not sp.extra_trees \
            and not use_bynode and not use_voting, \
            "intermediate monotone mode needs the hist stack and fixed " \
            "per-leaf scans (no extra_trees / bynode sampling / voting)"

    use_lazy = sp.has_cegb_lazy
    if use_lazy:
        assert not use_voting and not use_intermediate, \
            "cegb_penalty_feature_lazy composes with neither voting nor " \
            "intermediate monotone mode"
        if lazy_used is None:
            lazy_used = jnp.zeros((num_features, n), bool)

    def best_of(hist, sum_g, sum_h, cnt, parent_out, cmin=None, cmax=None,
                depth=None, rand_tag=0, used=None, branch=None,
                member_mask=None, lazy_mask=None, lazy_used_cur=None,
                adv=None):
        cm = col_mask
        if params.interaction_sets:
            cm = cm & allowed_of(branch)
        if use_bynode:
            cm = cm & _bynode_mask(rand_tag)
        if use_voting:
            # PV-Tree: vote + reduce only the elected features' histograms
            # (hist arg is ignored; the voted one is exact where elected)
            hist, elected = voting_hist_elect(
                binned, gh, member_mask, cm, parent_out, meta,
                params.voting, sp, hist_B, params.hist_method)
            cm = cm & elected
        kw: dict = {}
        if use_lazy:
            # per-feature on-demand cost: penalty x rows in the leaf whose
            # value for f has not been fetched yet (ref:
            # cost_effective_gradient_boosting.hpp:139)
            unused = jnp.sum(
                jnp.where(lazy_used_cur, 0.0, lazy_mask[None, :]), axis=1)
            kw["cegb_lazy_cost"] = meta.cegb_lazy * unused
        if sp.has_monotone:
            kw.update(monotone=meta.monotone, constraint_min=cmin,
                      constraint_max=cmax,
                      mono_penalty=mono_penalty_of(depth))
            if adv is not None:
                # advanced mode: per-child [F, B] constraint surfaces
                kw.update(constraint_min_left=adv[0],
                          constraint_max_left=adv[1],
                          constraint_min_right=adv[2],
                          constraint_max_right=adv[3])
        if sp.extra_trees:
            kw["rand_bin"] = _rand_bins(rand_tag)
            if sp.has_categorical:
                kw["rand_cat_u"] = _rand_cat_us(rand_tag)
        if sp.has_cegb:
            kw["cegb_coupled"] = meta.cegb_coupled
            kw["cegb_used"] = used
        with global_timer.device_scope("Tree::split_find"):
            return find_best_split(to_feature_hist(hist, sum_g, sum_h),
                                   meta.num_bin, meta.missing_type,
                                   meta.default_bin, meta.penalty, cm,
                                   sum_g, sum_h, cnt, parent_out, sp,
                                   is_cat_feature=meta.is_cat, **kw)

    # pow2 bucket ladder for the partitioned engine; the last bucket covers
    # the whole row range (used by the root split)
    bucket_sizes = []
    if 0 < params.compact_min < n and L > 2:
        s = params.compact_min
        while s < n:
            bucket_sizes.append(s)
            s *= 2
        bucket_sizes.append(n)
        # invariant for in-bounds dynamic slices: any segment larger than the
        # biggest sub-n bucket starts within the first S_MAX rows, so
        # start + n <= n + S_MAX (the padded order length) always holds
    use_partition = bool(bucket_sizes)
    S_MAX = bucket_sizes[-2] if len(bucket_sizes) > 1 else 0
    # binned in row-major [n, F] for per-segment row gathers (loop-invariant,
    # hoisted out of the split loop by XLA)
    binned_rows = binned.T if use_partition else None

    def go_left_of(fbins, feat, dleft, thr, isc, bitset):
        """Partition rule in bin space (ref: dense_bin.hpp:346-366
        SplitInner; categorical: bin in bitset -> left, ref: tree.h:372
        CategoricalDecision with the NaN/other bin 0 never in the set).
        Under EFB, fbins are BUNDLE codes: decode the feature's range,
        anything else means the feature sits at its default bin."""
        if params.has_bundles:
            local = fbins - meta.offset[feat]
            fbins = jnp.where((local >= 0) & (local < meta.num_bin[feat]),
                              local, meta.zero_bin[feat])
        mt_f = meta.missing_type[feat]
        is_missing = (((mt_f == MISSING_NAN) & (fbins == meta.num_bin[feat] - 1))
                      | ((mt_f == MISSING_ZERO) & (fbins == meta.default_bin[feat])))
        num_left = jnp.where(is_missing, dleft, fbins <= thr)
        if not sp.has_categorical:
            return num_left
        word = jnp.take(bitset, fbins // 32, mode="clip")
        cat_left = ((word >> (fbins % 32)) & 1) > 0
        return jnp.where(isc, cat_left, num_left)

    # ---- root (ref: serial_tree_learner BeforeTrain + root leaf splits) ----
    sum_g0 = jnp.sum(grad)
    sum_h0 = jnp.sum(hess)
    # explicit int32 accumulator: jnp.sum promotes int32 to int64 under
    # x64 (numpy semantics), which would widen the leaf_count scatter
    cnt0 = jnp.sum(row_mask, dtype=jnp.int32)
    root_hist = None if use_voting else hist_of(ones_mask)
    inf = jnp.asarray(jnp.inf, f32)
    if cegb_used is None:
        cegb_used = jnp.zeros(num_features if sp.has_cegb else 1, bool)
    branch0 = jnp.zeros(
        (L, num_features) if params.interaction_sets else (1, 1), bool)
    root_best = best_of(root_hist, sum_g0, sum_h0, cnt0,
                        jnp.asarray(0.0, f32), -inf, inf,
                        jnp.asarray(0, jnp.int32), rand_tag=0,
                        used=cegb_used, branch=branch0[0],
                        member_mask=row_mask, lazy_mask=row_mask,
                        lazy_used_cur=lazy_used)

    ni = max(L - 1, 1)
    W = cat_bitset_words(B)
    tree = TreeArrays(
        num_leaves=jnp.asarray(1, jnp.int32),
        split_feature=jnp.zeros(ni, jnp.int32),
        threshold_bin=jnp.zeros(ni, jnp.int32),
        default_left=jnp.zeros(ni, bool),
        split_gain=jnp.zeros(ni, f32),
        left_child=jnp.zeros(ni, jnp.int32),
        right_child=jnp.zeros(ni, jnp.int32),
        internal_value=jnp.zeros(ni, f32),
        internal_weight=jnp.zeros(ni, f32),
        internal_count=jnp.zeros(ni, jnp.int32),
        leaf_value=jnp.zeros(L, f32),
        leaf_weight=jnp.zeros(L, f32).at[0].set(sum_h0),
        leaf_count=jnp.zeros(L, jnp.int32).at[0].set(cnt0),
        leaf_parent=jnp.full(L, -1, jnp.int32),
        leaf_depth=jnp.zeros(L, jnp.int32),
        split_is_cat=jnp.zeros(ni, bool),
        cat_bitset=jnp.zeros((ni, W), jnp.int32))
    pending = _PendingSplits(
        gain=jnp.full(L, K_MIN_SCORE, f32),
        feature=jnp.zeros(L, jnp.int32), threshold=jnp.zeros(L, jnp.int32),
        default_left=jnp.zeros(L, bool),
        left_sum_gradient=jnp.zeros(L, f32), left_sum_hessian=jnp.zeros(L, f32),
        left_count=jnp.zeros(L, jnp.int32), left_output=jnp.zeros(L, f32),
        right_sum_gradient=jnp.zeros(L, f32), right_sum_hessian=jnp.zeros(L, f32),
        right_count=jnp.zeros(L, jnp.int32), right_output=jnp.zeros(L, f32),
        is_cat=jnp.zeros(L, bool), cat_bitset=jnp.zeros((L, W), jnp.int32))
    pending = _pending_set(pending, 0, root_best)

    if params.use_hist_stack:
        FH = binned.shape[0]
        hist_stack = jnp.zeros((L, FH, hist_B, 2), f32).at[0].set(root_hist)
    else:
        hist_stack = jnp.zeros((1, 1, 1, 2), f32)

    if use_partition:
        order0 = jnp.concatenate([jnp.arange(n, dtype=jnp.int32),
                                  jnp.zeros(max(S_MAX, 1), jnp.int32)])
        leaf_start0 = jnp.zeros(L, jnp.int32)
        leaf_seg_cnt0 = jnp.zeros(L, jnp.int32).at[0].set(n)
    else:
        order0 = jnp.zeros(1, jnp.int32)
        leaf_start0 = jnp.zeros(1, jnp.int32)
        leaf_seg_cnt0 = jnp.zeros(1, jnp.int32)

    if use_intermediate:
        # leaf hyper-rectangles in bin space (root covers every bin)
        leaf_lo0 = jnp.zeros((L, num_features), jnp.int32)
        leaf_hi0 = jnp.broadcast_to(meta.num_bin[None, :],
                                    (L, num_features)).astype(jnp.int32)
    else:
        leaf_lo0 = leaf_hi0 = jnp.zeros((1, 1), jnp.int32)
    state = _State(tree=tree, pending=pending,
                   leaf_id=jnp.zeros(n, jnp.int32), hist_stack=hist_stack,
                   leaf_sum_g=jnp.zeros(L, f32).at[0].set(sum_g0),
                   leaf_sum_h=jnp.zeros(L, f32).at[0].set(sum_h0),
                   order=order0, leaf_start=leaf_start0,
                   leaf_seg_cnt=leaf_seg_cnt0,
                   leaf_cmin=jnp.full(L if sp.has_monotone else 1, -jnp.inf,
                                      f32),
                   leaf_cmax=jnp.full(L if sp.has_monotone else 1, jnp.inf,
                                      f32),
                   cegb_used=cegb_used,
                   leaf_branch=branch0,
                   done=jnp.asarray(False),
                   leaf_lo=leaf_lo0, leaf_hi=leaf_hi0,
                   lazy_used=(lazy_used if use_lazy
                              else jnp.zeros((1, 1), bool)))

    def partition_and_hist(st: _State, best_leaf, new_leaf, feat, thr, dleft,
                           isc, bitset):
        """Partitioned engine: read the split leaf's segment through a pow2
        bucket, partition it in place (stable: left rows first), recolor the
        right rows' leaf_id, and build the smaller child's histogram from
        only the segment's rows (ref: DataPartition::Split +
        dense_bin.hpp:99 partitioned histogram scan)."""
        start = st.leaf_start[best_leaf]
        seg_cnt = st.leaf_seg_cnt[best_leaf]

        def make_branch(S):
            def branch(operand):
                order, leaf_id = operand
                idxs = jax.lax.dynamic_slice(order, (start,), (S,))
                valid = jnp.arange(S, dtype=jnp.int32) < seg_cnt
                rows = jnp.take(binned_rows, idxs, axis=0)     # [S, F']
                col = meta.group[feat] if params.has_bundles else feat
                fbins = jnp.take(rows, col, axis=1).astype(jnp.int32)
                gl = go_left_of(fbins, feat, dleft, thr, isc, bitset)
                lm = gl & valid
                rm = (~gl) & valid
                rmask = jnp.take(row_mask, idxs)
                cnt_l = jnp.sum(lm * rmask).astype(jnp.int32)
                cnt_r = jnp.sum(rm * rmask).astype(jnp.int32)
                gh_sub = jnp.take(gh, idxs, axis=0)
                smaller_is_left = cnt_l <= cnt_r
                if params.use_hist_stack:
                    small_m = jnp.where(smaller_is_left, lm, rm)
                    small_hist = hist_of_rows(rows, gh_sub,
                                              small_m.astype(f32))
                else:  # children rebuilt from scratch downstream
                    small_hist = jnp.zeros((binned.shape[0], hist_B, 2),
                                           f32)
                # stable in-place partition of the segment window; slots
                # beyond seg_cnt keep their original values
                cl_seg = jnp.sum(lm, dtype=jnp.int32)
                pos = jnp.where(
                    lm, jnp.cumsum(lm.astype(jnp.int32)) - 1,
                    jnp.where(rm,
                              cl_seg + jnp.cumsum(rm.astype(jnp.int32)) - 1,
                              S))
                buf = idxs.at[pos].set(idxs, mode="drop")
                order = jax.lax.dynamic_update_slice(order, buf, (start,))
                leaf_id = leaf_id.at[jnp.where(rm, idxs, n)].set(
                    new_leaf, mode="drop")
                return (order, leaf_id, small_hist, cnt_l, cnt_r, cl_seg,
                        smaller_is_left)
            return branch

        branches = [make_branch(S) for S in bucket_sizes]
        k = jnp.searchsorted(jnp.asarray(bucket_sizes, jnp.int32), seg_cnt)
        k = jnp.minimum(k, len(bucket_sizes) - 1)
        with global_timer.device_scope("Tree::partition"):
            (order, leaf_id, small_hist, cnt_l, cnt_r, cl_seg,
             smaller_is_left) = jax.lax.switch(k, branches,
                                               (st.order, st.leaf_id))
        leaf_start = st.leaf_start.at[new_leaf].set(start + cl_seg)
        leaf_seg_cnt = (st.leaf_seg_cnt.at[best_leaf].set(cl_seg)
                        .at[new_leaf].set(seg_cnt - cl_seg))
        return (order, leaf_id, leaf_start, leaf_seg_cnt, small_hist,
                cnt_l, cnt_r, smaller_is_left)

    def mask_and_hist(st: _State, best_leaf, new_leaf, feat, thr, dleft,
                      isc, bitset):
        """Masked engine: recolor by scanning all rows (data-parallel safe)."""
        with global_timer.device_scope("Tree::partition"):
            col = meta.group[feat] if params.has_bundles else feat
            fbins = jnp.take(binned, col, axis=0).astype(jnp.int32)
            gl = go_left_of(fbins, feat, dleft, thr, isc, bitset)
            in_leaf = st.leaf_id == best_leaf
            leaf_id = jnp.where(in_leaf & ~gl, new_leaf, st.leaf_id)
            lmaskf = (in_leaf & gl).astype(f32) * row_mask
            rmaskf = (in_leaf & ~gl).astype(f32) * row_mask
            cnt_l = jnp.sum(lmaskf).astype(jnp.int32)
            cnt_r = jnp.sum(rmaskf).astype(jnp.int32)
        smaller_is_left = cnt_l <= cnt_r
        if params.use_hist_stack:
            small_mask = jnp.where(smaller_is_left, lmaskf, rmaskf)
            small_hist = hist_of(small_mask)
        else:  # children rebuilt from scratch downstream
            small_hist = jnp.zeros((binned.shape[0], hist_B, 2), f32)
        return (st.order, leaf_id, st.leaf_start, st.leaf_seg_cnt, small_hist,
                cnt_l, cnt_r, smaller_is_left)

    KF = len(params.forced_splits)

    def body(i, st: _State, forced_leaf=None):
        # leaf selection (ref: serial_tree_learner.cpp:219 ArgMax over leaves);
        # max_depth gates children depth (ref: serial_tree_learner BeforeFindBestSplit)
        sel_gain = st.pending.gain
        if params.max_depth > 0:
            sel_gain = jnp.where(st.tree.leaf_depth < params.max_depth,
                                 sel_gain, K_MIN_SCORE)
        if forced_leaf is not None:
            # forced splits apply regardless of gain RANK but still
            # respect max_depth and the leaf budget (sel_gain carries the
            # depth mask; ForceSplits aborts past limits)
            best_leaf = jnp.asarray(forced_leaf, jnp.int32)
            proceed = jnp.logical_and(~st.done,
                                      sel_gain[best_leaf] > K_MIN_SCORE)
            proceed = jnp.logical_and(proceed, st.tree.num_leaves < L)
        else:
            best_leaf = jnp.argmax(sel_gain).astype(jnp.int32)
            proceed = jnp.logical_and(~st.done, sel_gain[best_leaf] > 0.0)
            # dynamic budget guard: with forced splits the loop trip
            # count exceeds the remaining budget (skipped forced steps
            # hand their slot back to best-gain growth)
            proceed = jnp.logical_and(proceed, st.tree.num_leaves < L)

        def do_split(st: _State) -> _State:
            # node index == step index in pure best-gain growth (static,
            # cheaper updates); skipped forced splits make them diverge,
            # so forced configs track the actual tree size dynamically
            if params.forced_splits:
                node = st.tree.num_leaves - 1
                new_leaf = st.tree.num_leaves
            else:
                node = i
                new_leaf = i + 1
            pd = st.pending
            feat = pd.feature[best_leaf]
            thr = pd.threshold[best_leaf]
            dleft = pd.default_left[best_leaf]
            isc = pd.is_cat[best_leaf]
            bitset = pd.cat_bitset[best_leaf]

            engine = partition_and_hist if use_partition else mask_and_hist
            (order, leaf_id, leaf_start, leaf_seg_cnt, small_hist,
             cnt_l, cnt_r, smaller_is_left) = engine(
                st, best_leaf, new_leaf, feat, thr, dleft, isc, bitset)

            # --- tree arrays (ref: tree.cpp Tree::Split) ---
            t = st.tree
            parent = t.leaf_parent[best_leaf]
            # fix the parent's child pointer that referenced ~best_leaf
            lc = jnp.where((parent >= 0) & (t.left_child[parent] == ~best_leaf),
                           node, t.left_child[parent])
            rc = jnp.where((parent >= 0) & (t.left_child[parent] != ~best_leaf),
                           node, t.right_child[parent])
            left_child = t.left_child.at[parent].set(
                jnp.where(parent >= 0, lc, t.left_child[parent]))
            right_child = t.right_child.at[parent].set(
                jnp.where(parent >= 0, rc, t.right_child[parent]))
            depth = t.leaf_depth[best_leaf] + 1
            tree = TreeArrays(
                num_leaves=t.num_leaves + 1,
                split_feature=t.split_feature.at[node].set(feat),
                threshold_bin=t.threshold_bin.at[node].set(thr),
                default_left=t.default_left.at[node].set(dleft),
                split_gain=t.split_gain.at[node].set(pd.gain[best_leaf]),
                left_child=left_child.at[node].set(~best_leaf),
                right_child=right_child.at[node].set(~new_leaf),
                internal_value=t.internal_value.at[node].set(t.leaf_value[best_leaf]),
                internal_weight=t.internal_weight.at[node].set(
                    pd.left_sum_hessian[best_leaf] + pd.right_sum_hessian[best_leaf]),
                internal_count=t.internal_count.at[node].set(cnt_l + cnt_r),
                leaf_value=t.leaf_value.at[best_leaf].set(pd.left_output[best_leaf])
                                       .at[new_leaf].set(pd.right_output[best_leaf]),
                leaf_weight=t.leaf_weight.at[best_leaf].set(pd.left_sum_hessian[best_leaf])
                                         .at[new_leaf].set(pd.right_sum_hessian[best_leaf]),
                leaf_count=t.leaf_count.at[best_leaf].set(cnt_l)
                                       .at[new_leaf].set(cnt_r),
                split_is_cat=t.split_is_cat.at[node].set(isc),
                cat_bitset=t.cat_bitset.at[node].set(bitset),
                leaf_parent=t.leaf_parent.at[best_leaf].set(node)
                                         .at[new_leaf].set(node),
                leaf_depth=t.leaf_depth.at[best_leaf].set(depth)
                                       .at[new_leaf].set(depth))

            # --- child histograms: smaller fresh, larger by subtraction
            # (ref: serial_tree_learner.cpp histogram subtraction) ---
            lsum_g, lsum_h = pd.left_sum_gradient[best_leaf], pd.left_sum_hessian[best_leaf]
            rsum_g, rsum_h = pd.right_sum_gradient[best_leaf], pd.right_sum_hessian[best_leaf]
            if params.use_hist_stack:
                parent_hist = st.hist_stack[best_leaf]
                large_hist = parent_hist - small_hist
                hist_l = jnp.where(smaller_is_left, small_hist, large_hist)
                hist_r = jnp.where(smaller_is_left, large_hist, small_hist)
                hist_stack = (st.hist_stack.at[best_leaf].set(hist_l)
                              .at[new_leaf].set(hist_r))
            else:
                # rebuild both children (memory-constrained / voting mode)
                lmaskf = (leaf_id == best_leaf).astype(f32) * row_mask
                rmaskf = (leaf_id == new_leaf).astype(f32) * row_mask
                if use_voting:  # best_of builds the voted hists itself
                    hist_l = hist_r = None
                else:
                    hist_l = hist_of(lmaskf)
                    hist_r = hist_of(rmaskf)
                hist_stack = st.hist_stack

            # --- monotone constraint propagation (basic mode, ref:
            # monotone_constraints.hpp:489 BasicLeafConstraints::Update:
            # the new leaf clones the parent entry, then a numerical split
            # on a monotone feature bounds both children at the midpoint)
            if sp.has_monotone and not use_intermediate:
                p_min = st.leaf_cmin[best_leaf]
                p_max = st.leaf_cmax[best_leaf]
                mc_w = meta.monotone[feat]
                mid = (pd.left_output[best_leaf]
                       + pd.right_output[best_leaf]) / 2.0
                apply = (mc_w != 0) & ~isc
                pos = apply & (mc_w > 0)
                neg = apply & (mc_w < 0)
                l_max = jnp.where(pos, jnp.minimum(p_max, mid), p_max)
                l_min = jnp.where(neg, jnp.maximum(p_min, mid), p_min)
                r_min = jnp.where(pos, jnp.maximum(p_min, mid), p_min)
                r_max = jnp.where(neg, jnp.minimum(p_max, mid), p_max)
                leaf_cmin = (st.leaf_cmin.at[best_leaf].set(l_min)
                             .at[new_leaf].set(r_min))
                leaf_cmax = (st.leaf_cmax.at[best_leaf].set(l_max)
                             .at[new_leaf].set(r_max))
            else:
                leaf_cmin, leaf_cmax = st.leaf_cmin, st.leaf_cmax
                l_min = l_max = r_min = r_max = None

            # CEGB bookkeeping (ref: UpdateLeafBestSplits): the winning
            # feature's coupled penalty is paid once; other leaves' pending
            # gains on that feature get the penalty added back
            if sp.has_cegb:
                newly_used = ~st.cegb_used[feat]
                used_vec = st.cegb_used.at[feat].set(True)
                if meta.cegb_coupled is not None:
                    refund = jnp.where(
                        newly_used & (pd.feature == feat)
                        & (pd.gain > K_MIN_SCORE),
                        sp.cegb_tradeoff
                        * meta.cegb_coupled[feat], 0.0)
                    pd = pd._replace(gain=pd.gain + refund)
            else:
                used_vec = st.cegb_used
            if params.interaction_sets:
                child_branch = st.leaf_branch[best_leaf].at[feat].set(True)
                leaf_branch = (st.leaf_branch.at[best_leaf].set(child_branch)
                               .at[new_leaf].set(child_branch))
            else:
                child_branch = st.leaf_branch[0]
                leaf_branch = st.leaf_branch
            new_sum_g = (st.leaf_sum_g.at[best_leaf].set(lsum_g)
                         .at[new_leaf].set(rsum_g))
            new_sum_h = (st.leaf_sum_h.at[best_leaf].set(lsum_h)
                         .at[new_leaf].set(rsum_h))

            if use_lazy:
                # mark the split leaf's (bagged-in) rows as fetched for the
                # winning feature BEFORE the child scans (ref:
                # cost_effective_gradient_boosting.hpp:125-135
                # UpdateLeafBestSplits lazy branch)
                lz_l = (leaf_id == best_leaf).astype(f32) * row_mask
                lz_r = (leaf_id == new_leaf).astype(f32) * row_mask
                in_parent = (lz_l + lz_r) > 0
                new_lazy = st.lazy_used.at[feat].set(
                    st.lazy_used[feat] | in_parent)
            else:
                lz_l = lz_r = None
                new_lazy = st.lazy_used

            if use_intermediate:
                # --- intermediate mode (ref: monotone_constraints.hpp:516
                # IntermediateLeafConstraints).  TPU redesign: instead of
                # the recursive GoUp/GoDownToFindLeavesToUpdate crawl that
                # finds contiguous leaves and re-finds their splits one by
                # one, track each leaf's bin-space hyper-rectangle, derive
                # every leaf's [min, max] from the pairwise contiguity
                # relation in one vectorized pass, and re-scan ALL leaves'
                # pending splits from the histogram stack (vmapped) —
                # exactly consistent constraints after every split.
                fvec = jnp.arange(num_features, dtype=jnp.int32) == feat
                lo_s = st.leaf_lo[best_leaf]
                hi_s = st.leaf_hi[best_leaf]
                cut = (thr + 1).astype(jnp.int32)
                narrow = fvec & ~isc   # categorical splits don't narrow
                # left child keeps the best_leaf slot ([lo, cut) along
                # feat); the right child inherits the parent rect with
                # lo_feat = cut
                leaf_lo = (st.leaf_lo
                           .at[new_leaf].set(jnp.where(narrow, cut, lo_s)))
                leaf_hi = (st.leaf_hi
                           .at[new_leaf].set(hi_s)
                           .at[best_leaf].set(jnp.where(narrow, cut, hi_s)))
                out = tree.leaf_value
                alive = jnp.arange(L, dtype=jnp.int32) < tree.num_leaves
                # [L, L, F]: do rects i and j overlap along f?
                ov = ((leaf_lo[:, None, :] < leaf_hi[None, :, :])
                      & (leaf_lo[None, :, :] < leaf_hi[:, None, :]))
                nov = (~ov).astype(jnp.int32)
                n_false = jnp.sum(nov, axis=2)
                # overlap in every feature except f (contiguity slice)
                exc = (n_false[:, :, None] - nov) == 0
                below = leaf_hi[:, None, :] <= leaf_lo[None, :, :]
                belowT = jnp.swapaxes(below, 0, 1)
                incf = (meta.monotone > 0)[None, None, :]
                decf = (meta.monotone < 0)[None, None, :]
                valid = (alive[None, :, None] & exc
                         & ~jnp.eye(L, dtype=bool)[:, :, None])
                # j's output upper-bounds i when j sits on i's increasing
                # side of an increasing feature (or decreasing side of a
                # decreasing one); lower bounds mirror it
                ubm = valid & ((below & incf) | (belowT & decf))
                lbm = valid & ((belowT & incf) | (below & decf))
                outj = out[None, :, None]
                leaf_cmax = jnp.min(jnp.where(ubm, outj, jnp.inf),
                                    axis=(1, 2))
                leaf_cmin = jnp.max(jnp.where(lbm, outj, -jnp.inf),
                                    axis=(1, 2))
                branch_all = (leaf_branch if params.interaction_sets
                              else jnp.zeros((L, 1), bool))

                if params.monotone_advanced:
                    # --- advanced mode (ref: monotone_constraints.hpp:858
                    # AdvancedLeafConstraints).  TPU redesign: instead of
                    # the reference's per-threshold constraint lists built
                    # by recursive tree crawls, derive PER-(leaf, feature,
                    # threshold) constraint surfaces from the leaf rects.
                    # A candidate split of leaf i on feature f at bin t
                    # makes children whose rects differ from i's only
                    # along f, so whether neighbor j bounds a child via
                    # monotone feature f' reduces to threshold-interval
                    # conditions on t — each contribution is a prefix or
                    # suffix interval of bins, aggregated with scatter-min
                    # plus a cumulative min/max along the bin axis.
                    i32_ = jnp.int32
                    inf = jnp.inf
                    inc, dec = incf, decf
                    novi = nov.astype(jnp.int32)           # [L, L, F]
                    # j bounds i above/below via feature g (parent rects)
                    sA = (below & inc) | (belowT & dec)    # [L, L, F]
                    sB = (belowT & inc) | (below & dec)
                    valid0 = (alive[None, :] & ~jnp.eye(L, dtype=bool))
                    # all features except f overlap / exactly one other
                    # non-overlapping feature
                    contig0 = (n_false[:, :, None] - novi) == 0
                    contig1 = (n_false[:, :, None] - novi) == 1
                    sA_any = jnp.sum(sA, axis=2, dtype=i32_)
                    sB_any = jnp.sum(sB, axis=2, dtype=i32_)
                    qual3A = contig1 & ((sA_any[:, :, None]
                                         - sA.astype(i32_)) >= 1)
                    qual3B = contig1 & ((sB_any[:, :, None]
                                         - sB.astype(i32_)) >= 1)
                    v0 = valid0[:, :, None]
                    lo_j = leaf_lo[None, :, :]             # [1, L, F]
                    hi_j = leaf_hi[None, :, :]
                    lo_i = leaf_lo[:, None, :]
                    hi_i = leaf_hi[:, None, :]
                    ovf = lo_i < hi_j                      # child-f overlap
                    ovf_r = lo_j < hi_i
                    B_ = B
                    ii = jnp.broadcast_to(
                        jnp.arange(L, dtype=i32_)[:, None, None], below.shape)
                    ff = jnp.broadcast_to(
                        jnp.arange(num_features,
                                   dtype=i32_)[None, None, :], below.shape)
                    ojb = jnp.broadcast_to(outj, below.shape)

                    def smin(gate, pos):
                        """[L, F, B] scatter-min of out_j at bin pos."""
                        p = jnp.where(gate & (pos >= 0), pos, B_)
                        return (jnp.full((L, num_features, B_ + 1), inf,
                                         f32)
                                .at[ii, ff, p].min(
                                    jnp.where(gate, ojb, inf))[:, :, :B_])

                    def smax(gate, pos):
                        p = jnp.where(gate & (pos >= 0), pos, B_)
                        return (jnp.full((L, num_features, B_ + 1), -inf,
                                         f32)
                                .at[ii, ff, p].max(
                                    jnp.where(gate, ojb, -inf))[:, :, :B_])

                    cummin_f = lambda a: jax.lax.cummin(a, axis=2)
                    cummin_r = lambda a: jax.lax.cummin(a, axis=2,
                                                        reverse=True)
                    cummax_f = lambda a: jax.lax.cummax(a, axis=2)
                    cummax_r = lambda a: jax.lax.cummax(a, axis=2,
                                                        reverse=True)

                    def cst(gate):
                        """[L, F] constant min over qualifying j."""
                        return jnp.min(jnp.where(gate, ojb, inf), axis=1)

                    def cst_max(gate):
                        return jnp.max(jnp.where(gate, ojb, -inf), axis=1)

                    # UPPER bounds, LEFT child ([lo_i, t+1) along f):
                    #  f'=f inc: t < lo_j  -> bins [0, lo_j): suffix min
                    #  f'=f dec: hi_j <= lo_i (belowT): all t
                    #  f'!=f: parent side + child overlaps j along f:
                    #         t >= lo_j -> prefix min
                    uL = jnp.minimum(
                        cummin_r(smin(v0 & contig0 & inc, lo_j - 1)),
                        cst(v0 & contig0 & dec & belowT)[:, :, None])
                    uL = jnp.minimum(
                        uL, cummin_f(smin(v0 & qual3A & ovf, lo_j)))
                    # UPPER bounds, RIGHT child ([t+1, hi_i)):
                    #  f'=f inc: hi_i <= lo_j (below): all t
                    #  f'=f dec: t >= hi_j - 1 -> prefix min
                    #  f'!=f: t <= hi_j - 2 -> suffix min
                    uR = jnp.minimum(
                        cst(v0 & contig0 & inc & below)[:, :, None],
                        cummin_f(smin(v0 & contig0 & dec, hi_j - 1)))
                    uR = jnp.minimum(
                        uR, cummin_r(smin(v0 & qual3A & ovf_r, hi_j - 2)))
                    # LOWER bounds mirror with sB / swapped sides
                    lL = jnp.maximum(
                        cummax_r(smax(v0 & contig0 & dec, lo_j - 1)),
                        cst_max(v0 & contig0 & inc & belowT)[:, :, None])
                    lL = jnp.maximum(
                        lL, cummax_f(smax(v0 & qual3B & ovf, lo_j)))
                    lR = jnp.maximum(
                        cst_max(v0 & contig0 & dec & below)[:, :, None],
                        cummax_f(smax(v0 & contig0 & inc, hi_j - 1)))
                    lR = jnp.maximum(
                        lR, cummax_r(smax(v0 & qual3B & ovf_r, hi_j - 2)))
                    adv_all = (lL, uL, lR, uR)

                    def _rescan(h, sg, sh, c, po, mn, mx, d, br, a0, a1,
                                a2, a3):
                        return best_of(h, sg, sh, c, po, mn, mx, d,
                                       rand_tag=0, used=used_vec, branch=br,
                                       adv=(a0, a1, a2, a3))

                    res = jax.vmap(_rescan)(
                        hist_stack, new_sum_g, new_sum_h, tree.leaf_count,
                        tree.leaf_value, leaf_cmin, leaf_cmax,
                        tree.leaf_depth, branch_all, *adv_all)
                else:
                    def _rescan(h, sg, sh, c, po, mn, mx, d, br):
                        return best_of(h, sg, sh, c, po, mn, mx, d,
                                       rand_tag=0, used=used_vec, branch=br)

                    res = jax.vmap(_rescan)(
                        hist_stack, new_sum_g, new_sum_h, tree.leaf_count,
                        tree.leaf_value, leaf_cmin, leaf_cmax,
                        tree.leaf_depth, branch_all)
                pending = _PendingSplits(
                    gain=jnp.where(alive, res.gain, K_MIN_SCORE),
                    feature=res.feature, threshold=res.threshold,
                    default_left=res.default_left,
                    left_sum_gradient=res.left_sum_gradient,
                    left_sum_hessian=res.left_sum_hessian,
                    left_count=res.left_count,
                    left_output=res.left_output,
                    right_sum_gradient=res.right_sum_gradient,
                    right_sum_hessian=res.right_sum_hessian,
                    right_count=res.right_count,
                    right_output=res.right_output,
                    is_cat=res.is_cat, cat_bitset=res.cat_bitset)
            else:
                leaf_lo, leaf_hi = st.leaf_lo, st.leaf_hi
                # tag spaces: forced prologue steps use [1..2KF], the main
                # loop [2KF+1..] — no collision between the two phases
                tag_base = i if forced_leaf is not None else i + KF
                best_l = best_of(hist_l, lsum_g, lsum_h, cnt_l,
                                 pd.left_output[best_leaf], l_min, l_max,
                                 depth, rand_tag=2 * tag_base + 1,
                                 used=used_vec, branch=child_branch,
                                 member_mask=lmaskf if use_voting else None,
                                 lazy_mask=lz_l, lazy_used_cur=new_lazy)
                best_r = best_of(hist_r, rsum_g, rsum_h, cnt_r,
                                 pd.right_output[best_leaf], r_min, r_max,
                                 depth, rand_tag=2 * tag_base + 2,
                                 used=used_vec, branch=child_branch,
                                 member_mask=rmaskf if use_voting else None,
                                 lazy_mask=lz_r, lazy_used_cur=new_lazy)
                pending = _pending_set(_pending_set(pd, best_leaf, best_l),
                                       new_leaf, best_r)
            return _State(tree=tree, pending=pending, leaf_id=leaf_id,
                          hist_stack=hist_stack,
                          leaf_sum_g=new_sum_g,
                          leaf_sum_h=new_sum_h,
                          order=order, leaf_start=leaf_start,
                          leaf_seg_cnt=leaf_seg_cnt,
                          leaf_cmin=leaf_cmin, leaf_cmax=leaf_cmax,
                          cegb_used=used_vec,
                          leaf_branch=leaf_branch,
                          done=st.done,
                          leaf_lo=leaf_lo, leaf_hi=leaf_hi,
                          lazy_used=new_lazy)

        if forced_leaf is not None:
            # an invalid forced split (empty child) is skipped; growth
            # continues (ForceSplits abandons forcing, not the tree)
            return jax.lax.cond(proceed, do_split, lambda s: s, st)
        return jax.lax.cond(proceed, do_split,
                            lambda s: s._replace(done=jnp.asarray(True)), st)

    def forced_pending(st: _State, leaf, feat, thr):
        """Pending entry for a forced (feature, threshold) split of
        `leaf` (shared gather: gather_forced_split)."""
        hist = bundle_hist_to_features(
            st.hist_stack[leaf], st.leaf_sum_g[leaf], st.leaf_sum_h[leaf],
            meta, B, hist_B, params.has_bundles)
        res = gather_forced_split(
            hist, feat, thr, st.leaf_sum_g[leaf], st.leaf_sum_h[leaf],
            st.tree.leaf_count[leaf].astype(f32), meta, B, sp)
        return st._replace(pending=_pending_set(st.pending, leaf, res))

    forcing_ok = jnp.asarray(True)
    for k, (fleaf, ffeat, fthr) in enumerate(params.forced_splits):
        if k >= L - 1:
            break
        old_pending = state.pending
        old_nl = state.tree.num_leaves
        state = forced_pending(state, fleaf, ffeat, fthr)
        # the parse-time BFS leaf numbers are only valid while every
        # forced split applies; after the first skip, abort the rest
        # (ForceSplits' abort semantics) by poisoning the forced gain
        state = state._replace(pending=state.pending._replace(
            gain=jnp.where(forcing_ok, state.pending.gain, K_MIN_SCORE)))
        state = body(k, state, forced_leaf=fleaf)
        applied = state.tree.num_leaves > old_nl
        forcing_ok = forcing_ok & applied
        # a skipped forced split must not clobber the leaf's real
        # pending entry (growth continues on real gains)
        state = state._replace(pending=jax.tree.map(
            lambda new, old: jnp.where(applied, new, old),
            state.pending, old_pending))
    if L > 1:
        # the full trip count runs even after forced steps: skipped
        # forced splits return their slot to best-gain growth, and the
        # dynamic num_leaves < L guard in body enforces the budget
        state = jax.lax.fori_loop(0, L - 1, body, state)
    if use_lazy:
        # persistent per-(feature, row) fetched bitset rides along so the
        # driver can thread it into the next tree
        return state.tree, state.leaf_id, state.lazy_used
    return state.tree, state.leaf_id


# two jit entries over the same tracer program: the boosting loop's
# default donates the per-class grad/hess slices (their buffers die
# here — XLA reuses the HBM for the tree program's scratch instead of
# holding both), while linear-tree training, which re-reads the slices
# for leaf fitting after growth, keeps the non-donating entry
# (boosting/gbdt.py selects; docs/Performance.md)
# tpulint: disable-next=donate-argnums -- linear-tree training reuses grad/hess after growth; the default loop takes grow_tree_donated
grow_tree = jax.jit(grow_tree_impl, static_argnames=("params",))
grow_tree_donated = jax.jit(grow_tree_impl, static_argnames=("params",),
                            donate_argnums=(1, 2))


def make_grow_tree(params: GrowParams):
    """Partial application helper so callers hold one traced function."""
    def fn(binned, grad, hess, row_mask, col_mask, meta):
        return grow_tree(binned, grad, hess, row_mask, col_mask, meta, params)
    return fn
