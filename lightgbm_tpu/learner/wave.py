"""Wave (level-batched best-first) tree growth — the TPU-fast engine.

Strict leaf-wise growth (learner/grow.py) splits one leaf per step: 254
sequential fori_loop iterations of gathers and bucket bookkeeping for a
255-leaf tree, which on TPU is dominated by per-op overheads rather than
FLOPs.  The wave engine instead splits EVERY positive-gain leaf per round
(capped by the num_leaves budget, best-gain-first like the reference's leaf
ordering), so a tree takes ~log2(num_leaves) rounds of fully vectorized
work:

  1. one fused multi-leaf Pallas histogram pass over all rows
     (ops/histogram.py build_histogram_wave — all leaves' histograms in one
     MXU sweep whose output columns are leaf slots; ref:
     cuda_histogram_constructor.cu builds per-leaf histograms in shared
     memory the same way),
  2. one vmapped gain scan over [NLp, F, B] (ref:
     feature_histogram.hpp:192 FindBestThreshold, batched over leaves),
  3. one vectorized recolor pass (rows look up their leaf's split through a
     single packed [NLp, 8] table row-gather; ref: dense_bin.hpp:346
     SplitInner applied to all splitting leaves at once).

The wave loop is UNROLLED over ceil(log2(num_leaves)) rounds with a
per-round slot bound (8, 16, ..., padded num_leaves), so early rounds pay
kernels sized to the leaves that actually exist; each round is wrapped in
lax.cond and skipped once no leaf splits.

Tree shape: identical to leaf-wise when split gains decrease monotonically
with depth (the common case on real losses); on non-monotone gain
landscapes leaf-wise may deepen one branch where wave spreads a level, a
quality-neutral tradeoff (XGBoost's depthwise analogue).  When the
num_leaves budget binds mid-round only the highest-gain leaves split,
matching leaf-wise's preference.  All row-axis ops are reductions/maps, so
the engine shards over a data mesh without changes.

Counts: the per-wave gain scan and the stored tree use EXACT partition
counts from a third histogram channel accumulating the row mask (the
reference's DataPartition counts, tree.cpp Tree::Split); the per-bin counts
inside the scan remain the reference's RoundInt(hess * cnt_factor)
approximation for parity (feature_histogram.hpp:871-874).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from ..ops.histogram import build_histogram_wave, wave_slot_pad
from ..ops.split import K_MIN_SCORE, cat_bitset_words, find_best_split
from .grow import (FeatureMeta, GrowParams, TreeArrays,
                   bundle_hist_to_features)


def _hist_wave_xla(binned_fm, slot, gh, *, max_bin, num_slots):
    """XLA fallback (CPU tests): per-slot masked histograms via one-hot
    einsum.  Small shapes only.  gh's LAST column is the count mask;
    returns (hist [NL, F, B, C], counts [NL]) like the Pallas kernel."""
    oh_slot = (slot[:, None] == jnp.arange(num_slots)[None, :])  # [n, NL]
    oh_bin = (binned_fm[:, :, None] ==
              jnp.arange(max_bin, dtype=jnp.int32)[None, None, :])  # [F,n,B]
    # [NL, F, B, C]; histograms are exact accumulators, so force fp32
    # contraction (the TPU default would round operands to bf16)
    hist = jnp.einsum("nl,fnb,nc->lfbc", oh_slot.astype(jnp.float32),
                      oh_bin.astype(jnp.float32), gh[:, :-1],
                      precision=jax.lax.Precision.HIGHEST)
    counts = jnp.einsum("nl,n->l", oh_slot.astype(jnp.float32), gh[:, -1],
                        precision=jax.lax.Precision.HIGHEST)
    return hist, counts


@functools.partial(jax.jit, static_argnames=("params",))
def grow_tree_wave(binned: jnp.ndarray, grad: jnp.ndarray, hess: jnp.ndarray,
                   row_mask: jnp.ndarray, col_mask: jnp.ndarray,
                   meta: FeatureMeta, params: GrowParams,
                   cegb_used: jnp.ndarray = None,
                   extra_tag: jnp.ndarray = None,
                   quant_scales: jnp.ndarray = None):
    """Grow one tree by waves.  Same contract as grow.grow_tree."""
    from ..ops.split import MISSING_NAN, MISSING_ZERO

    if params.has_bundles:
        num_features = meta.num_bin.shape[0]
    else:
        num_features = binned.shape[0]
    n = binned.shape[1]
    L = params.num_leaves
    B = params.max_bin
    hist_B = params.group_max_bin if params.has_bundles else B
    sp = params.split
    f32 = jnp.float32
    i32 = jnp.int32

    row_mask = row_mask.astype(f32)
    grad = grad.astype(f32) * row_mask
    hess = hess.astype(f32) * row_mask
    # 2 histogram channels; the trailing column is the count mask consumed
    # by the kernel's fused per-slot count output (output lanes are the MXU
    # cost driver — see _wave_kernel)
    gh = jnp.stack([grad, hess, row_mask], axis=1)

    use_pallas = params.hist_method == "pallas"

    use_int8 = (use_pallas and params.quant_bins > 0
                and quant_scales is not None)

    def hists_of(leaf_id, num_slots):
        """Group-space histograms; converted per slot at the scan."""
        if use_pallas:
            if use_int8:
                # quantized grid grads -> exact int32 accumulation through
                # the MXU int8 path (ref: dense_bin.hpp:174
                # ConstructHistogramIntInner)
                return build_histogram_wave(
                    binned, leaf_id, gh, max_bin=hist_B,
                    num_slots=num_slots, quant_bins=params.quant_bins,
                    quant_scales=quant_scales)
            return build_histogram_wave(binned, leaf_id, gh,
                                        max_bin=hist_B, num_slots=num_slots)
        return _hist_wave_xla(binned, leaf_id, gh, max_bin=hist_B,
                              num_slots=num_slots)

    if sp.extra_trees:
        _extra_key = jax.random.PRNGKey(sp.extra_seed)
        if extra_tag is not None:
            _extra_key = jax.random.fold_in(_extra_key, extra_tag)

        def _rand_bins(tag):
            """[NLp_max, F] random thresholds for this wave's leaf scans
            (ref: feature_histogram.hpp:204 USE_RAND; 2-bin features
            evaluate threshold 0)."""
            u = jax.random.uniform(jax.random.fold_in(_extra_key, tag),
                                   (Lp, num_features))
            span = jnp.maximum(meta.num_bin - 2, 1).astype(f32)[None, :]
            return jnp.clip((u * span).astype(jnp.int32), 0,
                            jnp.maximum(meta.num_bin - 3, 0)[None, :]
                            ).astype(jnp.int32)

        def _rand_cat_us(tag):
            """[NLp_max, F, 2] uniforms for the categorical USE_RAND
            draws (feature_histogram.cpp:187,268)."""
            return jax.random.uniform(
                jax.random.fold_in(jax.random.fold_in(_extra_key, 0x5EED),
                                   tag), (Lp, num_features, 2))

    if sp.has_monotone:
        def _pen_of(depth):
            """ref: monotone_constraints.hpp:357."""
            pen, d = sp.monotone_penalty, depth.astype(f32)
            return jnp.where(pen >= d + 1.0, 1e-15,
                             jnp.where(pen <= 1.0,
                                       1.0 - pen / jnp.exp2(d) + 1e-15,
                                       1.0 - jnp.exp2(pen - 1.0 - d)
                                       + 1e-15))

    use_bynode = params.feature_fraction_bynode < 1.0
    if use_bynode:
        _bynode_key = jax.random.PRNGKey(params.bynode_seed)
        if extra_tag is not None:
            _bynode_key = jax.random.fold_in(_bynode_key, extra_tag)
        _bynode_k = max(1, int(round(
            params.feature_fraction_bynode * num_features)))

        def _bynode_masks(tag):
            """[NLp_max, F] exactly-k column subsets per leaf scan
            (ref: col_sampler.hpp GetByNode)."""
            u = jax.random.uniform(jax.random.fold_in(_bynode_key, tag),
                                   (Lp, num_features))
            kth = jax.lax.top_k(u, _bynode_k)[0][:, -1:]
            return u >= kth

    def _best_one(h, sg, sh, c, po, cmin, cmax, dep, rb, rcu, used, bym):
        h = bundle_hist_to_features(h, sg, sh, meta, B, hist_B,
                                    params.has_bundles)
        kw = {}
        if sp.has_monotone:
            kw.update(monotone=meta.monotone, constraint_min=cmin,
                      constraint_max=cmax, mono_penalty=_pen_of(dep))
        if sp.extra_trees:
            kw["rand_bin"] = rb
            if sp.has_categorical:
                kw["rand_cat_u"] = rcu
        if sp.has_cegb:
            kw["cegb_coupled"] = meta.cegb_coupled
            kw["cegb_used"] = used
        cm = col_mask if bym is None else (col_mask & bym)
        return find_best_split(
            h, meta.num_bin, meta.missing_type, meta.default_bin,
            meta.penalty, cm, sg, sh, c, po, sp,
            is_cat_feature=meta.is_cat, **kw)

    best_vm = jax.vmap(_best_one,
                       in_axes=(0, 0, 0, 0, 0,
                                0 if sp.has_monotone else None,
                                0 if sp.has_monotone else None,
                                0 if sp.has_monotone else None,
                                0 if sp.extra_trees else None,
                                0 if (sp.extra_trees
                                      and sp.has_categorical) else None,
                                None,
                                0 if use_bynode else None))

    sum_g0 = jnp.sum(grad)
    sum_h0 = jnp.sum(hess)
    cnt0 = jnp.sum(row_mask).astype(i32)

    ni = max(L - 1, 1)
    W = cat_bitset_words(B)
    # leaf-indexed arrays are sized to the padded slot bound (>= L) so
    # static [:NLp] slices stay in range; sliced back to [L] on return
    Lp = wave_slot_pad(L)
    tree = TreeArrays(
        num_leaves=jnp.asarray(1, i32),
        split_feature=jnp.zeros(ni, i32),
        threshold_bin=jnp.zeros(ni, i32),
        default_left=jnp.zeros(ni, bool),
        split_gain=jnp.zeros(ni, f32),
        left_child=jnp.zeros(ni, i32),
        right_child=jnp.zeros(ni, i32),
        internal_value=jnp.zeros(ni, f32),
        internal_weight=jnp.zeros(ni, f32),
        internal_count=jnp.zeros(ni, i32),
        leaf_value=jnp.zeros(Lp, f32),
        leaf_weight=jnp.zeros(Lp, f32).at[0].set(sum_h0),
        leaf_count=jnp.zeros(Lp, i32).at[0].set(cnt0),
        leaf_parent=jnp.full(Lp, -1, i32),
        leaf_depth=jnp.zeros(Lp, i32),
        split_is_cat=jnp.zeros(ni, bool),
        cat_bitset=jnp.zeros((ni, W), i32))

    # per-leaf running sums / outputs for the gain scan
    leaf_sum_g0 = jnp.zeros(Lp, f32).at[0].set(sum_g0)
    leaf_sum_h0 = jnp.zeros(Lp, f32).at[0].set(sum_h0)
    leaf_out0 = jnp.zeros(Lp, f32)
    cm_n = Lp if sp.has_monotone else 1
    leaf_cmin0 = jnp.full(cm_n, -jnp.inf, f32)
    leaf_cmax0 = jnp.full(cm_n, jnp.inf, f32)

    def wave_body(state, NLp):
        """One wave with a static slot bound NLp >= current num_leaves."""
        (tree, leaf_id, leaf_sum_g, leaf_sum_h, leaf_out,
         leaf_cmin, leaf_cmax, used_vec, _) = state
        NL = tree.num_leaves

        # 1. all leaves' histograms + exact per-slot counts in one pass
        #    (DataPartition cnt_leaf_data)
        hists, fcounts = hists_of(leaf_id, NLp)       # [NLp, F, B, 2], [NLp]
        counts = jnp.round(fcounts).astype(i32)
        active = jnp.arange(NLp, dtype=i32) < NL
        rb = (_rand_bins(tree.num_leaves)[:NLp] if sp.extra_trees else None)
        rcu = (_rand_cat_us(tree.num_leaves)[:NLp]
               if sp.extra_trees and sp.has_categorical else None)
        mono_args = ((leaf_cmin[:NLp], leaf_cmax[:NLp],
                      tree.leaf_depth[:NLp]) if sp.has_monotone
                     else (None, None, None))
        bym = (_bynode_masks(tree.num_leaves)[:NLp] if use_bynode
               else None)
        best = best_vm(hists, leaf_sum_g[:NLp], leaf_sum_h[:NLp],
                       counts, leaf_out[:NLp], *mono_args, rb, rcu,
                       used_vec, bym)

        # 2. select splitting leaves: positive gain, active, depth ok,
        #    best-gain-first within the remaining leaf budget
        gain = jnp.where(active, best.gain, K_MIN_SCORE)
        if params.max_depth > 0:
            gain = jnp.where(tree.leaf_depth[:NLp] < params.max_depth,
                             gain, K_MIN_SCORE)
        want = gain > 0.0
        budget = L - NL
        order = jnp.argsort(-gain)                    # best first
        rank_of = jnp.zeros(NLp, i32).at[order].set(
            jnp.arange(NLp, dtype=i32))
        split_sel = want & (rank_of < budget)
        n_split = jnp.sum(split_sel.astype(i32))

        # node/new-leaf numbering by gain rank (leaf-wise split order)
        node_of = jnp.where(split_sel, NL - 1 + rank_of, 0)
        newleaf_of = jnp.where(split_sel, NL + rank_of, 0)

        # 3. tree arrays, vectorized over leaves (ref: tree.cpp Tree::Split)
        t = tree
        # parent child-pointer fix: nodes whose child pointer references a
        # splitting leaf now point at that leaf's new internal node
        def fix_child(child):
            ll = jnp.where(child < 0, ~child, 0)
            is_leaf_ref = (child < 0) & (jnp.arange(ni) < NL - 1)
            repl = jnp.take(node_of, jnp.clip(ll, 0, NLp - 1))
            hit = is_leaf_ref & jnp.take(split_sel, jnp.clip(ll, 0, NLp - 1))
            return jnp.where(hit, repl, child)
        left_child = fix_child(t.left_child)
        right_child = fix_child(t.right_child)

        # scatter per-splitting-leaf node records
        sl_nodes = node_of                             # [NLp] targets
        drop = jnp.where(split_sel, sl_nodes, ni)      # OOB -> dropped
        def nset(arr, vals):
            return arr.at[drop].set(vals, mode="drop")
        left_child = nset(left_child,
                          ~jnp.arange(NLp, dtype=i32))  # left = old leaf
        right_child = nset(right_child, ~newleaf_of)
        split_feature = nset(t.split_feature, best.feature)
        threshold_bin = nset(t.threshold_bin, best.threshold)
        default_left = nset(t.default_left, best.default_left)
        split_gain = nset(t.split_gain, best.gain)
        internal_value = nset(t.internal_value, t.leaf_value[:NLp])
        internal_weight = nset(t.internal_weight,
                               best.left_sum_hessian + best.right_sum_hessian)
        internal_count = nset(t.internal_count, counts)  # exact
        split_is_cat = nset(t.split_is_cat, best.is_cat)
        cat_bitset = t.cat_bitset.at[drop].set(best.cat_bitset, mode="drop")

        # leaf records: old slot becomes the left child, new slot the right
        ldrop = jnp.where(split_sel, jnp.arange(NLp, dtype=i32), Lp)
        rdrop = jnp.where(split_sel, newleaf_of, Lp)
        depth1 = t.leaf_depth[:NLp] + 1
        def lset(arr, lvals, rvals):
            return (arr.at[ldrop].set(lvals, mode="drop")
                    .at[rdrop].set(rvals, mode="drop"))
        leaf_value = lset(t.leaf_value, best.left_output, best.right_output)
        leaf_weight = lset(t.leaf_weight, best.left_sum_hessian,
                           best.right_sum_hessian)
        # leaf_count here is the scan's approximation; the exact counts are
        # restored from the count channel each wave and at finalization
        leaf_count = lset(t.leaf_count, best.left_count, best.right_count)
        leaf_parent = lset(t.leaf_parent, sl_nodes, sl_nodes)
        leaf_depth = lset(t.leaf_depth, depth1, depth1)
        leaf_sum_g = lset(leaf_sum_g, best.left_sum_gradient,
                          best.right_sum_gradient)
        leaf_sum_h = lset(leaf_sum_h, best.left_sum_hessian,
                          best.right_sum_hessian)
        leaf_out = lset(leaf_out, best.left_output, best.right_output)
        if sp.has_monotone:
            # basic-mode constraint propagation (BasicLeafConstraints::
            # Update): children bounded at the output midpoint
            p_min = leaf_cmin[:NLp]
            p_max = leaf_cmax[:NLp]
            mc_w = jnp.take(meta.monotone, best.feature)
            mid = (best.left_output + best.right_output) / 2.0
            apply = split_sel & (mc_w != 0) & ~best.is_cat
            pos = apply & (mc_w > 0)
            neg = apply & (mc_w < 0)
            l_max = jnp.where(pos, jnp.minimum(p_max, mid), p_max)
            l_min = jnp.where(neg, jnp.maximum(p_min, mid), p_min)
            r_min = jnp.where(pos, jnp.maximum(p_min, mid), p_min)
            r_max = jnp.where(neg, jnp.minimum(p_max, mid), p_max)
            leaf_cmin = lset(leaf_cmin, l_min, r_min)
            leaf_cmax = lset(leaf_cmax, l_max, r_max)

        tree = TreeArrays(
            num_leaves=NL + n_split,
            split_feature=split_feature, threshold_bin=threshold_bin,
            default_left=default_left, split_gain=split_gain,
            left_child=left_child, right_child=right_child,
            internal_value=internal_value, internal_weight=internal_weight,
            internal_count=internal_count,
            leaf_value=leaf_value, leaf_weight=leaf_weight,
            leaf_count=leaf_count, leaf_parent=leaf_parent,
            leaf_depth=leaf_depth,
            split_is_cat=split_is_cat, cat_bitset=cat_bitset)

        # 4. recolor rows: one packed table row-gather per row.  The table
        # is [NLp, 8] numerical-only; the categorical columns (is_cat +
        # bitset words) are appended only when the dataset has categorical
        # features, keeping the hot gather narrow in the common case.
        cols = [split_sel.astype(i32), best.feature, best.threshold,
                best.default_left.astype(i32), newleaf_of,
                jnp.take(meta.missing_type, best.feature),
                jnp.take(meta.default_bin, best.feature),
                jnp.take(meta.num_bin, best.feature)]
        if params.has_bundles:
            cols += [jnp.take(meta.group, best.feature),
                     jnp.take(meta.offset, best.feature),
                     jnp.take(meta.zero_bin, best.feature)]
        n_base = len(cols)
        if sp.has_categorical:
            packed = jnp.concatenate(
                [jnp.stack(cols + [best.is_cat.astype(i32)], axis=1),
                 best.cat_bitset], axis=1)
        else:
            packed = jnp.stack(cols, axis=1)
        prow = jnp.take(packed, leaf_id, axis=0)
        sel_r = prow[:, 0] > 0
        feat_r = prow[:, 1]
        thr_r = prow[:, 2]
        dleft_r = prow[:, 3] > 0
        new_r = prow[:, 4]
        mt_r = prow[:, 5]
        db_r = prow[:, 6]
        nb_r = prow[:, 7]
        if params.has_bundles:
            grp_r = prow[:, 8]
            off_r = prow[:, 9]
            zb_r = prow[:, 10]
            col_r = grp_r
        else:
            col_r = feat_r
        # per-row bin of the row's split column (one-hot select over F')
        fbin = jnp.sum(jnp.where(
            col_r[None, :] == jnp.arange(binned.shape[0],
                                         dtype=i32)[:, None],
            binned.astype(i32), 0), axis=0)
        if params.has_bundles:
            local = fbin - off_r
            fbin = jnp.where((local >= 0) & (local < nb_r), local, zb_r)
        is_missing = (((mt_r == MISSING_NAN) & (fbin == nb_r - 1))
                      | ((mt_r == MISSING_ZERO) & (fbin == db_r)))
        go_left = jnp.where(is_missing, dleft_r, fbin <= thr_r)
        if sp.has_categorical:
            isc_r = prow[:, n_base] > 0
            word_r = jnp.take_along_axis(
                prow[:, n_base + 1:],
                jnp.clip(fbin // 32, 0, W - 1)[:, None], 1)[:, 0]
            cat_left = ((word_r >> (fbin % 32)) & 1) > 0
            go_left = jnp.where(isc_r, cat_left, go_left)
        leaf_id = jnp.where(sel_r & ~go_left, new_r, leaf_id)

        if sp.has_cegb:
            # all of this wave's winning features become used (coupled
            # penalties within one wave are charged per splitting leaf —
            # a wave-batching deviation from the reference's per-split
            # accounting, which refunds later leaves in the same level)
            used_vec = used_vec.at[jnp.where(split_sel, best.feature,
                                             num_features)].set(
                True, mode="drop")
        cont = (n_split > 0) & (tree.num_leaves < L)
        return (tree, leaf_id, leaf_sum_g, leaf_sum_h, leaf_out,
                leaf_cmin, leaf_cmax, used_vec, cont)

    if cegb_used is None:
        cegb_used = jnp.zeros(num_features if sp.has_cegb else 1, bool)
    state = (tree, jnp.zeros(n, i32), leaf_sum_g0, leaf_sum_h0, leaf_out0,
             leaf_cmin0, leaf_cmax0, cegb_used, jnp.asarray(L > 1))
    num_waves = max(1, math.ceil(math.log2(L))) if L > 1 else 0
    for k in range(num_waves):
        NLp = wave_slot_pad(min(1 << k, L))
        state = jax.lax.cond(state[-1],
                             functools.partial(wave_body, NLp=NLp),
                             lambda s: s, state)
    if num_waves > 0:
        # growth slower than doubling (chain-shaped gain landscapes) needs
        # more rounds than the unrolled ladder: keep waving at the full
        # slot bound until no leaf splits or the budget is exhausted
        state = jax.lax.while_loop(
            lambda s: s[-1],
            functools.partial(wave_body, NLp=wave_slot_pad(L)), state)

    tree, leaf_id = state[0], state[1]
    if num_waves > 0:
        # exact final counts from the final partition (one scatter-add;
        # ref: DataPartition cnt_leaf_data)
        exact = (jnp.zeros(Lp, f32).at[leaf_id].add(row_mask)).astype(i32)
        tree = tree._replace(leaf_count=exact)
    if Lp != L:  # back to the caller-visible [L] leaf layout
        tree = tree._replace(
            leaf_value=tree.leaf_value[:L], leaf_weight=tree.leaf_weight[:L],
            leaf_count=tree.leaf_count[:L], leaf_parent=tree.leaf_parent[:L],
            leaf_depth=tree.leaf_depth[:L])
    return tree, leaf_id
