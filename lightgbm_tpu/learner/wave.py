"""Wave (level-batched best-first) tree growth — the TPU-fast engine.

Strict leaf-wise growth (learner/grow.py) splits one leaf per step: 254
sequential fori_loop iterations of gathers and bucket bookkeeping for a
255-leaf tree, which on TPU is dominated by per-op overheads rather than
FLOPs.  The wave engine instead splits EVERY positive-gain leaf per round
(capped by the num_leaves budget, best-gain-first like the reference's leaf
ordering), so a tree takes ~log2(num_leaves) rounds of fully vectorized
work:

  1. one fused multi-leaf Pallas histogram pass over all rows
     (ops/histogram.py build_histogram_wave — all leaves' histograms in one
     MXU sweep whose output columns are leaf slots; ref:
     cuda_histogram_constructor.cu builds per-leaf histograms in shared
     memory the same way),
  2. one vmapped gain scan over [NLp, F, B] (ref:
     feature_histogram.hpp:192 FindBestThreshold, batched over leaves),
  3. one vectorized recolor pass (rows look up their leaf's split through a
     single packed [NLp, 8] table row-gather; ref: dense_bin.hpp:346
     SplitInner applied to all splitting leaves at once).

The wave loop is UNROLLED over ceil(log2(num_leaves)) rounds with a
per-round slot bound (8, 16, ..., padded num_leaves), so early rounds pay
kernels sized to the leaves that actually exist; each round is wrapped in
lax.cond and skipped once no leaf splits.

Tree shape: identical to leaf-wise when split gains decrease monotonically
with depth (the common case on real losses); on non-monotone gain
landscapes leaf-wise may deepen one branch where wave spreads a level, a
quality-neutral tradeoff (XGBoost's depthwise analogue).  When the
num_leaves budget binds mid-round only the highest-gain leaves split,
matching leaf-wise's preference.  All row-axis ops are reductions/maps, so
the engine shards over a data mesh without changes.

Counts: the per-wave gain scan and the stored tree use EXACT partition
counts from a third histogram channel accumulating the row mask (the
reference's DataPartition counts, tree.cpp Tree::Split); the per-bin counts
inside the scan remain the reference's RoundInt(hess * cnt_factor)
approximation for parity (feature_histogram.hpp:871-874).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from ..ops.histogram import (build_histogram_wave, build_histogram_wave_hl,
                             hl_split_of, wave_hl_profitable, wave_slot_pad)
from ..ops.split import (K_MIN_SCORE, SplitResult, cat_bitset_words,
                         find_best_split)
from .grow import (FeatureMeta, GrowParams, TreeArrays,
                   bundle_hist_to_features, gather_forced_split)
from ..utils.timer import global_timer


def _hist_wave_xla(binned_fm, slot, gh, *, max_bin, num_slots):
    """XLA fallback (CPU tests): per-slot masked histograms via one-hot
    einsum.  Small shapes only.  gh's LAST column is the count mask;
    returns (hist [NL, F, B, C], counts [NL]) like the Pallas kernel."""
    oh_slot = (slot[:, None]
               == jnp.arange(num_slots, dtype=jnp.int32)[None, :])  # [n, NL]
    oh_bin = (binned_fm[:, :, None] ==
              jnp.arange(max_bin, dtype=jnp.int32)[None, None, :])  # [F,n,B]
    # [NL, F, B, C]; histograms are exact accumulators, so force fp32
    # contraction (the TPU default would round operands to bf16)
    hist = jnp.einsum("nl,fnb,nc->lfbc", oh_slot.astype(jnp.float32),
                      oh_bin.astype(jnp.float32), gh[:, :-1],
                      precision=jax.lax.Precision.HIGHEST)
    counts = jnp.einsum("nl,n->l", oh_slot.astype(jnp.float32), gh[:, -1],
                        precision=jax.lax.Precision.HIGHEST)
    return hist, counts


def grow_tree_wave_impl(binned: jnp.ndarray, grad: jnp.ndarray,
                        hess: jnp.ndarray, row_mask: jnp.ndarray,
                        col_mask: jnp.ndarray,
                        meta: FeatureMeta, params: GrowParams,
                        cegb_used: jnp.ndarray = None,
                        extra_tag: jnp.ndarray = None,
                        quant_scales: jnp.ndarray = None):
    """Grow one tree by waves.  Same contract as grow.grow_tree."""
    from ..ops.split import MISSING_NAN, MISSING_ZERO

    if params.has_bundles:
        num_features = meta.num_bin.shape[0]
    else:
        num_features = binned.shape[0]
    n = binned.shape[1]
    L = params.num_leaves
    B = params.max_bin
    hist_B = params.group_max_bin if params.has_bundles else B
    sp = params.split
    f32 = jnp.float32
    i32 = jnp.int32

    row_mask = row_mask.astype(f32)
    grad = grad.astype(f32) * row_mask
    hess = hess.astype(f32) * row_mask
    # 2 histogram channels; the trailing column is the count mask consumed
    # by the kernel's fused per-slot count output (output lanes are the MXU
    # cost driver — see _wave_kernel)
    gh = jnp.stack([grad, hess, row_mask], axis=1)

    use_pallas = params.hist_method == "pallas"

    # Under shard_map (parallel/data_parallel.py) rows are the local shard:
    # every row-axis reduction is completed by a psum over the data axis —
    # the same computed-slot histogram reduction the reference's
    # distributed learner performs with Network::ReduceScatter
    # (ref: data_parallel_tree_learner.cpp:282-295).  All other state
    # (tree arrays, caches, gain scan) is replicated, so the bookkeeping
    # needs no synchronization — the reference's SyncUpGlobalBestSplit
    # (:441) becomes a no-op by construction.
    def _psum(x):
        if params.data_axis is None:
            return x
        # the collective replacing the reference's Network::ReduceScatter
        # of histograms (data_parallel_tree_learner.cpp:282-295); tagged
        # so profiler timelines show time-in-collectives per wave
        with global_timer.device_scope("Network::psum"):
            # tpulint: disable-next=collective-discipline -- the wave engine's single histogram/count reduction point; parallel/data_parallel.py wraps this engine in shard_map and owns the data_axis contract
            return jax.lax.psum(x, params.data_axis)

    use_int8 = (use_pallas and params.quant_bins > 0
                and quant_scales is not None)

    binned_rm = None
    if use_pallas and not use_int8:
        # row-major copy for the decomposed small-S kernel's lo side
        # (transposed once per tree; bins are static so XLA keeps it
        # resident for all waves of the tree)
        binned_rm = binned.T

    def _hl_fits(true_slots):
        """VMEM gate for the decomposed kernel (no feature grouping)."""
        F_, Rt, C_ = binned.shape[0], 512, 2
        Bh, Bl = hl_split_of(hist_B, true_slots, C_)
        Wd = F_ * Bl * C_ * true_slots
        vmem = (F_ * Bh * Rt * 2 + Rt * Wd * 10 + F_ * Bh * Bl
                * C_ * true_slots * 4)
        return vmem <= (12 << 20)

    def hists_of(kslot, ghm, num_slots, true_slots=None):
        """Group-space histograms for the COMPUTED (compact) slots only;
        rows outside computed leaves carry zeroed gh channels.  The full
        per-leaf set is completed by sibling subtraction at the cache.
        `true_slots` (<= num_slots) is the unpadded computed-slot bound:
        when it is small the decomposed hi/lo kernel streams far less
        VMEM volume (ops/histogram.py _wave_kernel_hl)."""
        with global_timer.device_scope("Tree::histogram"):
            if use_pallas:
                if use_int8:
                    # quantized grid grads -> exact int32 accumulation
                    # through the MXU int8 path (ref: dense_bin.hpp:174
                    # ConstructHistogramIntInner)
                    H, cnt = build_histogram_wave(
                        binned, kslot, ghm, max_bin=hist_B,
                        num_slots=num_slots, quant_bins=params.quant_bins,
                        quant_scales=quant_scales)
                elif (true_slots is not None and binned_rm is not None
                        and wave_hl_profitable(hist_B, true_slots)
                        and _hl_fits(true_slots)):
                    H, cnt = build_histogram_wave_hl(
                        binned, binned_rm, kslot, ghm, max_bin=hist_B,
                        num_slots=true_slots, out_slots=num_slots)
                else:
                    # Rt stays 512: 1024 is ~3% faster on small slot
                    # counts but exceeds the 16 MB scoped-VMEM limit at
                    # 128 slots
                    H, cnt = build_histogram_wave(binned, kslot, ghm,
                                                  max_bin=hist_B,
                                                  num_slots=num_slots)
            else:
                H, cnt = _hist_wave_xla(binned, kslot, ghm, max_bin=hist_B,
                                        num_slots=num_slots)
            # shard-local -> global (psum is a no-op single-device)
            return _psum(H), _psum(cnt)

    if sp.extra_trees:
        _extra_key = jax.random.PRNGKey(sp.extra_seed)
        if extra_tag is not None:
            _extra_key = jax.random.fold_in(_extra_key, extra_tag)

        def _rand_bins(tag):
            """[NLp_max, F] random thresholds for this wave's leaf scans
            (ref: feature_histogram.hpp:204 USE_RAND; 2-bin features
            evaluate threshold 0)."""
            u = jax.random.uniform(jax.random.fold_in(_extra_key, tag),
                                   (Lp, num_features))
            span = jnp.maximum(meta.num_bin - 2, 1).astype(f32)[None, :]
            return jnp.clip((u * span).astype(jnp.int32), 0,
                            jnp.maximum(meta.num_bin - 3, 0)[None, :]
                            ).astype(jnp.int32)

        def _rand_cat_us(tag):
            """[NLp_max, F, 2] uniforms for the categorical USE_RAND
            draws (feature_histogram.cpp:187,268)."""
            return jax.random.uniform(
                jax.random.fold_in(jax.random.fold_in(_extra_key, 0x5EED),
                                   tag), (Lp, num_features, 2))

    if sp.has_monotone:
        def _pen_of(depth):
            """ref: monotone_constraints.hpp:357."""
            pen, d = sp.monotone_penalty, depth.astype(f32)
            return jnp.where(pen >= d + 1.0, 1e-15,
                             jnp.where(pen <= 1.0,
                                       1.0 - pen / jnp.exp2(d) + 1e-15,
                                       1.0 - jnp.exp2(pen - 1.0 - d)
                                       + 1e-15))

    use_interaction = bool(params.interaction_sets)
    if use_interaction:
        _iset_masks = jnp.stack([
            jnp.zeros(num_features, bool).at[jnp.asarray(S, jnp.int32)]
            .set(True) for S in params.interaction_sets])    # [S, F]

        def _allowed_of(branch):
            """[NLp, F] branch masks -> [NLp, F] allowed masks (ref:
            col_sampler.hpp:91 GetByNode, vectorized over leaves): a
            feature is allowed iff it lies in some constraint set that
            contains the leaf's whole branch, or is itself on the
            branch."""
            ok = ~jnp.any(branch[:, None, :] & ~_iset_masks[None, :, :],
                          axis=2)                            # [NLp, S]
            return branch | jnp.any(
                ok[:, :, None] & _iset_masks[None, :, :], axis=1)

    use_bynode = params.feature_fraction_bynode < 1.0
    if use_bynode:
        _bynode_key = jax.random.PRNGKey(params.bynode_seed)
        if extra_tag is not None:
            _bynode_key = jax.random.fold_in(_bynode_key, extra_tag)
        _bynode_k = max(1, int(round(
            params.feature_fraction_bynode * num_features)))

        def _bynode_masks(tag):
            """[NLp_max, F] exactly-k column subsets per leaf scan
            (ref: col_sampler.hpp GetByNode)."""
            u = jax.random.uniform(jax.random.fold_in(_bynode_key, tag),
                                   (Lp, num_features))
            kth = jax.lax.top_k(u, _bynode_k)[0][:, -1:]
            return u >= kth

    def _best_one(h, sg, sh, c, po, cmin, cmax, dep, rb, rcu, used, bym):
        h = bundle_hist_to_features(h, sg, sh, meta, B, hist_B,
                                    params.has_bundles)
        kw = {}
        if sp.has_monotone:
            kw.update(monotone=meta.monotone, constraint_min=cmin,
                      constraint_max=cmax, mono_penalty=_pen_of(dep))
        if sp.extra_trees:
            kw["rand_bin"] = rb
            if sp.has_categorical:
                kw["rand_cat_u"] = rcu
        if sp.has_cegb:
            kw["cegb_coupled"] = meta.cegb_coupled
            kw["cegb_used"] = used
        cm = col_mask if bym is None else (col_mask & bym)
        return find_best_split(
            h, meta.num_bin, meta.missing_type, meta.default_bin,
            meta.penalty, cm, sg, sh, c, po, sp,
            is_cat_feature=meta.is_cat, **kw)

    best_vm = jax.vmap(_best_one,
                       in_axes=(0, 0, 0, 0, 0,
                                0 if sp.has_monotone else None,
                                0 if sp.has_monotone else None,
                                0 if sp.has_monotone else None,
                                0 if sp.extra_trees else None,
                                0 if (sp.extra_trees
                                      and sp.has_categorical) else None,
                                None,
                                0 if (use_bynode or use_interaction)
                                else None))

    # incremental gain scan: a leaf's best split depends only on its own
    # histogram/sums, which change ONLY when the leaf is created — so in
    # the plain mode the per-wave scan touches just the <= 2*Kb leaves
    # the previous wave created instead of all NLp (the reference
    # likewise scans only the two fresh leaves per split,
    # serial_tree_learner.cpp:340 FindBestSplits).  Modes whose scan
    # inputs change globally per wave (fresh extra-trees/bynode draws,
    # branch-dependent interaction masks, monotone constraint updates,
    # CEGB's used-feature set) keep the full rescan.
    incremental_scan = not (sp.extra_trees or use_bynode
                            or use_interaction or sp.has_monotone
                            or sp.has_cegb)

    sum_g0 = _psum(jnp.sum(grad))
    sum_h0 = _psum(jnp.sum(hess))
    cnt0 = _psum(jnp.sum(row_mask)).astype(i32)

    # overgrow-and-prune quality mode (see GrowParams.wave_prune): the
    # ladder grows to Lg > L leaves, then the leaf-wise pop order is
    # simulated over the overgrown gains and the tree pruned back to L
    # prune composes with tail_halving: halving only changes WHICH nodes
    # the overgrown ladder explores (gain-adaptive tail allocation), the
    # replay then picks the leaf-wise order over whatever was grown.
    # Forced splits disable prune: the replay ranks by gain and could
    # discard a forced node (the reference keeps forced splits
    # unconditionally, serial_tree_learner.cpp:614).
    prune = (params.wave_prune and L > 2 and not sp.has_monotone
             and not sp.has_cegb and not params.forced_splits)
    Lg = (min(max(L, int(math.ceil(L * params.wave_prune_overshoot))),
              4 * L) if prune else L)
    # spike waves (prune mode): reserve part of the overgrow budget for
    # a few best-gain-ONLY waves after the broad ladder — narrow deep
    # probes into the top-gain frontier, which is where the leaf-wise
    # order spends the splits the level-uniform ladder misses (the
    # "exploration adaptivity" residual of PERF_NOTES).  Each spike wave
    # computes <= 8 slots, so it rides the cheap decomposed hi/lo kernel.
    spike_k = int(getattr(params, "wave_spike_k", 8) or 8)
    spike_waves = (int(params.wave_spike_reserve) // spike_k
                   if prune and L >= 8 * spike_k else 0)
    reserve = min(spike_waves * spike_k, max(Lg - L, 0))
    spike_waves = reserve // spike_k
    Lg_main = Lg - spike_waves * spike_k

    ni = max(Lg - 1, 1)
    W = cat_bitset_words(B)
    # leaf-indexed arrays are sized to the padded slot bound (>= Lg) so
    # static [:NLp] slices stay in range; sliced back to [L] on return
    Lp = wave_slot_pad(Lg)
    tree = TreeArrays(
        num_leaves=jnp.asarray(1, i32),
        split_feature=jnp.zeros(ni, i32),
        threshold_bin=jnp.zeros(ni, i32),
        default_left=jnp.zeros(ni, bool),
        split_gain=jnp.zeros(ni, f32),
        left_child=jnp.zeros(ni, i32),
        right_child=jnp.zeros(ni, i32),
        internal_value=jnp.zeros(ni, f32),
        internal_weight=jnp.zeros(ni, f32),
        internal_count=jnp.zeros(ni, i32),
        leaf_value=jnp.zeros(Lp, f32),
        leaf_weight=jnp.zeros(Lp, f32).at[0].set(sum_h0),
        leaf_count=jnp.zeros(Lp, i32).at[0].set(cnt0),
        leaf_parent=jnp.full(Lp, -1, i32),
        leaf_depth=jnp.zeros(Lp, i32),
        split_is_cat=jnp.zeros(ni, bool),
        cat_bitset=jnp.zeros((ni, W), i32))

    # per-leaf running sums / outputs for the gain scan
    leaf_sum_g0 = jnp.zeros(Lp, f32).at[0].set(sum_g0)
    leaf_sum_h0 = jnp.zeros(Lp, f32).at[0].set(sum_h0)
    leaf_out0 = jnp.zeros(Lp, f32)
    cm_n = Lp if sp.has_monotone else 1
    leaf_cmin0 = jnp.full(cm_n, -jnp.inf, f32)
    leaf_cmax0 = jnp.full(cm_n, jnp.inf, f32)

    # per-leaf histogram cache (flat [Lp, F'*B'*2] for MXU-friendly
    # selection matmuls) + exact count cache, carried across waves (the
    # HistogramPool analogue, feature_histogram.hpp:1367); completed by
    # sibling subtraction
    Fh = binned.shape[0]
    Dh = Fh * hist_B * 2
    cache_h0 = jnp.zeros((Lp, Dh), f32)
    cache_c0 = jnp.zeros(Lp, f32)
    # pending-split tables from the previous wave (Lp-indexed by the slot
    # that split): new right slot, pair rank (= compact kernel slot of the
    # smaller child), smaller-side flag
    pend_sel0 = jnp.zeros(Lp, bool)
    pend_new0 = jnp.zeros(Lp, i32)
    pend_rank0 = jnp.zeros(Lp, i32)
    pend_sl0 = jnp.zeros(Lp, bool)

    def wave_hists(kslot, cache_h, cache_c,
                   pend_sel, pend_new, pend_rank, pend_sl, Kb, first,
                   Ks=None):
        """Update the per-leaf histogram cache for the leaves created by
        the previous wave: ONE fused kernel pass computes the SMALLER
        child of each pending split (compact slot = pair rank), the larger
        sibling is parent − smaller (ref: serial_tree_learner.cpp:334
        smaller/larger leaf split, feature_histogram.hpp Subtract) — so
        late waves stream half the rows' worth of MXU lanes instead of
        every leaf's.  kslot [n] is the compact computed slot per row,
        assigned during the PREVIOUS wave's recolor (rows outside a
        computed leaf carry the out-of-range sentinel Lp, which matches no
        slot one-hot bucket — no per-row gather or gh masking needed
        here)."""
        H, cnt = hists_of(kslot, gh, Kb, Ks)           # [Kb, F', B', 2]
        cnt = cnt.astype(f32)
        if first:
            # root wave: kslot is all zeros; one computed slot
            cache_h = cache_h.at[0].set(H.reshape(Kb, Dh)[0])
            cache_c = cache_c.at[0].set(cnt[0])
            return cache_h, cache_c
        # rank -> (parent slot, right slot, smaller-left) tables
        rdrop = jnp.where(pend_sel, pend_rank, Kb)
        slots = jnp.arange(Lp, dtype=i32)
        p_of = jnp.zeros(Kb, i32).at[rdrop].set(slots, mode="drop")
        q_of = jnp.zeros(Kb, i32).at[rdrop].set(pend_new, mode="drop")
        sl_of = jnp.zeros(Kb, bool).at[rdrop].set(pend_sl, mode="drop")
        valid = jnp.zeros(Kb, bool).at[rdrop].set(True, mode="drop")
        # gather (parent) and scatter (children) as ONE-HOT MXU MATMULS:
        # XLA's slice gather/scatter runs ~1GB/s on TPU, while a [Kb, Lp]
        # selection matmul against the flat [Lp, D] cache is microseconds
        # on the MXU and EXACT — one-hot rows have at most one nonzero, so
        # there is no accumulation and HIGHEST precision reproduces the
        # fp32 operand bit-for-bit
        HI = jax.lax.Precision.HIGHEST
        Hf = H.reshape(Kb, Dh)
        lr = jnp.arange(Lp, dtype=i32)
        pv = jnp.where(valid, p_of, Lp)
        qv = jnp.where(valid, q_of, Lp)
        P_par = (pv[:, None] == lr[None, :]).astype(f32)    # [Kb, Lp]
        parent_h = jax.lax.dot_general(P_par, cache_h,
                                       (((1,), (0,)), ((), ())),
                                       precision=HI)        # [Kb, Dh]
        other_h = parent_h - Hf
        slb = sl_of[:, None]
        W = jnp.concatenate([(lr[:, None] == pv[None, :]),
                             (lr[:, None] == qv[None, :])],
                            axis=1).astype(f32)             # [Lp, 2Kb]
        child_h = jnp.concatenate([jnp.where(slb, Hf, other_h),
                                   jnp.where(slb, other_h, Hf)], axis=0)
        upd = jax.lax.dot_general(W, child_h, (((1,), (0,)), ((), ())),
                                  precision=HI)             # [Lp, Dh]
        keep = 1.0 - jnp.clip(jnp.sum(W, axis=1), 0.0, 1.0)
        cache_h = cache_h * keep[:, None] + upd
        parent_c = jnp.sum(P_par * cache_c[None, :], axis=1)
        other_c = parent_c - cnt
        child_c = jnp.concatenate([jnp.where(sl_of, cnt, other_c),
                                   jnp.where(sl_of, other_c, cnt)])
        cache_c = cache_c * keep + jnp.sum(W * child_c[None, :], axis=1)
        return cache_h, cache_c

    def _forced_entry(fleaf, ffeat, fthr, cache_h, cache_c, leaf_sum_g,
                      leaf_sum_h):
        """SplitResult for a forced split of `fleaf` from its cached
        histogram (shared gather: grow.gather_forced_split)."""
        hist = bundle_hist_to_features(
            cache_h[fleaf].reshape(Fh, hist_B, 2), leaf_sum_g[fleaf],
            leaf_sum_h[fleaf], meta, B, hist_B, params.has_bundles)
        res = gather_forced_split(hist, ffeat, fthr, leaf_sum_g[fleaf],
                                  leaf_sum_h[fleaf], cache_c[fleaf],
                                  meta, B, sp)
        return res, res.gain > K_MIN_SCORE

    def wave_body(state, NLp, Kb, first=False, Ks=None, lg_cap=None,
                  budget_cap=None, forced=None):
        """One wave with a static slot bound NLp >= current num_leaves and
        a static computed-slot bound Kb >= splits of the previous wave.
        Ks is the TRUE (unpadded) computed-slot bound for the decomposed
        small-S histogram kernel.  `lg_cap` bounds the leaf budget (the
        overgrow target for this PHASE of growth; defaults to Lg) and
        `budget_cap` additionally caps the splits of this single wave
        (the spike waves' narrow best-gain-only deepening)."""
        (tree, leaf_id, kslot, leaf_sum_g, leaf_sum_h, leaf_out,
         leaf_cmin, leaf_cmax, used_vec, leaf_branch, cache_h, cache_c,
         pend_sel, pend_new, pend_rank, pend_sl, best_state, _) = state
        NL = tree.num_leaves

        # 1. refresh the per-leaf cache for last wave's children (smaller
        #    child computed, larger by subtraction), then scan the leaves
        #    whose histograms changed (all of them on the first wave /
        #    non-incremental modes; DataPartition cnt_leaf_data exactness
        #    rides the count cache)
        cache_h, cache_c = wave_hists(kslot, cache_h, cache_c, pend_sel,
                                      pend_new, pend_rank, pend_sl, Kb,
                                      first, Ks)
        counts = jnp.round(cache_c[:NLp]).astype(i32)
        active = jnp.arange(NLp, dtype=i32) < NL
        rb = (_rand_bins(tree.num_leaves)[:NLp] if sp.extra_trees else None)
        rcu = (_rand_cat_us(tree.num_leaves)[:NLp]
               if sp.extra_trees and sp.has_categorical else None)
        mono_args = ((leaf_cmin[:NLp], leaf_cmax[:NLp],
                      tree.leaf_depth[:NLp]) if sp.has_monotone
                     else (None, None, None))
        bym = (_bynode_masks(tree.num_leaves)[:NLp] if use_bynode
               else None)
        if use_interaction:
            allow = _allowed_of(leaf_branch[:NLp])
            bym = allow if bym is None else (bym & allow)
        # the incremental rescan gathers [2*Kb, Dh] from the cache (XLA
        # gathers run ~1GB/s) and its cost scales with the STATIC bound
        # Kb, not realized splits — it only beats the resident full scan
        # when that bound is a small fraction of NLp.  In practice that
        # is the spike waves after the first (Kb=8 vs NLp=pad(Lg));
        # ladder waves, the chain-tail while loop (Kb=pad(Lg/2)), and
        # short forced prologues all keep the full scan
        use_inc = incremental_scan and not first and 4 * Kb <= NLp
        if not use_inc:
            hists = cache_h[:NLp].reshape(NLp, Fh, hist_B, 2)
            with global_timer.device_scope("Tree::split_find"):
                best = best_vm(hists, leaf_sum_g[:NLp], leaf_sum_h[:NLp],
                               counts, leaf_out[:NLp], *mono_args, rb,
                               rcu, used_vec, bym)
            if incremental_scan:
                best_state = jax.tree.map(
                    lambda a, u: a.at[:NLp].set(u), best_state, best)
        else:
            # rescan ONLY the <= 2*Kb leaves the previous wave created:
            # the split parents (now their left children, same slot) and
            # the new right slots
            psl = jnp.argsort(-pend_sel.astype(i32))[:Kb]
            valid_p = jnp.take(pend_sel, psl)
            parents = jnp.where(valid_p, psl, Lp)
            news = jnp.where(valid_p, jnp.take(pend_new, psl), Lp)
            changed = jnp.concatenate([parents, news])       # [2*Kb]
            ch = jnp.clip(changed, 0, Lp - 1)
            h_ch = jnp.take(cache_h, ch, axis=0).reshape(
                2 * Kb, Fh, hist_B, 2)
            with global_timer.device_scope("Tree::split_find"):
                best_ch = best_vm(h_ch, jnp.take(leaf_sum_g, ch),
                                  jnp.take(leaf_sum_h, ch),
                                  jnp.round(jnp.take(cache_c, ch))
                                  .astype(i32),
                                  jnp.take(leaf_out, ch), *mono_args,
                                  rb, rcu, used_vec, bym)
            best_state = jax.tree.map(
                lambda a, u: a.at[changed].set(u, mode="drop"),
                best_state, best_ch)
            best = jax.tree.map(lambda a: a[:NLp], best_state)

        # 2. select splitting leaves: positive gain, active, depth ok,
        #    best-gain-first within the remaining leaf budget
        if forced is not None:
            # forced wave (ref: serial_tree_learner.cpp:614 ForceSplits):
            # exactly one predetermined (leaf, feature, threshold) split,
            # applied regardless of gain RANK/SIGN but only with
            # non-empty children and within depth/leaf budget
            fleaf, ffeat, fthr = forced
            fentry, fvalid = _forced_entry(fleaf, ffeat, fthr, cache_h,
                                           cache_c, leaf_sum_g,
                                           leaf_sum_h)
            best = jax.tree.map(
                lambda a, u: a.at[fleaf].set(u), best, fentry)
            ok = fvalid & (fleaf < NL) & (NL < L)
            if params.max_depth > 0:
                ok = ok & (tree.leaf_depth[fleaf] < params.max_depth)
            split_sel = (jnp.arange(NLp, dtype=i32) == fleaf) & ok
            rank_of = jnp.zeros(NLp, i32)
            n_split = jnp.sum(split_sel, dtype=i32)
        else:
            gain = jnp.where(active, best.gain, K_MIN_SCORE)
            if params.max_depth > 0:
                gain = jnp.where(tree.leaf_depth[:NLp] < params.max_depth,
                                 gain, K_MIN_SCORE)
            want = gain > 0.0
            budget = (Lg if lg_cap is None else lg_cap) - NL
            if budget_cap is not None:
                budget = jnp.minimum(budget, budget_cap)
            if params.wave_tail_halving:
                # once the leaf budget binds, spend at most half of it
                # per wave (always best-gain-first): the tail of the
                # tree then allocates leaves closer to the leaf-wise
                # global-gain order at the cost of ~log2(L) extra
                # (cheap, few-slot) waves — see PERF_NOTES.md
                budget = jnp.where(budget < NL,
                                   jnp.maximum((budget + 1) // 2, 1),
                                   budget)
            order = jnp.argsort(-gain)                # best first
            rank_of = jnp.zeros(NLp, i32).at[order].set(
                jnp.arange(NLp, dtype=i32))
            split_sel = want & (rank_of < budget)
            n_split = jnp.sum(split_sel, dtype=i32)

        # node/new-leaf numbering by gain rank (leaf-wise split order)
        node_of = jnp.where(split_sel, NL - 1 + rank_of, 0)
        newleaf_of = jnp.where(split_sel, NL + rank_of, 0)

        # 3. tree arrays, vectorized over leaves (ref: tree.cpp Tree::Split)
        t = tree
        # parent child-pointer fix: nodes whose child pointer references a
        # splitting leaf now point at that leaf's new internal node
        def fix_child(child):
            ll = jnp.where(child < 0, ~child, 0)
            is_leaf_ref = (child < 0) & (jnp.arange(ni, dtype=i32)
                                         < NL - 1)
            repl = jnp.take(node_of, jnp.clip(ll, 0, NLp - 1))
            hit = is_leaf_ref & jnp.take(split_sel, jnp.clip(ll, 0, NLp - 1))
            return jnp.where(hit, repl, child)
        left_child = fix_child(t.left_child)
        right_child = fix_child(t.right_child)

        # scatter per-splitting-leaf node records
        sl_nodes = node_of                             # [NLp] targets
        drop = jnp.where(split_sel, sl_nodes, ni)      # OOB -> dropped
        def nset(arr, vals):
            return arr.at[drop].set(vals, mode="drop")
        left_child = nset(left_child,
                          ~jnp.arange(NLp, dtype=i32))  # left = old leaf
        right_child = nset(right_child, ~newleaf_of)
        split_feature = nset(t.split_feature, best.feature)
        threshold_bin = nset(t.threshold_bin, best.threshold)
        default_left = nset(t.default_left, best.default_left)
        split_gain = nset(t.split_gain, best.gain)
        internal_value = nset(t.internal_value, t.leaf_value[:NLp])
        internal_weight = nset(t.internal_weight,
                               best.left_sum_hessian + best.right_sum_hessian)
        internal_count = nset(t.internal_count, counts)  # exact
        split_is_cat = nset(t.split_is_cat, best.is_cat)
        cat_bitset = t.cat_bitset.at[drop].set(best.cat_bitset, mode="drop")

        # leaf records: old slot becomes the left child, new slot the right
        ldrop = jnp.where(split_sel, jnp.arange(NLp, dtype=i32), Lp)
        rdrop = jnp.where(split_sel, newleaf_of, Lp)
        depth1 = t.leaf_depth[:NLp] + 1
        def lset(arr, lvals, rvals):
            return (arr.at[ldrop].set(lvals, mode="drop")
                    .at[rdrop].set(rvals, mode="drop"))
        leaf_value = lset(t.leaf_value, best.left_output, best.right_output)
        leaf_weight = lset(t.leaf_weight, best.left_sum_hessian,
                           best.right_sum_hessian)
        # leaf_count here is the scan's approximation; the exact counts are
        # restored from the count channel each wave and at finalization
        leaf_count = lset(t.leaf_count, best.left_count, best.right_count)
        leaf_parent = lset(t.leaf_parent, sl_nodes, sl_nodes)
        leaf_depth = lset(t.leaf_depth, depth1, depth1)
        leaf_sum_g = lset(leaf_sum_g, best.left_sum_gradient,
                          best.right_sum_gradient)
        leaf_sum_h = lset(leaf_sum_h, best.left_sum_hessian,
                          best.right_sum_hessian)
        leaf_out = lset(leaf_out, best.left_output, best.right_output)
        if use_interaction:
            # children extend the branch with the winning feature (ref:
            # col_sampler.hpp used_feature_indices_ per-branch tracking)
            fb = (jnp.arange(num_features, dtype=i32)[None, :]
                  == best.feature[:, None]) & split_sel[:, None]
            newb = leaf_branch[:NLp] | fb
            leaf_branch = lset(leaf_branch, newb, newb)
        if sp.has_monotone:
            # basic-mode constraint propagation (BasicLeafConstraints::
            # Update): children bounded at the output midpoint
            p_min = leaf_cmin[:NLp]
            p_max = leaf_cmax[:NLp]
            mc_w = jnp.take(meta.monotone, best.feature)
            mid = (best.left_output + best.right_output) / 2.0
            apply = split_sel & (mc_w != 0) & ~best.is_cat
            pos = apply & (mc_w > 0)
            neg = apply & (mc_w < 0)
            l_max = jnp.where(pos, jnp.minimum(p_max, mid), p_max)
            l_min = jnp.where(neg, jnp.maximum(p_min, mid), p_min)
            r_min = jnp.where(pos, jnp.maximum(p_min, mid), p_min)
            r_max = jnp.where(neg, jnp.minimum(p_max, mid), p_max)
            leaf_cmin = lset(leaf_cmin, l_min, r_min)
            leaf_cmax = lset(leaf_cmax, l_max, r_max)

        tree = TreeArrays(
            num_leaves=NL + n_split,
            split_feature=split_feature, threshold_bin=threshold_bin,
            default_left=default_left, split_gain=split_gain,
            left_child=left_child, right_child=right_child,
            internal_value=internal_value, internal_weight=internal_weight,
            internal_count=internal_count,
            leaf_value=leaf_value, leaf_weight=leaf_weight,
            leaf_count=leaf_count, leaf_parent=leaf_parent,
            leaf_depth=leaf_depth,
            split_is_cat=split_is_cat, cat_bitset=cat_bitset)

        # 4. recolor rows: one packed table row-gather per row.  The table
        # is [NLp, 8] numerical-only; the categorical columns (is_cat +
        # bitset words) are appended only when the dataset has categorical
        # features, keeping the hot gather narrow in the common case.
        # smaller side per split pair, chosen by the SCAN's (approximate,
        # RoundInt-parity) counts — either choice yields the same exact
        # pair of histograms by subtraction
        small_left = best.left_count <= best.right_count
        cols = [split_sel.astype(i32), best.feature, best.threshold,
                best.default_left.astype(i32), newleaf_of,
                jnp.take(meta.missing_type, best.feature),
                jnp.take(meta.default_bin, best.feature),
                jnp.take(meta.num_bin, best.feature),
                rank_of, small_left.astype(i32)]
        if params.has_bundles:
            cols += [jnp.take(meta.group, best.feature),
                     jnp.take(meta.offset, best.feature),
                     jnp.take(meta.zero_bin, best.feature)]
        n_base = len(cols)
        if sp.has_categorical:
            # cat bitset words carry full 32-bit patterns: pre-split into
            # positive 16-bit halves so the byte decomposition below stays
            # exact
            bs = best.cat_bitset
            cols = (cols + [best.is_cat.astype(i32)]
                    + [bs[:, w] & 0xFFFF for w in range(W)]
                    + [(bs[:, w] >> 16) & 0xFFFF for w in range(W)])
        packed = jnp.stack(cols, axis=1)                # [NLp, nc] < 2^24
        # per-row table lookup as a one-hot MXU matmul instead of an XLA
        # row gather (~1GB/s on TPU): values are decomposed into bytes so
        # the bf16 operands are exact, and each output sums exactly one
        # nonzero product — bit-exact reconstruction
        nc = packed.shape[1]
        tab = jnp.concatenate([packed & 255, (packed >> 8) & 255,
                               (packed >> 16) & 255], axis=1)
        with global_timer.device_scope("Tree::partition"):
            oh_rows = (leaf_id[:, None] ==
                       jnp.arange(NLp, dtype=i32)[None, :]).astype(
                           jnp.bfloat16)
            got = jax.lax.dot_general(
                oh_rows, tab.astype(jnp.bfloat16),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)      # [n, 3*nc]
        prow = (got[:, :nc].astype(i32)
                + (got[:, nc:2 * nc].astype(i32) << 8)
                + (got[:, 2 * nc:].astype(i32) << 16))
        sel_r = prow[:, 0] > 0
        feat_r = prow[:, 1]
        thr_r = prow[:, 2]
        dleft_r = prow[:, 3] > 0
        new_r = prow[:, 4]
        mt_r = prow[:, 5]
        db_r = prow[:, 6]
        nb_r = prow[:, 7]
        rank_r = prow[:, 8]
        sleft_r = prow[:, 9] > 0
        if params.has_bundles:
            grp_r = prow[:, 10]
            off_r = prow[:, 11]
            zb_r = prow[:, 12]
            col_r = grp_r
        else:
            col_r = feat_r
        # per-row bin of the row's split column (one-hot select over F')
        fbin = jnp.sum(jnp.where(
            col_r[None, :] == jnp.arange(binned.shape[0],
                                         dtype=i32)[:, None],
            binned.astype(i32), 0), axis=0)
        if params.has_bundles:
            local = fbin - off_r
            fbin = jnp.where((local >= 0) & (local < nb_r), local, zb_r)
        is_missing = (((mt_r == MISSING_NAN) & (fbin == nb_r - 1))
                      | ((mt_r == MISSING_ZERO) & (fbin == db_r)))
        go_left = jnp.where(is_missing, dleft_r, fbin <= thr_r)
        if sp.has_categorical:
            isc_r = prow[:, n_base] > 0
            widx = jnp.clip(fbin // 32, 0, W - 1)[:, None]
            w_lo = jnp.take_along_axis(
                prow[:, n_base + 1:n_base + 1 + W], widx, 1)[:, 0]
            w_hi = jnp.take_along_axis(
                prow[:, n_base + 1 + W:n_base + 1 + 2 * W], widx, 1)[:, 0]
            word_r = w_lo | (w_hi << 16)
            cat_left = ((word_r >> (fbin % 32)) & 1) > 0
            go_left = jnp.where(isc_r, cat_left, go_left)
        leaf_id = jnp.where(sel_r & ~go_left, new_r, leaf_id)
        # the NEXT wave's computed-slot assignment rides this recolor pass
        # (no extra per-row gather): a row is in the computed set iff it
        # landed in its pair's smaller child; everyone else gets the
        # out-of-range sentinel Lp, which matches no slot one-hot bucket
        kslot = jnp.where(sel_r & (go_left == sleft_r), rank_r, Lp)

        if sp.has_cegb:
            # all of this wave's winning features become used (coupled
            # penalties within one wave are charged per splitting leaf —
            # a wave-batching deviation from the reference's per-split
            # accounting, which refunds later leaves in the same level)
            used_vec = used_vec.at[jnp.where(split_sel, best.feature,
                                             num_features)].set(
                True, mode="drop")
        # pending tables for the next wave's cache completion
        lpz = jnp.zeros(Lp, i32)
        pend_sel = jnp.zeros(Lp, bool).at[:NLp].set(split_sel)
        pend_new = lpz.at[:NLp].set(newleaf_of)
        pend_rank = lpz.at[:NLp].set(rank_of)
        pend_sl = jnp.zeros(Lp, bool).at[:NLp].set(small_left)
        cont = (n_split > 0) & (tree.num_leaves
                                < (Lg if lg_cap is None else lg_cap))
        return (tree, leaf_id, kslot, leaf_sum_g, leaf_sum_h, leaf_out,
                leaf_cmin, leaf_cmax, used_vec, leaf_branch, cache_h,
                cache_c, pend_sel, pend_new, pend_rank, pend_sl,
                best_state, cont)

    if cegb_used is None:
        cegb_used = jnp.zeros(num_features if sp.has_cegb else 1, bool)
    leaf_branch0 = jnp.zeros(
        (Lp, num_features) if use_interaction else (1, 1), bool)
    # per-leaf cached best splits for the incremental scan (dummy scalar
    # pytree when the full rescan runs — lax.cond branches must match)
    if incremental_scan:
        best0 = SplitResult(
            gain=jnp.full(Lp, K_MIN_SCORE, f32),
            feature=jnp.zeros(Lp, i32), threshold=jnp.zeros(Lp, i32),
            default_left=jnp.zeros(Lp, bool),
            left_sum_gradient=jnp.zeros(Lp, f32),
            left_sum_hessian=jnp.zeros(Lp, f32),
            left_count=jnp.zeros(Lp, i32), left_output=jnp.zeros(Lp, f32),
            right_sum_gradient=jnp.zeros(Lp, f32),
            right_sum_hessian=jnp.zeros(Lp, f32),
            right_count=jnp.zeros(Lp, i32),
            right_output=jnp.zeros(Lp, f32),
            is_cat=jnp.zeros(Lp, bool),
            cat_bitset=jnp.zeros((Lp, W), i32))
    else:
        best0 = jnp.zeros((), f32)
    state = (tree, jnp.zeros(n, i32), jnp.zeros(n, i32), leaf_sum_g0,
             leaf_sum_h0, leaf_out0, leaf_cmin0, leaf_cmax0, cegb_used,
             leaf_branch0, cache_h0, cache_c0, pend_sel0, pend_new0,
             pend_rank0, pend_sl0, best0, jnp.asarray(L > 1))
    # forced prologue (ref: serial_tree_learner.cpp:614 ForceSplits): one
    # forced split per wave, in the parse-time BFS numbering (one split
    # per step keeps the leaf ids aligned).  The first skipped forced
    # split aborts the rest (the reference's abort semantics); its slot
    # returns to best-gain growth.
    KF = min(len(params.forced_splits), max(L - 1, 0))
    if KF:
        forcing_ok = jnp.asarray(True)
        for k in range(KF):
            fleaf, ffeat, fthr = params.forced_splits[k]
            nl_before = state[0].num_leaves
            state = jax.lax.cond(
                forcing_ok,
                functools.partial(wave_body, NLp=wave_slot_pad(k + 2),
                                  Kb=wave_slot_pad(1), first=(k == 0),
                                  Ks=1, forced=(fleaf, ffeat, fthr)),
                lambda s: s, state)
            forcing_ok = forcing_ok & (state[0].num_leaves > nl_before)
        # re-arm growth for the best-gain phase
        state = state[:-1] + ((jnp.asarray(L > 1)
                               & (state[0].num_leaves < Lg_main)),)

    num_waves = max(1, math.ceil(math.log2(Lg_main))) if Lg_main > 1 else 0
    for k in range(num_waves):
        # entering ladder wave k the tree has grown from <= KF+1 leaves
        # (forced prologue) through k doubling waves: NL <= (KF+1)*2^k.
        # The bounds must be MULTIPLICATIVE in KF+1 — an additive bound
        # would undersize Ks and the hl kernel would silently zero-pad
        # real children (its out_slots contract)
        NLp = wave_slot_pad(min((KF + 1) << k, Lg_main))
        # computed slots this wave = splits of the previous wave (root
        # wave computes 1 slot; after a forced prologue the first ladder
        # wave's pending split is the last forced wave's single one)
        Ks = (1 if k == 0 and KF else
              min((KF + 1) << max(k - 1, 0), Lg_main))
        Kb = wave_slot_pad(Ks)
        state = jax.lax.cond(state[-1],
                             functools.partial(wave_body, NLp=NLp, Kb=Kb,
                                               first=(k == 0 and not KF),
                                               Ks=Ks, lg_cap=Lg_main),
                             lambda s: s, state)
    if num_waves > 0:
        # growth slower than doubling (chain-shaped gain landscapes) needs
        # more rounds than the unrolled ladder: keep waving at the full
        # slot bound until no leaf splits or the budget is exhausted.
        # Splits per wave <= min(NL, Lg - NL) <= Lg // 2.
        state = jax.lax.while_loop(
            lambda s: s[-1],
            functools.partial(wave_body, NLp=wave_slot_pad(Lg_main),
                              Kb=wave_slot_pad(max(Lg_main // 2, 1)),
                              lg_cap=Lg_main), state)
    for s_i in range(spike_waves):
        # narrow deepening: the previous wave may have split up to
        # spike_k leaves (or Lg_main//2 for the first spike), so the
        # computed-slot bound is that previous wave's split cap
        KsS = min(spike_k if s_i > 0 else max(Lg_main // 2, 1), Lg)
        # tpulint: disable-next=no-device-put-in-loop -- re-arm cont: trace-time constant in the unrolled spike ladder, not a runtime H2D
        state = state[:-1] + (jnp.asarray(True),)
        state = jax.lax.cond(
            state[0].num_leaves < Lg,
            functools.partial(wave_body, NLp=wave_slot_pad(Lg),
                              Kb=wave_slot_pad(KsS),
                              Ks=(KsS if KsS <= 16 else None),
                              budget_cap=spike_k),
            lambda s: s, state)

    def _prune_to_leafwise(tree, leaf_id):
        """Prune the overgrown (<= Lg leaves) tree back to L leaves in the
        reference's strict leaf-wise order (serial_tree_learner.cpp:219
        ArgMax over leaf gains): simulate the best-gain pop sequence over
        the overgrown tree's exact split gains, keep the popped splits,
        renumber nodes/leaves by pop order (the reference's creation
        order), and remap rows to their nearest kept ancestor's side.
        Exactly the leaf-wise tree whenever its splits lie within the
        overgrown region; a node's gain depends only on its row set, so
        kept gains are identical to what leaf-wise would have computed."""
        nodes = jnp.arange(ni, dtype=i32)
        NN = tree.num_leaves - 1                   # realized node count
        created = nodes < NN
        lc, rc = tree.left_child, tree.right_child
        # parent-of-node via child-pointer scatter
        lci = jnp.where(created & (lc >= 0), lc, ni)
        rci = jnp.where(created & (rc >= 0), rc, ni)
        par = (jnp.full(ni, -1, i32).at[lci].set(nodes, mode="drop")
               .at[rci].set(nodes, mode="drop"))
        gains = jnp.where(created, tree.split_gain, K_MIN_SCORE)

        nf = max(L - 1, 1)
        kept0 = jnp.zeros(ni, bool)
        avail0 = created & (par == -1)             # the root node
        new_id0 = jnp.zeros(ni, i32)
        pop0 = jnp.zeros(nf, i32)
        lid_of0 = jnp.zeros(ni, i32)               # leaf id a node splits
        dep_of0 = jnp.zeros(ni, i32)               # depth of that leaf
        nl_l0 = jnp.zeros(ni, i32)                 # left/right child leaf
        nl_r0 = jnp.zeros(ni, i32)                 # ids assigned at pop

        def pop_step(t, st):
            kept, avail, new_id, pop, lid_of, dep_of, nl_l, nl_r, cnt = st
            score = jnp.where(avail & ~kept & (gains > 0.0), gains,
                              K_MIN_SCORE)
            j = jnp.argmax(score).astype(i32)
            ok = score[j] > K_MIN_SCORE
            jd = jnp.where(ok, j, ni)
            kept = kept.at[jd].set(True, mode="drop")
            new_id = new_id.at[jd].set(cnt, mode="drop")
            pop = pop.at[jnp.where(ok, cnt, nf)].set(j, mode="drop")
            ll = lid_of[j]
            nl_l = nl_l.at[jd].set(ll, mode="drop")
            nl_r = nl_r.at[jd].set(cnt + 1, mode="drop")
            lcj, rcj = lc[j], rc[j]
            dl = dep_of[j] + 1
            lt = jnp.where(ok & (lcj >= 0), lcj, ni)
            rt = jnp.where(ok & (rcj >= 0), rcj, ni)
            lid_of = (lid_of.at[lt].set(ll, mode="drop")
                      .at[rt].set(cnt + 1, mode="drop"))
            dep_of = (dep_of.at[lt].set(dl, mode="drop")
                      .at[rt].set(dl, mode="drop"))
            avail = (avail.at[lt].set(True, mode="drop")
                     .at[rt].set(True, mode="drop"))
            return (kept, avail, new_id, pop, lid_of, dep_of, nl_l, nl_r,
                    cnt + jnp.where(ok, 1, 0))

        (kept, _, new_id, pop, lid_of, dep_of, nl_l, nl_r,
         n_kept) = jax.lax.fori_loop(
            0, nf, pop_step,
            (kept0, avail0, new_id0, pop0, lid_of0, dep_of0, nl_l0, nl_r0,
             jnp.asarray(0, i32)))

        # rebuild node arrays [nf] in pop order
        tf = jnp.arange(nf, dtype=i32)
        valid_t = tf < n_kept
        old = jnp.where(valid_t, pop, 0)

        def gat(a, fill=0):
            v = jnp.take(a, old, axis=0)
            if a.ndim > 1:
                return jnp.where(valid_t[:, None], v, fill)
            return jnp.where(valid_t, v, fill)

        olc, orc = jnp.take(lc, old), jnp.take(rc, old)
        olci, orci = jnp.clip(olc, 0, ni - 1), jnp.clip(orc, 0, ni - 1)
        lk = (olc >= 0) & jnp.take(kept, olci)
        rk = (orc >= 0) & jnp.take(kept, orci)
        onl_l, onl_r = jnp.take(nl_l, old), jnp.take(nl_r, old)
        left_f = jnp.where(valid_t,
                           jnp.where(lk, jnp.take(new_id, olci), ~onl_l), 0)
        right_f = jnp.where(valid_t,
                            jnp.where(rk, jnp.take(new_id, orci), ~onl_r), 0)

        # leaf arrays [Lp]: a kept node's side becomes a final leaf when
        # its overgrown child there is not kept — source values are the
        # overgrown leaf's (child < 0) or the pruned node's internal ones
        def side_leaf(oc, is_leaf_here, nl):
            oci = jnp.clip(oc, 0, ni - 1)
            osl = jnp.clip(~oc, 0, Lp - 1)
            lid = jnp.where(valid_t & is_leaf_here, nl, Lp)
            val = jnp.where(oc >= 0, jnp.take(tree.internal_value, oci),
                            jnp.take(tree.leaf_value, osl))
            wgt = jnp.where(oc >= 0, jnp.take(tree.internal_weight, oci),
                            jnp.take(tree.leaf_weight, osl))
            cntv = jnp.where(oc >= 0, jnp.take(tree.internal_count, oci),
                             jnp.take(tree.leaf_count, osl))
            return lid, val, wgt, cntv

        lid_l, val_l, wgt_l, cnt_l = side_leaf(olc, ~lk, onl_l)
        lid_r, val_r, wgt_r, cnt_r = side_leaf(orc, ~rk, onl_r)
        dep1 = jnp.take(dep_of, old) + 1

        def scat(init, vl, vr):
            return (init.at[lid_l].set(vl, mode="drop")
                    .at[lid_r].set(vr, mode="drop"))

        single = n_kept == 0                      # no kept split: 1 leaf
        leaf_value_f = jnp.where(
            single, jnp.zeros(Lp, f32).at[0].set(tree.leaf_value[0]),
            scat(jnp.zeros(Lp, f32), val_l, val_r))
        leaf_weight_f = jnp.where(
            single, jnp.zeros(Lp, f32).at[0].set(tree.leaf_weight[0]),
            scat(jnp.zeros(Lp, f32), wgt_l, wgt_r))
        leaf_count_f = jnp.where(
            single, jnp.zeros(Lp, i32).at[0].set(tree.leaf_count[0]),
            scat(jnp.zeros(Lp, i32), cnt_l, cnt_r))
        leaf_parent_f = jnp.where(
            single, jnp.full(Lp, -1, i32),
            scat(jnp.full(Lp, -1, i32), tf, tf))
        leaf_depth_f = jnp.where(
            single, jnp.zeros(Lp, i32),
            scat(jnp.zeros(Lp, i32), dep1, dep1))

        tree_f = TreeArrays(
            num_leaves=n_kept + 1,
            split_feature=gat(tree.split_feature),
            threshold_bin=gat(tree.threshold_bin),
            default_left=gat(tree.default_left, False),
            split_gain=gat(tree.split_gain, 0.0),
            left_child=left_f, right_child=right_f,
            internal_value=gat(tree.internal_value, 0.0),
            internal_weight=gat(tree.internal_weight, 0.0),
            internal_count=gat(tree.internal_count),
            leaf_value=leaf_value_f, leaf_weight=leaf_weight_f,
            leaf_count=leaf_count_f, leaf_parent=leaf_parent_f,
            leaf_depth=leaf_depth_f,
            split_is_cat=gat(tree.split_is_cat, False),
            cat_bitset=gat(tree.cat_bitset))

        # rows: overgrown leaf slot -> nearest kept ancestor's side leaf.
        # Walk up until the current node is kept (or the root is passed);
        # overgrown depth is bounded by the wave count but chain shapes
        # can be deep, so iterate to convergence.
        s_ids = jnp.arange(Lp, dtype=i32)
        node0 = tree.leaf_parent
        side0 = jnp.where(
            jnp.take(rc, jnp.clip(node0, 0, ni - 1)) == ~s_ids, 1, 0)

        def w_cond(st):
            node, _ = st
            done = (node < 0) | jnp.take(kept, jnp.clip(node, 0, ni - 1))
            return jnp.any(~done)

        def w_step(st):
            node, side = st
            nodei = jnp.clip(node, 0, ni - 1)
            done = (node < 0) | jnp.take(kept, nodei)
            pnode = jnp.take(par, nodei)
            pside = jnp.where(
                jnp.take(rc, jnp.clip(pnode, 0, ni - 1)) == node, 1, 0)
            return (jnp.where(done, node, pnode),
                    jnp.where(done, side, pside))

        node_w, side_w = jax.lax.while_loop(w_cond, w_step, (node0, side0))
        nwi = jnp.clip(node_w, 0, ni - 1)
        lid_map = jnp.where(
            node_w >= 0,
            jnp.where(side_w == 1, jnp.take(nl_r, nwi),
                      jnp.take(nl_l, nwi)), 0)
        # remap rows through the [Lp] table as a one-hot MXU matmul
        # (byte-decomposed, bit-exact; same rationale as the recolor pass)
        tab = jnp.stack([(lid_map & 255).astype(jnp.bfloat16),
                         ((lid_map >> 8) & 255).astype(jnp.bfloat16)], 1)
        ohr = (leaf_id[:, None] ==
               s_ids[None, :]).astype(jnp.bfloat16)
        got = jax.lax.dot_general(ohr, tab, (((1,), (0,)), ((), ())),
                                  preferred_element_type=f32)
        leaf_id_f = got[:, 0].astype(i32) + (got[:, 1].astype(i32) << 8)
        # exact final counts ride the SAME one-hot (ref: DataPartition
        # cnt_leaf_data): per-old-slot masked counts from one extra MXU
        # column, scattered through the [Lp] slot->leaf table — no second
        # [n, Lp] one-hot pass
        cnt_slot = _psum(jax.lax.dot_general(
            row_mask.astype(jnp.bfloat16)[None, :], ohr,
            (((1,), (0,)), ((), ())), preferred_element_type=f32)[0])
        exact = jnp.zeros(Lp, f32).at[lid_map].add(cnt_slot).astype(i32)
        tree_f = tree_f._replace(leaf_count=exact)
        return tree_f, leaf_id_f

    tree, leaf_id = state[0], state[1]
    if prune and num_waves > 0:
        tree, leaf_id = _prune_to_leafwise(tree, leaf_id)
    elif num_waves > 0:
        # exact final counts from the final partition (ref: DataPartition
        # cnt_leaf_data).  A one-hot MXU matmul instead of a 1M-element
        # scatter-add: the one-hot and 0/1 mask are exact in bf16 and the
        # fp32 accumulator holds integer sums < 2^24 exactly.
        oh = (leaf_id[:, None] ==
              jnp.arange(Lp, dtype=i32)[None, :]).astype(jnp.bfloat16)
        exact = _psum(jax.lax.dot_general(
            row_mask.astype(jnp.bfloat16)[None, :], oh,
            (((1,), (0,)), ((), ())),
            preferred_element_type=f32)[0]).astype(i32)
        tree = tree._replace(leaf_count=exact)
    if Lp != L:  # back to the caller-visible [L] leaf layout
        tree = tree._replace(
            leaf_value=tree.leaf_value[:L], leaf_weight=tree.leaf_weight[:L],
            leaf_count=tree.leaf_count[:L], leaf_parent=tree.leaf_parent[:L],
            leaf_depth=tree.leaf_depth[:L])
    return tree, leaf_id

# tpulint: disable-next=donate-argnums -- the shard_map wrapper (parallel/data_parallel.py) and linear-tree paths reuse grad/hess; the default loop takes grow_tree_wave_donated
grow_tree_wave = jax.jit(grow_tree_wave_impl, static_argnames=("params",))
# default single-device entry: the per-class grad/hess slices die at the
# grow call, so their HBM is donated into the tree program
# (boosting/gbdt.py selects; docs/Performance.md)
grow_tree_wave_donated = jax.jit(grow_tree_wave_impl,
                                 static_argnames=("params",),
                                 donate_argnums=(1, 2))
