"""Evaluation metrics (ref: src/metric/: regression_metric.hpp, binary_metric.hpp,
multiclass_metric.hpp, rank_metric.hpp, map_metric.hpp, xentropy_metric.hpp,
dcg_calculator.cpp; factory src/metric/metric.cpp:19).

Host-side NumPy implementations: metrics run once per `metric_freq` iterations
on scores pulled from device; pointwise transforms mirror the reference's use of
ObjectiveFunction::ConvertOutput.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .config import Config
from .utils import log


class Metric:
    """Base (ref: include/LightGBM/metric.h)."""

    name: str = ""
    is_higher_better = False

    def __init__(self, config: Config):
        self.config = config

    def init(self, metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = np.asarray(metadata.label, dtype=np.float64)
        self.weight = (None if metadata.weight is None
                       else np.asarray(metadata.weight, dtype=np.float64))
        self.sum_weights = (float(num_data) if self.weight is None
                            else float(self.weight.sum()))
        self.query_boundaries = metadata.query_boundaries

    def eval(self, score: np.ndarray, objective=None) -> List[Tuple[str, float]]:
        raise NotImplementedError

    def _convert(self, score, objective):
        # host metrics evaluate host-resident scores (valid sets, loaded
        # boosters): convert on host too — the old jnp round trip cost
        # one H2D + one D2H per (dataset, metric) every eval tick and
        # quietly downcast the float64 valid scores to f32
        # (docs/Performance.md host-boundary inventory)
        if objective is not None:
            return np.asarray(objective.convert_output_host(score))
        return score

    def _avg(self, pointwise: np.ndarray) -> float:
        if self.weight is None:
            return float(pointwise.sum() / self.sum_weights)
        return float((pointwise * self.weight).sum() / self.sum_weights)


# ------------------------------------------------------------------ regression
class _PointwiseRegression(Metric):
    def loss(self, label, score):
        raise NotImplementedError

    def eval(self, score, objective=None):
        conv = self._convert(score, objective)
        return [(self.name, self._avg(self.loss(self.label, conv)))]


class L2Metric(_PointwiseRegression):
    name = "l2"
    def loss(self, label, score):
        return (score - label) ** 2


class RMSEMetric(_PointwiseRegression):
    name = "rmse"
    def eval(self, score, objective=None):
        conv = self._convert(score, objective)
        return [(self.name, float(np.sqrt(self._avg((conv - self.label) ** 2))))]


class L1Metric(_PointwiseRegression):
    name = "l1"
    def loss(self, label, score):
        return np.abs(score - label)


class QuantileMetric(_PointwiseRegression):
    name = "quantile"
    def loss(self, label, score):
        alpha = self.config.alpha
        delta = label - score
        return np.where(delta < 0, (alpha - 1.0) * delta, alpha * delta)


class HuberMetric(_PointwiseRegression):
    name = "huber"
    def loss(self, label, score):
        a = self.config.alpha
        diff = np.abs(score - label)
        return np.where(diff <= a, 0.5 * diff * diff, a * (diff - 0.5 * a))


class FairMetric(_PointwiseRegression):
    name = "fair"
    def loss(self, label, score):
        c = self.config.fair_c
        x = np.abs(score - label)
        return c * x - c * c * np.log1p(x / c)


class PoissonMetric(_PointwiseRegression):
    name = "poisson"
    def loss(self, label, score):
        eps = 1e-10
        s = np.maximum(score, eps)
        return s - label * np.log(s)


class MAPEMetric(_PointwiseRegression):
    name = "mape"
    def loss(self, label, score):
        return np.abs((label - score) / np.maximum(1.0, np.abs(label)))


class GammaMetric(_PointwiseRegression):
    """Gamma negative log-likelihood, psi=1 (ref: regression_metric.hpp GammaMetric)."""
    name = "gamma"
    def loss(self, label, score):
        eps = 1e-10
        s = np.maximum(score, eps)
        return np.maximum(label, eps) / s + np.log(s)


class GammaDevianceMetric(_PointwiseRegression):
    name = "gamma_deviance"
    def loss(self, label, score):
        eps = 1e-10
        frac = label / np.maximum(score, eps)
        return 2.0 * (-np.log(np.maximum(frac, eps)) + frac - 1.0)


class TweedieMetric(_PointwiseRegression):
    name = "tweedie"
    def loss(self, label, score):
        rho = self.config.tweedie_variance_power
        eps = 1e-10
        s = np.maximum(score, eps)
        a = label * np.power(s, 1.0 - rho) / (1.0 - rho)
        b = np.power(s, 2.0 - rho) / (2.0 - rho)
        return -a + b


# ---------------------------------------------------------------------- binary
class BinaryLoglossMetric(Metric):
    name = "binary_logloss"
    def eval(self, score, objective=None):
        prob = self._convert(score, objective)
        eps = 1e-15
        prob = np.clip(prob, eps, 1 - eps)
        is_pos = self.label > 0
        pt = np.where(is_pos, -np.log(prob), -np.log(1.0 - prob))
        return [(self.name, self._avg(pt))]


class BinaryErrorMetric(Metric):
    name = "binary_error"
    def eval(self, score, objective=None):
        prob = self._convert(score, objective)
        pred_pos = prob > 0.5
        is_pos = self.label > 0
        return [(self.name, self._avg((pred_pos != is_pos).astype(np.float64)))]


class AUCMetric(Metric):
    """ref: binary_metric.hpp:159 AUCMetric (weighted rank-sum form)."""
    name = "auc"
    is_higher_better = True

    def eval(self, score, objective=None):
        order = np.argsort(-score, kind="stable")
        s = score[order]
        lab = self.label[order] > 0
        w = (np.ones(len(s)) if self.weight is None else self.weight[order])
        # group ties: process equal-score blocks together
        boundaries = np.nonzero(np.diff(s))[0] + 1
        idx = np.concatenate([[0], boundaries, [len(s)]])
        sum_pos = 0.0
        accum = 0.0
        cur_neg = 0.0
        for a, b in zip(idx[:-1], idx[1:]):
            blk_pos = float((w[a:b] * lab[a:b]).sum())
            blk_neg = float((w[a:b] * ~lab[a:b]).sum())
            accum += blk_neg * (sum_pos + blk_pos * 0.5)
            sum_pos += blk_pos
            cur_neg += blk_neg
        if sum_pos == 0 or cur_neg == 0:
            return [(self.name, 1.0)]
        return [(self.name, accum / (sum_pos * cur_neg))]


class AveragePrecisionMetric(Metric):
    """ref: binary_metric.hpp AveragePrecisionMetric."""
    name = "average_precision"
    is_higher_better = True

    def eval(self, score, objective=None):
        order = np.argsort(-score, kind="stable")
        lab = self.label[order] > 0
        w = (np.ones(len(order)) if self.weight is None else self.weight[order])
        tp = np.cumsum(w * lab)
        fp = np.cumsum(w * ~lab)
        precision = tp / np.maximum(tp + fp, 1e-20)
        delta_tp = w * lab
        total_pos = tp[-1]
        if total_pos == 0:
            return [(self.name, 1.0)]
        return [(self.name, float((precision * delta_tp).sum() / total_pos))]


# ------------------------------------------------------------------ multiclass
class MultiLoglossMetric(Metric):
    name = "multi_logloss"
    def eval(self, score, objective=None):
        # score [K, n] raw -> softmax
        prob = self._convert(score, objective)
        if prob.ndim == 1:
            k = self.config.num_class
            prob = prob.reshape(k, -1)
        li = self.label.astype(np.int64)
        p = np.clip(prob[li, np.arange(prob.shape[1])], 1e-15, 1.0)
        return [(self.name, self._avg(-np.log(p)))]


class MultiErrorMetric(Metric):
    name = "multi_error"
    def eval(self, score, objective=None):
        prob = self._convert(score, objective)
        if prob.ndim == 1:
            k = self.config.num_class
            prob = prob.reshape(k, -1)
        top_k = self.config.multi_error_top_k
        li = self.label.astype(np.int64)
        true_p = prob[li, np.arange(prob.shape[1])]
        # error if true-class prob is not within top_k; ties count AGAINST
        # the row (ref: multiclass_metric.hpp:142 LossOnPoint counts
        # num_larger with >= including the class itself, error when
        # num_larger > top_k)
        num_ge = (prob >= true_p[None, :]).sum(axis=0)
        err = (num_ge > top_k).astype(np.float64)
        return [(self.name, self._avg(err))]


class AucMuMetric(Metric):
    """AUC-mu multiclass ranking metric (ref: multiclass_metric.hpp:183
    AucMuMetric; Kleiman & Page, ICML'19).  For every class pair (i, j)
    the rows of the two classes are projected on the separating direction
    v = W[i] - W[j] (W the auc_mu weight matrix, default all-ones with
    zero diagonal, config.cpp:220) and a pairwise AUC S[i][j] is
    accumulated with the reference's tie handling: rows within kEpsilon
    (1e-15, meta.h:54) of the last j-class distance contribute 0.5 per
    tied j row.  Result = 2 * sum_{i<j} S[i][j]/(n_i*n_j) / (K*(K-1))."""
    name = "auc_mu"
    is_higher_better = True
    _EPS = 1e-15

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        K = self.config.num_class
        w = list(self.config.auc_mu_weights or [])
        if w:
            if len(w) != K * K:
                log.fatal(f"auc_mu_weights must have {K * K} elements, "
                          f"but found {len(w)}")
            W = np.asarray(w, np.float64).reshape(K, K)
            if np.abs(np.diag(W)).max() > 1e-35:
                log.info("AUC-mu matrix must have zeros on diagonal. "
                         "Overwriting.")
            np.fill_diagonal(W, 0.0)
        else:
            W = np.ones((K, K), np.float64)
            np.fill_diagonal(W, 0.0)
        self.class_weights = W
        li = self.label.astype(np.int64)
        self.class_idx = [np.nonzero(li == k)[0] for k in range(K)]
        self.class_sizes = np.array([len(ix) for ix in self.class_idx])
        if self.weight is not None:
            self.class_data_weights = np.array(
                [float(self.weight[ix].sum()) for ix in self.class_idx])

    def _pair_auc(self, score, i, j):
        """S[i][j] of the reference's Eval loop, vectorized."""
        idx = np.concatenate([self.class_idx[i], self.class_idx[j]])
        if len(self.class_idx[i]) == 0 or len(self.class_idx[j]) == 0:
            return 0.0
        v = self.class_weights[i] - self.class_weights[j]      # curr_v
        t1 = v[i] - v[j]
        dist = t1 * (v @ score[:, idx])                        # [n_i+n_j]
        lab = np.concatenate([np.full(len(self.class_idx[i]), i),
                              np.full(len(self.class_idx[j]), j)])
        w = (np.ones(len(idx)) if self.weight is None
             else self.weight[idx])
        # sort by distance; exact ties put class j first (the reference
        # comparator orders near-ties by label descending; exact-tie
        # grouping below covers the epsilon credit)
        order = np.lexsort((-lab, dist))
        dist, lab, w = dist[order], lab[order], w[order]
        is_j = lab == j
        wj = np.where(is_j, w, 0.0)
        cum_wj = np.cumsum(wj)                 # num_j including position
        # j-distance groups: a new group starts when the j row's distance
        # moves >= eps from the previous j row's (the reference chains
        # from the group-start distance; consecutive chaining is
        # equivalent except for pathological sub-eps ladders)
        jpos = np.nonzero(is_j)[0]
        if len(jpos) == 0:
            return 0.0
        jd = dist[jpos]
        new_grp = np.empty(len(jpos), bool)
        new_grp[0] = True
        new_grp[1:] = np.abs(np.diff(jd)) >= self._EPS
        grp_of_j = np.cumsum(new_grp) - 1
        starts = np.nonzero(new_grp)[0]
        grp_start_dist = jd[starts]
        grp_start_cumwj_before = cum_wj[jpos[starts]] - wj[jpos[starts]]
        # per row: index of the last j row at/before it
        last_j = np.searchsorted(jpos, np.arange(len(dist)), "right") - 1
        ipos = np.nonzero(~is_j)[0]
        li_ = last_j[ipos]
        has_j = li_ >= 0
        g = grp_of_j[np.maximum(li_, 0)]
        num_j_before = np.where(has_j, cum_wj[np.maximum(jpos[np.maximum(
            li_, 0)], 0)], 0.0) * has_j
        tie = has_j & (np.abs(dist[ipos] - grp_start_dist[g]) < self._EPS)
        num_cur_j = np.where(tie, num_j_before
                             - grp_start_cumwj_before[g], 0.0)
        contrib = w[ipos] * (num_j_before - 0.5 * num_cur_j)
        return float(contrib.sum())

    def eval(self, score, objective=None):
        K = self.config.num_class
        if score.ndim == 1:
            score = score.reshape(K, -1)
        score = np.asarray(score, np.float64)
        ans = 0.0
        for i in range(K):
            for j in range(i + 1, K):
                s = self._pair_auc(score, i, j)
                if self.weight is None:
                    den = (self.class_sizes[i] * self.class_sizes[j])
                else:
                    den = (self.class_data_weights[i]
                           * self.class_data_weights[j])
                if den > 0:
                    ans += s / den
        return [(self.name, 2.0 * ans / (K * (K - 1)))]


# --------------------------------------------------------------------- ranking
DEFAULT_LABEL_GAIN_SIZE = 31


def default_label_gain() -> List[float]:
    return [float((1 << i) - 1) for i in range(DEFAULT_LABEL_GAIN_SIZE)]


class NDCGMetric(Metric):
    """NDCG@k (ref: rank_metric.hpp:20, dcg_calculator.cpp)."""
    name = "ndcg"
    is_higher_better = True

    def __init__(self, config: Config):
        super().__init__(config)
        self.eval_at = list(config.eval_at) or [1, 2, 3, 4, 5]
        self.label_gain = list(config.label_gain) or default_label_gain()

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.query_boundaries is None:
            log.fatal("The NDCG metric requires query information")

    def eval(self, score, objective=None):
        qb = self.query_boundaries
        gains = np.asarray(self.label_gain)
        results = {k: [] for k in self.eval_at}
        for qi in range(len(qb) - 1):
            a, b = int(qb[qi]), int(qb[qi + 1])
            lab = self.label[a:b].astype(np.int64)
            sc = score[a:b]
            g = gains[lab]
            order = np.argsort(-sc, kind="stable")
            ideal = np.sort(g)[::-1]
            discounts = 1.0 / np.log2(np.arange(len(lab)) + 2.0)
            for k in self.eval_at:
                kk = min(k, len(lab))
                idcg = float((ideal[:kk] * discounts[:kk]).sum())
                if idcg > 0:
                    dcg = float((g[order][:kk] * discounts[:kk]).sum())
                    results[k].append(dcg / idcg)
                else:
                    results[k].append(1.0)
        return [(f"ndcg@{k}", float(np.mean(results[k]))) for k in self.eval_at]


class MapMetric(Metric):
    """MAP@k (ref: map_metric.hpp:17)."""
    name = "map"
    is_higher_better = True

    def __init__(self, config: Config):
        super().__init__(config)
        self.eval_at = list(config.eval_at) or [1, 2, 3, 4, 5]

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.query_boundaries is None:
            log.fatal("The MAP metric requires query information")

    def eval(self, score, objective=None):
        qb = self.query_boundaries
        results = {k: [] for k in self.eval_at}
        for qi in range(len(qb) - 1):
            a, b = int(qb[qi]), int(qb[qi + 1])
            rel = (self.label[a:b] > 0)[np.argsort(-score[a:b], kind="stable")]
            npos = int(rel.sum())
            cum = np.cumsum(rel)
            prec_at_hit = np.where(rel, cum / (np.arange(len(rel)) + 1.0), 0.0)
            for k in self.eval_at:
                kk = min(k, len(rel))
                denom = min(npos, kk)
                if denom > 0:
                    results[k].append(float(prec_at_hit[:kk].sum()) / denom)
                else:
                    results[k].append(1.0)
        return [(f"map@{k}", float(np.mean(results[k]))) for k in self.eval_at]


# ---------------------------------------------------------------- cross-entropy
class CrossEntropyMetric(Metric):
    name = "cross_entropy"
    def eval(self, score, objective=None):
        p = np.clip(self._convert(score, objective), 1e-15, 1 - 1e-15)
        y = self.label
        pt = -y * np.log(p) - (1 - y) * np.log(1 - p)
        return [(self.name, self._avg(pt))]


class CrossEntropyLambdaMetric(Metric):
    name = "cross_entropy_lambda"
    def eval(self, score, objective=None):
        hhat = self._convert(score, objective)  # log1p(exp(score))
        y = self.label
        w = self.weight if self.weight is not None else 1.0
        z = 1.0 - np.exp(-w * hhat)
        z = np.clip(z, 1e-15, 1 - 1e-15)
        pt = -y * np.log(z) - (1 - y) * np.log(1 - z)
        return [(self.name, float(np.mean(pt)))]


class KLDivergenceMetric(Metric):
    name = "kullback_leibler"
    def eval(self, score, objective=None):
        p = np.clip(self._convert(score, objective), 1e-15, 1 - 1e-15)
        y = np.clip(self.label, 1e-15, 1 - 1e-15)
        pt = y * np.log(y / p) + (1 - y) * np.log((1 - y) / (1 - p))
        return [(self.name, self._avg(pt))]


# --------------------------------------------------------------------- factory
_METRIC_ALIASES = {
    "l2": "l2", "mean_squared_error": "l2", "mse": "l2", "regression": "l2",
    "regression_l2": "l2",
    "l2_root": "rmse", "root_mean_squared_error": "rmse", "rmse": "rmse",
    "l1": "l1", "mean_absolute_error": "l1", "mae": "l1", "regression_l1": "l1",
    "quantile": "quantile", "huber": "huber", "fair": "fair", "poisson": "poisson",
    "mape": "mape", "mean_absolute_percentage_error": "mape",
    "gamma": "gamma", "gamma_deviance": "gamma_deviance", "tweedie": "tweedie",
    "binary_logloss": "binary_logloss", "binary": "binary_logloss",
    "binary_error": "binary_error",
    "auc": "auc", "average_precision": "average_precision",
    "multi_logloss": "multi_logloss", "multiclass": "multi_logloss",
    "softmax": "multi_logloss", "multiclassova": "multi_logloss",
    "multi_error": "multi_error", "auc_mu": "auc_mu",
    "ndcg": "ndcg", "lambdarank": "ndcg", "rank_xendcg": "ndcg",
    "xendcg": "ndcg", "map": "map", "mean_average_precision": "map",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda", "xentlambda": "cross_entropy_lambda",
    "kullback_leibler": "kullback_leibler", "kldiv": "kullback_leibler",
}

_METRIC_CLASSES = {
    "l2": L2Metric, "rmse": RMSEMetric, "l1": L1Metric,
    "quantile": QuantileMetric, "huber": HuberMetric, "fair": FairMetric,
    "poisson": PoissonMetric, "mape": MAPEMetric, "gamma": GammaMetric,
    "gamma_deviance": GammaDevianceMetric, "tweedie": TweedieMetric,
    "binary_logloss": BinaryLoglossMetric, "binary_error": BinaryErrorMetric,
    "auc": AUCMetric, "average_precision": AveragePrecisionMetric,
    "multi_logloss": MultiLoglossMetric, "multi_error": MultiErrorMetric,
    "auc_mu": AucMuMetric,
    "ndcg": NDCGMetric, "map": MapMetric,
    "cross_entropy": CrossEntropyMetric,
    "cross_entropy_lambda": CrossEntropyLambdaMetric,
    "kullback_leibler": KLDivergenceMetric,
}

# objective -> default metric (ref: config.cpp Config::GetMetricType)
_DEFAULT_FOR_OBJECTIVE = {
    "regression": "l2", "regression_l1": "l1", "huber": "huber", "fair": "fair",
    "poisson": "poisson", "quantile": "quantile", "mape": "mape",
    "gamma": "gamma", "tweedie": "tweedie", "binary": "binary_logloss",
    "multiclass": "multi_logloss", "multiclassova": "multi_logloss",
    "cross_entropy": "cross_entropy", "cross_entropy_lambda": "cross_entropy_lambda",
    "lambdarank": "ndcg", "rank_xendcg": "ndcg",
}


def create_metrics(config: Config, for_objective: Optional[str] = None) -> List[Metric]:
    """ref: src/metric/metric.cpp:19 Metric::CreateMetric + config metric parsing."""
    names = [str(m).strip().lower() for m in (config.metric or [])]
    if not names:
        obj = for_objective or config.objective
        if obj in _DEFAULT_FOR_OBJECTIVE:
            names = [_DEFAULT_FOR_OBJECTIVE[obj]]
    out: List[Metric] = []
    seen = set()
    for nm in names:
        if nm in ("", "na", "null", "none", "custom"):
            continue
        canon = _METRIC_ALIASES.get(nm)
        if canon is None:
            log.warning(f"Unknown metric: {nm}")
            continue
        if canon in seen:
            continue
        seen.add(canon)
        out.append(_METRIC_CLASSES[canon](config))
    return out


# ----------------------------------------------------- device (sharded) eval
def device_pointwise_loss(name: str, config: Config):
    """jnp pointwise-loss builder for the sharded train-metric evaluator
    (gbdt._eval_train_sharded): fn(converted_score, label) -> loss, or
    None when the metric has no device form.  Formulas mirror the host
    classes above exactly (which mirror src/metric/*_metric.hpp)."""
    import jax.numpy as jnp
    eps10, eps15 = 1e-10, 1e-15

    def clip_pos(s):
        return jnp.maximum(s, eps10)

    fns = {
        "l2": lambda s, y: (s - y) ** 2,
        "rmse": lambda s, y: (s - y) ** 2,          # sqrt after averaging
        "l1": lambda s, y: jnp.abs(s - y),
        "quantile": lambda s, y: jnp.where(
            (y - s) < 0, (config.alpha - 1.0) * (y - s),
            config.alpha * (y - s)),
        "huber": lambda s, y: jnp.where(
            jnp.abs(s - y) <= config.alpha,
            0.5 * (s - y) ** 2,
            config.alpha * (jnp.abs(s - y) - 0.5 * config.alpha)),
        "fair": lambda s, y: (config.fair_c * jnp.abs(s - y)
                              - config.fair_c ** 2
                              * jnp.log1p(jnp.abs(s - y) / config.fair_c)),
        "poisson": lambda s, y: clip_pos(s) - y * jnp.log(clip_pos(s)),
        "mape": lambda s, y: jnp.abs((y - s)
                                     / jnp.maximum(1.0, jnp.abs(y))),
        "gamma": lambda s, y: (jnp.maximum(y, eps10) / clip_pos(s)
                               + jnp.log(clip_pos(s))),
        "gamma_deviance": lambda s, y: 2.0 * (
            -jnp.log(jnp.maximum(y / clip_pos(s), eps10))
            + y / clip_pos(s) - 1.0),
        "tweedie": lambda s, y: (
            -y * clip_pos(s) ** (1.0 - config.tweedie_variance_power)
            / (1.0 - config.tweedie_variance_power)
            + clip_pos(s) ** (2.0 - config.tweedie_variance_power)
            / (2.0 - config.tweedie_variance_power)),
        "binary_logloss": lambda s, y: jnp.where(
            y > 0, -jnp.log(jnp.clip(s, eps15, 1 - eps15)),
            -jnp.log(1.0 - jnp.clip(s, eps15, 1 - eps15))),
        "binary_error": lambda s, y: ((s > 0.5) != (y > 0)).astype(
            jnp.float32),
        "xentropy": lambda s, y: -(y * jnp.log(jnp.clip(s, eps15, 1.0))
                                   + (1.0 - y)
                                   * jnp.log(jnp.clip(1.0 - s, eps15,
                                                      1.0))),
    }
    return fns.get(name)


def device_binned_auc(prob, label, w, num_bins: int = 16384):
    """Weighted AUC from a global score-bin histogram — the
    multi-process form (each term is a plain sum, so GSPMD reduces the
    sharded rows with one all-reduce).  Resolution 1/num_bins of
    probability space; ties within a bin get the same half-credit the
    host block form gives exact ties (binary_metric.hpp:159)."""
    import jax.numpy as jnp
    # scores need not be probabilities (regression/ranking objectives
    # report raw scores): min-max normalize over the weighted rows first
    # — AUC is invariant under monotone maps, so this only sets the
    # binning resolution.  Zero-weight (padding) rows are excluded from
    # the range so they cannot skew it.
    lo = jnp.min(jnp.where(w > 0, prob, jnp.inf))
    hi = jnp.max(jnp.where(w > 0, prob, -jnp.inf))
    span = jnp.maximum(hi - lo, 1e-30)
    unit = jnp.clip((prob - lo) / span, 0.0, 1.0)
    b = jnp.clip((unit * num_bins).astype(jnp.int32), 0, num_bins - 1)
    is_pos = label > 0
    pos_h = jnp.zeros(num_bins, jnp.float32).at[b].add(
        jnp.where(is_pos, w, 0.0))
    neg_h = jnp.zeros(num_bins, jnp.float32).at[b].add(
        jnp.where(is_pos, 0.0, w))
    # descending-score accumulation: higher bins first
    pos_above = (jnp.cumsum(pos_h[::-1])[::-1]) - pos_h
    accum = jnp.sum(neg_h * (pos_above + 0.5 * pos_h))
    tp, tn = jnp.sum(pos_h), jnp.sum(neg_h)
    return jnp.where((tp == 0) | (tn == 0), 1.0, accum
                     / jnp.maximum(tp * tn, 1e-30))


def device_binned_average_precision(prob, label, w, num_bins: int = 16384):
    """Weighted average precision from the same global score-bin
    histogram device_binned_auc uses (multi-process form of
    binary_metric.hpp AveragePrecisionMetric).  Within-bin ordering is
    quantized to 1/num_bins of score space, like the binned AUC."""
    import jax.numpy as jnp
    lo = jnp.min(jnp.where(w > 0, prob, jnp.inf))
    hi = jnp.max(jnp.where(w > 0, prob, -jnp.inf))
    span = jnp.maximum(hi - lo, 1e-30)
    unit = jnp.clip((prob - lo) / span, 0.0, 1.0)
    b = jnp.clip((unit * num_bins).astype(jnp.int32), 0, num_bins - 1)
    is_pos = label > 0
    pos_h = jnp.zeros(num_bins, jnp.float32).at[b].add(
        jnp.where(is_pos, w, 0.0))
    neg_h = jnp.zeros(num_bins, jnp.float32).at[b].add(
        jnp.where(is_pos, 0.0, w))
    # descending-score traversal: inclusive cumulative tp/fp from above
    tp = jnp.cumsum(pos_h[::-1])[::-1]
    fp = jnp.cumsum(neg_h[::-1])[::-1]
    prec = tp / jnp.maximum(tp + fp, 1e-20)
    total_pos = jnp.sum(pos_h)
    ap = jnp.sum(prec * pos_h) / jnp.maximum(total_pos, 1e-30)
    return jnp.where(total_pos == 0, 1.0, ap)


def device_auc_mu(prob, label, w, class_weights: np.ndarray,
                  num_bins: int = 4096):
    """auc_mu over sharded rows (multi-process form of AucMuMetric):
    each class pair's rows are projected on v = W[i]-W[j] (row-local),
    then a binned two-class AUC runs per pair — every term is a plain
    sum, so GSPMD reduces the sharded rows.  Tie credit is quantized to
    the bin resolution like device_binned_auc."""
    import jax.numpy as jnp
    K = prob.shape[0]
    Wm = np.asarray(class_weights, np.float32)
    total = 0.0
    for i in range(K):
        for j in range(i + 1, K):
            v = jnp.asarray(Wm[i] - Wm[j])
            t1 = float(Wm[i, i] - Wm[j, i] - (Wm[i, j] - Wm[j, j]))
            dist = t1 * jnp.einsum("k,kn->n", v, prob)
            in_pair = (label == i) | (label == j)
            wp = jnp.where(in_pair, w, 0.0)
            total = total + device_binned_auc(dist, (label == i), wp,
                                              num_bins=num_bins)
    return 2.0 * total / (K * (K - 1))


def map_device_plan(metric: "MapMetric", n_pad: int, shared_buckets=None):
    """Device evaluation plan for MAP@k over sharded scores (the
    multi-process form of MapMetric.eval; ref map_metric.hpp:17):
    per-query sorted-precision sums from bucketed sort programs, with
    per-query positive counts and denominators precomputed host-side
    (labels are static).  Returns (bucket_args, eval_fn)."""
    import jax.numpy as jnp
    lab_all = metric.label
    ks = list(metric.eval_at)
    buckets = []
    nq = 0
    for bi, b in enumerate(bucket_queries(metric.query_boundaries, n_pad)):
        Qb, m = len(b["qs"]), b["m"]
        rel = np.zeros((Qb, m), np.float32)
        denom = np.zeros((Qb, len(ks)), np.float32)
        for r, q in enumerate(b["qs"]):
            a, e = (int(metric.query_boundaries[q]),
                    int(metric.query_boundaries[q + 1]))
            rq = (lab_all[a:e] > 0)
            rel[r, :e - a] = rq
            npos = int(rq.sum())
            for ki, k in enumerate(ks):
                denom[r, ki] = min(npos, min(k, e - a))
        sh = (shared_buckets[bi] if shared_buckets is not None
              and bi < len(shared_buckets)
              and shared_buckets[bi]["idx"].shape == b["idx"].shape
              else None)
        buckets.append({"idx": sh["idx"] if sh else jnp.asarray(b["idx"]),
                        "val": sh["val"] if sh else jnp.asarray(b["val"]),
                        "rel": jnp.asarray(rel),
                        "denom": jnp.asarray(denom)})
        nq += Qb

    def eval_fn(sc, bucket_args):
        sums = jnp.zeros(len(ks), jnp.float32)
        for bk in bucket_args:
            m = bk["idx"].shape[1]
            scb = jnp.take(sc, bk["idx"])
            key = jnp.where(bk["val"], scb, -jnp.inf)
            order = jnp.argsort(-key, axis=1, stable=True)
            rel_sorted = jnp.take_along_axis(bk["rel"], order, 1)
            cum = jnp.cumsum(rel_sorted, axis=1)
            pos_idx = jnp.arange(m, dtype=jnp.float32) + 1.0
            prec_at_hit = jnp.where(rel_sorted > 0,
                                    cum / pos_idx[None, :], 0.0)
            terms = []
            for ki, k in enumerate(ks):
                kk = min(k, m)
                s = jnp.sum(prec_at_hit[:, :kk], axis=1)
                d = bk["denom"][:, ki]
                terms.append(jnp.sum(jnp.where(d > 0,
                                               s / jnp.maximum(d, 1.0),
                                               1.0)))
            sums = sums + jnp.stack(terms)
        return sums / nq

    return buckets, eval_fn


def bucket_queries(query_boundaries, n_pad: int):
    """Group queries by pow2-padded length for device-side per-query
    tensor programs (ranking gradients and ndcg eval share this):
    returns a list of dicts {qs: [query ids], idx: [Qb, m] int32 global
    row indices (padding -> n_pad - 1), val: [Qb, m] bool}."""
    qb = np.asarray(query_boundaries)
    lens = np.diff(qb).astype(np.int64)
    groups = {}
    for q, ln in enumerate(lens):
        m = max(8, 1 << int(ln - 1).bit_length())
        groups.setdefault(m, []).append(q)
    out = []
    for m, qs in sorted(groups.items()):
        Qb = len(qs)
        idx = np.full((Qb, m), n_pad - 1, np.int32)
        val = np.zeros((Qb, m), bool)
        for r, q in enumerate(qs):
            a, b = int(qb[q]), int(qb[q + 1])
            idx[r, :b - a] = np.arange(a, b)
            val[r, :b - a] = True
        out.append({"qs": qs, "m": m, "idx": idx, "val": val})
    return out


def ndcg_device_plan(metric: "NDCGMetric", n_pad: int,
                     shared_buckets=None):
    """Device evaluation plan for NDCG@k over sharded scores: per-query
    DCG from bucketed sort programs, ideal DCG precomputed host-side
    (labels are static).  Returns (bucket_args pytree, eval_fn) where
    eval_fn(scores_1d, bucket_args) -> [len(eval_at)] means — the
    multi-process form of NDCGMetric.eval (rank_metric.hpp:20)."""
    import jax.numpy as jnp
    gains_np = np.asarray(metric.label_gain, np.float64)
    lab_all = metric.label.astype(np.int64)
    ks = list(metric.eval_at)
    buckets = []
    nq = 0
    for bi, b in enumerate(bucket_queries(metric.query_boundaries, n_pad)):
        Qb, m = len(b["qs"]), b["m"]
        g = np.zeros((Qb, m), np.float32)
        idcg = np.zeros((Qb, len(ks)), np.float32)
        disc = 1.0 / np.log2(np.arange(m) + 2.0)
        for r, q in enumerate(b["qs"]):
            a, e = (int(metric.query_boundaries[q]),
                    int(metric.query_boundaries[q + 1]))
            gq = gains_np[lab_all[a:e]]
            ideal = np.sort(gq)[::-1]
            g[r, :e - a] = gq
            for ki, k in enumerate(ks):
                kk = min(k, e - a)
                idcg[r, ki] = (ideal[:kk] * disc[:kk]).sum()
        # a lambdarank objective has already uploaded identical idx/val
        # tensors (bucket_queries is deterministic) — share them instead
        # of holding a second device copy
        sh = (shared_buckets[bi] if shared_buckets is not None
              and bi < len(shared_buckets)
              and shared_buckets[bi]["idx"].shape == b["idx"].shape
              else None)
        buckets.append({"idx": sh["idx"] if sh else jnp.asarray(b["idx"]),
                        "val": sh["val"] if sh else jnp.asarray(b["val"]),
                        "g": jnp.asarray(g),
                        "idcg": jnp.asarray(idcg)})
        nq += Qb

    def eval_fn(sc, bucket_args):
        sums = jnp.zeros(len(ks), jnp.float32)
        for bk in bucket_args:
            m = bk["idx"].shape[1]
            scb = jnp.take(sc, bk["idx"])
            key = jnp.where(bk["val"], scb, -jnp.inf)
            order = jnp.argsort(-key, axis=1, stable=True)
            g_sorted = jnp.take_along_axis(bk["g"], order, 1)
            disc = (1.0 / jnp.log2(
                jnp.arange(m, dtype=jnp.float32) + 2.0))
            terms = []
            for ki, k in enumerate(ks):
                kk = min(k, m)
                dcg = jnp.sum(g_sorted[:, :kk] * disc[None, :kk], axis=1)
                nd = jnp.where(bk["idcg"][:, ki] > 0,
                               dcg / jnp.maximum(bk["idcg"][:, ki], 1e-30),
                               1.0)
                terms.append(jnp.sum(nd))
            sums = sums + jnp.stack(terms)
        return sums / nq

    return buckets, eval_fn
