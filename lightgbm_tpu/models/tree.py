"""Array-encoded decision tree + vectorized prediction + model text (de)serialization.

Mirrors the reference Tree (ref: include/LightGBM/tree.h:25, src/io/tree.cpp): internal
nodes live in parallel arrays sized num_leaves-1, leaves in arrays sized num_leaves;
child pointers use the `~leaf` encoding (negative = leaf index bitwise-complemented).
decision_type packs categorical(bit0) / default_left(bit1) / missing_type(bits 2-3)
(ref: tree.h:19-20,260-278).  Prediction is vectorized over rows (NumPy host path);
the jitted training/prediction paths use the same arrays as jnp tensors.

Text format is line-compatible with the reference's `Tree=N` blocks
(ref: src/io/tree.cpp:339-397 ToString, Tree::Tree(const char*) parser).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

import numpy as np

from ..io.binning import (K_ZERO_THRESHOLD, MISSING_NAN, MISSING_NONE,
                          MISSING_ZERO)
from ..utils import log

K_CATEGORICAL_MASK = 1
K_DEFAULT_LEFT_MASK = 2

_K_MAX_VAL = float(np.finfo(np.float64).max)


def _fmt(v: float, high: bool = False) -> str:
    """LightGBM-style number formatting (ref: common.h ArrayToString)."""
    if high:
        s = repr(float(v))
        if s.endswith(".0"):
            s = s[:-2]
        return s
    return f"{float(v):g}"


class Tree:
    """One decision tree (ref: tree.h:25 `class Tree`)."""

    def __init__(self, max_leaves: int, track_branch_features: bool = False,
                 is_linear: bool = False):
        self.max_leaves = max_leaves
        self.num_leaves = 1
        n = max(max_leaves - 1, 1)
        self.split_feature = np.zeros(n, dtype=np.int32)        # original feature index
        self.split_feature_inner = np.zeros(n, dtype=np.int32)  # inner (used) index
        self.split_gain = np.zeros(n, dtype=np.float32)
        self.threshold = np.zeros(n, dtype=np.float64)          # real-valued
        self.threshold_in_bin = np.zeros(n, dtype=np.int32)
        self.decision_type = np.zeros(n, dtype=np.int8)
        self.left_child = np.zeros(n, dtype=np.int32)
        self.right_child = np.zeros(n, dtype=np.int32)
        self.internal_value = np.zeros(n, dtype=np.float64)
        self.internal_weight = np.zeros(n, dtype=np.float64)
        self.internal_count = np.zeros(n, dtype=np.int64)
        self.leaf_value = np.zeros(max_leaves, dtype=np.float64)
        self.leaf_weight = np.zeros(max_leaves, dtype=np.float64)
        self.leaf_count = np.zeros(max_leaves, dtype=np.int64)
        self.leaf_parent = np.full(max_leaves, -1, dtype=np.int32)
        self.leaf_depth = np.zeros(max_leaves, dtype=np.int32)
        # categorical split storage (ref: tree.h cat_boundaries_/cat_threshold_)
        self.num_cat = 0
        self.cat_boundaries: List[int] = [0]
        self.cat_threshold: List[int] = []          # uint32 bitset words (real values)
        self.cat_boundaries_inner: List[int] = [0]
        self.cat_threshold_inner: List[int] = []    # uint32 bitset words (bins)
        self.shrinkage = 1.0
        # False only for trees parsed from model TEXT, whose bin-space
        # routing fields (threshold_in_bin, split_feature_inner, inner
        # cat bitsets) are unset — the text stores real-valued thresholds
        # only.  GBDT.continue_from reconstructs them against the
        # training dataset's bin mappers before any bin-space use
        # (_add_tree_score: DART drops, RF averaging).
        self._bin_space_valid = True
        self.is_linear = is_linear
        # linear-tree leaf models (ref: tree.h leaf_const_/leaf_coeff_/
        # leaf_features_; Shi et al. 1802.05640)
        self.leaf_const = np.zeros(max_leaves, dtype=np.float64)
        self.leaf_coeff: List[List[float]] = [[] for _ in range(max_leaves)]
        self.leaf_features: List[List[int]] = [[] for _ in range(max_leaves)]
        self.leaf_features_inner: List[List[int]] = [[] for _ in range(max_leaves)]

    # ------------------------------------------------------------------
    def split(self, leaf: int, inner_feature: int, real_feature: int,
              threshold_bin: int, threshold_double: float,
              left_value: float, right_value: float,
              left_cnt: int, right_cnt: int,
              left_weight: float, right_weight: float, gain: float,
              missing_type: int, default_left: bool) -> int:
        """Numerical split of `leaf`; returns the new internal node index
        (ref: tree.h:415 Split + tree.cpp Tree::Split)."""
        new_node = self.num_leaves - 1
        dtype = 0
        if default_left:
            dtype |= K_DEFAULT_LEFT_MASK
        dtype |= (missing_type & 3) << 2
        self.decision_type[new_node] = dtype
        self._split_common(new_node, leaf, inner_feature, real_feature,
                           left_value, right_value, left_cnt, right_cnt,
                           left_weight, right_weight, gain)
        self.threshold_in_bin[new_node] = threshold_bin
        self.threshold[new_node] = threshold_double
        return new_node

    def split_categorical(self, leaf: int, inner_feature: int, real_feature: int,
                          bins_in_left: List[int], cats_in_left: List[int],
                          left_value: float, right_value: float,
                          left_cnt: int, right_cnt: int,
                          left_weight: float, right_weight: float, gain: float,
                          missing_type: int) -> int:
        """Categorical split: left iff category in bitset (ref: tree.h SplitCategorical)."""
        new_node = self.num_leaves - 1
        self._split_common(new_node, leaf, inner_feature, real_feature,
                           left_value, right_value, left_cnt, right_cnt,
                           left_weight, right_weight, gain)
        self.register_cat_split(new_node, bins_in_left, cats_in_left,
                                missing_type)
        return new_node

    def register_cat_split(self, node: int, bins_in_left: List[int],
                           cats_in_left: List[int], missing_type: int) -> None:
        """Record `node`'s category set: threshold = cat index, bitsets
        appended, boundaries extended (ref: tree.h SplitCategorical
        cat_boundaries_/cat_threshold_ bookkeeping)."""
        self.decision_type[node] = K_CATEGORICAL_MASK | ((missing_type & 3) << 2)
        self.threshold_in_bin[node] = self.num_cat
        self.threshold[node] = self.num_cat
        self.cat_threshold.extend(_to_bitset(cats_in_left))
        self.cat_boundaries.append(len(self.cat_threshold))
        self.cat_threshold_inner.extend(_to_bitset(bins_in_left))
        self.cat_boundaries_inner.append(len(self.cat_threshold_inner))
        self.num_cat += 1

    def _split_common(self, new_node: int, leaf: int, inner_feature: int,
                      real_feature: int, left_value: float, right_value: float,
                      left_cnt: int, right_cnt: int, left_weight: float,
                      right_weight: float, gain: float) -> None:
        new_leaf = self.num_leaves
        parent = self.leaf_parent[leaf]
        if parent >= 0:
            if self.left_child[parent] == ~leaf:
                self.left_child[parent] = new_node
            else:
                self.right_child[parent] = new_node
        self.split_feature_inner[new_node] = inner_feature
        self.split_feature[new_node] = real_feature
        self.split_gain[new_node] = gain
        self.left_child[new_node] = ~leaf
        self.right_child[new_node] = ~new_leaf
        self.internal_value[new_node] = self.leaf_value[leaf]
        self.internal_weight[new_node] = left_weight + right_weight
        self.internal_count[new_node] = left_cnt + right_cnt
        depth = self.leaf_depth[leaf]
        self.leaf_value[leaf] = _clip_leaf(left_value)
        self.leaf_weight[leaf] = left_weight
        self.leaf_count[leaf] = left_cnt
        self.leaf_value[new_leaf] = _clip_leaf(right_value)
        self.leaf_weight[new_leaf] = right_weight
        self.leaf_count[new_leaf] = right_cnt
        self.leaf_parent[leaf] = new_node
        self.leaf_parent[new_leaf] = new_node
        self.leaf_depth[leaf] = depth + 1
        self.leaf_depth[new_leaf] = depth + 1
        self.num_leaves += 1

    # ------------------------------------------------------------------
    def apply_shrinkage(self, rate: float) -> None:
        """(ref: tree.h:187 Shrinkage; linear consts/coeffs scale too)."""
        self.leaf_value[:self.num_leaves] *= rate
        self.internal_value[:max(self.num_leaves - 1, 0)] *= rate
        if self.is_linear:
            self.leaf_const[:self.num_leaves] *= rate
            for i in range(self.num_leaves):
                self.leaf_coeff[i] = [c * rate for c in self.leaf_coeff[i]]
        self.shrinkage *= rate

    def add_bias(self, val: float) -> None:
        """(ref: tree.h:201 AddBias)."""
        self.leaf_value[:self.num_leaves] += val
        if self.is_linear:
            self.leaf_const[:self.num_leaves] += val
        self.internal_value[:max(self.num_leaves - 1, 0)] += val
        self.shrinkage = 1.0

    def set_leaf_output(self, leaf: int, value: float) -> None:
        self.leaf_value[leaf] = _clip_leaf(value)

    # ------------------------------------------------------------------
    def get_leaf_index(self, X: np.ndarray) -> np.ndarray:
        """Vectorized leaf assignment for raw feature rows [n, F_total]
        (ref: tree.h:422 GetLeaf)."""
        n = X.shape[0]
        if self.num_leaves <= 1:
            return np.zeros(n, dtype=np.int32)
        node = np.zeros(n, dtype=np.int32)  # >=0 internal, <0 => leaf ~idx
        for _ in range(self.num_leaves):  # depth bound
            active = node >= 0
            if not active.any():
                break
            nd = node[active]
            fvals = X[active, self.split_feature[nd]]
            go_left = self._decision(fvals, nd)
            node[active] = np.where(go_left, self.left_child[nd], self.right_child[nd])
        return (~node).astype(np.int32)

    def _decision(self, fvals: np.ndarray, nodes: np.ndarray) -> np.ndarray:
        dt = self.decision_type[nodes]
        missing_type = (dt >> 2) & 3
        default_left = (dt & K_DEFAULT_LEFT_MASK) > 0
        is_cat = (dt & K_CATEGORICAL_MASK) > 0
        nan_mask = np.isnan(fvals)
        # numerical (ref: tree.h:335 NumericalDecision)
        fv = np.where(nan_mask & (missing_type != MISSING_NAN), 0.0, fvals)
        is_zero = np.abs(fv) <= K_ZERO_THRESHOLD
        take_default = (((missing_type == MISSING_ZERO) & is_zero)
                        | ((missing_type == MISSING_NAN) & nan_mask))
        num_left = np.where(take_default, default_left,
                            fv <= self.threshold[nodes])
        if not is_cat.any():
            return num_left
        # categorical (ref: tree.h:372 CategoricalDecision)
        cat_left = np.zeros(len(fvals), dtype=bool)
        for i in np.nonzero(is_cat)[0]:
            v = fvals[i]
            if np.isnan(v) or int(v) < 0:
                cat_left[i] = False
                continue
            cat_idx = int(self.threshold[nodes[i]])
            cat_left[i] = self._find_in_bitset(
                self.cat_threshold, self.cat_boundaries, cat_idx, int(v))
        return np.where(is_cat, cat_left, num_left)

    @staticmethod
    def _find_in_bitset(bitset: List[int], boundaries: List[int], cat_idx: int,
                        val: int) -> bool:
        start, end = boundaries[cat_idx], boundaries[cat_idx + 1]
        word = val // 32
        if word >= end - start:
            return False
        return (bitset[start + word] >> (val % 32)) & 1 == 1

    def predict(self, X: np.ndarray) -> np.ndarray:
        if self.num_leaves <= 1:
            if self.is_linear:
                return np.full(X.shape[0], self.leaf_const[0])
            return np.full(X.shape[0], self.leaf_value[0])
        leaf = self.get_leaf_index(X)
        if not self.is_linear:
            return self.leaf_value[leaf]
        # linear leaves: const + coeffs . x; rows with NaN in any of the
        # leaf's features fall back to leaf_value (ref: tree.cpp:133)
        out = np.empty(X.shape[0])
        for l in range(self.num_leaves):
            rows = np.nonzero(leaf == l)[0]
            if len(rows) == 0:
                continue
            feats = self.leaf_features[l]
            val = np.full(len(rows), self.leaf_const[l])
            if feats:
                sub = X[np.ix_(rows, feats)]
                nan_rows = np.isnan(sub).any(axis=1)
                val += sub @ np.asarray(self.leaf_coeff[l])
                val = np.where(nan_rows, self.leaf_value[l], val)
            out[rows] = val
        return out

    # ------------------------------------------------------------------
    def to_string(self, index: int) -> str:
        """`Tree=N` block, line-compatible with the reference
        (ref: tree.cpp:339 ToString)."""
        nl = self.num_leaves
        ni = max(nl - 1, 0)
        lines = [f"Tree={index}",
                 f"num_leaves={nl}",
                 f"num_cat={self.num_cat}"]

        def arr(name, a, count, high=False):
            lines.append(name + "=" + " ".join(_fmt(x, high) for x in a[:count]))

        def iarr(name, a, count):
            lines.append(name + "=" + " ".join(str(int(x)) for x in a[:count]))

        iarr("split_feature", self.split_feature, ni)
        arr("split_gain", self.split_gain, ni)
        arr("threshold", self.threshold, ni, high=True)
        iarr("decision_type", self.decision_type, ni)
        iarr("left_child", self.left_child, ni)
        iarr("right_child", self.right_child, ni)
        arr("leaf_value", self.leaf_value, nl, high=True)
        arr("leaf_weight", self.leaf_weight, nl, high=True)
        iarr("leaf_count", self.leaf_count, nl)
        arr("internal_value", self.internal_value, ni)
        arr("internal_weight", self.internal_weight, ni)
        iarr("internal_count", self.internal_count, ni)
        if self.num_cat > 0:
            iarr("cat_boundaries", np.array(self.cat_boundaries), self.num_cat + 1)
            iarr("cat_threshold", np.array(self.cat_threshold), len(self.cat_threshold))
        lines.append(f"is_linear={int(self.is_linear)}")
        if self.is_linear:
            # ref: tree.cpp:379-399 linear serialization
            arr("leaf_const", self.leaf_const, nl, high=True)
            lines.append("num_features=" + " ".join(
                str(len(self.leaf_coeff[i])) for i in range(nl)))
            feats_parts = []
            coef_parts = []
            for i in range(nl):
                if self.leaf_coeff[i]:
                    feats_parts.append(" ".join(
                        str(f) for f in self.leaf_features[i]))
                    coef_parts.append(" ".join(
                        _fmt(c, True) for c in self.leaf_coeff[i]))
            lines.append("leaf_features=" + " ".join(feats_parts))
            lines.append("leaf_coeff=" + " ".join(coef_parts))
        lines.append(f"shrinkage={_fmt(self.shrinkage)}")
        lines.append("")
        return "\n".join(lines) + "\n"

    @classmethod
    def from_string(cls, text: str) -> "Tree":
        """Parse a `Tree=N` block (ref: tree.cpp Tree::Tree(const char*, size_t*))."""
        kv: Dict[str, str] = {}
        for line in text.strip().splitlines():
            if "=" in line:
                k, v = line.split("=", 1)
                kv[k.strip()] = v.strip()
        if "num_leaves" not in kv:
            log.fatal("Tree model string format error: missing num_leaves")
        nl = int(kv["num_leaves"])
        t = cls(max(nl, 2))
        t.num_leaves = nl
        t.num_cat = int(kv.get("num_cat", "0"))
        ni = max(nl - 1, 0)
        # the reference fatals on trees without the required fields
        # (ref: tree.cpp "Tree model should contain leaf_value field");
        # leaf_value is required even for single-leaf trees, the split
        # arrays only once a split exists
        required = ["leaf_value"]
        if nl > 1:
            required += ["split_feature", "threshold", "left_child",
                         "right_child"]
        for req in required:
            if req not in kv:
                log.fatal(f"Tree model should contain {req} field")

        def read_arr(key, dtype, count):
            if count == 0 or key not in kv or kv[key] == "":
                return np.zeros(count, dtype=dtype)
            vals = np.array([float(x) for x in kv[key].split()], dtype=np.float64)
            return vals.astype(dtype)

        if ni > 0:
            # bin-space routing cannot be recovered from text alone:
            # flag it so continue_from reconstructs against the training
            # dataset's bin mappers (real-threshold prediction is exact
            # without it; only training-time score adds need bins)
            t._bin_space_valid = False
            t.split_feature[:ni] = read_arr("split_feature", np.int32, ni)
            t.split_feature_inner[:ni] = t.split_feature[:ni]
            t.split_gain[:ni] = read_arr("split_gain", np.float32, ni)
            t.threshold[:ni] = read_arr("threshold", np.float64, ni)
            t.decision_type[:ni] = read_arr("decision_type", np.int8, ni)
            t.left_child[:ni] = read_arr("left_child", np.int32, ni)
            t.right_child[:ni] = read_arr("right_child", np.int32, ni)
            t.internal_value[:ni] = read_arr("internal_value", np.float64, ni)
            t.internal_weight[:ni] = read_arr("internal_weight", np.float64, ni)
            t.internal_count[:ni] = read_arr("internal_count", np.int64, ni)
        t.leaf_value[:nl] = read_arr("leaf_value", np.float64, nl)
        t.leaf_weight[:nl] = read_arr("leaf_weight", np.float64, nl)
        t.leaf_count[:nl] = read_arr("leaf_count", np.int64, nl)
        if t.num_cat > 0:
            t.cat_boundaries = [int(x) for x in kv["cat_boundaries"].split()]
            t.cat_threshold = [int(float(x)) for x in kv["cat_threshold"].split()]
            t.cat_boundaries_inner = list(t.cat_boundaries)
            t.cat_threshold_inner = list(t.cat_threshold)
        t.shrinkage = float(kv.get("shrinkage", "1"))
        t.is_linear = bool(int(kv.get("is_linear", "0")))
        if t.is_linear:
            # ref: tree.cpp Tree(const char*) linear block
            t.leaf_const[:nl] = read_arr("leaf_const", np.float64, nl)
            nfeat = [int(x) for x in kv.get("num_features", "").split()]
            feats = [int(x) for x in kv.get("leaf_features", "").split()]
            coefs = [float(x) for x in kv.get("leaf_coeff", "").split()]
            pos = 0
            for i in range(nl):
                k = nfeat[i] if i < len(nfeat) else 0
                t.leaf_features[i] = feats[pos:pos + k]
                t.leaf_features_inner[i] = list(t.leaf_features[i])
                t.leaf_coeff[i] = coefs[pos:pos + k]
                pos += k
        return t

    def to_json(self, index: int) -> dict:
        """(ref: tree.cpp Tree::ToJSON/NodeToJSON)."""
        def node_json(i: int) -> dict:
            if i < 0:
                leaf = ~i
                return {"leaf_index": leaf,
                        "leaf_value": float(self.leaf_value[leaf]),
                        "leaf_weight": float(self.leaf_weight[leaf]),
                        "leaf_count": int(self.leaf_count[leaf])}
            dt = int(self.decision_type[i])
            is_cat = bool(dt & K_CATEGORICAL_MASK)
            mt = {MISSING_NONE: "None", MISSING_ZERO: "Zero",
                  MISSING_NAN: "NaN"}[(dt >> 2) & 3]
            return {
                "split_index": int(i),
                "split_feature": int(self.split_feature[i]),
                "split_gain": float(self.split_gain[i]),
                "threshold": (float(self.threshold[i]) if not is_cat else
                              "||".join(str(c) for c in self._cats_of_node(i))),
                "decision_type": "==" if is_cat else "<=",
                "default_left": bool(dt & K_DEFAULT_LEFT_MASK),
                "missing_type": mt,
                "internal_value": float(self.internal_value[i]),
                "internal_weight": float(self.internal_weight[i]),
                "internal_count": int(self.internal_count[i]),
                "left_child": node_json(int(self.left_child[i])),
                "right_child": node_json(int(self.right_child[i])),
            }
        return {"tree_index": index, "num_leaves": int(self.num_leaves),
                "num_cat": int(self.num_cat), "shrinkage": float(self.shrinkage),
                "tree_structure": node_json(0 if self.num_leaves > 1 else ~0)}

    def _cats_of_node(self, node: int) -> List[int]:
        cat_idx = int(self.threshold[node])
        start, end = self.cat_boundaries[cat_idx], self.cat_boundaries[cat_idx + 1]
        out = []
        for w in range(start, end):
            word = self.cat_threshold[w]
            for b in range(32):
                if (word >> b) & 1:
                    out.append((w - start) * 32 + b)
        return out

    # ------------------------------------------------------------------
    def feature_importance_split(self, num_features: int) -> np.ndarray:
        out = np.zeros(num_features, dtype=np.float64)
        for i in range(self.num_leaves - 1):
            if self.split_gain[i] > 0:
                out[self.split_feature[i]] += 1
        return out

    def feature_importance_gain(self, num_features: int) -> np.ndarray:
        out = np.zeros(num_features, dtype=np.float64)
        for i in range(self.num_leaves - 1):
            if self.split_gain[i] > 0:
                out[self.split_feature[i]] += self.split_gain[i]
        return out


def _clip_leaf(v: float) -> float:
    if math.isnan(v):
        return 0.0
    return min(max(v, -_K_MAX_VAL), _K_MAX_VAL)


def _to_bitset(vals: List[int]) -> List[int]:
    """(ref: utils/common.h ConstructBitset)."""
    if not vals:
        return [0]
    nwords = max(v for v in vals) // 32 + 1
    words = [0] * nwords
    for v in vals:
        words[v // 32] |= 1 << (v % 32)
    return words
