"""Native runtime pieces (C, ctypes-loaded, compiled on demand).

The reference keeps its host-side runtime in C++ (TreeSHAP in
src/io/tree.cpp, the predictor in src/application/predictor.hpp); the TPU
framework's device path is XLA, but host-side recursive algorithms with no
vectorizable structure stay native here too.  Compilation uses the
toolchain's cc once per source hash, cached under ~/.cache/lightgbm_tpu.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Optional

import numpy as np

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))
_shap_lib = None
_shap_tried = False


def _cache_dir() -> str:
    base = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    d = os.path.join(base, "lightgbm_tpu")
    os.makedirs(d, exist_ok=True)
    return d


def _compile(src_path: str, tag: str,
             extra_flags: tuple = ()) -> Optional[str]:
    """Compile src to a cached shared library; returns its path or None.
    extra_flags are best-effort: compilation retries without them."""
    with open(src_path, "rb") as f:
        src = f.read()
    h = hashlib.sha256(src + repr(extra_flags).encode()).hexdigest()[:16]
    out = os.path.join(_cache_dir(), f"lib{tag}-{h}.so")
    if os.path.exists(out):
        return out
    for flags in ((*extra_flags,), ()) if extra_flags else ((),):
        for cc in ("cc", "gcc", "g++", "clang"):
            try:
                tmp = tempfile.mktemp(suffix=".so", dir=_cache_dir())
                r = subprocess.run(
                    [cc, "-O2", "-shared", "-fPIC", *flags, "-o", tmp,
                     src_path, "-lm"],
                    capture_output=True, timeout=120)
                if r.returncode == 0:
                    os.replace(tmp, out)
                    return out
            except (OSError, subprocess.TimeoutExpired):
                continue
    return None


_parser_lib = None
_parser_tried = False
_pred_lib = None
_pred_tried = False


def predictor_lib():
    """The compiled batch predictor (OpenMP over rows when the compiler
    supports it; ref: src/application/predictor.hpp)."""
    global _pred_lib, _pred_tried
    if _pred_tried:
        return _pred_lib
    _pred_tried = True
    path = _compile(os.path.join(_SRC_DIR, "predict.c"), "predict",
                    extra_flags=("-fopenmp",))
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:  # stale/foreign cached .so: fall back to Python
        return None
    c_dbl = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
    c_i32 = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    c_i8 = np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS")
    c_u32 = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
    c_long = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    lib.lgbt_predict_batch.argtypes = [
        c_dbl, ctypes.c_long, ctypes.c_long,
        c_i32, c_dbl, c_i8, c_i32, c_i32, c_dbl, c_u32, c_i32,
        c_long, c_long, c_long, c_long,
        ctypes.c_long, ctypes.c_long, ctypes.c_int, c_dbl]
    lib.lgbt_predict_batch.restype = None
    lib.lgbt_predict_leaf.argtypes = [
        c_dbl, ctypes.c_long, ctypes.c_long,
        c_i32, c_dbl, c_i8, c_i32, c_i32, c_u32, c_i32,
        c_long, c_long, c_long,
        ctypes.c_long,
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")]
    lib.lgbt_predict_leaf.restype = None
    _pred_lib = lib
    return lib


class PackedPredictor:
    """Flattened tree arrays for repeated native predict calls (the
    packing is O(model size); callers cache one per model slice)."""

    def __init__(self, trees):
        self.ok = not any(getattr(t, "is_linear", False) for t in trees)
        if not self.ok:
            return
        self._pack(trees)

    def _pack(self, trees):
        self.T = len(trees)
        sf, th, dt, lc, rc, lv, cw, cb = [], [], [], [], [], [], [], []
        node_off = [0]
        leaf_off = [0]
        cw_off = [0]
        cb_off = [0]
        for t in trees:
            nl = t.num_leaves
            ni = max(nl - 1, 0)
            sf.append(np.asarray(t.split_feature[:ni], np.int32))
            th.append(np.asarray(t.threshold[:ni], np.float64))
            dt.append(np.asarray(t.decision_type[:ni], np.int8))
            lc.append(np.asarray(t.left_child[:ni], np.int32))
            rc.append(np.asarray(t.right_child[:ni], np.int32))
            lv.append(np.asarray(t.leaf_value[:max(nl, 1)], np.float64))
            words = np.asarray(t.cat_threshold, np.uint32)
            bounds = np.asarray(t.cat_boundaries, np.int32)
            cw.append(words)
            cb.append(bounds)
            node_off.append(node_off[-1] + ni)
            leaf_off.append(leaf_off[-1] + max(nl, 1))
            cw_off.append(cw_off[-1] + len(words))
            cb_off.append(cb_off[-1] + len(bounds))

        def cat(parts, dtype):
            return (np.concatenate(parts) if parts
                    else np.zeros(0, dtype)).astype(dtype)
        self.sf = cat(sf, np.int32)
        self.th = cat(th, np.float64)
        self.dt = cat(dt, np.int8)
        self.lc = cat(lc, np.int32)
        self.rc = cat(rc, np.int32)
        self.lv = cat(lv, np.float64)
        self.cw = cat(cw, np.uint32)
        self.cb = cat(cb, np.int32)
        self.node_off = np.asarray(node_off, np.int64)
        self.leaf_off = np.asarray(leaf_off, np.int64)
        self.cw_off = np.asarray(cw_off, np.int64)
        self.cb_off = np.asarray(cb_off, np.int64)

    def predict_leaf(self, X: np.ndarray) -> Optional[np.ndarray]:
        """[n, T] leaf indices, or None when unavailable."""
        lib = predictor_lib()
        if lib is None or not self.ok:
            return None
        X = np.ascontiguousarray(X, np.float64)
        n = X.shape[0]
        out = np.zeros((n, self.T), np.int32)
        lib.lgbt_predict_leaf(
            X, n, X.shape[1], self.sf, self.th, self.dt, self.lc, self.rc,
            self.cw, self.cb, self.node_off, self.cw_off, self.cb_off,
            self.T, out)
        return out

    def predict(self, X: np.ndarray, K: int,
                average: bool) -> Optional[np.ndarray]:
        lib = predictor_lib()
        if lib is None or not self.ok:
            return None
        X = np.ascontiguousarray(X, np.float64)
        n = X.shape[0]
        out = np.zeros((n, K), np.float64)
        lib.lgbt_predict_batch(
            X, n, X.shape[1], self.sf, self.th, self.dt, self.lc, self.rc,
            self.lv, self.cw, self.cb, self.node_off, self.leaf_off,
            self.cw_off, self.cb_off, self.T, K, int(bool(average)), out)
        return out


class SingleRowFastPredictor:
    """Cached single-row predict state (ref: c_api.h:1350-1379
    LGBM_BoosterPredictForMatSingleRowFastInit / ...SingleRowFast, whose
    FastConfig caches the parsed config and buffers, c_api.cpp:125-160).

    Everything reusable is prepared ONCE: the flattened tree pack, the
    input/output buffers, and the host-side output conversion — a
    predict() call is one buffer write + one ctypes call, microseconds
    per row instead of the full batch-path entry cost."""

    def __init__(self, packed: "PackedPredictor", num_features: int,
                 K: int, average: bool, convert=None):
        self._packed = packed
        self._K = K
        self._convert = convert
        self._X = np.zeros((1, num_features), np.float64)
        self._out = np.zeros((1, K), np.float64)
        self._lib = predictor_lib()
        if self._lib is None or not packed.ok:
            return
        # marshalling 19 ndpointer args costs ~10us EACH per call: bind
        # the raw pointers ONCE through a second (argtype-free) handle —
        # every buffer is owned by this object / the pack, so the
        # addresses are stable for the predictor's lifetime
        p = packed
        lib2 = ctypes.CDLL(self._lib._name)
        self._fn = lib2.lgbt_predict_batch
        self._fn.restype = None
        vp = ctypes.c_void_p
        cl = ctypes.c_long
        self._cargs = (
            vp(self._X.ctypes.data), cl(1), cl(num_features),
            vp(p.sf.ctypes.data), vp(p.th.ctypes.data),
            vp(p.dt.ctypes.data), vp(p.lc.ctypes.data),
            vp(p.rc.ctypes.data), vp(p.lv.ctypes.data),
            vp(p.cw.ctypes.data), vp(p.cb.ctypes.data),
            vp(p.node_off.ctypes.data), vp(p.leaf_off.ctypes.data),
            vp(p.cw_off.ctypes.data), vp(p.cb_off.ctypes.data),
            cl(p.T), cl(K), ctypes.c_int(int(bool(average))),
            vp(self._out.ctypes.data))

    @property
    def ok(self) -> bool:
        return self._lib is not None and self._packed.ok

    def predict(self, row) -> np.ndarray:
        """row: [F] array-like -> [K] predictions (converted unless the
        predictor was built raw)."""
        self._X[0, :] = row
        self._out[0, :] = 0.0        # the C kernel accumulates (+=)
        self._fn(*self._cargs)
        out = self._out[0]
        if self._convert is not None:
            out = self._convert(out)
        return out.copy()


def predict_batch_native(trees, X: np.ndarray, K: int,
                         average: bool) -> Optional[np.ndarray]:
    """One-shot native prediction (packs then predicts); callers with
    repeated predicts should cache a PackedPredictor instead."""
    if predictor_lib() is None:
        return None
    packed = PackedPredictor(trees)
    return packed.predict(X, K, average) if packed.ok else None


def parser_lib():
    """The compiled text-parser library, or None when no compiler works
    (ref: src/io/parser.cpp — the reference's parsers are C++ too)."""
    global _parser_lib, _parser_tried
    if _parser_tried:
        return _parser_lib
    _parser_tried = True
    path = _compile(os.path.join(_SRC_DIR, "parser.c"), "parser")
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    c_dbl_p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
    lib.lgbt_parse_dense.argtypes = [
        ctypes.c_char_p, ctypes.c_long, ctypes.c_char, ctypes.c_long,
        ctypes.c_long, c_dbl_p]
    lib.lgbt_parse_dense.restype = ctypes.c_long
    lib.lgbt_libsvm_scan.argtypes = [
        ctypes.c_char_p, ctypes.c_long, ctypes.POINTER(ctypes.c_long)]
    lib.lgbt_libsvm_scan.restype = ctypes.c_long
    lib.lgbt_parse_libsvm.argtypes = [
        ctypes.c_char_p, ctypes.c_long, ctypes.c_long, ctypes.c_long,
        c_dbl_p, c_dbl_p]
    lib.lgbt_parse_libsvm.restype = ctypes.c_long
    _parser_lib = lib
    return lib


def parse_dense_native(data: bytes, delim: str, n_rows: int,
                       n_cols: int):
    """Dense text -> [n_rows, n_cols] float64 (NaN missing), or None when
    the native parser is unavailable; raises ValueError on ragged rows."""
    lib = parser_lib()
    if lib is None:
        return None
    out = np.empty((n_rows, n_cols), np.float64)
    got = lib.lgbt_parse_dense(data, len(data), delim.encode()[:1],
                               n_rows, n_cols, out)
    if got < 0:
        raise ValueError("bad token or inconsistent column count on data "
                         f"line {-got}")
    return out[:got]


def parse_libsvm_native(data: bytes, line_offset: int = 0):
    """LibSVM text -> (features [n, max_idx+1] float64, labels [n]), or
    None when the native parser is unavailable.  line_offset shifts
    error line numbers for chunked (streamed) inputs."""
    lib = parser_lib()
    if lib is None:
        return None
    max_idx = ctypes.c_long(-1)
    n = lib.lgbt_libsvm_scan(data, len(data), ctypes.byref(max_idx))
    n_cols = max(int(max_idx.value) + 1, 1)
    feats = np.zeros((n, n_cols), np.float64)
    labels = np.empty(n, np.float64)
    got = lib.lgbt_parse_libsvm(data, len(data), n, n_cols, labels, feats)
    if got < 0:
        raise ValueError("malformed libsvm pair on data line "
                         f"{line_offset - got}")
    return feats[:got], labels[:got]


def treeshap_lib():
    """The compiled TreeSHAP library, or None when no compiler works."""
    global _shap_lib, _shap_tried
    if _shap_tried:
        return _shap_lib
    _shap_tried = True
    path = _compile(os.path.join(_SRC_DIR, "treeshap.c"), "treeshap")
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    c_int_p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    c_dbl_p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
    c_i8_p = np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS")
    c_u32_p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
    lib.treeshap_batch.argtypes = [
        c_int_p, c_dbl_p, c_i8_p, c_int_p, c_int_p,      # split/thr/dt/lc/rc
        c_dbl_p, c_dbl_p, c_dbl_p,                       # leaf_value/ic/lc
        c_u32_p, c_int_p, ctypes.c_int, ctypes.c_int,    # cat, num_cat, nl
        c_dbl_p, ctypes.c_long, ctypes.c_int,            # X, rows, xcols
        c_dbl_p, ctypes.c_int, c_dbl_p]                  # phi, ncol, scratch
    lib.treeshap_batch.restype = ctypes.c_int
    _shap_lib = lib
    return lib


def tree_shap(tree, X: np.ndarray, phi: np.ndarray) -> None:
    """Accumulate one tree's SHAP values into phi [n, ncol] (last column =
    expected value).  Native when a compiler is available, Python fallback
    otherwise (ref: tree.h:139 PredictContrib)."""
    n = X.shape[0]
    ncol = phi.shape[1]
    nl = tree.num_leaves
    if nl <= 1:
        phi[:, -1] += tree.leaf_value[0]
        return
    ni = nl - 1
    depth = int(np.max(tree.leaf_depth[:nl])) if nl > 1 else 1
    lib = treeshap_lib()
    X = np.ascontiguousarray(X, np.float64)
    if lib is not None:
        scratch = np.zeros(((depth + 2) * (depth + 3) // 2) * 4, np.float64)
        if tree.num_cat:
            cat_thr = np.ascontiguousarray(tree.cat_threshold, np.uint32)
            cat_b = np.ascontiguousarray(tree.cat_boundaries, np.int32)
        else:
            cat_thr = np.zeros(1, np.uint32)
            cat_b = np.zeros(2, np.int32)
        rc = lib.treeshap_batch(
            np.ascontiguousarray(tree.split_feature[:ni], np.int32),
            np.ascontiguousarray(tree.threshold[:ni], np.float64),
            np.ascontiguousarray(tree.decision_type[:ni], np.int8),
            np.ascontiguousarray(tree.left_child[:ni], np.int32),
            np.ascontiguousarray(tree.right_child[:ni], np.int32),
            np.ascontiguousarray(tree.leaf_value[:nl], np.float64),
            np.ascontiguousarray(tree.internal_count[:ni], np.float64),
            np.ascontiguousarray(tree.leaf_count[:nl], np.float64),
            cat_thr, cat_b, int(tree.num_cat), int(nl),
            X, n, X.shape[1], phi, ncol, scratch)
        if rc == 0:
            return
    _tree_shap_py(tree, X, phi)


# ---------------------------------------------------------------- fallback
def _tree_shap_py(tree, X, phi):
    """Pure-Python TreeSHAP (Lundberg et al. 2018, Algorithm 2) — slow;
    used only when no C compiler is available."""
    nl = tree.num_leaves
    counts = {}

    def node_count(nd):
        return (tree.leaf_count[~nd] if nd < 0
                else tree.internal_count[nd])

    expected = float(np.dot(tree.leaf_value[:nl], tree.leaf_count[:nl])
                     / max(tree.internal_count[0], 1))

    def extend(path, zf, of, fi):
        path = path + [[fi, zf, of, 1.0 if not path else 0.0]]
        d = len(path) - 1
        for i in range(d - 1, -1, -1):
            path[i + 1][3] += of * path[i][3] * (i + 1) / (d + 1)
            path[i][3] = zf * path[i][3] * (d - i) / (d + 1)
        return path

    def unwound_sum(path, pi):
        d = len(path) - 1
        of, zf = path[pi][2], path[pi][1]
        nop = path[d][3]
        total = 0.0
        for i in range(d - 1, -1, -1):
            if of != 0:
                tmp = nop * (d + 1) / ((i + 1) * of)
                total += tmp
                nop = path[i][3] - tmp * zf * (d - i) / (d + 1)
            else:
                total += path[i][3] / (zf * (d - i) / (d + 1))
        return total

    def unwind(path, pi):
        d = len(path) - 1
        of, zf = path[pi][2], path[pi][1]
        nop = path[d][3]
        path = [list(e) for e in path]
        for i in range(d - 1, -1, -1):
            if of != 0:
                tmp = path[i][3]
                path[i][3] = nop * (d + 1) / ((i + 1) * of)
                nop = tmp - path[i][3] * zf * (d - i) / (d + 1)
            else:
                path[i][3] = path[i][3] * (d + 1) / (zf * (d - i))
        for i in range(pi, d):
            path[i][:3] = path[i + 1][:3]
        return path[:d]

    def recurse(r, node, path, zf, of, fi, ph):
        path = extend([list(e) for e in path], zf, of, fi)
        if node < 0:
            v = tree.leaf_value[~node]
            for i in range(1, len(path)):
                w = unwound_sum(path, i)
                ph[path[i][0]] += w * (path[i][2] - path[i][1]) * v
            return
        feat = tree.split_feature[node]
        go_left = bool(tree._decision(
            np.asarray([X[r, feat]]), np.asarray([node]))[0])
        hot = tree.left_child[node] if go_left else tree.right_child[node]
        cold = (tree.right_child[node] if go_left
                else tree.left_child[node])
        w = node_count(node)
        hzf = node_count(hot) / w
        czf = node_count(cold) / w
        izf = iof = 1.0
        pi = next((i for i, e in enumerate(path) if e[0] == feat), None)
        if pi is not None:
            izf, iof = path[pi][1], path[pi][2]
            path = unwind(path, pi)
        recurse(r, hot, path, hzf * izf, iof, feat, ph)
        recurse(r, cold, path, czf * izf, 0.0, feat, ph)

    for r in range(X.shape[0]):
        recurse(r, 0, [], 1.0, 1.0, -1, phi[r])
        phi[r, -1] += expected
