"""Native runtime pieces (C, ctypes-loaded, compiled on demand).

The reference keeps its host-side runtime in C++ (TreeSHAP in
src/io/tree.cpp, the predictor in src/application/predictor.hpp); the TPU
framework's device path is XLA, but host-side recursive algorithms with no
vectorizable structure stay native here too.  Compilation uses the
toolchain's cc once per source hash, cached under ~/.cache/lightgbm_tpu.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Optional

import numpy as np

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))
_shap_lib = None
_shap_tried = False


def _cache_dir() -> str:
    base = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    d = os.path.join(base, "lightgbm_tpu")
    os.makedirs(d, exist_ok=True)
    return d


def _compile(src_path: str, tag: str) -> Optional[str]:
    """Compile src to a cached shared library; returns its path or None."""
    with open(src_path, "rb") as f:
        src = f.read()
    h = hashlib.sha256(src).hexdigest()[:16]
    out = os.path.join(_cache_dir(), f"lib{tag}-{h}.so")
    if os.path.exists(out):
        return out
    for cc in ("cc", "gcc", "g++", "clang"):
        try:
            tmp = tempfile.mktemp(suffix=".so", dir=_cache_dir())
            r = subprocess.run(
                [cc, "-O2", "-shared", "-fPIC", "-o", tmp, src_path, "-lm"],
                capture_output=True, timeout=120)
            if r.returncode == 0:
                os.replace(tmp, out)
                return out
        except (OSError, subprocess.TimeoutExpired):
            continue
    return None


_parser_lib = None
_parser_tried = False


def parser_lib():
    """The compiled text-parser library, or None when no compiler works
    (ref: src/io/parser.cpp — the reference's parsers are C++ too)."""
    global _parser_lib, _parser_tried
    if _parser_tried:
        return _parser_lib
    _parser_tried = True
    path = _compile(os.path.join(_SRC_DIR, "parser.c"), "parser")
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    c_dbl_p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
    lib.lgbt_parse_dense.argtypes = [
        ctypes.c_char_p, ctypes.c_long, ctypes.c_char, ctypes.c_long,
        ctypes.c_long, c_dbl_p]
    lib.lgbt_parse_dense.restype = ctypes.c_long
    lib.lgbt_libsvm_scan.argtypes = [
        ctypes.c_char_p, ctypes.c_long, ctypes.POINTER(ctypes.c_long)]
    lib.lgbt_libsvm_scan.restype = ctypes.c_long
    lib.lgbt_parse_libsvm.argtypes = [
        ctypes.c_char_p, ctypes.c_long, ctypes.c_long, ctypes.c_long,
        c_dbl_p, c_dbl_p]
    lib.lgbt_parse_libsvm.restype = ctypes.c_long
    _parser_lib = lib
    return lib


def parse_dense_native(data: bytes, delim: str, n_rows: int,
                       n_cols: int):
    """Dense text -> [n_rows, n_cols] float64 (NaN missing), or None when
    the native parser is unavailable; raises ValueError on ragged rows."""
    lib = parser_lib()
    if lib is None:
        return None
    out = np.empty((n_rows, n_cols), np.float64)
    got = lib.lgbt_parse_dense(data, len(data), delim.encode()[:1],
                               n_rows, n_cols, out)
    if got < 0:
        raise ValueError("bad token or inconsistent column count on data "
                         f"line {-got}")
    return out[:got]


def parse_libsvm_native(data: bytes):
    """LibSVM text -> (features [n, max_idx+1] float64, labels [n]), or
    None when the native parser is unavailable."""
    lib = parser_lib()
    if lib is None:
        return None
    max_idx = ctypes.c_long(-1)
    n = lib.lgbt_libsvm_scan(data, len(data), ctypes.byref(max_idx))
    n_cols = max(int(max_idx.value) + 1, 1)
    feats = np.zeros((n, n_cols), np.float64)
    labels = np.empty(n, np.float64)
    got = lib.lgbt_parse_libsvm(data, len(data), n, n_cols, labels, feats)
    if got < 0:
        raise ValueError(f"malformed libsvm pair on data line {-got}")
    return feats[:got], labels[:got]


def treeshap_lib():
    """The compiled TreeSHAP library, or None when no compiler works."""
    global _shap_lib, _shap_tried
    if _shap_tried:
        return _shap_lib
    _shap_tried = True
    path = _compile(os.path.join(_SRC_DIR, "treeshap.c"), "treeshap")
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    c_int_p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    c_dbl_p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
    c_i8_p = np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS")
    c_u32_p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
    lib.treeshap_batch.argtypes = [
        c_int_p, c_dbl_p, c_i8_p, c_int_p, c_int_p,      # split/thr/dt/lc/rc
        c_dbl_p, c_dbl_p, c_dbl_p,                       # leaf_value/ic/lc
        c_u32_p, c_int_p, ctypes.c_int, ctypes.c_int,    # cat, num_cat, nl
        c_dbl_p, ctypes.c_long, ctypes.c_int,            # X, rows, xcols
        c_dbl_p, ctypes.c_int, c_dbl_p]                  # phi, ncol, scratch
    lib.treeshap_batch.restype = ctypes.c_int
    _shap_lib = lib
    return lib


def tree_shap(tree, X: np.ndarray, phi: np.ndarray) -> None:
    """Accumulate one tree's SHAP values into phi [n, ncol] (last column =
    expected value).  Native when a compiler is available, Python fallback
    otherwise (ref: tree.h:139 PredictContrib)."""
    n = X.shape[0]
    ncol = phi.shape[1]
    nl = tree.num_leaves
    if nl <= 1:
        phi[:, -1] += tree.leaf_value[0]
        return
    ni = nl - 1
    depth = int(np.max(tree.leaf_depth[:nl])) if nl > 1 else 1
    lib = treeshap_lib()
    X = np.ascontiguousarray(X, np.float64)
    if lib is not None:
        scratch = np.zeros(((depth + 2) * (depth + 3) // 2) * 4, np.float64)
        if tree.num_cat:
            cat_thr = np.ascontiguousarray(tree.cat_threshold, np.uint32)
            cat_b = np.ascontiguousarray(tree.cat_boundaries, np.int32)
        else:
            cat_thr = np.zeros(1, np.uint32)
            cat_b = np.zeros(2, np.int32)
        rc = lib.treeshap_batch(
            np.ascontiguousarray(tree.split_feature[:ni], np.int32),
            np.ascontiguousarray(tree.threshold[:ni], np.float64),
            np.ascontiguousarray(tree.decision_type[:ni], np.int8),
            np.ascontiguousarray(tree.left_child[:ni], np.int32),
            np.ascontiguousarray(tree.right_child[:ni], np.int32),
            np.ascontiguousarray(tree.leaf_value[:nl], np.float64),
            np.ascontiguousarray(tree.internal_count[:ni], np.float64),
            np.ascontiguousarray(tree.leaf_count[:nl], np.float64),
            cat_thr, cat_b, int(tree.num_cat), int(nl),
            X, n, X.shape[1], phi, ncol, scratch)
        if rc == 0:
            return
    _tree_shap_py(tree, X, phi)


# ---------------------------------------------------------------- fallback
def _tree_shap_py(tree, X, phi):
    """Pure-Python TreeSHAP (Lundberg et al. 2018, Algorithm 2) — slow;
    used only when no C compiler is available."""
    nl = tree.num_leaves
    counts = {}

    def node_count(nd):
        return (tree.leaf_count[~nd] if nd < 0
                else tree.internal_count[nd])

    expected = float(np.dot(tree.leaf_value[:nl], tree.leaf_count[:nl])
                     / max(tree.internal_count[0], 1))

    def extend(path, zf, of, fi):
        path = path + [[fi, zf, of, 1.0 if not path else 0.0]]
        d = len(path) - 1
        for i in range(d - 1, -1, -1):
            path[i + 1][3] += of * path[i][3] * (i + 1) / (d + 1)
            path[i][3] = zf * path[i][3] * (d - i) / (d + 1)
        return path

    def unwound_sum(path, pi):
        d = len(path) - 1
        of, zf = path[pi][2], path[pi][1]
        nop = path[d][3]
        total = 0.0
        for i in range(d - 1, -1, -1):
            if of != 0:
                tmp = nop * (d + 1) / ((i + 1) * of)
                total += tmp
                nop = path[i][3] - tmp * zf * (d - i) / (d + 1)
            else:
                total += path[i][3] / (zf * (d - i) / (d + 1))
        return total

    def unwind(path, pi):
        d = len(path) - 1
        of, zf = path[pi][2], path[pi][1]
        nop = path[d][3]
        path = [list(e) for e in path]
        for i in range(d - 1, -1, -1):
            if of != 0:
                tmp = path[i][3]
                path[i][3] = nop * (d + 1) / ((i + 1) * of)
                nop = tmp - path[i][3] * zf * (d - i) / (d + 1)
            else:
                path[i][3] = path[i][3] * (d + 1) / (zf * (d - i))
        for i in range(pi, d):
            path[i][:3] = path[i + 1][:3]
        return path[:d]

    def recurse(r, node, path, zf, of, fi, ph):
        path = extend([list(e) for e in path], zf, of, fi)
        if node < 0:
            v = tree.leaf_value[~node]
            for i in range(1, len(path)):
                w = unwound_sum(path, i)
                ph[path[i][0]] += w * (path[i][2] - path[i][1]) * v
            return
        feat = tree.split_feature[node]
        go_left = bool(tree._decision(
            np.asarray([X[r, feat]]), np.asarray([node]))[0])
        hot = tree.left_child[node] if go_left else tree.right_child[node]
        cold = (tree.right_child[node] if go_left
                else tree.left_child[node])
        w = node_count(node)
        hzf = node_count(hot) / w
        czf = node_count(cold) / w
        izf = iof = 1.0
        pi = next((i for i, e in enumerate(path) if e[0] == feat), None)
        if pi is not None:
            izf, iof = path[pi][1], path[pi][2]
            path = unwind(path, pi)
        recurse(r, hot, path, hzf * izf, iof, feat, ph)
        recurse(r, cold, path, czf * izf, 0.0, feat, ph)

    for r in range(X.shape[0]):
        recurse(r, 0, [], 1.0, 1.0, -1, phi[r])
        phi[r, -1] += expected
