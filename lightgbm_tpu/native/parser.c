/* Native text parser: dense CSV/TSV and LibSVM hot loops.
 *
 * TPU-framework analogue of the reference's C++ parser layer
 * (ref: src/io/parser.cpp:1-395 CSVParser/TSVParser/LibSVMParser with
 * Common::Atof; dataset_loader.cpp:1263 ExtractFeaturesFromMemory): the
 * format/label detection stays in Python (io/parser.py), the per-token
 * work runs here.  Loaded via ctypes (native/__init__.py), compiled once
 * per source hash.
 */
#include <math.h>
#include <stdlib.h>
#include <string.h>

/* strtod accepts "nan"/"inf"; empty tokens and na/null map to NaN.
 * Any other token that strtod cannot FULLY consume sets *err — matching
 * the Python fallback's float(tok) ValueError, so native and fallback
 * reject the same inputs (ref: parser.cpp Common::Atof strictness). */
static double parse_token(const char *s, const char *end, int *err) {
  while (s < end && (*s == ' ' || *s == '\r')) ++s;
  const char *e = end;
  while (e > s && (e[-1] == ' ' || e[-1] == '\r')) --e;
  if (s == e) return NAN;
  if ((e - s) == 2 && (s[0] == 'n' || s[0] == 'N') &&
      (s[1] == 'a' || s[1] == 'A'))
    return NAN;
  if ((e - s) == 4 && (s[0] == 'n' || s[0] == 'N') &&
      (s[1] == 'u' || s[1] == 'U') && (s[2] == 'l' || s[2] == 'L') &&
      (s[3] == 'l' || s[3] == 'L'))
    return NAN;
  char tmp[64];
  size_t len = (size_t)(e - s);
  if (len >= sizeof(tmp)) { *err = 1; return NAN; }
  memcpy(tmp, s, len);
  tmp[len] = '\0';
  char *endp = NULL;
  double v = strtod(tmp, &endp);
  if (endp != tmp + len) { *err = 1; return NAN; }
  return v;
}

/* Parse dense delimiter-separated text into out[n_rows * n_cols].
 * Blank lines are skipped.  Returns rows filled, or -(line_no) when a
 * non-blank line has a different column count (1-based over data lines). */
long lgbt_parse_dense(const char *buf, long len, char delim, long n_rows,
                      long n_cols, double *out) {
  long row = 0;
  const char *p = buf, *bend = buf + len;
  while (p < bend && row < n_rows) {
    const char *line_end = memchr(p, '\n', (size_t)(bend - p));
    if (!line_end) line_end = bend;
    /* skip blank lines */
    const char *q = p;
    while (q < line_end && (*q == ' ' || *q == '\r' || *q == '\t')) ++q;
    if (q == line_end) { p = line_end + 1; continue; }
    double *dst = out + row * n_cols;
    long col = 0;
    const char *tok = p;
    for (const char *c = p; ; ++c) {
      if (c == line_end || *c == delim) {
        if (col >= n_cols) return -(row + 1);
        int err = 0;
        dst[col++] = parse_token(tok, c, &err);
        if (err) return -(row + 1);
        tok = c + 1;
        if (c == line_end) break;
      }
    }
    if (col != n_cols) return -(row + 1);
    ++row;
    p = line_end + 1;
  }
  return row;
}

/* LibSVM pass 1: count data rows and the max feature index.
 * Returns row count; *max_idx gets the largest k seen in "k:v" (or -1). */
long lgbt_libsvm_scan(const char *buf, long len, long *max_idx) {
  long rows = 0, mx = -1;
  const char *p = buf, *bend = buf + len;
  while (p < bend) {
    const char *line_end = memchr(p, '\n', (size_t)(bend - p));
    if (!line_end) line_end = bend;
    const char *q = p;
    while (q < line_end && (*q == ' ' || *q == '\r' || *q == '\t')) ++q;
    if (q < line_end) {
      ++rows;
      for (const char *c = q; c < line_end; ++c) {
        if (*c == ':') {
          long k = 0;
          const char *d = c - 1;
          long mul = 1;
          while (d >= q && *d >= '0' && *d <= '9') {
            k += (*d - '0') * mul;
            mul *= 10;
            --d;
          }
          if (mul > 1 && k > mx) mx = k;
        }
      }
    }
    p = line_end + 1;
  }
  *max_idx = mx;
  return rows;
}

/* LibSVM pass 2: labels[n_rows] and dense out[n_rows * n_cols] (caller
 * zero-fills; absent entries mean 0 in LibSVM).  Returns rows filled,
 * or -(line_no) on a malformed pair / out-of-range index. */
long lgbt_parse_libsvm(const char *buf, long len, long n_rows, long n_cols,
                       double *labels, double *out) {
  long row = 0;
  const char *p = buf, *bend = buf + len;
  while (p < bend && row < n_rows) {
    const char *line_end = memchr(p, '\n', (size_t)(bend - p));
    if (!line_end) line_end = bend;
    const char *q = p;
    while (q < line_end && (*q == ' ' || *q == '\r' || *q == '\t')) ++q;
    if (q == line_end) { p = line_end + 1; continue; }
    /* label = first whitespace-separated token */
    const char *t = q;
    while (t < line_end && *t != ' ' && *t != '\t') ++t;
    int err = 0;
    labels[row] = parse_token(q, t, &err);
    if (err) return -(row + 1);
    double *dst = out + row * n_cols;
    const char *c = t;
    while (c < line_end) {
      while (c < line_end && (*c == ' ' || *c == '\t' || *c == '\r')) ++c;
      if (c == line_end) break;
      const char *pair_end = c;
      while (pair_end < line_end && *pair_end != ' ' && *pair_end != '\t' &&
             *pair_end != '\r')
        ++pair_end;
      const char *colon = memchr(c, ':', (size_t)(pair_end - c));
      if (!colon) return -(row + 1);
      char *idx_end = NULL;
      long k = strtol(c, &idx_end, 10);
      if (idx_end != colon || k < 0 || k >= n_cols) return -(row + 1);
      dst[k] = parse_token(colon + 1, pair_end, &err);
      if (err) return -(row + 1);
      c = pair_end;
    }
    ++row;
    p = line_end + 1;
  }
  return row;
}
