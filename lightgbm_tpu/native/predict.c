/* Native batch predictor: traverse every tree for every row, OpenMP over
 * rows.
 *
 * TPU-framework analogue of the reference's native prediction stack
 * (ref: src/application/predictor.hpp:30 batch Predictor with OMP;
 * include/LightGBM/tree.h:335 NumericalDecision, :372 CategoricalDecision,
 * :422 GetLeaf).  Trees are passed as flat arrays with per-tree node
 * offsets; leaf values already carry shrinkage, so raw score = sum over
 * trees.  Linear-tree models stay on the Python path (leaf ridge models
 * need per-leaf feature gathers).
 */
#include <math.h>
#include <stdint.h>

#define K_ZERO_THRESHOLD 1e-35 /* ref: include/LightGBM/meta.h:56 */
#define MISSING_ZERO 1
#define MISSING_NAN 2

/* One tree's traversal for one row; mirrors models/tree.py _decision.
 * Returns the LEAF index — the single source of routing semantics for
 * both value prediction and pred_leaf. */
static int32_t get_leaf_node(const double *row, const int32_t *split_feature,
                             const double *threshold, const int8_t *dtype,
                             const int32_t *left, const int32_t *right,
                             const uint32_t *cat_words,
                             const int32_t *cat_bound) {
  int32_t node = 0;
  while (node >= 0) {
    double fv = row[split_feature[node]];
    int8_t dt = dtype[node];
    int missing_type = (dt >> 2) & 3;
    int is_nan = isnan(fv);
    int go_left;
    if (dt & 1) { /* categorical */
      go_left = 0;
      /* match the Python path exactly (tree.py _decision): v = int(fv)
       * truncates toward zero, negatives go right, and values past any
       * bitset word fall out of range (go right).  fv in (-1, 0)
       * truncates to category 0; doubles beyond long range would be UB
       * to cast, and always exceed the bitset anyway. */
      if (!is_nan && fv > -1.0 && fv < 9.2e18) {
        long v = (long)fv;
        long cat_idx = (long)threshold[node];
        long start = cat_bound[cat_idx], end = cat_bound[cat_idx + 1];
        long word = v / 32;
        if (word < end - start)
          go_left = (cat_words[start + word] >> (v % 32)) & 1u;
      }
    } else {
      double f = (is_nan && missing_type != MISSING_NAN) ? 0.0 : fv;
      int is_zero = fabs(f) <= K_ZERO_THRESHOLD;
      int take_default = (missing_type == MISSING_ZERO && is_zero) ||
                         (missing_type == MISSING_NAN && is_nan);
      go_left = take_default ? ((dt & 2) != 0) : (f <= threshold[node]);
    }
    node = go_left ? left[node] : right[node];
  }
  return ~node;
}

/* Sum T trees' outputs into out[n_rows * K] (class k = tree index % K).
 * Flat layout: tree t's nodes live at node_off[t]..node_off[t+1] in the
 * node arrays, leaves at leaf_off[t].., categorical words/bounds at
 * cat_word_off[t] / cat_bound_off[t].  average > 0 divides by T/K (RF). */
void lgbt_predict_batch(const double *X, long n_rows, long n_cols,
                        const int32_t *split_feature, const double *threshold,
                        const int8_t *dtype, const int32_t *left,
                        const int32_t *right, const double *leaf_value,
                        const uint32_t *cat_words, const int32_t *cat_bound,
                        const long *node_off, const long *leaf_off,
                        const long *cat_word_off, const long *cat_bound_off,
                        long T, long K, int average, double *out) {
  long iters = K > 0 ? T / K : 0;
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
  for (long r = 0; r < n_rows; ++r) {
    const double *row = X + r * n_cols;
    for (long t = 0; t < T; ++t) {
      long k = t % K;
      double v;
      if (node_off[t + 1] - node_off[t] <= 0) {
        /* stump: single leaf */
        v = leaf_value[leaf_off[t]];
      } else {
        int32_t leaf = get_leaf_node(
            row, split_feature + node_off[t], threshold + node_off[t],
            dtype + node_off[t], left + node_off[t], right + node_off[t],
            cat_words + cat_word_off[t], cat_bound + cat_bound_off[t]);
        v = leaf_value[leaf_off[t] + leaf];
      }
      out[r * K + k] += v;
    }
    if (average && iters > 0)
      for (long k = 0; k < K; ++k) out[r * K + k] /= (double)iters;
  }
}

/* Leaf indices per (row, tree) into out_idx[n_rows * T]
 * (ref: tree.h:422 GetLeaf; used by pred_leaf / refit). */
void lgbt_predict_leaf(const double *X, long n_rows, long n_cols,
                       const int32_t *split_feature, const double *threshold,
                       const int8_t *dtype, const int32_t *left,
                       const int32_t *right, const uint32_t *cat_words,
                       const int32_t *cat_bound, const long *node_off,
                       const long *cat_word_off, const long *cat_bound_off,
                       long T, int32_t *out_idx) {
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
  for (long r = 0; r < n_rows; ++r) {
    const double *row = X + r * n_cols;
    for (long t = 0; t < T; ++t) {
      long base = node_off[t];
      if (node_off[t + 1] - base <= 0) {
        out_idx[r * T + t] = 0; /* stump */
        continue;
      }
      out_idx[r * T + t] = get_leaf_node(
          row, split_feature + base, threshold + base, dtype + base,
          left + base, right + base, cat_words + cat_word_off[t],
          cat_bound + cat_bound_off[t]);
    }
  }
}
