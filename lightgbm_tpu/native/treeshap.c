/* TreeSHAP: exact per-row SHAP values for decision-tree ensembles.
 *
 * Native-runtime analogue of the reference's C++ TreeSHAP
 * (ref: include/LightGBM/tree.h:139 PredictContrib; src/io/tree.cpp).
 * Implemented from the published algorithm (Lundberg, Erion & Lee 2018,
 * "Consistent Individualized Feature Attribution for Tree Ensembles",
 * Algorithm 2) — not a translation of the reference source.
 *
 * Tree encoding matches models/tree.py: internal nodes indexed >= 0,
 * leaves as ~leaf; decision_type bit 0 = categorical, bit 1 =
 * default_left, bits 2-3 = missing type (0 none, 1 zero, 2 nan).
 *
 * Compile: gcc -O2 -shared -fPIC -o libtreeshap.so treeshap.c
 */

#include <math.h>
#include <stdint.h>
#include <string.h>

#define MISSING_NONE 0
#define MISSING_ZERO 1
#define MISSING_NAN 2
#define K_ZERO_THRESHOLD 1e-35

typedef struct {
  int feature_index;
  double zero_fraction;
  double one_fraction;
  double pweight;
} PathElement;

typedef struct {
  const int *split_feature;   /* [ni] real feature index */
  const double *threshold;    /* [ni] */
  const int8_t *decision_type;/* [ni] */
  const int *left_child;      /* [ni] */
  const int *right_child;     /* [ni] */
  const double *leaf_value;   /* [nl] */
  const double *internal_count; /* [ni] */
  const double *leaf_count;   /* [nl] */
  const uint32_t *cat_threshold; /* bitset words */
  const int *cat_boundaries;  /* [num_cat+1] */
  int num_cat;
} TreeData;

static double node_count(const TreeData *t, int node) {
  return node < 0 ? t->leaf_count[~node] : t->internal_count[node];
}

static int decision(const TreeData *t, int node, const double *x) {
  /* mirrors tree.h:335 NumericalDecision / :372 CategoricalDecision */
  double fval = x[t->split_feature[node]];
  int8_t dt = t->decision_type[node];
  int missing_type = (dt >> 2) & 3;
  int default_left = (dt & 2) != 0;
  int is_cat = (dt & 1) != 0;
  if (is_cat) {
    if (isnan(fval) || fval < 0) return 0;
    int v = (int)fval;
    int cat_idx = (int)t->threshold[node];
    int start = t->cat_boundaries[cat_idx];
    int end = t->cat_boundaries[cat_idx + 1];
    int word = v / 32;
    if (word >= end - start) return 0;
    return (t->cat_threshold[start + word] >> (v % 32)) & 1u;
  }
  if (isnan(fval) && missing_type != MISSING_NAN) fval = 0.0;
  if ((missing_type == MISSING_ZERO && fabs(fval) <= K_ZERO_THRESHOLD) ||
      (missing_type == MISSING_NAN && isnan(fval)))
    return default_left;
  return fval <= t->threshold[node];
}

static void extend_path(PathElement *path, int unique_depth,
                        double zero_fraction, double one_fraction,
                        int feature_index) {
  path[unique_depth].feature_index = feature_index;
  path[unique_depth].zero_fraction = zero_fraction;
  path[unique_depth].one_fraction = one_fraction;
  path[unique_depth].pweight = unique_depth == 0 ? 1.0 : 0.0;
  for (int i = unique_depth - 1; i >= 0; i--) {
    path[i + 1].pweight +=
        one_fraction * path[i].pweight * (i + 1) / (double)(unique_depth + 1);
    path[i].pweight = zero_fraction * path[i].pweight *
                      (unique_depth - i) / (double)(unique_depth + 1);
  }
}

static void unwind_path(PathElement *path, int unique_depth, int path_index) {
  double one_fraction = path[path_index].one_fraction;
  double zero_fraction = path[path_index].zero_fraction;
  double next_one_portion = path[unique_depth].pweight;
  for (int i = unique_depth - 1; i >= 0; i--) {
    if (one_fraction != 0) {
      double tmp = path[i].pweight;
      path[i].pweight =
          next_one_portion * (unique_depth + 1) / ((i + 1) * one_fraction);
      next_one_portion = tmp - path[i].pweight * zero_fraction *
                                   (unique_depth - i) /
                                   (double)(unique_depth + 1);
    } else {
      path[i].pweight = path[i].pweight * (unique_depth + 1) /
                        (zero_fraction * (unique_depth - i));
    }
  }
  for (int i = path_index; i < unique_depth; i++) {
    path[i].feature_index = path[i + 1].feature_index;
    path[i].zero_fraction = path[i + 1].zero_fraction;
    path[i].one_fraction = path[i + 1].one_fraction;
  }
}

static double unwound_path_sum(const PathElement *path, int unique_depth,
                               int path_index) {
  double one_fraction = path[path_index].one_fraction;
  double zero_fraction = path[path_index].zero_fraction;
  double next_one_portion = path[unique_depth].pweight;
  double total = 0.0;
  for (int i = unique_depth - 1; i >= 0; i--) {
    if (one_fraction != 0) {
      double tmp =
          next_one_portion * (unique_depth + 1) / ((i + 1) * one_fraction);
      total += tmp;
      next_one_portion = path[i].pweight - tmp * zero_fraction *
                                               (unique_depth - i) /
                                               (double)(unique_depth + 1);
    } else {
      total += path[i].pweight /
               (zero_fraction * (unique_depth - i) /
                (double)(unique_depth + 1));
    }
  }
  return total;
}

static void shap_recurse(const TreeData *t, const double *x, double *phi,
                         int node, PathElement *parent_path, int unique_depth,
                         double parent_zero_fraction,
                         double parent_one_fraction, int parent_feature) {
  PathElement *path = parent_path + unique_depth + 1;
  if (unique_depth > 0)
    memcpy(path, parent_path, unique_depth * sizeof(PathElement));
  extend_path(path, unique_depth, parent_zero_fraction, parent_one_fraction,
              parent_feature);

  if (node < 0) { /* leaf */
    double v = t->leaf_value[~node];
    for (int i = 1; i <= unique_depth; i++) {
      double w = unwound_path_sum(path, unique_depth, i);
      phi[path[i].feature_index] +=
          w * (path[i].one_fraction - path[i].zero_fraction) * v;
    }
    return;
  }

  int feature = t->split_feature[node];
  int lc = t->left_child[node];
  int rc = t->right_child[node];
  int hot = decision(t, node, x) ? lc : rc;
  int cold = hot == lc ? rc : lc;
  double w = node_count(t, node);
  double hot_zero_fraction = node_count(t, hot) / w;
  double cold_zero_fraction = node_count(t, cold) / w;
  double incoming_zero_fraction = 1.0;
  double incoming_one_fraction = 1.0;

  int path_index = 0;
  for (; path_index <= unique_depth; path_index++)
    if (path[path_index].feature_index == feature) break;
  if (path_index != unique_depth + 1) {
    incoming_zero_fraction = path[path_index].zero_fraction;
    incoming_one_fraction = path[path_index].one_fraction;
    unwind_path(path, unique_depth, path_index);
    unique_depth -= 1;
  }

  shap_recurse(t, x, phi, hot, path, unique_depth + 1,
               hot_zero_fraction * incoming_zero_fraction,
               incoming_one_fraction, feature);
  shap_recurse(t, x, phi, cold, path, unique_depth + 1,
               cold_zero_fraction * incoming_zero_fraction, 0.0, feature);
}

/* phi: [num_rows, num_columns] preallocated, num_columns >= max feature
 * index + 2; column num_columns-1 accumulates the expected value.
 * X: [num_rows, num_x_cols] row-major raw features.
 * scratch: at least (max_depth+2)*(max_depth+3)/2 PathElements worth of
 * doubles*4, caller-allocated. Returns 0 on success. */
int treeshap_batch(
    const int *split_feature, const double *threshold,
    const int8_t *decision_type, const int *left_child, const int *right_child,
    const double *leaf_value, const double *internal_count,
    const double *leaf_count, const uint32_t *cat_threshold,
    const int *cat_boundaries, int num_cat, int num_leaves,
    const double *X, long num_rows, int num_x_cols,
    double *phi, int num_columns, double *scratch) {
  TreeData t = {split_feature, threshold, decision_type, left_child,
                right_child, leaf_value, internal_count, leaf_count,
                cat_threshold, cat_boundaries, num_cat};
  if (num_leaves <= 1) {
    for (long r = 0; r < num_rows; r++)
      phi[r * num_columns + num_columns - 1] += leaf_value[0];
    return 0;
  }
  double root_count = t.internal_count[0];
  double expected = 0.0;
  for (int l = 0; l < num_leaves; l++)
    expected += leaf_value[l] * leaf_count[l];
  expected /= root_count;
  PathElement *paths = (PathElement *)scratch;
  for (long r = 0; r < num_rows; r++) {
    const double *x = X + (long)r * num_x_cols;
    double *ph = phi + (long)r * num_columns;
    shap_recurse(&t, x, ph, 0, paths, 0, 1.0, 1.0, -1);
    ph[num_columns - 1] += expected;
  }
  return 0;
}
