"""Objective functions: closed-form gradient/hessian ops in pure jnp.

TPU-native replacement for src/objective/ (ref: regression_objective.hpp,
binary_objective.hpp, multiclass_objective.hpp, xentropy_objective.hpp) and its
CUDA twins (src/objective/cuda/): each objective is a pair of jittable maps
score -> (grad, hess) and score -> prediction, plus a host-side
boost_from_score (ref: ObjectiveFunction::BoostFromScore) and an optional
per-leaf output renewal (ref: RenewTreeOutput).

Interface mirrors include/LightGBM/objective_function.h; the factory mirrors
src/objective/objective_function.cpp:20 CreateObjectiveFunction.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config
from .utils import log


def _weighted_percentile(values: np.ndarray, weights: Optional[np.ndarray],
                         alpha: float) -> float:
    """ref: regression_objective.hpp:25-90 PercentileFun/WeightedPercentileFun."""
    if len(values) == 0:
        return 0.0
    order = np.argsort(values, kind="stable")
    v = values[order]
    if weights is None:
        if alpha <= 1.0 / (len(v) + 1):
            return float(v[0])
        if alpha >= len(v) / (len(v) + 1.0):
            return float(v[-1])
        position = alpha * (len(v) + 1)
        idx = int(np.floor(position)) - 1
        frac = position - idx - 1
        return float(v[idx] + frac * (v[idx + 1] - v[idx]))
    w = weights[order].astype(np.float64)
    wsum = w.sum()
    threshold = wsum * alpha
    cum = np.cumsum(w) - w / 2.0
    idx = int(np.searchsorted(cum, threshold, side="right")) - 1
    if idx < 0:
        return float(v[0])
    if idx >= len(v) - 1:
        return float(v[-1])
    frac = (threshold - cum[idx]) / max(cum[idx + 1] - cum[idx], 1e-300)
    return float(v[idx] + frac * (v[idx + 1] - v[idx]))


class ObjectiveFunction:
    """Base (ref: include/LightGBM/objective_function.h)."""

    name = "custom"
    num_model_per_iteration_ = 1
    is_constant_hessian = False
    need_renew_tree_output = False

    def __init__(self, config: Config):
        self.config = config
        self.label: Optional[np.ndarray] = None
        self.weight: Optional[np.ndarray] = None

    def init(self, metadata, num_data: int) -> None:
        self.label = np.asarray(metadata.label, dtype=np.float32)
        self.weight = (None if metadata.weight is None
                       else np.asarray(metadata.weight, dtype=np.float32))
        self.num_data = num_data

    def num_model_per_iteration(self) -> int:
        return self.num_model_per_iteration_

    # -- device-side ops ----------------------------------------------------
    def get_gradients(self, score: jnp.ndarray, label: jnp.ndarray,
                      weight: Optional[jnp.ndarray]):
        """score -> (grad, hess); jittable."""
        raise NotImplementedError

    def convert_output(self, score: jnp.ndarray) -> jnp.ndarray:
        """Raw score -> prediction space (ref: ObjectiveFunction::ConvertOutput)."""
        return score

    def convert_output_host(self, score: np.ndarray) -> np.ndarray:
        """NumPy mirror of convert_output for latency-critical host
        paths (single-row fast predict): no device round-trip."""
        return score

    # -- host-side ----------------------------------------------------------
    def boost_from_score(self, class_id: int = 0) -> float:
        return 0.0

    def renew_tree_output(self, leaf_id: np.ndarray, score: np.ndarray,
                          num_leaves: int) -> Optional[np.ndarray]:
        """Per-leaf output renewal (ref: RenewTreeOutput); returns [num_leaves]
        new outputs or None."""
        return None

    def _apply_weight(self, grad, hess, weight):
        if weight is not None:
            grad = grad * weight
            hess = hess * weight
        return grad, hess


# ------------------------------------------------------------------ regression
class RegressionL2(ObjectiveFunction):
    """ref: regression_objective.hpp:93 RegressionL2loss."""
    name = "regression"
    is_constant_hessian = True  # when unweighted

    def __init__(self, config: Config):
        super().__init__(config)
        self.sqrt = config.reg_sqrt

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.sqrt:
            self.raw_label = self.label
            self.label = (np.sign(self.label) *
                          np.sqrt(np.abs(self.label))).astype(np.float32)

    def get_gradients(self, score, label, weight):
        grad = score - label
        hess = jnp.ones_like(score)
        return self._apply_weight(grad, hess, weight)

    def convert_output(self, score):
        if self.sqrt:
            return jnp.sign(score) * score * score
        return score

    def convert_output_host(self, score):
        if self.sqrt:
            return np.sign(score) * score * score
        return score

    def boost_from_score(self, class_id: int = 0) -> float:
        if self.weight is None:
            return float(np.mean(self.label))
        return float(np.sum(self.label * self.weight) / np.sum(self.weight))


class RegressionL1(RegressionL2):
    """ref: regression_objective.hpp:206 RegressionL1loss."""
    name = "regression_l1"
    need_renew_tree_output = True
    is_constant_hessian = True

    def __init__(self, config: Config):
        super().__init__(config)
        self.sqrt = False

    def get_gradients(self, score, label, weight):
        diff = score - label
        grad = jnp.sign(diff)
        hess = jnp.ones_like(score)
        return self._apply_weight(grad, hess, weight)

    def boost_from_score(self, class_id: int = 0) -> float:
        return _weighted_percentile(self.label, self.weight, 0.5)

    def renew_tree_output(self, leaf_id, score, num_leaves):
        """Per-leaf weighted median of residuals (ref: hpp:243-287)."""
        out = np.zeros(num_leaves)
        resid = self.label - score
        for leaf in range(num_leaves):
            m = leaf_id == leaf
            if m.any():
                w = None if self.weight is None else self.weight[m]
                out[leaf] = _weighted_percentile(resid[m], w, 0.5)
        return out


class RegressionHuber(RegressionL2):
    """ref: regression_objective.hpp:292 RegressionHuberLoss."""
    name = "huber"
    is_constant_hessian = True

    def __init__(self, config: Config):
        super().__init__(config)
        self.sqrt = False
        self.alpha = config.alpha

    def get_gradients(self, score, label, weight):
        diff = score - label
        grad = jnp.where(jnp.abs(diff) <= self.alpha, diff,
                         jnp.sign(diff) * self.alpha)
        hess = jnp.ones_like(score)
        return self._apply_weight(grad, hess, weight)


class RegressionFair(RegressionL2):
    """ref: regression_objective.hpp:350 RegressionFairLoss."""
    name = "fair"

    def __init__(self, config: Config):
        super().__init__(config)
        self.sqrt = False
        self.c = config.fair_c

    def get_gradients(self, score, label, weight):
        x = score - label
        c = self.c
        grad = c * x / (jnp.abs(x) + c)
        hess = c * c / (jnp.abs(x) + c) ** 2
        return self._apply_weight(grad, hess, weight)


class RegressionPoisson(RegressionL2):
    """ref: regression_objective.hpp:397 RegressionPoissonLoss
    (score is log-rate; grad = exp(f) - y, hess = exp(f) * exp(max_delta_step))."""
    name = "poisson"

    def __init__(self, config: Config):
        super().__init__(config)
        self.sqrt = False
        self.max_delta_step = config.poisson_max_delta_step

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if (self.label < 0).any():
            log.fatal("[poisson]: at least one target label is negative")

    def get_gradients(self, score, label, weight):
        exp_score = jnp.exp(score)
        grad = exp_score - label
        hess = exp_score * float(np.exp(self.max_delta_step))
        return self._apply_weight(grad, hess, weight)

    def convert_output(self, score):
        return jnp.exp(score)

    def convert_output_host(self, score):
        return np.exp(score)

    def boost_from_score(self, class_id: int = 0) -> float:
        return float(np.log(max(super().boost_from_score(), 1e-20)))


class RegressionQuantile(RegressionL2):
    """ref: regression_objective.hpp:480 RegressionQuantileloss."""
    name = "quantile"
    need_renew_tree_output = True
    is_constant_hessian = True

    def __init__(self, config: Config):
        super().__init__(config)
        self.sqrt = False
        self.alpha = config.alpha

    def get_gradients(self, score, label, weight):
        delta = score - label
        grad = jnp.where(delta >= 0, 1.0 - self.alpha, -self.alpha)
        hess = jnp.ones_like(score)
        return self._apply_weight(grad, hess, weight)

    def boost_from_score(self, class_id: int = 0) -> float:
        return _weighted_percentile(self.label, self.weight, self.alpha)

    def renew_tree_output(self, leaf_id, score, num_leaves):
        out = np.zeros(num_leaves)
        resid = self.label - score
        for leaf in range(num_leaves):
            m = leaf_id == leaf
            if m.any():
                w = None if self.weight is None else self.weight[m]
                out[leaf] = _weighted_percentile(resid[m], w, self.alpha)
        return out


class RegressionMAPE(RegressionL1):
    """ref: regression_objective.hpp:578 RegressionMAPELOSS."""
    name = "mape"
    need_renew_tree_output = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.label_weight = (1.0 / np.maximum(1.0, np.abs(self.label))
                             ).astype(np.float32)
        if self.weight is not None:
            self.label_weight = self.label_weight * self.weight

    def get_gradients(self, score, label, weight):
        lw = 1.0 / jnp.maximum(1.0, jnp.abs(label))
        if weight is not None:
            lw = lw * weight
        diff = score - label
        grad = jnp.sign(diff) * lw
        hess = jnp.ones_like(score) if weight is None else weight
        return grad, hess

    def boost_from_score(self, class_id: int = 0) -> float:
        return _weighted_percentile(self.label, self.label_weight, 0.5)

    def renew_tree_output(self, leaf_id, score, num_leaves):
        out = np.zeros(num_leaves)
        resid = self.label - score
        for leaf in range(num_leaves):
            m = leaf_id == leaf
            if m.any():
                out[leaf] = _weighted_percentile(resid[m], self.label_weight[m], 0.5)
        return out


class RegressionGamma(RegressionPoisson):
    """ref: regression_objective.hpp:679 RegressionGammaLoss."""
    name = "gamma"

    def get_gradients(self, score, label, weight):
        exp_neg = jnp.exp(-score)
        grad = 1.0 - label * exp_neg
        hess = label * exp_neg
        return self._apply_weight(grad, hess, weight)


class RegressionTweedie(RegressionPoisson):
    """ref: regression_objective.hpp:717 RegressionTweedieLoss."""
    name = "tweedie"

    def __init__(self, config: Config):
        super().__init__(config)
        self.rho = config.tweedie_variance_power

    def get_gradients(self, score, label, weight):
        rho = self.rho
        e1 = jnp.exp((1.0 - rho) * score)
        e2 = jnp.exp((2.0 - rho) * score)
        grad = -label * e1 + e2
        hess = -label * (1.0 - rho) * e1 + (2.0 - rho) * e2
        return self._apply_weight(grad, hess, weight)


# ---------------------------------------------------------------------- binary
class BinaryLogloss(ObjectiveFunction):
    """ref: binary_objective.hpp:20 BinaryLogloss."""
    name = "binary"

    def __init__(self, config: Config, is_pos=None):
        super().__init__(config)
        self.sigmoid = config.sigmoid
        self.is_unbalance = config.is_unbalance
        self.scale_pos_weight = config.scale_pos_weight
        self.is_pos = is_pos or (lambda label: label > 0)
        if self.sigmoid <= 0:
            log.fatal(f"Sigmoid parameter {self.sigmoid} should be greater than zero")

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        pos = self.is_pos(self.label)
        cnt_pos, cnt_neg = int(pos.sum()), int((~pos).sum())
        w_pos, w_neg = 1.0, 1.0
        if self.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                w_neg = cnt_pos / cnt_neg
            else:
                w_pos = cnt_neg / cnt_pos
        w_pos *= self.scale_pos_weight
        self.w_pos, self.w_neg = w_pos, w_neg
        self.cnt_pos, self.cnt_neg = cnt_pos, cnt_neg
        self.need_train = not (cnt_neg == 0 or cnt_pos == 0)
        if not self.need_train:
            log.warning("Contains only one class")

    def get_gradients(self, score, label, weight):
        pos = self.is_pos(label)  # predicate is jnp-compatible
        lv = jnp.where(pos, 1.0, -1.0)
        lw = jnp.where(pos, self.w_pos, self.w_neg)
        response = -lv * self.sigmoid / (1.0 + jnp.exp(lv * self.sigmoid * score))
        abs_resp = jnp.abs(response)
        grad = response * lw
        hess = abs_resp * (self.sigmoid - abs_resp) * lw
        return self._apply_weight(grad, hess, weight)

    def convert_output(self, score):
        return 1.0 / (1.0 + jnp.exp(-self.sigmoid * score))

    def convert_output_host(self, score):
        return 1.0 / (1.0 + np.exp(-self.sigmoid * score))

    def boost_from_score(self, class_id: int = 0) -> float:
        """ref: binary_objective.hpp:139-160."""
        if self.weight is not None:
            suml = float(np.sum(self.is_pos(self.label) * self.weight))
            sumw = float(np.sum(self.weight))
        else:
            suml = float(self.cnt_pos)
            sumw = float(self.num_data)
        pavg = min(max(suml / max(sumw, 1e-300), 1e-10), 1.0 - 1e-10)
        init = float(np.log(pavg / (1.0 - pavg)) / self.sigmoid)
        log.info(f"[{self.name}:BoostFromScore]: pavg={pavg:.6f} -> initscore={init:.6f}")
        return init


# ------------------------------------------------------------------ multiclass
class MulticlassSoftmax(ObjectiveFunction):
    """ref: multiclass_objective.hpp:20 MulticlassSoftmax."""
    name = "multiclass"

    def __init__(self, config: Config):
        super().__init__(config)
        self.num_class = config.num_class
        self.num_model_per_iteration_ = config.num_class

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        li = self.label.astype(np.int32)
        if (li < 0).any() or (li >= self.num_class).any():
            log.fatal(f"Label must be in [0, {self.num_class})")
        self.label_int = li
        probs = np.zeros(self.num_class)
        w = self.weight if self.weight is not None else np.ones(num_data)
        np.add.at(probs, li, w)
        self.class_init_probs = probs / w.sum()
        self.factor = self.num_class / (self.num_class - 1.0)

    def get_gradients(self, score, label, weight):
        """score: [K, n]; returns grad/hess [K, n]."""
        p = jax.nn.softmax(score, axis=0)
        onehot = (label.astype(jnp.int32)[None, :]
                  == jnp.arange(self.num_class)[:, None])
        grad = p - onehot.astype(p.dtype)
        hess = self.factor * p * (1.0 - p)
        if weight is not None:
            grad = grad * weight[None, :]
            hess = hess * weight[None, :]
        return grad, hess

    def convert_output(self, score):
        return jax.nn.softmax(score, axis=0)

    def convert_output_host(self, score):
        e = np.exp(score - np.max(score, axis=0, keepdims=True))
        return e / np.sum(e, axis=0, keepdims=True)

    def boost_from_score(self, class_id: int = 0) -> float:
        p = self.class_init_probs[class_id]
        return float(np.log(p)) if p > 0 else -np.inf


class MulticlassOVA(ObjectiveFunction):
    """ref: multiclass_objective.hpp:130 MulticlassOVA (per-class binary)."""
    name = "multiclassova"

    def __init__(self, config: Config):
        super().__init__(config)
        self.num_class = config.num_class
        self.num_model_per_iteration_ = config.num_class
        self.binary: list[BinaryLogloss] = []
        for k in range(config.num_class):
            self.binary.append(BinaryLogloss(
                config, is_pos=(lambda label, kk=k: label.astype(np.int32) == kk)))

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        for b in self.binary:
            b.init(metadata, num_data)

    def get_gradients(self, score, label, weight):
        grads, hesses = [], []
        for k in range(self.num_class):
            g, h = self.binary[k].get_gradients(score[k], label, weight)
            grads.append(g)
            hesses.append(h)
        return jnp.stack(grads), jnp.stack(hesses)

    def convert_output(self, score):
        return 1.0 / (1.0 + jnp.exp(-self.binary[0].sigmoid * score))

    def convert_output_host(self, score):
        return 1.0 / (1.0 + np.exp(-self.binary[0].sigmoid * score))

    def boost_from_score(self, class_id: int = 0) -> float:
        return self.binary[class_id].boost_from_score()


# --------------------------------------------------------------- cross-entropy
class CrossEntropy(ObjectiveFunction):
    """Label in [0,1] (ref: xentropy_objective.hpp:29 CrossEntropy)."""
    name = "cross_entropy"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if (self.label < 0).any() or (self.label > 1).any():
            log.fatal("[cross_entropy]: label must be in [0, 1]")

    def get_gradients(self, score, label, weight):
        p = 1.0 / (1.0 + jnp.exp(-score))
        if weight is None:
            return p - label, p * (1.0 - p)
        return (p - label) * weight, p * (1.0 - p) * weight

    def convert_output(self, score):
        return 1.0 / (1.0 + jnp.exp(-score))

    def convert_output_host(self, score):
        return 1.0 / (1.0 + np.exp(-score))

    def boost_from_score(self, class_id: int = 0) -> float:
        w = self.weight if self.weight is not None else np.ones_like(self.label)
        pavg = float(np.sum(self.label * w) / np.sum(w))
        pavg = min(max(pavg, 1e-10), 1.0 - 1e-10)
        return float(np.log(pavg / (1.0 - pavg)))


class CrossEntropyLambda(ObjectiveFunction):
    """ref: xentropy_objective.hpp:162 CrossEntropyLambda (weights enter via
    log1p link)."""
    name = "cross_entropy_lambda"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if (self.label < 0).any() or (self.label > 1).any():
            log.fatal("[cross_entropy_lambda]: label must be in [0, 1]")

    def get_gradients(self, score, label, weight):
        if weight is None:
            z = 1.0 / (1.0 + jnp.exp(-score))
            return z - label, z * (1.0 - z)
        # weighted case (ref: xentropy_objective.hpp:234-250)
        w, y = weight, label
        epf = jnp.exp(score)
        hhat = jnp.log1p(epf)
        z = 1.0 - jnp.exp(-w * hhat)
        enf = 1.0 / epf
        grad = (1.0 - y / z) * w / (1.0 + enf)
        c = 1.0 / (1.0 - z)
        d = 1.0 + epf
        a = w * epf / (d * d)
        d2 = c - 1.0
        b = (c / (d2 * d2)) * (1.0 + w * epf - c)
        hess = a * (1.0 + y * b)
        return grad, hess

    def convert_output(self, score):
        return jnp.log1p(jnp.exp(score))

    def convert_output_host(self, score):
        return np.log1p(np.exp(score))

    def boost_from_score(self, class_id: int = 0) -> float:
        w = self.weight if self.weight is not None else np.ones_like(self.label)
        pavg = float(np.sum(self.label * w) / np.sum(w))
        pavg = min(max(pavg, 1e-10), 1.0 - 1e-10)
        return float(np.log(pavg / (1.0 - pavg)))


# --------------------------------------------------------------------- factory
_REGISTRY = {
    "regression": RegressionL2,
    "regression_l1": RegressionL1,
    "huber": RegressionHuber,
    "fair": RegressionFair,
    "poisson": RegressionPoisson,
    "quantile": RegressionQuantile,
    "mape": RegressionMAPE,
    "gamma": RegressionGamma,
    "tweedie": RegressionTweedie,
    "binary": BinaryLogloss,
    "multiclass": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "cross_entropy": CrossEntropy,
    "cross_entropy_lambda": CrossEntropyLambda,
}


def create_objective(config: Config) -> Optional[ObjectiveFunction]:
    """ref: src/objective/objective_function.cpp:20 CreateObjectiveFunction."""
    name = config.objective
    if name in ("custom", "", "none"):
        return None
    if name in ("lambdarank", "rank_xendcg"):
        from .ranking import create_ranking_objective
        return create_ranking_objective(name, config)
    if name not in _REGISTRY:
        log.fatal(f"Unknown objective type name: {name}")
    return _REGISTRY[name](config)
