"""Observability subsystem: metrics registry, structured JSONL event
log, hot-path tracing hooks, training watchdogs, compiled-cost roofline
accounting, the always-on flight recorder, and Prometheus exposition
(docs/Observability.md).

The reference engine's TIMETAG timers print an aggregate table at exit;
production-scale training additionally needs machine-readable per-
iteration telemetry (phase timings, eval results, tree stats, checkpoint
and fault events) that bench.py and the distributed supervisor can
consume, plus watchdogs for the failure modes unique to the XLA runtime
(mid-training recompiles, HBM growth).  The performance-observatory
layer (ISSUE 11) adds WHAT THE CHIP DID to when it did it: compiled-HLO
flop/byte accounting per jitted entry (costmodel.py), a bounded ring of
recent iteration/serving history dumpable from dying processes
(flightrec.py), and a `/metrics` scrape surface (prom.py).

Knobs:
  * `train(metrics_dir=...)` / CLI `metrics_dir=` — JSONL event log
  * `profile_dir=` — brackets training with jax.profiler start/stop_trace
  * `roofline=` — compiled-cost harvesting + per-phase measured MFU
  * `metrics_port=` — Prometheus `GET /metrics` listener
  * `LIGHTGBM_TPU_TIMETAG=1` — host phase timers (utils/timer.py)
  * `LIGHTGBM_TPU_TRACE=1` — jax.profiler.TraceAnnotation per scope
"""

from .compile_cache import configure_compile_cache
from .costmodel import (backend_peaks, enable_cost_model,
                        global_cost_model, roofline)
from .events import (EventLogger, emit_event, get_event_logger,
                     set_event_logger)
from .flightrec import (FlightRecorder, dump_flight_record,
                        flight_file_path, flight_recorder)
from .hostio import (AsyncWriter, clear_preemption_hook, flush_host_io,
                     install_sigterm_flush, set_preemption_hook)
from .prom import (parse_prometheus_text, render_prometheus,
                   start_metrics_http)
from .registry import MetricsRegistry, global_registry, process_rank
from .tracing import (SloTracker, SpanAssembler, TraceContext, make_span,
                      new_span_id, new_trace_id)
from .watchdog import (RecompileDetector, sample_device_memory,
                       update_memory_gauges)

__all__ = [
    "AsyncWriter", "configure_compile_cache",
    "backend_peaks", "enable_cost_model", "global_cost_model", "roofline",
    "EventLogger", "emit_event", "get_event_logger", "set_event_logger",
    "FlightRecorder", "dump_flight_record", "flight_file_path",
    "flight_recorder",
    "flush_host_io", "install_sigterm_flush",
    "set_preemption_hook", "clear_preemption_hook",
    "MetricsRegistry", "global_registry", "process_rank",
    "parse_prometheus_text", "render_prometheus", "start_metrics_http",
    "SloTracker", "SpanAssembler", "TraceContext", "make_span",
    "new_span_id", "new_trace_id",
    "RecompileDetector", "sample_device_memory", "update_memory_gauges",
]
