"""Observability subsystem: metrics registry, structured JSONL event
log, hot-path tracing hooks and training watchdogs
(docs/Observability.md).

The reference engine's TIMETAG timers print an aggregate table at exit;
production-scale training additionally needs machine-readable per-
iteration telemetry (phase timings, eval results, tree stats, checkpoint
and fault events) that bench.py and the distributed supervisor can
consume, plus watchdogs for the failure modes unique to the XLA runtime
(mid-training recompiles, HBM growth).

Knobs:
  * `train(metrics_dir=...)` / CLI `metrics_dir=` — JSONL event log
  * `profile_dir=` — brackets training with jax.profiler start/stop_trace
  * `LIGHTGBM_TPU_TIMETAG=1` — host phase timers (utils/timer.py)
  * `LIGHTGBM_TPU_TRACE=1` — jax.profiler.TraceAnnotation per scope
"""

from .compile_cache import configure_compile_cache
from .events import (EventLogger, emit_event, get_event_logger,
                     set_event_logger)
from .hostio import (AsyncWriter, clear_preemption_hook, flush_host_io,
                     install_sigterm_flush, set_preemption_hook)
from .registry import MetricsRegistry, global_registry, process_rank
from .watchdog import (RecompileDetector, sample_device_memory,
                       update_memory_gauges)

__all__ = [
    "AsyncWriter", "configure_compile_cache",
    "EventLogger", "emit_event", "get_event_logger", "set_event_logger",
    "flush_host_io", "install_sigterm_flush",
    "set_preemption_hook", "clear_preemption_hook",
    "MetricsRegistry", "global_registry", "process_rank",
    "RecompileDetector", "sample_device_memory", "update_memory_gauges",
]
