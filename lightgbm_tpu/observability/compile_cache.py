"""Persistent XLA compilation cache wiring (docs/Performance.md).

Every fresh process pays the full trace+compile cost of the jitted tree
program before its first iteration — ~60 s for the 255-leaf wave ladder
at bench scale (PERF_NOTES: "setup gap is compile ... a persistent jax
compilation cache would remove it for repeat runs").  The
`compile_cache_dir` parameter turns on JAX's persistent compilation
cache so a repeat run with the same configuration deserializes the
compiled executables instead of recompiling.

Hit/miss visibility: JAX reports cache activity through
`jax.monitoring`; a process-wide listener forwards the events into the
metrics registry as `compile_cache_hits` / `compile_cache_misses`, so
they appear in the per-iteration JSONL events and a second run of the
same config can assert hits > 0 (tests/test_async_io.py).

Only programs whose compile takes >= 1 s are persisted (the ladder
compile is the multi-second cost being amortized); the micro-jits
around it recompile cheaply each process.
"""

from __future__ import annotations

import os
from typing import Optional

from ..utils import log
from .registry import global_registry

_EVENT_COUNTERS = {
    "/jax/compilation_cache/cache_hits": "compile_cache_hits",
    "/jax/compilation_cache/cache_misses": "compile_cache_misses",
}

_configured_dir: Optional[str] = None
_listener_installed = False


def _on_monitoring_event(event: str, **_kwargs) -> None:
    name = _EVENT_COUNTERS.get(event)
    if name is not None:
        global_registry.inc(name)


def configure_compile_cache(cache_dir: str) -> bool:
    """Point JAX's persistent compilation cache at `cache_dir` (created
    if missing) and install the hit/miss counter listener.  Idempotent;
    returns False (with a warning) when the runtime refuses — a cache
    problem must never block training."""
    global _configured_dir, _listener_installed
    cache_dir = os.fspath(cache_dir)
    if _configured_dir == cache_dir:
        return True
    try:
        import jax
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # Keep a >=1 s compile-time gate: the target is the multi-second
        # ladder compile, and persisting the dozens of micro-jits around
        # it buys nothing — and deserializing many tiny executables
        # triggers a flaky interpreter-shutdown segfault in this
        # jaxlib's CPU client (reproduced at gate 0.0, absent at 1.0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        if not _listener_installed:
            jax.monitoring.register_event_listener(_on_monitoring_event)
            _listener_installed = True
        _configured_dir = cache_dir
        log.debug(f"Persistent compilation cache enabled at {cache_dir}")
        return True
    except Exception as e:  # noqa: BLE001 - best effort, never fatal
        log.warning(f"Could not enable the persistent compilation cache "
                    f"at {cache_dir}: {e}")
        return False
