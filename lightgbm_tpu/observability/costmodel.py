"""Compiled-HLO cost accounting: measured per-phase MFU and roofline
classification (docs/Observability.md).

The MFU number the ROADMAP tracks (`b10m_useful_mac_mfu = 7e-05`) was a
single hand-derived analytic estimate in tools/bench_10m.py — a MAC
guess divided by wall clock divided by a hardcoded peak.  It says the
chip is idle but not WHERE, so the Pallas-histogram work has nothing to
aim at.  This module asks the compiler instead: every hot jitted entry
point is already wrapped in a `RecompileDetector` (grow/grow-wave,
donated or not; the gradient program; DeviceEval's packed tick; every
bucket of the inference ladder), and XLA's lowered module carries its
own cost analysis — `fn.lower(...).cost_analysis()` returns the
program's flops and bytes_accessed without compiling anything
(jax.stages.Lowered; ~4 ms once per signature, then cached here).  The
detector reports each call into the `CostModel`, keyed by the SAME
(shape, dtype, static) signature the recompile watchdog fingerprints,
so the accounting can never disagree with the watchdog about which
executable ran.

Combined with the per-phase `::device` times (`Timer.block` credits the
settle wait to `<scope>::device`) and a per-backend peak table, the
per-iteration event and the serving stats gain measured MFU, arithmetic
intensity (flops/byte), and a roofline classification: an entry whose
intensity sits below the ridge point (peak_flops / peak_bytes_per_s) is
HBM-bound — more MXU utilization is physically impossible without
cutting bytes — while one above it is compute-bound and worth a kernel.
This is the measurement foundation the Pallas-histogram ROADMAP item
optimizes against.

Zero steady-state cost when disabled (one attribute check per wrapped
call); when enabled, a dict add behind a lock per call — the same
budget as the metrics registry.  `engine.train` enables it for metrics
runs and the serving daemon for its lifetime (param `roofline`).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional, Tuple

from ..utils import log

# Per-backend (peak_flops_per_s, peak_hbm_bytes_per_s).  The TPU row is
# the v5e the BENCH trajectory anchors on (197 TFLOP/s bf16 MXU,
# 819 GB/s HBM); cpu/gpu rows are nominal single-device figures so the
# roofline CLASSIFICATION still works off-chip (the absolute MFU there
# is not a number anyone tunes against).  Override with
# LGBM_TPU_PEAK_FLOPS / LGBM_TPU_PEAK_BYTES_PER_S for other parts.
PEAK_TABLE: Dict[str, Tuple[float, float]] = {
    "tpu": (197e12, 819e9),
    "gpu": (312e12, 2.0e12),
    "cpu": (1e11, 2e10),
}


def backend_peaks(backend: Optional[str] = None) -> Tuple[float, float]:
    """(peak_flops_per_s, peak_bytes_per_s) for `backend` (default: the
    active jax backend; "cpu" row when jax is not initialized)."""
    if backend is None:
        try:
            import jax
            backend = jax.default_backend()
        except Exception:  # noqa: BLE001 - peaks must never raise
            backend = "cpu"
    flops, bw = PEAK_TABLE.get(str(backend), PEAK_TABLE["cpu"])
    env_f = os.environ.get("LGBM_TPU_PEAK_FLOPS")
    env_b = os.environ.get("LGBM_TPU_PEAK_BYTES_PER_S")
    try:
        if env_f:
            flops = float(env_f)
        if env_b:
            bw = float(env_b)
    except ValueError:
        log.warning("Ignoring malformed LGBM_TPU_PEAK_FLOPS / "
                    "LGBM_TPU_PEAK_BYTES_PER_S override")
    return flops, bw


def _extract_cost(analysis) -> Optional[Tuple[float, float]]:
    """(flops, bytes_accessed) out of a cost_analysis() result, which is
    a dict on this jax (0.4.x) and a single-element list of dicts on
    some other versions; None when the module reports neither."""
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else {}
    if not isinstance(analysis, dict):
        return None
    flops = float(analysis.get("flops", 0.0) or 0.0)
    bytes_accessed = float(analysis.get("bytes accessed", 0.0) or 0.0)
    if flops <= 0.0 and bytes_accessed <= 0.0:
        return None
    return flops, bytes_accessed


def group_of(name: str) -> str:
    """Accounting group of a RecompileDetector name: the bucket-ladder
    entries (`device_predict[convert@4096]`) fold into one
    `device_predict` group; everything else groups by its own name."""
    return name.split("[", 1)[0]


# detector-name group -> the host timer scope whose ::device split times
# that group's dispatches (docs/Observability.md Timer scopes)
GROUP_PHASES: Dict[str, str] = {
    "grow_tree": "GBDT::grow_tree",
    "gradients": "GBDT::gradients",
    "device_eval": "GBDT::eval",
    "device_predict": "DevicePredictor::dispatch",
}


def roofline(flops: float, bytes_accessed: float, seconds: float,
             backend: Optional[str] = None) -> Dict[str, Any]:
    """Measured utilization + roofline classification for `flops` /
    `bytes_accessed` of work that took `seconds` of device time."""
    peak_flops, peak_bw = backend_peaks(backend)
    out: Dict[str, Any] = {
        "flops": flops, "bytes": bytes_accessed,
        "peak_flops_per_s": peak_flops, "peak_bytes_per_s": peak_bw,
    }
    ridge = peak_flops / max(peak_bw, 1.0)
    ai = flops / bytes_accessed if bytes_accessed > 0 else None
    out["arithmetic_intensity"] = ai
    out["ridge_intensity"] = ridge
    # which roof binds this program: below the ridge the memory system
    # caps achievable flops/s no matter how good the kernel is
    out["bound"] = ("unknown" if ai is None
                    else "compute" if ai >= ridge else "hbm")
    if seconds and seconds > 0:
        out["mfu"] = flops / seconds / peak_flops
        out["achieved_flops_per_s"] = flops / seconds
        out["achieved_bytes_per_s"] = bytes_accessed / seconds
        out["bw_util"] = bytes_accessed / seconds / peak_bw
    else:
        out["mfu"] = None
    return out


class CostModel:
    """Cumulative compiled-cost ledger over the wrapped jitted entries.

    `observe()` is called by RecompileDetector on every wrapped call
    (only when `enabled`): the first sighting of a (name, signature)
    harvests the lowered module's cost analysis, every call accumulates
    flops/bytes/calls into the entry's group.  `snapshot()` is the
    timer-snapshot analogue — per-iteration deltas come from diffing two
    snapshots (observability/callback record_metrics)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.enabled = False
        # (name, sig) -> (flops, bytes) per call, or None when the entry
        # could not be harvested (no .lower, cost analysis unavailable)
        self._per_sig: Dict[Tuple[str, Any], Optional[Tuple[float, float]]] \
            = {}
        # name -> newest harvested (flops, bytes): O(1) lookup for call
        # sites that account their own dispatches (DevicePredictor)
        self._latest: Dict[str, Tuple[float, float]] = {}
        # group -> [flops, bytes, calls, unharvested_calls]
        self._totals: Dict[str, list] = {}

    # ------------------------------------------------------------- harvest
    def _harvest(self, fn, name: str, args, kwargs
                 ) -> Optional[Tuple[float, float]]:
        lower = getattr(fn, "lower", None)
        if lower is None:
            return None
        try:
            cost = _extract_cost(lower(*args, **kwargs).cost_analysis())
        except Exception as e:  # noqa: BLE001 - accounting must never kill the dispatch
            log.debug(f"cost_analysis harvest failed for {name}: {e}")
            return None
        if cost is not None:
            log.debug(f"cost model: {name} -> {cost[0]:.3e} flops, "
                      f"{cost[1]:.3e} bytes per call")
        return cost

    def observe(self, name: str, sig, fn, args, kwargs) -> None:
        """One call of a wrapped jitted entry with signature `sig`."""
        key = (name, sig)
        with self._lock:
            known = key in self._per_sig
            cost = self._per_sig.get(key)
        if not known:
            # harvest OUTSIDE the lock: lower() re-enters jax, and a
            # concurrent duplicate harvest is idempotent
            cost = self._harvest(fn, name, args, kwargs)
            with self._lock:
                self._per_sig[key] = cost
                if cost is not None:
                    self._latest[name] = cost
        group = group_of(name)
        with self._lock:
            tot = self._totals.setdefault(group, [0.0, 0.0, 0, 0])
            tot[2] += 1
            if cost is not None:
                tot[0] += cost[0]
                tot[1] += cost[1]
            else:
                tot[3] += 1

    # ------------------------------------------------------------- readout
    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """Point-in-time cumulative totals {group: {flops, bytes, calls,
        unharvested}} — per-iteration roofline deltas diff two of these,
        exactly like Timer.snapshot."""
        with self._lock:
            return {g: {"flops": t[0], "bytes": t[1], "calls": t[2],
                        "unharvested": t[3]}
                    for g, t in self._totals.items()}

    def per_call(self, name: str) -> Optional[Tuple[float, float]]:
        """Harvested (flops, bytes) per call of `name`'s newest
        signature, or None.  O(1): dispatch-site accounting
        (DevicePredictor._run) reads this per serving dispatch."""
        with self._lock:
            return self._latest.get(name)

    def signatures_harvested(self) -> int:
        with self._lock:
            return sum(1 for c in self._per_sig.values() if c is not None)

    def reset(self) -> None:
        with self._lock:
            self._per_sig.clear()
            self._latest.clear()
            self._totals.clear()

    # ---------------------------------------------------------- aggregates
    def phase_roofline(self, prev: Dict[str, Dict[str, float]],
                       cur: Dict[str, Dict[str, float]],
                       phases: Dict[str, float],
                       backend: Optional[str] = None
                       ) -> Dict[str, Dict[str, Any]]:
        """Per-group roofline over one window: `prev`/`cur` are
        snapshot() results bracketing it, `phases` the timer's seconds
        deltas for the same window.  Device time prefers the
        `<scope>::device` split (pure settle wait) and falls back to the
        host scope total (which DeviceEval's synchronous fetch makes a
        fair device proxy)."""
        out: Dict[str, Dict[str, Any]] = {}
        for group, tot in cur.items():
            was = prev.get(group, {"flops": 0.0, "bytes": 0.0, "calls": 0})
            calls = int(tot["calls"] - was["calls"])
            if calls <= 0:
                continue
            flops = tot["flops"] - was["flops"]
            bytes_accessed = tot["bytes"] - was["bytes"]
            scope = GROUP_PHASES.get(group)
            dev_s = None
            if scope is not None:
                dev_s = phases.get(scope + "::device",
                                   phases.get(scope))
            entry = roofline(flops, bytes_accessed, dev_s or 0.0,
                             backend=backend)
            entry["calls"] = calls
            entry["device_s"] = dev_s
            # trim the verbose constants out of the per-iteration event
            # (they are invariant per backend; docs carry the table)
            for k in ("peak_flops_per_s", "peak_bytes_per_s",
                      "achieved_flops_per_s", "achieved_bytes_per_s"):
                entry.pop(k, None)
            out[group] = entry
        return out


# the process-wide ledger every RecompileDetector reports into
global_cost_model = CostModel()


def enable_cost_model(on: bool = True) -> bool:
    """Flip the process-wide cost model; returns the PREVIOUS state so
    scoped enablers (engine.train) can restore it."""
    prev = global_cost_model.enabled
    global_cost_model.enabled = bool(on)
    return prev
