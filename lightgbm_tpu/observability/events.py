"""Structured JSONL event log of a training run.

One `EventLogger` per process writes append-only JSON lines to
`<metrics_dir>/events-rank<r>.jsonl` (rank-tagged so multi-process SPMD
runs produce one file per rank with no write contention).  Every record
carries `event`, `ts` (unix seconds) and `rank`; the `iteration` event —
one per boosting round, emitted by the `record_metrics` callback — adds
the phase-timing breakdown, eval results, tree shape stats and the
cumulative counter/gauge snapshot (schema: docs/Observability.md).

A module-level "current logger" lets deep layers (checkpoint writes,
fault injection, the recompile watchdog) emit events without threading a
logger handle through every call: `engine.train` installs its logger for
the duration of the run and `emit_event(...)` is a no-op outside one.
Writes are flushed per event so a crashed run's log is complete up to
the failure.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

import numpy as np

from .registry import process_rank


def _json_default(o):
    if isinstance(o, np.integer):
        return int(o)
    if isinstance(o, (np.floating, np.float32, np.float64)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    return str(o)


class EventLogger:
    """Append-only JSONL writer for one process of one run."""

    def __init__(self, directory: str, rank=None):
        self.dir = os.fspath(directory)
        os.makedirs(self.dir, exist_ok=True)
        self.rank = process_rank() if rank is None else rank
        self.path = os.path.join(self.dir, f"events-rank{self.rank}.jsonl")
        self._fh = open(self.path, "a")

    def emit(self, event: str, **fields) -> None:
        rec = {"event": event, "ts": time.time(), "rank": self.rank}
        rec.update(fields)
        self._fh.write(json.dumps(rec, default=_json_default) + "\n")
        self._fh.flush()

    def close(self) -> None:
        try:
            self._fh.close()
        except OSError:
            pass


_current: Optional[EventLogger] = None


def set_event_logger(logger: Optional[EventLogger]) -> None:
    """Install (or clear, with None) the run-scoped event logger that
    `emit_event` routes to."""
    global _current
    _current = logger


def get_event_logger() -> Optional[EventLogger]:
    return _current


def emit_event(event: str, **fields) -> None:
    """Emit through the current run's logger; silently a no-op when no
    run is recording (so instrumented subsystems cost nothing outside
    metrics runs)."""
    if _current is not None:
        try:
            _current.emit(event, **fields)
        except (OSError, ValueError):
            pass  # a failed telemetry write must never kill training
