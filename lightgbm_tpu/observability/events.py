"""Structured JSONL event log of a training run.

One `EventLogger` per process writes append-only JSON lines to
`<metrics_dir>/events-rank<r>.jsonl` (rank-tagged so multi-process SPMD
runs produce one file per rank with no write contention).  Every record
carries `event`, `ts` (unix seconds) and `rank`; the `iteration` event —
one per boosting round, emitted by the `record_metrics` callback — adds
the phase-timing breakdown, eval results, tree shape stats and the
cumulative counter/gauge snapshot (schema: docs/Observability.md).

A module-level "current logger" lets deep layers (checkpoint writes,
fault injection, the recompile watchdog) emit events without threading a
logger handle through every call: `engine.train` installs its logger for
the duration of the run and `emit_event(...)` is a no-op outside one.
Writes are flushed per event so a crashed run's log is complete up to
the failure.

Multi-day runs: `rotate_mb` (param `metrics_rotate_mb`, 0 = off) caps
the live file's size — when an emit would push `events-rank<r>.jsonl`
past the cap, existing rollovers shift up (`.1` -> `.2`, ...), the live
file becomes `.1`, and a fresh live file is opened.  Newest events are
always in the unsuffixed file; history is unbounded by design (the
operator prunes old `.N` files, the logger never deletes data).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

import numpy as np

from .registry import process_rank


def _json_default(o):
    if isinstance(o, np.integer):
        return int(o)
    if isinstance(o, (np.floating, np.float32, np.float64)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    return str(o)


class EventLogger:
    """Append-only JSONL writer for one process of one run.

    With `writer` (an observability.hostio.AsyncWriter) the file append
    runs on the writer thread: emit() serializes the record on the
    calling thread (field values and `ts` are captured at emit time)
    and queues only the finished line, so async and sync runs produce
    byte-identical logs in the same order (single FIFO worker)."""

    def __init__(self, directory: str, rank=None, rotate_mb: float = 0,
                 writer=None):
        self.dir = os.fspath(directory)
        os.makedirs(self.dir, exist_ok=True)
        self.rank = process_rank() if rank is None else rank
        self.path = os.path.join(self.dir, f"events-rank{self.rank}.jsonl")
        self.rotate_bytes = int(float(rotate_mb) * (1 << 20))
        self.writer = writer
        # the stall watchdog embeds the run's last record in its
        # diagnosis — the "how far did we get" marker r05 never had
        self.last_record = None
        # serializes appends against rotation's handle swap: _append
        # runs on the writer thread in async mode but on the calling
        # thread in sync mode, and both coexist around train end
        self._io_lock = threading.RLock()
        self._fh = open(self.path, "a")

    def _rotate(self) -> None:
        """Shift events-rank<r>.jsonl -> .1 -> .2 -> ... and reopen."""
        with self._io_lock:
            self._fh.close()
            n = 1
            while os.path.exists(f"{self.path}.{n}"):
                n += 1
            for i in range(n, 1, -1):
                os.replace(f"{self.path}.{i - 1}", f"{self.path}.{i}")
            os.replace(self.path, f"{self.path}.1")
            self._fh = open(self.path, "a")

    def _record(self, event: str, fields) -> str:
        rec = {"event": event, "ts": time.time(), "rank": self.rank}
        rec.update(fields)
        self.last_record = rec
        return json.dumps(rec, default=_json_default) + "\n"

    def emit(self, event: str, **fields) -> None:
        line = self._record(event, fields)
        if self.writer is not None:
            self.writer.submit(self._append, line)
        else:
            self._append(line)

    def emit_sync(self, event: str, **fields) -> None:
        """Terminal-path emit for DYING processes: the SIGTERM handler
        and the stall watchdog's exit path must record their final
        event even when the AsyncWriter worker is wedged — queueing
        through `submit` would block forever on a full bounded queue
        (the hazard tpulint's signal-handler-safety rule flags).  The
        record is appended on THIS thread through a private O_APPEND
        handle: no queue, no shared-handle lock a hung worker could be
        holding; one JSONL line is a single buffered write, flushed on
        close, so it cannot interleave mid-record with the worker."""
        line = self._record(event, fields)
        try:
            with open(self.path, "a") as f:
                f.write(line)
        except OSError:
            pass  # a failed telemetry write must never block the exit

    def _append(self, line: str) -> None:
        with self._io_lock:
            if self.rotate_bytes > 0 and self._fh.tell() \
                    and self._fh.tell() + len(line) > self.rotate_bytes:
                try:
                    self._rotate()
                except OSError:
                    pass  # a failed rotation must never kill training
            self._fh.write(line)
            self._fh.flush()

    def flush(self, timeout: Optional[float] = None) -> None:
        """Land every queued record on disk (bounded wait in async mode:
        the SIGTERM handler calls this and must not wedge the exit).
        The handle flush deliberately takes NO lock: a wedged worker
        holding `_io_lock` must not deadlock the terminal flush, and a
        handle closed mid-rotation lands in the except below."""
        try:
            if self.writer is not None:
                self.writer.flush(timeout=timeout)
            # tpulint: disable-next=thread-shared-state -- lock-free on purpose (see docstring): a rotation-closed handle raises ValueError, which counts as flushed; taking _io_lock here could block the SIGTERM exit behind a hung worker
            self._fh.flush()
        except (OSError, ValueError):
            pass

    def close(self) -> None:
        try:
            if self.writer is not None:
                self.writer.flush()
            self._fh.close()
        except OSError:
            pass


_current: Optional[EventLogger] = None


def set_event_logger(logger: Optional[EventLogger]) -> None:
    """Install (or clear, with None) the run-scoped event logger that
    `emit_event` routes to."""
    global _current
    # tpulint: disable-next=thread-shared-state -- atomic pointer rebind: readers (incl. the SIGTERM handler) snapshot the reference once; a CPython name assignment cannot tear
    _current = logger


def get_event_logger() -> Optional[EventLogger]:
    return _current


def emit_event(event: str, **fields) -> None:
    """Emit through the current run's logger; silently a no-op when no
    run is recording (so instrumented subsystems cost nothing outside
    metrics runs)."""
    if _current is not None:
        try:
            _current.emit(event, **fields)
        except (OSError, ValueError):
            pass  # a failed telemetry write must never kill training


def emit_event_sync(event: str, **fields) -> None:
    """`emit_event` for a process on its way out: routes around the
    AsyncWriter queue and the shared file handle entirely (see
    EventLogger.emit_sync).  The SIGTERM flush and the stall watchdog's
    exit path call this — PR 7's "synchronously, never via the
    possibly-hung AsyncWriter" rule, now enforced by tpulint's
    signal-handler-safety analysis."""
    if _current is not None:
        try:
            _current.emit_sync(event, **fields)
        except (OSError, ValueError):
            pass
