"""Structured JSONL event log of a training run.

One `EventLogger` per process writes append-only JSON lines to
`<metrics_dir>/events-rank<r>.jsonl` (rank-tagged so multi-process SPMD
runs produce one file per rank with no write contention).  Every record
carries `event`, `ts` (unix seconds) and `rank`; the `iteration` event —
one per boosting round, emitted by the `record_metrics` callback — adds
the phase-timing breakdown, eval results, tree shape stats and the
cumulative counter/gauge snapshot (schema: docs/Observability.md).

A module-level "current logger" lets deep layers (checkpoint writes,
fault injection, the recompile watchdog) emit events without threading a
logger handle through every call: `engine.train` installs its logger for
the duration of the run and `emit_event(...)` is a no-op outside one.
Writes are flushed per event so a crashed run's log is complete up to
the failure.

Multi-day runs: `rotate_mb` (param `metrics_rotate_mb`, 0 = off) caps
the live file's size — when an emit would push `events-rank<r>.jsonl`
past the cap, existing rollovers shift up (`.1` -> `.2`, ...), the live
file becomes `.1`, and a fresh live file is opened.  Newest events are
always in the unsuffixed file; history is unbounded by design (the
operator prunes old `.N` files, the logger never deletes data).
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

import numpy as np

from .registry import process_rank


def _json_default(o):
    if isinstance(o, np.integer):
        return int(o)
    if isinstance(o, (np.floating, np.float32, np.float64)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    return str(o)


class EventLogger:
    """Append-only JSONL writer for one process of one run.

    With `writer` (an observability.hostio.AsyncWriter) the file append
    runs on the writer thread: emit() serializes the record on the
    calling thread (field values and `ts` are captured at emit time)
    and queues only the finished line, so async and sync runs produce
    byte-identical logs in the same order (single FIFO worker)."""

    def __init__(self, directory: str, rank=None, rotate_mb: float = 0,
                 writer=None):
        self.dir = os.fspath(directory)
        os.makedirs(self.dir, exist_ok=True)
        self.rank = process_rank() if rank is None else rank
        self.path = os.path.join(self.dir, f"events-rank{self.rank}.jsonl")
        self.rotate_bytes = int(float(rotate_mb) * (1 << 20))
        self.writer = writer
        # the stall watchdog embeds the run's last record in its
        # diagnosis — the "how far did we get" marker r05 never had
        self.last_record = None
        self._fh = open(self.path, "a")

    def _rotate(self) -> None:
        """Shift events-rank<r>.jsonl -> .1 -> .2 -> ... and reopen."""
        self._fh.close()
        n = 1
        while os.path.exists(f"{self.path}.{n}"):
            n += 1
        for i in range(n, 1, -1):
            os.replace(f"{self.path}.{i - 1}", f"{self.path}.{i}")
        os.replace(self.path, f"{self.path}.1")
        self._fh = open(self.path, "a")

    def emit(self, event: str, **fields) -> None:
        rec = {"event": event, "ts": time.time(), "rank": self.rank}
        rec.update(fields)
        self.last_record = rec
        line = json.dumps(rec, default=_json_default) + "\n"
        if self.writer is not None:
            self.writer.submit(self._append, line)
        else:
            self._append(line)

    def _append(self, line: str) -> None:
        if self.rotate_bytes > 0 and self._fh.tell() \
                and self._fh.tell() + len(line) > self.rotate_bytes:
            try:
                self._rotate()
            except OSError:
                pass  # a failed rotation must never kill training
        self._fh.write(line)
        self._fh.flush()

    def flush(self, timeout: Optional[float] = None) -> None:
        """Land every queued record on disk (bounded wait in async mode:
        the SIGTERM handler calls this and must not wedge the exit)."""
        try:
            if self.writer is not None:
                self.writer.flush(timeout=timeout)
            self._fh.flush()
        except (OSError, ValueError):
            pass

    def close(self) -> None:
        try:
            if self.writer is not None:
                self.writer.flush()
            self._fh.close()
        except OSError:
            pass


_current: Optional[EventLogger] = None


def set_event_logger(logger: Optional[EventLogger]) -> None:
    """Install (or clear, with None) the run-scoped event logger that
    `emit_event` routes to."""
    global _current
    _current = logger


def get_event_logger() -> Optional[EventLogger]:
    return _current


def emit_event(event: str, **fields) -> None:
    """Emit through the current run's logger; silently a no-op when no
    run is recording (so instrumented subsystems cost nothing outside
    metrics runs)."""
    if _current is not None:
        try:
            _current.emit(event, **fields)
        except (OSError, ValueError):
            pass  # a failed telemetry write must never kill training
