"""Always-on flight recorder: the last N iterations and serving traces,
in memory, dumpable on a dying or wedged process's last breath
(docs/Observability.md).

The stall watchdog's diagnosis (PR 7) answers "where is it stuck"; a
crash log's traceback answers "what raised".  Neither answers "what was
the run DOING just before" — the per-iteration JSONL log has that, but
it may be buffered behind a hung AsyncWriter, rotated away, or on a
disk the failing rank cannot reach.  So a bounded ring buffer keeps the
recent history IN PROCESS, always on (two deque appends per iteration
and per sampled request — no knob to forget):

* per-iteration records — iteration, wall time, per-phase ms (device
  split included), recompile/HBM gauges, rows/s;
* sampled per-request serving traces — trace id plus the
  enqueue -> coalesce -> dispatch -> device-settle -> respond stage
  timestamps (param `serve_trace_sample`: every Nth request); in the
  ROUTER process the same ring also holds `kind: "assembled_trace"`
  summaries of the cross-process span waterfalls
  (observability/tracing.py SpanAssembler) and replicas record
  `kind: "dispatch_error"` entries carrying the failed requests'
  trace ids, so a crash dump stays greppable by trace id;
* a coalesce-batch-size histogram (power-of-two buckets, requests and
  rows) — the shape of the batching the wait-knob trade actually buys.

`dump()` writes everything to `<dir>/flight-rank<r>.json` SYNCHRONOUSLY
via the atomic-write path — never through the AsyncWriter, per the PR-9
terminal-event rule: the dump runs from the stall watchdog's exit, the
crash path, and the SIGUSR2 handler, where the writer thread may be
exactly what is hung.  The read side is deliberately LOCK-FREE (a
snapshot of a deque plus retry): a signal handler interrupting the
thread that holds the recorder's lock must not deadlock on it.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ..utils import atomic_write_text, log

# power-of-two histogram: bucket k counts dispatches with batch size in
# [2^k, 2^(k+1)); the last bucket is open-ended
_HIST_BUCKETS = 17


def _bucket_of(n: int) -> int:
    return min(max(int(n), 1).bit_length() - 1, _HIST_BUCKETS - 1)


class FlightRecorder:
    """Bounded in-memory ring of recent telemetry (see module doc)."""

    def __init__(self, capacity: int = 256, trace_capacity: int = 256):
        self._iters: deque = deque(maxlen=max(int(capacity), 8))
        self._traces: deque = deque(maxlen=max(int(trace_capacity), 8))
        self._batch_req_hist = [0] * _HIST_BUCKETS
        self._batch_row_hist = [0] * _HIST_BUCKETS
        self._trace_seq = itertools.count()
        # guards appends only; every read path is lock-free on purpose
        # (signal handlers dump through here — see module docstring)
        self._lock = threading.Lock()

    def resize(self, capacity: int) -> None:
        """Re-bound the iteration ring (param `flight_recorder_size`);
        keeps the newest records."""
        capacity = max(int(capacity), 8)
        with self._lock:
            if self._iters.maxlen != capacity:
                self._iters = deque(self._iters, maxlen=capacity)

    # ------------------------------------------------------------- writers
    def record_iteration(self, **fields) -> None:
        rec = {"ts": time.time()}
        rec.update(fields)
        with self._lock:
            self._iters.append(rec)

    def next_trace_id(self) -> int:
        return next(self._trace_seq)

    def record_trace(self, **fields) -> None:
        rec = {"ts": time.time()}
        rec.update(fields)
        with self._lock:
            self._traces.append(rec)

    def record_batch(self, num_requests: int, num_rows: int) -> None:
        with self._lock:
            self._batch_req_hist[_bucket_of(num_requests)] += 1
            self._batch_row_hist[_bucket_of(num_rows)] += 1

    # ------------------------------------------------------------- readers
    @staticmethod
    def _tail_of(buf: deque, n: Optional[int]) -> List[Dict[str, Any]]:
        # lock-free: a deque snapshot can raise RuntimeError when an
        # append lands mid-iteration; retry a few times, then settle for
        # whatever copied — a partial tail beats a deadlocked handler
        for _ in range(4):
            try:
                items = list(buf)
                return items[-n:] if n else items
            except RuntimeError:
                continue
        return []

    def tail(self, n: int = 32) -> List[Dict[str, Any]]:
        """Newest `n` iteration records (lock-free, signal-safe)."""
        return self._tail_of(self._iters, n)

    def trace_tail(self, n: int = 32) -> List[Dict[str, Any]]:
        return self._tail_of(self._traces, n)

    def contents(self) -> Dict[str, Any]:
        """Everything the recorder holds, as one JSON-ready dict.
        Deliberately lock-free AND deliberately not named `snapshot`:
        it runs from signal handlers, where the locked snapshot idiom
        of the registry/timer classes would deadlock."""
        return {
            # tpulint: disable-next=thread-shared-state -- lock-free on purpose (signal-safe read; _tail_of retries a torn deque copy, and a partial tail is acceptable telemetry loss)
            "iterations": self._tail_of(self._iters, None),
            "serve_traces": self._tail_of(self._traces, None),
            # tpulint: disable-next=thread-shared-state -- lock-free on purpose (signal-safe read; a list copy racing one int increment reads a momentarily-stale bucket, never a torn structure)
            "coalesce_batch_requests_hist": list(self._batch_req_hist),
            # tpulint: disable-next=thread-shared-state -- lock-free on purpose (same racy-copy argument as the requests histogram above)
            "coalesce_batch_rows_hist": list(self._batch_row_hist),
            "hist_bucket_base": 2,
        }

    def reset(self) -> None:
        with self._lock:
            self._iters.clear()
            self._traces.clear()
            self._batch_req_hist = [0] * _HIST_BUCKETS
            self._batch_row_hist = [0] * _HIST_BUCKETS


# the process-wide recorder every subsystem writes into; always on —
# bounded memory, O(1) appends, no configuration needed to have had it
# running when something finally breaks
flight_recorder = FlightRecorder()


def flight_file_path(directory: str, rank: int) -> str:
    return os.path.join(os.fspath(directory), f"flight-rank{rank}.json")


def dump_flight_record(directory: str, rank: int,
                       reason: str = "on_demand") -> Optional[str]:
    """Write the flight recorder + a registry snapshot to
    `<directory>/flight-rank<rank>.json`, synchronously and atomically.
    Safe from signal handlers and watchdog exit paths: lock-free reads,
    no AsyncWriter, no jax — which is why `rank` is the CALLER's
    problem (resolving it queries the jax runtime; handlers resolve it
    at registration time).  Returns the path, or None on failure (a
    failed telemetry dump must never worsen the failure being dumped)."""
    from .registry import global_registry
    payload = {
        "kind": "flight_record",
        "reason": reason,
        "rank": int(rank),
        "pid": os.getpid(),
        "ts": time.time(),
    }
    payload.update(flight_recorder.contents())
    payload["registry"] = global_registry.snapshot_nolock()
    path = flight_file_path(directory, int(rank))
    try:
        os.makedirs(os.fspath(directory), exist_ok=True)
        atomic_write_text(path, json.dumps(payload, indent=1, default=str))
        return path
    except (OSError, ValueError) as e:
        log.warning(f"Could not write the flight record to {path}: {e}")
        return None
