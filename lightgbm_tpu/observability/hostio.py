"""Bounded single-worker host-I/O thread (docs/Performance.md).

JAX dispatch is asynchronous, so the training loop only goes as fast as
its slowest HOST work: before this module, every JSONL event append and
every checkpoint (model-text serialization, npz packing, fsync, rename)
ran inline on the training thread, stalling dispatch for milliseconds to
seconds while the accelerator idled.  `AsyncWriter` drains that work on
ONE worker thread:

* single worker + FIFO queue — writes land in submission order, so the
  event log and checkpoint rotation behave exactly like the synchronous
  path (byte-identical files; tests/test_async_io.py pins it);
* bounded queue — a slow disk backpressures the training loop instead
  of buffering unboundedly (the reference's equivalent is simply "the
  CLI blocks on fwrite");
* failure isolation — a task that raises is logged and counted
  (`host_io_errors`), never re-raised into training; checkpoint tasks
  install their own handler so a failed write still increments
  `checkpoint_failures` and training continues (docs/Reliability.md).

`flush()` blocks until everything queued so far has executed; the engine
flushes on train end and on error so a crashed run's log is complete up
to the failure.  After `close()`, submissions run inline (synchronous
fallback) rather than being dropped.
"""

from __future__ import annotations

import os
import queue
import signal
import threading
import time
import weakref

from ..utils import log
from .registry import global_registry

# every live AsyncWriter, so the SIGTERM flush handler can drain them
# all without the engine threading handles into the signal layer
_live_writers: "weakref.WeakSet" = weakref.WeakSet()


class AsyncWriter:
    """One daemon worker draining host-I/O callables in FIFO order."""

    def __init__(self, max_queue: int = 256):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(int(max_queue), 1))
        self._thread = None
        self._lock = threading.Lock()
        self._closed = False
        _live_writers.add(self)

    # ------------------------------------------------------------- worker
    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="lgbm-tpu-hostio", daemon=True)
                self._thread.start()

    def _run(self) -> None:
        while True:
            task = self._q.get()
            try:
                if task is None:
                    return
                fn, args, kwargs = task
                fn(*args, **kwargs)
            except Exception as e:  # noqa: BLE001 - I/O must not kill training
                global_registry.inc("host_io_errors")
                log.warning(f"Async host write failed: {e}")
            finally:
                self._q.task_done()

    # -------------------------------------------------------------- API
    def submit(self, fn, *args, **kwargs) -> None:
        """Queue `fn(*args, **kwargs)` for the worker.  Blocks when the
        queue is full (bounded backpressure).  After close(), runs the
        task inline so late stragglers are never silently dropped."""
        if self._closed:
            try:
                fn(*args, **kwargs)
            except Exception as e:  # noqa: BLE001
                global_registry.inc("host_io_errors")
                log.warning(f"Host write failed: {e}")
            return
        self._ensure_thread()
        self._q.put((fn, args, kwargs))

    def flush(self, timeout: float = None) -> None:
        """Block until every task submitted so far has executed.  With
        `timeout` the wait is bounded (polling unfinished_tasks): the
        stall watchdog and the SIGTERM handler flush through here and
        must never wedge on a worker that is itself part of the hang."""
        # tpulint: disable-next=signal-handler-safety -- _lock guards only the thread handle swap, never I/O: held for nanoseconds, it cannot wedge the SIGTERM flush
        with self._lock:
            t = self._thread
        if t is None or not t.is_alive():
            return
        if timeout is None:
            # tpulint: disable-next=signal-handler-safety -- handler/exit-path callers always pass a bounded timeout (flush_host_io, RunGuard); the unbounded branch serves train-end close() on a live worker
            self._q.join()
            return
        deadline = time.monotonic() + float(timeout)
        while time.monotonic() < deadline:
            # tpulint: disable-next=signal-handler-safety -- the queue condition is held only momentarily by the worker's task_done bookkeeping, and this poll loop is deadline-bounded
            with self._q.all_tasks_done:
                if self._q.unfinished_tasks == 0:
                    return
            time.sleep(0.02)

    def close(self) -> None:
        """Flush, stop the worker, switch to inline fallback."""
        self.flush()
        with self._lock:
            self._closed = True
            t = self._thread
            self._thread = None
        if t is not None and t.is_alive():
            self._q.put(None)
            t.join(timeout=10.0)

    @property
    def pending(self) -> int:
        return self._q.qsize()


# --------------------------------------------------------------------------
# SIGTERM: preemption notice handling.  A supervisor kill must never drop
# the final events that would explain the failure, and a preemption
# notice with a grace window should not cost completed work either — the
# run-scoped preemption hook (engine.train) captures an out-of-band
# checkpoint before the signal is re-delivered (docs/Reliability.md).
# --------------------------------------------------------------------------

_sigterm_installed = False

# bound on every terminal-path drain (SIGTERM flush, stall exit): long
# enough to land a realistic queue on a healthy disk, short enough that
# a wedged worker cannot eat the whole preemption grace window.  Module
# level so the reliability drills can shorten it.
TERMINAL_FLUSH_TIMEOUT_S = 5.0

# run-scoped preemption hook: a zero-arg callable (engine.train's
# checkpoint-on-demand closure) installed for the duration of a train()
# call.  Kept out of the signal layer's signature on purpose: the
# handler is installed once per process, the hook swaps per run.
_preempt_hook = None


def set_preemption_hook(fn) -> None:
    """Install the callable the SIGTERM handler runs BEFORE flushing and
    re-delivering — the engine's bounded checkpoint-on-demand."""
    global _preempt_hook
    # tpulint: disable-next=thread-shared-state -- atomic pointer rebind on the main thread; the handler snapshots the reference once before calling (a CPython name assignment cannot tear)
    _preempt_hook = fn


def clear_preemption_hook() -> None:
    global _preempt_hook
    _preempt_hook = None


def finish_preemption() -> None:
    """Terminal half of preemption handling: final `sigterm` event,
    bounded host-I/O flush, then restore the default disposition and
    re-deliver — the exit status stays "killed by SIGTERM" (143), which
    supervisors classify as *preempt*.  Called by the SIGTERM handler
    directly, or by the engine's iteration boundary when the save was
    deferred past a mid-update signal.

    The queued records are drained FIRST (bounded), then the terminal
    event is written through `emit_event_sync` — NEVER the AsyncWriter:
    queueing it would block forever on a full bounded queue whose
    worker is exactly what may be hung (tpulint signal-handler-safety;
    the bug this replaced put the handler on `queue.put` with no
    timeout).  With a healthy worker the order is unchanged — every
    queued record lands, then `sigterm` is the log's last line; with a
    wedged worker the flush times out and the `sigterm` record still
    lands."""
    flush_host_io(timeout=TERMINAL_FLUSH_TIMEOUT_S)
    from .events import emit_event_sync
    try:
        emit_event_sync("sigterm", pid=os.getpid())
    except Exception:  # noqa: BLE001
        pass
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    os.kill(os.getpid(), signal.SIGTERM)


def flush_host_io(timeout: float = 5.0) -> None:
    """Bounded flush of every live AsyncWriter and the run's EventLogger
    (in that order: the logger's queued appends drain through its
    writer first, then its file handle is fsync'd to the OS)."""
    for w in list(_live_writers):
        try:
            w.flush(timeout=timeout)
        except Exception:  # noqa: BLE001 - flushing must never raise
            pass
    from .events import get_event_logger
    lg = get_event_logger()
    if lg is not None:
        lg.flush(timeout=timeout)


def install_sigterm_flush() -> bool:
    """Install a SIGTERM handler that treats the signal as a PREEMPTION
    NOTICE: run the preemption hook (when a train() call installed one —
    it captures an out-of-band checkpoint inside its grace budget and
    emits a `preempt` event), emit a final `sigterm` event, drain the
    async host-I/O queue (bounded wait), then re-raise the default
    termination — so a preempted worker dies with its completed work
    checkpointed and a COMPLETE event log, and its exit status is still
    "killed by SIGTERM" (143), which `classify_returncode` maps to
    *preempt*, distinct from crash/hang.  Idempotent; returns False when
    it cannot be installed (non-main thread, platforms without SIGTERM
    handling)."""
    global _sigterm_installed
    if _sigterm_installed:
        return True

    def _handler(signum, frame):
        hook = _preempt_hook
        if hook is not None:
            # CPython delivers signals on the main thread, which IS the
            # training thread here — so the hook's state capture (incl.
            # the score-buffer D2H) runs exactly where the PR-5
            # capture/write split expects it to.  A False return means
            # the signal landed MID-UPDATE (model/scores/iteration are
            # not a consistent triple): the hook has queued the save for
            # the iteration boundary, where the engine finishes it and
            # calls finish_preemption() itself — exiting here would
            # checkpoint a torn state.
            try:
                if hook() is False:
                    return
            except Exception as e:  # noqa: BLE001 - dying anyway; flush next
                log.warning(f"Preemption checkpoint hook failed: {e}")
        finish_preemption()

    try:
        signal.signal(signal.SIGTERM, _handler)
    except (ValueError, OSError, AttributeError):
        return False  # not the main thread / unsupported platform
    _sigterm_installed = True
    return True
