"""Bounded single-worker host-I/O thread (docs/Performance.md).

JAX dispatch is asynchronous, so the training loop only goes as fast as
its slowest HOST work: before this module, every JSONL event append and
every checkpoint (model-text serialization, npz packing, fsync, rename)
ran inline on the training thread, stalling dispatch for milliseconds to
seconds while the accelerator idled.  `AsyncWriter` drains that work on
ONE worker thread:

* single worker + FIFO queue — writes land in submission order, so the
  event log and checkpoint rotation behave exactly like the synchronous
  path (byte-identical files; tests/test_async_io.py pins it);
* bounded queue — a slow disk backpressures the training loop instead
  of buffering unboundedly (the reference's equivalent is simply "the
  CLI blocks on fwrite");
* failure isolation — a task that raises is logged and counted
  (`host_io_errors`), never re-raised into training; checkpoint tasks
  install their own handler so a failed write still increments
  `checkpoint_failures` and training continues (docs/Reliability.md).

`flush()` blocks until everything queued so far has executed; the engine
flushes on train end and on error so a crashed run's log is complete up
to the failure.  After `close()`, submissions run inline (synchronous
fallback) rather than being dropped.
"""

from __future__ import annotations

import queue
import threading

from ..utils import log
from .registry import global_registry


class AsyncWriter:
    """One daemon worker draining host-I/O callables in FIFO order."""

    def __init__(self, max_queue: int = 256):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(int(max_queue), 1))
        self._thread = None
        self._lock = threading.Lock()
        self._closed = False

    # ------------------------------------------------------------- worker
    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="lgbm-tpu-hostio", daemon=True)
                self._thread.start()

    def _run(self) -> None:
        while True:
            task = self._q.get()
            try:
                if task is None:
                    return
                fn, args, kwargs = task
                fn(*args, **kwargs)
            except Exception as e:  # noqa: BLE001 - I/O must not kill training
                global_registry.inc("host_io_errors")
                log.warning(f"Async host write failed: {e}")
            finally:
                self._q.task_done()

    # -------------------------------------------------------------- API
    def submit(self, fn, *args, **kwargs) -> None:
        """Queue `fn(*args, **kwargs)` for the worker.  Blocks when the
        queue is full (bounded backpressure).  After close(), runs the
        task inline so late stragglers are never silently dropped."""
        if self._closed:
            try:
                fn(*args, **kwargs)
            except Exception as e:  # noqa: BLE001
                global_registry.inc("host_io_errors")
                log.warning(f"Host write failed: {e}")
            return
        self._ensure_thread()
        self._q.put((fn, args, kwargs))

    def flush(self) -> None:
        """Block until every task submitted so far has executed."""
        if self._thread is not None and self._thread.is_alive():
            self._q.join()

    def close(self) -> None:
        """Flush, stop the worker, switch to inline fallback."""
        self.flush()
        with self._lock:
            self._closed = True
            t = self._thread
            self._thread = None
        if t is not None and t.is_alive():
            self._q.put(None)
            t.join(timeout=10.0)

    @property
    def pending(self) -> int:
        return self._q.qsize()
