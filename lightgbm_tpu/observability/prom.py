"""Prometheus text-format exposition of the metrics registry
(docs/Observability.md): the scrape surface the fleet/router/canary
layer needs.

The serving daemon's stats were a poll-only JSON op — fine for a human
with `nc`, useless for a router that wants to load-balance on queue
depth or a canary controller watching p99 drift across replicas.  This
module renders the process-wide registry (counters, gauges), the
serving daemon's latency window and per-model state, and the cost
model's roofline aggregates in the Prometheus text format (version
0.0.4: `# TYPE` lines + `name{label="v"} value`), and serves it two
ways:

* `GET /metrics` on a tiny threaded HTTP listener (`start_metrics_http`,
  param `metrics_port`: -1 off, 0 ephemeral, >0 fixed) — what a
  Prometheus scraper, k8s probe, or fleet router actually pulls;
* `op=metrics` on the line-JSON TCP front end (frontend.py) — the same
  text inline, for clients already on that wire.

Everything renders from one `snapshot()` read, so a scrape costs two
dict copies and string formatting — no device interaction, no locks
held across I/O.  Counters whose registry name carries a `::label`
suffix (e.g. `serve_requests_by_model::higgs`, maintained by the
coalescer) render as labelled series:
`lgbm_serve_requests_by_model{model="higgs"}`.
"""

from __future__ import annotations

import re
import threading
from typing import Dict, List, Optional

from ..utils import log

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")


def _metric_name(name: str, prefix: str) -> str:
    return prefix + _NAME_OK.sub("_", name)


def _escape_label(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt(value) -> str:
    if value is None:
        return "NaN"
    f = float(value)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def parse_prometheus_text(page: str) -> Dict[str, Dict[str, float]]:
    """Inverse of `render_prometheus`, for the fleet aggregator: parse a
    text-format page into `{"counters": {...}, "gauges": {...}}` keyed
    by the FULL series name (labels included, e.g.
    `lgbm_serve_requests_by_model{model="higgs"}`).  `# TYPE` lines
    route each family to its kind; unparseable lines are skipped (a
    replica mid-restart must never poison the merged view)."""
    out: Dict[str, Dict[str, float]] = {"counters": {}, "gauges": {}}
    kinds: Dict[str, str] = {}
    for line in page.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                kinds[parts[2]] = parts[3]
            continue
        name, sep, value = line.rpartition(" ")
        if not sep or not name:
            continue
        try:
            val = float(value)
        except ValueError:
            continue
        base = name.split("{", 1)[0]
        table = out["counters"] if kinds.get(base) == "counter" \
            else out["gauges"]
        table[name] = val
    return out


def render_prometheus(registry=None, daemon=None, prefix: str = "lgbm_",
                      extra_gauges: Optional[Dict[str, float]] = None,
                      gauges_cb=None, text_cb=None) -> str:
    """One Prometheus text page: registry counters/gauges (+ labelled
    `name::label` series), serving latency quantiles / queue depth /
    per-model state when a daemon is given, roofline aggregates when
    the cost model is enabled, and any `extra_gauges`.  `gauges_cb` is
    the LIVE form of extra_gauges — a zero-arg callable re-evaluated at
    every scrape (the fleet router feeds its p50/p99 and replica-state
    gauges through it; a static dict would freeze at registration).
    `text_cb` returns a pre-rendered text BLOCK appended verbatim —
    the fleet aggregator renders its merged multi-replica families
    through it (labelled series with non-`model` label keys, which the
    `::label` counter folding cannot express)."""
    if registry is None:
        from .registry import global_registry
        registry = global_registry
    snap = registry.snapshot()
    lines: List[str] = []

    def emit_family(kind: str, base: str,
                    series: List[tuple]) -> None:
        # series: [(labels_dict_or_None, value), ...]
        lines.append(f"# TYPE {base} {kind}")
        for labels, value in series:
            if labels:
                lab = ",".join(f'{k}="{_escape_label(v)}"'
                               for k, v in sorted(labels.items()))
                lines.append(f"{base}{{{lab}}} {_fmt(value)}")
            else:
                lines.append(f"{base} {_fmt(value)}")

    # registry counters: plain names become one series; `name::label`
    # names fold into one labelled family per base name
    for kind, table in (("counter", snap["counters"]),
                        ("gauge", snap["gauges"])):
        families: Dict[str, List[tuple]] = {}
        for name in sorted(table):
            base, sep, label = name.partition("::")
            key = _metric_name(base, prefix)
            families.setdefault(key, []).append(
                ({"model": label} if sep else None, table[name]))
        for base, series in families.items():
            emit_family(kind, base, series)

    if daemon is not None:
        try:
            p50, p99 = daemon.latency.percentiles((50.0, 99.0))
            emit_family("gauge", f"{prefix}serve_latency_ms",
                        [({"quantile": "0.5"}, p50),
                         ({"quantile": "0.99"}, p99)])
            emit_family("gauge", f"{prefix}serve_queue_pending",
                        [(None, daemon.coalescer.pending)])
            rstats = daemon.registry.stats()
            emit_family("gauge", f"{prefix}serve_recompiles",
                        [(None, rstats.get("serve_recompiles", 0))])
            models = rstats.get("models", {})
            for field in ("version", "in_flight"):
                emit_family(
                    "gauge", f"{prefix}serve_model_{field}",
                    [({"model": n}, m.get(field))
                     for n, m in sorted(models.items())] or [(None, 0)])
        except Exception as e:  # noqa: BLE001 - a scrape must never kill serving
            log.warning(f"/metrics: daemon stats unavailable: {e}")

    from .costmodel import global_cost_model
    if global_cost_model.enabled:
        cm = global_cost_model.snapshot()
        for field, kind in (("flops", "counter"), ("bytes", "counter"),
                            ("calls", "counter")):
            series = [({"phase": g}, tot[field])
                      for g, tot in sorted(cm.items())]
            if series:
                emit_family(kind, f"{prefix}cost_{field}_total", series)

    live = dict(extra_gauges or {})
    if gauges_cb is not None:
        try:
            live.update(gauges_cb() or {})
        except Exception as e:  # noqa: BLE001 - a scrape must never kill serving
            log.warning(f"/metrics: gauges_cb failed: {e}")
    for name, value in sorted(live.items()):
        emit_family("gauge", _metric_name(name, prefix), [(None, value)])
    if text_cb is not None:
        try:
            block = text_cb()
            if block:
                lines.append(str(block).rstrip("\n"))
        except Exception as e:  # noqa: BLE001 - a scrape must never kill serving
            log.warning(f"/metrics: text_cb failed: {e}")
    return "\n".join(lines) + "\n"


class _MetricsServer:
    """Tiny threaded HTTP listener exposing `GET /metrics`."""

    def __init__(self, server, thread):
        self._server = server
        self._thread = thread

    @property
    def port(self) -> int:
        return int(self._server.server_address[1])

    def shutdown(self) -> None:
        try:
            self._server.shutdown()
            self._server.server_close()
        except OSError:
            pass


def start_metrics_http(port: int = 0, host: str = "127.0.0.1",
                       daemon=None, registry=None,
                       prefix: str = "lgbm_",
                       gauges_cb=None, text_cb=None,
                       traces_cb=None) -> Optional[_MetricsServer]:
    """Bind `GET /metrics` (port 0 = ephemeral; read `server.port`) and
    serve on a background thread.  Returns None (with a warning) when
    the bind fails — a metrics port conflict must never block serving
    or training.  With `traces_cb` (a `trace_id_or_None -> dict|None`
    callable, the router's SpanAssembler) the listener also answers
    `GET /trace/<id>` — and bare `GET /trace` with the newest — as the
    assembled cross-process waterfall JSON (docs/Observability.md
    "Distributed tracing")."""
    import json as _json
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - http.server API
            path = self.path.split("?", 1)[0]
            if traces_cb is not None and (path == "/trace"
                                          or path.startswith("/trace/")):
                trace_id = path[len("/trace/"):] or None \
                    if path.startswith("/trace/") else None
                try:
                    trace = traces_cb(trace_id)
                except Exception as e:  # noqa: BLE001 - debug surface must answer
                    self.send_error(500, str(e))
                    return
                if trace is None:
                    self.send_error(404, "no such trace (sampled out, "
                                         "evicted, or never assembled)")
                    return
                body = _json.dumps(trace, indent=1, default=str).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if path != "/metrics":
                self.send_error(404, "try /metrics"
                                + (" or /trace/<id>" if traces_cb else ""))
                return
            try:
                body = render_prometheus(registry=registry, daemon=daemon,
                                         prefix=prefix,
                                         gauges_cb=gauges_cb,
                                         text_cb=text_cb).encode()
            except Exception as e:  # noqa: BLE001 - scrape must answer, not raise
                self.send_error(500, str(e))
                return
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):  # route through utils.log
            log.debug(f"/metrics: {fmt % args}")

    try:
        srv = ThreadingHTTPServer((host, int(port)), _Handler)
    except OSError as e:
        log.warning(f"Could not bind the metrics listener on "
                    f"{host}:{port}: {e}")
        return None
    srv.daemon_threads = True
    t = threading.Thread(target=srv.serve_forever,
                         name="lgbm-metrics-http", daemon=True)
    t.start()
    log.info(f"Prometheus /metrics listening on "
             f"{srv.server_address[0]}:{srv.server_address[1]}")
    return _MetricsServer(srv, t)
