"""Process-wide metrics registry: counters and gauges, rank-tagged.

The training loop, the reliability subsystem and the watchdogs all
increment into one registry; the per-iteration JSONL event
(observability/events.py) snapshots it so a run's structured log carries
the cumulative counter state next to each iteration's phase timings.
Counter updates are a dict add behind a lock — cheap enough to stay
unconditionally on (the reference's equivalent state, e.g. the
HistogramPool hit counters, is likewise always maintained).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional, Tuple, Union

Number = Union[int, float]


def process_rank() -> int:
    """This process's rank in a multi-process SPMD cluster (0 when
    single-process or when jax is not initialized yet)."""
    try:
        import jax
        return int(jax.process_index())
    except Exception:
        return 0


class MetricsRegistry:
    """Counters (monotonic) and gauges (last-write-wins)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Number] = {}
        self._gauges: Dict[str, Number] = {}

    def inc(self, name: str, value: Number = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def set_gauge(self, name: str, value: Number) -> None:
        with self._lock:
            self._gauges[name] = value

    def counter(self, name: str) -> Number:
        with self._lock:
            return self._counters.get(name, 0)

    def gauge(self, name: str, default: Number = None) -> Number:
        with self._lock:
            return self._gauges.get(name, default)

    def snapshot(self) -> Dict[str, Dict[str, Number]]:
        with self._lock:
            return {"counters": dict(self._counters),
                    "gauges": dict(self._gauges)}

    def snapshot_nolock(self) -> Dict[str, Dict[str, Number]]:
        """Signal-path snapshot: the SIGUSR2 flight dump and the stall
        watchdog's exit read through here, where taking `_lock` could
        deadlock on the very thread the handler interrupted.  A dict
        copy racing a writer can raise RuntimeError; retry, then settle
        for empty — a partial snapshot beats a wedged handler."""
        for _ in range(4):
            try:
                return {"counters": dict(self._counters),
                        "gauges": dict(self._gauges)}
            except RuntimeError:
                continue
        return {"counters": {}, "gauges": {}}

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()


class LatencyWindow:
    """Bounded ring of recent latency samples with percentile readout.

    The serving daemon records one sample per request (submit ->
    response, ms) and `stats()` reads p50/p99 over the most recent
    `capacity` samples — a rolling tail-latency view that costs O(1)
    per request and never grows (a long-lived daemon must not hoard
    per-request history; the bench computes its EXACT percentiles from
    its own client-side lists instead)."""

    def __init__(self, capacity: int = 8192):
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=max(int(capacity), 16))
        self._count = 0

    def record(self, value_ms: float) -> None:
        with self._lock:
            self._buf.append(float(value_ms))
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def percentiles(self, qs: Tuple[float, ...] = (50.0, 99.0)
                    ) -> Tuple[Optional[float], ...]:
        """Percentiles (ms) over the retained window; Nones when empty."""
        with self._lock:
            data = list(self._buf)
        if not data:
            return tuple(None for _ in qs)
        import numpy as np
        arr = np.asarray(data, np.float64)
        return tuple(float(np.percentile(arr, q)) for q in qs)

    def reset(self) -> None:
        with self._lock:
            self._buf.clear()
            self._count = 0


# the process-wide registry every subsystem increments into
global_registry = MetricsRegistry()
