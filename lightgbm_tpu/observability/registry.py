"""Process-wide metrics registry: counters and gauges, rank-tagged.

The training loop, the reliability subsystem and the watchdogs all
increment into one registry; the per-iteration JSONL event
(observability/events.py) snapshots it so a run's structured log carries
the cumulative counter state next to each iteration's phase timings.
Counter updates are a dict add behind a lock — cheap enough to stay
unconditionally on (the reference's equivalent state, e.g. the
HistogramPool hit counters, is likewise always maintained).
"""

from __future__ import annotations

import threading
from typing import Dict, Union

Number = Union[int, float]


def process_rank() -> int:
    """This process's rank in a multi-process SPMD cluster (0 when
    single-process or when jax is not initialized yet)."""
    try:
        import jax
        return int(jax.process_index())
    except Exception:
        return 0


class MetricsRegistry:
    """Counters (monotonic) and gauges (last-write-wins)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Number] = {}
        self._gauges: Dict[str, Number] = {}

    def inc(self, name: str, value: Number = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def set_gauge(self, name: str, value: Number) -> None:
        with self._lock:
            self._gauges[name] = value

    def counter(self, name: str) -> Number:
        with self._lock:
            return self._counters.get(name, 0)

    def gauge(self, name: str, default: Number = None) -> Number:
        with self._lock:
            return self._gauges.get(name, default)

    def snapshot(self) -> Dict[str, Dict[str, Number]]:
        with self._lock:
            return {"counters": dict(self._counters),
                    "gauges": dict(self._gauges)}

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()


# the process-wide registry every subsystem increments into
global_registry = MetricsRegistry()
