"""Zero-dependency distributed tracing + SLO burn-rate tracking
(docs/Observability.md "Distributed tracing" / "Fleet metrics & SLO").

PR 13 made serving a multi-process fleet; the flight recorder's sampled
stage traces (PR 11) stayed per-process, so nothing followed ONE request
across client -> router -> replica -> coalescer -> device dispatch.
This module is the shared vocabulary that fixes it:

* **TraceContext** — (trace_id, span_id, parent_id, sampled) propagated
  as a `trace` field on the existing line-JSON wire protocol.  The
  client or router EDGE generates a context when a request arrives
  without one and honors one that is already present; every hop that
  does work derives a child context so its spans parent correctly.
  Ids come from `os.urandom` (no RNG-stream interaction with training,
  which tpulint's rng-discipline rule polices).

* **Spans** — plain dicts (`make_span`), deliberately JSON-ready so
  they ride the response envelope back to the router with zero
  serialization ceremony: `{trace_id, span_id, parent_id, name, ts,
  dur_ms, pid, attrs[, links]}`.  `ts` is wall-clock (`time.time()`);
  all fleet processes share a host today, and a cross-host skew shows
  up as a bounded offset in the waterfall rather than corrupt data.
  `links` attribute a COALESCED dispatch to every batch-mate request it
  served (the one-span-many-traces relation OpenTelemetry models the
  same way).

* **SpanAssembler** — router-side: joins the router's own route/attempt
  spans with the replica-returned spans into one cross-process
  waterfall, records it into the flight recorder ring, and keeps a
  bounded id-indexed map behind `op=trace` / `GET /trace/<id>`.

* **SloTracker** — multi-window burn-rate computation over the
  router's request outcomes: a request is BAD when it failed or when
  its latency exceeded `serve_slo_p99_ms`; the bad-fraction over a
  fast (default 1 min) and a slow (default 30 min) window, divided by
  the error budget `serve_slo_error_pct`, gives the burn rates.  Both
  above `serve_slo_burn_threshold` = the SLO is burning: one
  structured `slo_burn` event per onset (edge-triggered), a
  `fleet_slo_burning` gauge while it lasts, and an `slo_burn_total`
  counter — the signal the canary/auto-rollback machinery and future
  autoscaling key off.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional

# spans returned in one response envelope are bounded: a pathological
# request must not balloon the reply it rides in
MAX_SPANS_PER_REQUEST = 32


def _hex_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


def new_trace_id() -> str:
    return _hex_id(8)


def new_span_id() -> str:
    return _hex_id(4)


class TraceContext:
    """One hop's position in a trace (see module docstring)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "sampled")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str] = None, sampled: bool = False):
        self.trace_id = str(trace_id)
        self.span_id = str(span_id)
        self.parent_id = parent_id if parent_id is None else str(parent_id)
        self.sampled = bool(sampled)

    @classmethod
    def new(cls, sampled: bool = False) -> "TraceContext":
        """Root context, generated at the client/router edge."""
        return cls(new_trace_id(), new_span_id(), None, sampled)

    def child(self) -> "TraceContext":
        """Context for a child span: fresh span id, this span as parent."""
        return TraceContext(self.trace_id, new_span_id(), self.span_id,
                            self.sampled)

    # ----------------------------------------------------------------- wire
    def to_wire(self) -> Dict[str, object]:
        """The `trace` field of a line-JSON request."""
        out: Dict[str, object] = {"id": self.trace_id, "span": self.span_id,
                                  "sampled": self.sampled}
        if self.parent_id is not None:
            out["parent"] = self.parent_id
        return out

    @classmethod
    def from_wire(cls, obj) -> Optional["TraceContext"]:
        """Parse a request's `trace` field; None (never a raise) on
        anything malformed — a bad trace header must not fail the
        request it annotates."""
        if not isinstance(obj, dict):
            return None
        tid, sid = obj.get("id"), obj.get("span")
        if not tid or not sid:
            return None
        return cls(str(tid), str(sid), obj.get("parent"),
                   bool(obj.get("sampled")))

    def __repr__(self) -> str:  # greppable in logs
        return (f"trace={self.trace_id} span={self.span_id} "
                f"sampled={int(self.sampled)}")


def make_span(ctx: TraceContext, name: str, t_start: float, t_end: float,
              links: Optional[List[Dict[str, str]]] = None,
              **attrs) -> Dict[str, object]:
    """One completed span as a JSON-ready dict.  `t_start`/`t_end` are
    wall-clock seconds (`time.time()`); attrs with None values are
    dropped so envelopes stay small."""
    span: Dict[str, object] = {
        "trace_id": ctx.trace_id,
        "span_id": ctx.span_id,
        "parent_id": ctx.parent_id,
        "name": str(name),
        "ts": round(float(t_start), 6),
        "dur_ms": round(max(t_end - t_start, 0.0) * 1000.0, 3),
        "pid": os.getpid(),
    }
    clean = {k: v for k, v in attrs.items() if v is not None}
    if clean:
        span["attrs"] = clean
    if links:
        span["links"] = list(links)
    return span


class SpanAssembler:
    """Router-side joiner: spans from every hop -> one waterfall.

    Bounded id-indexed retention (`capacity` most recent traces) behind
    the `op=trace` / `GET /trace/<id>` debug surface; every assembled
    trace is also recorded into the flight recorder ring (kind
    `assembled_trace`), so a crash dump carries the recent cross-process
    waterfalls next to the router's own stage traces."""

    def __init__(self, capacity: int = 128):
        self._lock = threading.Lock()
        self._traces: "OrderedDict[str, Dict]" = OrderedDict()
        self._capacity = max(int(capacity), 8)

    def assemble(self, trace_id: str, spans: List[Dict],
                 **meta) -> Dict[str, object]:
        """Build + retain the waterfall for one trace.  Spans sort by
        start stamp; `rel_ms` offsets each from the trace start so the
        dumped JSON reads as a waterfall without clock context."""
        spans = sorted((s for s in spans if s), key=lambda s: s.get("ts", 0))
        t0 = spans[0]["ts"] if spans else 0.0
        for s in spans:
            s["rel_ms"] = round((s["ts"] - t0) * 1000.0, 3)
        trace: Dict[str, object] = {
            "trace_id": str(trace_id),
            "ts": t0,
            "spans": spans,
            "span_count": len(spans),
            "processes": sorted({s.get("pid") for s in spans
                                 if s.get("pid") is not None}),
        }
        trace.update({k: v for k, v in meta.items() if v is not None})
        with self._lock:
            self._traces[str(trace_id)] = trace
            self._traces.move_to_end(str(trace_id))
            while len(self._traces) > self._capacity:
                self._traces.popitem(last=False)
        from .flightrec import flight_recorder
        flight_recorder.record_trace(
            kind="assembled_trace", trace_id=str(trace_id),
            spans=len(spans), processes=trace["processes"],
            **{k: v for k, v in meta.items() if v is not None})
        from .events import emit_event
        emit_event("trace_assembled", trace_id=str(trace_id),
                   spans=len(spans), processes=len(trace["processes"]),
                   **{k: v for k, v in meta.items() if v is not None})
        return trace

    def get(self, trace_id: str) -> Optional[Dict[str, object]]:
        with self._lock:
            return self._traces.get(str(trace_id))

    def latest(self) -> Optional[Dict[str, object]]:
        with self._lock:
            return next(reversed(self._traces.values()), None) \
                if self._traces else None

    def ids(self) -> List[str]:
        """Newest-last trace ids currently retained."""
        with self._lock:
            return list(self._traces)

    def traces(self) -> List[Dict[str, object]]:
        with self._lock:
            return list(self._traces.values())


class SloTracker:
    """Multi-window SLO burn-rate computation (module docstring).

    `observe()` is called once per routed request outcome; the retained
    per-request records are bounded by the slow window AND a hard cap,
    so a hot router cannot hoard unbounded history.  All state is
    lock-guarded — router worker threads observe concurrently."""

    _EVAL_EVERY = 8      # evaluate burn state every N observations
    _MAX_SAMPLES = 65536

    def __init__(self, p99_ms: float, error_pct: float = 1.0,
                 fast_window_s: float = 60.0,
                 slow_window_s: float = 1800.0,
                 burn_threshold: float = 1.0):
        self.p99_ms = float(p99_ms)
        # budget: allowed bad-request fraction (1.0 pct -> 0.01)
        self.budget = max(float(error_pct), 1e-6) / 100.0
        self.fast_window_s = max(float(fast_window_s), 0.5)
        self.slow_window_s = max(float(slow_window_s), self.fast_window_s)
        self.burn_threshold = float(burn_threshold)
        self._lock = threading.Lock()
        self._samples: deque = deque(maxlen=self._MAX_SAMPLES)
        self._n = 0
        self._burning = False

    @property
    def enabled(self) -> bool:
        return self.p99_ms > 0

    def observe(self, latency_ms: float, ok: bool = True,
                now: Optional[float] = None) -> None:
        """Record one request outcome; re-evaluates the burn state every
        few observations (edge-triggered `slo_burn` event on onset)."""
        if not self.enabled:
            return
        now = time.monotonic() if now is None else float(now)
        bad = (not ok) or (float(latency_ms) > self.p99_ms)
        with self._lock:
            self._samples.append((now, bad))
            self._n += 1
            evaluate = self._n % self._EVAL_EVERY == 0
        if evaluate:
            self.evaluate(now=now)

    def _window_rate(self, now: float, window_s: float) -> float:
        """Bad fraction over [now - window_s, now]; caller holds lock."""
        lo = now - window_s
        total = bad = 0
        for ts, is_bad in reversed(self._samples):
            if ts < lo:
                break
            total += 1
            bad += int(is_bad)
        return bad / total if total else 0.0

    def burn_rates(self, now: Optional[float] = None
                   ) -> Dict[str, float]:
        """{"fast": rate, "slow": rate}: window bad-fraction / budget."""
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            fast = self._window_rate(now, self.fast_window_s)
            slow = self._window_rate(now, self.slow_window_s)
        return {"fast": fast / self.budget, "slow": slow / self.budget}

    def evaluate(self, now: Optional[float] = None) -> bool:
        """Re-derive the burning state; emits/clears the telemetry on
        transitions.  Returns the current state."""
        rates = self.burn_rates(now=now)
        burning = (rates["fast"] > self.burn_threshold
                   and rates["slow"] > self.burn_threshold)
        with self._lock:
            onset = burning and not self._burning
            cleared = self._burning and not burning
            self._burning = burning
        from .registry import global_registry
        global_registry.set_gauge("fleet_slo_burning", 1.0 if burning
                                  else 0.0)
        if onset:
            global_registry.inc("slo_burn_total")
            from .events import emit_event
            emit_event("slo_burn",
                       slo_p99_ms=self.p99_ms,
                       error_budget_pct=self.budget * 100.0,
                       burn_rate_fast=round(rates["fast"], 3),
                       burn_rate_slow=round(rates["slow"], 3),
                       fast_window_s=self.fast_window_s,
                       slow_window_s=self.slow_window_s)
            from ..utils import log
            log.warning(
                f"SLO BURNING: p99<={self.p99_ms:g}ms budget "
                f"{self.budget * 100.0:g}% — burn rates fast="
                f"{rates['fast']:.2f} slow={rates['slow']:.2f} "
                f"(threshold {self.burn_threshold:g})")
        elif cleared:
            from ..utils import log
            log.info("SLO burn cleared")
        return burning

    @property
    def burning(self) -> bool:
        with self._lock:
            return self._burning

    def stats(self) -> Dict[str, object]:
        rates = self.burn_rates()
        return {"slo_p99_ms": self.p99_ms,
                "slo_error_budget_pct": self.budget * 100.0,
                "burn_rate_fast": round(rates["fast"], 4),
                "burn_rate_slow": round(rates["slow"], 4),
                "burning": self.burning}
