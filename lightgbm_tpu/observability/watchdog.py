"""Training watchdogs: recompile detection and device-memory sampling.

Recompiles are the silent TPU performance killer: a mid-training shape
change (a differently-sized eval batch, a resized bagging mask, a new
static argument) re-traces and re-compiles the whole jitted program — a
multi-second stall that looks like "training got slow" with no other
signal.  `RecompileDetector` wraps a jitted entry point, fingerprints
every call's argument shapes/dtypes (+ static values), and warns ONCE
per new signature after the first, naming the offending signature.

The device-memory gauge samples `Device.memory_stats()` (absent on the
CPU backend — the sampler degrades to an empty dict) into the metrics
registry so the per-iteration event log carries HBM occupancy, the TPU
analogue of the reference's histogram-pool size accounting.
"""

from __future__ import annotations

import functools
from typing import Dict

from ..utils import log
from .costmodel import global_cost_model
from .events import emit_event
from .registry import global_registry


def call_signature(args, kwargs):
    """Fingerprint of a jitted call: ((shape, dtype), ...) for array
    leaves plus the static (non-array) leaves' reprs.  Two calls with
    equal signatures hit the same executable; a new signature re-traces."""
    import jax
    leaves = jax.tree_util.tree_leaves((args, kwargs))
    arrays, static = [], []
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            arrays.append((tuple(leaf.shape), str(leaf.dtype)))
        else:
            static.append(repr(leaf))
    return tuple(arrays), tuple(static)


class RecompileDetector:
    """Wraps a jitted callable; warns once per NEW argument signature
    after the first call (each one is an XLA re-trace + re-compile)."""

    def __init__(self, fn, name: str):
        self._fn = fn
        self._name = name
        self._seen = set()
        functools.update_wrapper(self, fn,
                                 assigned=("__name__", "__doc__"),
                                 updated=())

    def __call__(self, *args, **kwargs):
        sig = call_signature(args, kwargs)
        if sig not in self._seen:
            if self._seen:
                log.warning(
                    f"{self._name}: input signature changed mid-training — "
                    f"XLA re-traces and recompiles the program (array "
                    f"shapes/dtypes now {list(sig[0])}). Recompiles stall "
                    f"the accelerator for seconds; keep shapes stable "
                    f"across iterations.")
                global_registry.inc("recompiles")
                emit_event("recompile", fn=self._name,
                           signature=[list(s) for s in sig[0]])
            self._seen.add(sig)
        if global_cost_model.enabled:
            # compiled-cost accounting (costmodel.py): keyed by the SAME
            # signature this watchdog fingerprints, so the flop/byte
            # ledger can never disagree about which executable ran; the
            # harvest itself uses .lower() (no compile, no new trace)
            global_cost_model.observe(self._name, sig, self._fn,
                                      args, kwargs)
        return self._fn(*args, **kwargs)

    @property
    def signatures_seen(self) -> int:
        return len(self._seen)

    def __getattr__(self, name):
        # transparent proxy: expose the wrapped callable's attributes
        # (e.g. the sharded-wave fn's `.build` used by collective tests)
        return getattr(self._fn, name)


def sample_device_memory() -> Dict[str, int]:
    """Sum of the local devices' live/peak HBM bytes, or {} when the
    backend exposes no memory stats (CPU)."""
    try:
        import jax
        all_stats = [d.memory_stats() for d in jax.local_devices()]
    except Exception:
        return {}
    all_stats = [s for s in all_stats if s]
    if not all_stats:
        return {}
    out: Dict[str, int] = {}
    for src, dst in (("bytes_in_use", "device_bytes_in_use"),
                     ("peak_bytes_in_use", "device_peak_bytes_in_use"),
                     ("bytes_limit", "device_bytes_limit")):
        vals = [s.get(src) for s in all_stats if s.get(src) is not None]
        if vals:
            out[dst] = int(sum(vals))
    return out


def update_memory_gauges() -> Dict[str, int]:
    """Sample device memory into the global registry (the engine calls
    this on every nonfinite_check_freq tick)."""
    stats = sample_device_memory()
    for k, v in stats.items():
        global_registry.set_gauge(k, v)
    return stats
