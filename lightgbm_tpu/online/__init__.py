"""Online continual learning: the closed train->serve loop
(docs/Online.md).

A `ChunkSource` sequences arriving row chunks with monotone generation
ids; the `OnlineTrainer` consumes them — boosting additional trees via
init_model continuation or refitting leaf values on the fresh chunk —
checkpoints every generation through the existing CheckpointManager
(byte-exact SIGTERM/crash resume), and publishes each generation
atomically into serving (local ModelRegistry hot swap, in-process
Router rolling/canary rollout, or `op=publish` over the wire) while the
previous generation keeps serving.  The freshness plane measures
`model_freshness_lag_s` (chunk arrival -> first request served by a
model that saw it) against the `online_max_lag_s` SLO.

`python -m lightgbm_tpu task=train-and-serve` is the CLI front end;
`bench.py --online` the closed-loop bench with the SIGTERM drill.
"""

from .chunks import (Chunk, ChunkSource, DirectoryChunkSource,
                     MemoryChunkSource, write_chunk)
from .trainer import (LocalPublisher, OnlineTrainer, PublishError,
                      RouterPublisher, WirePublisher)

__all__ = [
    "Chunk", "ChunkSource", "DirectoryChunkSource", "MemoryChunkSource",
    "write_chunk",
    "LocalPublisher", "OnlineTrainer", "PublishError", "RouterPublisher",
    "WirePublisher",
]
