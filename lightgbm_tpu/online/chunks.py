"""Chunk sources for the online continual-learning loop (docs/Online.md).

A `ChunkSource` sequences arriving row chunks with MONOTONE generation
ids: `poll()` yields the next unconsumed generation (or None), never the
same generation twice, never out of order.  Generation ids are the
loop's clock — the trainer checkpoints, publishes and measures
freshness per generation, and a resumed trainer re-opens its source at
`last_checkpointed_generation + 1`.

Two implementations:

* `DirectoryChunkSource` — a directory watcher: producers land files
  named `chunk-<generation>.npz|npy|csv` (the generation is the file
  name, so ordering survives any producer) and MUST rename them into
  place atomically (`write_chunk` below does; a torn partial write
  surfaces as a corrupt chunk, which the trainer skips).  npz chunks
  carry `X` and `y` arrays; npy/csv chunks are one 2-D matrix whose
  FIRST column is the label (the CLI-file convention).
* `MemoryChunkSource` — an in-process feeder for tests and the bench:
  `push(X, y)` assigns the next generation and stamps its arrival.

A chunk that cannot be read (torn write, injected `online_chunk_corrupt`
fault, malformed matrix) is returned with `error` set instead of
raising: the SOURCE advances past it (monotonicity holds), and the
TRAINER decides — skip the generation, keep the previous one serving.
"""

from __future__ import annotations

import io
import os
import re
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

from ..utils import atomic_write_bytes

_CHUNK_RE = re.compile(r"^chunk-(\d+)\.(npz|npy|csv)$")


@dataclass
class Chunk:
    """One generation of fresh rows.  `t_arrival` is the monotonic stamp
    the source first saw it (the freshness-lag epoch); `error` set means
    the bytes could not be read — skip, do not train."""

    generation: int
    X: Optional[np.ndarray]
    y: Optional[np.ndarray]
    t_arrival: float
    path: Optional[str] = None
    error: Optional[str] = None

    @property
    def ok(self) -> bool:
        return self.error is None

    @property
    def num_rows(self) -> int:
        return 0 if self.X is None else int(self.X.shape[0])


class ChunkSource:
    """Base protocol: `poll()` -> next Chunk or None; `close()`."""

    def poll(self) -> Optional[Chunk]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemoryChunkSource(ChunkSource):
    """In-process feeder (tests/bench): `push(X, y)` assigns the next
    monotone generation and stamps arrival; `poll()` pops in order.
    Thread-safe — the bench pushes from its driver thread while the
    trainer thread polls."""

    def __init__(self, start_generation: int = 1):
        self._lock = threading.Lock()
        self._queue: deque = deque()
        self._next_gen = int(start_generation)

    def push(self, X, y) -> int:
        X = np.asarray(X)
        y = np.asarray(y)
        if X.ndim != 2 or X.shape[0] == 0 or len(y) != X.shape[0]:
            raise ValueError(f"chunk must be a non-empty 2-D matrix with "
                             f"matching labels (got X {X.shape}, "
                             f"y {np.shape(y)})")
        with self._lock:
            gen = self._next_gen
            self._next_gen += 1
            self._queue.append(Chunk(gen, X, y, time.monotonic()))
        return gen

    def poll(self) -> Optional[Chunk]:
        with self._lock:
            chunk = self._queue.popleft() if self._queue else None
        if chunk is not None:
            from ..reliability import faults
            if faults.active() and faults.maybe_online_chunk_corrupt(
                    chunk.generation):
                chunk = Chunk(chunk.generation, None, None,
                              chunk.t_arrival,
                              error="injected online_chunk_corrupt")
        return chunk

    @property
    def pending(self) -> int:
        with self._lock:
            return len(self._queue)


def _read_chunk(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """Decode one chunk file -> (X, y).  Raises OSError/ValueError/
    KeyError on damage — the caller converts that into an error Chunk."""
    ext = os.path.splitext(path)[1].lower()
    if ext == ".npz":
        with np.load(path, allow_pickle=False) as z:
            X = np.asarray(z["X"])
            y = np.asarray(z["y"])
    else:
        if ext == ".npy":
            mat = np.asarray(np.load(path, allow_pickle=False))
        else:  # .csv: label-first-column, comma-separated
            mat = np.genfromtxt(path, delimiter=",", dtype=np.float64)
        mat = np.atleast_2d(mat)
        if mat.shape[1] < 2:
            raise ValueError(f"chunk matrix needs a label column plus at "
                             f"least one feature (shape {mat.shape})")
        y = mat[:, 0]
        X = mat[:, 1:]
    if X.ndim != 2 or X.shape[0] == 0 or len(y) != X.shape[0]:
        raise ValueError(f"malformed chunk: X {X.shape}, y {np.shape(y)}")
    if not np.all(np.isfinite(np.asarray(y, np.float64))):
        raise ValueError("chunk labels contain non-finite values")
    return X, y


def write_chunk(directory: str, generation: int, X, y) -> str:
    """Land one npz chunk ATOMICALLY (temp sibling + os.replace): the
    watcher can never observe a half-written chunk — it either sees
    nothing or the complete file.  Producers should use this (or the
    same rename idiom) rather than writing `chunk-*.npz` in place."""
    X = np.asarray(X)
    y = np.asarray(y)
    buf = io.BytesIO()
    np.savez(buf, X=X, y=y)
    path = os.path.join(os.fspath(directory), f"chunk-{generation:07d}.npz")
    atomic_write_bytes(path, buf.getvalue())
    return path


class DirectoryChunkSource(ChunkSource):
    """Directory watcher: yields `chunk-<gen>.*` files in generation
    order, starting at `start_generation` (a resumed trainer passes
    `last_checkpointed + 1`, so already-consumed chunks are never
    re-trained).  Gaps in the id sequence are allowed — the smallest
    unconsumed generation wins each poll; ids below the cursor are
    ignored forever (monotonicity).  Non-matching names (temp files,
    dotfiles) are invisible, which is what makes the atomic-rename
    producer contract sufficient."""

    def __init__(self, directory: str, start_generation: int = 1):
        self.dir = os.fspath(directory)
        self._next_gen = int(start_generation)

    def fast_forward(self, last_consumed: int) -> None:
        """Advance the cursor past `last_consumed` (a resumed trainer
        calls this with its checkpointed generation; never rewinds)."""
        self._next_gen = max(self._next_gen, int(last_consumed) + 1)

    def poll(self) -> Optional[Chunk]:
        try:
            names = os.listdir(self.dir)
        except OSError:
            return None
        best: Optional[Tuple[int, str]] = None
        for fname in names:
            m = _CHUNK_RE.match(fname)
            if m is None:
                continue
            gen = int(m.group(1))
            if gen < self._next_gen:
                continue
            if best is None or gen < best[0]:
                best = (gen, fname)
        if best is None:
            return None
        gen, fname = best
        path = os.path.join(self.dir, fname)
        t_arrival = time.monotonic()
        self._next_gen = gen + 1
        from ..reliability import faults
        if faults.active():
            faults.maybe_online_chunk_corrupt(gen, path)
        try:
            X, y = _read_chunk(path)
        except Exception as e:  # noqa: BLE001 - damage takes many shapes (BadZipFile, OSError, ValueError); all mean "skip this generation"
            return Chunk(gen, None, None, t_arrival, path=path,
                         error=f"{type(e).__name__}: {e}")
        return Chunk(gen, X, y, t_arrival, path=path)
