"""OnlineTrainer: the closed train->serve loop (docs/Online.md).

The reference engine's cheapest production win is that a trained model
is never final: `init_model` continued training and `refit` leaf
re-estimation (ref: gbdt.cpp:252 RefitTree) let CTR/fraud/ranking
deployments chase non-stationary data.  This module wires the
ingredients the repo already holds — byte-exact checkpoint/resume
(reliability/checkpoint.py), continued training, hot-swap serving
(serving/registry.py) and fleet publish (serving/router.py) — into one
loop:

    per chunk generation g (ChunkSource, monotone ids):
      1. TRAIN   — boost `online_trees_per_chunk` new trees via
                   init_model continuation, or refit the existing
                   leaves on the fresh chunk (`online_mode`; auto picks
                   refit when the chunk has fewer rows than the
                   ensemble has trees — too little signal to grow new
                   structure, plenty to re-estimate leaf values);
      2. CHECKPOINT — through the existing CheckpointManager keyed by
                   generation id: a SIGTERM/crash mid-generation
                   resumes from the last completed generation and
                   re-trains the interrupted one BYTE-EXACTLY (each
                   generation is a pure function of (model text, chunk
                   bytes));
      3. PUBLISH — atomically into serving (a local ModelRegistry hot
                   swap, an in-process Router rolling/canary rollout,
                   or `op=publish` over the wire) while the previous
                   generation keeps serving.  A failed publish keeps
                   the old generation serving and retries with backoff
                   (`online_publish_retry_max`) — never a half-
                   published model;
      4. FRESHNESS — one probe request through the serving path proves
                   a model that saw the chunk is answering; the lag
                   (chunk arrival -> probe response) lands on the
                   `model_freshness_lag_s` gauge, the `online_publish`
                   event, and — with `online_max_lag_s` > 0 — the
                   PR-14 SloTracker burn-rate windows.

Publishers deliberately serialize the model TEXT (or publish the
generation's immutable checkpoint file): the registry builds its own
Booster from the bytes, so the trainer's live booster — which refit
mutates IN PLACE — never aliases trees a serving entry is dispatching
(the PR-10 mutation-repack hazard, closed structurally here).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from ..config import Config
from ..observability import emit_event
from ..observability.registry import global_registry
from ..observability.tracing import SloTracker
from ..utils import log
from .chunks import Chunk, ChunkSource, DirectoryChunkSource


class PublishError(RuntimeError):
    """A publish attempt failed; the previous generation keeps serving."""


class LocalPublisher:
    """Publish into an in-process `ServingDaemon` (or bare
    `ModelRegistry`): a background load + warmup, then the atomic
    one-pointer hot swap — requests in flight finish on the old entry.
    The probe rides the daemon's real submit path (coalescer included)
    so the measured freshness lag is what a client would see."""

    def __init__(self, target, timeout_s: float = 300.0):
        self._daemon = target if hasattr(target, "registry") else None
        self._registry = target.registry if self._daemon else target
        self._timeout_s = float(timeout_s)

    def publish(self, name: str, model_str: str,
                path: Optional[str]) -> int:
        handle = self._registry.register(name, model_str=model_str,
                                         block=True,
                                         timeout=self._timeout_s)
        return int(handle.entry.version)

    def probe(self, name: str, rows: np.ndarray):
        if self._daemon is not None:
            fut = self._daemon.submit(name, rows)
            out = fut.result(timeout=self._timeout_s)
            return np.asarray(out), fut.version
        entry = self._registry.get(name)
        try:
            return (np.asarray(entry.predictor.predict(
                np.asarray(rows, np.float32))), entry.version)
        finally:
            entry.release()


class RouterPublisher:
    """Publish through an in-process fleet `Router`: rolling publish
    replica-by-replica (canary split + auto-rollback when
    `serve_canary_pct` > 0 — a rolled-back canary surfaces as a
    PublishError, so the trainer counts the generation skipped and the
    incumbent keeps serving fleet-wide)."""

    def __init__(self, router, timeout_s: float = 300.0):
        self._router = router
        self._timeout_s = float(timeout_s)

    def publish(self, name: str, model_str: str,
                path: Optional[str]) -> int:
        if not path:
            raise PublishError("router publish needs the generation's "
                               "on-disk model path (set checkpoint_dir)")
        out = self._router.publish(name, path, timeout_s=self._timeout_s)
        if out.get("canary"):
            verdict = self._router.canary_wait(name,
                                               timeout=self._timeout_s)
            if verdict != "promoted":
                raise PublishError(f"canary verdict: {verdict}")
        versions = out.get("replicas") or {}
        return int(max(versions.values())) if versions else 0

    def probe(self, name: str, rows: np.ndarray):
        r = self._router.predict(name, np.asarray(rows).tolist())
        return np.asarray(r.preds), r.version


class WirePublisher:
    """Publish over the line-JSON wire (`op=publish`) to a remote
    router or replica front end — TCP (`host:port`) or a Unix socket
    (`uds_path`).  The probe is one wire predict on the same
    connection."""

    def __init__(self, host: Optional[str] = None,
                 port: Optional[int] = None,
                 uds_path: Optional[str] = None,
                 timeout_s: float = 300.0):
        from ..serving.frontend import LineClient
        self._conn = LineClient(host, port, uds_path=uds_path)
        self._conn_lock = threading.Lock()
        self._timeout_s = float(timeout_s)

    def publish(self, name: str, model_str: str,
                path: Optional[str]) -> int:
        if not path:
            raise PublishError("wire publish needs the generation's "
                               "on-disk model path (set checkpoint_dir)")
        with self._conn_lock:
            reply = self._conn.request(
                {"op": "publish", "model": name, "path": str(path),
                 "timeout_s": self._timeout_s},
                timeout_s=self._timeout_s)
        if not reply.get("ok"):
            raise PublishError(f"remote publish failed: "
                               f"{reply.get('error')}")
        return int(reply.get("version") or 0)

    def probe(self, name: str, rows: np.ndarray):
        with self._conn_lock:
            reply = self._conn.request(
                {"model": name, "rows": np.asarray(rows).tolist()},
                timeout_s=self._timeout_s)
        if not reply.get("ok"):
            raise PublishError(f"probe failed: {reply.get('error')}")
        return np.asarray(reply["preds"]), reply.get("version")

    def close(self) -> None:
        self._conn.close()


# params the per-generation inner train() must NOT inherit: the online
# loop owns checkpointing/telemetry/serving itself, and the boosting
# round count is online_trees_per_chunk
_TRAIN_PARAM_STRIP = ("task", "data", "valid", "input_model",
                      "output_model", "checkpoint_dir", "checkpoint_freq",
                      "checkpoint_keep", "resume", "metrics_dir",
                      "metrics_port", "num_iterations")


class OnlineTrainer:
    """The streaming trainer (docs/Online.md).  Single consumer loop:
    construct, optionally `install_signal_handlers()`, then `run()` —
    or drive `step()` manually from a test.  `stats()` is thread-safe
    (the bench reads it while the loop runs)."""

    def __init__(self, source: ChunkSource, publisher,
                 params: Optional[Dict[str, Any]] = None,
                 config: Optional[Config] = None,
                 checkpoint_dir: Optional[str] = None,
                 model_name: Optional[str] = None,
                 seed_model=None, on_publish=None):
        self.config = Config(dict(params or {})) if config is None \
            else config
        cfg = self.config
        self.source = source
        self.publisher = publisher
        self.model_name = model_name or cfg.online_model_name
        self.trees_per_chunk = max(int(cfg.online_trees_per_chunk), 1)
        self.poll_interval_s = max(float(cfg.online_poll_interval_s), 0.01)
        self.publish_retry_max = max(int(cfg.online_publish_retry_max), 0)
        self.publish_backoff_s = max(
            float(cfg.online_publish_backoff_ms), 0.0) / 1000.0
        # the full params hash-gate the checkpoint (online_*/serve_* are
        # _HASH_EXCLUDEd); the inner train() gets the stripped subset
        self._params = dict(cfg.raw_params)
        self._train_params = {
            k: v for k, v in self._params.items()
            if k not in _TRAIN_PARAM_STRIP
            and not k.startswith(("online_", "serve_"))}
        self.ckpt_mgr = None
        if checkpoint_dir or cfg.checkpoint_dir:
            from ..reliability import CheckpointManager
            self.ckpt_mgr = CheckpointManager(
                checkpoint_dir or cfg.checkpoint_dir,
                keep_last=cfg.checkpoint_keep, params=self._params)
        self._seed_model = seed_model
        self._on_publish = on_publish
        # freshness SLO: per-generation lag observations feed the PR-14
        # multi-window burn tracker; inert when online_max_lag_s == 0
        self.slo = SloTracker(
            p99_ms=float(cfg.online_max_lag_s) * 1000.0,
            error_pct=float(cfg.serve_slo_error_pct),
            fast_window_s=float(cfg.serve_slo_fast_window_s),
            slow_window_s=float(cfg.serve_slo_slow_window_s),
            burn_threshold=float(cfg.serve_slo_burn_threshold))
        self._stop = threading.Event()
        # guards the published-state the loop writes and stats() reads
        # from other threads (bench driver, CLI status)
        self._lock = threading.Lock()
        self.booster = None
        self.generation = 0
        self._published_version: Optional[int] = None
        self._last_lag_s: Optional[float] = None
        self._published = 0
        self._skipped = 0
        self._started = False
        self._probe_rows: Optional[np.ndarray] = None

    # ------------------------------------------------------------- control
    def request_stop(self) -> None:
        self._stop.set()

    @property
    def stopped(self) -> bool:
        return self._stop.is_set()

    def install_signal_handlers(self) -> bool:
        """SIGTERM = stop notice: the loop exits at the next boundary;
        a signal landing MID-GENERATION terminates the process after the
        host-I/O flush (exit stays 143) and the next launch resumes from
        the last completed generation's checkpoint, re-training the
        interrupted one byte-exactly."""
        from ..observability import install_sigterm_flush, \
            set_preemption_hook
        ok = install_sigterm_flush()
        if ok:
            set_preemption_hook(self._sigterm)
        return ok

    def _sigterm(self):
        self._stop.set()
        return None  # finish_preemption() flushes and re-delivers

    # -------------------------------------------------------------- startup
    def start(self) -> "OnlineTrainer":
        """Resume (or seed) the model and publish it so serving starts
        from the newest complete generation — a relaunch must never
        regress the served version below its own checkpoint."""
        if self._started:
            return self
        self._started = True
        from ..basic import Booster
        resumed = None
        if self.ckpt_mgr is not None:
            resumed = self.ckpt_mgr.resumable(self._params)
        if resumed is not None:
            booster = Booster(model_file=resumed.model_path)
            with self._lock:
                self.booster = booster
                self.generation = int(resumed.iteration)
            emit_event("online_resume", generation=self.generation,
                       model=resumed.model_path)
            log.info(f"Online trainer resuming at generation "
                     f"{self.generation} ({resumed.model_path})")
            if isinstance(self.source, DirectoryChunkSource):
                self.source.fast_forward(self.generation)
            self._publish_current("resume", resumed.model_path)
        elif self._seed_model is not None:
            booster = (self._seed_model if hasattr(self._seed_model,
                                                   "model_to_string")
                       else Booster(model_file=os.fspath(self._seed_model)))
            with self._lock:
                self.booster = booster
            path = (os.fspath(self._seed_model)
                    if not hasattr(self._seed_model, "model_to_string")
                    else None)
            self._publish_current("seed", path)
        return self

    # ----------------------------------------------------------------- loop
    def run(self, max_generations: Optional[int] = None,
            idle_exit_s: Optional[float] = None) -> Dict[str, Any]:
        """Blocking loop: poll -> train -> checkpoint -> publish until
        stopped (SIGTERM/request_stop), `max_generations` chunks have
        been consumed, or the source has been idle for `idle_exit_s`."""
        cfg = self.config
        if max_generations is None:
            max_generations = int(cfg.online_max_generations) or None
        if idle_exit_s is None:
            idle_exit_s = float(cfg.online_idle_exit_s) or None
        self.start()
        emit_event("online_start", model=self.model_name,
                   mode=cfg.online_mode,
                   trees_per_chunk=self.trees_per_chunk,
                   max_lag_s=cfg.online_max_lag_s or None)
        processed = 0
        last_progress = time.monotonic()
        while not self._stop.is_set():
            if self.step():
                processed += 1
                last_progress = time.monotonic()
                if max_generations and processed >= max_generations:
                    break
                continue
            if idle_exit_s is not None and \
                    time.monotonic() - last_progress > idle_exit_s:
                log.info(f"Online trainer idle for {idle_exit_s:g}s; "
                         "exiting")
                break
            self._stop.wait(self.poll_interval_s)
        out = self.stats()
        emit_event("online_stop", **{k: v for k, v in out.items()
                                     if not isinstance(v, dict)})
        return out

    def step(self) -> bool:
        """Consume at most one chunk; returns True when one was
        processed (published OR skipped), False when the source was
        empty."""
        chunk = self.source.poll()
        if chunk is None:
            return False
        if not chunk.ok:
            self._skip(chunk, chunk.error or "unreadable chunk")
            return True
        mode = self._pick_mode(chunk)
        try:
            t0 = time.monotonic()
            booster = self._train(chunk, mode)
            train_s = time.monotonic() - t0
        except Exception as e:  # noqa: BLE001 - a bad chunk must not kill the loop
            self._skip(chunk, f"train failed: {e}")
            return True
        with self._lock:
            self.booster = booster
            self.generation = chunk.generation
        path = self._checkpoint(chunk.generation)
        self._publish(chunk, mode, path, train_s)
        return True

    # ------------------------------------------------------------ internals
    def _pick_mode(self, chunk: Chunk) -> str:
        if self.booster is None:
            return "boost"  # nothing to refit yet
        mode = self.config.online_mode
        if mode in ("boost", "refit"):
            return mode
        # auto heuristic: a chunk with fewer rows than the ensemble has
        # trees cannot support growing trees_per_chunk fresh trees of
        # structure, but is plenty to re-estimate the existing leaves
        # on the new distribution (the reference's cheap-update path)
        return "refit" if chunk.num_rows < self.booster.num_trees() \
            else "boost"

    def _train(self, chunk: Chunk, mode: str):
        from ..basic import Dataset
        from ..engine import train
        if mode == "refit":
            # in-place leaf re-estimation; bumps the mutation counter so
            # every slice-keyed predictor cache repacks (PR-10 hazard)
            self.booster.refit(chunk.X, chunk.y)
            return self.booster
        return train(dict(self._train_params),
                     Dataset(np.asarray(chunk.X, np.float64),
                             label=np.asarray(chunk.y, np.float64)),
                     num_boost_round=self.trees_per_chunk,
                     init_model=self.booster)

    def _checkpoint(self, generation: int) -> Optional[str]:
        if self.ckpt_mgr is None:
            return None
        try:
            ck = self.ckpt_mgr.save(self.booster, generation)
            return ck.model_path
        except OSError as e:
            # a lost checkpoint widens the redo window on the next
            # resume but must not stop the publish — serving freshness
            # is the loop's product, the checkpoint its insurance
            log.warning(f"Online checkpoint at generation {generation} "
                        f"failed: {e}; continuing")
            emit_event("checkpoint_write_failed", iteration=generation,
                       error=str(e))
            return None

    def _skip(self, chunk: Chunk, reason: str) -> None:
        with self._lock:
            self._skipped += 1
        global_registry.inc("online_generations_skipped")
        emit_event("online_chunk_skipped", generation=chunk.generation,
                   reason=str(reason)[:200])
        log.warning(f"Online chunk generation {chunk.generation} "
                    f"skipped: {reason}")
        # a skipped generation is a freshness failure: the fleet keeps
        # serving a model that never saw this chunk
        self.slo.observe(0.0, ok=False)

    def _publish_attempts(self, generation: int, model_str: str,
                          path: Optional[str]) -> Optional[int]:
        """Publish with bounded retry/backoff; None = gave up (the
        previous generation keeps serving)."""
        from ..reliability import faults
        attempt = 0
        while True:
            try:
                if faults.active():
                    faults.maybe_online_publish_fail(generation)
                return self.publisher.publish(self.model_name, model_str,
                                              path)
            except Exception as e:  # noqa: BLE001 - publish failures are retried/reported
                attempt += 1
                global_registry.inc("online_publish_retries")
                emit_event("online_publish_failed", generation=generation,
                           attempt=attempt, error=str(e)[:200])
                log.warning(f"Publish of generation {generation} failed "
                            f"(attempt {attempt}/"
                            f"{self.publish_retry_max + 1}): {e}")
                if attempt > self.publish_retry_max or \
                        self._stop.is_set():
                    return None
                time.sleep(self.publish_backoff_s * (2 ** (attempt - 1)))

    def _probe_freshness(self, version: Optional[int]
                         ) -> Optional[float]:
        """One request through the serving path; returns its monotonic
        completion stamp once a model AT LEAST as new as `version` is
        answering (None: probe failed / version still older)."""
        rows = self._probe_rows
        if rows is None:
            return None
        try:
            _, served = self.publisher.probe(self.model_name, rows)
        except Exception as e:  # noqa: BLE001 - freshness must not kill the loop
            log.warning(f"Freshness probe failed: {e}")
            return None
        if served is not None and version is not None \
                and int(served) < int(version):
            return None  # raced an older entry; lag unknown this round
        return time.monotonic()

    def _publish(self, chunk: Chunk, mode: str, path: Optional[str],
                 train_s: float) -> None:
        model_str = self.booster.model_to_string(num_iteration=-1)
        if self._probe_rows is None:
            # fixed probe rows (first row of the first chunk): constant
            # width, constant bucket — the probe never retraces
            self._probe_rows = np.ascontiguousarray(
                np.asarray(chunk.X[:1], np.float32))
        version = self._publish_attempts(chunk.generation, model_str, path)
        if version is None:
            self._skip(chunk, "publish failed after "
                              f"{self.publish_retry_max + 1} attempt(s)")
            return
        t_served = self._probe_freshness(version)
        lag_s = (t_served - chunk.t_arrival) if t_served is not None \
            else None
        with self._lock:
            self._published += 1
            self._published_version = version
            self._last_lag_s = lag_s
        global_registry.inc("online_generations_published")
        global_registry.set_gauge("online_generation", chunk.generation)
        if lag_s is not None:
            global_registry.set_gauge("model_freshness_lag_s",
                                      round(lag_s, 6))
            self.slo.observe(lag_s * 1000.0, ok=True)
        emit_event("online_publish", generation=chunk.generation,
                   version=version, mode=mode, rows=chunk.num_rows,
                   trees=self.booster.num_trees(),
                   train_s=round(train_s, 3),
                   freshness_lag_s=(round(lag_s, 6)
                                    if lag_s is not None else None))
        if self._on_publish is not None:
            self._on_publish(chunk.generation, version, model_str)
        log.info(f"Online generation {chunk.generation} published as "
                 f"{self.model_name!r} v{version} ({mode}, "
                 f"{chunk.num_rows} rows"
                 + (f", lag {lag_s * 1000.0:.0f} ms" if lag_s is not None
                    else "") + ")")

    def _publish_current(self, reason: str,
                         path: Optional[str]) -> None:
        """Publish the resumed/seeded model before consuming chunks, so
        a relaunch serves its newest checkpoint immediately.  The
        on-disk checkpoint text is published VERBATIM when there is one:
        a load/serialize round trip can normalize the embedded
        parameters block, and the resumed publish must be byte-identical
        to what the pre-kill process published."""
        model_str = None
        if path is not None:
            try:
                with open(path) as f:
                    model_str = f.read()
            except OSError:
                model_str = None
        if model_str is None:
            model_str = self.booster.model_to_string(num_iteration=-1)
        version = self._publish_attempts(self.generation, model_str, path)
        if version is None:
            log.warning(f"Initial ({reason}) publish failed; serving "
                        "keeps whatever it already holds")
            return
        with self._lock:
            self._published_version = version
        emit_event("online_publish", generation=self.generation,
                   version=version, mode=reason,
                   rows=0, trees=self.booster.num_trees(),
                   train_s=0.0, freshness_lag_s=None)
        if self._on_publish is not None:
            self._on_publish(self.generation, version, model_str)

    # ------------------------------------------------------------ telemetry
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {
                "model": self.model_name,
                "generation": self.generation,
                "published": self._published,
                "skipped": self._skipped,
                "version": self._published_version,
                "freshness_lag_s": self._last_lag_s,
            }
        out["generations_published"] = int(
            global_registry.counter("online_generations_published"))
        out["generations_skipped"] = int(
            global_registry.counter("online_generations_skipped"))
        if self.slo.enabled:
            out["slo"] = self.slo.stats()
        return out
