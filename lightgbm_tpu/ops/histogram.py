"""Histogram construction: the hottest op of GBDT training, as XLA computations.

TPU-native replacement for the reference's per-bin accumulation loops
(ref: src/io/dense_bin.hpp:99-176 ConstructHistogramInner and the CUDA
shared-memory kernels in src/treelearner/cuda/cuda_histogram_constructor.cu).
Instead of scalar scatter loops, histograms are built as one XLA computation over
the whole binned matrix:

  hist[f, b, c] = sum over rows r of (binned[f, r] == b) * gh[r, c]

Two interchangeable lowerings:

* ``segment`` — flat `segment_sum` keyed by ``f * B + bin`` (a single fused
  scatter-add; exact fp32 accumulation, the default).
* ``onehot`` — one-hot matmul ``gh.T @ onehot(bin)`` that maps onto the MXU
  systolic array (per the pallas guide's "histogram as matmul" recipe).

Both are row-chunked with `lax.scan` so peak memory is bounded regardless of
num_data; the row axis is the data-parallel sharding axis, so under pjit/shard_map
the chunk reduction lowers to a `psum` across the mesh — the ICI/DCN equivalent of
the reference's `Network::ReduceScatter` of histograms
(ref: src/treelearner/data_parallel_tree_learner.cpp:284).

The histogram stores 2 channels (sum_gradient, sum_hessian) per bin, matching the
reference's float histogram entry (ref: include/LightGBM/bin.h:46 kHistEntrySize);
data counts are derived downstream from hessian sums exactly as the reference does
(Common::RoundInt(hess * cnt_factor), ref: feature_histogram.hpp:873).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _pick_chunk(n: int, num_features: int, max_bin: int, method: str) -> int:
    """Row-chunk size.  For `onehot` the [F, R, B] one-hot materialization is
    the memory driver (keep it ~64MB); for `segment` the flat id/value copies
    are (keep F*R around 4M)."""
    if method == "onehot":
        r = (64 << 20) // max(num_features * max_bin * 2, 1)
    elif method == "onehot_hp":
        r = (64 << 20) // max(num_features * max_bin * 4, 1)
    else:
        r = (1 << 22) // max(num_features, 1)
    r = max(1024, r)
    r = 1 << (int(r) - 1).bit_length()  # next pow2
    return min(r, _round_up(n, 1024))


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _hist_chunk_segment(binned_c: jnp.ndarray, gh_c: jnp.ndarray,
                        num_bins_total: int, max_bin: int) -> jnp.ndarray:
    """One chunk: binned_c [F, R] int, gh_c [R, 2] -> [F*B, 2] via segment_sum."""
    num_features = binned_c.shape[0]
    offsets = (jnp.arange(num_features, dtype=jnp.int32) * max_bin)[:, None]
    ids = (binned_c.astype(jnp.int32) + offsets).reshape(-1)  # [F*R]
    vals = jnp.broadcast_to(gh_c[None, :, :],
                            (num_features,) + gh_c.shape).reshape(-1, gh_c.shape[-1])
    return jax.ops.segment_sum(vals, ids, num_segments=num_bins_total,
                               indices_are_sorted=False, unique_indices=False)


def _hist_chunk_onehot(binned_c: jnp.ndarray, gh_c: jnp.ndarray,
                       num_bins_total: int, max_bin: int,
                       compute_dtype=jnp.bfloat16) -> jnp.ndarray:
    """One chunk via MXU one-hot matmul: [C, R] @ [R, F*B] with C=gh channels.

    Default is single-pass bf16 multiply with fp32 accumulation — the
    one-hot side is exact in bf16 and the reference's own GPU learner uses
    single-precision histograms by default (ref: gpu_tree_learner.h:79
    gpu_use_dp=false; its quantized path even uses int8 grads).  Pass
    compute_dtype=float32 for the 3-pass high-precision variant.
    """
    num_features, rows = binned_c.shape
    onehot = (binned_c[:, :, None] ==
              jnp.arange(max_bin, dtype=binned_c.dtype)[None, None, :])
    onehot = onehot.astype(compute_dtype)                   # [F, R, B]
    onehot = jnp.transpose(onehot, (1, 0, 2)).reshape(rows, num_features * max_bin)
    precision = (jax.lax.Precision.HIGH if compute_dtype == jnp.float32
                 else jax.lax.Precision.DEFAULT)
    return jax.lax.dot_general(
        gh_c.astype(compute_dtype), onehot, (((0,), (0,)), ((), ())),
        precision=precision,
        preferred_element_type=jnp.float32).T               # [F*B, C]


def _hist_pallas_kernel(Fg: int, Bp: int, C: int):
    """Fused one-hot histogram kernel: per (feature-group, row-tile) build
    the [Fg, Bp, Rt] one-hot in VMEM only (never HBM) and contract all
    features' bins against gh in ONE MXU dot — the Pallas analogue of the
    CUDA shared-memory histogram kernel (ref:
    cuda_histogram_constructor.cu:18-230, which accumulates per-block
    histograms in shared memory for the same reason)."""
    def kernel(rows_ref, gh_ref, out_ref):
        @pl.when(pl.program_id(1) == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)
        rows = rows_ref[...].astype(jnp.int32)        # [Fg, Rt]
        ghv = gh_ref[...].astype(jnp.bfloat16)        # [Rt, C]
        Rt = rows.shape[1]
        biota = jax.lax.broadcasted_iota(jnp.int32, (Fg, Bp, Rt), 1)
        oh = (rows[:, None, :] == biota).astype(jnp.bfloat16)  # [Fg, Bp, Rt]
        acc = jax.lax.dot_general(
            oh.reshape(Fg * Bp, Rt), ghv, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # [Fg*Bp, C]
        out_ref[...] += acc.reshape(Fg, Bp, C)
    return kernel


@functools.partial(jax.jit, static_argnames=("max_bin", "row_tile"))
def build_histogram_rows_pallas(rows: jnp.ndarray, gh: jnp.ndarray,
                                mask: jnp.ndarray, *, max_bin: int,
                                row_tile: int = 512) -> jnp.ndarray:
    """Histogram over row-major binned data [S, F] via the fused Pallas
    kernel.  S must be a multiple of row_tile.  Returns [F, B, C] float32."""
    S, F = rows.shape
    C = gh.shape[-1]
    Bp = (max_bin + 127) // 128 * 128
    if S % row_tile != 0:
        raise ValueError(f"rows {S} not a multiple of row_tile {row_tile}")
    gh = (gh * mask.astype(gh.dtype)[:, None]).astype(jnp.float32)
    # feature-major layout; pad F to the TPU's 8-sublane block granule
    Fp = (F + 7) // 8 * 8
    rows_fm = rows.T
    if Fp != F:
        rows_fm = jnp.pad(rows_fm, ((0, Fp - F), (0, 0)))
    # feature group bounded by the [Fg, Bp, Rt] bf16 one-hot in VMEM (~2MB)
    Fg = _pick_feature_group(Fp, Bp * row_tile * 2, 2 << 20)
    out = pl.pallas_call(
        _hist_pallas_kernel(Fg, Bp, C),
        grid=(Fp // Fg, S // row_tile),
        in_specs=[pl.BlockSpec((Fg, row_tile), lambda g, i: (g, i)),
                  pl.BlockSpec((row_tile, C), lambda g, i: (i, 0))],
        out_specs=pl.BlockSpec((Fg, Bp, C), lambda g, i: (g, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((Fp, Bp, C), jnp.float32),
    )(rows_fm, gh)
    return out[:F, :max_bin, :]                       # [F, B, C]


def _wave_kernel(C: int, Fg: int, Bg: int, NLg: int):
    """Multi-leaf fused histogram kernel for wave (level-batched) growth.

    Per (slot-group, bin-group, feature-group, row-tile) grid cell, build
    the [Fg, Bg, Rt] bin one-hot and the slot-separated channel matrix
    [Rt, C*NLg] in VMEM, then ONE MXU dot accumulates all NLg leaves' and
    all C channels' histograms at once.  The leaf-slot axis is what fills
    the MXU's 128-wide output dimension — a plain per-leaf histogram dot
    has C=2 output columns and idles 126/128 of the systolic array, which
    is the dominant cost of histogram construction on TPU.  Fusing the
    channels into the output dimension (instead of one dot per channel)
    matters for the same reason: the MXU pads output lanes to 128, so
    early waves with few slots pay for 128 lanes regardless — C dots at
    NLg<=64 slots cost C times one fused dot.  (TPU replacement for the
    CUDA per-leaf shared-memory kernels,
    ref: cuda_histogram_constructor.cu:18.)"""
    def kernel(rows_ref, slot_ref, gh_ref, out_ref, cnt_ref):
        bg = pl.program_id(0)
        g = pl.program_id(1)
        @pl.when(pl.program_id(2) == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)
        @pl.when((bg == 0) & (g == 0) & (pl.program_id(2) == 0))
        def _init_cnt():
            cnt_ref[...] = jnp.zeros_like(cnt_ref)
        # quantized mode: int8 operands (half the one-hot bytes, 2x MXU
        # int8 rate) with exact int32 accumulation — Mosaic legalizes int8
        # select and int8 dot, but NOT int8 multiply, so the channel
        # matrix is built with where() instead of mask*value
        int8_mode = out_ref.dtype == jnp.int32
        mxu_t = jnp.int8 if int8_mode else jnp.bfloat16
        acc_t = jnp.int32 if int8_mode else jnp.float32
        # offset the SMALL [Fg, Rt] rows instead of the big [Fg, Bg, Rt]
        # iota: the one-hot construction is the per-wave VPU floor, so
        # every elementwise pass over the big shape counts
        rows = rows_ref[...].astype(jnp.int32) - bg * Bg  # [Fg, Rt]
        slot = slot_ref[...].astype(jnp.int32)           # [Rt, 1]
        gh = gh_ref[...]                                 # [Rt, C+1]
        Rt = rows.shape[1]
        biota = jax.lax.broadcasted_iota(jnp.int32, (Fg, Bg, Rt), 1)
        oh = (rows[:, None, :] == biota).astype(mxu_t)
        oh2 = oh.reshape(Fg * Bg, Rt)
        S = out_ref.shape[-1] // (C * NLg)
        for s in range(S):  # slot groups REUSE the bin one-hot (its VPU
            # construction, not the MXU dot, is the per-wave cost floor)
            loc = slot - s * NLg
            soh = (loc == jax.lax.broadcasted_iota(jnp.int32, (Rt, NLg), 1))
            # [Rt, C*NLg] (c-major): channel value where the slot matches
            # (built 2-D per channel — Mosaic cannot insert a bf16 minor dim)
            if int8_mode:
                # select in int32 (Mosaic relayouts i1->i8 selects badly),
                # then narrow to int8 for the MXU operand
                sc = jnp.concatenate(
                    [jnp.where(soh,
                               jnp.broadcast_to(gh[:, c:c + 1], (Rt, NLg)),
                               0).astype(jnp.int8)
                     for c in range(C)], axis=1)
            else:
                sohb = soh.astype(jnp.bfloat16)
                sc = jnp.concatenate(
                    [sohb * gh[:, c:c + 1].astype(jnp.bfloat16)
                     for c in range(C)], axis=1)
            acc = jax.lax.dot_general(
                oh2, sc, (((1,), (0,)), ((), ())),
                preferred_element_type=acc_t)            # [Fg*Bg, C*NLg]
            # lane dim stays flat (Mosaic cannot split the lane dim); the
            # caller unscrambles the (slot-group, channel, slot) layout
            w = C * NLg
            out_ref[:, :, s * w:(s + 1) * w] += acc.reshape(Fg, Bg, w)
            # exact per-slot row counts ride along as a [8, NLg] dot of the
            # mask column (gh[:, C]) against the slot one-hot — one cell
            # only, replacing a separate 20ms scatter-add pass
            @pl.when((bg == 0) & (g == 0))
            def _count():
                if int8_mode:
                    mask8 = jnp.broadcast_to(gh[:, C:C + 1],
                                             (Rt, 8)).T.astype(jnp.int8)
                    sohm = jnp.where(
                        soh, 1, 0).astype(jnp.int8)
                else:
                    mask8 = jnp.broadcast_to(
                        gh[:, C:C + 1].astype(mxu_t), (Rt, 8)).T
                    sohm = soh.astype(mxu_t)
                cacc = jax.lax.dot_general(
                    mask8, sohm, (((1,), (0,)), ((), ())),
                    preferred_element_type=acc_t)        # [8, NLg]
                cnt_ref[:, s * NLg:(s + 1) * NLg] += cacc
    return kernel


def _wave_kernel_hl(C: int, Fg: int, Bh: int, Bl: int, S: int, P: int):
    """Decomposed (hi/lo outer-product) wave kernel for FEW computed slots.

    The flat-floor cost of `_wave_kernel` is the F*B*Rt bin one-hot built
    in VMEM every wave.  For waves whose computed-slot count S is small,
    the one-hot factors over a hi/lo split of the bin code

        onehot_B(bin) = onehot_Bh(bin >> log2(Bl)) (x) onehot_Bl(bin & Bl-1)

        hist[f, bh, bl, (c,s)] = sum_n 1[hi=bh] * (1[lo=bl] * w[n,(c,s)])

    so the materialized volume drops from F*B*Rt to
    F*(Bh + Bl*C*S)*Rt — e.g. 48 vs 256 lane-units per feature per row at
    S=1.  Measured on the v5e chip this is ~1.5x the full kernel at S<=2
    and ~1.25x at S=4 (tools/profile_hl.py); the advantage vanishes by
    S=16, where `_wave_kernel`'s slot-riding RHS is already optimal.

    The RHS is built at FULL 128-lane width with expander matmuls —
    sub-128-lane elementwise ops pad to whole vregs on TPU, so a naive
    per-feature [Rt, C*S] build would pay full-width cost anyway:

        d  = [lo_rm | 1] @ [E ; -bl_pat]   (lo minus the column's target
                                            bl; zero exactly on match)
        wt = w_sc @ T                      (tile CS channels across cols)
        sc = where(d == 0, wt, 0)

    Main dots pack P features into M and P column blocks into N; only the
    diagonal (f, f) blocks of each [P*Bh, P*Bl*C*S] product are kept.
    (Counterpart of the same smaller-child histogramming the reference
    does serially, dense_bin.hpp:99-176; decomposition is TPU-only.)"""
    CS = C * S
    Wd = Fg * Bl * CS
    shift = Bl.bit_length() - 1

    def kernel(rows_ref, rows_rm_ref, slot_ref, gh_ref, out_ref, cnt_ref):
        @pl.when(pl.program_id(0) == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)
            cnt_ref[...] = jnp.zeros_like(cnt_ref)
        i32, bf16 = jnp.int32, jnp.bfloat16
        rows = rows_ref[...].astype(i32)          # [Fg, Rt] (lanes=Rt)
        Rt = rows.shape[1]
        rows_rm = rows_rm_ref[...].astype(i32)    # [Rt, Fg] (sublanes=Rt)
        slot = slot_ref[...].astype(i32)          # [Rt, 1]
        gh = gh_ref[...]                          # [Rt, C+1]

        hi = rows >> shift
        biota = jax.lax.broadcasted_iota(i32, (Fg, Bh, Rt), 1)
        hi_oh = (hi[:, None, :] == biota).astype(bf16)

        # w_sc [Rt, C*S]: slot one-hot x channels (c-major)
        soh = (slot == jax.lax.broadcasted_iota(i32, (Rt, S), 1))
        sohb = soh.astype(bf16)
        w_sc = jnp.concatenate(
            [sohb * gh[:, c:c + 1].astype(bf16) for c in range(C)], axis=1)

        lo = (rows_rm & (Bl - 1)).astype(bf16)    # [Rt, Fg]
        ones = jnp.ones((Rt, 1), bf16)
        lhs2 = jnp.concatenate([lo, ones], axis=1)            # [Rt, Fg+1]
        colf = jax.lax.broadcasted_iota(i32, (Fg + 1, Wd), 1) // (Bl * CS)
        rowi = jax.lax.broadcasted_iota(i32, (Fg + 1, Wd), 0)
        blp = (jax.lax.broadcasted_iota(i32, (Fg + 1, Wd), 1) // CS) % Bl
        E2 = jnp.where(rowi == Fg, (-blp).astype(bf16),
                       (colf == rowi).astype(bf16))           # [Fg+1, Wd]
        d = jax.lax.dot_general(lhs2, E2, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        csp = jax.lax.broadcasted_iota(i32, (CS, Wd), 1)
        Tm = (csp % CS ==
              jax.lax.broadcasted_iota(i32, (CS, Wd), 0)).astype(bf16)
        wt = jax.lax.dot_general(w_sc, Tm, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        sc = jnp.where(d == 0.0, wt, 0.0).astype(bf16)        # [Rt, Wd]

        BCS = Bl * CS
        for f0 in range(0, Fg, P):
            lhs = hi_oh[f0:f0 + P].reshape(P * Bh, Rt)
            rhs = sc[:, f0 * BCS:(f0 + P) * BCS]
            acc = jax.lax.dot_general(lhs, rhs, (((1,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
            for p in range(P):
                out_ref[f0 + p] += acc[p * Bh:(p + 1) * Bh,
                                       p * BCS:(p + 1) * BCS]
        # ride-along exact counts (mask column against the slot one-hot)
        mask8 = jnp.broadcast_to(gh[:, C:C + 1].astype(bf16), (Rt, 8)).T
        cacc = jax.lax.dot_general(mask8, sohb, (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)
        cnt_ref[...] += cacc
    return kernel


def hl_split_of(max_bin: int, num_slots: int, C: int):
    """(Bh, Bl) split for the decomposed kernel, tuned on the chip
    (tools/profile_hl.py): balance Bh against Bl*C*S."""
    CS = C * num_slots
    best = None
    for Bl in (2, 4, 8, 16, 32):
        Bh = -(-max_bin // Bl)
        Bh8 = max(8, -(-Bh // 8) * 8)
        cost = Bh8 + Bl * CS
        if best is None or cost < best[0]:
            best = (cost, Bh8, Bl)
    return best[1], best[2]


def wave_hl_profitable(max_bin: int, num_slots: int, C: int = 2) -> bool:
    """True when the decomposed kernel's materialized volume is
    meaningfully below the full kernel's F*B (measured crossover ~0.6)."""
    Bh, Bl = hl_split_of(max_bin, num_slots, C)
    # Bh > 256 would overflow the feature-packed M dimension (and such
    # giant max_bin configs gain nothing from decomposition anyway)
    return Bh <= 256 and (Bh + Bl * C * num_slots) <= 0.6 * max_bin


@functools.partial(jax.jit,
                   static_argnames=("max_bin", "num_slots", "out_slots",
                                    "row_tile"))
def build_histogram_wave_hl(binned_fm: jnp.ndarray, binned_rm: jnp.ndarray,
                            slot: jnp.ndarray, gh: jnp.ndarray, *,
                            max_bin: int, num_slots: int, out_slots: int,
                            row_tile: int = 512):
    """Decomposed-kernel variant of `build_histogram_wave` for waves with
    few computed slots (see `_wave_kernel_hl`).  `num_slots` is the TRUE
    computed-slot bound; the output is zero-padded to `out_slots` rows so
    callers keep the padded-Kb contract.  Returns
    (hist [out_slots, F, B, C] float32, counts [out_slots] float32)."""
    F, n = binned_fm.shape
    C = gh.shape[-1] - 1
    S = num_slots
    Bh, Bl = hl_split_of(max_bin, S, C)
    P = next((p for p in (4, 2, 1) if F % p == 0 and p * Bh <= 256), 1)
    if n % row_tile != 0:
        raise ValueError(f"n {n} not a multiple of row_tile {row_tile}")
    out, cnt = pl.pallas_call(
        _wave_kernel_hl(C, F, Bh, Bl, S, P),
        grid=(n // row_tile,),
        in_specs=[
            pl.BlockSpec((F, row_tile), lambda i: (0, i)),
            pl.BlockSpec((row_tile, F), lambda i: (i, 0)),
            pl.BlockSpec((row_tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((row_tile, C + 1), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((F, Bh, Bl * C * S), lambda i: (0, 0, 0)),
            pl.BlockSpec((8, S), lambda i: (0, 0))],
        out_shape=[
            jax.ShapeDtypeStruct((F, Bh, Bl * C * S), jnp.float32),
            jax.ShapeDtypeStruct((8, S), jnp.float32)],
    )(binned_fm, binned_rm, slot.reshape(n, 1), gh)
    # [F, Bh, (bl, c, s)] -> [S, F, B, C], zero-padded to out_slots
    h = out.reshape(F, Bh, Bl, C, S).transpose(4, 0, 1, 2, 3)
    h = h.reshape(S, F, Bh * Bl, C)[:, :, :max_bin, :]
    pad = out_slots - S
    if pad > 0:
        h = jnp.concatenate(
            [h, jnp.zeros((pad,) + h.shape[1:], h.dtype)], axis=0)
        cntv = jnp.concatenate([cnt[0], jnp.zeros(pad, cnt.dtype)])
    else:
        cntv = cnt[0]
    return h, cntv


def _pick_feature_group(Fp: int, unit_bytes: int, budget: int) -> int:
    """Largest 8-multiple divisor of Fp whose VMEM cost Fg*unit_bytes fits
    the budget (TPU blocks need 8-aligned sublane dims; 8 is the floor)."""
    Fg = 8
    for cand in range(8, Fp + 1, 8):
        if Fp % cand == 0 and cand * unit_bytes <= budget:
            Fg = cand
    return Fg


def wave_slot_pad(num_slots: int) -> int:
    """Slot-axis padding for the wave kernel: the out block's last dim must
    be a multiple of 128 or the whole (padded) slot axis."""
    if num_slots <= 128:
        return max(8, (num_slots + 7) // 8 * 8)
    return (num_slots + 127) // 128 * 128


def wave_pallas_vmem_ok(num_features: int, max_bin: int,
                        num_slots: int) -> bool:
    """True when the wave kernel's VMEM accumulator fits at the smallest
    legal tile (Fg=8, Bg<=128, NLg<=128, 3 channels)."""
    Bg = min((max_bin + 7) // 8 * 8, 128)
    NLg = min(wave_slot_pad(num_slots), 128)
    return 3 * 8 * Bg * NLg * 4 <= (8 << 20)


@functools.partial(jax.jit,
                   static_argnames=("max_bin", "num_slots", "row_tile",
                                    "quant_bins"))
def build_histogram_wave(binned_fm: jnp.ndarray, slot: jnp.ndarray,
                         gh: jnp.ndarray, *, max_bin: int, num_slots: int,
                         row_tile: int = 512, quant_bins: int = 0,
                         quant_scales: jnp.ndarray = None):
    """Histograms for all leaf slots in one fused pass over the rows.

    Grid = (bin groups, feature groups, row tiles); each cell builds the
    bin one-hot ONCE and loops the slot groups inside, one MXU dot per
    slot group whose output columns are (channel, slot) pairs.  The leaf-
    slot axis fills the MXU's 128-wide output dimension — a plain per-leaf
    histogram dot has C=2 output columns and idles most of the systolic
    array.  The one-hot's VPU construction is the cost floor, so its
    volume (F*B*n per wave) is built exactly once regardless of slot
    count.  Exact per-slot row counts ride along as a second output — the
    mask column against the slot one-hot.  (TPU replacement for the CUDA
    per-leaf shared-memory kernels, cuda_histogram_constructor.cu:18.)

    Args:
      binned_fm: [F, n] feature-major bin codes.
      slot: [n] int32 leaf slot per row.
      gh: [n, C+1] per-row accumulands (gradient, hessian, ..., row-mask);
        the LAST column is the count mask (zeros for excluded rows).
      max_bin: B (static).  num_slots: NL leaf slots (static).
      quant_bins: when > 0, gh's channels carry grid-snapped quantized
        values (ref: gradient_discretizer.cpp DiscretizeGradients): the
        kernel recovers the int8 grid indices and accumulates EXACT int32
        histograms through the MXU's 2x int8 path, dequantizing on the
        way out — the TPU analogue of the reference's int16/int32
        quantized histograms (dense_bin.hpp:174 ConstructHistogramIntInner).

    Returns: (hist [NL, F, B, C] float32, counts [NL] float32).
    """
    F, n = binned_fm.shape
    C = gh.shape[-1] - 1
    use_int8 = quant_scales is not None
    if use_int8:
        assert quant_bins <= 126, "int8 grid bound"
        # gh's channels carry k * scale for int grid indices k; divide by
        # the TRUE scales (threaded from DiscretizeGradients) so the
        # round() recovers the exact ints
        gh = jnp.concatenate(
            [jnp.round(gh[:, :C] / quant_scales[None, :]).astype(jnp.int32),
             (gh[:, C:] > 0).astype(jnp.int32)], axis=1)
    NLp = wave_slot_pad(num_slots)
    NLg = min(NLp, 128)
    Bp = max(8, (max_bin + 7) // 8 * 8)
    # one bin group when it fits: rows are then streamed once per wave
    Bg = min(Bp, 256)
    if Bp % Bg != 0:
        Bp = (Bp + Bg - 1) // Bg * Bg
    if n % row_tile != 0:
        raise ValueError(f"n {n} not a multiple of row_tile {row_tile}")
    S = NLp // NLg
    # TPU block constraint: the binned block's second-to-last dim (Fg) must
    # be a multiple of 8 OR the whole (unpadded) F.  Prefer the single
    # full-F group when its VMEM footprint fits — it avoids padding F up
    # to a multiple of 8 (12.5% wasted one-hot volume and MXU rows at the
    # bench's 28 features) and cuts grid-cell overheads.
    unit = Bg * (S * C * NLg * 4 + row_tile * 2)
    # gate at the measured 16 MB scoped-VMEM limit (wave.py's documented
    # Mosaic bound) — shapes in the 16-24 MB window compile on CPU tests
    # but can fail Mosaic on device; fall back to the grouped path there
    if F * unit <= (16 << 20):
        Fp = Fg = F
    else:
        Fp = (F + 7) // 8 * 8
        if Fp != F:
            binned_fm = jnp.pad(binned_fm, ((0, Fp - F), (0, 0)))
        # feature group bounded by the VMEM accumulator [Fg, Bg, S*C*NLg]
        # plus the [Fg, Bg, Rt] bf16 one-hot
        Fg = _pick_feature_group(Fp, unit, 6 << 20)
    acc_t = jnp.int32 if use_int8 else jnp.float32
    out, cnt = pl.pallas_call(
        _wave_kernel(C, Fg, Bg, NLg),
        grid=(Bp // Bg, Fp // Fg, n // row_tile),
        in_specs=[
            pl.BlockSpec((Fg, row_tile), lambda bg, g, i: (g, i)),
            pl.BlockSpec((row_tile, 1), lambda bg, g, i: (i, 0)),
            pl.BlockSpec((row_tile, C + 1), lambda bg, g, i: (i, 0))],
        out_specs=[
            pl.BlockSpec((Fg, Bg, S * C * NLg),
                         lambda bg, g, i: (g, bg, 0)),
            pl.BlockSpec((8, NLp), lambda bg, g, i: (0, 0))],
        out_shape=[
            jax.ShapeDtypeStruct((Fp, Bp, S * C * NLg), acc_t),
            jax.ShapeDtypeStruct((8, NLp), acc_t)],
    )(binned_fm, slot.reshape(n, 1), gh)
    # [Fp, Bp, (s, c, lg)] -> [NL, F, B, C]
    out = out.reshape(Fp, Bp, S, C, NLg).transpose(2, 4, 0, 1, 3)
    hist = out.reshape(S * NLg, Fp, Bp, C)[:num_slots, :F, :max_bin, :]
    if use_int8:
        # dequantize the exact int sums back to the float grid
        hist = hist.astype(jnp.float32) * quant_scales[None, None, None, :]
        return hist, cnt[0, :num_slots].astype(jnp.float32)
    return hist, cnt[0, :num_slots]


@functools.partial(jax.jit, static_argnames=("max_bin", "method", "row_chunk"))
def build_histogram(binned: jnp.ndarray, gh: jnp.ndarray, mask: jnp.ndarray,
                    *, max_bin: int, method: str = "segment",
                    row_chunk: int = 0) -> jnp.ndarray:
    """Masked histogram over all rows.

    Args:
      binned: [F, n] integer bin codes (n padded to a multiple of the chunk).
      gh:     [n, C] per-row values to accumulate (gradient, hessian, ...).
      mask:   [n] 0/1 leaf-membership x bagging mask (float or bool).
      max_bin: B, the padded per-feature bin count (static).
      method: "segment" (scatter-add) or "onehot" (MXU matmul).
      row_chunk: rows per scan step; 0 = auto.

    Returns: hist [F, B, C] float32.
    """
    num_features, n = binned.shape
    channels = gh.shape[-1]
    gh = gh * mask.astype(gh.dtype)[:, None]
    total = num_features * max_bin
    chunk = row_chunk or _pick_chunk(n, num_features, max_bin, method)
    if method == "segment":
        kernel = _hist_chunk_segment
    elif method == "onehot":
        kernel = _hist_chunk_onehot
    elif method == "onehot_hp":
        kernel = functools.partial(_hist_chunk_onehot,
                                   compute_dtype=jnp.float32)
    else:
        raise ValueError(f"unknown histogram method {method!r}")
    if n <= chunk:
        out = kernel(binned, gh, total, max_bin)
        return out.reshape(num_features, max_bin, channels)

    while n % chunk != 0 and chunk > 1024:
        chunk //= 2  # n is padded to a 1024 multiple; shrink to a divisor
    if n % chunk != 0:
        raise ValueError(f"num_data {n} must be padded to a multiple of {chunk}")
    num_chunks = n // chunk
    binned_chunks = binned.reshape(num_features, num_chunks, chunk).transpose(1, 0, 2)
    gh_chunks = gh.reshape(num_chunks, chunk, channels)

    def step(acc, xs):
        bc, gc = xs
        return acc + kernel(bc, gc, total, max_bin), None

    init = jnp.zeros((total, channels), dtype=jnp.float32)
    out, _ = jax.lax.scan(step, init, (binned_chunks, gh_chunks))
    return out.reshape(num_features, max_bin, channels)
